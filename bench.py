"""Benchmark entry: TPC-H operator-pipeline throughput on device.

Mirrors the reference's operator benchmark metric (reference
presto-benchmark/.../AbstractOperatorBenchmark.java:303-330 reports
input_rows_per_second over hand-built operator pipelines,
HandTpchQuery1.java / HandTpchQuery6.java). Three staged configs
(BASELINE.md): Q6 @ SF1 (scan-filter-agg), Q1 @ SF10 (group-by
aggregation), Q3 @ SF10 (3-way join + high-cardinality group-by + top-n;
set BENCH_SF_Q3=100 for the full-scale config when wall-clock allows).

Baseline: the reference publishes no absolute numbers and no JVM exists
in this image (BASELINE.md requires measuring the Java harness; `which
java` is empty here), so `vs_baseline` is measured against a vectorized
NumPy implementation of the IDENTICAL pipeline over the IDENTICAL
pre-generated chunks on this host (single core, like one Presto driver
thread). The proxy favors the baseline: NumPy's C kernels are at least
as fast per core as Presto's Java operator loops (whose PageProcessor
makes per-row virtual calls per column), so the reported ratio is a
LOWER bound on the vs-Java speedup per core.

Input generation is excluded from both sides' timing (both sides would
share the same host generator; the reference harness likewise reads
pre-staged in-memory pages). Device timing covers all compute plus the
final result readback; input staging is untimed on both sides.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline",
"sub_metrics": [...]}.
"""
from __future__ import annotations

import datetime
import json
import os
import time

import numpy as np


def _epoch_day(y, m, d) -> int:
    return (datetime.date(y, m, d) - datetime.date(1970, 1, 1)).days


D_Q1 = _epoch_day(1998, 9, 2)    # 1998-12-01 - 90 days
D_Q3 = _epoch_day(1995, 3, 15)


def _stage(conn, table, cols, rows_per_batch, device: bool):
    """Generate a table's chunks once; host copies always, device copies
    optionally (np.array copies: a zero-copy view of a CPU-backend jax
    buffer could be invalidated once the device pipeline reuses it)."""
    from presto_tpu.connectors.spi import TableHandle

    th = TableHandle("tpch", "t", table)
    split = conn.split_manager.splits(th, 1)[0]
    dev, host, n = [], [], 0
    schema = None
    for b in conn.page_source(split, cols, rows_per_batch=rows_per_batch
                              ).batches():
        schema = b.schema
        if device:
            dev.append(b)
        host.append(tuple(np.array(c.data) for c in b.columns)
                    + (np.array(b.row_mask),))
        n += int(np.sum(host[-1][-1]))
    return dev, host, n, schema


def _time(fn):
    fn()                            # warmup + compile
    t0 = time.perf_counter()
    got = fn()
    return got, time.perf_counter() - t0


# ---------------------------------------------------------------------------
# Q6: scan-filter-aggregate (reference HandTpchQuery6.java)
# ---------------------------------------------------------------------------

def bench_q6(sf: float):
    import jax
    import jax.numpy as jnp
    from presto_tpu import types as T
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.expr.compiler import compile_filter, compile_projection
    from presto_tpu.ops.aggregation import AggSpec, global_aggregate
    import __graft_entry__ as ge

    conn = TpchConnector(sf=sf)
    dev, host, total, _ = _stage(conn, "lineitem", ge._Q6_COLS, 1 << 20,
                                 True)

    schema, pred, proj = ge._q6_exprs()
    filt = compile_filter(pred, schema)
    project = compile_projection(proj, ["rev"], schema)
    aggs = [AggSpec("sum", 0, T.DOUBLE, "revenue")]

    @jax.jit
    def q6_partial(batch):
        p = global_aggregate(project(filt(batch)), aggs, mode="partial")
        return p.columns[0].data[0]

    combine = jax.jit(lambda vs: jnp.sum(jnp.stack(vs)))

    def run_device():
        # async dispatch per batch; sync exactly once at the final scalar
        # (the tunnel's ~100ms readback RTT would otherwise dominate)
        return float(combine([q6_partial(b) for b in dev]))

    def run_numpy():
        acc = 0.0
        for ship, disc, qty, price, mask in host:
            # decimal columns re-quantized to 2dp: the TPU f64 is a
            # double-double that can lose the final ULP
            disc2, qty2, price2 = (np.round(c, 2)
                                   for c in (disc, qty, price))
            m = (mask & (ship >= 8766) & (ship < 9131)
                 & (disc2 >= 0.05) & (disc2 <= 0.07) & (qty2 < 24.0))
            acc += float(np.sum(np.where(m, price2 * disc2, 0.0)))
        return acc

    got, dev_s = _time(run_device)
    want, np_s = _time(run_numpy)
    assert abs(got - want) <= 1e-8 * max(abs(want), 1.0), (got, want)
    return total, dev_s, np_s


# ---------------------------------------------------------------------------
# Q1: group-by aggregation (reference HandTpchQuery1.java)
# ---------------------------------------------------------------------------

_Q1_COLS = ["l_returnflag", "l_linestatus", "l_quantity", "l_extendedprice",
            "l_discount", "l_tax", "l_shipdate"]


def bench_q1(sf: float):
    import jax
    from presto_tpu import types as T
    from presto_tpu.batch import Batch, Column, Schema, concat_batches
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.ops.aggregation import AggSpec, grouped_aggregate

    conn = TpchConnector(sf=sf)
    dev, host, total, schema = _stage(conn, "lineitem", _Q1_COLS, 1 << 20,
                                      True)
    rf_vocab = dev[0].columns[0].dictionary
    ls_vocab = dev[0].columns[1].dictionary

    aggs = [
        AggSpec("sum", 2, T.DOUBLE, "sum_qty"),
        AggSpec("sum", 3, T.DOUBLE, "sum_base"),
        AggSpec("sum", 7, T.DOUBLE, "sum_disc_price"),
        AggSpec("sum", 8, T.DOUBLE, "sum_charge"),
        AggSpec("avg", 2, T.DOUBLE, "avg_qty"),
        AggSpec("avg", 3, T.DOUBLE, "avg_price"),
        AggSpec("avg", 4, T.DOUBLE, "avg_disc"),
        AggSpec("count_star", None, T.BIGINT, "count_order"),
    ]
    ext_schema = Schema(list(zip(schema.names, schema.types)) + [
        ("disc_price", T.DOUBLE), ("charge", T.DOUBLE)])

    @jax.jit
    def q1_partial(b: Batch) -> Batch:
        mask = b.row_mask & (b.columns[6].data <= D_Q1)
        price, disc, tax = (b.columns[i].data for i in (3, 4, 5))
        disc_price = price * (1.0 - disc)
        charge = disc_price * (1.0 + tax)
        valid = b.columns[3].validity & b.columns[4].validity
        cols = list(b.columns) + [
            Column(T.DOUBLE, disc_price, valid, None),
            Column(T.DOUBLE, charge, valid & b.columns[5].validity, None),
        ]
        ext = Batch(ext_schema, cols, mask)
        # <= 6 distinct (returnflag, linestatus) groups per chunk: a
        # fixed 128-slot compaction needs no host sync
        return grouped_aggregate(ext, [0, 1], aggs,
                                 mode="partial").compact(128, check=False)

    @jax.jit
    def q1_final(parts):
        states = concat_batches(parts, capacity=128 * len(parts))
        return grouped_aggregate(states, [0, 1], aggs, mode="final")

    def run_device():
        out = q1_final([q1_partial(b) for b in dev])
        jax.block_until_ready(jax.tree_util.tree_leaves(out))
        return out

    def run_numpy():
        sums = {}
        for (rf, ls, qty, price, disc, tax, ship, mask) in host:
            m = mask & (ship <= D_Q1)
            qty2, price2, disc2, tax2 = (np.round(c, 2)
                                         for c in (qty, price, disc, tax))
            for code_rf in range(len(rf_vocab)):
                for code_ls in range(len(ls_vocab)):
                    g = m & (rf == code_rf) & (ls == code_ls)
                    if not g.any():
                        continue
                    dp = price2[g] * (1.0 - disc2[g])
                    ch = dp * (1.0 + tax2[g])
                    acc = sums.setdefault((code_rf, code_ls), np.zeros(6))
                    acc += [qty2[g].sum(), price2[g].sum(), dp.sum(),
                            ch.sum(), disc2[g].sum(), g.sum()]
        return sums

    out, dev_s = _time(run_device)
    want, np_s = _time(run_numpy)
    got = {(rf_vocab.index(r[0]), ls_vocab.index(r[1])): r[2:]
           for r in out.to_pylist()}
    assert set(got) == set(want), (sorted(got), sorted(want))
    for k, acc in want.items():
        g = got[k]
        for gv, wv in zip(g[:4], acc[:4]):
            assert abs(gv - wv) <= 1e-6 * max(abs(wv), 1.0), (k, g, acc)
        assert g[7] == int(acc[5]), (k, g, acc)
    return total, dev_s, np_s


# ---------------------------------------------------------------------------
# Q3: 3-way join + group-by + top-n (reference
# HashBuildAndJoinBenchmark.java shape with HandTpchQuery-style agg)
# ---------------------------------------------------------------------------

def bench_q3(sf: float):
    import jax
    import jax.numpy as jnp
    from presto_tpu import types as T
    from presto_tpu.batch import (
        Batch, Column, Schema, bucket_capacity, concat_batches,
    )
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.ops.aggregation import AggSpec, grouped_aggregate
    from presto_tpu.ops.join import lookup_join, semi_join_mask
    from presto_tpu.ops.sort import SortKey, top_n

    conn = TpchConnector(sf=sf)
    li_cols = ["l_orderkey", "l_extendedprice", "l_discount", "l_shipdate"]
    o_cols = ["o_orderkey", "o_custkey", "o_orderdate", "o_shippriority"]
    c_cols = ["c_custkey", "c_mktsegment"]
    # lineitem beyond ~SF20 would not fit on one chip: stream from host
    li_device = sf <= 20
    li_dev, li_host, n_li, li_schema = _stage(conn, "lineitem", li_cols,
                                              1 << 20, li_device)
    o_dev, o_host, n_o, _ = _stage(conn, "orders", o_cols, 1 << 20, True)
    c_dev, c_host, n_c, _ = _stage(conn, "customer", c_cols, 1 << 20, True)
    total = n_li + n_o + n_c
    seg_code = c_dev[0].columns[1].dictionary.index("BUILDING")

    orders = concat_batches(o_dev) if len(o_dev) > 1 else o_dev[0]
    customer = concat_batches(c_dev) if len(c_dev) > 1 else c_dev[0]
    aggs = [AggSpec("sum", 3, T.DOUBLE, "revenue")]

    @jax.jit
    def build_orders(orders: Batch, customer: Batch) -> Batch:
        cust_mask = customer.row_mask & (customer.columns[1].data
                                         == seg_code)
        cust = Batch(customer.schema, customer.columns, cust_mask)
        omask = (orders.row_mask & (orders.columns[2].data < D_Q3)
                 & semi_join_mask(orders, cust, [1], [0]))
        return Batch(orders.schema, orders.columns, omask)

    @jax.jit
    def probe(li: Batch, build: Batch) -> Batch:
        lmask = li.row_mask & (li.columns[3].data > D_Q3)
        li = Batch(li.schema, li.columns, lmask)
        j = lookup_join(li, build, [0], [0], payload=[2, 3],
                        payload_names=["o_orderdate", "o_shippriority"],
                        join_type="inner")
        # j: l_orderkey, l_extendedprice, l_discount, l_shipdate,
        #    o_orderdate, o_shippriority
        rev = j.columns[1].data * (1.0 - j.columns[2].data)
        fields = [("l_orderkey", T.BIGINT),
                  ("o_orderdate", j.schema.types[4]),
                  ("o_shippriority", j.schema.types[5]),
                  ("revenue", T.DOUBLE)]
        cols = [j.columns[0], j.columns[4], j.columns[5],
                Column(T.DOUBLE, rev,
                       j.columns[1].validity & j.columns[2].validity, None)]
        ext = Batch(Schema(fields), cols, j.row_mask)
        return grouped_aggregate(ext, [0, 1, 2], aggs, mode="partial")

    def merge_fn(scap):
        @jax.jit
        def merge(parts):
            m = grouped_aggregate(concat_batches(parts), [0, 1, 2], aggs,
                                  mode="merge")
            # group count is bounded by the filtered orders, so a fixed
            # compaction capacity needs no host sync
            return m.compact(scap, check=False)
        return merge

    @jax.jit
    def finalize(state: Batch) -> Batch:
        out = grouped_aggregate(state, [0, 1, 2], aggs, mode="final")
        return top_n(out, [SortKey(3, ascending=False), SortKey(1)], 10)

    def device_chunks():
        if li_device:
            yield from li_dev
            return
        for c in li_host:
            arrays, mask = c[:-1], c[-1]
            yield Batch.from_arrays(li_schema, list(arrays),
                                    num_rows=int(mask.sum()))

    def run_device():
        build = build_orders(orders, customer)
        live_build = int(jnp.sum(build.row_mask))      # one host sync
        scap = bucket_capacity(max(live_build, 1))
        merge = merge_fn(scap)
        parts, state = [], None
        for b in device_chunks():
            parts.append(probe(b, build))
            if len(parts) == 8:
                grp = parts if state is None else [state] + parts
                state = merge(grp)
                parts = []
        if parts or state is None:
            grp = ([state] if state is not None else []) + parts
            state = merge(grp)
        return finalize(state).to_pylist()

    def run_numpy():
        ck, cseg, cmask = tuple(
            np.concatenate([h[i] for h in c_host]) for i in range(3))
        ok_, ocust, odate, oprio, omask = tuple(
            np.concatenate([h[i] for h in o_host]) for i in range(5))
        cust_keys = np.sort(ck[cmask & (cseg == seg_code)])
        om = omask & (odate < D_Q3)
        if len(cust_keys):
            pos = np.minimum(np.searchsorted(cust_keys, ocust),
                             len(cust_keys) - 1)
            om &= cust_keys[pos] == ocust
        else:
            om &= False
        bk = ok_[om]
        order_sort = np.argsort(bk, kind="stable")
        bkey = bk[order_sort]
        bdate = odate[om][order_sort]
        bprio = oprio[om][order_sort]
        rev_acc = np.zeros(len(bkey))
        for (lk, price, disc, ship, mask) in li_host:
            m = mask & (ship > D_Q3)
            price2 = np.round(price, 2)
            disc2 = np.round(disc, 2)
            if not len(bkey):
                continue
            p = np.minimum(np.searchsorted(bkey, lk), len(bkey) - 1)
            hit = m & (bkey[p] == lk)
            np.add.at(rev_acc, p[hit], price2[hit] * (1.0 - disc2[hit]))
        nz = rev_acc > 0
        order = np.lexsort((bdate[nz], -rev_acc[nz]))[:10]
        return [(int(k), float(r), int(d), int(pr))
                for k, r, d, pr in zip(bkey[nz][order], rev_acc[nz][order],
                                       bdate[nz][order], bprio[nz][order])]

    got_rows, dev_s = _time(run_device)
    want, np_s = _time(run_numpy)
    got = [(r[0], r[3], r[1], r[2]) for r in got_rows]
    assert len(got) == len(want), (got, want)
    for g, w in zip(got, want):
        assert g[0] == w[0] and abs(g[1] - w[1]) <= 1e-6 * abs(w[1]), (g, w)
    return total, dev_s, np_s


def main() -> None:
    sf_q6 = float(os.environ.get("BENCH_SF_Q6",
                                 os.environ.get("BENCH_SF", "1")))
    sf_q1 = float(os.environ.get("BENCH_SF_Q1", "10"))
    sf_q3 = float(os.environ.get("BENCH_SF_Q3", "10"))

    results = []
    for name, sf, fn in (("q6", sf_q6, bench_q6), ("q1", sf_q1, bench_q1),
                         ("q3", sf_q3, bench_q3)):
        total, dev_s, np_s = fn(sf)
        results.append({
            "metric": f"tpch_sf{sf:g}_{name}_rows_per_sec",
            "value": round(total / dev_s),
            "unit": "rows/s",
            "vs_baseline": round(np_s / dev_s, 3),
        })

    headline = dict(next(r for r in results if "_q1_" in r["metric"]))
    headline["sub_metrics"] = [r for r in results
                               if r["metric"] != headline["metric"]]
    print(json.dumps(headline))


if __name__ == "__main__":
    main()

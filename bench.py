"""Benchmark entry: TPC-H Q6 scan-filter-aggregate throughput on device.

Mirrors the reference's operator benchmark metric (reference
presto-benchmark/.../AbstractOperatorBenchmark.java:303-330 reports
input_rows_per_second over the hand-built Q6 pipeline in
HandTpchQuery6.java). The reference publishes no absolute numbers
(BASELINE.md), so `vs_baseline` is measured against a vectorized NumPy
implementation of the identical pipeline on this host — a stand-in for the
single-node columnar-Java operator loop until the Java harness is run on
comparable hardware.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""
from __future__ import annotations

import json
import os
import time

import numpy as np


def _numpy_q6(cols):
    """The same Q6 pipeline in vectorized NumPy (baseline proxy).

    The decimal-valued columns are re-quantized to 2dp: the TPU backend
    round-trips f64 as a double-double (f32 hi/lo) pair, which can lose
    the final ULP (0.05 -> 0.049999999999999996), and these columns are
    semantically DECIMAL(p,2) values, so rounding restores them exactly.
    """
    ship, disc, qty, price, mask = cols
    disc, qty, price = (np.round(c, 2) for c in (disc, qty, price))
    m = (mask & (ship >= 8766) & (ship < 9131)
         & (disc >= 0.05) & (disc <= 0.07) & (qty < 24.0))
    return float(np.sum(np.where(m, price * disc, 0.0)))


def main() -> None:
    sf = float(os.environ.get("BENCH_SF", "1"))
    import jax
    import jax.numpy as jnp

    from presto_tpu import types as T
    from presto_tpu.connectors.spi import TableHandle
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.expr.compiler import compile_filter, compile_projection
    from presto_tpu.ops.aggregation import AggSpec, global_aggregate

    import __graft_entry__ as ge

    conn = TpchConnector(sf=sf)
    th = TableHandle("tpch", "t", "lineitem")
    split = conn.split_manager.splits(th, 1)[0]
    host_batches = []  # keep host copies for the numpy baseline
    dev_batches = []
    total_rows = 0
    for b in conn.page_source(split, ge._Q6_COLS,
                              rows_per_batch=1 << 20).batches():
        dev_batches.append(b)
        # np.array (copy): np.asarray of a CPU-backend jax array can be a
        # zero-copy view whose XLA buffer is later reused, corrupting the
        # oracle inputs once the device pipeline runs.
        host_batches.append(tuple(
            np.array(c.data) for c in b.columns) + (np.array(b.row_mask),))
        total_rows += b.host_count()

    schema, pred, proj = ge._q6_exprs()
    filt = compile_filter(pred, schema)
    project = compile_projection(proj, ["rev"], schema)
    aggs = [AggSpec("sum", 0, T.DOUBLE, "revenue")]

    def q6_partial(batch):
        # one fused kernel per batch; a single scalar leaves the device
        p = global_aggregate(project(filt(batch)), aggs, mode="partial")
        return p.columns[0].data[0]

    step = jax.jit(q6_partial)
    combine = jax.jit(lambda vs: jnp.sum(jnp.stack(vs)))

    def run_device():
        # dispatch every batch asynchronously; sync exactly once at the
        # final scalar — the tunnel's ~100ms readback RTT would otherwise
        # dominate (a per-batch float() costs one full round trip each)
        parts = [step(b) for b in dev_batches]
        return float(combine(parts))

    got = run_device()  # warmup + compile
    t0 = time.perf_counter()
    got = run_device()
    dev_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    want = sum(_numpy_q6(c) for c in host_batches)
    np_s = time.perf_counter() - t0

    # double-double accumulation on TPU carries ~49 mantissa bits
    assert abs(got - want) <= 1e-8 * max(abs(want), 1.0), (got, want)
    dev_rps = total_rows / dev_s
    np_rps = total_rows / np_s
    print(json.dumps({
        "metric": f"tpch_sf{sf:g}_q6_rows_per_sec",
        "value": round(dev_rps),
        "unit": "rows/s",
        "vs_baseline": round(dev_rps / np_rps, 3),
    }))


if __name__ == "__main__":
    main()

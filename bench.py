"""Benchmark entry: TPC-H operator-pipeline throughput on device.

Mirrors the reference's operator benchmark metric (reference
presto-benchmark/.../AbstractOperatorBenchmark.java:303-330 reports
input_rows_per_second over hand-built operator pipelines,
HandTpchQuery1.java / HandTpchQuery6.java). Staged configs
(BASELINE.md): Q6 @ SF1 (scan-filter-agg), Q1 @ SF10 (group-by
aggregation), Q3 @ SF10 (3-way join + high-cardinality group-by + top-n;
set BENCH_SF_Q3=100 for the full-scale config when wall-clock allows),
and TPC-DS q55/q27 @ SF1 (star joins + ROLLUP, BASELINE config 4; the
engine runs the full SQL path — parse/plan/optimize/execute — while the
proxy computes the identical query; set BENCH_SF_DS to rescale).

Baseline: the reference publishes no absolute numbers and no JVM exists
in this image (BASELINE.md requires measuring the Java harness; `which
java` is empty here), so `vs_baseline` is measured against a vectorized
NumPy implementation of the IDENTICAL pipeline over the IDENTICAL
pre-generated chunks on this host (single core, like one Presto driver
thread). The proxy favors the baseline: NumPy's C kernels are at least
as fast per core as Presto's Java operator loops (whose PageProcessor
makes per-row virtual calls per column), so the reported ratio is a
LOWER bound on the vs-Java speedup per core.

Input generation is excluded from both sides' timing (both sides would
share the same host generator; the reference harness likewise reads
pre-staged in-memory pages). Device timing covers all compute plus the
final result readback; input staging is untimed on both sides.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline",
"sub_metrics": [...]}.
"""
from __future__ import annotations

import datetime
import json
import os
import time

import numpy as np

def _enable_compile_cache() -> None:
    """Persistent XLA compilation cache: the tunneled-TPU compile RTT
    dominates cold runs (a cold TPC-DS pipeline compiles for minutes);
    the cache makes driver re-runs warm. The env-var form is ignored by
    this backend, so set it through the config API (works any time
    before the first compilation)."""
    import jax
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def _epoch_day(y, m, d) -> int:
    return (datetime.date(y, m, d) - datetime.date(1970, 1, 1)).days


D_Q1 = _epoch_day(1998, 9, 2)    # 1998-12-01 - 90 days
D_Q3 = _epoch_day(1995, 3, 15)


def _stage(conn, table, cols, rows_per_batch, device: bool):
    """Generate a table's chunks once. Host copies keep the chunked shape
    (one chunk = one Presto page for the NumPy baseline); the device copy
    is ONE concatenated batch per table — a single large transfer per
    column instead of hundreds of small ones (the tunnel's per-transfer
    latency would otherwise dominate staging), and one big kernel launch
    instead of many (larger batches use the device better anyway)."""
    from presto_tpu.batch import Batch
    from presto_tpu.connectors.spi import TableHandle

    th = TableHandle("tpch", "t", table)
    split = conn.split_manager.splits(th, 1)[0]
    host, n = [], 0
    schema = None
    dicts = None
    # generate host-side (host_chunks): staging must not round-trip the
    # tunnel per chunk; the device copy below is one transfer per column
    ps = conn.page_source(split, cols, rows_per_batch=rows_per_batch)
    for chunk_schema, data, cn in ps.host_chunks():
        schema = chunk_schema.select(list(cols))
        arrays = []
        dicts = []
        for name in cols:
            arr, vocab = data[name]
            assert vocab != "text", "free-text columns not staged"
            arrays.append(np.asarray(arr))
            dicts.append(tuple(vocab) if vocab is not None else None)
        mask_np = np.ones(cn, dtype=bool)
        host.append(tuple(arrays) + (mask_np,))
        n += cn
    vocabs = dicts
    dev = []
    if device:
        # chunk the device copy at 2^23 rows: one 2^26-capacity batch made
        # the combined filter+8-agg kernel fault on v5e (each half of the
        # kernel runs fine at 2^26; the fused whole does not), and chunking
        # additionally reuses one compiled kernel, pipelines dispatch, and
        # caps HBM peaks. Chunks stay far above the size where per-launch
        # overhead matters.
        chunk_rows = 1 << 23
        arrays = [np.concatenate([h[i] for h in host])
                  for i in range(len(cols))]
        for lo in range(0, n, chunk_rows):
            cn = min(chunk_rows, n - lo)
            dev.append(Batch.from_arrays(
                schema, [a[lo:lo + cn] for a in arrays],
                dictionaries=dicts, num_rows=cn))
    return dev, host, n, schema, vocabs


def _time(fn):
    fn()                            # warmup + compile
    t0 = time.perf_counter()
    got = fn()
    return got, time.perf_counter() - t0


#: proxy repetitions for the CURRENT config — set by main() per config:
#: 1 when a pinned proxy time exists (the pin carries the ratio), else 3
_PROXY_RUNS = 3


def _time_proxy(fn):
    """Warmup + best-of-N wall clock for the NumPy proxy. The proxy runs
    on a SHARED host: a contention spike on one run used to swing
    `vs_baseline` 2-3x between rounds (docs/perf.md) — min-of-N rejects
    the spikes, and main() additionally pins the first clean measurement
    in BASELINE_PROXY.json so later rounds' gate numbers move only when
    the ENGINE moves."""
    got, best = _time(fn)
    for _ in range(max(0, _PROXY_RUNS - 1)):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return got, best


_PROXY_PIN_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BASELINE_PROXY.json")


def _load_proxy_pins() -> dict:
    try:
        with open(_PROXY_PIN_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _pin_proxy_seconds(metric: str, measured: float) -> float:
    """Proxy-seconds used for the gate ratio: the committed pin when one
    exists (so the ratio can't swing with host contention), else the
    fresh measurement — which is then written back so the NEXT run is
    pinned. BENCH_REPIN=1 forces re-measurement to take over the pin."""
    pins = _load_proxy_pins()
    if metric in pins and not os.environ.get("BENCH_REPIN"):
        return float(pins[metric])
    pins[metric] = round(measured, 4)
    try:
        with open(_PROXY_PIN_PATH, "w") as f:
            json.dump(pins, f, indent=2, sort_keys=True)
            f.write("\n")
    except OSError:
        pass
    return measured


# ---------------------------------------------------------------------------
# Q6: scan-filter-aggregate (reference HandTpchQuery6.java)
# ---------------------------------------------------------------------------

def bench_q6(sf: float):
    import jax
    import jax.numpy as jnp
    from presto_tpu import types as T
    from presto_tpu.expr.compiler import compile_filter, compile_projection
    from presto_tpu.ops.aggregation import AggSpec, global_aggregate
    import __graft_entry__ as ge

    conn = _shared_tpch(sf)
    dev, host, total, _, _ = _stage(conn, "lineitem", ge._Q6_COLS,
                                    1 << 20, True)

    schema, pred, proj = ge._q6_exprs()
    filt = compile_filter(pred, schema)
    project = compile_projection(proj, ["rev"], schema)
    aggs = [AggSpec("sum", 0, T.DOUBLE, "revenue")]

    @jax.jit
    def q6_partial(batch):
        p = global_aggregate(project(filt(batch)), aggs, mode="partial")
        return p.columns[0].data[0]

    combine = jax.jit(lambda vs: jnp.sum(jnp.stack(vs)))

    def run_device():
        # async dispatch per batch; sync exactly once at the final scalar
        # (the tunnel's ~100ms readback RTT would otherwise dominate)
        return float(combine([q6_partial(b) for b in dev]))

    def run_numpy():
        acc = 0.0
        for ship, disc, qty, price, mask in host:
            # decimal columns re-quantized to 2dp: the TPU f64 is a
            # double-double that can lose the final ULP
            disc2, qty2, price2 = (np.round(c, 2)
                                   for c in (disc, qty, price))
            m = (mask & (ship >= 8766) & (ship < 9131)
                 & (disc2 >= 0.05) & (disc2 <= 0.07) & (qty2 < 24.0))
            acc += float(np.sum(np.where(m, price2 * disc2, 0.0)))
        return acc

    got, dev_s = _time(run_device)
    want, np_s = _time_proxy(run_numpy)
    assert abs(got - want) <= 1e-8 * max(abs(want), 1.0), (got, want)
    return total, dev_s, np_s


# ---------------------------------------------------------------------------
# Q1: group-by aggregation (reference HandTpchQuery1.java)
# ---------------------------------------------------------------------------

_Q1_COLS = ["l_returnflag", "l_linestatus", "l_quantity", "l_extendedprice",
            "l_discount", "l_tax", "l_shipdate"]


def bench_q1(sf: float):
    import jax
    from presto_tpu import types as T
    from presto_tpu.batch import Batch, Column, Schema, concat_batches
    from presto_tpu.ops.aggregation import AggSpec, grouped_aggregate

    conn = _shared_tpch(sf)
    dev, host, total, schema, _ = _stage(conn, "lineitem", _Q1_COLS,
                                         1 << 20, True)
    rf_vocab = dev[0].columns[0].dictionary
    ls_vocab = dev[0].columns[1].dictionary

    aggs = [
        AggSpec("sum", 2, T.DOUBLE, "sum_qty"),
        AggSpec("sum", 3, T.DOUBLE, "sum_base"),
        AggSpec("sum", 7, T.DOUBLE, "sum_disc_price"),
        AggSpec("sum", 8, T.DOUBLE, "sum_charge"),
        AggSpec("avg", 2, T.DOUBLE, "avg_qty"),
        AggSpec("avg", 3, T.DOUBLE, "avg_price"),
        AggSpec("avg", 4, T.DOUBLE, "avg_disc"),
        AggSpec("count_star", None, T.BIGINT, "count_order"),
    ]
    ext_schema = Schema(list(zip(schema.names, schema.types)) + [
        ("disc_price", T.DOUBLE), ("charge", T.DOUBLE)])

    @jax.jit
    def q1_partial(b: Batch) -> Batch:
        mask = b.row_mask & (b.columns[6].data <= D_Q1)
        price, disc, tax = (b.columns[i].data for i in (3, 4, 5))
        disc_price = price * (1.0 - disc)
        charge = disc_price * (1.0 + tax)
        valid = b.columns[3].validity & b.columns[4].validity
        cols = list(b.columns) + [
            Column(T.DOUBLE, disc_price, valid, None),
            Column(T.DOUBLE, charge, valid & b.columns[5].validity, None),
        ]
        ext = Batch(ext_schema, cols, mask)
        # <= 12 possible (returnflag, linestatus) slots: emit the partial
        # straight at 128-slot capacity — materializing a partial at the
        # 2^26 input capacity (13 state cols x 67M x 8B ~ 7GB) OOMs HBM
        # at SF10, which is what killed the round-2 bench
        return grouped_aggregate(ext, [0, 1], aggs, mode="partial",
                                 output_capacity=128)

    @jax.jit
    def q1_final(parts):
        states = concat_batches(parts, capacity=128 * len(parts))
        return grouped_aggregate(states, [0, 1], aggs, mode="final")

    def run_device():
        import jax.numpy as jnp
        out = q1_final([q1_partial(b) for b in dev])
        # scalar readback: on the tunneled backend block_until_ready
        # returns before remote execution completes, so force the whole
        # chain (and pay one honest result-delivery RTT, like the other
        # configs' result readbacks)
        float(jnp.sum(out.columns[2].data))
        return out

    def run_numpy():
        sums = {}
        for (rf, ls, qty, price, disc, tax, ship, mask) in host:
            m = mask & (ship <= D_Q1)
            qty2, price2, disc2, tax2 = (np.round(c, 2)
                                         for c in (qty, price, disc, tax))
            for code_rf in range(len(rf_vocab)):
                for code_ls in range(len(ls_vocab)):
                    g = m & (rf == code_rf) & (ls == code_ls)
                    if not g.any():
                        continue
                    dp = price2[g] * (1.0 - disc2[g])
                    ch = dp * (1.0 + tax2[g])
                    acc = sums.setdefault((code_rf, code_ls), np.zeros(6))
                    acc += [qty2[g].sum(), price2[g].sum(), dp.sum(),
                            ch.sum(), disc2[g].sum(), g.sum()]
        return sums

    out, dev_s = _time(run_device)
    want, np_s = _time_proxy(run_numpy)
    got = {(rf_vocab.index(r[0]), ls_vocab.index(r[1])): r[2:]
           for r in out.to_pylist()}
    assert set(got) == set(want), (sorted(got), sorted(want))
    for k, acc in want.items():
        g = got[k]
        for gv, wv in zip(g[:4], acc[:4]):
            assert abs(gv - wv) <= 1e-6 * max(abs(wv), 1.0), (k, g, acc)
        assert g[7] == int(acc[5]), (k, g, acc)
    return total, dev_s, np_s


# ---------------------------------------------------------------------------
# Q3: 3-way join + group-by + top-n (reference
# HashBuildAndJoinBenchmark.java shape with HandTpchQuery-style agg)
# ---------------------------------------------------------------------------

def bench_q3(sf: float):
    """Q3 device plan: eager aggregation pushed through the join.

    The grouping key (l_orderkey) IS the join key and o_orderkey is
    unique, so revenue partials can be aggregated on the probe side
    BEFORE the join (the reference's
    iterative/rule/PushPartialAggregationThroughJoin.java rewrite) into
    a direct-address slot table over the o_orderkey span (reference
    BigintGroupByHash.java's dense-int mode). The join then degenerates
    to ONE gather per filtered order — no sort, no per-chunk group-by,
    no probe binary search. Exact sums come from i32 digit scatters
    (ops/scatter_agg.py): f64/i64 scatters are ~14x slower on this chip.
    TPC-H spec: at most 7 lineitems per order, so i32 digit sums cannot
    overflow (w=28: 2^28 * 7 < 2^31)."""
    import jax
    import jax.numpy as jnp
    from presto_tpu.batch import Batch, bucket_capacity, concat_batches
    from presto_tpu.ops.scatter_agg import segment_sum_exact

    conn = _shared_tpch(sf)
    li_cols = ["l_orderkey", "l_extendedprice", "l_discount", "l_shipdate"]
    o_cols = ["o_orderkey", "o_custkey", "o_orderdate", "o_shippriority"]
    c_cols = ["c_custkey", "c_mktsegment"]
    # lineitem beyond ~SF20 would not fit on one chip: stream from host
    li_device = sf <= 20
    li_dev, li_host, n_li, li_schema, _ = _stage(conn, "lineitem",
                                                 li_cols, 1 << 20,
                                                 li_device)
    o_dev, o_host, n_o, _, _ = _stage(conn, "orders", o_cols, 1 << 20,
                                      True)
    c_dev, c_host, n_c, _, _ = _stage(conn, "customer", c_cols, 1 << 20,
                                      True)
    total = n_li + n_o + n_c
    seg_code = c_dev[0].columns[1].dictionary.index("BUILDING")

    orders = concat_batches(o_dev) if len(o_dev) > 1 else o_dev[0]
    customer = concat_batches(c_dev) if len(c_dev) > 1 else c_dev[0]

    @jax.jit
    def all_key_bounds(orders: Batch, customer: Batch):
        out = []
        for b in (orders, customer):
            k = b.columns[0].data
            live = b.row_mask & b.columns[0].validity
            out.append(jnp.min(jnp.where(live, k,
                                         jnp.iinfo(jnp.int64).max)))
            out.append(jnp.max(jnp.where(live, k,
                                         jnp.iinfo(jnp.int64).min)))
        return jnp.stack(out)

    def partial_fn(ok_lo, ok_cap):
        @jax.jit
        def partial(li: Batch, acc):
            # shipdate filter + revenue in 4-decimal fixed point (exact:
            # price/discount are 2-decimal quantities)
            lmask = li.row_mask & (li.columns[3].data > D_Q3)
            price, disc = li.columns[1].data, li.columns[2].data
            rev_int = jnp.round(price * (1.0 - disc) * 1e4).astype(
                jnp.int64)
            slot = jnp.clip(li.columns[0].data - ok_lo, 0,
                            ok_cap - 1).astype(jnp.int32)
            vals = jnp.where(lmask, rev_int, 0)
            # l_orderkey is physically ascending within a staged chunk
            return acc + segment_sum_exact(
                vals, slot, ok_cap, max_rows_per_segment=7,
                value_bits=31, indices_are_sorted=True)
        return partial

    def finalize_fn(ok_lo, ok_cap, c_lo, c_cap):
        @jax.jit
        def finalize(orders: Batch, customer: Batch, acc):
            # customer BUILDING membership as a direct-address bool table
            c_slot = jnp.clip(customer.columns[0].data - c_lo, 0,
                              c_cap - 1).astype(jnp.int32)
            c_building = (customer.row_mask & customer.columns[0].validity
                          & (customer.columns[1].data == seg_code))
            seg_table = jnp.zeros(c_cap, dtype=bool).at[c_slot].max(
                c_building)
            ok, ocust = orders.columns[0].data, orders.columns[1].data
            odate = orders.columns[2].data.astype(jnp.int64)
            oprio = orders.columns[3].data
            o_live = (orders.row_mask & (odate < D_Q3)
                      & jnp.take(seg_table,
                                 jnp.clip(ocust - c_lo, 0, c_cap - 1)
                                 .astype(jnp.int32), axis=0))
            # the pushed-down join: one gather of the revenue slot table
            rev_int = jnp.take(acc, jnp.clip(ok - ok_lo, 0, ok_cap - 1)
                               .astype(jnp.int32), axis=0)
            cand = o_live & (rev_int > 0)
            # ORDER BY revenue DESC, o_orderdate ASC as one packed i64:
            # rev_int < 2^43 and epoch-day < 2^15
            key = jnp.where(cand, rev_int * (1 << 15) + (32767 - odate),
                            -1)
            top, idx = jax.lax.top_k(key, 10)
            gather = lambda a: jnp.take(a, idx, axis=0)
            return (top, gather(ok), gather(rev_int), gather(odate),
                    gather(oprio))
        return finalize

    def device_chunks():
        if li_device:
            yield from li_dev
            return
        for c in li_host:
            arrays, mask = c[:-1], c[-1]
            yield Batch.from_arrays(li_schema, list(arrays),
                                    num_rows=int(mask.sum()))

    def run_device():
        bounds = [int(v) for v in all_key_bounds(orders, customer)]
        ok_lo, ok_hi, c_lo, c_hi = bounds             # one host sync
        ok_cap = bucket_capacity(max(ok_hi - ok_lo + 1, 1))
        c_cap = bucket_capacity(max(c_hi - c_lo + 1, 1))
        partial = partial_fn(ok_lo, ok_cap)
        finalize = finalize_fn(ok_lo, ok_cap, c_lo, c_cap)
        acc = jnp.zeros(ok_cap, dtype=jnp.int64)
        for b in device_chunks():
            acc = partial(b, acc)
        top, ok, rev_int, odate, oprio = (
            np.asarray(v) for v in finalize(orders, customer, acc))
        return [(int(k), int(r) / 1e4, int(d), int(p))
                for t, k, r, d, p in zip(top, ok, rev_int, odate, oprio)
                if t >= 0]

    def run_numpy():
        ck, cseg, cmask = tuple(
            np.concatenate([h[i] for h in c_host]) for i in range(3))
        ok_, ocust, odate, oprio, omask = tuple(
            np.concatenate([h[i] for h in o_host]) for i in range(5))
        cust_keys = np.sort(ck[cmask & (cseg == seg_code)])
        om = omask & (odate < D_Q3)
        if len(cust_keys):
            pos = np.minimum(np.searchsorted(cust_keys, ocust),
                             len(cust_keys) - 1)
            om &= cust_keys[pos] == ocust
        else:
            om &= False
        bk = ok_[om]
        order_sort = np.argsort(bk, kind="stable")
        bkey = bk[order_sort]
        bdate = odate[om][order_sort]
        bprio = oprio[om][order_sort]
        rev_acc = np.zeros(len(bkey))
        for (lk, price, disc, ship, mask) in li_host:
            m = mask & (ship > D_Q3)
            price2 = np.round(price, 2)
            disc2 = np.round(disc, 2)
            if not len(bkey):
                continue
            p = np.minimum(np.searchsorted(bkey, lk), len(bkey) - 1)
            hit = m & (bkey[p] == lk)
            np.add.at(rev_acc, p[hit], price2[hit] * (1.0 - disc2[hit]))
        nz = rev_acc > 0
        order = np.lexsort((bdate[nz], -rev_acc[nz]))[:10]
        return [(int(k), float(r), int(d), int(pr))
                for k, r, d, pr in zip(bkey[nz][order], rev_acc[nz][order],
                                       bdate[nz][order], bprio[nz][order])]

    got, dev_s = _time(run_device)
    want, np_s = _time_proxy(run_numpy)
    assert len(got) == len(want), (got, want)
    for g, w in zip(got, want):
        assert g[0] == w[0] and abs(g[1] - w[1]) <= 1e-6 * abs(w[1]), (g, w)
    return total, dev_s, np_s


# ---------------------------------------------------------------------------
# Q1 through the ENGINE SQL path: parse -> plan -> optimize -> execute.
# The hand pipeline above proves the kernels; this config makes the
# planner/executor overhead on TPC-H visible to the gate (VERDICT.md
# weak point 2 — previously only the TPC-DS configs exercised it).
# ---------------------------------------------------------------------------

_TPCH_Q1 = """
select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty,
  sum(l_extendedprice) as sum_base_price,
  sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
  sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
  avg(l_quantity) as avg_qty, avg(l_extendedprice) as avg_price,
  avg(l_discount) as avg_disc, count(*) as count_order
from lineitem
where l_shipdate <= date '1998-12-01' - interval '90' day
group by l_returnflag, l_linestatus
order by l_returnflag, l_linestatus
"""


def bench_q1sql(sf: float):
    conn = _shared_tpch(sf)
    runner = _shared_runner("tpch", sf)
    _, host, total, _, vocabs = _stage(conn, "lineitem", _Q1_COLS,
                                       1 << 20, False)
    rf_vocab, ls_vocab = vocabs[0], vocabs[1]

    def run_engine():
        return runner.execute(_TPCH_Q1).rows

    def run_numpy():
        sums = {}
        for (rf, ls, qty, price, disc, tax, ship, mask) in host:
            m = mask & (ship <= D_Q1)
            qty2, price2, disc2, tax2 = (np.round(c, 2)
                                         for c in (qty, price, disc, tax))
            for code_rf in range(len(rf_vocab)):
                for code_ls in range(len(ls_vocab)):
                    g = m & (rf == code_rf) & (ls == code_ls)
                    if not g.any():
                        continue
                    dp = price2[g] * (1.0 - disc2[g])
                    ch = dp * (1.0 + tax2[g])
                    acc = sums.setdefault((code_rf, code_ls), np.zeros(6))
                    acc += [qty2[g].sum(), price2[g].sum(), dp.sum(),
                            ch.sum(), disc2[g].sum(), g.sum()]
        rows = []
        for (crf, cls_), a in sums.items():
            n = a[5]
            rows.append((rf_vocab[crf], ls_vocab[cls_], a[0], a[1], a[2],
                         a[3], a[0] / n, a[1] / n, a[4] / n, int(n)))
        rows.sort(key=lambda r: (r[0], r[1]))
        return rows

    got, dev_s = _time(run_engine)
    want, np_s = _time_proxy(run_numpy)
    assert len(got) == len(want), (got, want)
    for g, w in zip(got, want):
        assert (str(g[0]), str(g[1])) == (w[0], w[1]), (g, w)
        for gv, wv in zip(g[2:9], w[2:9]):
            assert abs(float(gv) - wv) <= 1e-6 * max(abs(wv), 1.0), (g, w)
        assert int(g[9]) == w[9], (g, w)
    return total, dev_s, np_s


# ---------------------------------------------------------------------------
# TPC-DS q55 / q27 (BASELINE config 4): macro SQL benchmark, engine vs a
# vectorized NumPy implementation of the identical query over the identical
# pre-staged data (reference presto-benchto-benchmarks/.../tpcds/q55.sql,
# q27.sql; macro metric per PrestoBenchmarkDriver = query wall-clock).
# ---------------------------------------------------------------------------

_DS_Q55 = """
select i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manager_id = 28 and d_moy = 11 and d_year = 1999
group by i_brand, i_brand_id
order by ext_price desc, i_brand_id
limit 100
"""

_DS_Q27 = """
select i_item_id, s_state, grouping(s_state) g_state,
       avg(ss_quantity) agg1, avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3, avg(ss_sales_price) agg4
from store_sales, customer_demographics, date_dim, store, item
where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
  and ss_store_sk = s_store_sk and ss_cdemo_sk = cd_demo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College' and d_year = 2002
  and s_state in ('TN', 'TN', 'TN', 'TN', 'TN', 'TN')
group by rollup (i_item_id, s_state)
order by i_item_id nulls last, s_state nulls last
limit 100
"""


#: shared connector/runner instances across query configs: q55 and q27
#: used to each rebuild the SF10 TPC-DS dataset from scratch (~230s of
#: wall per config, mostly datagen); one TpcdsConnector + one engine
#: runner per scale factor means the tables generate once, and the
#: engine-side device scan cache (exec/scancache.py) carries hot split
#: data from one config's warmup into the next config's run
_SHARED_CONNS: dict = {}
_SHARED_RUNNERS: dict = {}


def _shared_tpch(sf: float):
    from presto_tpu.connectors.tpch import TpchConnector
    key = ("tpch", sf)
    if key not in _SHARED_CONNS:
        _SHARED_CONNS[key] = TpchConnector(sf=sf)
    return _SHARED_CONNS[key]


def _shared_tpcds(sf: float):
    from presto_tpu.connectors.tpcds import TpcdsConnector
    key = ("tpcds", sf)
    if key not in _SHARED_CONNS:
        _SHARED_CONNS[key] = TpcdsConnector(sf=sf)
    return _SHARED_CONNS[key]


def _shared_runner(catalog: str, sf: float):
    """One LocalRunner per (catalog, sf), mounted over the shared
    connector; the device scan cache persists across configs so the
    engine's timed runs read device-resident pages — the same footing
    as the NumPy proxy and the reference harness
    (AbstractOperatorBenchmark reads pre-staged in-memory pages)."""
    from presto_tpu.connectors.spi import CatalogManager
    from presto_tpu.exec.runner import LocalRunner
    key = (catalog, sf)
    if key not in _SHARED_RUNNERS:
        conn = (_shared_tpch(sf) if catalog == "tpch"
                else _shared_tpcds(sf))
        catalogs = CatalogManager()
        catalogs.register(catalog, conn)
        # 2^22-row scan batches for the TPC-DS macro configs: the
        # device-resident scan cache makes big batches free on re-runs
        # (no host re-decode per query), and 4x fewer batches means 4x
        # fewer per-batch tunnel dispatches and fused-chain liveness
        # syncs — the round-5/6 notes put per-batch dispatch latency
        # among q55/q27's dominant costs. Stays 16x under the 2^26
        # capacity that faulted a fused kernel on v5e (round 2) and 2x
        # under the 2^23 staging chunks the hand configs already use.
        rpb = (1 << 22) if catalog == "tpcds" else (1 << 20)
        runner = LocalRunner(catalogs=catalogs, catalog=catalog,
                             rows_per_batch=rpb)
        # SF10 q1sql/q27 column sets run ~2-3.5GB of decoded device
        # columns each; the default 2GB cap would thrash between
        # configs (the limit is process-wide, so set it on the cache —
        # it is deliberately not a session property)
        from presto_tpu.exec.scancache import CACHE
        CACHE.set_limit(6 << 30)
        # 4 scan threads: 4-way split datagen/decode overlap on the
        # cold pass (the warm pass reads the cache either way)
        runner.session.properties["scan_threads"] = 4
        _SHARED_RUNNERS[key] = runner
    return _SHARED_RUNNERS[key]


#: per-table UNION of every proxy config's columns, so one generation
#: pass serves both q55 and q27 (the raw arrays cache undecoded;
#: dictionary decode happens per request below)
_DS_PROXY_COLS = {
    "date_dim": ("d_date_sk", "d_moy", "d_year"),
    "item": ("i_item_sk", "i_item_id", "i_brand_id", "i_brand",
             "i_manager_id"),
    "store": ("s_store_sk", "s_state"),
    "customer_demographics": ("cd_demo_sk", "cd_gender",
                              "cd_marital_status",
                              "cd_education_status"),
    "store_sales": ("ss_sold_date_sk", "ss_item_sk",
                    "ss_ext_sales_price", "ss_cdemo_sk", "ss_store_sk",
                    "ss_quantity", "ss_list_price", "ss_coupon_amt",
                    "ss_sales_price"),
}
_NP_COLS_CACHE: dict = {}


def _np_cols(conn, table, cols, decode=()):
    """One table's columns as host numpy arrays (dict columns decoded to
    object arrays when listed in ``decode``), generated host-side ONCE
    per (connector, table) — the union of every config's columns — and
    served from cache thereafter."""
    from presto_tpu.connectors.spi import TableHandle

    key = (id(conn), table)
    got = _NP_COLS_CACHE.get(key)
    if got is None:
        gen_cols = list(_DS_PROXY_COLS.get(table, ()))
        for c in cols:
            if c not in gen_cols:
                gen_cols.append(c)
        th = TableHandle("tpcds", "default", table)
        parts = {c: [] for c in gen_cols}
        vocabs: dict = {}
        n = 0
        for split in conn.split_manager.splits(th, 1):
            ps = conn.page_source(split, gen_cols, rows_per_batch=1 << 20)
            for _, data, cn in ps.host_chunks():
                for c in gen_cols:
                    arr, vocab = data[c]
                    parts[c].append(np.asarray(arr))
                    vocabs[c] = vocab
                n += cn
        got = ({c: np.concatenate(v) for c, v in parts.items()},
               vocabs, n)
        _NP_COLS_CACHE[key] = got
    raw, vocabs, n = got
    out = {}
    for c in cols:
        arr = raw[c]
        vocab = vocabs.get(c)
        if c in decode and vocab is not None and vocab != "text":
            arr = np.asarray(tuple(vocab), dtype=object)[arr]
        out[c] = arr
    return out, n


def bench_q55(sf: float):
    conn = _shared_tpcds(sf)
    runner = _shared_runner("tpcds", sf)

    dd, n_dd = _np_cols(conn, "date_dim", ["d_date_sk", "d_moy", "d_year"])
    it, n_it = _np_cols(conn, "item",
                        ["i_item_sk", "i_brand_id", "i_brand",
                         "i_manager_id"], decode=("i_brand",))
    ss, n_ss = _np_cols(conn, "store_sales",
                        ["ss_sold_date_sk", "ss_item_sk",
                         "ss_ext_sales_price"])
    total = n_dd + n_it + n_ss

    def run_engine():
        return runner.execute(_DS_Q55).rows

    def run_numpy():
        dks = np.sort(dd["d_date_sk"][(dd["d_moy"] == 11)
                                      & (dd["d_year"] == 1999)])
        im = it["i_manager_id"] == 28
        iks = it["i_item_sk"][im]
        order = np.argsort(iks, kind="stable")
        iks = iks[order]
        brand_id = it["i_brand_id"][im][order]
        brand = it["i_brand"][im][order]
        m = np.zeros(len(ss["ss_item_sk"]), dtype=bool)
        if len(dks):
            p = np.minimum(np.searchsorted(dks, ss["ss_sold_date_sk"]),
                           len(dks) - 1)
            m = dks[p] == ss["ss_sold_date_sk"]
        if not len(iks):
            return []
        q = np.minimum(np.searchsorted(iks, ss["ss_item_sk"]), len(iks) - 1)
        m &= iks[q] == ss["ss_item_sk"]
        acc = np.zeros(len(iks))
        np.add.at(acc, q[m], np.round(ss["ss_ext_sales_price"][m], 2))
        # group by (brand, brand_id): item_sk -> brand ids may repeat
        keys = {}
        for j in np.nonzero(acc != 0)[0]:
            k = (int(brand_id[j]), str(brand[j]))
            keys[k] = keys.get(k, 0.0) + acc[j]
        rows = sorted(((bid, b, v) for (bid, b), v in keys.items()),
                      key=lambda r: (-r[2], r[0]))[:100]
        return rows

    got, dev_s = _time(run_engine)
    # the scan-cache warm/cold sub-metric (acceptance: warm re-run of a
    # scan-heavy query measurably beats its cold run): the timed run
    # above hit the device-resident cache; one more run with the
    # scan_cache=false escape hatch pays the decode+staging wall again
    # (kernels stay jit-warm, so the delta isolates the input side)
    t0 = time.perf_counter()
    nocache = runner.execute(_DS_Q55,
                             properties={"scan_cache": False}).rows
    nocache_s = time.perf_counter() - t0
    assert nocache == got, "scan_cache=false changed q55 results"
    want, np_s = _time_proxy(run_numpy)
    assert len(got) == len(want), (got[:3], want[:3])
    for g, w in zip(got, want):
        assert int(g[0]) == w[0] and str(g[1]) == w[1], (g, w)
        assert abs(float(g[2]) - w[2]) <= 1e-6 * max(abs(w[2]), 1.0), (g, w)
    return total, dev_s, np_s, {
        "scan_cache_warm_s": round(dev_s, 4),
        "scan_cache_cold_s": round(nocache_s, 4)}


def bench_q27(sf: float):
    conn = _shared_tpcds(sf)
    runner = _shared_runner("tpcds", sf)

    dd, n_dd = _np_cols(conn, "date_dim", ["d_date_sk", "d_year"])
    it, n_it = _np_cols(conn, "item", ["i_item_sk", "i_item_id"],
                        decode=("i_item_id",))
    st, n_st = _np_cols(conn, "store", ["s_store_sk", "s_state"],
                        decode=("s_state",))
    cd, n_cd = _np_cols(conn, "customer_demographics",
                        ["cd_demo_sk", "cd_gender", "cd_marital_status",
                         "cd_education_status"],
                        decode=("cd_gender", "cd_marital_status",
                                "cd_education_status"))
    ss, n_ss = _np_cols(conn, "store_sales",
                        ["ss_sold_date_sk", "ss_item_sk", "ss_cdemo_sk",
                         "ss_store_sk", "ss_quantity", "ss_list_price",
                         "ss_coupon_amt", "ss_sales_price"])
    total = n_dd + n_it + n_st + n_cd + n_ss

    def run_engine():
        return runner.execute(_DS_Q27).rows

    def run_numpy():
        def member_mask(sorted_keys, values):
            if not len(sorted_keys):
                return np.zeros(len(values), dtype=bool)
            p = np.minimum(np.searchsorted(sorted_keys, values),
                           len(sorted_keys) - 1)
            return sorted_keys[p] == values

        dks = np.sort(dd["d_date_sk"][dd["d_year"] == 2002])
        cdm = ((cd["cd_gender"] == "M") & (cd["cd_marital_status"] == "S")
               & (cd["cd_education_status"] == "College"))
        cks = np.sort(cd["cd_demo_sk"][cdm])
        stm = st["s_state"] == "TN"
        sks = st["s_store_sk"][stm]
        s_order = np.argsort(sks, kind="stable")
        sks_sorted = sks[s_order]
        state_by_store = st["s_state"][stm][s_order]
        iks = it["i_item_sk"]
        i_order = np.argsort(iks, kind="stable")
        iks_sorted = iks[i_order]
        iid_by_item = it["i_item_id"][i_order]

        m = (member_mask(dks, ss["ss_sold_date_sk"])
             & member_mask(cks, ss["ss_cdemo_sk"])
             & member_mask(sks_sorted, ss["ss_store_sk"])
             & member_mask(iks_sorted, ss["ss_item_sk"]))
        ii = np.searchsorted(iks_sorted, ss["ss_item_sk"][m])
        si = np.searchsorted(sks_sorted, ss["ss_store_sk"][m])
        measures = np.stack([
            np.round(ss["ss_quantity"][m].astype(np.float64), 2),
            np.round(ss["ss_list_price"][m], 2),
            np.round(ss["ss_coupon_amt"][m], 2),
            np.round(ss["ss_sales_price"][m], 2)], axis=1)

        def agg(keys_tuple):
            groups = {}
            for idx in range(len(ii)):
                k = keys_tuple(idx)
                s, c = groups.setdefault(k, (np.zeros(4), 0))
                groups[k] = (s + measures[idx], c + 1)
            return groups

        rows = []
        g1 = agg(lambda i: (str(iid_by_item[ii[i]]),
                            str(state_by_store[si[i]])))
        for (iid, state), (s, c) in g1.items():
            rows.append((iid, state, 0) + tuple(s / c))
        g2 = agg(lambda i: str(iid_by_item[ii[i]]))
        for iid, (s, c) in g2.items():
            rows.append((iid, None, 1) + tuple(s / c))
        g3 = agg(lambda i: ())
        for _, (s, c) in g3.items():
            rows.append((None, None, 1) + tuple(s / c))
        rows.sort(key=lambda r: ((r[0] is None, r[0]),
                                 (r[1] is None, r[1])))
        return rows[:100]

    got, dev_s = _time(run_engine)
    want, np_s = _time_proxy(run_numpy)
    assert len(got) == len(want), (len(got), len(want))
    for g, w in zip(got, want):
        assert (g[0], g[1], int(g[2])) == (w[0], w[1], w[2]), (g, w)
        for gv, wv in zip(g[3:], w[3:]):
            assert abs(float(gv) - wv) <= 1e-6 * max(abs(wv), 1.0), (g, w)
    return total, dev_s, np_s


# ---------------------------------------------------------------------------
# BASELINE config 5: Hive/ORC lineitem — scan-filter-aggregate with
# on-device columnar (RLEv2) decode through the real ORC reader
# (formats/orc_rle.py), the config VERDICT.md round 5 flagged as never
# benchmarked. Slow-tier guarded: the ORC dataset writes once per run
# and the decode path is the cost being measured, so the config only
# joins the tuple under BENCH_ORC=1 (BENCH_SF_ORC rescales; BASELINE.md
# names SF1000 — far beyond this container, like configs 3/4's SF100).
# ---------------------------------------------------------------------------

_ORC_Q6 = """
select sum(l_extendedprice * l_discount) as revenue
from lineitem
where l_shipdate >= date '1994-01-01' and l_shipdate < date '1995-01-01'
  and l_discount between 0.05 and 0.07 and l_quantity < 24
"""


def bench_q6orc(sf: float):
    import tempfile

    from presto_tpu.batch import Batch
    from presto_tpu.connectors.orc import OrcConnector
    from presto_tpu.connectors.spi import CatalogManager
    from presto_tpu.exec.runner import LocalRunner
    import __graft_entry__ as ge

    import shutil

    src = _shared_tpch(sf)
    _, host, total, schema, _ = _stage(src, "lineitem", ge._Q6_COLS,
                                       1 << 20, False)
    root = tempfile.mkdtemp(prefix="bench_orc_")
    try:
        conn = OrcConnector(root)
        conn.create_table("lineitem", schema)
        for chunk in host:
            arrays, mask = chunk[:-1], chunk[-1]
            conn.append("lineitem", Batch.from_arrays(
                schema, list(arrays), num_rows=int(mask.sum())))
        catalogs = CatalogManager()
        catalogs.register("orc", conn)
        runner = LocalRunner(catalogs=catalogs, catalog="orc",
                             rows_per_batch=1 << 20)
        # the decode path IS the measurement: the device scan cache
        # would serve the warm (timed) run without touching the reader
        runner.session.properties["scan_cache"] = False

        def run_engine():
            return float(runner.execute(_ORC_Q6).rows[0][0])

        def run_numpy():
            acc = 0.0
            for ship, disc, qty, price, mask in host:
                disc2, qty2, price2 = (np.round(c, 2)
                                       for c in (disc, qty, price))
                m = (mask & (ship >= 8766) & (ship < 9131)
                     & (disc2 >= 0.05) & (disc2 <= 0.07)
                     & (qty2 < 24.0))
                acc += float(np.sum(np.where(m, price2 * disc2, 0.0)))
            return acc

        got, dev_s = _time(run_engine)
        want, np_s = _time_proxy(run_numpy)
        assert abs(got - want) <= 1e-6 * max(abs(want), 1.0), (got, want)
        return total, dev_s, np_s
    finally:
        # a GB-scale dataset per run must not accumulate across rounds
        shutil.rmtree(root, ignore_errors=True)


# ---------------------------------------------------------------------------
# Serving: concurrent-throughput axis (ROADMAP item 3). N concurrent
# protocol clients drive a mix of repeated parameterized statements
# through a real PrestoTpuServer (resource groups, plan cache, shared
# scans) — the axis every other config ignores: queries/sec under
# multi-tenant load, not one query's wall-clock. Run via
# `python bench.py serving` (or BENCH_SERVING=1); writes the summary to
# SERVING_OUT (default stdout only). tools/check_bench_regression.py
# gates it against the committed SERVING_r*.json.
# ---------------------------------------------------------------------------

#: the repeated-statement mix: dashboard-shaped parameterized queries —
#: a handful of distinct shapes, each fired many times (the plan cache's
#: steady-state case), plus EXECUTE-driven prepared statements
_SERVING_STATEMENTS = [
    "select count(*), sum(l_extendedprice) from lineitem "
    "where l_quantity > {q}",
    "select l_returnflag, count(*) from lineitem "
    "where l_discount between 0.0{d} and 0.08 group by l_returnflag "
    "order by l_returnflag",
    "select o_orderpriority, count(*) from orders "
    "where o_totalprice > {p} group by o_orderpriority "
    "order by o_orderpriority",
    "select n_name, count(*) from nation group by n_name "
    "order by n_name limit {n}",
]


def _serving_mix(n: int):
    """Deterministic mixed workload: ~4 distinct statement shapes over a
    small parameter domain, so most executions repeat an already-seen
    fingerprint (the dashboard traffic the plan cache exists for)."""
    out = []
    for i in range(n):
        tmpl = _SERVING_STATEMENTS[i % len(_SERVING_STATEMENTS)]
        out.append(tmpl.format(q=10 + (i // 4) % 3, d=1 + (i // 4) % 2,
                               p=1000 * (1 + (i // 4) % 3),
                               n=5 + (i // 4) % 2))
    return out


#: the EXECUTE-fleet mix: two prepared shapes, every client binding its
#: own parameters — the parameter-generic template cache's steady state
#: (one plan + one warm executable set across ALL bindings; each bound
#: fingerprint is distinct, so the result cache stays out of the way)
_SERVING_PREPARES = [
    ("dash_q", "select count(*), sum(l_extendedprice) from lineitem "
               "where l_quantity > ?"),
    ("dash_p", "select o_orderpriority, count(*) from orders "
               "where o_totalprice > ? group by o_orderpriority "
               "order by o_orderpriority"),
]


def _execute_fleet_mix(n: int):
    out = []
    for i in range(n):
        if i % 2 == 0:
            out.append(f"execute dash_q using {1 + i % 47}")
        else:
            out.append(f"execute dash_p using {100 * (1 + i % 97)}")
    return out


def _repeated_mix(n: int):
    """The standing-query mix: the SAME four statements over and over
    (dashboard refresh) — after the first executions every request is a
    result-cache hit served from stored host rows."""
    fixed = [_SERVING_STATEMENTS[j].format(q=10, d=1, p=1000, n=5)
             for j in range(len(_SERVING_STATEMENTS))]
    return [fixed[i % len(fixed)] for i in range(n)]


def _pct(sorted_vals, p):
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(int(p * len(sorted_vals)),
                           len(sorted_vals) - 1)]


def _slo_block(timeseries, slo) -> dict:
    """The summary's ``slo`` block — one schema, one builder
    (presto_tpu/obs/slo.py ``slo_block``; the coordinator serves the
    same document live on GET /v1/slo). Schema is owned by
    tools/slo_report.py — check_bench_regression --kind serving
    validates every pin through it."""
    from presto_tpu.obs.slo import slo_block
    return slo_block(timeseries, slo)


def bench_serving(sf: float = 0.01, clients: int = 16,
                  per_client: int = 8, mixes=("mixed", "execute",
                                              "repeated")):
    """Queries/sec + latency percentiles (overall AND per resource
    group) at ``clients`` concurrent protocol clients, across three
    workload phases:

    - **mixed** (the headline, metric-compatible with SERVING_r01): the
      dashboard statement mix, now served by the full cache stack
      (plan cache + plan templates + result cache);
    - **execute**: the EXECUTE fleet — two prepared statements, every
      client binding its own parameters; measures the parameter-generic
      template cache (hit rate = dep-valid template found minus guard
      fallbacks, over all lookups);
    - **repeated**: the standing-query mix (identical statements over
      and over); measures the versioned result cache.

    Plus the cold/warm probe split (cold pays
    parse+plan+optimize+compile; warm rides the caches).
    ``SERVING_CLIENTS`` / ``SERVING_QUERIES`` / ``SERVING_MIX`` (comma
    list of phases) make re-pins reproducible at any scale."""
    import threading

    from presto_tpu.client import StatementClient
    from presto_tpu.connectors.spi import CatalogManager
    from presto_tpu.exec.runner import LocalRunner
    from presto_tpu.obs.metrics import REGISTRY
    from presto_tpu.obs.slo import SLO
    from presto_tpu.obs.timeseries import TIMESERIES
    from presto_tpu.server.protocol import PrestoTpuServer

    catalogs = CatalogManager()
    catalogs.register("tpch", _shared_tpch(sf))
    runner = LocalRunner(catalogs=catalogs, rows_per_batch=1 << 17)
    # the serving stack under test: parameter-generic templates +
    # versioned result cache on top of the PR 8 plan cache. The mesh
    # auto-router (PR 11) stays at its default — with >1 visible device
    # cold executions shard over the mesh; the summary records whether
    # it engaged.
    runner.session.properties.update({"plan_template_cache": True,
                                      "result_cache": True})
    # both serving tenants declare SLOs (docs/observability.md): the
    # health plane (obs/timeseries.py + obs/slo.py) tracks them live
    # and the summary's ``slo`` block pins objectives + burn timeline.
    # Thresholds are deliberately generous — the pin asserts the plane
    # WORKS (timeline, windowed p95, no spurious pages), not that this
    # machine class is fast.
    _slo_spec = {"latencyTargetMs": 2000, "latencyObjective": 0.95,
                 "availabilityObjective": 0.99}
    srv = PrestoTpuServer(runner, resource_groups={
        "rootGroups": [
            {"name": "serving", "hardConcurrencyLimit": 8,
             "maxQueued": 10_000,
             "subGroups": [
                 {"name": "dash", "hardConcurrencyLimit": 8,
                  "schedulingWeight": 2, "slo": dict(_slo_spec)},
                 {"name": "adhoc", "hardConcurrencyLimit": 8,
                  "schedulingWeight": 1, "slo": dict(_slo_spec)}]}],
        "selectors": [{"user": "dash-.*", "group": "serving.dash"},
                      {"group": "serving.adhoc"}]})
    # dense sampling for the bench's short wall: the 5s default would
    # catch ~2 points per phase; 0.2s gives the burn timeline real
    # resolution. srv.start() installs the tracker + starts the loop.
    TIMESERIES.reset()
    SLO.reset()
    TIMESERIES.configure(sample_interval_s=0.2)
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        probe = _SERVING_STATEMENTS[0].format(q=10)

        # cold: first-ever execution pays parse+plan+optimize+jit
        # compile; warm (after the traffic phase): fingerprint hit in
        # the caches + warm executables
        c = StatementClient(base, user="bench")
        t0 = time.perf_counter()
        cold_rows = c.execute(probe).rows
        cold_s = time.perf_counter() - t0

        for name, sql in _SERVING_PREPARES:
            c.execute(f"prepare {name} from {sql}")

        _FAMS = ("plan_cache_", "plan_template_cache_", "result_cache_",
                 "scan_shared_attach_total", "mesh_path_selected_total")

        def snap():
            return {m["name"]: m["value"] for m in REGISTRY.snapshot()
                    if m["name"].startswith(_FAMS)}

        def run_phase(statements):
            """One concurrent phase; returns (overall latencies,
            per-group latencies, wall seconds, metric deltas)."""
            # warmup: one pass over the distinct statements so the
            # timed phase measures steady-state serving, not
            # first-compile
            for s in sorted(set(statements)):
                c.execute(s)
            # phase-edge sample: a toy-scale phase can finish entirely
            # between two 0.2s sampler ticks, leaving the SLO timeline
            # without a single windowed point for it ("degenerate slo
            # block") — flush one sample at phase open and one at phase
            # close so even the smallest run pins real p95 points
            TIMESERIES.sample()
            before = snap()
            latencies = []
            by_group = {"dash": [], "adhoc": []}
            lat_lock = threading.Lock()
            errors = []

            def client_loop(ci: int) -> None:
                group = "dash" if ci % 2 == 0 else "adhoc"
                cl = StatementClient(base, user=f"{group}-{ci}")
                try:
                    for qi in range(per_client):
                        sql = statements[(ci * per_client + qi)
                                         % len(statements)]
                        t = time.perf_counter()
                        cl.execute(sql)
                        dt = time.perf_counter() - t
                        with lat_lock:
                            latencies.append(dt)
                            by_group[group].append(dt)
                except Exception as e:   # surfaced, not lost
                    errors.append(f"client {ci}: {e}")

            threads = [threading.Thread(target=client_loop, args=(i,))
                       for i in range(clients)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall_s = time.perf_counter() - t0
            assert not errors, errors
            TIMESERIES.sample()   # phase-close flush (see phase open)
            after = snap()
            delta = {k: after.get(k, 0.0) - before.get(k, 0.0)
                     for k in after}
            latencies.sort()
            for v in by_group.values():
                v.sort()
            return latencies, by_group, wall_s, delta

        n = clients * per_client
        known = ("mixed", "execute", "repeated")
        bad = [m for m in mixes if m not in known]
        if bad or not mixes:
            raise ValueError(
                f"SERVING_MIX: unknown phase(s) {bad or mixes} — "
                f"choose from {', '.join(known)}")
        phases = {}
        if "mixed" in mixes:
            phases["mixed"] = run_phase(_serving_mix(n))
        if "execute" in mixes:
            phases["execute"] = run_phase(_execute_fleet_mix(n))
        if "repeated" in mixes:
            phases["repeated"] = run_phase(_repeated_mix(n))

        t0 = time.perf_counter()
        warm_rows = c.execute(probe).rows
        warm_s = time.perf_counter() - t0
        assert warm_rows == cold_rows, "warm re-run changed results"

        def rate(d, fam, extra_miss=0.0):
            hits = d.get(f"{fam}_hit_total", 0.0)
            misses = d.get(f"{fam}_miss_total", 0.0) + extra_miss
            return hits / max(hits + misses, 1.0)

        lat, groups, wall_s, delta = phases.get(
            "mixed", next(iter(phases.values())))
        qps = round(len(lat) / wall_s, 2)
        summary = {
            "metric": f"serving_tpch_sf{sf:g}_qps",
            "value": qps,
            "unit": "queries/s",
            "clients": clients,
            "queries": len(lat),
            "p50_ms": round(_pct(lat, 0.50) * 1e3, 2),
            "p95_ms": round(_pct(lat, 0.95) * 1e3, 2),
            "p99_ms": round(_pct(lat, 0.99) * 1e3, 2),
            "groups": {
                g: {"queries": len(v),
                    "p50_ms": round(_pct(v, 0.50) * 1e3, 2),
                    "p95_ms": round(_pct(v, 0.95) * 1e3, 2),
                    "p99_ms": round(_pct(v, 0.99) * 1e3, 2)}
                for g, v in groups.items()},
            "plan_cache_hit_rate": round(rate(delta, "plan_cache"), 4),
            "result_cache_hit_rate": round(
                rate(delta, "result_cache"), 4),
            "shared_scan_attaches": int(
                delta.get("scan_shared_attach_total", 0.0)),
            "mesh_path_selected": int(
                delta.get("mesh_path_selected_total", 0.0)),
            "cold_ms": round(cold_s * 1e3, 2),
            "warm_ms": round(warm_s * 1e3, 2),
            "warm_speedup": round(cold_s / warm_s, 2),
            "sub_metrics": [
                {"metric": f"serving_tpch_sf{sf:g}_p95_latency_ms",
                 "value": round(_pct(lat, 0.95) * 1e3, 2), "unit": "ms"},
                {"metric": f"serving_tpch_sf{sf:g}_warm_speedup",
                 "value": round(cold_s / warm_s, 2), "unit": "x"},
                {"metric": f"serving_tpch_sf{sf:g}_dash_p99_ms",
                 "value": round(_pct(groups["dash"], 0.99) * 1e3, 2),
                 "unit": "ms"},
                {"metric": f"serving_tpch_sf{sf:g}_adhoc_p99_ms",
                 "value": round(_pct(groups["adhoc"], 0.99) * 1e3, 2),
                 "unit": "ms"},
            ],
        }
        if "execute" in phases:
            elat, egroups, ewall, edelta = phases["execute"]
            tpl_hits = edelta.get("plan_template_cache_hit_total", 0.0)
            tpl_miss = edelta.get("plan_template_cache_miss_total", 0.0)
            tpl_fb = edelta.get(
                "plan_template_cache_guard_fallback_total", 0.0)
            tpl_rate = (tpl_hits - tpl_fb) / max(tpl_hits + tpl_miss,
                                                 1.0)
            summary["sub_metrics"] += [
                {"metric": f"serving_tpch_sf{sf:g}_execute_qps",
                 "value": round(len(elat) / ewall, 2),
                 "unit": "queries/s",
                 "p95_ms": round(_pct(elat, 0.95) * 1e3, 2),
                 "p99_ms": round(_pct(elat, 0.99) * 1e3, 2)},
                {"metric": f"serving_tpch_sf{sf:g}_template_hit_rate",
                 "value": round(tpl_rate, 4), "unit": "ratio",
                 "guard_fallbacks": int(tpl_fb)},
            ]
        if "repeated" in phases:
            rlat, rgroups, rwall, rdelta = phases["repeated"]
            summary["sub_metrics"] += [
                {"metric": f"serving_tpch_sf{sf:g}_repeated_qps",
                 "value": round(len(rlat) / rwall, 2),
                 "unit": "queries/s",
                 "p95_ms": round(_pct(rlat, 0.95) * 1e3, 2),
                 "p99_ms": round(_pct(rlat, 0.99) * 1e3, 2)},
                {"metric": f"serving_tpch_sf{sf:g}_result_hit_rate",
                 "value": round(rate(rdelta, "result_cache"), 4),
                 "unit": "ratio",
                 "partials": int(rdelta.get(
                     "result_cache_partial_total", 0.0))},
            ]
        summary["slo"] = _slo_block(TIMESERIES, SLO)
        return summary
    finally:
        TIMESERIES.stop()
        srv.stop()


def bench_serving_fleet(sf: float = 0.01, clients: int = 16,
                        per_client: int = 8,
                        mixes=("mixed", "execute", "repeated"),
                        n_coordinators: int = 3):
    """The horizontal-serving axis (SERVING_r04+): the SAME phases as
    :func:`bench_serving`, served by ``n_coordinators`` coordinator
    SUBPROCESSES (tools/fleet.py) over ONE shared worker pool, with
    every client a round-robin :class:`FleetClient` across the fleet.

    Beyond the classic summary (metric-compatible headline + phase
    sub-metrics + slo block, all aggregated fleet-wide), the summary
    carries a ``fleet`` block pinning what only a fleet can show:

    - per-coordinator QPS during the headline phase, plus the
      aggregate (the horizontal-scaling claim);
    - cache COHERENCE across coordinators: a write through coordinator
      0 must invalidate coordinator 1's warm result-cache entry via the
      bump broadcast (fleet_bump_fold_total observed over the wire),
      and the re-read through coordinator 1 must be row-exact;
    - the coordinator-kill drill: SIGKILL one coordinator mid-phase —
      ZERO failed statements (FleetClient failover) and the survivors
      declare the loss (coordinator_lost_total via staleness grace).

    The ``slo`` block becomes the MERGED multi-coordinator form
    (``coordinators: N``, every objective/timeline row tagged with its
    coordinator) — tools/slo_report.py validates both forms."""
    import tempfile
    import threading

    from presto_tpu.client import FleetClient, StatementClient
    from tools.fleet import launch_fleet

    tmpdir = tempfile.mkdtemp(prefix="fleet_bench_")
    sqlite_path = os.path.join(tmpdir, "fleet.db")
    fleet = launch_fleet(n_coordinators=n_coordinators, sf=sf,
                         workers=1, sqlite_path=sqlite_path,
                         heartbeat_s=0.5)
    urls = fleet.urls
    _FAMS = ("plan_cache_", "plan_template_cache_", "result_cache_",
             "scan_shared_attach_total", "mesh_path_selected_total",
             "serving_requests_total", "fleet_bump_", "fleet_heartbeat_",
             "coordinator_lost_total")
    try:
        # one pinned client per coordinator: warmup and the coherence
        # probe need COORDINATOR-ADDRESSED statements (caches are
        # per-process; FleetClient would smear them across the fleet)
        pinned = [StatementClient(u, user="bench") for u in urls]
        probe = _SERVING_STATEMENTS[0].format(q=10)

        t0 = time.perf_counter()
        cold_rows = pinned[0].execute(probe).rows
        cold_s = time.perf_counter() - t0

        # prepared statements are per-coordinator server state
        for cl in pinned:
            for name, sql in _SERVING_PREPARES:
                cl.execute(f"prepare {name} from {sql}")

        def live_idx():
            return [i for i, c in enumerate(fleet.coordinators)
                    if c["proc"].poll() is None]

        def fleet_snap():
            """(per-coordinator, aggregate) counter snapshots scraped
            from every live coordinator's /v1/metrics."""
            per, agg = {}, {}
            for i in live_idx():
                m = {k: v for k, v in fleet.metrics(i).items()
                     if k.startswith(_FAMS)}
                per[fleet.coordinators[i]["node_id"]] = m
                for k, v in m.items():
                    agg[k] = agg.get(k, 0.0) + v
            return per, agg

        def flush_slo():
            # GET /v1/slo samples the child's store first — the fleet
            # form of the phase-edge flush (degenerate-slo-block fix)
            for i in live_idx():
                fleet.slo(i)

        def run_fleet_phase(statements, kill_at: int = -1):
            """One concurrent phase through FleetClients. With
            ``kill_at >= 0``: SIGKILL that coordinator once a third of
            the statements completed (the chaos drill — still expects
            ZERO failed statements)."""
            for s in sorted(set(statements)):   # per-coordinator warm
                for cl in pinned:
                    if kill_at < 0 or cl is not pinned[kill_at] \
                            or fleet.coordinators[kill_at]["proc"]\
                            .poll() is None:
                        cl.execute(s)
            flush_slo()
            before_per, before = fleet_snap()
            latencies, errors = [], []
            by_group = {"dash": [], "adhoc": []}
            lat_lock = threading.Lock()
            failovers = [0]
            retries = [0]
            n = len(statements)

            def client_loop(ci: int) -> None:
                group = "dash" if ci % 2 == 0 else "adhoc"
                fc = FleetClient(urls, user=f"{group}-{ci}")
                try:
                    for qi in range(per_client):
                        sql = statements[(ci * per_client + qi) % n]
                        t = time.perf_counter()
                        fc.execute(sql)
                        dt = time.perf_counter() - t
                        with lat_lock:
                            latencies.append(dt)
                            by_group[group].append(dt)
                except Exception as e:   # surfaced, not lost
                    errors.append(f"client {ci}: {e}")
                finally:
                    with lat_lock:
                        failovers[0] += fc.failovers_total
                        retries[0] += fc.retries_total
                    fc.close()

            killer = None
            if kill_at >= 0:
                def kill_when_hot():
                    deadline = time.monotonic() + 120
                    while time.monotonic() < deadline:
                        with lat_lock:
                            done = len(latencies)
                        if done >= max(1, n // 3):
                            break
                        time.sleep(0.01)
                    fleet.kill_coordinator(kill_at)
                killer = threading.Thread(target=kill_when_hot)
                killer.start()

            threads = [threading.Thread(target=client_loop, args=(i,))
                       for i in range(clients)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall_s = time.perf_counter() - t0
            if killer is not None:
                killer.join()
            flush_slo()
            after_per, after = fleet_snap()
            delta = {k: after.get(k, 0.0) - before.get(k, 0.0)
                     for k in after}
            per_delta = {
                node: {k: m.get(k, 0.0) - before_per.get(node, {})
                       .get(k, 0.0) for k in m}
                for node, m in after_per.items()}
            latencies.sort()
            for v in by_group.values():
                v.sort()
            return {"lat": latencies, "groups": by_group,
                    "wall_s": wall_s, "delta": delta,
                    "per_delta": per_delta, "errors": errors,
                    "failovers": failovers[0], "retries": retries[0]}

        n = clients * per_client
        known = ("mixed", "execute", "repeated")
        bad = [m for m in mixes if m not in known]
        if bad or not mixes:
            raise ValueError(
                f"SERVING_MIX: unknown phase(s) {bad or mixes} — "
                f"choose from {', '.join(known)}")
        phases = {}
        if "mixed" in mixes:
            phases["mixed"] = run_fleet_phase(_serving_mix(n))
        if "execute" in mixes:
            phases["execute"] = run_fleet_phase(_execute_fleet_mix(n))
        if "repeated" in mixes:
            phases["repeated"] = run_fleet_phase(_repeated_mix(n))
        for name, ph in phases.items():
            assert not ph["errors"], (name, ph["errors"])

        t0 = time.perf_counter()
        warm_rows = pinned[0].execute(probe).rows
        warm_s = time.perf_counter() - t0
        assert warm_rows == cold_rows, "warm re-run changed results"

        # -- coherence probe: write through coordinator 0, observe the
        # bump fold AND the invalidated re-read on coordinator 1 ------
        coh_sql = "select count(*), sum(x) from fleetdb.default.coh"
        pinned[0].execute(
            "create table fleetdb.default.coh as select 1 as x")
        time.sleep(0.2)   # CTAS bump reaches peers before the warm read
        rows_before = pinned[1].execute(coh_sql).rows
        m1 = fleet.metrics(1)
        hits0 = m1.get("result_cache_hit_total", 0.0)
        folds0 = m1.get("fleet_bump_fold_total", 0.0)
        # second identical read on coordinator 1 = its OWN result-cache
        # hit (the cross-coordinator warm entry the write must kill)
        assert pinned[1].execute(coh_sql).rows == rows_before
        xcoord_hits = fleet.metrics(1).get(
            "result_cache_hit_total", 0.0) - hits0
        pinned[0].execute(
            "insert into fleetdb.default.coh select 2 as x")
        deadline = time.monotonic() + 10
        folds_after = folds0
        while time.monotonic() < deadline:
            folds_after = fleet.metrics(1).get(
                "fleet_bump_fold_total", 0.0)
            if folds_after > folds0:
                break
            time.sleep(0.05)
        rows_after = pinned[1].execute(coh_sql).rows
        coherence = {
            "bump_fold_delta": folds_after - folds0,
            "remote_invalidation_observed": folds_after > folds0,
            "xcoord_result_cache_hits": int(xcoord_hits),
            "rows_before": [[int(a), int(b)] for a, b in rows_before],
            "rows_after": [[int(a), int(b)] for a, b in rows_after],
            "row_exact": [[int(a), int(b)] for a, b in rows_after]
            == [[2, 3]],
        }
        assert coherence["remote_invalidation_observed"], coherence
        assert coherence["row_exact"], coherence

        # merged multi-coordinator slo block (all coordinators alive)
        slo_merged = {"coordinators": len(urls),
                      "sample_interval_s": None,
                      "objectives": [], "alerts": [], "timeline": []}
        for i in live_idx():
            node = fleet.coordinators[i]["node_id"]
            blk = fleet.slo(i)
            if slo_merged["sample_interval_s"] is None:
                slo_merged["sample_interval_s"] = \
                    blk.get("sample_interval_s")
            for key in ("objectives", "alerts", "timeline"):
                for row in blk.get(key) or ():
                    slo_merged[key].append(
                        {**row, "coordinator": node})

        # -- the kill drill: lose coordinator N-1 mid-phase -----------
        kill_at = len(urls) - 1
        killed_id = fleet.coordinators[kill_at]["node_id"]
        kp = run_fleet_phase(_serving_mix(n), kill_at=kill_at)
        lost = 0.0
        survivor_lost = []
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            _, agg_now = fleet_snap()
            lost = agg_now.get("coordinator_lost_total", 0.0)
            # wait for the SURVEYED survivor's own sweep, not just any
            # survivor's counter — each coordinator declares the loss
            # on its own heartbeat cadence
            survivor_lost = fleet.fleet_status(0).get("lost", [])
            if lost >= 1.0 and killed_id in survivor_lost:
                break
            time.sleep(0.1)
        kill_block = {
            "killed": killed_id,
            "queries": len(kp["lat"]),
            "failed_queries": len(kp["errors"]),
            "client_failovers": kp["failovers"],
            "client_retries": kp["retries"],
            "coordinator_lost_total": lost,
            "survivor_lost_view": survivor_lost,
        }
        assert kill_block["failed_queries"] == 0, kp["errors"]
        assert lost >= 1.0, kill_block
        assert killed_id in survivor_lost, kill_block

        def rate(d, fam, extra_miss=0.0):
            hits = d.get(f"{fam}_hit_total", 0.0)
            misses = d.get(f"{fam}_miss_total", 0.0) + extra_miss
            return hits / max(hits + misses, 1.0)

        head = phases.get("mixed", next(iter(phases.values())))
        lat, groups = head["lat"], head["groups"]
        wall_s, delta = head["wall_s"], head["delta"]
        qps = round(len(lat) / wall_s, 2)

        def coord_requests(per_delta):
            return {node: sum(v for k, v in d.items()
                              if k.startswith("serving_requests_total"))
                    for node, d in per_delta.items()}

        head_reqs = coord_requests(head["per_delta"])
        per_coordinator_qps = {
            node: round(reqs / wall_s, 2)
            for node, reqs in sorted(head_reqs.items())}

        summary = {
            "metric": f"serving_tpch_sf{sf:g}_qps",
            "value": qps,
            "unit": "queries/s",
            "clients": clients,
            "queries": len(lat),
            "p50_ms": round(_pct(lat, 0.50) * 1e3, 2),
            "p95_ms": round(_pct(lat, 0.95) * 1e3, 2),
            "p99_ms": round(_pct(lat, 0.99) * 1e3, 2),
            "groups": {
                g: {"queries": len(v),
                    "p50_ms": round(_pct(v, 0.50) * 1e3, 2),
                    "p95_ms": round(_pct(v, 0.95) * 1e3, 2),
                    "p99_ms": round(_pct(v, 0.99) * 1e3, 2)}
                for g, v in groups.items()},
            "plan_cache_hit_rate": round(rate(delta, "plan_cache"), 4),
            "result_cache_hit_rate": round(
                rate(delta, "result_cache"), 4),
            "shared_scan_attaches": int(
                delta.get("scan_shared_attach_total", 0.0)),
            "mesh_path_selected": int(
                delta.get("mesh_path_selected_total", 0.0)),
            "cold_ms": round(cold_s * 1e3, 2),
            "warm_ms": round(warm_s * 1e3, 2),
            "warm_speedup": round(cold_s / warm_s, 2),
            "fleet": {
                "coordinators": len(urls),
                "workers": len(fleet.workers),
                "per_coordinator_qps": per_coordinator_qps,
                "aggregate_qps": qps,
                "client_failovers": head["failovers"],
                "coherence": coherence,
                "kill": kill_block,
            },
            "sub_metrics": [
                {"metric": f"serving_tpch_sf{sf:g}_p95_latency_ms",
                 "value": round(_pct(lat, 0.95) * 1e3, 2), "unit": "ms"},
                {"metric": f"serving_tpch_sf{sf:g}_warm_speedup",
                 "value": round(cold_s / warm_s, 2), "unit": "x"},
                {"metric": f"serving_tpch_sf{sf:g}_dash_p99_ms",
                 "value": round(_pct(groups["dash"], 0.99) * 1e3, 2),
                 "unit": "ms"},
                {"metric": f"serving_tpch_sf{sf:g}_adhoc_p99_ms",
                 "value": round(_pct(groups["adhoc"], 0.99) * 1e3, 2),
                 "unit": "ms"},
            ],
        }
        if "execute" in phases:
            ep = phases["execute"]
            edelta = ep["delta"]
            tpl_hits = edelta.get("plan_template_cache_hit_total", 0.0)
            tpl_miss = edelta.get("plan_template_cache_miss_total", 0.0)
            tpl_fb = edelta.get(
                "plan_template_cache_guard_fallback_total", 0.0)
            tpl_rate = (tpl_hits - tpl_fb) / max(tpl_hits + tpl_miss,
                                                 1.0)
            summary["sub_metrics"] += [
                {"metric": f"serving_tpch_sf{sf:g}_execute_qps",
                 "value": round(len(ep["lat"]) / ep["wall_s"], 2),
                 "unit": "queries/s",
                 "p95_ms": round(_pct(ep["lat"], 0.95) * 1e3, 2),
                 "p99_ms": round(_pct(ep["lat"], 0.99) * 1e3, 2)},
                {"metric": f"serving_tpch_sf{sf:g}_template_hit_rate",
                 "value": round(tpl_rate, 4), "unit": "ratio",
                 "guard_fallbacks": int(tpl_fb)},
            ]
        if "repeated" in phases:
            rp = phases["repeated"]
            summary["sub_metrics"] += [
                {"metric": f"serving_tpch_sf{sf:g}_repeated_qps",
                 "value": round(len(rp["lat"]) / rp["wall_s"], 2),
                 "unit": "queries/s",
                 "p95_ms": round(_pct(rp["lat"], 0.95) * 1e3, 2),
                 "p99_ms": round(_pct(rp["lat"], 0.99) * 1e3, 2)},
                {"metric": f"serving_tpch_sf{sf:g}_result_hit_rate",
                 "value": round(rate(rp["delta"], "result_cache"), 4),
                 "unit": "ratio",
                 "partials": int(rp["delta"].get(
                     "result_cache_partial_total", 0.0))},
            ]
        summary["slo"] = slo_merged
        return summary
    finally:
        fleet.stop()


def main_serving() -> None:
    import sys
    _enable_compile_cache()
    sf = float(os.environ.get("BENCH_SERVING_SF", "0.01"))
    # SERVING_COORDINATORS >= 2 switches to the horizontal fleet
    # topology (config.py ENV_VARS): N coordinator subprocesses over
    # one shared worker pool, FleetClient round-robin on the client
    # side. Unset/0/1 keeps the classic single-coordinator bench.
    n_coords = int(os.environ.get("SERVING_COORDINATORS", "0"))
    # SERVING_CLIENTS/SERVING_QUERIES are the documented knobs;
    # BENCH_SERVING_* kept for back-compat with r01 runbooks. The
    # fleet default offers LESS client concurrency (same total
    # statement count): the coordinators are subprocesses sharing the
    # host with the load generator, and on a small box 100 client OS
    # threads measure the client-side scheduler, not the fleet — the
    # closed-loop throughput knee sits at a few dozen in-flight
    # statements either way.
    clients = int(os.environ.get(
        "SERVING_CLIENTS", os.environ.get(
            "BENCH_SERVING_CLIENTS",
            "24" if n_coords >= 2 else "100")))
    per_client = int(os.environ.get(
        "SERVING_QUERIES", os.environ.get(
            "BENCH_SERVING_QUERIES",
            "34" if n_coords >= 2 else "8")))
    mixes = tuple(m.strip() for m in os.environ.get(
        "SERVING_MIX", "mixed,execute,repeated").split(",")
        if m.strip())
    if n_coords >= 2:
        summary = bench_serving_fleet(sf, clients, per_client,
                                      mixes=mixes,
                                      n_coordinators=n_coords)
    else:
        summary = bench_serving(sf, clients, per_client, mixes=mixes)
    line = json.dumps(summary)
    print(line, flush=True)
    out_path = os.environ.get("SERVING_OUT")
    if out_path:
        try:
            tmp = out_path + ".tmp"
            with open(tmp, "w") as f:
                f.write(line + "\n")
            os.replace(tmp, out_path)
        except OSError as e:
            print(f"[bench] SERVING_OUT write failed: {e}",
                  file=sys.stderr)


# ---------------------------------------------------------------------------
# MULTICHIP: the mesh-scaling axis on REAL queries (ROADMAP item 1).
# Every earlier round pinned only a dry-run exit code; this runs
# q1sql/q3/q27/q55 through the engine SQL path at n_devices in
# {1, 2, 4, 8} — n=1 is the single-device executor (the honest
# baseline), n>1 the SPMD mesh path (mesh_execution/mesh_devices) —
# and reports per-query rows/s plus scaling efficiency
# rows_per_sec(n) / (n * rows_per_sec(1)). Results are row-checked
# across device counts, and the mesh selection metric is asserted so a
# silently-local "mesh" number can never pin. CPU-mesh numbers are
# acceptable in-container (BENCH_MULTICHIP_FORCE_CPU=1, the default,
# self-provisions the virtual device platform); the TPU tunnel re-pin
# sets it to 0 and inherits real chips. MULTICHIP_OUT=path writes the
# summary tools/check_bench_regression.py gates with
# ``--kind multichip``; the legacy dry-run ``ok``/``rc`` booleans ride
# on the headline for back-compat.
# ---------------------------------------------------------------------------

#: TPC-H Q3 through the engine SQL path (the BENCH q3 config is a hand
#: pipeline with no SQL text; the mesh axis runs real queries only)
_TPCH_Q3_SQL = """
select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
  o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
  and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate limit 10
"""

#: (name, catalog, module attr of the SQL, scanned tables for the
#: rows/s numerator)
_MULTICHIP_QUERIES = (
    ("q1sql", "tpch", "_TPCH_Q1", ("lineitem",)),
    ("q3", "tpch", "_TPCH_Q3_SQL", ("lineitem", "orders", "customer")),
    ("q27", "tpcds", "_DS_Q27",
     ("store_sales", "customer_demographics", "date_dim", "store",
      "item")),
    ("q55", "tpcds", "_DS_Q55", ("store_sales", "date_dim", "item")),
)


def _multichip_rows(rows):
    out = []
    for r in rows:
        out.append(tuple(v.item() if hasattr(v, "item") else v
                         for v in r))
    return out


def _multichip_rows_match(a, b, rel: float = 1e-6) -> bool:
    """Row equality with relative float tolerance: shard-count-
    dependent reduction order legitimately shifts big float64 sums in
    the last ulps, so exact equality would fail spuriously exactly
    when the mesh works (same contract as the parity tests)."""
    if len(a) != len(b):
        return False
    for ra, rb in zip(a, b):
        if len(ra) != len(rb):
            return False
        for va, vb in zip(ra, rb):
            if isinstance(va, float) and isinstance(vb, float):
                if abs(va - vb) > rel * max(abs(va), abs(vb), 1.0):
                    return False
            elif va != vb:
                return False
    return True


def main_multichip() -> None:
    import sys

    n_max = int(os.environ.get("BENCH_MULTICHIP_DEVICES", "8"))
    if os.environ.get("BENCH_MULTICHIP_FORCE_CPU", "1") == "1" \
            and n_max > 1:
        # container default: no TPU — self-provision the virtual CPU
        # platform BEFORE any backend initializes (same contract as
        # the dry run / tests/conftest.py; importing engine modules
        # would initialize the backend, so this is pure env + config).
        # The tunnel re-pin sets BENCH_MULTICHIP_FORCE_CPU=0 and
        # inherits the real chips.
        xla_flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in xla_flags:
            os.environ["XLA_FLAGS"] = (
                xla_flags
                + f" --xla_force_host_platform_device_count={n_max}"
            ).strip()
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
    _enable_compile_cache()
    import jax

    from presto_tpu.connectors.spi import TableHandle
    from presto_tpu.obs.metrics import REGISTRY

    have = len(jax.devices())
    counts = [n for n in (1, 2, 4, 8) if n <= min(n_max, have)]
    sf = float(os.environ.get("BENCH_MULTICHIP_SF", "0.05"))
    budget_s = float(os.environ.get("BENCH_BUDGET_S", "1380"))
    t_start = time.perf_counter()
    results = []

    def emit():
        if not results:
            return
        headline = dict(results[0])
        headline["sub_metrics"] = results[1:]
        # dry-run back-compat keys (MULTICHIP_r01..r05 pinned only
        # these): consumers of the old schema keep reading True
        headline.update({"ok": True, "rc": 0, "skipped": False,
                         "n_devices": max(counts), "sf": sf})
        line = json.dumps(headline)
        print(line, flush=True)
        out_path = os.environ.get("MULTICHIP_OUT")
        if out_path:
            try:
                tmp = out_path + ".tmp"
                with open(tmp, "w") as f:
                    f.write(line + "\n")
                os.replace(tmp, out_path)
            except OSError as e:
                print(f"[bench] MULTICHIP_OUT write failed: {e}",
                      file=sys.stderr)

    def selected() -> float:
        return REGISTRY.value("mesh_path_selected_total")

    for name, catalog, attr, tables in _MULTICHIP_QUERIES:
        elapsed = time.perf_counter() - t_start
        if results and elapsed > budget_s:
            print(f"[bench] budget exhausted ({elapsed:.0f}s); "
                  f"skipping {name}", file=sys.stderr)
            continue
        sql = globals()[attr]
        runner = _shared_runner(catalog, sf)
        conn = _SHARED_CONNS[(catalog, sf)]
        total_rows = sum(
            int(conn.metadata.table_stats(
                TableHandle(catalog, "default", t)).row_count)
            for t in tables)
        base_rps = None
        reference = None
        for n in counts:
            elapsed = time.perf_counter() - t_start
            if results and elapsed > budget_s:
                print(f"[bench] budget exhausted ({elapsed:.0f}s); "
                      f"skipping {name} n={n}", file=sys.stderr)
                break
            props = ({"mesh_execution": "off"} if n == 1 else
                     {"mesh_execution": "auto", "mesh_devices": n})
            print(f"[bench] multichip {name} sf={sf:g} n={n} "
                  f"at {time.perf_counter() - t_start:.0f}s",
                  file=sys.stderr, flush=True)
            sel0 = selected()
            disp0 = REGISTRY.value("mesh_dispatches_total")
            got, secs = _time(
                lambda: runner.execute(sql, properties=props).rows)
            dispatches = REGISTRY.value("mesh_dispatches_total") - disp0
            if n > 1:
                assert selected() > sel0, \
                    f"{name} n={n}: mesh path was not selected"
            rows = _multichip_rows(got)
            if reference is None:
                reference = rows
            else:
                assert _multichip_rows_match(rows, reference), \
                    f"{name} n={n}: rows diverged from n=1"
            rps = total_rows / secs
            metric = (f"multichip_{catalog}_sf{sf:g}_{name}"
                      f"_n{n}_rows_per_sec")
            rec = {"metric": metric, "value": round(rps),
                   "unit": "rows/s", "devices": n,
                   "wall_s": round(secs, 4)}
            if n > 1:
                # host dispatches the timed run cost: the fused
                # exchange's ">= 3x fewer dispatches" evidence rides
                # the pin next to the wall-clock it bought
                rec["dispatches"] = int(dispatches)
                # flight-recorder attribution for the timed run
                # (obs/flight.py): the pin carries WHERE the wall went
                # — tools/mesh_report.py diffs pins bucket-by-bucket
                # and check_bench_regression enforces bucket budgets,
                # so a re-pin must prove overhead moved, not just
                # rows/s
                from presto_tpu.obs.flight import FLIGHTS
                fl = FLIGHTS.last()
                if fl is not None and fl.attribution is not None:
                    rec["attribution"] = fl.attribution
            results.append(rec)
            if n == 1:
                base_rps = rps
            elif base_rps:
                results.append({
                    "metric": (f"multichip_{catalog}_sf{sf:g}_{name}"
                               f"_n{n}_scaling_eff"),
                    "value": round(rps / (n * base_rps), 4),
                    "unit": "x", "devices": n})
            emit()


def main() -> None:
    import sys

    _enable_compile_cache()
    # SF10 default: at SF1 the ~100ms tunnel readback RTT dominates the
    # device's few ms of compute and the ratio measures latency, not
    # throughput
    sf_q6 = float(os.environ.get("BENCH_SF_Q6",
                                 os.environ.get("BENCH_SF", "10")))
    sf_q1 = float(os.environ.get("BENCH_SF_Q1", "10"))
    sf_q1sql = float(os.environ.get("BENCH_SF_Q1SQL", "10"))
    sf_q3 = float(os.environ.get("BENCH_SF_Q3", "10"))
    # SF10 default for the TPC-DS macro configs (BASELINE config 4 names
    # SF100): at SF1 the ~100ms tunnel RTT and per-operator dispatch
    # dominate the device's milliseconds of compute and the ratio
    # measures latency, not throughput
    sf_ds = float(os.environ.get("BENCH_SF_DS", "10"))
    # hard wall-clock budget: the driver kills the bench process at
    # ~1800s, so leave headroom — skip remaining configs rather than risk
    # the whole run (and every completed number) being killed
    budget_s = float(os.environ.get("BENCH_BUDGET_S", "1380"))
    t_start = time.perf_counter()

    import signal

    class _ConfigTimeout(Exception):
        pass

    def _on_alarm(signum, frame):
        raise _ConfigTimeout()

    alarm_ok = hasattr(signal, "SIGALRM")
    if alarm_ok:
        signal.signal(signal.SIGALRM, _on_alarm)

    def emit(results):
        """Print the CURRENT summary as one JSON line. Called after every
        config (not just at the end) so that if the driver kills this
        process mid-run, the last stdout line is still a complete summary
        of every config that finished — round 4 lost ALL its numbers by
        printing only at exit (BENCH_r04: rc=124, parsed=null).
        BENCH_OUT=path additionally overwrites that file with the same
        summary — the input tools/check_bench_regression.py diffs
        against the latest committed BENCH_r*.json."""
        headline = dict(next((r for r in results if "_q1_" in r["metric"]),
                             results[0]))
        headline["sub_metrics"] = [r for r in results
                                   if r["metric"] != headline["metric"]]
        line = json.dumps(headline)
        print(line, flush=True)
        out_path = os.environ.get("BENCH_OUT")
        if out_path:
            try:
                # write-then-rename: a driver SIGKILL mid-write must not
                # leave a truncated summary (the whole point of emitting
                # per config is surviving exactly that kill)
                tmp = out_path + ".tmp"
                with open(tmp, "w") as f:
                    f.write(line + "\n")
                os.replace(tmp, out_path)
            except OSError as e:
                print(f"[bench] BENCH_OUT write failed: {e}",
                      file=sys.stderr)

    results = []
    global _PROXY_RUNS
    configs = [
        ("q6", sf_q6, bench_q6, "tpch"),
        ("q1", sf_q1, bench_q1, "tpch"),
        ("q1sql", sf_q1sql, bench_q1sql, "tpch"),
        ("q3", sf_q3, bench_q3, "tpch"),
        ("q55", sf_ds, bench_q55, "tpcds"),
        ("q27", sf_ds, bench_q27, "tpcds"),
    ]
    if os.environ.get("BENCH_ORC"):
        # BASELINE config 5 (ORC device decode): slow-tier guarded —
        # writing the ORC dataset costs minutes at interesting SFs
        sf_orc = float(os.environ.get("BENCH_SF_ORC", "1"))
        configs.append(("q6orc", sf_orc, bench_q6orc, "orc"))
    for name, sf, fn, prefix in configs:
        elapsed = time.perf_counter() - t_start
        if results and elapsed > budget_s:
            print(f"[bench] budget exhausted ({elapsed:.0f}s); "
                  f"skipping {name}", file=sys.stderr)
            continue
        print(f"[bench] {name} sf={sf:g} starting at {elapsed:.0f}s",
              file=sys.stderr, flush=True)
        metric = f"{prefix}_sf{sf:g}_{name}_rows_per_sec"
        # pinned proxy: one measured run suffices (results still verify);
        # unpinned — or re-pinning — runs best-of-3 to reject
        # host-contention spikes before the value is frozen
        _PROXY_RUNS = (1 if metric in _load_proxy_pins()
                       and not os.environ.get("BENCH_REPIN") else 3)
        # per-config watchdog: one pathological compile/run must not eat
        # every later config's slot NOR push the whole process past the
        # driver's kill timeout (completed numbers stay reportable)
        if alarm_ok:
            signal.alarm(int(max(budget_s * 1.05 - elapsed, 120)))
        try:
            out = fn(sf)
            total, dev_s, np_s = out[:3]
            extra = out[3] if len(out) > 3 else {}
        except _ConfigTimeout:
            print(f"[bench] {name} exceeded its time slot; skipping",
                  file=sys.stderr, flush=True)
            continue
        finally:
            if alarm_ok:
                signal.alarm(0)
        pinned_s = _pin_proxy_seconds(metric, np_s)
        print(f"[bench] {name} done: {round(total / dev_s):,} rows/s "
              f"(vs {pinned_s / dev_s:.2f}, measured proxy {np_s:.2f}s, "
              f"pinned {pinned_s:.2f}s)", file=sys.stderr, flush=True)
        results.append({
            "metric": metric,
            "value": round(total / dev_s),
            "unit": "rows/s",
            "vs_baseline": round(pinned_s / dev_s, 3),
            "proxy_s_pinned": round(pinned_s, 4),
            "proxy_s_measured": round(np_s, 4),
            **extra,
        })
        emit(results)


if __name__ == "__main__":
    import sys as _sys
    if "serving" in _sys.argv[1:] or os.environ.get("BENCH_SERVING"):
        main_serving()
    elif "multichip" in _sys.argv[1:] \
            or os.environ.get("BENCH_MULTICHIP"):
        main_multichip()
    else:
        main()

"""Memory accounting: per-query device-memory pool + operator contexts.

Conceptual parity with the reference's memory stack (reference
presto-memory-context/.../AggregatedMemoryContext.java,
LocalMemoryContext.java; pools memory/MemoryPool.java:44,111,143; revoke
execution/MemoryRevokingScheduler.java:46) re-shaped for a device runtime:

- the accounted resource is DEVICE-RESIDENT batch bytes (HBM), the scarce
  resource on a TPU chip; host DRAM is the spill target, so host copies
  are deliberately not charged;
- a reservation is *revocable* when its context registered a revoke
  callback (operators that can stage their state to host DRAM — join
  build, sort runs, agg state — reference HashBuilderOperator's
  SPILLING_INPUT states :165-180);
- revoking is synchronous and only ever targets OTHER contexts: an
  operator whose own reservation fails spills itself (try_reserve returns
  False); a reservation that still doesn't fit after revoking raises.
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Callable, List, Optional

from .obs.metrics import REGISTRY

UNLIMITED = 1 << 62

#: process-wide high-water mark across every query pool (the per-query
#: peak lives on MemoryStats; this is the fleet view)
_POOL_PEAK = REGISTRY.gauge("memory_pool_peak_bytes")


def batch_device_bytes(batch) -> int:
    """Accounted HBM footprint of a batch (data + validity + row mask)."""
    total = batch.row_mask.size  # bool mask, 1 byte/slot
    for c in batch.columns:
        total += c.data.size * c.data.dtype.itemsize
        total += c.validity.size
    return int(total)


@dataclasses.dataclass
class MemoryStats:
    peak_bytes: int = 0
    revocations: int = 0
    spilled_bytes: int = 0          # device bytes staged to host DRAM
    disk_spilled_bytes: int = 0     # compressed page bytes written to disk


class MemoryLimitExceeded(RuntimeError):
    pass


class QueryMemoryPool:
    """Per-query device-memory budget (reference memory/MemoryPool.java)."""

    def __init__(self, limit_bytes: Optional[int] = None,
                 disk_threshold: Optional[int] = None,
                 spill_dir: Optional[str] = None,
                 group=None):
        self.limit = limit_bytes if limit_bytes is not None else UNLIMITED
        #: serving-plane group account (serving/groups.py): every change
        #: to ``reserved`` is mirrored to the admitting resource group
        #: via ``group.charge(delta)``; a charge may raise when the
        #: group's hard memory limit is hit — the requesting query dies,
        #: its siblings in the group survive
        self.group = group
        # host-DRAM staging budget before the second (disk) tier kicks in
        # (reference NodeSpillConfig.maxSpillPerNode + spiller-spill-path)
        self.disk_threshold = disk_threshold
        self.spill_dir = spill_dir
        # host DRAM currently staged by ALL of this query's spill stores
        self.host_staged_bytes = 0
        self.reserved = 0
        self.stats = MemoryStats()
        self._contexts: List["OperatorMemoryContext"] = []
        # one re-entrant lock serializes pool accounting AND the spill
        # buffers' state transitions: a build side draining on the main
        # thread can trigger revoke callbacks into buffers owned by the
        # probe-prefetch thread (exec/local.py probe_prefetch), and an
        # unsynchronized revoke double-stages batches a concurrent merge
        # is also consuming (observed as duplicated aggregate inputs).
        # Re-entrant because a buffer's reserve under the lock can revoke
        # the same thread's other buffers.
        self.lock = threading.RLock()

    def context(self, name: str,
                revoke_cb: Optional[Callable[[], int]] = None
                ) -> "OperatorMemoryContext":
        ctx = OperatorMemoryContext(self, name, revoke_cb)
        self._contexts.append(ctx)
        return ctx

    def try_reserve(self, n: int, ctx: "OperatorMemoryContext") -> bool:
        """Reserve n bytes for ctx; revokes other revocable contexts
        (largest first) if needed. False = caller must spill itself."""
        with self.lock:
            if n > self.limit:
                return False  # can never fit: don't force futile spills
            if self.reserved + n > self.limit:
                self._revoke_others(self.reserved + n - self.limit, ctx)
            if self.reserved + n > self.limit:
                return False
            if self.group is not None:
                # bill the resource group BEFORE taking the bytes: a
                # hard-limit raise must leave both ledgers untouched
                self.group.charge(n)
            self.reserved += n
            ctx.bytes += n
            if self.reserved > self.stats.peak_bytes:
                self.stats.peak_bytes = self.reserved
                _POOL_PEAK.max_update(self.reserved)
            return True

    def reserve(self, n: int, ctx: "OperatorMemoryContext") -> None:
        """Like try_reserve but raising — for state that cannot spill."""
        if not self.try_reserve(n, ctx):
            raise MemoryLimitExceeded(
                f"query memory limit {self.limit} bytes exceeded: "
                f"reserved {self.reserved}, requested {n} ({ctx.name})")

    def _revoke_others(self, needed: int,
                       requester: "OperatorMemoryContext") -> None:
        holders = sorted(
            (c for c in self._contexts
             if c is not requester and c.revocable and c.bytes > 0),
            key=lambda c: -c.bytes)
        freed = 0
        for c in holders:
            if freed >= needed:
                break
            freed += c.revoke()
            self.stats.revocations += 1


class OperatorMemoryContext:
    """One operator's reservation (reference LocalMemoryContext).

    ``revoke_cb`` (if set) makes the reservation revocable: when invoked
    it must release the context's device memory (staging it to host) and
    return the bytes freed.
    """

    def __init__(self, pool: QueryMemoryPool, name: str,
                 revoke_cb: Optional[Callable[[], int]] = None):
        self.pool = pool
        self.name = name
        self.bytes = 0
        self._revoke_cb = revoke_cb

    @property
    def revocable(self) -> bool:
        return self._revoke_cb is not None

    def pin(self) -> None:
        """End revocability: the holder has handed its state to a consumer
        (a finished build side being probed), so revoking could no longer
        actually free the device memory."""
        self._revoke_cb = None

    def revoke(self) -> int:
        # spilled-byte accounting happens at the staging site (the buffer
        # knows what it moved to host), not here — a revoke that finds an
        # empty buffer frees nothing yet later adds still stage
        with self.pool.lock:
            freed = self._revoke_cb() if self._revoke_cb is not None else 0
            self.release_all()
            return freed

    def release_all(self) -> None:
        with self.pool.lock:
            if self.pool.group is not None and self.bytes:
                self.pool.group.charge(-self.bytes)
            self.pool.reserved -= self.bytes
            self.bytes = 0

    def close(self) -> None:
        with self.pool.lock:
            self.release_all()
            if self in self.pool._contexts:
                self.pool._contexts.remove(self)

"""Exchange primitives: Presto's network shuffle as XLA collectives.

Conceptual parity with the exchange layer (reference
presto-main/.../operator/PartitionedOutputOperator.java:48 hash-partitions
rows to per-partition buffers; operator/ExchangeClient.java:141 pulls them
over HTTP) — re-designed for TPU: inside a mesh, a hash exchange is one
``all_to_all`` over ICI and a broadcast exchange is one ``all_gather``.
There is no serde and no buffer protocol; batches stay device-resident
struct-of-arrays end to end.

All functions here are *collective*: they must run inside ``shard_map``
over the mesh axis they name. Host-side orchestration (which stage runs
where) lives in exec/; these are the data-plane moves.

Wire cost: the default exchange is ``repartition_by_hash_compact`` —
rows sort by destination on device and exactly ``quota`` slots ship to
each peer, so a shard moves ~C rows per exchange (n*quota with quota
sized to the max per-(src,dst) count). The masked ``repartition_by_hash``
(n*C cost) remains as the correctness baseline and for callers without a
quota readback.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..batch import Batch, Column


def _fnv1a64(s: str) -> int:
    """Deterministic 64-bit string hash (FNV-1a) — stable across chunks
    and processes, so dictionary VALUES (not per-chunk codes) decide
    partition placement."""
    h = 0xCBF29CE484222325
    for b in s.encode("utf-8"):
        h = ((h ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


def _vocab_hash_table(vocab: Tuple[str, ...]) -> jnp.ndarray:
    vals = [_fnv1a64(s) for s in vocab] + [0]  # sentinel slot for -1 codes
    return jnp.asarray(np.asarray(vals, dtype=np.uint64))


def _splitmix64(x: jnp.ndarray) -> jnp.ndarray:
    """Device splitmix64 finalizer — the row-hash for partition placement
    (role of Presto's InterpretedHashGenerator / HashGenerationOptimizer)."""
    x = x.astype(jnp.uint64)
    x = (x ^ (x >> 30)) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> 27)) * jnp.uint64(0x94D049BB133111EB)
    return x ^ (x >> 31)


def hash_partition_ids(batch: Batch, key_cols: Sequence[int],
                       n_partitions: int) -> jnp.ndarray:
    """Partition id per row in [0, n), mixing any number of key columns.

    Placement only needs equal-tuple -> equal-shard, so columns fold into
    one splitmix chain (validity folds in too: NULL and sentinel-valued
    keys may share a shard, which is harmless for colocation).
    """
    h = jnp.zeros(batch.capacity, dtype=jnp.uint64)
    for ci in key_cols:
        c = batch.columns[ci]
        data = c.data
        if c.type.is_string:
            # hash the string VALUE via the vocab, never the code: codes
            # differ between chunks/sides with different dictionaries
            table = _vocab_hash_table(c.dictionary or ())
            idx = jnp.where(data >= 0, data, table.shape[0] - 1)
            data = jnp.take(table, idx, axis=0)
        elif data.dtype == jnp.bool_:
            data = data.astype(jnp.int32)
        elif jnp.issubdtype(data.dtype, jnp.floating):
            # value-deterministic int image (collisions only co-locate)
            data = (data * 65536.0).astype(jnp.int64)
        if getattr(data, "ndim", 1) == 2:
            # long-decimal limb pairs fold into one word first
            data = data[..., 0] ^ _splitmix64(
                data[..., 1].astype(jnp.uint64)).astype(jnp.int64)
        # neutralize NULL rows' storage: stale per-row garbage (e.g.
        # from nullif-produced NULLs) must not scatter one NULL key
        # group across shards — validity is mixed separately below
        data = jnp.where(c.validity, data, jnp.zeros_like(data))
        h = _splitmix64(h ^ data.astype(jnp.uint64)
                        ^ (c.validity.astype(jnp.uint64) << jnp.uint64(63)))
    return (h % jnp.uint64(n_partitions)).astype(jnp.int32)


def repartition_by_hash(batch: Batch, key_cols: Sequence[int],
                        axis_name: str, n_partitions: int) -> Batch:
    """Collective hash exchange: rows land on the shard owning hash(key)%n.

    Must run inside shard_map over ``axis_name`` with exactly
    ``n_partitions`` shards. Output capacity is n*C (each peer may send up
    to its full local batch); masks encode which slots are live.
    """
    pid = hash_partition_ids(batch, key_cols, n_partitions)
    return repartition_by_ids(batch, pid, axis_name, n_partitions)


def repartition_by_ids(batch: Batch, pid: jnp.ndarray,
                       axis_name: str, n_partitions: int) -> Batch:
    """Masked all-to-all by caller-supplied destination ids — the shared
    engine under hash exchange AND range exchange (distributed sort)."""
    dest = jnp.arange(n_partitions, dtype=jnp.int32)[:, None]
    bucket_mask = batch.row_mask[None, :] & (pid[None, :] == dest)  # [n, C]

    recv_mask = jax.lax.all_to_all(
        bucket_mask, axis_name, split_axis=0, concat_axis=0, tiled=False)
    out_mask = recv_mask.reshape(-1)

    out_cols: List[Column] = []
    for c in batch.columns:
        data = jnp.broadcast_to(c.data[None, :],
                                (n_partitions,) + c.data.shape)
        valid = jnp.broadcast_to(c.validity[None, :],
                                 (n_partitions,) + c.validity.shape)
        rdata = jax.lax.all_to_all(data, axis_name, 0, 0, tiled=False)
        rvalid = jax.lax.all_to_all(valid, axis_name, 0, 0, tiled=False)
        # fold (peer, row) but keep trailing dims (limb pairs, tiles)
        out_cols.append(Column(c.type,
                               rdata.reshape((-1,) + rdata.shape[2:]),
                               rvalid.reshape(-1) & out_mask, c.dictionary))
    return Batch(batch.schema, out_cols, out_mask)


def partition_counts(batch: Batch, key_cols: Sequence[int],
                     n_partitions: int) -> jnp.ndarray:
    """Live rows per destination on this shard: int64[n_partitions].

    Collective-free; callers host-max across shards (or pmax) to size the
    static quota for ``repartition_by_hash_compact``."""
    pid = hash_partition_ids(batch, key_cols, n_partitions)
    dest = jnp.arange(n_partitions, dtype=jnp.int32)[:, None]
    return jnp.sum(batch.row_mask[None, :] & (pid[None, :] == dest),
                   axis=1).astype(jnp.int64)


def repartition_by_hash_compact(batch: Batch, key_cols: Sequence[int],
                                axis_name: str, n_partitions: int,
                                quota: int) -> Batch:
    """Quota-compacted hash exchange: rows sort by destination and exactly
    ``quota`` slots ship to each peer, so the wire/output cost is n*quota
    (~C for a uniform hash) instead of the masked all_to_all's n*C — the
    role of Presto's per-partition page builders (reference
    operator/PartitionedOutputOperator.java:48 PagePartitioner).

    ``quota`` must be >= the max per-(src,dst) live count across all
    shards (host-max of ``partition_counts``); rows beyond it would be
    silently dropped. Output capacity = n_partitions * quota.
    """
    pid = hash_partition_ids(batch, key_cols, n_partitions)
    return repartition_by_pids_compact(batch, pid, axis_name,
                                       n_partitions, quota)


def repartition_by_buckets_compact(batch: Batch, key_cols: Sequence[int],
                                   axis_name: str, n_partitions: int,
                                   assign: Sequence[int],
                                   quota: int) -> Batch:
    """Quota-compacted exchange through a bucket indirection: rows hash
    into ``len(assign)`` buckets and ``assign[bucket]`` names the owning
    shard. Equal keys always share a bucket, so colocation holds under
    ANY assignment — which is the point: the host can re-balance hot
    buckets between batches (adaptive re-splitting of a skewed key
    space) without touching per-key semantics, Presto's skewed-
    partition rebalancing reshaped for a static-shape collective."""
    bucket = hash_partition_ids(batch, key_cols, len(assign))
    pid = jnp.take(jnp.asarray(np.asarray(assign, dtype=np.int32)),
                   bucket, axis=0)
    return repartition_by_pids_compact(batch, pid, axis_name,
                                       n_partitions, quota)


def repartition_by_pids_compact(batch: Batch, pid: jnp.ndarray,
                                axis_name: str, n_partitions: int,
                                quota: int) -> Batch:
    """The shared quota-compacted engine under the hash and bucket
    exchanges: caller supplies per-row destination ids."""
    cap = batch.capacity
    spid = jnp.where(batch.row_mask, pid,
                     n_partitions).astype(jnp.int32)   # dead rows last
    idx = jnp.arange(cap, dtype=jnp.int32)
    sorted_pid, sorted_idx = jax.lax.sort((spid, idx), num_keys=1,
                                          is_stable=True)
    dests = jnp.arange(n_partitions, dtype=jnp.int32)
    start = jnp.searchsorted(sorted_pid, dests, side="left")
    counts = jnp.searchsorted(sorted_pid, dests, side="right") - start
    q = jnp.arange(quota, dtype=jnp.int32)[None, :]
    slot_live = q < counts[:, None]                               # [n, Q]
    src = jnp.take(sorted_idx,
                   jnp.minimum(start[:, None] + q, cap - 1), axis=0)

    recv_live = jax.lax.all_to_all(slot_live, axis_name, 0, 0, tiled=False)
    out_mask = recv_live.reshape(-1)
    out_cols: List[Column] = []
    for c in batch.columns:
        d = jnp.take(c.data, src, axis=0)
        v = jnp.take(c.validity, src, axis=0) & slot_live
        rd = jax.lax.all_to_all(d, axis_name, 0, 0, tiled=False)
        rv = jax.lax.all_to_all(v, axis_name, 0, 0, tiled=False)
        out_cols.append(Column(c.type,
                               rd.reshape((-1,) + rd.shape[2:]),
                               rv.reshape(-1) & out_mask, c.dictionary))
    return Batch(batch.schema, out_cols, out_mask)


def repartition_fused(batch: Batch, key_cols: Sequence[int],
                      axis_name: str, n_partitions: int,
                      assign: Sequence[int],
                      quota: int) -> Tuple[Batch, jnp.ndarray]:
    """Bucket-count + quota-compacted ship fused into ONE collective
    program: returns ``(shipped, counts)`` where ``counts`` is
    ``int64[len(assign)]`` live rows per bucket on this shard — the
    ``_PartitionMap.observe`` feed, left on device so the host fetches
    control scalars once per stage instead of once per round.

    The caller passes a *capacity-safe* static ``quota`` (the per-shard
    lane count): any per-(src, dst) live count is bounded by the source
    shard's live rows, so no counts readback is needed to size the
    exchange and no row can ever be dropped. Wire/output cost is n*C —
    the masked all_to_all's cost — traded for erasing the per-round
    dispatch -> fetch -> redispatch triple; when a tighter stats bound
    exists, pass it instead and the cost matches the compact path."""
    bucket = hash_partition_ids(batch, key_cols, len(assign))
    b_ids = jnp.arange(len(assign), dtype=jnp.int32)[:, None]
    counts = jnp.sum(batch.row_mask[None, :] & (bucket[None, :] == b_ids),
                     axis=1).astype(jnp.int64)
    pid = jnp.take(jnp.asarray(np.asarray(assign, dtype=np.int32)),
                   bucket, axis=0)
    return repartition_by_pids_compact(batch, pid, axis_name,
                                       n_partitions, quota), counts


def broadcast_batch(batch: Batch, axis_name: str) -> Batch:
    """Collective broadcast exchange: every shard receives all rows
    (Presto FIXED_BROADCAST_DISTRIBUTION — the replicated-join build side)."""
    out_cols: List[Column] = []
    mask = jax.lax.all_gather(batch.row_mask, axis_name, tiled=True)
    for c in batch.columns:
        data = jax.lax.all_gather(c.data, axis_name, tiled=True)
        valid = jax.lax.all_gather(c.validity, axis_name, tiled=True)
        out_cols.append(Column(c.type, data, valid, c.dictionary))
    return Batch(batch.schema, out_cols, mask)


# -- host-side helpers (not collective) -------------------------------------

def shard_batch(batch: Batch, mesh: jax.sharding.Mesh,
                axis: str) -> Batch:
    """Place a host-built batch row-sharded over the mesh axis.

    The data-plane analogue of assigning splits to workers
    (reference execution/scheduler/UniformNodeSelector.java): row range i
    lives in shard i's HBM.
    """
    spec = jax.sharding.PartitionSpec(axis)
    sharding = jax.sharding.NamedSharding(mesh, spec)
    put = lambda x: jax.device_put(x, sharding)
    cols = [Column(c.type, put(c.data), put(c.validity), c.dictionary)
            for c in batch.columns]
    return Batch(batch.schema, cols, put(batch.row_mask))


def local_shard(batch: Batch, shard_index: int, n_shards: int) -> Batch:
    """Slice shard i's rows out of a host batch (for per-process staging)."""
    cap = batch.capacity
    assert cap % n_shards == 0, "capacity must divide evenly across shards"
    per = cap // n_shards
    lo = shard_index * per
    sl = lambda x: x[lo:lo + per]
    cols = [Column(c.type, sl(c.data), sl(c.validity), c.dictionary)
            for c in batch.columns]
    return Batch(batch.schema, cols, sl(batch.row_mask))

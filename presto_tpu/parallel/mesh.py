"""Device mesh construction for distributed query execution.

The TPU-native replacement for Presto's worker-node topology (reference
presto-main/.../metadata/DiscoveryNodeManager.java:68 tracks workers;
execution/scheduler/NodeScheduler.java places splits on them): a stage's
"tasks" become shards of one SPMD program laid over a jax.sharding.Mesh
axis, so the hash-exchange between stages rides ICI collectives instead of
HTTP page transfers.

One flat data-parallel axis ("dp") is the default — Presto's
FIXED_HASH_DISTRIBUTION over N workers maps to shard_map over dp with an
all-to-all per exchange. Multi-axis meshes (dp × within-host) are layered
on later by the stage scheduler.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np

default_axis = "dp"


def make_mesh(n_devices: Optional[int] = None,
              axis: str = default_axis) -> jax.sharding.Mesh:
    """A 1-D mesh over the first n devices (all by default)."""
    devices = jax.devices()
    n = n_devices or len(devices)
    if n > len(devices):
        raise ValueError(f"requested {n} devices, have {len(devices)}")
    return jax.sharding.Mesh(np.array(devices[:n]), (axis,))

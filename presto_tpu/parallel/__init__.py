from .mesh import make_mesh, default_axis  # noqa: F401
from .exchange import (  # noqa: F401
    hash_partition_ids, repartition_by_hash, broadcast_batch, shard_batch,
    local_shard,
)

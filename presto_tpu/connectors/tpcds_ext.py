"""TPC-DS full-schema extension: catalog/web channels, returns,
inventory, and the remaining dimensions.

Completes the connector's table surface to what the reference's TPC-DS
suite queries (reference presto-tpcds/.../TpcdsMetadata.java serves all
24 spec tables; presto-benchto-benchmarks/.../sql/presto/tpcds/*.sql is
the consumer this surface is sized against). Same generator design as
the base module (connectors/tpcds.py): every column is a stateless
splitmix64 hash of the row's surrogate key, so any split generates any
row range referentially consistently; exact dsdgen bit-compatibility is
NOT a goal — correctness tests compare against an oracle over this same
generated data.
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from .. import types as T
from .tpch import _U64, _h, _money, _pick, _randint

V = T.VARCHAR

# fact channels: catalog ~= ss/2, web ~= ss/4; returns ~= 10% of sales
# (spec's rough channel proportions)
EXT_ROWS = {
    "catalog_sales": lambda sf: max(1, int(1_440_000 * sf)),
    "web_sales": lambda sf: max(1, int(720_000 * sf)),
    "store_returns": lambda sf: max(1, int(288_000 * sf)),
    "catalog_returns": lambda sf: max(1, int(144_000 * sf)),
    "web_returns": lambda sf: max(1, int(72_000 * sf)),
    "inventory": lambda sf: max(1000, int(1_200_000 * sf)),
    "warehouse": lambda sf: max(1, int(5 * max(sf, 1) ** 0.5)),
    "ship_mode": lambda sf: 20,
    "reason": lambda sf: 35,
    "call_center": lambda sf: max(1, int(6 * max(sf, 1) ** 0.5)),
    "catalog_page": lambda sf: max(1, int(11_718 * max(sf, 1) ** 0.5)),
    "web_site": lambda sf: max(1, int(30 * max(sf, 1) ** 0.5)),
    "web_page": lambda sf: max(1, int(60 * max(sf, 1) ** 0.5)),
    "income_band": lambda sf: 20,
}

_D = T.DOUBLE
_B = T.BIGINT
_I = T.INTEGER


def _sales_schema(p: str, extra: List[Tuple[str, T.Type]]):
    return [
        (f"{p}_sold_date_sk", _B), (f"{p}_sold_time_sk", _B),
        (f"{p}_ship_date_sk", _B),
        (f"{p}_bill_customer_sk", _B), (f"{p}_bill_cdemo_sk", _B),
        (f"{p}_bill_hdemo_sk", _B), (f"{p}_bill_addr_sk", _B),
        (f"{p}_ship_customer_sk", _B), (f"{p}_ship_addr_sk", _B),
        (f"{p}_ship_mode_sk", _B), (f"{p}_warehouse_sk", _B),
        (f"{p}_item_sk", _B), (f"{p}_promo_sk", _B),
        (f"{p}_order_number", _B),
        (f"{p}_quantity", _I), (f"{p}_wholesale_cost", _D),
        (f"{p}_list_price", _D), (f"{p}_sales_price", _D),
        (f"{p}_ext_discount_amt", _D), (f"{p}_ext_sales_price", _D),
        (f"{p}_ext_wholesale_cost", _D), (f"{p}_ext_list_price", _D),
        (f"{p}_ext_ship_cost", _D), (f"{p}_coupon_amt", _D),
        (f"{p}_net_paid", _D), (f"{p}_net_paid_inc_tax", _D),
        (f"{p}_net_profit", _D),
    ] + extra


EXT_SCHEMAS: Dict[str, List[Tuple[str, T.Type]]] = {
    "catalog_sales": _sales_schema("cs", [
        ("cs_call_center_sk", _B), ("cs_catalog_page_sk", _B)]),
    "web_sales": _sales_schema("ws", [
        ("ws_web_page_sk", _B), ("ws_web_site_sk", _B),
        ("ws_ship_hdemo_sk", _B)]),
    "store_returns": [
        ("sr_returned_date_sk", _B), ("sr_item_sk", _B),
        ("sr_customer_sk", _B), ("sr_cdemo_sk", _B),
        ("sr_hdemo_sk", _B), ("sr_addr_sk", _B), ("sr_store_sk", _B),
        ("sr_reason_sk", _B), ("sr_ticket_number", _B),
        ("sr_return_quantity", _I), ("sr_return_amt", _D),
        ("sr_return_tax", _D), ("sr_return_amt_inc_tax", _D),
        ("sr_fee", _D), ("sr_refunded_cash", _D),
        ("sr_reversed_charge", _D), ("sr_store_credit", _D),
        ("sr_net_loss", _D),
    ],
    "catalog_returns": [
        ("cr_returned_date_sk", _B), ("cr_item_sk", _B),
        ("cr_refunded_customer_sk", _B), ("cr_refunded_cdemo_sk", _B),
        ("cr_refunded_addr_sk", _B),
        ("cr_returning_customer_sk", _B), ("cr_returning_cdemo_sk", _B),
        ("cr_returning_addr_sk", _B),
        ("cr_call_center_sk", _B), ("cr_catalog_page_sk", _B),
        ("cr_reason_sk", _B), ("cr_order_number", _B),
        ("cr_return_quantity", _I), ("cr_return_amount", _D),
        ("cr_return_tax", _D), ("cr_return_amt_inc_tax", _D),
        ("cr_fee", _D), ("cr_refunded_cash", _D),
        ("cr_reversed_charge", _D), ("cr_store_credit", _D),
        ("cr_net_loss", _D),
    ],
    "web_returns": [
        ("wr_returned_date_sk", _B), ("wr_item_sk", _B),
        ("wr_refunded_customer_sk", _B), ("wr_refunded_cdemo_sk", _B),
        ("wr_refunded_addr_sk", _B),
        ("wr_returning_customer_sk", _B), ("wr_returning_cdemo_sk", _B),
        ("wr_returning_addr_sk", _B),
        ("wr_web_page_sk", _B), ("wr_reason_sk", _B),
        ("wr_order_number", _B),
        ("wr_return_quantity", _I), ("wr_return_amt", _D),
        ("wr_return_tax", _D), ("wr_return_amt_inc_tax", _D),
        ("wr_fee", _D), ("wr_refunded_cash", _D),
        ("wr_reversed_charge", _D), ("wr_account_credit", _D),
        ("wr_net_loss", _D),
    ],
    "inventory": [
        ("inv_date_sk", _B), ("inv_item_sk", _B),
        ("inv_warehouse_sk", _B), ("inv_quantity_on_hand", _I),
    ],
    "warehouse": [
        ("w_warehouse_sk", _B), ("w_warehouse_id", T.varchar(16)),
        ("w_warehouse_name", T.varchar(20)),
        ("w_warehouse_sq_ft", _I), ("w_city", T.varchar(60)),
        ("w_county", T.varchar(30)), ("w_state", T.varchar(2)),
        ("w_country", T.varchar(20)),
    ],
    "ship_mode": [
        ("sm_ship_mode_sk", _B), ("sm_ship_mode_id", T.varchar(16)),
        ("sm_type", T.varchar(30)), ("sm_code", T.varchar(10)),
        ("sm_carrier", T.varchar(20)),
    ],
    "reason": [
        ("r_reason_sk", _B), ("r_reason_id", T.varchar(16)),
        ("r_reason_desc", T.varchar(100)),
    ],
    "call_center": [
        ("cc_call_center_sk", _B), ("cc_call_center_id", T.varchar(16)),
        ("cc_name", T.varchar(50)), ("cc_manager", T.varchar(40)),
        ("cc_county", T.varchar(30)),
    ],
    "catalog_page": [
        ("cp_catalog_page_sk", _B), ("cp_catalog_page_id", T.varchar(16)),
    ],
    "web_site": [
        ("web_site_sk", _B), ("web_site_id", T.varchar(16)),
        ("web_name", T.varchar(50)), ("web_company_name", T.varchar(50)),
    ],
    "web_page": [
        ("wp_web_page_sk", _B), ("wp_web_page_id", T.varchar(16)),
        ("wp_char_count", _I),
    ],
    "income_band": [
        ("ib_income_band_sk", _B), ("ib_lower_bound", _I),
        ("ib_upper_bound", _I),
    ],
}

EXT_PRIMARY_KEYS = {
    "catalog_sales": (), "web_sales": (), "store_returns": (),
    "catalog_returns": (), "web_returns": (), "inventory": (),
    "warehouse": ("w_warehouse_sk",), "ship_mode": ("sm_ship_mode_sk",),
    "reason": ("r_reason_sk",), "call_center": ("cc_call_center_sk",),
    "catalog_page": ("cp_catalog_page_sk",),
    "web_site": ("web_site_sk",), "web_page": ("wp_web_page_sk",),
    "income_band": ("ib_income_band_sk",),
}

_SHIP_TYPES = ("EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR", "LIBRARY")
_CARRIERS = ("UPS", "FEDEX", "AIRBORNE", "USPS", "DHL", "TBS", "ZHOU",
             "LATVIAN", "DIAMOND", "BARIAN")
_CC_NAMES = ("NY Metro", "Mid Atlantic", "Pacific Northwest",
             "North Midwest", "California", "Hawaii/Alaska")
_WEB_COMPANIES = ("pri", "able", "ought", "ese", "anti", "cally")
_REASONS = tuple(f"reason {i}" for i in range(1, 36))
_CLASSES = ("accessories", "blazers", "dresses", "pants", "shirts",
            "shoes", "sports", "swimwear", "athletic", "classical",
            "country", "pop", "rock", "fiction", "history", "romance")
_COLORS = ("azure", "beige", "black", "blue", "brown", "coral", "cream",
           "cyan", "gold", "green", "grey", "indigo", "ivory", "khaki",
           "lime", "magenta", "maroon", "navy", "olive", "orange",
           "pink", "plum", "purple", "red", "rose", "salmon", "silver",
           "tan", "teal", "violet", "white", "yellow")
_SIZES = ("petite", "small", "medium", "large", "extra large",
          "economy", "N/A")
_UNITS = ("Each", "Box", "Case", "Dozen", "Pallet", "Gross", "Unknown",
          "Carton", "Bundle", "Ton", "Lb", "Oz")
_SALUTATIONS = ("Mr.", "Mrs.", "Ms.", "Dr.", "Miss", "Sir")
_COUNTRIES = ("UNITED STATES", "CANADA", "MEXICO", "GERMANY", "FRANCE",
              "JAPAN", "CHILE", "BRAZIL", "INDIA", "AUSTRALIA")
_STREET_NAMES = ("Main", "Oak", "Park", "First", "Second", "Elm",
                 "Maple", "Cedar", "Pine", "Washington", "Lake", "Hill")
_STREET_TYPES = ("Street", "Avenue", "Boulevard", "Road", "Lane",
                 "Drive", "Court", "Circle", "Way", "Parkway")
_LOCATION_TYPES = ("apartment", "condo", "single family")
_QUARTERS = tuple(f"{y}Q{q}" for y in range(1900, 2101)
                  for q in range(1, 5))


class ExtGen:
    """Generator mixin for the extension tables (merged into _Gen)."""

    # populated in _Gen.__init__
    sf: float

    def _n(self, table: str) -> int:
        return EXT_ROWS[table](self.sf)

    # -- shared sales-channel pricing (cs_/ws_) -----------------------------
    def _channel_sales(self, key: np.ndarray, cols: Sequence[str],
                       p: str, tag0: int, lines_per_order: int):
        """Lazy per-column generation: only the requested columns hash
        (the base module's per-column elif dispatch, expressed as a
        thunk table); the mutually-consistent pricing intermediates
        memoize so a multi-price projection still computes each once."""
        from .tpcds import D_BASE_SK, SALES_D0, SALES_D1
        memo: Dict[str, np.ndarray] = {}

        def mget(name: str, f):
            v = memo.get(name)
            if v is None:
                v = memo[name] = f()
            return v

        qty = lambda: mget("qty", lambda: 1 + (
            _h(key, tag0 + 1) % _U64(100)).astype(np.int64))
        wholesale = lambda: mget("wh", lambda: _money(
            key, tag0 + 2, 1.0, 100.0))
        list_price = lambda: mget("lp", lambda: np.round(
            wholesale() * (1.0 + (_h(key, tag0 + 3) % _U64(100))
                           .astype(np.float64) / 100.0), 2))
        sales_price = lambda: mget("sp", lambda: np.round(
            list_price() * ((_h(key, tag0 + 4) % _U64(100))
                            .astype(np.float64) / 100.0), 2))
        ext_sales = lambda: mget("es", lambda: np.round(
            sales_price() * qty(), 2))
        coupon = lambda: mget("cp", lambda: np.where(
            _h(key, tag0 + 5) % _U64(10) == 0,
            np.round(ext_sales() * 0.1, 2), 0.0))
        sold = lambda: mget("sold", lambda: SALES_D0 + (
            _h(key, tag0 + 6) % _U64(SALES_D1 - SALES_D0)
        ).astype(np.int64))

        def fk(tag, n):
            return lambda: 1 + (_h(key, tag0 + tag)
                                % _U64(max(n, 1))).astype(np.int64)

        vals = {
            "sold_date_sk": lambda: D_BASE_SK + sold(),
            "sold_time_sk": lambda: (_h(key, tag0 + 7)
                                     % _U64(86_400)).astype(np.int64),
            "ship_date_sk": lambda: D_BASE_SK + sold() + 1 + (
                _h(key, tag0 + 8) % _U64(90)).astype(np.int64),
            "bill_customer_sk": fk(9, self.n_cust),
            "bill_cdemo_sk": fk(10, self.n_demo),
            "bill_hdemo_sk": fk(11, self.n_hdemo),
            "bill_addr_sk": fk(12, self.n_addr),
            "ship_customer_sk": fk(13, self.n_cust),
            "ship_addr_sk": fk(14, self.n_addr),
            "ship_mode_sk": fk(15, self._n("ship_mode")),
            "warehouse_sk": fk(16, self._n("warehouse")),
            "item_sk": fk(17, self.n_item),
            "promo_sk": fk(18, self.n_promo),
            "order_number": lambda: 1 + (key.astype(np.int64) - 1)
            // lines_per_order,
            "quantity": lambda: qty().astype(np.int32),
            "wholesale_cost": wholesale,
            "list_price": list_price,
            "sales_price": sales_price,
            "ext_discount_amt": lambda: np.round(
                (list_price() - sales_price()) * qty(), 2),
            "ext_sales_price": ext_sales,
            "ext_wholesale_cost": lambda: np.round(
                wholesale() * qty(), 2),
            "ext_list_price": lambda: np.round(list_price() * qty(), 2),
            "ext_ship_cost": lambda: _money(key, tag0 + 19, 0.0, 20.0),
            "coupon_amt": coupon,
            "net_paid": lambda: np.round(ext_sales() - coupon(), 2),
            "net_paid_inc_tax": lambda: np.round(
                (ext_sales() - coupon()) * 1.05, 2),
            "net_profit": lambda: np.round(
                ext_sales() - coupon() - wholesale() * qty(), 2),
            "call_center_sk": fk(20, self._n("call_center")),
            "catalog_page_sk": fk(21, self._n("catalog_page")),
            "web_page_sk": fk(22, self._n("web_page")),
            "web_site_sk": fk(23, self._n("web_site")),
            "ship_hdemo_sk": fk(24, self.n_hdemo),
        }
        return {c: (vals[c[len(p) + 1:]](), None) for c in cols}

    def catalog_sales(self, key, cols):
        return self._channel_sales(key, cols, "cs", 400, 4)

    def web_sales(self, key, cols):
        return self._channel_sales(key, cols, "ws", 440, 3)

    # -- returns ------------------------------------------------------------
    def _returns(self, key: np.ndarray, cols: Sequence[str], p: str,
                 tag0: int, sales_table: str, lines_per_order: int):
        """Lazy per-column generation (see _channel_sales)."""
        from .tpcds import D_BASE_SK, SALES_D0, SALES_D1
        memo: Dict[str, np.ndarray] = {}

        def mget(name: str, f):
            v = memo.get(name)
            if v is None:
                v = memo[name] = f()
            return v

        amt = lambda: mget("amt", lambda: _money(key, tag0 + 2, 1.0,
                                                 500.0))
        tax = lambda: mget("tax", lambda: np.round(amt() * 0.05, 2))
        cash = lambda: mget("cash", lambda: np.round(
            amt() * ((_h(key, tag0 + 3) % _U64(100))
                     .astype(np.float64) / 100.0), 2))
        n_orders = max(1, EXT_ROWS.get(
            sales_table, lambda sf: int(2_880_000 * sf))(self.sf)
            // lines_per_order)

        def fk(tag, n):
            return lambda: 1 + (_h(key, tag0 + tag)
                                % _U64(max(n, 1))).astype(np.int64)

        vals = {
            "returned_date_sk": lambda: D_BASE_SK + SALES_D0 + (
                _h(key, tag0 + 4) % _U64(SALES_D1 - SALES_D0)
            ).astype(np.int64),
            "item_sk": fk(5, self.n_item),
            "customer_sk": fk(6, self.n_cust),
            "cdemo_sk": fk(7, self.n_demo),
            "hdemo_sk": fk(8, self.n_hdemo),
            "addr_sk": fk(9, self.n_addr),
            "store_sk": fk(10, self.n_store),
            "reason_sk": fk(11, self._n("reason")),
            "ticket_number": fk(12, n_orders),
            "order_number": fk(12, n_orders),
            "refunded_customer_sk": fk(6, self.n_cust),
            "refunded_cdemo_sk": fk(7, self.n_demo),
            "refunded_addr_sk": fk(9, self.n_addr),
            "returning_customer_sk": fk(13, self.n_cust),
            "returning_cdemo_sk": fk(14, self.n_demo),
            "returning_addr_sk": fk(15, self.n_addr),
            "call_center_sk": fk(16, self._n("call_center")),
            "catalog_page_sk": fk(17, self._n("catalog_page")),
            "web_page_sk": fk(18, self._n("web_page")),
            "return_quantity": lambda: (1 + (
                _h(key, tag0 + 1) % _U64(100)).astype(np.int64)
            ).astype(np.int32),
            "return_amt": amt,
            "return_amount": amt,
            "return_tax": tax,
            "return_amt_inc_tax": lambda: np.round(amt() + tax(), 2),
            "fee": lambda: _money(key, tag0 + 19, 0.5, 100.0),
            "refunded_cash": cash,
            "reversed_charge": lambda: np.round((amt() - cash()) * 0.5, 2),
            "store_credit": lambda: np.round((amt() - cash()) * 0.5, 2),
            "account_credit": lambda: np.round((amt() - cash()) * 0.5, 2),
            "net_loss": lambda: _money(key, tag0 + 20, 0.5, 300.0),
        }
        return {c: (vals[c[len(p) + 1:]](), None) for c in cols}

    def store_returns(self, key, cols):
        # ss_ticket_number packs 8 lines per ticket (tpcds.py)
        return self._returns(key, cols, "sr", 480, "store_sales", 8)

    def catalog_returns(self, key, cols):
        return self._returns(key, cols, "cr", 500, "catalog_sales", 4)

    def web_returns(self, key, cols):
        return self._returns(key, cols, "wr", 520, "web_sales", 3)

    # -- inventory ----------------------------------------------------------
    def inventory(self, key: np.ndarray, cols: Sequence[str]):
        from .tpcds import D_BASE_SK, SALES_D0
        out = {}
        for c in cols:
            if c == "inv_date_sk":
                # weekly snapshots across the active window
                week = (_h(key, 541) % _U64(261)).astype(np.int64)
                out[c] = (D_BASE_SK + SALES_D0 + week * 7, None)
            elif c == "inv_item_sk":
                out[c] = (1 + (_h(key, 542)
                               % _U64(self.n_item)).astype(np.int64), None)
            elif c == "inv_warehouse_sk":
                out[c] = (1 + (_h(key, 543)
                               % _U64(self._n("warehouse"))
                               ).astype(np.int64), None)
            elif c == "inv_quantity_on_hand":
                out[c] = (_randint(key, 544, 0, 1000).astype(np.int32),
                          None)
            else:
                raise KeyError(c)
        return out

    # -- small dimensions ---------------------------------------------------
    def warehouse(self, key: np.ndarray, cols: Sequence[str]):
        from .tpcds import CITIES, COUNTIES, STATES
        uniq = tuple(dict.fromkeys(STATES))
        remap = np.array([uniq.index(s) for s in STATES], dtype=np.int32)
        out = {}
        for c in cols:
            if c == "w_warehouse_sk":
                out[c] = (key.astype(np.int64), None)
            elif c == "w_warehouse_id":
                out[c] = ([f"AAAAAAAA{i:08d}" for i in key], "text")
            elif c == "w_warehouse_name":
                names = tuple(f"Warehouse {i}" for i in range(1, 31))
                out[c] = ((key.astype(np.int64) - 1).astype(np.int32)
                          % len(names), names)
            elif c == "w_warehouse_sq_ft":
                out[c] = (_randint(key, 551, 50_000,
                                   1_000_000).astype(np.int32), None)
            elif c == "w_city":
                out[c] = ((_h(key, 552)
                           % _U64(len(CITIES))).astype(np.int32), CITIES)
            elif c == "w_county":
                out[c] = ((_h(key, 553)
                           % _U64(len(COUNTIES))).astype(np.int32),
                          COUNTIES)
            elif c == "w_state":
                out[c] = (remap[_pick(key, 554, STATES)], uniq)
            elif c == "w_country":
                out[c] = (np.zeros(len(key), dtype=np.int32),
                          ("United States",))
            else:
                raise KeyError(c)
        return out

    def ship_mode(self, key: np.ndarray, cols: Sequence[str]):
        out = {}
        for c in cols:
            if c == "sm_ship_mode_sk":
                out[c] = (key.astype(np.int64), None)
            elif c == "sm_ship_mode_id":
                out[c] = ([f"AAAAAAAA{i:08d}" for i in key], "text")
            elif c == "sm_type":
                out[c] = (((key.astype(np.int64) - 1)
                           % len(_SHIP_TYPES)).astype(np.int32),
                          _SHIP_TYPES)
            elif c == "sm_code":
                codes = ("AIR", "SURFACE", "SEA")
                out[c] = (((key.astype(np.int64) - 1)
                           % len(codes)).astype(np.int32), codes)
            elif c == "sm_carrier":
                out[c] = (((key.astype(np.int64) - 1)
                           % len(_CARRIERS)).astype(np.int32), _CARRIERS)
            else:
                raise KeyError(c)
        return out

    def reason(self, key: np.ndarray, cols: Sequence[str]):
        out = {}
        for c in cols:
            if c == "r_reason_sk":
                out[c] = (key.astype(np.int64), None)
            elif c == "r_reason_id":
                out[c] = ([f"AAAAAAAA{i:08d}" for i in key], "text")
            elif c == "r_reason_desc":
                out[c] = (((key.astype(np.int64) - 1)
                           % len(_REASONS)).astype(np.int32), _REASONS)
            else:
                raise KeyError(c)
        return out

    def call_center(self, key: np.ndarray, cols: Sequence[str]):
        from .tpcds import COUNTIES, FIRST_NAMES, LAST_NAMES
        out = {}
        for c in cols:
            if c == "cc_call_center_sk":
                out[c] = (key.astype(np.int64), None)
            elif c == "cc_call_center_id":
                out[c] = ([f"AAAAAAAA{i:08d}" for i in key], "text")
            elif c == "cc_name":
                out[c] = (((key.astype(np.int64) - 1)
                           % len(_CC_NAMES)).astype(np.int32), _CC_NAMES)
            elif c == "cc_manager":
                fn = _h(key, 561) % _U64(len(FIRST_NAMES))
                ln = _h(key, 562) % _U64(len(LAST_NAMES))
                out[c] = ([f"{FIRST_NAMES[int(a)]} {LAST_NAMES[int(b)]}"
                           for a, b in zip(fn, ln)], "text")
            elif c == "cc_county":
                out[c] = ((_h(key, 563)
                           % _U64(len(COUNTIES))).astype(np.int32),
                          COUNTIES)
            else:
                raise KeyError(c)
        return out

    def catalog_page(self, key: np.ndarray, cols: Sequence[str]):
        out = {}
        for c in cols:
            if c == "cp_catalog_page_sk":
                out[c] = (key.astype(np.int64), None)
            elif c == "cp_catalog_page_id":
                out[c] = ([f"AAAAAAAA{i:08d}" for i in key], "text")
            else:
                raise KeyError(c)
        return out

    def web_site(self, key: np.ndarray, cols: Sequence[str]):
        out = {}
        for c in cols:
            if c == "web_site_sk":
                out[c] = (key.astype(np.int64), None)
            elif c == "web_site_id":
                out[c] = ([f"AAAAAAAA{i:08d}" for i in key], "text")
            elif c == "web_name":
                names = tuple(f"site_{i}" for i in range(30))
                out[c] = (((key.astype(np.int64) - 1)
                           % len(names)).astype(np.int32), names)
            elif c == "web_company_name":
                out[c] = (((key.astype(np.int64) - 1)
                           % len(_WEB_COMPANIES)).astype(np.int32),
                          _WEB_COMPANIES)
            else:
                raise KeyError(c)
        return out

    def web_page(self, key: np.ndarray, cols: Sequence[str]):
        out = {}
        for c in cols:
            if c == "wp_web_page_sk":
                out[c] = (key.astype(np.int64), None)
            elif c == "wp_web_page_id":
                out[c] = ([f"AAAAAAAA{i:08d}" for i in key], "text")
            elif c == "wp_char_count":
                out[c] = (_randint(key, 571, 100,
                                   8000).astype(np.int32), None)
            else:
                raise KeyError(c)
        return out

    def income_band(self, key: np.ndarray, cols: Sequence[str]):
        out = {}
        sk = key.astype(np.int64)
        for c in cols:
            if c == "ib_income_band_sk":
                out[c] = (sk, None)
            elif c == "ib_lower_bound":
                out[c] = (((sk - 1) * 10_000).astype(np.int32), None)
            elif c == "ib_upper_bound":
                out[c] = ((sk * 10_000).astype(np.int32), None)
            else:
                raise KeyError(c)
        return out

    # -- extra columns on the base dimensions -------------------------------
    def ext_column(self, table: str, c: str, key: np.ndarray):
        """Generator for columns the base module's dimensions don't carry
        (the long tail the reference SQL references)."""
        from .tpcds import D_BASE_SK, SALES_D0, SALES_D1
        k = key.astype(np.int64)
        if table == "date_dim":
            days = k - 1
            if c == "d_dow":
                return ((days + 1) % 7).astype(np.int32), None   # 1900-01-01 = Monday
            if c == "d_week_seq":
                return (days // 7 + 1).astype(np.int32), None
            if c == "d_month_seq":
                dt = (np.datetime64("1900-01-01")
                      + days.astype("timedelta64[D]"))
                years = dt.astype("datetime64[Y]").astype(np.int64) + 1970
                months = dt.astype("datetime64[M]").astype(np.int64) \
                    % 12 + 1
                return ((years - 1900) * 12 + months - 1).astype(np.int32), None
            if c == "d_quarter_name":
                dt = (np.datetime64("1900-01-01")
                      + days.astype("timedelta64[D]"))
                years = dt.astype("datetime64[Y]").astype(np.int64) + 1970
                months = dt.astype("datetime64[M]").astype(np.int64) \
                    % 12 + 1
                qi = (years - 1900) * 4 + (months - 1) // 3
                return qi.astype(np.int32), _QUARTERS
        if table == "item":
            if c == "i_class_id":
                return (1 + _h(key, 580)
                        % _U64(len(_CLASSES))).astype(np.int32), None
            if c == "i_class":
                return (_h(key, 580)
                        % _U64(len(_CLASSES))).astype(np.int32), _CLASSES
            if c == "i_item_desc":
                return [f"Item description {int(i)}" for i in k], "text"
            if c == "i_manufact":
                return [f"manufact#{int(_h(np.asarray([i]), 581)[0] % 1000)}"
                        for i in k], "text"
            if c == "i_color":
                return (_h(key, 582)
                        % _U64(len(_COLORS))).astype(np.int32), _COLORS
            if c == "i_product_name":
                return [f"product {int(i)}" for i in k], "text"
            if c == "i_size":
                return (_h(key, 583)
                        % _U64(len(_SIZES))).astype(np.int32), _SIZES
            if c == "i_units":
                return (_h(key, 584)
                        % _U64(len(_UNITS))).astype(np.int32), _UNITS
            if c == "i_wholesale_cost":
                return _money(key, 585, 0.02, 80.0), None
        if table == "store":
            if c == "s_company_id":
                return np.ones(len(key), dtype=np.int32), None
            if c == "s_company_name":
                return np.zeros(len(key), dtype=np.int32), ("Unknown",)
            if c == "s_market_id":
                return _randint(key, 586, 1, 10).astype(np.int32), None
            if c == "s_street_number":
                return [str(100 + int(i) * 7 % 900) for i in k], "text"
            if c == "s_street_name":
                return (_h(key, 587)
                        % _U64(len(_STREET_NAMES))).astype(np.int32), \
                    _STREET_NAMES
            if c == "s_street_type":
                return (_h(key, 588)
                        % _U64(len(_STREET_TYPES))).astype(np.int32), \
                    _STREET_TYPES
            if c == "s_suite_number":
                return [f"Suite {int(i) % 100}" for i in k], "text"
        if table == "customer":
            if c == "c_salutation":
                return (_h(key, 590)
                        % _U64(len(_SALUTATIONS))).astype(np.int32), \
                    _SALUTATIONS
            if c == "c_birth_country":
                return (_h(key, 591)
                        % _U64(len(_COUNTRIES))).astype(np.int32), \
                    _COUNTRIES
            if c == "c_birth_day":
                return _randint(key, 592, 1, 28).astype(np.int32), None
            if c == "c_birth_month":
                return _randint(key, 593, 1, 12).astype(np.int32), None
            if c == "c_email_address":
                return [f"user{int(i)}@example.com" for i in k], "text"
            if c == "c_login":
                return [f"login{int(i)}" for i in k], "text"
            if c in ("c_first_sales_date_sk", "c_first_shipto_date_sk",
                     "c_last_review_date_sk"):
                tag = {"c_first_sales_date_sk": 594,
                       "c_first_shipto_date_sk": 595,
                       "c_last_review_date_sk": 596}[c]
                d = SALES_D0 + (_h(key, tag)
                                % _U64(SALES_D1 - SALES_D0)
                                ).astype(np.int64)
                return D_BASE_SK + d, None
        if table == "customer_address":
            if c == "ca_location_type":
                return (_h(key, 597)
                        % _U64(len(_LOCATION_TYPES))).astype(np.int32), \
                    _LOCATION_TYPES
            if c == "ca_street_number":
                return [str(100 + int(i) * 3 % 900) for i in k], "text"
            if c == "ca_street_name":
                return (_h(key, 598)
                        % _U64(len(_STREET_NAMES))).astype(np.int32), \
                    _STREET_NAMES
            if c == "ca_street_type":
                return (_h(key, 599)
                        % _U64(len(_STREET_TYPES))).astype(np.int32), \
                    _STREET_TYPES
            if c == "ca_suite_number":
                return [f"Suite {int(i) % 100}" for i in k], "text"
        raise KeyError(f"{table}.{c}")

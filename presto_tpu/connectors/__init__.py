from .spi import (  # noqa: F401
    Connector, ConnectorMetadata, ConnectorSplitManager, PageSource, Split,
    TableHandle, ColumnStats, TableStats, CatalogManager,
)

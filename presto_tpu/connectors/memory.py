"""In-memory table connector.

Conceptual parity with presto-memory (reference presto-memory/src/main/
java/io/prestosql/plugin/memory/MemoryConnectorFactory.java,
MemoryMetadata.java, MemoryPagesStore.java): CTAS/INSERT append batches to
a per-table store, scans serve them back — the workhorse connector for
engine tests, exactly as in the reference's test suites.
"""
from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence

from ..batch import Batch, Schema
from .spi import (
    Connector, ConnectorMetadata, ConnectorSplitManager, PageSource,
    Split, TableHandle, TableStats, notify_data_change,
)


class MemoryPageSource(PageSource):
    def __init__(self, batches: List[Batch], columns: Sequence[str]):
        self._batches = batches
        self._columns = list(columns)

    def batches(self) -> Iterator[Batch]:
        for b in self._batches:
            yield b.select(self._columns)


class _Metadata(ConnectorMetadata):
    def __init__(self, store):
        self._store = store

    def list_tables(self, schema: Optional[str] = None) -> List[str]:
        return sorted(self._store.tables)

    def table_schema(self, table: TableHandle) -> Schema:
        if table.table not in self._store.tables:
            raise KeyError(f"table {table.table!r} does not exist")
        return self._store.schemas[table.table]

    def table_stats(self, table: TableHandle) -> TableStats:
        rows = sum(b.host_count()
                   for b in self._store.tables.get(table.table, []))
        return TableStats(row_count=float(rows))


class _SplitManager(ConnectorSplitManager):
    def splits(self, table: TableHandle, desired: int = 1) -> List[Split]:
        return [Split(table, (0,))]


class MemoryConnector(Connector):
    """Writable catalog; one split per table (batches are pre-partitioned
    by however they were inserted)."""

    name = "memory"

    def __init__(self):
        self.tables: Dict[str, List[Batch]] = {}
        self.schemas: Dict[str, Schema] = {}
        self._metadata = _Metadata(self)
        self._split_manager = _SplitManager()
        # monotonic per-table data versions: the scan-cache key surface
        # (spi.Connector.data_version); bumped on every write
        self._vseq = 0
        self._versions: Dict[str, int] = {}

    def _data_changed(self, name: str) -> None:
        self._vseq += 1
        self._versions[name] = self._vseq
        notify_data_change(self, name)

    def data_version(self, table: str):
        return self._versions.get(table, 0)

    @property
    def metadata(self) -> ConnectorMetadata:
        return self._metadata

    @property
    def split_manager(self) -> ConnectorSplitManager:
        return self._split_manager

    def page_source(self, split: Split, columns: Sequence[str],
                    pushdown=None, rows_per_batch: int = 1 << 17
                    ) -> PageSource:
        # snapshot: INSERT INTO t SELECT ... FROM t must read the
        # pre-insert contents, not chase its own appends
        return MemoryPageSource(list(self.tables.get(split.table.table, [])),
                                columns)

    # -- transactions (reference spi ConnectorTransactionHandle role) -------
    def transaction_snapshot(self):
        """Cheap structural snapshot: batches are immutable, so shallow
        list copies capture the whole state."""
        return ({t: list(bs) for t, bs in self.tables.items()},
                dict(self.schemas))

    def transaction_restore(self, snap) -> None:
        tables, schemas = snap
        touched = set(self.tables) | set(tables)
        self.tables = {t: list(bs) for t, bs in tables.items()}
        self.schemas = dict(schemas)
        for t in touched:            # rollback changes data too
            self._data_changed(t)

    # -- write surface (reference spi/connector/ConnectorPageSink.java) ------
    def create_table(self, name: str, schema: Schema,
                     if_not_exists: bool = False) -> None:
        if name in self.tables:
            if if_not_exists:
                return
            raise ValueError(f"table {name!r} already exists")
        self.tables[name] = []
        self.schemas[name] = schema
        self._data_changed(name)

    def drop_table(self, name: str, if_exists: bool = False) -> None:
        if name not in self.tables:
            if if_exists:
                return
            raise KeyError(f"table {name!r} does not exist")
        del self.tables[name]
        del self.schemas[name]
        self._data_changed(name)

    def append(self, name: str, batch: Batch) -> int:
        if name not in self.tables:
            raise KeyError(f"table {name!r} does not exist")
        expected = self.schemas[name]
        if [t.display() for t in batch.schema.types] != \
                [t.display() for t in expected.types]:
            raise ValueError(
                f"insert schema mismatch for {name!r}: "
                f"{batch.schema!r} vs {expected!r}")
        # re-label columns with the table's canonical names
        relabeled = Batch(expected, batch.columns, batch.row_mask)
        self.tables[name].append(relabeled)
        self._data_changed(name)
        return relabeled.host_count()

"""Connector SPI: the plugin boundary between the engine and data sources.

Conceptual parity with Presto's SPI (reference presto-spi/src/main/java/io/
prestosql/spi/connector/: ConnectorMetadata, ConnectorSplitManager,
ConnectorPageSource(Provider), and spi/Plugin.java:33-78), reshaped for the
TPU engine: a PageSource yields device Batches (struct-of-arrays) instead of
Pages, declares which string columns have *stable dictionaries* (safe to
compile against), and accepts column pruning + conjunctive predicate
pushdown at split-source creation (the LazyBlock + TupleDomain roles).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

from ..batch import Batch, Schema
from ..types import Type


@dataclasses.dataclass(frozen=True)
class TableHandle:
    catalog: str
    schema: str
    table: str

    def __str__(self) -> str:
        return f"{self.catalog}.{self.schema}.{self.table}"


@dataclasses.dataclass(frozen=True)
class ColumnStats:
    """Per-column statistics for the cost-based optimizer (reference
    presto-spi/.../statistics/ColumnStatistics.java)."""

    distinct_count: Optional[float] = None
    null_fraction: float = 0.0
    min_value: Optional[Any] = None
    max_value: Optional[Any] = None


@dataclasses.dataclass(frozen=True)
class TableStats:
    row_count: Optional[float] = None
    columns: Dict[str, ColumnStats] = dataclasses.field(default_factory=dict)
    #: columns forming a unique key, if any — drives join build-side choice
    #: (reference spi/statistics/TableStatistics.java has no PK notion;
    #: Presto infers uniqueness from distinct counts, we declare it)
    primary_key: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class Split:
    """A unit of scan parallelism (reference spi/connector/ConnectorSplit).
    ``info`` is connector-opaque."""

    table: TableHandle
    info: Tuple = ()


class PageSource:
    """Produces device batches for one split (reference
    spi/connector/ConnectorPageSource.java)."""

    def batches(self) -> Iterator[Batch]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class ConnectorMetadata:
    """Catalog surface (reference spi/connector/ConnectorMetadata.java)."""

    def list_schemas(self) -> List[str]:
        """Schemas this catalog exposes. Most connectors here flatten
        schemas into one namespace; the default advertises just
        "default". The planner consults this to resolve two-part names
        the reference way (``x.y`` = schema ``x`` in the session catalog
        when that schema exists, catalog-first only as a fallback)."""
        return ["default"]

    def list_tables(self, schema: Optional[str] = None) -> List[str]:
        raise NotImplementedError

    def table_schema(self, table: TableHandle) -> Schema:
        raise NotImplementedError

    def table_stats(self, table: TableHandle) -> TableStats:
        return TableStats()


class ConnectorSplitManager:
    """Split enumeration (reference spi/connector/ConnectorSplitManager)."""

    def splits(self, table: TableHandle, desired: int = 1) -> List[Split]:
        raise NotImplementedError


class Connector:
    """One mounted catalog (reference spi/connector/Connector.java)."""

    name: str = "connector"

    @property
    def metadata(self) -> ConnectorMetadata:
        raise NotImplementedError

    @property
    def split_manager(self) -> ConnectorSplitManager:
        raise NotImplementedError

    def data_version(self, table: str) -> Optional[Any]:
        """Data-version token for one table, or None when the connector
        cannot attest one. The engine's cross-query device scan cache
        (exec/scancache.py) keys cached split data by this token:

        - None (the default) disables caching for the table — correct
          for live/views-of-state sources (system.runtime) and for
          connectors whose underlying data can change without the
          connector seeing the write;
        - immutable generators (tpch/tpcds) return a constant;
        - writable connectors return a counter bumped on every write,
          through the same code path that invalidates their own stats
          caches (and that calls :func:`notify_data_change`).
        """
        return None

    def page_source(
        self,
        split: Split,
        columns: Sequence[str],
        pushdown: Optional[object] = None,
        rows_per_batch: int = 1 << 17,
    ) -> PageSource:
        raise NotImplementedError


# -- data-change notification -------------------------------------------------
# The engine-side hook connector writes flow through so cross-connector
# caches (the device scan cache, exec/scancache.py) invalidate on the
# SAME path that invalidates a connector's own stats/schema caches.
# Listener registration is process-wide and append-only (like the
# reference's event-listener plumbing, but synchronous and in-process).

_DATA_CHANGE_LISTENERS: List[Any] = []


def on_data_change(listener) -> None:
    """Register ``listener(connector, table_name)`` to run after every
    connector write (append / create / drop / transaction restore)."""
    _DATA_CHANGE_LISTENERS.append(listener)


def notify_data_change(connector: "Connector", table: str) -> None:
    """Connectors call this from their write paths, right where they
    invalidate their own caches."""
    for listener in list(_DATA_CHANGE_LISTENERS):
        listener(connector, table)


class CatalogManager:
    """catalog name -> Connector registry (reference
    presto-main/.../metadata/CatalogManager.java + ConnectorManager)."""

    def __init__(self):
        self._catalogs: Dict[str, Connector] = {}

    def register(self, name: str, connector: Connector) -> None:
        self._catalogs[name] = connector

    def get(self, name: str) -> Connector:
        if name not in self._catalogs:
            raise KeyError(f"unknown catalog {name!r}")
        return self._catalogs[name]

    def exists(self, name: str) -> bool:
        return name in self._catalogs

    def names(self) -> List[str]:
        return sorted(self._catalogs)

"""TPC-H data-generator connector.

Conceptual parity with presto-tpch (reference presto-tpch/src/main/java/io/
prestosql/plugin/tpch/TpchConnectorFactory.java, TpchMetadata.java,
TpchRecordSetProvider wrapping io.airlift.tpch generators), re-designed for
vectorized device-feeding: every column is a pure stateless-hash function of
the row's primary key (splitmix64), so any split can generate any row range
with full referential consistency (l_extendedprice really is quantity *
p_retailprice(l_partkey), lineitem dates derive from the parent order's
orderdate) and no cross-table reads — the generator is embarrassingly
parallel across splits and hosts.

Distributions follow the TPC-H spec shapes (selectivities match within
sampling noise; e.g. Q6's date/discount/quantity predicate selects ~2%).
Exact dbgen bit-compatibility is NOT a goal: correctness tests compare
against an oracle computed over this same data.

Low-cardinality columns carry *stable dictionaries* (compile-friendly);
formatted/unique names and comments are per-batch text columns.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from .. import types as T
from ..batch import Batch, Schema
from .spi import (
    ColumnStats, Connector, ConnectorMetadata, ConnectorSplitManager,
    PageSource, Split, TableHandle, TableStats,
)

# Epoch-day constants (see spec 4.2.3)
START_DATE = 8035        # 1992-01-01
END_ORDERDATE = 10440    # 1998-08-02
CURRENT_DATE = 9298      # 1995-06-17
ORDERDATE_SPAN = END_ORDERDATE - START_DATE + 1

_U64 = np.uint64
_GOLDEN = _U64(0x9E3779B97F4A7C15)


def _splitmix64(x: np.ndarray) -> np.ndarray:
    x = (x + _GOLDEN).astype(_U64)
    x = ((x ^ (x >> _U64(30))) * _U64(0xBF58476D1CE4E5B9)).astype(_U64)
    x = ((x ^ (x >> _U64(27))) * _U64(0x94D049BB133111EB)).astype(_U64)
    return (x ^ (x >> _U64(31))).astype(_U64)


def _h(key: np.ndarray, tag: int) -> np.ndarray:
    """Per-column hash stream over a key array."""
    tag_mix = _U64((tag * 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF)
    k = key.astype(_U64) ^ tag_mix
    return _splitmix64(k)


def _randint(key, tag, lo, hi) -> np.ndarray:
    """Uniform integers in [lo, hi] as int64."""
    h = _h(key, tag)
    span = _U64(hi - lo + 1)
    # add in int64: NumPy 2 (NEP 50) raises OverflowError mixing a negative
    # python int with a uint64 array
    return np.int64(lo) + (h % span).astype(np.int64)


def _money(key, tag, lo, hi) -> np.ndarray:
    """Uniform price with 2 decimal digits, as double."""
    cents = _randint(key, tag, int(lo * 100), int(hi * 100))
    return cents.astype(np.float64) / 100.0


# -- word lists (spec appendix; abbreviated but spec-shaped) -----------------

SEGMENTS = ("AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD")
PRIORITIES = ("1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW")
INSTRUCTS = ("DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN")
MODES = ("REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB")
ORDER_STATUS = ("F", "O", "P")
RETURN_FLAGS = ("A", "N", "R")
LINE_STATUS = ("O", "F")
TYPE_S1 = ("STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO")
TYPE_S2 = ("ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED")
TYPE_S3 = ("TIN", "NICKEL", "BRASS", "STEEL", "COPPER")
P_TYPES = tuple(f"{a} {b} {c}" for a in TYPE_S1 for b in TYPE_S2 for c in TYPE_S3)
CONTAINER_S1 = ("SM", "LG", "MED", "JUMBO", "WRAP")
CONTAINER_S2 = ("CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM")
CONTAINERS = tuple(f"{a} {b}" for a in CONTAINER_S1 for b in CONTAINER_S2)
MFGRS = tuple(f"Manufacturer#{i}" for i in range(1, 6))
BRANDS = tuple(f"Brand#{i}{j}" for i in range(1, 6) for j in range(1, 6))
P_NAME_WORDS = (
    "almond antique aquamarine azure beige bisque black blanched blue blush".split()
    + "brown burlywood burnished chartreuse chiffon chocolate coral cornflower".split()
    + "cornsilk cream cyan dark deep dim dodger drab firebrick floral".split()
    + "forest frosted gainsboro ghost goldenrod green grey honeydew hot indian".split()
    + "ivory khaki lace lavender lawn lemon light lime linen magenta".split()
    + "maroon medium metallic midnight mint misty moccasin navajo navy olive".split()
    + "orange orchid pale papaya peach peru pink plum powder puff".split()
    + "purple red rose rosy royal saddle salmon sandy seashell sienna".split()
    + "sky slate smoke snow spring steel tan thistle tomato turquoise".split()
    + "violet wheat white yellow".split()
)
NATIONS = (
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
)
REGIONS = ("AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST")
COMMENT_WORDS = (
    "furiously quickly carefully slyly blithely final express regular special "
    "pending unusual ironic even bold silent fluffy ruthless idle busy daring "
    "deposits requests accounts packages instructions theodolites foxes ideas "
    "pinto beans dependencies excuses platelets asymptotes courts dolphins "
    "sleep nag haggle wake dazzle cajole boost detect engage integrate"
).split()


def _pick(key, tag, values: Tuple[str, ...]) -> np.ndarray:
    """Enum column: int32 codes into a stable dictionary."""
    return (_h(key, tag) % _U64(len(values))).astype(np.int32)


def _comment(key, tag, nwords=4) -> List[str]:
    idx = [(_h(key, tag * 97 + i) % _U64(len(COMMENT_WORDS))).astype(np.int64)
           for i in range(nwords)]
    w = np.asarray(COMMENT_WORDS, dtype=object)
    parts = [w[i] for i in idx]
    out = parts[0]
    for p in parts[1:]:
        out = out + " " + p
    return list(out)


def _p_name(key) -> List[str]:
    w = np.asarray(P_NAME_WORDS, dtype=object)
    parts = [w[(_h(key, 300 + i) % _U64(len(P_NAME_WORDS))).astype(np.int64)]
             for i in range(5)]
    out = parts[0]
    for p in parts[1:]:
        out = out + " " + p
    return list(out)


def _phone(key, tag, nationkey) -> List[str]:
    a = 10 + nationkey
    b = _randint(key, tag + 1, 100, 999)
    c = _randint(key, tag + 2, 100, 999)
    d = _randint(key, tag + 3, 1000, 9999)
    return [f"{ai}-{bi}-{ci}-{di}" for ai, bi, ci, di in zip(a, b, c, d)]


def _retailprice(partkey: np.ndarray) -> np.ndarray:
    # spec 4.2.3: (90000 + ((partkey/10) mod 20001) + 100*(partkey mod 1000))/100
    pk = partkey.astype(np.int64)
    return (90000 + (pk // 10) % 20001 + 100 * (pk % 1000)) / 100.0


def _supplier_of_part(partkey, i, scale_suppliers):
    # spec 4.2.3 partsupp.suppkey formula: spreads each part's 4 suppliers
    pk = partkey.astype(np.int64)
    s = scale_suppliers
    return (pk + i * (s // 4 + (pk - 1) // s)) % s + 1


# -- per-table row counts ----------------------------------------------------

def _rows(table: str, sf: float) -> int:
    base = {
        "customer": 150_000, "orders": 1_500_000, "part": 200_000,
        "supplier": 10_000, "partsupp": 800_000,
        "nation": 25, "region": 5,
    }
    if table == "lineitem":
        # ~4 lines per order on average (exact count derived per split)
        return int(6_000_000 * sf)
    if table in ("nation", "region"):
        return base[table]
    return int(base[table] * sf)


# -- schemas (types match presto-tpch defaults: DOUBLE prices) ---------------

V = T.VARCHAR
_SCHEMAS: Dict[str, List[Tuple[str, T.Type]]] = {
    "lineitem": [
        ("l_orderkey", T.BIGINT), ("l_partkey", T.BIGINT),
        ("l_suppkey", T.BIGINT), ("l_linenumber", T.INTEGER),
        ("l_quantity", T.DOUBLE), ("l_extendedprice", T.DOUBLE),
        ("l_discount", T.DOUBLE), ("l_tax", T.DOUBLE),
        ("l_returnflag", T.varchar(1)), ("l_linestatus", T.varchar(1)),
        ("l_shipdate", T.DATE), ("l_commitdate", T.DATE),
        ("l_receiptdate", T.DATE), ("l_shipinstruct", T.varchar(25)),
        ("l_shipmode", T.varchar(10)), ("l_comment", T.varchar(44)),
    ],
    "orders": [
        ("o_orderkey", T.BIGINT), ("o_custkey", T.BIGINT),
        ("o_orderstatus", T.varchar(1)), ("o_totalprice", T.DOUBLE),
        ("o_orderdate", T.DATE), ("o_orderpriority", T.varchar(15)),
        ("o_clerk", T.varchar(15)), ("o_shippriority", T.INTEGER),
        ("o_comment", T.varchar(79)),
    ],
    "customer": [
        ("c_custkey", T.BIGINT), ("c_name", T.varchar(25)),
        ("c_address", T.varchar(40)), ("c_nationkey", T.BIGINT),
        ("c_phone", T.varchar(15)), ("c_acctbal", T.DOUBLE),
        ("c_mktsegment", T.varchar(10)), ("c_comment", T.varchar(117)),
    ],
    "part": [
        ("p_partkey", T.BIGINT), ("p_name", T.varchar(55)),
        ("p_mfgr", T.varchar(25)), ("p_brand", T.varchar(10)),
        ("p_type", T.varchar(25)), ("p_size", T.INTEGER),
        ("p_container", T.varchar(10)), ("p_retailprice", T.DOUBLE),
        ("p_comment", T.varchar(23)),
    ],
    "supplier": [
        ("s_suppkey", T.BIGINT), ("s_name", T.varchar(25)),
        ("s_address", T.varchar(40)), ("s_nationkey", T.BIGINT),
        ("s_phone", T.varchar(15)), ("s_acctbal", T.DOUBLE),
        ("s_comment", T.varchar(101)),
    ],
    "partsupp": [
        ("ps_partkey", T.BIGINT), ("ps_suppkey", T.BIGINT),
        ("ps_availqty", T.INTEGER), ("ps_supplycost", T.DOUBLE),
        ("ps_comment", T.varchar(199)),
    ],
    "nation": [
        ("n_nationkey", T.BIGINT), ("n_name", T.varchar(25)),
        ("n_regionkey", T.BIGINT), ("n_comment", T.varchar(152)),
    ],
    "region": [
        ("r_regionkey", T.BIGINT), ("r_name", T.varchar(25)),
        ("r_comment", T.varchar(152)),
    ],
}

TABLES = tuple(_SCHEMAS)


def _orders_orderdate(okey: np.ndarray) -> np.ndarray:
    return START_DATE + (_h(okey, 5) % _U64(ORDERDATE_SPAN)).astype(np.int64)


def _lines_per_order(okey: np.ndarray) -> np.ndarray:
    return 1 + (_h(okey, 100) % _U64(7)).astype(np.int64)


class _Gen:
    """Column generators. Each returns (np storage array, dictionary|None)
    given the key array (primary key / row id, 1-based)."""

    def __init__(self, sf: float):
        self.sf = sf
        self.n_cust = _rows("customer", sf)
        self.n_part = _rows("part", sf)
        self.n_supp = _rows("supplier", sf)
        self.n_orders = _rows("orders", sf)

    # ---- orders ----
    def orders(self, key: np.ndarray, cols: Sequence[str]):
        out = {}
        odate = _orders_orderdate(key)
        for c in cols:
            if c == "o_orderkey":
                out[c] = (key.astype(np.int64), None)
            elif c == "o_custkey":
                ck = 1 + (_h(key, 1) % _U64(self.n_cust)).astype(np.int64)
                # spec: a third of customers never place orders
                ck = np.where(ck % 3 == 0, np.maximum(ck - 1, 1), ck)
                out[c] = (ck, None)
            elif c == "o_orderstatus":
                # F = all lines shipped (old orders), O = none (recent), P = mixed
                code = np.where(odate + 182 < CURRENT_DATE, 0,
                                np.where(odate > CURRENT_DATE, 1, 2))
                out[c] = (code.astype(np.int32), ORDER_STATUS)
            elif c == "o_totalprice":
                out[c] = (_money(key, 3, 1000.0, 500000.0), None)
            elif c == "o_orderdate":
                out[c] = (odate.astype(np.int32), None)
            elif c == "o_orderpriority":
                out[c] = (_pick(key, 6, PRIORITIES), PRIORITIES)
            elif c == "o_clerk":
                n = max(1, int(1000 * self.sf))
                ids = 1 + (_h(key, 7) % _U64(n)).astype(np.int64)
                out[c] = ([f"Clerk#{i:09d}" for i in ids], "text")
            elif c == "o_shippriority":
                out[c] = (np.zeros(len(key), dtype=np.int32), None)
            elif c == "o_comment":
                out[c] = (_comment(key, 8, 5), "text")
            else:
                raise KeyError(c)
        return out

    # ---- lineitem (key = orderkey*8 + linenumber) ----
    def lineitem(self, okey: np.ndarray, ln: np.ndarray, cols: Sequence[str]):
        key = (okey.astype(np.int64) * 8 + ln).astype(np.int64)
        odate = _orders_orderdate(okey)
        out = {}
        partkey = 1 + (_h(key, 11) % _U64(self.n_part)).astype(np.int64)
        quantity = 1 + (_h(key, 13) % _U64(50)).astype(np.int64)
        shipdate = odate + 1 + (_h(key, 17) % _U64(121)).astype(np.int64)
        receipt = shipdate + 1 + (_h(key, 19) % _U64(30)).astype(np.int64)
        for c in cols:
            if c == "l_orderkey":
                out[c] = (okey.astype(np.int64), None)
            elif c == "l_partkey":
                out[c] = (partkey, None)
            elif c == "l_suppkey":
                i = (_h(key, 12) % _U64(4)).astype(np.int64)
                out[c] = (_supplier_of_part(partkey, i, self.n_supp), None)
            elif c == "l_linenumber":
                out[c] = ((ln + 1).astype(np.int32), None)
            elif c == "l_quantity":
                out[c] = (quantity.astype(np.float64), None)
            elif c == "l_extendedprice":
                out[c] = (quantity * _retailprice(partkey), None)
            elif c == "l_discount":
                out[c] = ((_h(key, 14) % _U64(11)).astype(np.float64) / 100.0, None)
            elif c == "l_tax":
                out[c] = ((_h(key, 15) % _U64(9)).astype(np.float64) / 100.0, None)
            elif c == "l_returnflag":
                r = (_h(key, 16) % _U64(2)).astype(np.int32)  # A or R
                code = np.where(receipt <= CURRENT_DATE, r * 2, 1)  # N else
                out[c] = (code.astype(np.int32), RETURN_FLAGS)
            elif c == "l_linestatus":
                out[c] = (np.where(shipdate > CURRENT_DATE, 0, 1).astype(np.int32),
                          LINE_STATUS)
            elif c == "l_shipdate":
                out[c] = (shipdate.astype(np.int32), None)
            elif c == "l_commitdate":
                commit = odate + 30 + (_h(key, 18) % _U64(61)).astype(np.int64)
                out[c] = (commit.astype(np.int32), None)
            elif c == "l_receiptdate":
                out[c] = (receipt.astype(np.int32), None)
            elif c == "l_shipinstruct":
                out[c] = (_pick(key, 20, INSTRUCTS), INSTRUCTS)
            elif c == "l_shipmode":
                out[c] = (_pick(key, 21, MODES), MODES)
            elif c == "l_comment":
                out[c] = (_comment(key, 22, 3), "text")
            else:
                raise KeyError(c)
        return out

    # ---- customer ----
    def customer(self, key: np.ndarray, cols: Sequence[str]):
        out = {}
        nation = (_h(key, 31) % _U64(25)).astype(np.int64)
        for c in cols:
            if c == "c_custkey":
                out[c] = (key.astype(np.int64), None)
            elif c == "c_name":
                out[c] = ([f"Customer#{i:09d}" for i in key], "text")
            elif c == "c_address":
                out[c] = (_comment(key, 32, 3), "text")
            elif c == "c_nationkey":
                out[c] = (nation, None)
            elif c == "c_phone":
                out[c] = (_phone(key, 33, nation), "text")
            elif c == "c_acctbal":
                out[c] = (_money(key, 34, -999.99, 9999.99), None)
            elif c == "c_mktsegment":
                out[c] = (_pick(key, 35, SEGMENTS), SEGMENTS)
            elif c == "c_comment":
                out[c] = (_comment(key, 36, 6), "text")
            else:
                raise KeyError(c)
        return out

    # ---- part ----
    def part(self, key: np.ndarray, cols: Sequence[str]):
        out = {}
        for c in cols:
            if c == "p_partkey":
                out[c] = (key.astype(np.int64), None)
            elif c == "p_name":
                out[c] = (_p_name(key), "text")
            elif c == "p_mfgr":
                m = (_h(key, 41) % _U64(5)).astype(np.int32)
                out[c] = (m, MFGRS)
            elif c == "p_brand":
                # brand within mfgr: Brand#MJ
                m = (_h(key, 41) % _U64(5)).astype(np.int64)
                j = (_h(key, 42) % _U64(5)).astype(np.int64)
                out[c] = ((m * 5 + j).astype(np.int32), BRANDS)
            elif c == "p_type":
                out[c] = (_pick(key, 43, P_TYPES), P_TYPES)
            elif c == "p_size":
                out[c] = (_randint(key, 44, 1, 50).astype(np.int32), None)
            elif c == "p_container":
                out[c] = (_pick(key, 45, CONTAINERS), CONTAINERS)
            elif c == "p_retailprice":
                out[c] = (_retailprice(key), None)
            elif c == "p_comment":
                out[c] = (_comment(key, 46, 2), "text")
            else:
                raise KeyError(c)
        return out

    # ---- supplier ----
    def supplier(self, key: np.ndarray, cols: Sequence[str]):
        out = {}
        nation = (_h(key, 51) % _U64(25)).astype(np.int64)
        for c in cols:
            if c == "s_suppkey":
                out[c] = (key.astype(np.int64), None)
            elif c == "s_name":
                out[c] = ([f"Supplier#{i:09d}" for i in key], "text")
            elif c == "s_address":
                out[c] = (_comment(key, 52, 3), "text")
            elif c == "s_nationkey":
                out[c] = (nation, None)
            elif c == "s_phone":
                out[c] = (_phone(key, 53, nation), "text")
            elif c == "s_acctbal":
                out[c] = (_money(key, 54, -999.99, 9999.99), None)
            elif c == "s_comment":
                # spec: some suppliers have "Customer Complaints"/"Recommends"
                base = _comment(key, 55, 5)
                h = _h(key, 56) % _U64(2000)
                txt = [
                    ("Customer Complaints " + b) if hi < 10 else
                    ("Customer Recommends " + b) if hi < 20 else b
                    for b, hi in zip(base, h)
                ]
                out[c] = (txt, "text")
            else:
                raise KeyError(c)
        return out

    # ---- partsupp (key = row id 1..4*n_part) ----
    def partsupp(self, key: np.ndarray, cols: Sequence[str]):
        out = {}
        pk = 1 + (key.astype(np.int64) - 1) // 4
        i = (key.astype(np.int64) - 1) % 4
        for c in cols:
            if c == "ps_partkey":
                out[c] = (pk, None)
            elif c == "ps_suppkey":
                out[c] = (_supplier_of_part(pk, i, self.n_supp), None)
            elif c == "ps_availqty":
                out[c] = (_randint(key, 61, 1, 9999).astype(np.int32), None)
            elif c == "ps_supplycost":
                out[c] = (_money(key, 62, 1.0, 1000.0), None)
            elif c == "ps_comment":
                out[c] = (_comment(key, 63, 6), "text")
            else:
                raise KeyError(c)
        return out

    # ---- nation / region (tiny, fixed) ----
    def nation(self, key: np.ndarray, cols: Sequence[str]):
        out = {}
        names = tuple(n for n, _ in NATIONS)
        for c in cols:
            if c == "n_nationkey":
                out[c] = (key.astype(np.int64) - 1, None)
            elif c == "n_name":
                out[c] = ((key - 1).astype(np.int32), names)
            elif c == "n_regionkey":
                rk = np.asarray([NATIONS[int(k) - 1][1] for k in key], dtype=np.int64)
                out[c] = (rk, None)
            elif c == "n_comment":
                out[c] = (_comment(key, 71, 4), "text")
            else:
                raise KeyError(c)
        return out

    def region(self, key: np.ndarray, cols: Sequence[str]):
        out = {}
        for c in cols:
            if c == "r_regionkey":
                out[c] = (key.astype(np.int64) - 1, None)
            elif c == "r_name":
                out[c] = ((key - 1).astype(np.int32), REGIONS)
            elif c == "r_comment":
                out[c] = (_comment(key, 72, 4), "text")
            else:
                raise KeyError(c)
        return out


def _to_batch(schema: Schema, cols: Sequence[str], data: Dict, n: int) -> Batch:
    arrays, dicts = [], []
    out_schema = schema.select(list(cols))
    for name in cols:
        arr, vocab = data[name]
        if vocab == "text":
            # per-batch vocabulary for free-text columns
            uniq: Dict[str, int] = {}
            codes = np.empty(n, dtype=np.int32)
            for i, s in enumerate(arr):
                code = uniq.get(s)
                if code is None:
                    code = uniq[s] = len(uniq)
                codes[i] = code
            arrays.append(codes)
            dicts.append(tuple(uniq))
        elif vocab is not None:
            arrays.append(arr)
            dicts.append(tuple(vocab))
        else:
            arrays.append(arr)
            dicts.append(None)
    return Batch.from_arrays(out_schema, arrays, None, dicts, num_rows=n)


class TpchPageSource(PageSource):
    def __init__(self, gen: _Gen, split: Split, columns: Sequence[str],
                 rows_per_batch: int):
        self.gen = gen
        self.split = split
        self.columns = list(columns)
        self.rows_per_batch = rows_per_batch

    def host_chunks(self):
        """(schema, generated column dict, row count) per chunk, host-side
        only — lets callers that want host arrays (bench staging, oracles)
        skip the device round trip."""
        table = self.split.table.table
        schema = tpch_schema(table)
        if table == "lineitem":
            o_start, o_end = self.split.info
            # orders per chunk such that ~rows_per_batch lines (avg 4/order)
            step = max(1, self.rows_per_batch // 4)
            for a in range(o_start, o_end, step):
                b = min(a + step, o_end)
                okeys = np.arange(a, b, dtype=np.int64)
                counts = _lines_per_order(okeys)
                rep_ok = np.repeat(okeys, counts)
                ln = np.arange(len(rep_ok)) - np.repeat(
                    np.cumsum(counts) - counts, counts)
                data = self.gen.lineitem(rep_ok, ln, self.columns)
                yield schema, data, len(rep_ok)
            return
        start, end = self.split.info
        genfn = getattr(self.gen, table)
        for a in range(start, end, self.rows_per_batch):
            b = min(a + self.rows_per_batch, end)
            keys = np.arange(a, b, dtype=np.int64)
            yield schema, genfn(keys, self.columns), b - a

    def batches(self) -> Iterator[Batch]:
        for schema, data, n in self.host_chunks():
            yield _to_batch(schema, self.columns, data, n)


def tpch_schema(table: str) -> Schema:
    return Schema(_SCHEMAS[table])


class _Metadata(ConnectorMetadata):
    def __init__(self, sf: float):
        self.sf = sf

    def list_tables(self, schema: Optional[str] = None) -> List[str]:
        return list(TABLES)

    def table_schema(self, table: TableHandle) -> Schema:
        if table.table not in _SCHEMAS:
            raise KeyError(f"unknown tpch table {table.table!r}")
        return tpch_schema(table.table)

    _PRIMARY_KEYS = {
        "lineitem": ("l_orderkey", "l_linenumber"),
        "orders": ("o_orderkey",),
        "customer": ("c_custkey",),
        "part": ("p_partkey",),
        "supplier": ("s_suppkey",),
        "partsupp": ("ps_partkey", "ps_suppkey"),
        "nation": ("n_nationkey",),
        "region": ("r_regionkey",),
    }

    def table_stats(self, table: TableHandle) -> TableStats:
        t = table.table
        n = float(_rows(t, self.sf))
        cols: Dict[str, ColumnStats] = {}
        if t == "lineitem":
            n_orders = _rows("orders", self.sf)
            cols["l_orderkey"] = ColumnStats(n_orders, 0.0, 1, n_orders)
            cols["l_partkey"] = ColumnStats(
                _rows("part", self.sf), 0.0, 1, _rows("part", self.sf))
            cols["l_suppkey"] = ColumnStats(
                _rows("supplier", self.sf), 0.0, 1,
                _rows("supplier", self.sf))
            cols["l_linenumber"] = ColumnStats(7, 0.0, 1, 7)
            cols["l_shipdate"] = ColumnStats(ORDERDATE_SPAN + 151, 0.0, START_DATE, END_ORDERDATE + 151)
            cols["l_discount"] = ColumnStats(11, 0.0, 0.0, 0.10)
            cols["l_tax"] = ColumnStats(9, 0.0, 0.0, 0.08)
            cols["l_quantity"] = ColumnStats(50, 0.0, 1.0, 50.0)
        if t == "orders":
            cols["o_orderkey"] = ColumnStats(n, 0.0, 1, int(n))
            cols["o_custkey"] = ColumnStats(
                _rows("customer", self.sf), 0.0, 1,
                _rows("customer", self.sf))
            cols["o_orderdate"] = ColumnStats(ORDERDATE_SPAN, 0.0, START_DATE, END_ORDERDATE)
        # dimension key bounds are EXACT from the generator (sequential
        # 1..n keys; nation/region domains fixed by spec) — hard bounds,
        # so the planner may select dense-key direct-address joins
        # (optimizer._attach_join_strategy) and stats-bounded grouping
        # on these columns, same contract as TpcdsConnector.table_stats
        if t == "customer":
            cols["c_custkey"] = ColumnStats(n, 0.0, 1, int(n))
            cols["c_nationkey"] = ColumnStats(25, 0.0, 0, 24)
        if t == "part":
            cols["p_partkey"] = ColumnStats(n, 0.0, 1, int(n))
            cols["p_size"] = ColumnStats(50, 0.0, 1, 50)
        if t == "supplier":
            cols["s_suppkey"] = ColumnStats(n, 0.0, 1, int(n))
            cols["s_nationkey"] = ColumnStats(25, 0.0, 0, 24)
        if t == "partsupp":
            cols["ps_partkey"] = ColumnStats(
                _rows("part", self.sf), 0.0, 1, _rows("part", self.sf))
            cols["ps_suppkey"] = ColumnStats(
                _rows("supplier", self.sf), 0.0, 1,
                _rows("supplier", self.sf))
        if t == "nation":
            cols["n_nationkey"] = ColumnStats(25, 0.0, 0, 24)
            cols["n_regionkey"] = ColumnStats(5, 0.0, 0, 4)
        if t == "region":
            cols["r_regionkey"] = ColumnStats(5, 0.0, 0, 4)
        for pk in self._PRIMARY_KEYS.get(t, ()):
            if pk not in cols:
                cols[pk] = ColumnStats(distinct_count=n if len(self._PRIMARY_KEYS[t]) == 1 else None)
        return TableStats(row_count=n, columns=cols,
                          primary_key=self._PRIMARY_KEYS.get(t, ()))


class _SplitManager(ConnectorSplitManager):
    def __init__(self, sf: float):
        self.sf = sf

    def splits(self, table: TableHandle, desired: int = 1) -> List[Split]:
        t = table.table
        if t == "lineitem":
            n = _rows("orders", self.sf)
        else:
            n = _rows(t, self.sf)
        desired = max(1, min(desired, n))
        bounds = np.linspace(1, n + 1, desired + 1, dtype=np.int64)
        return [
            Split(table, (int(bounds[i]), int(bounds[i + 1])))
            for i in range(desired)
            if bounds[i] < bounds[i + 1]
        ]


class TpchConnector(Connector):
    """catalog 'tpch', schema names are scale factors ('sf1', 'tiny'...)."""

    name = "tpch"

    def __init__(self, sf: float = 0.01):
        self.sf = sf
        self._metadata = _Metadata(sf)
        self._splits = _SplitManager(sf)
        self._gen = _Gen(sf)

    def data_version(self, table: str):
        # stateless generator: any split regenerates identically for the
        # connector's whole lifetime, so the device scan cache may hold it
        return 0

    @property
    def metadata(self) -> ConnectorMetadata:
        return self._metadata

    @property
    def split_manager(self) -> ConnectorSplitManager:
        return self._splits

    def page_source(self, split: Split, columns: Sequence[str],
                    pushdown=None, rows_per_batch: int = 1 << 17) -> PageSource:
        return TpchPageSource(self._gen, split, columns, rows_per_batch)

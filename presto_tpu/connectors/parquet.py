"""Parquet connector: a table is a directory of parquet files.

The Parquet sibling of connectors/orc.py on the shared directory-
connector base (reference presto-hive/.../HivePageSourceProvider.java
dispatching to parquet/ParquetPageSourceFactory.java); row-group min/max
pruning from footer statistics rides the scan pushdown (reference
predicate/TupleDomainParquetPredicate.java).
"""
from __future__ import annotations

from typing import Sequence

from ..formats.parquet import ParquetReader
from .filebase import FileConnectorBase
from .spi import PageSource


class _ParquetPageSource(PageSource):
    def __init__(self, conn: "ParquetConnector", path: str,
                 columns: Sequence[str], pushdown):
        self.conn = conn
        self.path = path
        self.columns = list(columns)
        self.pushdown = pushdown

    def batches(self):
        yield from self.conn.reader(self.path).batches(
            self.columns, self.pushdown)


class ParquetConnector(FileConnectorBase):
    name = "parquet"
    extension = ".parquet"

    def open_reader(self, path: str) -> ParquetReader:
        return ParquetReader(path)

    def write_file(self, path: str, schema, batches) -> int:
        import numpy as np
        from ..formats.parquet import write_parquet
        cols = [[] for _ in schema.names]
        n = 0
        for b in batches:
            rows = b.to_pylist()
            n += len(rows)
            for r in rows:
                for i, v in enumerate(r):
                    cols[i].append(v)
        write_parquet(path, schema, cols)
        return n

    def make_page_source(self, path, columns, pushdown) -> PageSource:
        return _ParquetPageSource(self, path, columns, pushdown)

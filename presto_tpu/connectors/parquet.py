"""Parquet connector: a table is a directory of parquet files.

The Parquet sibling of connectors/orc.py (reference
presto-hive/.../HivePageSourceProvider.java dispatching to
parquet/ParquetPageSourceFactory.java): schema = directory, table =
subdirectory (or a single ``.parquet`` file), one split per file,
row-group min/max pruning from footer statistics (reference
predicate/TupleDomainParquetPredicate.java).
"""
from __future__ import annotations

import os
from collections import OrderedDict
from typing import List, Optional, Sequence, Tuple

from ..batch import Schema
from ..formats.parquet import ParquetReader
from .spi import (
    Connector, ConnectorMetadata, ConnectorSplitManager, PageSource, Split,
    TableHandle, TableStats,
)

_READERS: "OrderedDict[Tuple[str, float], ParquetReader]" = OrderedDict()


def _reader(path: str) -> ParquetReader:
    key = (path, os.path.getmtime(path))
    r = _READERS.get(key)
    if r is None:
        r = _READERS[key] = ParquetReader(path)
        while len(_READERS) > 64:
            _READERS.popitem(last=False)
    else:
        _READERS.move_to_end(key)
    return r


def _table_files(root: str, table: str) -> List[str]:
    path = os.path.join(root, table)
    if os.path.isdir(path):
        return sorted(
            os.path.join(path, f) for f in os.listdir(path)
            if f.endswith(".parquet"))
    if os.path.isfile(path + ".parquet"):
        return [path + ".parquet"]
    raise KeyError(f"unknown parquet table {table!r}")


class _Metadata(ConnectorMetadata):
    def __init__(self, root: str):
        self.root = root

    def list_tables(self, schema: Optional[str] = None) -> List[str]:
        out = []
        for entry in sorted(os.listdir(self.root)):
            full = os.path.join(self.root, entry)
            if os.path.isdir(full) and _table_files(self.root, entry):
                out.append(entry)
            elif entry.endswith(".parquet"):
                out.append(entry[:-8])
        return out

    def table_schema(self, table: TableHandle) -> Schema:
        files = _table_files(self.root, table.table)
        return _reader(files[0]).schema

    def table_stats(self, table: TableHandle) -> TableStats:
        rows = 0.0
        for f in _table_files(self.root, table.table):
            rows += _reader(f).num_rows
        return TableStats(row_count=rows, columns={}, primary_key=())


class _SplitManager(ConnectorSplitManager):
    def __init__(self, root: str):
        self.root = root

    def splits(self, table: TableHandle, desired: int = 1) -> List[Split]:
        return [Split(table, (f,))
                for f in _table_files(self.root, table.table)]


class _ParquetPageSource(PageSource):
    def __init__(self, split: Split, columns: Sequence[str], pushdown):
        self.path = split.info[0]
        self.columns = list(columns)
        self.pushdown = pushdown

    def batches(self):
        yield from _reader(self.path).batches(self.columns, self.pushdown)


class ParquetConnector(Connector):
    name = "parquet"

    def __init__(self, root: str):
        self.root = root
        self._metadata = _Metadata(root)
        self._splits = _SplitManager(root)

    @property
    def metadata(self) -> ConnectorMetadata:
        return self._metadata

    @property
    def split_manager(self) -> ConnectorSplitManager:
        return self._splits

    def page_source(self, split: Split, columns: Sequence[str],
                    pushdown=None, rows_per_batch: int = 1 << 17
                    ) -> PageSource:
        return _ParquetPageSource(split, columns, pushdown)

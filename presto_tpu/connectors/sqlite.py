"""SQLite connector: a real external data store behind the SPI.

The proof that the connector SPI carries a foreign store end to end —
metadata discovery, rowid-range splits, filter pushdown compiled into the
foreign system's own SQL, and a write surface for CTAS/INSERT. Conceptual
parity with the reference's JDBC connector framework (reference
presto-base-jdbc/src/main/java/io/prestosql/plugin/jdbc/JdbcClient.java:1,
JdbcMetadata.java's TupleDomain pushdown, JdbcRecordSetProvider.java:1),
re-shaped for this engine: the pushdown language is the planner's
(column, lo, hi) bound tuples (our TupleDomain analogue), rendered here
as WHERE conjuncts so filtering happens inside SQLite before any rows
cross into device memory.

Loaded from etc/catalog/*.properties via plugin.py with
``connector.name=sqlite`` + ``sqlite.path=/path/db.sqlite``.
"""
from __future__ import annotations

import sqlite3
import threading
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from .. import types as T
from ..batch import Batch, Schema, bucket_capacity
from .spi import (
    ColumnStats, Connector, ConnectorMetadata, ConnectorSplitManager,
    PageSource, Split, TableHandle, TableStats, notify_data_change,
)

#: SQLite declared-type affinity -> engine type (reference
#: base-jdbc StandardColumnMappings.java role)
_AFFINITY = (
    (("INT",), T.BIGINT),
    (("CHAR", "CLOB", "TEXT"), T.VARCHAR),
    (("REAL", "FLOA", "DOUB"), T.DOUBLE),
    (("BOOL",), T.BOOLEAN),
    (("DATE",), T.DATE),
)


def _affinity_type(decl: str) -> T.Type:
    d = (decl or "").upper()
    for keys, typ in _AFFINITY:
        if any(k in d for k in keys):
            return typ
    # SQLite NUMERIC affinity / untyped: floats round-trip exactly
    return T.DOUBLE


def _q(ident: str) -> str:
    """Quote an identifier for foreign SQL, doubling embedded double
    quotes — identifiers can't be parameterized, so this is the one
    escaping path for table/column names in every statement this
    connector renders (page source, stats, DDL, insert)."""
    return '"' + str(ident).replace('"', '""') + '"'


class _Meta(ConnectorMetadata):
    def __init__(self, conn: "SqliteConnector"):
        self._conn = conn

    def list_tables(self, schema: Optional[str] = None) -> List[str]:
        cur = self._conn._db().execute(
            "select name from sqlite_master where type in ('table','view')"
            " and name not like 'sqlite_%' order by name")
        return [r[0] for r in cur.fetchall()]

    def table_schema(self, table: TableHandle) -> Schema:
        return self._conn._schema(table.table)

    def table_stats(self, table: TableHandle) -> TableStats:
        return self._conn._stats(table.table)


class _Splits(ConnectorSplitManager):
    def __init__(self, conn: "SqliteConnector"):
        self._conn = conn

    def splits(self, table: TableHandle, desired: int = 1) -> List[Split]:
        """Rowid-range splits (the JDBC connector's analogue of
        partitioned reads; SQLite exposes a dense-ish integer rowid)."""
        db = self._conn._db()
        row = db.execute(
            f'select min(rowid), max(rowid) from {_q(table.table)}'
        ).fetchone()
        lo, hi = row if row and row[0] is not None else (None, None)
        if lo is None:
            return [Split(table, info=(None, None))]
        desired = max(1, desired)
        span = hi - lo + 1
        per = -(-span // desired)
        out = []
        for s in range(lo, hi + 1, per):
            out.append(Split(table, info=(s, min(s + per - 1, hi))))
        return out


class _SqlitePageSource(PageSource):
    def __init__(self, conn, table: str, columns: Sequence[str],
                 schema: Schema, rowid_lo, rowid_hi, pushdown,
                 rows_per_batch: int):
        self._conn = conn
        self._table = table
        self._columns = list(columns)
        self._schema = schema
        self._rows_per_batch = rows_per_batch
        sel = ", ".join(_q(c) for c in self._columns) or "1"
        where, params = [], []
        if rowid_lo is not None:
            where.append("rowid between ? and ?")
            params += [rowid_lo, rowid_hi]
        # TupleDomain-equivalent pushdown rendered as foreign-SQL
        # conjuncts: filtering happens INSIDE sqlite (reference
        # JdbcMetadata.applyFilter -> QueryBuilder WHERE clause). String
        # bounds arrive as dictionary codes — untranslatable, skipped
        # (the engine's own filter still applies; pushdown is advisory).
        for name, lo, hi in (pushdown or ()):
            if name not in self._columns \
                    or self._schema.type_of(name).is_string:
                continue
            if lo is not None:
                where.append(f'{_q(name)} >= ?')
                params.append(lo)
            if hi is not None:
                where.append(f'{_q(name)} <= ?')
                params.append(hi)
        sql = f'select {sel} from {_q(table)}'
        if where:
            sql += " where " + " and ".join(where)
        self._sql, self._params = sql, params

    def batches(self) -> Iterator[Batch]:
        cur = self._conn._db().execute(self._sql, self._params)
        types = [self._schema.type_of(c) for c in self._columns]
        while True:
            rows = cur.fetchmany(self._rows_per_batch)
            if not rows:
                return
            yield self._to_batch(rows, types)

    def _to_batch(self, rows, types) -> Batch:
        n = len(rows)
        arrays, valids, dicts = [], [], []
        for i, t in enumerate(types):
            col = [r[i] for r in rows]
            valid = np.asarray([v is not None for v in col])
            if t.is_string:
                vocab: List[str] = []
                index: Dict[str, int] = {}
                codes = np.zeros(n, dtype=np.int32)
                for j, v in enumerate(col):
                    if v is None:
                        continue
                    s = str(v)
                    k = index.get(s)
                    if k is None:
                        k = index[s] = len(vocab)
                        vocab.append(s)
                    codes[j] = k
                arrays.append(codes)
                dicts.append(tuple(vocab))
            else:
                dt = np.dtype(t.storage_dtype)
                vals = np.zeros(n, dtype=dt)
                for j, v in enumerate(col):
                    if v is not None:
                        vals[j] = v
                arrays.append(vals)
                dicts.append(None)
        schema = Schema([(c, t) for c, t in zip(self._columns, types)])
        return Batch.from_arrays(schema, arrays,
                                 validity=[np.asarray(
                                     [r[i] is not None for r in rows])
                                     for i in range(len(types))],
                                 dictionaries=dicts, num_rows=n)


class SqliteConnector(Connector):
    """One SQLite database file as a catalog."""

    def __init__(self, path: str):
        self.name = "sqlite"
        self.path = path
        self._local = threading.local()
        self._meta = _Meta(self)
        self._split_mgr = _Splits(self)
        self._schema_cache: Dict[str, Schema] = {}
        # TableStats are full-scan-priced (count(*) + per-column
        # min/max/distinct); cache per table, invalidated by this
        # connector's own writes (ADVICE r5 — planning must not re-scan
        # sqlite per optimizer estimate)
        self._stats_cache: Dict[str, TableStats] = {}
        # monotonic per-table data versions (scan-cache key surface),
        # bumped by the SAME writes that invalidate the stats cache
        self._vseq = 0
        self._versions: Dict[str, int] = {}

    def data_version(self, table: str):
        # the write counter covers THIS connector's writes; sqlite's
        # own PRAGMA data_version covers commits from OTHER connections
        # to the same database file (it bumps per foreign commit seen
        # by this connection), so externally-modified tables miss
        # instead of serving stale cached splits
        try:
            ext = self._db().execute("pragma data_version").fetchone()[0]
        except sqlite3.Error:
            ext = None
        return (self._versions.get(table, 0), ext)

    def _db(self) -> sqlite3.Connection:
        db = getattr(self._local, "db", None)
        if db is None:
            db = self._local.db = sqlite3.connect(self.path)
        return db

    @property
    def metadata(self) -> ConnectorMetadata:
        return self._meta

    @property
    def split_manager(self) -> ConnectorSplitManager:
        return self._split_mgr

    def _schema(self, table: str) -> Schema:
        got = self._schema_cache.get(table)
        if got is None:
            info = self._db().execute(
                f'pragma table_info({_q(table)})').fetchall()
            if not info:
                raise KeyError(f"sqlite table {table!r} not found")
            got = Schema([(r[1], _affinity_type(r[2])) for r in info])
            self._schema_cache[table] = got
        return got

    def _invalidate(self, table: str) -> None:
        self._schema_cache.pop(table, None)
        self._note_write(table)

    def _note_write(self, table: str) -> None:
        """One write happened: drop the priced stats, bump the data
        version, and notify engine-side caches (the device scan cache
        invalidates through this same path)."""
        self._stats_cache.pop(table, None)
        self._vseq += 1
        self._versions[table] = self._vseq
        notify_data_change(self, table)

    def _stats(self, table: str) -> TableStats:
        got = self._stats_cache.get(table)
        if got is not None:
            return got
        db = self._db()
        try:
            n = db.execute(
                f'select count(*) from {_q(table)}').fetchone()[0]
        except sqlite3.Error:
            return TableStats()
        cols: Dict[str, ColumnStats] = {}
        schema = self._schema(table)
        for f in schema.fields:
            if f.type.is_string:
                continue
            lo, hi, d = db.execute(
                f'select min({_q(f.name)}), max({_q(f.name)}),'
                f' count(distinct {_q(f.name)}) from {_q(table)}'
            ).fetchone()
            cols[f.name] = ColumnStats(distinct_count=float(d),
                                       min_value=lo, max_value=hi)
        got = TableStats(row_count=float(n), columns=cols)
        self._stats_cache[table] = got
        return got

    def page_source(self, split: Split, columns: Sequence[str],
                    pushdown=None, rows_per_batch: int = 1 << 17
                    ) -> PageSource:
        table = split.table.table
        lo, hi = split.info
        return _SqlitePageSource(self, table, columns,
                                 self._schema(table), lo, hi, pushdown,
                                 rows_per_batch)

    # -- write surface (CTAS / INSERT ... SELECT) ----------------------------
    @property
    def tables(self) -> List[str]:
        return self._meta.list_tables()

    def create_table(self, name: str, schema: Schema,
                     if_not_exists: bool = False) -> None:
        decl = {T.BIGINT: "INTEGER", T.INTEGER: "INTEGER",
                T.BOOLEAN: "BOOLEAN", T.DOUBLE: "REAL", T.DATE: "DATE"}
        cols = ", ".join(
            f'{_q(f.name)} '
            + ("TEXT" if f.type.is_string
               else decl.get(f.type, "REAL"))
            for f in schema.fields)
        ine = "if not exists " if if_not_exists else ""
        self._db().execute(f'create table {ine}{_q(name)} ({cols})')
        self._db().commit()
        self._invalidate(name)

    def append(self, name: str, batch: Batch) -> int:
        import datetime
        import decimal
        rows = batch.to_pylist()
        if not rows:
            return 0

        def conv(v):
            # DATE stores as epoch days (matches the read path's DATE
            # affinity -> int32 mapping); decimals as REAL; numpy scalars
            # unwrap (sqlite3 would otherwise BLOB them via the buffer
            # protocol)
            if hasattr(v, "item"):
                v = v.item()
            if isinstance(v, datetime.date):
                return (v - datetime.date(1970, 1, 1)).days
            if isinstance(v, decimal.Decimal):
                return float(v)
            if isinstance(v, bool):
                return int(v)
            return v

        ph = ", ".join("?" for _ in batch.schema.fields)
        self._db().executemany(
            f'insert into {_q(name)} values ({ph})',
            [tuple(conv(v) for v in r) for r in rows])
        self._db().commit()
        self._note_write(name)
        return len(rows)

    def drop_table(self, name: str, if_exists: bool = False) -> None:
        if not if_exists and name not in self.tables:
            raise KeyError(f"sqlite table {name!r} not found")
        self._db().execute(f'drop table if exists {_q(name)}')
        self._db().commit()
        self._invalidate(name)


def connector_factory(props: Dict[str, str]) -> SqliteConnector:
    """Plugin entry (plugin.py ConnectorFactory contract): etc catalog
    properties -> connector instance."""
    path = props.get("sqlite.path") or props.get("path")
    if not path:
        raise ValueError("sqlite catalog needs sqlite.path=<db file>")
    return SqliteConnector(path)

"""Shared base for file-format directory connectors (ORC, Parquet).

The minimal shape of the reference's Hive connector (reference
presto-hive/.../HiveMetadata.java, HivePageSourceProvider.java:58,85
dispatching each split to a format page source;
BackgroundHiveSplitLoader.java:262 listing partitions/files into splits):
schema = directory, table = subdirectory (or a single ``.<ext>`` file),
one split per file, footer statistics drive pruning.

Hive-style partitioning: a table directory may contain nested
``key=value`` subdirectories; the keys become trailing table columns
whose constant values attach per split, and scan pushdown bounds prune
whole partitions before any file IO (reference
HivePartitionManager.java partition pruning). A ``CREATE TABLE ... WITH
(partitioned_by = ARRAY['k'])`` write routes rows into those directories
(reference HiveMetadata.finishInsert + HivePageSink partition routing).

Concrete connectors supply (extension, reader factory, writer hook);
readers are cached by (path, mtime) since planning asks for schema/stats
repeatedly and footers are ranged reads anyway.
"""
from __future__ import annotations

import os
import threading
import uuid
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import types as T
from ..batch import Batch, Column, Schema
from .spi import (
    Connector, ConnectorMetadata, ConnectorSplitManager, PageSource, Split,
    TableHandle, TableStats, notify_data_change,
)


def _parse_partition_value(raw: str):
    """Hive path convention: values are strings in the path; int-looking
    values are served as BIGINT (the common date_sk-style layout)."""
    try:
        return int(raw), T.BIGINT
    except ValueError:
        return raw, T.VARCHAR


class _EmptySource(PageSource):
    def batches(self):
        return iter(())


class _PartitionedSource(PageSource):
    """Wraps a file page source, appending constant partition columns and
    re-projecting to the requested column order."""

    def __init__(self, inner: PageSource, columns: Sequence[str],
                 part_fields, part_values):
        self.inner = inner
        self.columns = list(columns)
        self.part_fields = part_fields        # [(name, type)]
        self.part_values = part_values        # parallel python values

    def batches(self):
        import jax.numpy as jnp
        for b in self.inner.batches():
            by_name = dict(zip(b.schema.names,
                               zip(b.columns, b.schema.types)))
            for (name, t), v in zip(self.part_fields, self.part_values):
                if t.is_string:
                    col = Column(t, jnp.zeros(b.capacity, dtype=jnp.int32),
                                 b.row_mask, (str(v),))
                else:
                    col = Column(t, jnp.full(b.capacity, t.to_storage(v),
                                             dtype=t.storage_dtype),
                                 b.row_mask, None)
                by_name[name] = (col, t)
            cols = [by_name[c][0] for c in self.columns]
            fields = [(c, by_name[c][1]) for c in self.columns]
            yield Batch(Schema(fields), cols, b.row_mask)

    def close(self):
        self.inner.close()


class FileConnectorBase(Connector):
    """Directory-of-files connector parameterized by format."""

    #: file extension including the dot, e.g. ".orc"
    extension: str = ""

    def __init__(self, root: str):
        self.root = root
        self._metadata = _Metadata(self)
        self._splits = _SplitManager(self)
        self._readers: "OrderedDict[Tuple[str, float], object]" = \
            OrderedDict()
        self._write_lock = threading.Lock()
        self._declared_parts: Dict[str, List[str]] = {}
        #: per-table partition-field cache: page_source runs once per
        #: split and must not re-walk the directory tree per file
        self._pfields_cache: Dict[str, List[Tuple[str, T.Type]]] = {}
        # per-table data versions for the device scan cache: a counter
        # bumped on this connector's OWN writes, combined with the
        # table's (file, mtime) fingerprint so files rewritten behind
        # the connector's back change the version too — the same
        # externally-visible contract as the (path, mtime)-keyed reader
        # cache below
        self._vseq = 0
        self._versions: Dict[str, int] = {}

    def _data_changed(self, name: str) -> None:
        self._vseq += 1
        self._versions[name] = self._vseq
        notify_data_change(self, name)

    def data_version(self, table: str):
        try:
            files = tuple(
                (os.path.relpath(f, self.root), os.path.getmtime(f))
                for f in self.table_files(table))
        except (OSError, KeyError):
            files = ()
        return (self._versions.get(table, 0), files)

    # -- format hooks --------------------------------------------------------
    def open_reader(self, path: str):
        raise NotImplementedError

    def make_page_source(self, path: str, columns: Sequence[str],
                         pushdown) -> PageSource:
        raise NotImplementedError

    def write_file(self, path: str, schema: Schema, batches) -> int:
        """Write one file of this connector's format; return row count."""
        raise NotImplementedError(
            f"catalog {self.name!r} is not writable")

    # -- shared machinery ----------------------------------------------------
    def reader(self, path: str):
        key = (path, os.path.getmtime(path))
        r = self._readers.get(key)
        if r is None:
            r = self._readers[key] = self.open_reader(path)
            while len(self._readers) > 64:
                self._readers.popitem(last=False)
        else:
            self._readers.move_to_end(key)
        return r

    # -- partition discovery -------------------------------------------------
    def partition_keys(self, table: str) -> List[str]:
        """Partition column names, from the first key=value dir chain."""
        path = os.path.join(self.root, table)
        keys: List[str] = []
        while os.path.isdir(path):
            sub = sorted(d for d in os.listdir(path)
                         if "=" in d
                         and os.path.isdir(os.path.join(path, d)))
            if not sub:
                break
            keys.append(sub[0].split("=", 1)[0])
            path = os.path.join(path, sub[0])
        return keys

    def partitioned_files(self, table: str) -> List[Tuple[str, Tuple]]:
        """[(file path, partition value strings)] under hive layout."""
        base = os.path.join(self.root, table)
        ext = self.extension
        if not os.path.isdir(base):
            if os.path.isfile(base + ext):
                return [(base + ext, ())]
            raise KeyError(f"unknown {self.name} table {table!r}")
        out: List[Tuple[str, Tuple]] = []

        def walk(path: str, values: Tuple) -> None:
            for e in sorted(os.listdir(path)):
                full = os.path.join(path, e)
                if os.path.isdir(full) and "=" in e:
                    walk(full, values + (e.split("=", 1)[1],))
                elif e.endswith(ext):
                    out.append((full, values))

        walk(base, ())
        if not out:
            raise KeyError(
                f"unknown {self.name} table {table!r} (empty dir)")
        return out

    def table_files(self, table: str) -> List[str]:
        return [f for f, _ in self.partitioned_files(table)]

    def _partition_fields(self, table: str) -> List[Tuple[str, T.Type]]:
        cached = self._pfields_cache.get(table)
        if cached is not None:
            return cached
        keys = self.partition_keys(table)
        if not keys:
            out: List[Tuple[str, T.Type]] = []
        else:
            _, values = self.partitioned_files(table)[0]
            out = [(k, _parse_partition_value(v)[1])
                   for k, v in zip(keys, values)]
        self._pfields_cache[table] = out
        return out

    @property
    def metadata(self) -> ConnectorMetadata:
        return self._metadata

    @property
    def split_manager(self) -> ConnectorSplitManager:
        return self._splits

    def page_source(self, split: Split, columns: Sequence[str],
                    pushdown=None, rows_per_batch: int = 1 << 17
                    ) -> PageSource:
        path = split.info[0]
        part_values = split.info[1] if len(split.info) > 1 else ()
        pfields = self._partition_fields(split.table.table)
        pnames = [n for n, _ in pfields]
        if pushdown:
            # partition pruning BEFORE any file IO (reference
            # HivePartitionManager prunes partitions from the metastore
            # listing; dynamic-filter bounds land here too)
            for name, lo, hi in pushdown:
                if name not in pnames:
                    continue
                raw = part_values[pnames.index(name)]
                v, _t = _parse_partition_value(raw)
                if not isinstance(v, int):
                    continue
                if (lo is not None and v < lo) or \
                        (hi is not None and v > hi):
                    return _EmptySource()
        file_cols = [c for c in columns if c not in pnames]
        file_pushdown = (tuple(p for p in pushdown if p[0] not in pnames)
                         if pushdown else None)
        inner = self.make_page_source(path, file_cols, file_pushdown)
        if not pnames:
            return inner
        sel = [(f, _parse_partition_value(v)[0])
               for f, v in zip(pfields, part_values) if f[0] in columns]
        return _PartitionedSource(inner, columns,
                                  [f for f, _ in sel], [v for _, v in sel])

    # -- write surface (reference HiveMetadata + HivePageSink) --------------
    @property
    def tables(self) -> Dict[str, None]:
        try:
            return {t: None for t in self._metadata.list_tables()}
        except FileNotFoundError:
            return {}

    def create_table(self, name: str, schema: Schema,
                     if_not_exists: bool = False,
                     partitioned_by: Sequence[str] = ()) -> None:
        path = os.path.join(self.root, name)
        if os.path.isdir(path) or os.path.isfile(path + self.extension):
            if if_not_exists:
                return
            raise ValueError(f"table {name!r} already exists")
        for k in partitioned_by:
            if k not in schema.names:
                raise ValueError(
                    f"partition column {k!r} not in table schema")
        os.makedirs(path)
        self._declared_parts[name] = list(partitioned_by)
        self._pfields_cache.pop(name, None)
        self._data_changed(name)

    def drop_table(self, name: str, if_exists: bool = False) -> None:
        import shutil
        path = os.path.join(self.root, name)
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.isfile(path + self.extension):
            os.remove(path + self.extension)
        elif not if_exists:
            raise KeyError(f"table {name!r} does not exist")
        self._declared_parts.pop(name, None)
        self._pfields_cache.pop(name, None)
        self._data_changed(name)

    def append(self, name: str, batch: Batch) -> int:
        part_keys = self._declared_parts.get(name)
        if part_keys is None:
            part_keys = self.partition_keys(name)
        base = os.path.join(self.root, name)
        if not os.path.isdir(base):
            raise KeyError(f"table {name!r} does not exist")
        # unique per-write file id: sequence numbers from a fresh
        # process would silently clobber files written by an earlier one
        fid = uuid.uuid4().hex[:12]
        self._pfields_cache.pop(name, None)
        if not part_keys:
            path = os.path.join(base, f"part-{fid}{self.extension}")
            n = self.write_file(path, batch.schema, [batch])
            # bump AFTER the file lands: a concurrent scan between the
            # bump and the write would cache pre-write data under the
            # post-write version and serve it forever
            self._data_changed(name)
            return n
        # route rows into key=value directories (HivePageSink role);
        # partition columns move to the path, data columns to the files
        names = list(batch.schema.names)
        part_idx = [names.index(k) for k in part_keys]
        data_idx = [i for i in range(len(names)) if i not in part_idx]
        data_schema = Schema([(names[i], batch.schema.types[i])
                              for i in data_idx])
        mask = np.asarray(batch.row_mask)
        part_cols = []
        for i in part_idx:
            c = batch.columns[i]
            arr = np.asarray(c.data)
            if c.type.is_string:
                vocab = c.dictionary or ()
                part_cols.append(np.asarray(
                    [vocab[v] if 0 <= v < len(vocab) else ""
                     for v in arr.tolist()], dtype=object))
            else:
                part_cols.append(arr)
        n = 0
        live = np.nonzero(mask)[0]
        keys_here = {tuple(pc[r] for pc in part_cols) for r in live}
        import jax.numpy as jnp
        for kv in sorted(keys_here, key=str):
            sel = mask.copy()
            for pc, v in zip(part_cols, kv):
                sel &= pc == v
            sub = Batch(data_schema, [batch.columns[i] for i in data_idx],
                        batch.row_mask & jnp.asarray(sel))
            d = base
            for k, v in zip(part_keys, kv):
                d = os.path.join(d, f"{k}={v}")
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, f"part-{fid}{self.extension}")
            n += self.write_file(path, data_schema, [sub])
        self._data_changed(name)   # after every partition file landed
        return n


class _Metadata(ConnectorMetadata):
    def __init__(self, conn: FileConnectorBase):
        self.conn = conn

    def list_tables(self, schema: Optional[str] = None) -> List[str]:
        out = []
        ext = self.conn.extension
        for entry in sorted(os.listdir(self.conn.root)):
            full = os.path.join(self.conn.root, entry)
            if os.path.isdir(full):
                try:
                    if self.conn.table_files(entry):
                        out.append(entry)
                except KeyError:
                    continue
            elif entry.endswith(ext):
                out.append(entry[:-len(ext)])
        return out

    def table_schema(self, table: TableHandle) -> Schema:
        files = self.conn.table_files(table.table)
        file_schema = self.conn.reader(files[0]).schema
        pfields = self.conn._partition_fields(table.table)
        if not pfields:
            return file_schema
        return Schema(list(zip(file_schema.names, file_schema.types))
                      + pfields)

    def table_stats(self, table: TableHandle) -> TableStats:
        rows = 0.0
        for f in self.conn.table_files(table.table):
            rows += self.conn.reader(f).num_rows
        return TableStats(row_count=rows, columns={}, primary_key=())


class _SplitManager(ConnectorSplitManager):
    def __init__(self, conn: FileConnectorBase):
        self.conn = conn

    def splits(self, table: TableHandle, desired: int = 1) -> List[Split]:
        return [Split(table, (f, values))
                for f, values in self.conn.partitioned_files(table.table)]

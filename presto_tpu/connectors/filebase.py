"""Shared base for file-format directory connectors (ORC, Parquet).

The minimal shape of the reference's Hive connector read path (reference
presto-hive/.../HivePageSourceProvider.java:58,85 dispatching each split
to a format page source; BackgroundHiveSplitLoader.java listing files
into splits): schema = directory, table = subdirectory (or a single
``.<ext>`` file), one split per file, footer statistics drive pruning.
Concrete connectors supply (extension, reader factory); readers are
cached by (path, mtime) since planning asks for schema/stats repeatedly
and footers are ranged reads anyway.
"""
from __future__ import annotations

import os
from collections import OrderedDict
from typing import Callable, List, Optional, Sequence, Tuple

from ..batch import Schema
from .spi import (
    Connector, ConnectorMetadata, ConnectorSplitManager, PageSource, Split,
    TableHandle, TableStats,
)


class FileConnectorBase(Connector):
    """Directory-of-files connector parameterized by format."""

    #: file extension including the dot, e.g. ".orc"
    extension: str = ""

    def __init__(self, root: str):
        self.root = root
        self._metadata = _Metadata(self)
        self._splits = _SplitManager(self)
        self._readers: "OrderedDict[Tuple[str, float], object]" = \
            OrderedDict()

    # -- format hooks --------------------------------------------------------
    def open_reader(self, path: str):
        raise NotImplementedError

    def make_page_source(self, path: str, columns: Sequence[str],
                         pushdown) -> PageSource:
        raise NotImplementedError

    # -- shared machinery ----------------------------------------------------
    def reader(self, path: str):
        key = (path, os.path.getmtime(path))
        r = self._readers.get(key)
        if r is None:
            r = self._readers[key] = self.open_reader(path)
            while len(self._readers) > 64:
                self._readers.popitem(last=False)
        else:
            self._readers.move_to_end(key)
        return r

    def table_files(self, table: str) -> List[str]:
        path = os.path.join(self.root, table)
        ext = self.extension
        if os.path.isdir(path):
            files = sorted(
                os.path.join(path, f) for f in os.listdir(path)
                if f.endswith(ext))
            if not files:
                raise KeyError(
                    f"unknown {self.name} table {table!r} (empty dir)")
            return files
        if os.path.isfile(path + ext):
            return [path + ext]
        raise KeyError(f"unknown {self.name} table {table!r}")

    @property
    def metadata(self) -> ConnectorMetadata:
        return self._metadata

    @property
    def split_manager(self) -> ConnectorSplitManager:
        return self._splits

    def page_source(self, split: Split, columns: Sequence[str],
                    pushdown=None, rows_per_batch: int = 1 << 17
                    ) -> PageSource:
        return self.make_page_source(split.info[0], columns, pushdown)


class _Metadata(ConnectorMetadata):
    def __init__(self, conn: FileConnectorBase):
        self.conn = conn

    def list_tables(self, schema: Optional[str] = None) -> List[str]:
        out = []
        ext = self.conn.extension
        for entry in sorted(os.listdir(self.conn.root)):
            full = os.path.join(self.conn.root, entry)
            if os.path.isdir(full):
                try:
                    if self.conn.table_files(entry):
                        out.append(entry)
                except KeyError:
                    continue
            elif entry.endswith(ext):
                out.append(entry[:-len(ext)])
        return out

    def table_schema(self, table: TableHandle) -> Schema:
        files = self.conn.table_files(table.table)
        return self.conn.reader(files[0]).schema

    def table_stats(self, table: TableHandle) -> TableStats:
        rows = 0.0
        for f in self.conn.table_files(table.table):
            rows += self.conn.reader(f).num_rows
        return TableStats(row_count=rows, columns={}, primary_key=())


class _SplitManager(ConnectorSplitManager):
    def __init__(self, conn: FileConnectorBase):
        self.conn = conn

    def splits(self, table: TableHandle, desired: int = 1) -> List[Split]:
        return [Split(table, (f,))
                for f in self.conn.table_files(table.table)]

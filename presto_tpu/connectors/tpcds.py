"""TPC-DS data-generator connector (star-schema subset).

Conceptual parity with presto-tpcds (reference presto-tpcds/src/main/java/
io/prestosql/plugin/tpcds/TpcdsMetadata.java, TpcdsRecordSetProvider
wrapping the teradata tpcds generators), built with the same TPU-first
design as the TPC-H connector (connectors/tpch.py): every column is a
stateless splitmix64 hash of the row's surrogate key, so any split can
generate any row range referentially consistently and in parallel.

Tables are the star-schema subset the BASELINE q27/q55 configs touch:
``store_sales`` (fact), ``date_dim``, ``item``, ``store``,
``customer_demographics``. Distributions follow the spec's shapes
(demographics are the spec's exact cross-product encoding; date_dim is a
real calendar); exact dsdgen bit-compatibility is NOT a goal —
correctness tests compare against an oracle over this same data.
"""
from __future__ import annotations

import datetime
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from .. import types as T
from ..batch import Batch, Schema
from .spi import (
    ColumnStats, Connector, ConnectorMetadata, ConnectorSplitManager,
    PageSource, Split, TableHandle, TableStats,
)
from .tpch import _U64, _h, _money, _pick, _randint

# date_dim spans 1900-01-01 .. 2100-01-01 (spec); sk = julian day number,
# stored here as days since 1900-01-01 plus the spec's base surrogate
D_BASE_SK = 2415022            # julian day of 1900-01-01 (spec's first sk)
D_DAYS = 73_049                # rows in date_dim (fixed, scale-independent)
_EPOCH_1900 = datetime.date(1900, 1, 1)

# fact sales dates concentrate in 1998-2002 (spec's active window)
SALES_D0 = (datetime.date(1998, 1, 1) - _EPOCH_1900).days
SALES_D1 = (datetime.date(2003, 1, 1) - _EPOCH_1900).days

GENDERS = ("M", "F")
MARITAL = ("M", "S", "D", "W", "U")
EDUCATION = ("Primary", "Secondary", "College", "2 yr Degree",
             "4 yr Degree", "Advanced Degree", "Unknown")
CD_PURCHASE_MAX = 20           # purchase estimate buckets (500,1000,..)
CREDIT_RATING = ("Low Risk", "Good", "High Risk", "Unknown")
N_DEMOGRAPHICS = (len(GENDERS) * len(MARITAL) * len(EDUCATION)
                  * CD_PURCHASE_MAX * len(CREDIT_RATING)
                  * 7 * 7 * 7)   # dep, dep_employed, dep_college counts 0-6

STATES = ("TN", "TN", "TN", "TN", "TN", "TN", "AL", "GA", "KY", "NC",
          "OH", "TX", "VA", "MO", "SC")   # TN-heavy like dsdgen defaults
CATEGORIES = ("Books", "Children", "Electronics", "Home", "Jewelry",
              "Men", "Music", "Shoes", "Sports", "Women")
CITIES = ("Midway", "Fairview", "Oak Grove", "Five Points", "Centerville",
          "Liberty", "Pleasant Hill", "Riverside", "Salem", "Union",
          "Greenville", "Bethel", "Springfield", "Clinton", "Marion")
COUNTIES = ("Williamson County", "Walker County", "Ziebach County",
            "Franklin Parish", "Luce County", "Richland County",
            "Bronx County", "Orange County", "Maverick County",
            "Mobile County")
BUY_POTENTIAL = ("0-500", "501-1000", "1001-5000", "5001-10000",
                 ">10000", "Unknown")
FIRST_NAMES = ("James", "Mary", "John", "Patricia", "Robert", "Jennifer",
               "Michael", "Linda", "William", "Elizabeth", "David",
               "Barbara", "Richard", "Susan", "Joseph", "Jessica")
LAST_NAMES = ("Smith", "Johnson", "Williams", "Brown", "Jones", "Garcia",
              "Miller", "Davis", "Rodriguez", "Martinez", "Hernandez",
              "Lopez", "Gonzalez", "Wilson", "Anderson", "Thomas")
MEAL_TIMES = ("breakfast", "lunch", "dinner", "")


def _rows(table: str, sf: float) -> int:
    if table == "store_sales":
        return int(2_880_000 * sf)
    if table == "date_dim":
        return D_DAYS
    if table == "item":
        return max(1, int(18_000 * max(sf, 1) ** 0.5))
    if table == "store":
        return max(1, int(12 * max(sf, 1) ** 0.5))
    if table == "customer_demographics":
        return 1_920_800     # fixed cross-product (spec)
    if table == "customer":
        return max(1, int(100_000 * max(sf, 1) ** 0.5))
    if table == "customer_address":
        return max(1, int(50_000 * max(sf, 1) ** 0.5))
    if table == "household_demographics":
        return 7_200         # fixed cross-product (spec)
    if table == "promotion":
        return max(1, int(300 * max(sf, 1) ** 0.5))
    if table == "time_dim":
        return 86_400        # one row per second of day (spec)
    if table in EXT_ROWS:
        return EXT_ROWS[table](sf)
    raise KeyError(table)


V = T.VARCHAR
_SCHEMAS: Dict[str, List[Tuple[str, T.Type]]] = {
    "store_sales": [
        ("ss_sold_date_sk", T.BIGINT), ("ss_sold_time_sk", T.BIGINT),
        ("ss_item_sk", T.BIGINT),
        ("ss_customer_sk", T.BIGINT), ("ss_cdemo_sk", T.BIGINT),
        ("ss_hdemo_sk", T.BIGINT), ("ss_addr_sk", T.BIGINT),
        ("ss_store_sk", T.BIGINT), ("ss_promo_sk", T.BIGINT),
        ("ss_ticket_number", T.BIGINT),
        ("ss_quantity", T.INTEGER), ("ss_wholesale_cost", T.DOUBLE),
        ("ss_list_price", T.DOUBLE), ("ss_sales_price", T.DOUBLE),
        ("ss_ext_sales_price", T.DOUBLE), ("ss_coupon_amt", T.DOUBLE),
        ("ss_ext_discount_amt", T.DOUBLE),
        ("ss_ext_wholesale_cost", T.DOUBLE),
        ("ss_ext_list_price", T.DOUBLE), ("ss_ext_tax", T.DOUBLE),
        ("ss_net_paid", T.DOUBLE), ("ss_net_paid_inc_tax", T.DOUBLE),
        ("ss_net_profit", T.DOUBLE),
    ],
    "date_dim": [
        ("d_date_sk", T.BIGINT), ("d_date", T.DATE),
        ("d_year", T.INTEGER), ("d_moy", T.INTEGER),
        ("d_dom", T.INTEGER), ("d_qoy", T.INTEGER),
        ("d_day_name", T.varchar(9)), ("d_dow", T.INTEGER),
        ("d_month_seq", T.INTEGER), ("d_week_seq", T.INTEGER),
        ("d_quarter_name", T.varchar(6)),
    ],
    "item": [
        ("i_item_sk", T.BIGINT), ("i_item_id", T.varchar(16)),
        ("i_brand_id", T.INTEGER), ("i_brand", T.varchar(50)),
        ("i_manufact_id", T.INTEGER), ("i_manager_id", T.INTEGER),
        ("i_category_id", T.INTEGER), ("i_category", T.varchar(50)),
        ("i_current_price", T.DOUBLE), ("i_class_id", T.INTEGER),
        ("i_class", T.varchar(50)), ("i_item_desc", T.varchar(200)),
        ("i_manufact", T.varchar(50)), ("i_color", T.varchar(20)),
        ("i_product_name", T.varchar(50)), ("i_size", T.varchar(20)),
        ("i_units", T.varchar(10)), ("i_wholesale_cost", T.DOUBLE),
    ],
    "store": [
        ("s_store_sk", T.BIGINT), ("s_store_id", T.varchar(16)),
        ("s_store_name", T.varchar(50)), ("s_city", T.varchar(60)),
        ("s_county", T.varchar(30)), ("s_state", T.varchar(2)),
        ("s_zip", T.varchar(10)), ("s_number_employees", T.INTEGER),
        ("s_gmt_offset", T.DOUBLE), ("s_company_id", T.INTEGER),
        ("s_company_name", T.varchar(50)), ("s_market_id", T.INTEGER),
        ("s_street_number", T.varchar(10)),
        ("s_street_name", T.varchar(60)),
        ("s_street_type", T.varchar(15)),
        ("s_suite_number", T.varchar(10)),
    ],
    "customer_demographics": [
        ("cd_demo_sk", T.BIGINT), ("cd_gender", T.varchar(1)),
        ("cd_marital_status", T.varchar(1)),
        ("cd_education_status", T.varchar(20)),
        ("cd_purchase_estimate", T.INTEGER),
        ("cd_credit_rating", T.varchar(10)),
        ("cd_dep_count", T.INTEGER),
        ("cd_dep_employed_count", T.INTEGER),
        ("cd_dep_college_count", T.INTEGER),
    ],
    "customer": [
        ("c_customer_sk", T.BIGINT), ("c_customer_id", T.varchar(16)),
        ("c_current_cdemo_sk", T.BIGINT),
        ("c_current_hdemo_sk", T.BIGINT),
        ("c_current_addr_sk", T.BIGINT),
        ("c_first_name", T.varchar(20)), ("c_last_name", T.varchar(30)),
        ("c_preferred_cust_flag", T.varchar(1)),
        ("c_birth_year", T.INTEGER), ("c_salutation", T.varchar(10)),
        ("c_birth_country", T.varchar(20)), ("c_birth_day", T.INTEGER),
        ("c_birth_month", T.INTEGER),
        ("c_email_address", T.varchar(50)), ("c_login", T.varchar(13)),
        ("c_first_sales_date_sk", T.BIGINT),
        ("c_first_shipto_date_sk", T.BIGINT),
        ("c_last_review_date_sk", T.BIGINT),
    ],
    "customer_address": [
        ("ca_address_sk", T.BIGINT), ("ca_address_id", T.varchar(16)),
        ("ca_city", T.varchar(60)), ("ca_county", T.varchar(30)),
        ("ca_state", T.varchar(2)), ("ca_zip", T.varchar(10)),
        ("ca_country", T.varchar(20)), ("ca_gmt_offset", T.DOUBLE),
        ("ca_location_type", T.varchar(20)),
        ("ca_street_number", T.varchar(10)),
        ("ca_street_name", T.varchar(60)),
        ("ca_street_type", T.varchar(15)),
        ("ca_suite_number", T.varchar(10)),
    ],
    "household_demographics": [
        ("hd_demo_sk", T.BIGINT), ("hd_income_band_sk", T.BIGINT),
        ("hd_buy_potential", T.varchar(15)), ("hd_dep_count", T.INTEGER),
        ("hd_vehicle_count", T.INTEGER),
    ],
    "promotion": [
        ("p_promo_sk", T.BIGINT), ("p_promo_id", T.varchar(16)),
        ("p_channel_dmail", T.varchar(1)),
        ("p_channel_email", T.varchar(1)),
        ("p_channel_event", T.varchar(1)),
        ("p_channel_tv", T.varchar(1)),
    ],
    "time_dim": [
        ("t_time_sk", T.BIGINT), ("t_time", T.INTEGER),
        ("t_hour", T.INTEGER), ("t_minute", T.INTEGER),
        ("t_second", T.INTEGER), ("t_meal_time", T.varchar(20)),
    ],
}

from .tpcds_ext import (  # noqa: E402
    EXT_PRIMARY_KEYS, EXT_ROWS, EXT_SCHEMAS, ExtGen,
)
_SCHEMAS.update(EXT_SCHEMAS)

TABLES = tuple(_SCHEMAS)

_DAY_NAMES = ("Monday", "Tuesday", "Wednesday", "Thursday", "Friday",
              "Saturday", "Sunday")
_BRANDS = tuple(f"Brand#{i}" for i in range(1, 1001))


class _Gen(ExtGen):
    """Column generators keyed by 1-based surrogate row keys."""

    def __init__(self, sf: float):
        self.sf = sf
        self.n_item = _rows("item", sf)
        self.n_store = _rows("store", sf)
        self.n_demo = _rows("customer_demographics", sf)
        self.n_cust = _rows("customer", sf)
        self.n_addr = _rows("customer_address", sf)
        self.n_hdemo = _rows("household_demographics", sf)
        self.n_promo = _rows("promotion", sf)

    # ---- store_sales (fact; key = row id) ----
    def store_sales(self, key: np.ndarray, cols: Sequence[str]):
        out = {}
        qty = 1 + (_h(key, 201) % _U64(100)).astype(np.int64)
        wholesale = _money(key, 202, 1.0, 100.0)
        list_price = np.round(wholesale * (1.0 + (
            _h(key, 203) % _U64(100)).astype(np.float64) / 100.0), 2)
        sales_price = np.round(list_price * (
            (_h(key, 204) % _U64(100)).astype(np.float64) / 100.0), 2)
        ext_sales = np.round(sales_price * qty, 2)
        coupon = np.where(_h(key, 205) % _U64(10) == 0,
                          np.round(ext_sales * 0.1, 2), 0.0)
        for c in cols:
            if c == "ss_sold_date_sk":
                d = SALES_D0 + (_h(key, 200)
                                % _U64(SALES_D1 - SALES_D0)).astype(np.int64)
                out[c] = (D_BASE_SK + d, None)
            elif c == "ss_item_sk":
                out[c] = (1 + (_h(key, 206)
                               % _U64(self.n_item)).astype(np.int64), None)
            elif c == "ss_customer_sk":
                out[c] = (1 + (_h(key, 207)
                               % _U64(self.n_cust)).astype(np.int64), None)
            elif c == "ss_cdemo_sk":
                out[c] = (1 + (_h(key, 208)
                               % _U64(self.n_demo)).astype(np.int64), None)
            elif c == "ss_store_sk":
                out[c] = (1 + (_h(key, 209)
                               % _U64(self.n_store)).astype(np.int64), None)
            elif c == "ss_sold_time_sk":
                out[c] = ((_h(key, 210)
                           % _U64(86_400)).astype(np.int64), None)
            elif c == "ss_hdemo_sk":
                out[c] = (1 + (_h(key, 211)
                               % _U64(self.n_hdemo)).astype(np.int64),
                          None)
            elif c == "ss_addr_sk":
                out[c] = (1 + (_h(key, 212)
                               % _U64(self.n_addr)).astype(np.int64), None)
            elif c == "ss_promo_sk":
                out[c] = (1 + (_h(key, 213)
                               % _U64(self.n_promo)).astype(np.int64),
                          None)
            elif c == "ss_ticket_number":
                out[c] = (1 + (key.astype(np.int64) - 1) // 8, None)
            elif c == "ss_quantity":
                out[c] = (qty.astype(np.int32), None)
            elif c == "ss_wholesale_cost":
                out[c] = (wholesale, None)
            elif c == "ss_list_price":
                out[c] = (list_price, None)
            elif c == "ss_sales_price":
                out[c] = (sales_price, None)
            elif c == "ss_ext_sales_price":
                out[c] = (ext_sales, None)
            elif c == "ss_coupon_amt":
                out[c] = (coupon, None)
            elif c == "ss_net_paid":
                out[c] = (np.round(ext_sales - coupon, 2), None)
            elif c == "ss_net_profit":
                out[c] = (np.round(ext_sales - coupon
                                   - wholesale * qty, 2), None)
            elif c == "ss_ext_discount_amt":
                out[c] = (np.round((list_price - sales_price) * qty, 2),
                          None)
            elif c == "ss_ext_wholesale_cost":
                out[c] = (np.round(wholesale * qty, 2), None)
            elif c == "ss_ext_list_price":
                out[c] = (np.round(list_price * qty, 2), None)
            elif c == "ss_ext_tax":
                out[c] = (np.round(ext_sales * 0.05, 2), None)
            elif c == "ss_net_paid_inc_tax":
                out[c] = (np.round((ext_sales - coupon) * 1.05, 2), None)
            else:
                raise KeyError(c)
        return out

    # ---- date_dim (key = 1..D_DAYS; calendar date = 1900-01-01 + key-1) --
    def date_dim(self, key: np.ndarray, cols: Sequence[str]):
        out = {}
        days = key.astype(np.int64) - 1
        # vectorized calendar via numpy datetime64
        dt = (np.datetime64("1900-01-01") + days.astype("timedelta64[D]"))
        years = dt.astype("datetime64[Y]").astype(np.int64) + 1970
        months = dt.astype("datetime64[M]").astype(np.int64) % 12 + 1
        dom = (dt - dt.astype("datetime64[M]")).astype(np.int64) + 1
        for c in cols:
            if c == "d_date_sk":
                out[c] = (D_BASE_SK + days, None)
            elif c == "d_date":
                # engine DATE storage = days since 1970-01-01
                epoch70 = (np.datetime64("1900-01-01")
                           - np.datetime64("1970-01-01")).astype(np.int64)
                out[c] = ((days + epoch70).astype(np.int32), None)
            elif c == "d_year":
                out[c] = (years.astype(np.int32), None)
            elif c == "d_moy":
                out[c] = (months.astype(np.int32), None)
            elif c == "d_dom":
                out[c] = (dom.astype(np.int32), None)
            elif c == "d_qoy":
                out[c] = (((months - 1) // 3 + 1).astype(np.int32), None)
            elif c == "d_day_name":
                # 1900-01-01 was a Monday
                out[c] = ((days % 7).astype(np.int32), _DAY_NAMES)
            else:
                out[c] = self.ext_column("date_dim", c, key)
        return out

    # ---- item ----
    def item(self, key: np.ndarray, cols: Sequence[str]):
        out = {}
        brand_id = 1 + (_h(key, 221) % _U64(1000)).astype(np.int64)
        cat = (_h(key, 222) % _U64(len(CATEGORIES))).astype(np.int64)
        for c in cols:
            if c == "i_item_sk":
                out[c] = (key.astype(np.int64), None)
            elif c == "i_item_id":
                out[c] = ([f"AAAAAAAA{i:08d}" for i in key], "text")
            elif c == "i_brand_id":
                out[c] = (brand_id.astype(np.int32), None)
            elif c == "i_brand":
                out[c] = ((brand_id - 1).astype(np.int32), _BRANDS)
            elif c == "i_manufact_id":
                out[c] = (_randint(key, 223, 1, 1000).astype(np.int32), None)
            elif c == "i_manager_id":
                out[c] = (_randint(key, 224, 1, 100).astype(np.int32), None)
            elif c == "i_category_id":
                out[c] = ((cat + 1).astype(np.int32), None)
            elif c == "i_category":
                out[c] = (cat.astype(np.int32), CATEGORIES)
            elif c == "i_current_price":
                out[c] = (_money(key, 225, 0.09, 99.99), None)
            else:
                out[c] = self.ext_column("item", c, key)
        return out

    # ---- store ----
    def store(self, key: np.ndarray, cols: Sequence[str]):
        out = {}
        for c in cols:
            if c == "s_store_sk":
                out[c] = (key.astype(np.int64), None)
            elif c == "s_store_id":
                out[c] = ([f"AAAAAAAA{i:08d}" for i in key], "text")
            elif c == "s_store_name":
                names = ("ought", "able", "pri", "ese", "anti", "cally",
                         "ation", "eing", "n st", "bar")
                out[c] = ((_h(key, 231)
                           % _U64(len(names))).astype(np.int32), names)
            elif c == "s_state":
                # STATES holds duplicates (TN-heavy weighting); codes must
                # index the deduped dictionary, not the weighted tuple
                uniq = tuple(dict.fromkeys(STATES))
                remap = np.array([uniq.index(s) for s in STATES],
                                 dtype=np.int32)
                out[c] = (remap[_pick(key, 232, STATES)], uniq)
            elif c == "s_number_employees":
                out[c] = (_randint(key, 233, 200, 300).astype(np.int32),
                          None)
            elif c == "s_city":
                out[c] = ((_h(key, 234)
                           % _U64(len(CITIES))).astype(np.int32), CITIES)
            elif c == "s_county":
                out[c] = ((_h(key, 235)
                           % _U64(len(COUNTIES))).astype(np.int32),
                          COUNTIES)
            elif c == "s_zip":
                zips = 10000 + (_h(key, 236) % _U64(90000)).astype(np.int64)
                out[c] = ([str(z) for z in zips], "text")
            elif c == "s_gmt_offset":
                out[c] = (np.where(_h(key, 237) % _U64(2) == 0,
                                   -5.0, -6.0), None)
            else:
                out[c] = self.ext_column("store", c, key)
        return out

    # ---- customer_demographics (exact cross-product, spec encoding) ----
    def customer_demographics(self, key: np.ndarray, cols: Sequence[str]):
        out = {}
        i = key.astype(np.int64) - 1
        g = i % len(GENDERS)
        i2 = i // len(GENDERS)
        ms = i2 % len(MARITAL)
        i3 = i2 // len(MARITAL)
        ed = i3 % len(EDUCATION)
        i4 = i3 // len(EDUCATION)
        pe = i4 % CD_PURCHASE_MAX
        i5 = i4 // CD_PURCHASE_MAX
        cr = i5 % len(CREDIT_RATING)
        i6 = i5 // len(CREDIT_RATING)
        dep = i6 % 7
        i7 = i6 // 7
        dep_emp = i7 % 7
        dep_col = (i7 // 7) % 7
        for c in cols:
            if c == "cd_demo_sk":
                out[c] = (key.astype(np.int64), None)
            elif c == "cd_gender":
                out[c] = (g.astype(np.int32), GENDERS)
            elif c == "cd_marital_status":
                out[c] = (ms.astype(np.int32), MARITAL)
            elif c == "cd_education_status":
                out[c] = (ed.astype(np.int32), EDUCATION)
            elif c == "cd_purchase_estimate":
                out[c] = (((pe + 1) * 500).astype(np.int32), None)
            elif c == "cd_credit_rating":
                out[c] = (cr.astype(np.int32), CREDIT_RATING)
            elif c == "cd_dep_count":
                out[c] = (dep.astype(np.int32), None)
            elif c == "cd_dep_employed_count":
                out[c] = (dep_emp.astype(np.int32), None)
            elif c == "cd_dep_college_count":
                out[c] = (dep_col.astype(np.int32), None)
            else:
                raise KeyError(c)
        return out

    # ---- customer ----
    def customer(self, key: np.ndarray, cols: Sequence[str]):
        out = {}
        for c in cols:
            if c == "c_customer_sk":
                out[c] = (key.astype(np.int64), None)
            elif c == "c_customer_id":
                out[c] = ([f"AAAAAAAA{i:08d}" for i in key], "text")
            elif c == "c_current_cdemo_sk":
                out[c] = (1 + (_h(key, 241)
                               % _U64(self.n_demo)).astype(np.int64), None)
            elif c == "c_current_hdemo_sk":
                out[c] = (1 + (_h(key, 242)
                               % _U64(self.n_hdemo)).astype(np.int64),
                          None)
            elif c == "c_current_addr_sk":
                out[c] = (1 + (_h(key, 243)
                               % _U64(self.n_addr)).astype(np.int64), None)
            elif c == "c_first_name":
                out[c] = ((_h(key, 244)
                           % _U64(len(FIRST_NAMES))).astype(np.int32),
                          FIRST_NAMES)
            elif c == "c_last_name":
                out[c] = ((_h(key, 245)
                           % _U64(len(LAST_NAMES))).astype(np.int32),
                          LAST_NAMES)
            elif c == "c_preferred_cust_flag":
                out[c] = ((_h(key, 246) % _U64(2)).astype(np.int32),
                          ("N", "Y"))
            elif c == "c_birth_year":
                out[c] = (_randint(key, 247, 1924, 1992).astype(np.int32),
                          None)
            else:
                out[c] = self.ext_column("customer", c, key)
        return out

    # ---- customer_address ----
    def customer_address(self, key: np.ndarray, cols: Sequence[str]):
        out = {}
        for c in cols:
            if c == "ca_address_sk":
                out[c] = (key.astype(np.int64), None)
            elif c == "ca_address_id":
                out[c] = ([f"AAAAAAAA{i:08d}" for i in key], "text")
            elif c == "ca_city":
                out[c] = ((_h(key, 251)
                           % _U64(len(CITIES))).astype(np.int32), CITIES)
            elif c == "ca_county":
                out[c] = ((_h(key, 252)
                           % _U64(len(COUNTIES))).astype(np.int32),
                          COUNTIES)
            elif c == "ca_state":
                uniq = tuple(dict.fromkeys(STATES))
                out[c] = ((_h(key, 253)
                           % _U64(len(uniq))).astype(np.int32), uniq)
            elif c == "ca_zip":
                zips = 10000 + (_h(key, 254) % _U64(90000)).astype(np.int64)
                out[c] = ([str(z) for z in zips], "text")
            elif c == "ca_country":
                out[c] = (np.zeros(len(key), dtype=np.int32),
                          ("United States",))
            elif c == "ca_gmt_offset":
                out[c] = (np.where(_h(key, 255) % _U64(2) == 0,
                                   -5.0, -6.0), None)
            else:
                out[c] = self.ext_column("customer_address", c, key)
        return out

    # ---- household_demographics (cross-product, spec encoding) ----
    def household_demographics(self, key: np.ndarray, cols: Sequence[str]):
        out = {}
        i = key.astype(np.int64) - 1
        inc = i % 20
        i2 = i // 20
        bp = i2 % len(BUY_POTENTIAL)
        i3 = i2 // len(BUY_POTENTIAL)
        dep = i3 % 10
        veh = (i3 // 10) % 6
        for c in cols:
            if c == "hd_demo_sk":
                out[c] = (key.astype(np.int64), None)
            elif c == "hd_income_band_sk":
                out[c] = ((inc + 1).astype(np.int64), None)
            elif c == "hd_buy_potential":
                out[c] = (bp.astype(np.int32), BUY_POTENTIAL)
            elif c == "hd_dep_count":
                out[c] = (dep.astype(np.int32), None)
            elif c == "hd_vehicle_count":
                out[c] = ((veh - 1).astype(np.int32), None)  # -1..4 (spec)
            else:
                raise KeyError(c)
        return out

    # ---- promotion ----
    def promotion(self, key: np.ndarray, cols: Sequence[str]):
        out = {}
        yn = ("N", "Y")
        for c in cols:
            if c == "p_promo_sk":
                out[c] = (key.astype(np.int64), None)
            elif c == "p_promo_id":
                out[c] = ([f"AAAAAAAA{i:08d}" for i in key], "text")
            elif c == "p_channel_dmail":
                out[c] = ((_h(key, 261) % _U64(2)).astype(np.int32), yn)
            elif c == "p_channel_email":
                out[c] = ((_h(key, 262) % _U64(10) == 0)
                          .astype(np.int32), yn)
            elif c == "p_channel_event":
                out[c] = ((_h(key, 263) % _U64(10) == 0)
                          .astype(np.int32), yn)
            elif c == "p_channel_tv":
                out[c] = ((_h(key, 264) % _U64(2)).astype(np.int32), yn)
            else:
                raise KeyError(c)
        return out

    # ---- time_dim (key = 1..86400; second of day = key - 1) ----
    def time_dim(self, key: np.ndarray, cols: Sequence[str]):
        out = {}
        sec = key.astype(np.int64) - 1
        hour = sec // 3600
        for c in cols:
            if c == "t_time_sk":
                out[c] = (sec, None)          # spec: sk == second of day
            elif c == "t_time":
                out[c] = (sec.astype(np.int32), None)
            elif c == "t_hour":
                out[c] = (hour.astype(np.int32), None)
            elif c == "t_minute":
                out[c] = (((sec // 60) % 60).astype(np.int32), None)
            elif c == "t_second":
                out[c] = ((sec % 60).astype(np.int32), None)
            elif c == "t_meal_time":
                mt = np.full(len(key), 3, dtype=np.int32)
                mt = np.where((hour >= 6) & (hour <= 9), 0, mt)
                mt = np.where((hour >= 11) & (hour <= 13), 1, mt)
                mt = np.where((hour >= 17) & (hour <= 20), 2, mt)
                out[c] = (mt, MEAL_TIMES)
            else:
                raise KeyError(c)
        return out


def tpcds_schema(table: str) -> Schema:
    return Schema(_SCHEMAS[table])


class TpcdsPageSource(PageSource):
    def __init__(self, gen: _Gen, split: Split, columns: Sequence[str],
                 rows_per_batch: int):
        self.gen = gen
        self.split = split
        self.columns = list(columns)
        self.rows_per_batch = rows_per_batch

    def host_chunks(self):
        """(schema, generated column dict, n) per chunk, host-side only."""
        table = self.split.table.table
        schema = tpcds_schema(table)
        start, end = self.split.info
        genfn = getattr(self.gen, table)
        for a in range(start, end, self.rows_per_batch):
            b = min(a + self.rows_per_batch, end)
            keys = np.arange(a, b, dtype=np.int64)
            yield schema, genfn(keys, self.columns), b - a

    def batches(self) -> Iterator[Batch]:
        from .tpch import _to_batch
        for schema, data, n in self.host_chunks():
            yield _to_batch(schema, self.columns, data, n)


class _Metadata(ConnectorMetadata):
    def __init__(self, sf: float):
        self.sf = sf

    def list_tables(self, schema: Optional[str] = None) -> List[str]:
        return list(TABLES)

    def table_schema(self, table: TableHandle) -> Schema:
        if table.table not in _SCHEMAS:
            raise KeyError(f"unknown tpcds table {table.table!r}")
        return tpcds_schema(table.table)

    _PRIMARY_KEYS = {
        "store_sales": (),           # fact rows are not keyed by one column
        "date_dim": ("d_date_sk",),
        "item": ("i_item_sk",),
        "store": ("s_store_sk",),
        "customer_demographics": ("cd_demo_sk",),
        "customer": ("c_customer_sk",),
        "customer_address": ("ca_address_sk",),
        "household_demographics": ("hd_demo_sk",),
        "promotion": ("p_promo_sk",),
        "time_dim": ("t_time_sk",),
        **EXT_PRIMARY_KEYS,
    }

    def table_stats(self, table: TableHandle) -> TableStats:
        """Row counts plus EXACT per-column min/max and distinct counts
        for the generated key and low-cardinality columns. The
        generators are stateless functions of the surrogate key, so
        these bounds are true by construction (surrogate keys are dense
        1..n; fact foreign keys are uniform over the referenced
        domain) — which is exactly what lets the optimizer treat them
        as HARD bounds for the dense scatter group-by
        (optimizer._attach_group_bounds) and lets the greedy join order
        rank dimensions by real selectivity instead of bare size."""
        t = table.table
        n = float(_rows(t, self.sf))

        import math

        def sk(lo: int, hi: int, d: Optional[float] = None,
               draws: bool = False) -> ColumnStats:
            # ``draws``: the column is n uniform draws from the domain
            # (fact foreign keys), so publish the expected distinct count
            # E[d] = domain * (1 - (1 - 1/domain)^n). Publishing the raw
            # domain size would overstate NDV past the row count at small
            # scale factors and trip the optimizer's near-unique
            # heuristic (_key_unique's 0.999 * rows test) on foreign
            # keys that DO repeat — a silently wrong unique-build join.
            # Non-draw columns (dense surrogate ranges, calendar fields)
            # publish their exact domain cardinality.
            domain = float(d if d is not None else hi - lo + 1)
            est = domain
            if draws and domain > 1:
                est = domain * -math.expm1(n * math.log1p(-1.0 / domain))
            return ColumnStats(distinct_count=min(est, domain, n),
                               min_value=lo, max_value=hi)

        date_lo, date_hi = D_BASE_SK, D_BASE_SK + D_DAYS - 1
        sales_days = SALES_D1 - SALES_D0
        # one thunk per table so a stats call prices ONLY the requested
        # table (planning a 5-table query calls this once per table per
        # optimization pass; building all ten tables' ColumnStats each
        # time was ~10x dead work, and sk()'s draw math uses THIS
        # table's row count, so cross-table entries were wrong anyway)
        per_table: Dict[str, object] = {
            "store_sales": lambda: {
                "ss_sold_date_sk": sk(D_BASE_SK + SALES_D0,
                                      D_BASE_SK + SALES_D1 - 1,
                                      sales_days, draws=True),
                "ss_sold_time_sk": sk(0, 86_399, draws=True),
                "ss_item_sk": sk(1, _rows("item", self.sf), draws=True),
                "ss_customer_sk": sk(1, _rows("customer", self.sf),
                                     draws=True),
                "ss_cdemo_sk": sk(1, _rows("customer_demographics",
                                           self.sf), draws=True),
                "ss_hdemo_sk": sk(1, _rows("household_demographics",
                                           self.sf), draws=True),
                "ss_addr_sk": sk(1, _rows("customer_address", self.sf),
                                 draws=True),
                "ss_store_sk": sk(1, _rows("store", self.sf), draws=True),
                "ss_promo_sk": sk(1, _rows("promotion", self.sf),
                                  draws=True),
                "ss_quantity": sk(1, 100, draws=True),
            },
            "date_dim": lambda: {
                "d_date_sk": sk(date_lo, date_hi),
                "d_year": sk(1900, 2100, 201),
                "d_moy": sk(1, 12),
                "d_dom": sk(1, 31),
                "d_qoy": sk(1, 4),
            },
            "item": lambda: {
                "i_item_sk": sk(1, _rows("item", self.sf)),
                "i_brand_id": sk(1, 1000, min(1000.0, n)),
                "i_brand": ColumnStats(distinct_count=min(1000.0, n)),
                "i_manufact_id": sk(1, 1000, min(1000.0, n)),
                "i_manager_id": sk(1, 100, min(100.0, n)),
                "i_category_id": sk(1, len(CATEGORIES)),
                "i_category": ColumnStats(
                    distinct_count=float(len(CATEGORIES))),
            },
            "store": lambda: {
                "s_store_sk": sk(1, _rows("store", self.sf)),
                "s_state": ColumnStats(distinct_count=float(
                    len(dict.fromkeys(STATES)))),
            },
            "customer_demographics": lambda: {
                "cd_demo_sk": sk(1, _rows("customer_demographics",
                                          self.sf)),
                "cd_gender": ColumnStats(
                    distinct_count=float(len(GENDERS))),
                "cd_marital_status": ColumnStats(
                    distinct_count=float(len(MARITAL))),
                "cd_education_status": ColumnStats(
                    distinct_count=float(len(EDUCATION))),
                "cd_purchase_estimate": sk(500, 500 * CD_PURCHASE_MAX,
                                           CD_PURCHASE_MAX),
                "cd_credit_rating": ColumnStats(
                    distinct_count=float(len(CREDIT_RATING))),
                "cd_dep_count": sk(0, 6),
            },
            "customer": lambda: {
                "c_customer_sk": sk(1, _rows("customer", self.sf)),
                "c_current_cdemo_sk": sk(1, _rows(
                    "customer_demographics", self.sf), draws=True),
                "c_current_addr_sk": sk(1, _rows("customer_address",
                                                 self.sf), draws=True),
            },
            "customer_address": lambda: {
                "ca_address_sk": sk(1, _rows("customer_address",
                                             self.sf)),
            },
            "household_demographics": lambda: {
                "hd_demo_sk": sk(1, _rows("household_demographics",
                                          self.sf)),
            },
            "promotion": lambda: {
                "p_promo_sk": sk(1, _rows("promotion", self.sf)),
            },
            "time_dim": lambda: {
                "t_time_sk": sk(0, 86_399),
            },
        }
        thunk = per_table.get(t)
        cols: Dict[str, ColumnStats] = dict(thunk()) if thunk else {}
        schema_cols = {c for c, _ in _SCHEMAS.get(t, ())}
        cols = {c: s for c, s in cols.items() if c in schema_cols}
        for pk in self._PRIMARY_KEYS.get(t, ()):
            if pk not in cols:
                cols[pk] = ColumnStats(distinct_count=n)
        return TableStats(row_count=n, columns=cols,
                          primary_key=self._PRIMARY_KEYS.get(t, ()))


class _SplitManager(ConnectorSplitManager):
    def __init__(self, sf: float):
        self.sf = sf

    def splits(self, table: TableHandle, desired: int = 1) -> List[Split]:
        n = _rows(table.table, self.sf)
        desired = max(1, min(desired, n))
        bounds = np.linspace(1, n + 1, desired + 1, dtype=np.int64)
        return [
            Split(table, (int(bounds[i]), int(bounds[i + 1])))
            for i in range(desired)
            if bounds[i] < bounds[i + 1]
        ]


class TpcdsConnector(Connector):
    name = "tpcds"

    def __init__(self, sf: float = 0.01):
        self.sf = sf
        self._metadata = _Metadata(sf)
        self._splits = _SplitManager(sf)
        self._gen = _Gen(sf)

    def data_version(self, table: str):
        # stateless generator: any split regenerates identically for the
        # connector's whole lifetime, so the device scan cache may hold it
        return 0

    @property
    def metadata(self) -> ConnectorMetadata:
        return self._metadata

    @property
    def split_manager(self) -> ConnectorSplitManager:
        return self._splits

    def page_source(self, split: Split, columns: Sequence[str],
                    pushdown=None, rows_per_batch: int = 1 << 17
                    ) -> PageSource:
        return TpcdsPageSource(self._gen, split, columns, rows_per_batch)

"""System connector: engine metadata as queryable tables.

The role of the reference's system/information_schema connectors
(reference presto-main/.../connector/system/ — system.runtime.{nodes,
queries} tables — and connector/informationschema/
InformationSchemaMetadata.java): catalogs, tables, columns, the node
list, and the query log are ordinary tables served from live engine
state, so observability rides the same SQL surface as data.

Tables (schema "runtime"/"information_schema" flattened into one
namespace like the rest of the engine's two-level names):

- ``catalogs``  (catalog_name)
- ``tables``    (table_catalog, table_name)
- ``columns``   (table_catalog, table_name, column_name, ordinal,
                 data_type)
- ``queries``   (query_id, state, query, elapsed_ms, user, error,
                 create_time) — the runner's log (reference
                 system.runtime.queries)
- ``tasks``     (task_id, query_id, stage_id, task_partition, node_id,
                 state, elapsed_ms) — worker tasks from the process-wide
                 obs registry (reference system.runtime.tasks)
- ``metrics``   (name, kind, value) — the obs metrics registry
                 (the reference's JMX connector role: engine metrics as
                 a SQL table)
- ``nodes``     (node_id, coordinator, state)

These double as the ``system.runtime.*`` names: the engine flattens
schemas, so ``system.runtime.queries`` and ``system.default.queries``
are the same table.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, Optional, Sequence

from .. import types as T
from ..batch import Batch, Schema
from .spi import (
    Connector, ConnectorMetadata, ConnectorSplitManager, PageSource,
    Split, TableHandle, TableStats,
)

V = T.VARCHAR

_SCHEMAS: Dict[str, List] = {
    "catalogs": [("catalog_name", V)],
    "tables": [("table_catalog", V), ("table_name", V)],
    "columns": [("table_catalog", V), ("table_name", V),
                ("column_name", V), ("ordinal", T.BIGINT),
                ("data_type", V)],
    "queries": [("query_id", V), ("state", V), ("query", V),
                ("elapsed_ms", T.DOUBLE), ("user", V), ("error", V),
                ("create_time", T.DOUBLE)],
    "tasks": [("task_id", V), ("query_id", V), ("stage_id", T.BIGINT),
              ("task_partition", T.BIGINT), ("node_id", V), ("state", V),
              ("elapsed_ms", T.DOUBLE)],
    "metrics": [("name", V), ("kind", V), ("value", T.DOUBLE)],
    "nodes": [("node_id", V), ("coordinator", T.BOOLEAN), ("state", V)],
}


@dataclasses.dataclass
class QueryLogEntry:
    query_id: str
    state: str
    query: str
    elapsed_ms: float
    user: str = ""
    error: Optional[str] = None
    create_time: float = 0.0


class _Metadata(ConnectorMetadata):
    def __init__(self, conn: "SystemConnector"):
        self.conn = conn

    def list_tables(self, schema: Optional[str] = None) -> List[str]:
        return list(_SCHEMAS)

    def table_schema(self, table: TableHandle) -> Schema:
        if table.table not in _SCHEMAS:
            raise KeyError(f"unknown system table {table.table!r}")
        return Schema(_SCHEMAS[table.table])

    def table_stats(self, table: TableHandle) -> TableStats:
        return TableStats(row_count=100.0, columns={}, primary_key=())


class _SplitManager(ConnectorSplitManager):
    def splits(self, table: TableHandle, desired: int = 1) -> List[Split]:
        return [Split(table, ())]


class _RowsPageSource(PageSource):
    def __init__(self, schema: Schema, columns: Sequence[str],
                 rows: List[tuple]):
        self.schema = schema
        self.columns = list(columns)
        self.rows = rows

    def batches(self) -> Iterator[Batch]:
        idx = [self.schema.names.index(c) for c in self.columns]
        data = {
            self.schema.names[i]: (self.schema.types[i],
                                   [r[i] for r in self.rows])
            for i in idx
        }
        yield Batch.from_pydict(data)


class SystemConnector(Connector):
    name = "system"

    def __init__(self, catalogs, query_log: Optional[List] = None):
        self.catalogs = catalogs        # CatalogManager (live reference)
        self.query_log: List[QueryLogEntry] = (
            query_log if query_log is not None else [])
        self._metadata = _Metadata(self)
        self._splits = _SplitManager()

    @property
    def metadata(self) -> ConnectorMetadata:
        return self._metadata

    @property
    def split_manager(self) -> ConnectorSplitManager:
        return self._splits

    def _rows(self, table: str) -> List[tuple]:
        if table == "catalogs":
            return [(c,) for c in self.catalogs.names()]
        if table == "tables":
            out = []
            for cat in self.catalogs.names():
                conn = self.catalogs.get(cat)
                try:
                    for t in conn.metadata.list_tables():
                        out.append((cat, t))
                except Exception:
                    continue
            return out
        if table == "columns":
            out = []
            for cat in self.catalogs.names():
                conn = self.catalogs.get(cat)
                try:
                    tables = conn.metadata.list_tables()
                except Exception:
                    continue
                for t in tables:
                    try:
                        ts = conn.metadata.table_schema(
                            TableHandle(cat, "default", t))
                    except Exception:
                        continue
                    for i, f in enumerate(ts.fields):
                        out.append((cat, t, f.name, i + 1,
                                    f.type.display()))
            return out
        if table == "queries":
            return [(q.query_id, q.state, q.query, q.elapsed_ms,
                     q.user, q.error, q.create_time)
                    for q in self.query_log]
        if table == "tasks":
            from ..obs.metrics import TASKS
            out = []
            for t in TASKS.snapshot():
                out.append((t.get("task_id", ""),
                            t.get("query_id", ""),
                            int(t.get("stage_id", 0)),
                            int(t.get("partition", 0)),
                            t.get("node_id", ""),
                            t.get("state", ""),
                            float(t.get("elapsed_ms", 0.0))))
            return out
        if table == "metrics":
            from ..obs.metrics import REGISTRY
            return [(m["name"], m["kind"], float(m["value"]))
                    for m in REGISTRY.snapshot()]
        if table == "nodes":
            import jax
            return [(f"device-{d.id}", d.id == 0, "active")
                    for d in jax.devices()]
        raise KeyError(table)

    def page_source(self, split: Split, columns: Sequence[str],
                    pushdown=None, rows_per_batch: int = 1 << 17
                    ) -> PageSource:
        table = split.table.table
        return _RowsPageSource(Schema(_SCHEMAS[table]), columns,
                               self._rows(table))

"""System connector: engine metadata as queryable tables.

The role of the reference's system/information_schema connectors
(reference presto-main/.../connector/system/ — system.runtime.{nodes,
queries} tables — and connector/informationschema/
InformationSchemaMetadata.java): catalogs, tables, columns, the node
list, and the query log are ordinary tables served from live engine
state, so observability rides the same SQL surface as data.

Tables (schema "runtime"/"information_schema" flattened into one
namespace like the rest of the engine's two-level names):

- ``catalogs``  (catalog_name)
- ``tables``    (table_catalog, table_name)
- ``columns``   (table_catalog, table_name, column_name, ordinal,
                 data_type)
- ``queries``   (query_id, state, query, elapsed_ms, user, error,
                 create_time) — the runner's log (reference
                 system.runtime.queries)
- ``tasks``     (task_id, query_id, stage_id, task_partition, node_id,
                 state, elapsed_ms, output_rows, output_bytes,
                 straggler, skew_ratio) — worker tasks from the
                 process-wide obs registry (reference
                 system.runtime.tasks), straggler/skew columns fed by
                 the coordinator's StageMonitor
- ``metrics``   (name, kind, value, sampled_at) — the obs metrics
                 registry (the reference's JMX connector role: engine
                 metrics as a SQL table); histograms flatten to
                 ``.count/.sum/.min/.max/.p50/.p95/.p99`` rows
                 (lifetime quantiles — windowed ones live in
                 ``timeseries``); ``sampled_at`` is one wall-clock
                 read per query so successive snapshots are
                 distinguishable
- ``timeseries`` (name, kind, ts, value) — windowed derived series
                 from the time-series store (obs/timeseries.py):
                 counters as per-interval ``.rate`` points, histograms
                 as per-interval ``.p50/.p95/.p99`` + ``.rate``,
                 gauges raw
- ``slo``       (group, objective, rule, target, threshold_ms, state,
                 since, burn_short, burn_long, budget_remaining) — one
                 row per declared resource-group objective
                 (obs/slo.py)
- ``alerts``    (ts, group, objective, rule, from_state, to_state,
                 burn_short, burn_long) — the SLO alert transition
                 log ring, oldest first
- ``nodes``     (node_id, state, coordinator, heartbeat_age_s,
                 active_tasks, mem_pool_peak_bytes, uri) — the
                 coordinator's node federator view (falls back to local
                 jax devices outside a cluster)
- ``completed_queries`` — the persistent query history
                 (obs/history.py), local and cluster queries
- ``operator_stats``    — per-operator (local) / per-task (cluster)
                 rows/batches/wall from the same history records, plus
                 profiled device_time_s/flops/hbm_bytes
- ``executables``       — per compiled jit entry: compile seconds,
                 invocations, device time, XLA cost/memory analysis
                 (obs/profiler.py)

These double as the ``system.runtime.*`` names: the engine flattens
schemas, so ``system.runtime.queries`` and ``system.default.queries``
are the same table.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, Optional, Sequence

from .. import types as T
from ..batch import Batch, Schema
from .spi import (
    Connector, ConnectorMetadata, ConnectorSplitManager, PageSource,
    Split, TableHandle, TableStats,
)

V = T.VARCHAR

_SCHEMAS: Dict[str, List] = {
    "catalogs": [("catalog_name", V)],
    "tables": [("table_catalog", V), ("table_name", V)],
    "columns": [("table_catalog", V), ("table_name", V),
                ("column_name", V), ("ordinal", T.BIGINT),
                ("data_type", V)],
    "queries": [("query_id", V), ("state", V), ("query", V),
                ("elapsed_ms", T.DOUBLE), ("user", V), ("error", V),
                ("create_time", T.DOUBLE)],
    "tasks": [("task_id", V), ("query_id", V), ("stage_id", T.BIGINT),
              ("task_partition", T.BIGINT), ("node_id", V), ("state", V),
              ("elapsed_ms", T.DOUBLE), ("output_rows", T.BIGINT),
              ("output_bytes", T.BIGINT), ("straggler", T.BOOLEAN),
              ("skew_ratio", T.DOUBLE)],
    "metrics": [("name", V), ("kind", V), ("value", T.DOUBLE),
                ("sampled_at", T.DOUBLE)],
    # windowed derived points from the time-series store
    # (obs/timeseries.py): the SQL face of /v1/metrics/history
    "timeseries": [("name", V), ("kind", V), ("ts", T.DOUBLE),
                   ("value", T.DOUBLE)],
    # one row per declared resource-group SLO objective (obs/slo.py)
    "slo": [("group_path", V), ("objective", V), ("rule", V),
            ("target", T.DOUBLE), ("threshold_ms", T.DOUBLE),
            ("state", V), ("since", T.DOUBLE),
            ("burn_short", T.DOUBLE), ("burn_long", T.DOUBLE),
            ("budget_remaining", T.DOUBLE)],
    # the SLO alert transition log ring, oldest first (obs/slo.py)
    "alerts": [("ts", T.DOUBLE), ("group_path", V), ("objective", V),
               ("rule", V), ("from_state", V), ("to_state", V),
               ("burn_short", T.DOUBLE), ("burn_long", T.DOUBLE)],
    "nodes": [("node_id", V), ("state", V), ("coordinator", T.BOOLEAN),
              ("heartbeat_age_s", T.DOUBLE), ("active_tasks", T.BIGINT),
              ("mem_pool_peak_bytes", T.BIGINT),
              ("hbm_in_use_bytes", T.BIGINT),
              ("hbm_peak_bytes", T.BIGINT), ("uri", V)],
    "completed_queries": [
        ("query_id", V), ("state", V), ("user", V), ("query", V),
        ("error", V), ("error_code", V), ("create_time", T.DOUBLE),
        ("elapsed_ms", T.DOUBLE), ("cpu_ms", T.DOUBLE),
        ("device_sync_ms", T.DOUBLE), ("planning_ms", T.DOUBLE),
        ("peak_memory_bytes", T.BIGINT), ("rows", T.BIGINT),
        ("mode", V), ("plan_summary", V), ("retries", T.BIGINT),
        ("mesh_rounds", T.BIGINT), ("mesh_dominant_bucket", V),
        ("mesh_overhead_ms", T.DOUBLE), ("mesh_buckets", V)],
    # mesh flight recorder (obs/flight.py): one row per exchange round
    # of the most recent mesh-path queries — the SQL-queryable form of
    # the EXPLAIN ANALYZE "Mesh rounds" section (same row shape:
    # flight.ROUND_COLUMNS)
    "mesh_rounds": [
        ("query_id", V), ("round", T.BIGINT), ("stage", T.BIGINT),
        ("kind", V), ("bucket", V), ("t_start", T.DOUBLE),
        ("wall_s", T.DOUBLE), ("rows", T.BIGINT), ("bytes", T.BIGINT),
        ("loads", V), ("blocking", T.BOOLEAN), ("rounds", T.BIGINT)],
    "operator_stats": [
        ("query_id", V), ("operator", V), ("rows", T.BIGINT),
        ("batches", T.BIGINT), ("wall_ms", T.DOUBLE),
        ("bytes", T.BIGINT), ("device_time_s", T.DOUBLE),
        ("flops", T.DOUBLE), ("hbm_bytes", T.BIGINT)],
    # serving plane: every resource group of every live manager in the
    # process — admission state, memory ledger, and the device
    # scheduler's per-group quanta share (serving/groups.group_snapshot;
    # reference system.runtime resource-group MBeans made queryable)
    "resource_groups": [
        ("group", V), ("state", V), ("running", T.BIGINT),
        ("queued", T.BIGINT), ("memory_reserved_bytes", T.BIGINT),
        ("soft_memory_limit_bytes", T.BIGINT),
        ("scheduling_weight", T.BIGINT),
        ("device_seconds", T.DOUBLE), ("device_share", T.DOUBLE),
        ("quanta", T.BIGINT)],
    # per compiled jit entry (ops/jitcache + fused chains): compile
    # cost, invocation/device-time ledger, and lazy XLA introspection
    # (cost_analysis FLOPs/bytes, memory_analysis sizes) — the feed is
    # obs/profiler.EXECUTABLES (reference: the generated-class caches
    # behind PageFunctionCompiler, made queryable)
    "executables": [
        ("name", V), ("static_key", V), ("compiles", T.BIGINT),
        ("compile_seconds", T.DOUBLE), ("invocations", T.BIGINT),
        ("device_time_s", T.DOUBLE), ("flops", T.DOUBLE),
        ("bytes_accessed", T.DOUBLE), ("arg_bytes", T.BIGINT),
        ("output_bytes", T.BIGINT), ("temp_bytes", T.BIGINT),
        ("generated_code_bytes", T.BIGINT)],
}


@dataclasses.dataclass
class QueryLogEntry:
    query_id: str
    state: str
    query: str
    elapsed_ms: float
    user: str = ""
    error: Optional[str] = None
    create_time: float = 0.0


class _Metadata(ConnectorMetadata):
    def __init__(self, conn: "SystemConnector"):
        self.conn = conn

    def list_tables(self, schema: Optional[str] = None) -> List[str]:
        return list(_SCHEMAS)

    def table_schema(self, table: TableHandle) -> Schema:
        if table.table not in _SCHEMAS:
            raise KeyError(f"unknown system table {table.table!r}")
        return Schema(_SCHEMAS[table.table])

    def table_stats(self, table: TableHandle) -> TableStats:
        return TableStats(row_count=100.0, columns={}, primary_key=())


class _SplitManager(ConnectorSplitManager):
    def splits(self, table: TableHandle, desired: int = 1) -> List[Split]:
        return [Split(table, ())]


class _RowsPageSource(PageSource):
    def __init__(self, schema: Schema, columns: Sequence[str],
                 rows: List[tuple]):
        self.schema = schema
        self.columns = list(columns)
        self.rows = rows

    def batches(self) -> Iterator[Batch]:
        idx = [self.schema.names.index(c) for c in self.columns]
        if not idx:
            # count(*) prunes every column; the batch must still carry
            # the row count or the aggregate sees an empty table
            yield Batch.from_arrays(Schema([]), [], num_rows=len(self.rows))
            return
        data = {
            self.schema.names[i]: (self.schema.types[i],
                                   [r[i] for r in self.rows])
            for i in idx
        }
        yield Batch.from_pydict(data)


class SystemConnector(Connector):
    name = "system"

    def __init__(self, catalogs, query_log: Optional[List] = None):
        self.catalogs = catalogs        # CatalogManager (live reference)
        self.query_log: List[QueryLogEntry] = (
            query_log if query_log is not None else [])
        self._metadata = _Metadata(self)
        self._splits = _SplitManager()

    @property
    def metadata(self) -> ConnectorMetadata:
        return self._metadata

    @property
    def split_manager(self) -> ConnectorSplitManager:
        return self._splits

    def _rows(self, table: str) -> List[tuple]:
        if table == "catalogs":
            return [(c,) for c in self.catalogs.names()]
        if table == "tables":
            out = []
            for cat in self.catalogs.names():
                conn = self.catalogs.get(cat)
                try:
                    for t in conn.metadata.list_tables():
                        out.append((cat, t))
                except Exception:
                    continue
            return out
        if table == "columns":
            out = []
            for cat in self.catalogs.names():
                conn = self.catalogs.get(cat)
                try:
                    tables = conn.metadata.list_tables()
                except Exception:
                    continue
                for t in tables:
                    try:
                        ts = conn.metadata.table_schema(
                            TableHandle(cat, "default", t))
                    except Exception:
                        continue
                    for i, f in enumerate(ts.fields):
                        out.append((cat, t, f.name, i + 1,
                                    f.type.display()))
            return out
        if table == "queries":
            return [(q.query_id, q.state, q.query, q.elapsed_ms,
                     q.user, q.error, q.create_time)
                    for q in self.query_log]
        if table == "tasks":
            from ..obs.metrics import TASKS
            out = []
            for t in TASKS.snapshot():
                out.append((t.get("task_id", ""),
                            t.get("query_id", ""),
                            int(t.get("stage_id", 0)),
                            int(t.get("partition", 0)),
                            t.get("node_id", ""),
                            t.get("state", ""),
                            float(t.get("elapsed_ms", 0.0)),
                            int(t.get("output_rows", 0) or 0),
                            int(t.get("output_bytes", 0) or 0),
                            bool(t.get("straggler", False)),
                            float(t.get("skew_ratio", 0.0) or 0.0)))
            return out
        if table == "metrics":
            import time

            from ..obs.metrics import REGISTRY
            from ..obs.timeseries import TIMESERIES
            sampled_at = time.time()   # ONE clock read per query
            out = [(m["name"], m["kind"], float(m["value"]),
                    sampled_at)
                   for m in REGISTRY.snapshot()]
            # windowed quantiles next to the lifetime ``.p95`` rows:
            # ``.p95_5m`` means "over the last 5 minutes" (absent
            # until the sampler has two points in the window)
            out.extend((name, "histogram", value, sampled_at)
                       for name, value in
                       TIMESERIES.window_quantile_rows(300.0))
            return out
        if table == "timeseries":
            from ..obs.timeseries import TIMESERIES
            return TIMESERIES.rows()
        if table == "slo":
            from ..obs.slo import SLO
            return SLO.snapshot_rows()
        if table == "alerts":
            from ..obs.slo import SLO
            return SLO.alert_rows()
        if table == "nodes":
            from ..obs.metrics import NODES
            rows = NODES.snapshot()
            if rows:
                return [(n.get("node_id", ""),
                         n.get("state", ""),
                         bool(n.get("coordinator", False)),
                         float(n.get("heartbeat_age_s", 0.0)),
                         int(n.get("active_tasks", 0) or 0),
                         int(n.get("mem_pool_peak_bytes", 0) or 0),
                         int(n.get("hbm_in_use_bytes", 0) or 0),
                         int(n.get("hbm_peak_bytes", 0) or 0),
                         n.get("uri", ""))
                        for n in rows]
            # no cluster federation running: local device view, with a
            # live HBM sample per device (memory_stats-less backends,
            # e.g. XLA:CPU, report 0)
            import jax

            from ..obs.profiler import sample_hbm
            # key by device id, not by re-deriving sample_hbm's label
            # string — the two recipes must not be able to drift apart
            hbm = {d["device_id"]: d for d in sample_hbm()}
            out = []
            for d in jax.devices():
                h = hbm.get(getattr(d, "id", 0), {})
                out.append((f"device-{d.id}", "active", d.id == 0, 0.0,
                            0, 0, int(h.get("bytes_in_use", 0)),
                            int(h.get("peak_bytes_in_use", 0)), ""))
            return out
        if table == "completed_queries":
            from ..obs.history import HISTORY
            return [(r.get("query_id", ""), r.get("state", ""),
                     r.get("user", ""), r.get("query", ""),
                     r.get("error"), r.get("error_code"),
                     float(r.get("create_time") or 0.0),
                     float(r.get("elapsed_ms") or 0.0),
                     float(r.get("cpu_ms") or 0.0),
                     float(r.get("device_sync_ms") or 0.0),
                     float(r.get("planning_ms") or 0.0),
                     int(r.get("peak_memory_bytes") or 0),
                     int(r.get("rows") or 0),
                     r.get("mode", ""), r.get("plan_summary", ""),
                     int(r.get("retries") or 0),
                     int(r.get("mesh_rounds") or 0),
                     r.get("mesh_dominant_bucket"),
                     float(r.get("mesh_overhead_ms") or 0.0),
                     r.get("mesh_buckets"))
                    for r in HISTORY.snapshot()]
        if table == "mesh_rounds":
            from ..obs.flight import FLIGHTS
            return FLIGHTS.rows()
        if table == "operator_stats":
            from ..obs.history import HISTORY
            out = []
            for r in HISTORY.snapshot():
                for op in r.get("operators") or ():
                    out.append((r.get("query_id", ""),
                                op.get("operator", ""),
                                int(op.get("rows") or 0),
                                int(op.get("batches") or 0),
                                float(op.get("wall_ms") or 0.0),
                                int(op.get("bytes") or 0),
                                float(op.get("device_time_s") or 0.0),
                                float(op.get("flops") or 0.0),
                                int(op.get("hbm_bytes") or 0)))
            return out
        if table == "resource_groups":
            from ..serving.groups import group_snapshot
            return [(g["group"], g["state"], int(g["running"]),
                     int(g["queued"]),
                     int(g["memory_reserved_bytes"] or 0),
                     None if g["soft_memory_limit_bytes"] is None
                     else int(g["soft_memory_limit_bytes"]),
                     int(g["scheduling_weight"]),
                     float(g["device_seconds"]),
                     float(g["device_share"]), int(g["quanta"]))
                    for g in group_snapshot()]
        if table == "executables":
            from ..obs.profiler import EXECUTABLES
            return [(e["name"], e["static_key"], int(e["compiles"]),
                     float(e["compile_seconds"]),
                     int(e["invocations"]),
                     float(e["device_time_s"]),
                     None if e["flops"] is None else float(e["flops"]),
                     None if e["bytes_accessed"] is None
                     else float(e["bytes_accessed"]),
                     e["arg_bytes"], e["output_bytes"], e["temp_bytes"],
                     e["generated_code_bytes"])
                    for e in EXECUTABLES.snapshot(analyze=True)]
        raise KeyError(table)

    def page_source(self, split: Split, columns: Sequence[str],
                    pushdown=None, rows_per_batch: int = 1 << 17
                    ) -> PageSource:
        table = split.table.table
        return _RowsPageSource(Schema(_SCHEMAS[table]), columns,
                               self._rows(table))

"""ORC/Hive-style connector: a table is a directory of ORC files.

The minimal shape of the reference's Hive connector read path (reference
presto-hive/.../HivePageSourceProvider.java:58,85 dispatches each split
to OrcPageSource.java:46; BackgroundHiveSplitLoader.java lists files
into splits): here schema = directory, table = subdirectory (or a single
``.orc`` file), one split per file, and each split decodes stripe-by-
stripe into device batches via formats/orc.py. Min/max predicate
pushdown prunes whole files on their footer statistics — the role of
TupleDomainOrcPredicate.java:77.
"""
from __future__ import annotations

import os
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

from ..batch import Schema
from ..formats.orc import OrcReader
from .spi import (
    ColumnStats, Connector, ConnectorMetadata, ConnectorSplitManager,
    PageSource, Split, TableHandle, TableStats,
)

_READERS: "OrderedDict[Tuple[str, float], OrcReader]" = OrderedDict()


def _reader(path: str) -> OrcReader:
    """Footer-parsed readers cached by (path, mtime): planning asks for
    schema and stats repeatedly, and footers are ranged reads anyway."""
    key = (path, os.path.getmtime(path))
    r = _READERS.get(key)
    if r is None:
        r = _READERS[key] = OrcReader(path)
        while len(_READERS) > 64:
            _READERS.popitem(last=False)
    else:
        _READERS.move_to_end(key)
    return r


def _table_files(root: str, table: str) -> List[str]:
    path = os.path.join(root, table)
    if os.path.isdir(path):
        return sorted(
            os.path.join(path, f) for f in os.listdir(path)
            if f.endswith(".orc"))
    if os.path.isfile(path + ".orc"):
        return [path + ".orc"]
    raise KeyError(f"unknown orc table {table!r}")


class _Metadata(ConnectorMetadata):
    def __init__(self, root: str):
        self.root = root

    def list_tables(self, schema: Optional[str] = None) -> List[str]:
        out = []
        for entry in sorted(os.listdir(self.root)):
            full = os.path.join(self.root, entry)
            if os.path.isdir(full) and _table_files(self.root, entry):
                out.append(entry)
            elif entry.endswith(".orc"):
                out.append(entry[:-4])
        return out

    def table_schema(self, table: TableHandle) -> Schema:
        files = _table_files(self.root, table.table)
        return _reader(files[0]).schema

    def table_stats(self, table: TableHandle) -> TableStats:
        rows = 0.0
        for f in _table_files(self.root, table.table):
            rows += _reader(f).num_rows
        return TableStats(row_count=rows, columns={}, primary_key=())


class _SplitManager(ConnectorSplitManager):
    def __init__(self, root: str):
        self.root = root

    def splits(self, table: TableHandle, desired: int = 1) -> List[Split]:
        return [Split(table, (f,))
                for f in _table_files(self.root, table.table)]


class _OrcPageSource(PageSource):
    def __init__(self, split: Split, columns: Sequence[str],
                 min_max: Optional[Dict[str, Tuple[int, int]]]):
        self.path = split.info[0]
        self.columns = list(columns)
        self.min_max = min_max

    def batches(self):
        yield from _reader(self.path).batches(self.columns, self.min_max)


class OrcConnector(Connector):
    name = "orc"

    def __init__(self, root: str):
        self.root = root
        self._metadata = _Metadata(root)
        self._splits = _SplitManager(root)

    @property
    def metadata(self) -> ConnectorMetadata:
        return self._metadata

    @property
    def split_manager(self) -> ConnectorSplitManager:
        return self._splits

    def page_source(self, split: Split, columns: Sequence[str],
                    pushdown=None, rows_per_batch: int = 1 << 17
                    ) -> PageSource:
        # engine pushdown: ((column, lo, hi), ...) -> {column: (lo, hi)}
        min_max = ({name: (lo, hi) for name, lo, hi in pushdown}
                   if pushdown else None)
        return _OrcPageSource(split, columns, min_max)

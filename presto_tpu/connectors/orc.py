"""ORC/Hive-style connector: a table is a directory of ORC files.

The minimal shape of the reference's Hive connector read path (reference
presto-hive/.../HivePageSourceProvider.java:58,85 dispatches each split
to OrcPageSource.java:46; BackgroundHiveSplitLoader.java lists files
into splits) on the shared directory-connector base: one split per file,
stripe-by-stripe device decode via formats/orc.py, min/max predicate
pushdown pruning whole files on footer statistics — the role of
TupleDomainOrcPredicate.java:77.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

from ..formats.orc import OrcReader
from .filebase import FileConnectorBase
from .spi import PageSource


class _OrcPageSource(PageSource):
    def __init__(self, conn: "OrcConnector", path: str,
                 columns: Sequence[str],
                 min_max: Optional[Dict[str, Tuple[int, int]]]):
        self.conn = conn
        self.path = path
        self.columns = list(columns)
        self.min_max = min_max

    def batches(self):
        yield from self.conn.reader(self.path).batches(
            self.columns, self.min_max)


class OrcConnector(FileConnectorBase):
    name = "orc"
    extension = ".orc"

    def open_reader(self, path: str) -> OrcReader:
        return OrcReader(path)

    def write_file(self, path: str, schema, batches) -> int:
        from ..formats.orc_writer import write_orc
        return write_orc(path, schema, batches)

    def make_page_source(self, path, columns, pushdown) -> PageSource:
        # engine pushdown: ((column, lo, hi), ...) -> {column: (lo, hi)}
        min_max = ({name: (lo, hi) for name, lo, hi in pushdown}
                   if pushdown else None)
        return _OrcPageSource(self, path, columns, min_max)

"""Plugin loading: external modules extend the engine without edits.

The role of the reference's plugin system (reference
presto-spi/.../spi/Plugin.java:33-78 — getConnectorFactories,
getFunctions, getEventListenerFactories — loaded by
server/PluginManager.java:121 loadPlugins/installPlugin:165). Python
replaces the per-plugin classloader isolation with module namespaces:
each plugin is an importable module (or a directory added to sys.path),
discovered either from ``plugin.modules`` / ``plugin.dir`` in
etc/config.properties or installed programmatically.

A plugin module exposes its contributions one of three ways (checked in
order):

1. a module-level ``PLUGIN`` object,
2. a module-level ``get_plugin()`` factory,
3. module-level ``Plugin`` subclasses (instantiated with no args).
"""
from __future__ import annotations

import importlib
import os
import sys
from typing import Callable, Iterable, List, Optional, Tuple


class Plugin:
    """Contribution surface (reference spi/Plugin.java).

    Subclasses override any subset; every getter returns an iterable.
    """

    def get_connector_factories(self) -> Iterable[Tuple[str, Callable]]:
        """[(connector.name value, factory(props) -> Connector), ...]"""
        return ()

    def get_scalar_functions(self) -> Iterable[Tuple[str, Callable,
                                                     Callable]]:
        """[(name, impl(args, out_type) -> Val,
            infer(arg_types) -> Type), ...]"""
        return ()

    def get_event_listeners(self) -> Iterable[Callable]:
        """[listener factories invoked with no args, ...]"""
        return ()


class PluginManager:
    """Discovers and installs plugins (reference
    server/PluginManager.java:121)."""

    def __init__(self):
        self.installed: List[str] = []

    def load_module(self, module_name: str) -> List[Plugin]:
        mod = importlib.import_module(module_name)
        plugins = self._discover(mod)
        if not plugins:
            raise ValueError(
                f"module {module_name!r} exposes no plugin (expected "
                "PLUGIN, get_plugin(), or a Plugin subclass)")
        for p in plugins:
            self.install(p, origin=module_name)
        return plugins

    def load_dir(self, plugin_dir: str) -> List[Plugin]:
        """Each subdirectory (or .py file) of ``plugin_dir`` is one
        plugin module — the etc/plugin/ drop-in layout of the reference's
        plugin/ directory of jars."""
        out: List[Plugin] = []
        if not os.path.isdir(plugin_dir):
            return out
        if plugin_dir not in sys.path:
            sys.path.insert(0, plugin_dir)
        for entry in sorted(os.listdir(plugin_dir)):
            path = os.path.join(plugin_dir, entry)
            if entry.endswith(".py") and not entry.startswith("_"):
                out.extend(self.load_module(entry[:-3]))
            elif os.path.isdir(path) and os.path.isfile(
                    os.path.join(path, "__init__.py")):
                out.extend(self.load_module(entry))
        return out

    @staticmethod
    def _discover(mod) -> List[Plugin]:
        if hasattr(mod, "PLUGIN"):
            return [mod.PLUGIN]
        if hasattr(mod, "get_plugin"):
            return [mod.get_plugin()]
        found = []
        for v in vars(mod).values():
            if (isinstance(v, type) and issubclass(v, Plugin)
                    and v is not Plugin):
                found.append(v())
        return found

    def install(self, plugin: Plugin, origin: str = "<direct>") -> None:
        """Register every contribution (reference installPlugin:165)."""
        from .config import register_connector_factory
        from .expr.functions import register_external
        for name, factory in plugin.get_connector_factories():
            register_connector_factory(name, factory)
        for name, impl, infer in plugin.get_scalar_functions():
            register_external(name, impl, infer)
        self.installed.append(
            f"{origin}:{type(plugin).__name__}")


GLOBAL = PluginManager()


def load_plugins_from_config(props: dict) -> List[Plugin]:
    """Boot-time loading driven by etc/config.properties:
    ``plugin.modules=pkg1,pkg2`` and/or ``plugin.dir=etc/plugin``
    (reference PluginManager reads plugin.dir/plugin.bundles)."""
    out: List[Plugin] = []
    mods = props.get("plugin.modules", "")
    for m in [s.strip() for s in mods.split(",") if s.strip()]:
        out.extend(GLOBAL.load_module(m))
    pdir = props.get("plugin.dir")
    if pdir:
        out.extend(GLOBAL.load_dir(pdir))
    return out

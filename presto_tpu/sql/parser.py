"""Recursive-descent SQL parser (Pratt expressions).

Hand-written replacement for the reference's ANTLR parser (reference
presto-parser/.../parser/SqlParser.java:95 createStatement and
AstBuilder.java) covering the query language TPC-H/TPC-DS needs plus
session/EXPLAIN/SHOW/CTAS statements. Precedence mirrors SqlBase.g4:
OR < AND < NOT < predicate (IS/BETWEEN/IN/LIKE/comparison) < + - < * / %
< unary < postfix.
"""
from __future__ import annotations

from decimal import Decimal
from typing import List, Optional, Tuple

from . import ast as A
from .lexer import NON_RESERVED, SqlSyntaxError, Token, tokenize


def parse_statement(sql: str) -> A.Node:
    p = _Parser(tokenize(sql))
    stmt = p.statement()
    p.expect_kind("EOF")
    return stmt


def parse_expression(sql: str) -> A.Expression:
    p = _Parser(tokenize(sql))
    e = p.expression()
    p.expect_kind("EOF")
    return e


class _Parser:
    def __init__(self, tokens: List[Token]):
        self.toks = tokens
        self.i = 0

    # -- token helpers ------------------------------------------------------
    def peek(self, ahead: int = 0) -> Token:
        return self.toks[min(self.i + ahead, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.kind != "EOF":
            self.i += 1
        return t

    def at_kw(self, *words: str) -> bool:
        t = self.peek()
        return t.kind == "KEYWORD" and t.text in words

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.kind == "OP" and t.text in ops

    def accept_kw(self, *words: str) -> bool:
        if self.at_kw(*words):
            self.next()
            return True
        return False

    def accept_op(self, *ops: str) -> bool:
        if self.at_op(*ops):
            self.next()
            return True
        return False

    def expect_kw(self, word: str) -> Token:
        t = self.peek()
        if not self.at_kw(word):
            raise SqlSyntaxError(f"expected {word.upper()}, found {t.text!r}",
                                 t.line, t.col)
        return self.next()

    def expect_op(self, op: str) -> Token:
        t = self.peek()
        if not self.at_op(op):
            raise SqlSyntaxError(f"expected {op!r}, found {t.text!r}",
                                 t.line, t.col)
        return self.next()

    def expect_kind(self, kind: str) -> Token:
        t = self.peek()
        if t.kind != kind:
            raise SqlSyntaxError(f"expected {kind}, found {t.text!r}",
                                 t.line, t.col)
        return self.next()

    def identifier(self) -> str:
        t = self.peek()
        if t.kind == "IDENT" or t.kind == "QIDENT":
            return self.next().text
        if t.kind == "KEYWORD" and t.text in NON_RESERVED:
            return self.next().text
        raise SqlSyntaxError(f"expected identifier, found {t.text!r}",
                             t.line, t.col)

    def qualified_name(self) -> Tuple[str, ...]:
        parts = [self.identifier()]
        while self.at_op(".") and self.peek(1).kind in ("IDENT", "QIDENT") or (
                self.at_op(".") and self.peek(1).kind == "KEYWORD"
                and self.peek(1).text in NON_RESERVED):
            self.next()
            parts.append(self.identifier())
        return tuple(parts)

    # -- statements ---------------------------------------------------------
    def statement(self) -> A.Node:
        if self.at_kw("explain"):
            self.next()
            etype, fmt = "logical", "text"
            if self.at_op("(") and self.peek(1).text.lower() in (
                    "type", "format"):
                self.next()
                while True:
                    t = self.next()
                    word = t.text.lower()
                    if word == "type":
                        etype = self.next().text.lower()
                        if etype not in ("logical", "distributed",
                                         "validate", "io"):
                            raise SqlSyntaxError(
                                f"unknown EXPLAIN type {etype!r}",
                                t.line, t.col)
                    elif word == "format":
                        fmt = self.next().text.lower()
                        if fmt not in ("text", "json", "graphviz"):
                            raise SqlSyntaxError(
                                f"unknown EXPLAIN format {fmt!r}",
                                t.line, t.col)
                    else:
                        raise SqlSyntaxError(
                            "expected TYPE or FORMAT", t.line, t.col)
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
            analyze = self.accept_kw("analyze")
            return A.Explain(self.statement(), analyze=analyze,
                             type=etype, format=fmt)
        if self.at_kw("show"):
            return self._show()
        if self.at_kw("describe"):
            self.next()
            t = self.peek()
            if t.kind == "IDENT" and t.text.lower() in ("input", "output") \
                    and self.peek(1).kind in ("IDENT", "QIDENT"):
                kind = self.next().text.lower()
                name = self.identifier()
                return (A.DescribeInput(name) if kind == "input"
                        else A.DescribeOutput(name))
            return A.ShowColumns(self.qualified_name())
        if self.at_kw("set"):
            self.next()
            if self.accept_kw("role"):
                t = self.next()
                return A.SetRole(t.text.lower() if t.kind == "KEYWORD"
                                 else t.text)
            self.expect_kw("session")
            name = ".".join(self.qualified_name())
            self.expect_op("=")
            return A.SetSession(name, self.expression())
        if self.at_kw("grant"):
            return self._grant_revoke(grant=True)
        if self.at_kw("revoke"):
            return self._grant_revoke(grant=False)
        if self.at_kw("reset"):
            self.next()
            self.expect_kw("session")
            return A.ResetSession(".".join(self.qualified_name()))
        if self.at_kw("start"):
            self.next()
            self.expect_kw("transaction")
            isolation, read_only = "READ COMMITTED", False
            while True:
                if self.accept_kw("isolation"):
                    self.expect_kw("level")
                    w1 = self.next().text.lower()
                    isolation = (w1 if w1 == "serializable"
                                 else f"{w1} {self.next().text}").upper()
                elif (self.peek().text == "read"
                      and self.peek().kind in ("IDENT", "KEYWORD")):
                    self.next()
                    read_only = self.accept_kw("only")
                    if not read_only:
                        t = self.next()
                        if t.text != "write":
                            raise SqlSyntaxError(
                                f"expected ONLY or WRITE, found "
                                f"{t.text!r}", t.line, t.col)
                elif not self.accept_op(","):
                    break
            return A.StartTransaction(isolation, read_only)
        if self.at_kw("commit"):
            self.next()
            self.accept_kw("work")
            return A.Commit()
        if self.at_kw("rollback"):
            self.next()
            self.accept_kw("work")
            return A.Rollback()
        if self.at_kw("create"):
            return self._create()
        if self.at_kw("drop"):
            self.next()
            if self.accept_kw("role"):
                return A.DropRole(self.identifier())
            is_view = False
            if self.peek().kind == "IDENT" \
                    and self.peek().text.lower() == "view":
                self.next()
                is_view = True
            else:
                self.expect_kw("table")
            if_exists = False
            if self.accept_kw("if"):
                self.expect_kw("exists")
                if_exists = True
            name = self.qualified_name()
            return (A.DropView(name, if_exists) if is_view
                    else A.DropTable(name, if_exists))
        if self.peek().kind == "IDENT" \
                and self.peek().text.lower() == "prepare":
            self.next()
            name = self.identifier()
            self.expect_kw("from")
            return A.Prepare(name, self.statement())
        if self.peek().kind == "IDENT" \
                and self.peek().text.lower() == "execute":
            self.next()
            name = self.identifier()
            args: List[A.Expression] = []
            if self.accept_kw("using"):
                args.append(self.expression())
                while self.accept_op(","):
                    args.append(self.expression())
            return A.ExecuteStmt(name, tuple(args))
        if self.peek().kind == "IDENT" \
                and self.peek().text.lower() == "deallocate":
            self.next()
            t = self.next()
            if t.text.lower() != "prepare":
                raise SqlSyntaxError("expected PREPARE", t.line, t.col)
            return A.Deallocate(self.identifier())
        if self.at_kw("insert"):
            self.next()
            self.expect_kw("into")
            name = self.qualified_name()
            cols: Tuple[str, ...] = ()
            if self.at_op("(") and self._looks_like_column_list():
                self.next()
                names = [self.identifier()]
                while self.accept_op(","):
                    names.append(self.identifier())
                self.expect_op(")")
                cols = tuple(names)
            return A.InsertInto(name, self.query(), cols)
        return self.query()

    def _looks_like_column_list(self) -> bool:
        # distinguish INSERT INTO t (a, b) SELECT ... from INSERT INTO t (SELECT...)
        return not (self.peek(1).kind == "KEYWORD"
                    and self.peek(1).text in ("select", "with", "values"))

    def _show(self) -> A.Node:
        self.expect_kw("show")
        if self.accept_kw("tables"):
            schema = None
            if self.accept_kw("from") or self.accept_kw("in"):
                schema = self.qualified_name()
            return A.ShowTables(schema)
        if self.accept_kw("columns"):
            self.expect_kw("from")
            return A.ShowColumns(self.qualified_name())
        if self.accept_kw("catalogs"):
            return A.ShowCatalogs()
        if self.accept_kw("session"):
            return A.ShowSession()
        if self.accept_kw("roles"):
            return A.ShowRoles()
        if self.accept_kw("grants"):
            table: tuple = ()
            if self.accept_kw("on"):
                self.accept_kw("table")
                table = self.qualified_name()
            return A.ShowGrants(table)
        t = self.peek()
        raise SqlSyntaxError(f"unsupported SHOW {t.text!r}", t.line, t.col)

    def _grant_revoke(self, grant: bool) -> A.Node:
        """GRANT/REVOKE of roles and of table privileges (reference
        sql/tree/Grant.java + GrantRoles.java; SqlBase.g4 grant rules)."""
        self.next()                       # grant | revoke
        # role form: GRANT r1, r2 TO u1, u2 — detected by the absence of
        # a privilege keyword / ALL / ON
        privs: List[str] = []
        is_priv = False
        t = self.peek()
        if t.kind == "KEYWORD" and t.text in ("select", "insert", "all"):
            is_priv = True
        elif t.kind == "IDENT" and t.text.lower() in ("delete", "update"):
            is_priv = True
        if is_priv:
            if self.accept_kw("all"):
                if self.peek().kind == "IDENT" \
                        and self.peek().text.lower() == "privileges":
                    self.next()
                privs = ["SELECT", "INSERT", "DELETE"]
            else:
                while True:
                    privs.append(self.next().text.upper())
                    if not self.accept_op(","):
                        break
            self.expect_kw("on")
            self.accept_kw("table")
            table = self.qualified_name()
            if grant:
                self.expect_kw("to")
            else:
                self.expect_kw("from")
            grantee = self._grantee()
            opt = False
            if grant and self.accept_kw("with"):
                self.expect_kw("grant")
                self.expect_kw("option")
                opt = True
            return (A.GrantPrivileges(tuple(privs), table, grantee, opt)
                    if grant else
                    A.RevokePrivileges(tuple(privs), table, grantee))
        roles = [self.identifier()]
        while self.accept_op(","):
            roles.append(self.identifier())
        if grant:
            self.expect_kw("to")
        else:
            self.expect_kw("from")
        grantees = [self._grantee()]
        while self.accept_op(","):
            grantees.append(self._grantee())
        admin = False
        if grant and self.accept_kw("with"):
            t = self.next()
            if t.text.lower() != "admin":
                raise SqlSyntaxError("expected ADMIN OPTION", t.line, t.col)
            self.expect_kw("option")
            admin = True
        return (A.GrantRoles(tuple(roles), tuple(grantees), admin)
                if grant else A.RevokeRoles(tuple(roles), tuple(grantees)))

    def _grantee(self) -> str:
        # optional USER/ROLE prefix like the reference's principal rule
        t = self.peek()
        if t.kind == "IDENT" and t.text.lower() in ("user",) \
                and self.peek(1).kind in ("IDENT", "QIDENT"):
            self.next()
        elif self.at_kw("role") and self.peek(1).kind in ("IDENT", "QIDENT"):
            self.next()
        return self.identifier()

    def _create(self) -> A.Node:
        self.expect_kw("create")
        if self.accept_kw("role"):
            return A.CreateRole(self.identifier())
        or_replace = False
        if self.accept_kw("or"):
            t = self.next()
            if t.text.lower() != "replace":
                raise SqlSyntaxError("expected REPLACE", t.line, t.col)
            or_replace = True
        if self.peek().kind == "IDENT" \
                and self.peek().text.lower() == "view":
            self.next()
            name = self.qualified_name()
            self.expect_kw("as")
            q = self.query()
            return A.CreateView(name, q, or_replace=or_replace)
        if or_replace:
            t = self.peek()
            raise SqlSyntaxError("OR REPLACE only applies to CREATE VIEW",
                                 t.line, t.col)
        self.expect_kw("table")
        if_not_exists = False
        if self.accept_kw("if"):
            self.expect_kw("not")
            self.expect_kw("exists")
            if_not_exists = True
        name = self.qualified_name()
        props: List[Tuple[str, object]] = []
        if self.accept_kw("with"):
            self.expect_op("(")
            while True:
                key = self.identifier()
                self.expect_op("=")
                props.append((key, self._property_value()))
                if not self.accept_op(","):
                    break
            self.expect_op(")")
        self.expect_kw("as")
        return A.CreateTableAsSelect(name, self.query(), if_not_exists,
                                     properties=tuple(props))

    def _property_value(self):
        """Table property literal: string/number/bool or ARRAY[...] of
        strings (reference sql/tree/Property.java values)."""
        t = self.peek()
        if t.kind == "IDENT" and t.text.lower() == "array":
            self.next()
            self.expect_op("[")
            items: List[object] = []
            if not self.accept_op("]"):
                while True:
                    items.append(self._property_value())
                    if not self.accept_op(","):
                        break
                self.expect_op("]")
            return tuple(items)
        t = self.next()
        if t.kind == "STRING":
            return t.text          # lexer already unquotes
        if t.kind == "INTEGER":
            return int(t.text)
        if t.kind == "NUMBER":
            return float(t.text)
        if t.kind in ("IDENT", "KEYWORD") \
                and t.text.lower() in ("true", "false"):
            return t.text.lower() == "true"
        raise SqlSyntaxError("expected property value", t.line, t.col)

    # -- queries ------------------------------------------------------------
    def query(self) -> A.Query:
        with_: List[Tuple[str, A.Query]] = []
        if self.accept_kw("with"):
            self.accept_kw("recursive")
            while True:
                cte = self.identifier()
                self.expect_kw("as")
                self.expect_op("(")
                q = self.query()
                self.expect_op(")")
                with_.append((cte, q))
                if not self.accept_op(","):
                    break
        body = self._set_expr()
        # ORDER BY / LIMIT bind at query level (SqlBase.g4 queryNoWith),
        # covering the whole set operation
        order_by = self._order_by()
        limit = self._limit()
        if order_by or limit is not None:
            import dataclasses as _dc
            if isinstance(body, A.ValuesQuery):
                body = A.Query(body=body)
            if isinstance(body, A.Query):
                # '(query) ORDER BY ...': order the parenthesized result —
                # wrap as a subquery so an inner LIMIT/WITH is preserved
                body = A.QuerySpecification(
                    select=(A.SelectItem(A.Star()),),
                    from_=A.SubqueryRelation(body),
                    order_by=order_by, limit=limit)
            else:
                body = _dc.replace(body, order_by=order_by, limit=limit)
        return A.Query(body=body, with_=tuple(with_))

    def _set_expr(self) -> A.Node:
        # UNION/EXCEPT are left-associative peers; INTERSECT binds
        # tighter (SqlBase.g4 queryTerm: setOperation precedence)
        left = self._intersect_term()
        while self.at_kw("union", "except"):
            op = self.next().text
            distinct = True
            if self.accept_kw("all"):
                distinct = False
            else:
                self.accept_kw("distinct")
            right = self._intersect_term()
            left = A.SetOperation(op, distinct, left, right)
        return left

    def _intersect_term(self) -> A.Node:
        left = self._query_term()
        while self.at_kw("intersect"):
            self.next()
            distinct = True
            if self.accept_kw("all"):
                distinct = False
            else:
                self.accept_kw("distinct")
            right = self._query_term()
            left = A.SetOperation("intersect", distinct, left, right)
        return left

    def _query_term(self) -> A.Node:
        if self.accept_op("("):
            q = self.query()          # queryPrimary: '(' queryNoWith ')'
            self.expect_op(")")
            return q
        if self.accept_kw("values"):
            rows = [self._values_row()]
            while self.accept_op(","):
                rows.append(self._values_row())
            return A.ValuesQuery(tuple(rows))
        return self.query_spec()

    def _values_row(self) -> Tuple[A.Expression, ...]:
        if self.accept_op("("):
            items = [self.expression()]
            while self.accept_op(","):
                items.append(self.expression())
            self.expect_op(")")
            return tuple(items)
        return (self.expression(),)

    def query_spec(self) -> A.QuerySpecification:
        self.expect_kw("select")
        distinct = False
        if self.accept_kw("distinct"):
            distinct = True
        else:
            self.accept_kw("all")
        items = [self._select_item()]
        while self.accept_op(","):
            items.append(self._select_item())
        from_ = None
        if self.accept_kw("from"):
            from_ = self._relation()
            while self.accept_op(","):
                right = self._relation()
                from_ = A.Join("implicit", from_, right)
        where = self.expression() if self.accept_kw("where") else None
        group_by: Tuple[A.Expression, ...] = ()
        grouping_sets = None
        if self.accept_kw("group"):
            self.expect_kw("by")
            group_by, grouping_sets = self._group_by()
        having = self.expression() if self.accept_kw("having") else None
        return A.QuerySpecification(
            select=tuple(items), distinct=distinct, from_=from_, where=where,
            group_by=group_by, having=having, grouping_sets=grouping_sets)

    def _group_by(self):
        """GROUP BY: plain expr list, or ROLLUP/CUBE/GROUPING SETS, which
        desugar to (distinct exprs, index sets) — reference
        sql/tree/GroupingSets.java / Rollup.java / Cube.java."""
        def expr_list():
            self.expect_op("(")
            if self.accept_op(")"):
                return []
            out = [self.expression()]
            while self.accept_op(","):
                out.append(self.expression())
            self.expect_op(")")
            return out

        def at_ident(word, then_op=None, then_ident=None):
            t, t1 = self.peek(), self.peek(1)
            if not (t.kind == "IDENT" and t.text == word):
                return False
            if then_op is not None:
                return t1.kind == "OP" and t1.text == then_op
            if then_ident is not None:
                return t1.kind == "IDENT" and t1.text == then_ident
            return True

        def no_mixing():
            if self.at_op(","):
                t = self.peek()
                raise SqlSyntaxError(
                    "mixing ROLLUP/CUBE/GROUPING SETS with plain GROUP BY "
                    "expressions is not supported", t.line, t.col)

        if at_ident("rollup", then_op="("):
            self.next()
            exprs = expr_list()
            no_mixing()
            n = len(exprs)
            sets = [tuple(range(k)) for k in range(n, -1, -1)]
        elif at_ident("cube", then_op="("):
            self.next()
            exprs = expr_list()
            no_mixing()
            n = len(exprs)
            sets = [tuple(i for i in range(n) if m >> i & 1)
                    for m in range((1 << n) - 1, -1, -1)]
        elif at_ident("grouping", then_ident="sets"):
            self.next()
            self.next()
            self.expect_op("(")
            raw_sets = []
            exprs = []
            while True:
                if self.at_op("("):
                    one = expr_list()
                else:
                    one = [self.expression()]
                idxs = []
                for e in one:
                    if e not in exprs:
                        exprs.append(e)
                    idxs.append(exprs.index(e))
                raw_sets.append(tuple(idxs))
                if not self.accept_op(","):
                    break
            self.expect_op(")")
            no_mixing()
            sets = raw_sets
        else:
            exprs = [self.expression()]
            while self.accept_op(","):
                if (at_ident("rollup", then_op="(")
                        or at_ident("cube", then_op="(")
                        or at_ident("grouping", then_ident="sets")):
                    t = self.peek()
                    raise SqlSyntaxError(
                        "mixing ROLLUP/CUBE/GROUPING SETS with plain GROUP "
                        "BY expressions is not supported", t.line, t.col)
                exprs.append(self.expression())
            return tuple(exprs), None
        return tuple(exprs), tuple(sets)

    def _order_by(self) -> Tuple[A.SortItem, ...]:
        if not self.accept_kw("order"):
            return ()
        self.expect_kw("by")
        items = [self._sort_item()]
        while self.accept_op(","):
            items.append(self._sort_item())
        return tuple(items)

    def _sort_item(self) -> A.SortItem:
        key = self.expression()
        asc = True
        if self.accept_kw("asc"):
            asc = True
        elif self.accept_kw("desc"):
            asc = False
        nulls_first: Optional[bool] = None
        if self.accept_kw("nulls"):
            if self.accept_kw("first"):
                nulls_first = True
            else:
                self.expect_kw("last")
                nulls_first = False
        return A.SortItem(key, asc, nulls_first)

    def _limit(self) -> Optional[int]:
        if self.accept_kw("limit"):
            t = self.expect_kind("INTEGER")
            return int(t.text)
        return None

    def _select_item(self) -> A.SelectItem:
        if self.at_op("*"):
            self.next()
            return A.SelectItem(A.Star())
        # t.* form
        if (self.peek().kind in ("IDENT", "QIDENT") and self.peek(1).kind == "OP"
                and self.peek(1).text == "." and self.peek(2).kind == "OP"
                and self.peek(2).text == "*"):
            q = self.identifier()
            self.next()
            self.next()
            return A.SelectItem(A.Star(qualifier=q))
        e = self.expression()
        alias = None
        if self.accept_kw("as"):
            alias = self.identifier()
        elif self.peek().kind in ("IDENT", "QIDENT"):
            alias = self.identifier()
        return A.SelectItem(e, alias)

    # -- relations ----------------------------------------------------------
    def _relation(self) -> A.Relation:
        left = self._aliased_relation()
        while True:
            if self.accept_kw("cross"):
                self.expect_kw("join")
                right = self._aliased_relation()
                left = A.Join("cross", left, right)
                continue
            join_type = None
            if self.at_kw("join"):
                join_type = "inner"
            elif self.at_kw("inner"):
                join_type = "inner"
                self.next()
            elif self.at_kw("left"):
                join_type = "left"
                self.next()
                self.accept_kw("outer")
            elif self.at_kw("right"):
                join_type = "right"
                self.next()
                self.accept_kw("outer")
            elif self.at_kw("full"):
                join_type = "full"
                self.next()
                self.accept_kw("outer")
            if join_type is None:
                return left
            self.expect_kw("join")
            right = self._aliased_relation()
            self.expect_kw("on")
            cond = self.expression()
            left = A.Join(join_type, left, right, cond)

    def _aliased_relation(self) -> A.Relation:
        rel = self._primary_relation()
        alias = None
        cols: Tuple[str, ...] = ()
        if self.accept_kw("as"):
            alias = self.identifier()
        elif self.peek().kind in ("IDENT", "QIDENT"):
            alias = self.identifier()
        if alias is not None and self.at_op("("):
            # aliased column list: t(a, b, c)
            self.next()
            names = [self.identifier()]
            while self.accept_op(","):
                names.append(self.identifier())
            self.expect_op(")")
            cols = tuple(names)
        if alias is not None:
            return A.AliasedRelation(rel, alias, cols)
        return rel

    def _primary_relation(self) -> A.Relation:
        if self.accept_op("("):
            # disambiguate subquery vs parenthesized join tree (the
            # reference grammar's aliasedRelation '(' relation ')' branch
            # vs subquery, SqlBase.g4). A leading SELECT usually means a
            # subquery, but '((select ...) t JOIN ...)' is a relation —
            # try the query parse and backtrack if the close paren
            # doesn't follow.
            j = 0
            while self.peek(j).kind == "OP" and self.peek(j).text == "(":
                j += 1
            t = self.peek(j)
            starts_query = (t.kind == "KEYWORD"
                            and t.text in ("select", "with", "values"))
            if self.at_kw("select", "with", "values") or starts_query:
                mark = self.i
                try:
                    q = self.query()
                    if self.at_op(")"):
                        self.next()
                        return A.SubqueryRelation(q)
                except SqlSyntaxError:
                    pass
                self.i = mark            # a join tree follows: relation
            rel = self._relation()
            self.expect_op(")")
            return rel
        t = self.peek()
        if t.kind == "IDENT" and t.text.lower() == "unnest" \
                and self.peek(1).kind == "OP" and self.peek(1).text == "(":
            self.next()
            self.next()
            exprs = [self.expression()]
            while self.accept_op(","):
                exprs.append(self.expression())
            self.expect_op(")")
            ordinality = False
            if self.accept_kw("with"):
                w = self.next()
                if w.text.lower() != "ordinality":
                    raise SqlSyntaxError("expected ORDINALITY",
                                         w.line, w.col)
                ordinality = True
            return A.Unnest(tuple(exprs), ordinality)
        return A.Table(self.qualified_name())

    # -- expressions (Pratt) ------------------------------------------------
    def expression(self) -> A.Expression:
        return self._or_expr()

    def _or_expr(self) -> A.Expression:
        left = self._and_expr()
        while self.accept_kw("or"):
            left = A.LogicalBinary("or", left, self._and_expr())
        return left

    def _and_expr(self) -> A.Expression:
        left = self._not_expr()
        while self.accept_kw("and"):
            left = A.LogicalBinary("and", left, self._not_expr())
        return left

    def _not_expr(self) -> A.Expression:
        if self.accept_kw("not"):
            return A.Not(self._not_expr())
        return self._predicate()

    def _predicate(self) -> A.Expression:
        left = self._additive()
        while True:
            if self.at_op("=", "<>", "!=", "<", "<=", ">", ">="):
                op = self.next().text
                if op == "!=":
                    op = "<>"
                right = self._additive()
                left = A.Comparison(op, left, right)
                continue
            negated = False
            save = self.i
            if self.accept_kw("not"):
                if not self.at_kw("between", "in", "like"):
                    # NOT here belongs to an IS NOT NULL-style form or is an
                    # error; rewind and stop
                    self.i = save
                    return left
                negated = True
            if self.accept_kw("between"):
                lo = self._additive()
                self.expect_kw("and")
                hi = self._additive()
                left = A.Between(left, lo, hi, negated)
                continue
            if self.accept_kw("in"):
                self.expect_op("(")
                if self.at_kw("select", "with"):
                    q = self.query()
                    self.expect_op(")")
                    left = A.InSubquery(left, q, negated)
                else:
                    items = [self.expression()]
                    while self.accept_op(","):
                        items.append(self.expression())
                    self.expect_op(")")
                    left = A.InList(left, tuple(items), negated)
                continue
            if self.accept_kw("like"):
                pattern = self._additive()
                escape = None
                if self.accept_kw("escape"):
                    escape = self._additive()
                left = A.Like(left, pattern, escape, negated)
                continue
            if self.at_kw("is"):
                self.next()
                neg = self.accept_kw("not")
                self.expect_kw("null")
                left = A.IsNull(left, neg)
                continue
            return left

    def _additive(self) -> A.Expression:
        left = self._multiplicative()
        while True:
            if self.at_op("+", "-"):
                op = self.next().text
                left = A.ArithmeticBinary(op, left, self._multiplicative())
            elif self.at_op("||"):
                self.next()
                left = A.FunctionCall("concat", (left, self._multiplicative()))
            else:
                return left

    def _multiplicative(self) -> A.Expression:
        left = self._unary()
        while self.at_op("*", "/", "%"):
            op = self.next().text
            left = A.ArithmeticBinary(op, left, self._unary())
        return left

    def _unary(self) -> A.Expression:
        if self.at_op("-", "+"):
            op = self.next().text
            v = self._unary()
            if op == "-" and isinstance(v, A.LongLiteral):
                return A.LongLiteral(-v.value)
            if op == "-" and isinstance(v, A.DecimalLiteral):
                return A.DecimalLiteral(-v.value)
            if op == "-" and isinstance(v, A.DoubleLiteral):
                return A.DoubleLiteral(-v.value)
            return A.ArithmeticUnary(op, v) if op == "-" else v
        return self._primary()

    def _primary(self) -> A.Expression:
        t = self.peek()
        if t.kind == "OP" and t.text == "?":
            self.next()
            self._param_count = getattr(self, "_param_count", 0)
            idx = self._param_count
            self._param_count += 1
            return A.Parameter(idx)
        # lambda: x -> expr  |  (x, y) -> expr
        if t.kind in ("IDENT", "QIDENT") and self.peek(1).kind == "OP" \
                and self.peek(1).text == "->":
            name = self.identifier()
            self.expect_op("->")
            return A.Lambda((name,), self.expression())
        if t.kind == "OP" and t.text == "(":
            params = self._try_lambda_params()
            if params is not None:
                return A.Lambda(params, self.expression())
        if t.kind == "IDENT" and t.text.lower() == "array" \
                and self.peek(1).kind == "OP" and self.peek(1).text == "[":
            self.next()
            self.next()
            items: List[A.Expression] = []
            if not self.at_op("]"):
                items.append(self.expression())
                while self.accept_op(","):
                    items.append(self.expression())
            self.expect_op("]")
            return self._postfix(A.ArrayLiteral(tuple(items)))
        if t.kind == "INTEGER":
            self.next()
            return A.LongLiteral(int(t.text))
        if t.kind == "NUMBER":
            self.next()
            if "e" in t.text.lower():
                return A.DoubleLiteral(float(t.text))
            return A.DecimalLiteral(Decimal(t.text))
        if t.kind == "STRING":
            self.next()
            return A.StringLiteral(t.text)
        if t.kind == "KEYWORD":
            return self._keyword_primary(t)
        if t.kind == "OP" and t.text == "(":
            self.next()
            if self.at_kw("select", "with"):
                q = self.query()
                self.expect_op(")")
                return A.ScalarSubquery(q)
            e = self.expression()
            self.expect_op(")")
            return self._postfix(e)
        if t.kind in ("IDENT", "QIDENT"):
            return self._ident_primary()
        raise SqlSyntaxError(f"unexpected token {t.text!r}", t.line, t.col)

    def _keyword_primary(self, t: Token) -> A.Expression:
        w = t.text
        if w == "null":
            self.next()
            return A.NullLiteral()
        if w in ("true", "false"):
            self.next()
            return A.BooleanLiteral(w == "true")
        if w == "date":
            if self.peek(1).kind == "STRING":
                self.next()
                s = self.next()
                return A.DateLiteral(s.text)
            return self._ident_primary()
        if w == "timestamp" and self.peek(1).kind == "STRING":
            self.next()
            s = self.next()
            return A.FunctionCall("parse_timestamp_literal",
                                  (A.StringLiteral(s.text),))
        if w == "interval":
            self.next()
            sign = 1
            if self.accept_op("-"):
                sign = -1
            else:
                self.accept_op("+")
            v = self.expect_kind("STRING")
            unit_t = self.peek()
            if not (unit_t.kind == "KEYWORD" and unit_t.text in (
                    "year", "month", "day", "hour", "minute", "second")):
                raise SqlSyntaxError("expected interval unit",
                                     unit_t.line, unit_t.col)
            self.next()
            return A.IntervalLiteral(v.text, unit_t.text, sign)
        if w in ("cast", "try_cast"):
            self.next()
            self.expect_op("(")
            e = self.expression()
            self.expect_kw("as")
            type_name = self._type_name()
            self.expect_op(")")
            return self._postfix(A.Cast(e, type_name, try_cast=(w == "try_cast")))
        if w == "extract":
            self.next()
            self.expect_op("(")
            field = self.identifier() if not self.peek().kind == "KEYWORD" \
                else self.next().text
            self.expect_kw("from")
            e = self.expression()
            self.expect_op(")")
            return A.Extract(field, e)
        if w == "case":
            return self._case()
        if w == "exists":
            self.next()
            self.expect_op("(")
            q = self.query()
            self.expect_op(")")
            return A.Exists(q)
        if w == "coalesce":
            self.next()
            self.expect_op("(")
            args = [self.expression()]
            while self.accept_op(","):
                args.append(self.expression())
            self.expect_op(")")
            return A.Coalesce(tuple(args))
        if w == "nullif":
            self.next()
            self.expect_op("(")
            first = self.expression()
            self.expect_op(",")
            second = self.expression()
            self.expect_op(")")
            return A.NullIf(first, second)
        if w in NON_RESERVED:
            return self._ident_primary()
        raise SqlSyntaxError(f"unexpected keyword {w!r}", t.line, t.col)

    def _case(self) -> A.Expression:
        self.expect_kw("case")
        operand = None
        if not self.at_kw("when"):
            operand = self.expression()
        whens = []
        while self.accept_kw("when"):
            cond = self.expression()
            self.expect_kw("then")
            res = self.expression()
            whens.append(A.WhenClause(cond, res))
        default = None
        if self.accept_kw("else"):
            default = self.expression()
        self.expect_kw("end")
        if operand is not None:
            return A.SimpleCase(operand, tuple(whens), default)
        return A.SearchedCase(tuple(whens), default)

    def _type_name(self) -> str:
        base = self.identifier() if self.peek().kind != "KEYWORD" \
            else self.next().text
        if base.lower() in ("array", "map") and self.accept_op("("):
            args = [self._type_name()]
            while self.accept_op(","):
                args.append(self._type_name())
            self.expect_op(")")
            return f"{base}({','.join(args)})"
        if self.accept_op("("):
            args = [self.expect_kind("INTEGER").text]
            while self.accept_op(","):
                args.append(self.expect_kind("INTEGER").text)
            self.expect_op(")")
            return f"{base}({','.join(args)})"
        return base

    def _ident_primary(self) -> A.Expression:
        # DECIMAL 'ddd.dd' typed literal (reference SqlBase.g4
        # DECIMAL_VALUE / AstBuilder.visitTypeConstructor)
        t = self.peek()
        if t.kind == "IDENT" and t.text.lower() == "decimal" \
                and self.peek(1).kind == "STRING":
            self.next()
            s = self.next()
            try:
                d = Decimal(s.text.strip())
                if not d.is_finite():
                    raise ValueError("non-finite")
                # normalize exponent forms (1E5) to plain digits so the
                # (precision, scale) derivation sees the true magnitude
                if int(d.as_tuple().exponent) > 0:
                    d = d.quantize(Decimal(1))
                return A.DecimalLiteral(d)
            except SqlSyntaxError:
                raise
            except Exception as e:
                raise SqlSyntaxError(f"bad DECIMAL literal {s.text!r}",
                                     t.line, t.col) from e
        name = self.identifier()
        # function call?
        if self.at_op("("):
            self.next()
            if self.accept_op("*"):
                self.expect_op(")")
                return self._maybe_window(
                    A.FunctionCall(name.lower(), (), is_star=True))
            distinct = False
            args: List[A.Expression] = []
            if not self.at_op(")"):
                if self.accept_kw("distinct"):
                    distinct = True
                else:
                    self.accept_kw("all")
                args.append(self.expression())
                while self.accept_op(","):
                    args.append(self.expression())
            self.expect_op(")")
            return self._postfix(self._maybe_window(
                A.FunctionCall(name.lower(), tuple(args), distinct=distinct)))
        e: A.Expression = A.Identifier(name)
        return self._postfix(e)

    def _maybe_window(self, call: A.FunctionCall) -> A.Expression:
        """fn(...) OVER (PARTITION BY ... ORDER BY ... [frame])."""
        if not self.at_kw("over"):
            return call
        self.next()
        self.expect_op("(")
        partition: List[A.Expression] = []
        if self.accept_kw("partition"):
            self.expect_kw("by")
            partition.append(self.expression())
            while self.accept_op(","):
                partition.append(self.expression())
        order_by = self._order_by()
        # full frame grammar (reference operator/window/FrameInfo.java):
        # ROWS|RANGE [BETWEEN] <bound> [AND <bound>], bounds = UNBOUNDED
        # PRECEDING | <n> PRECEDING | CURRENT ROW | <n> FOLLOWING |
        # UNBOUNDED FOLLOWING. Default: RANGE UNBOUNDED..CURRENT ROW.
        frame = "range"
        fstart = ("unbounded_preceding", 0)
        fend = ("current_row", 0)
        if self.at_kw("rows", "range"):
            frame = "rows" if self.at_kw("rows") else "range"
            self.next()
            if self.accept_kw("between"):
                fstart = self._frame_bound()
                self.expect_kw("and")
                fend = self._frame_bound()
            else:
                # frame-start-only spelling: end defaults to CURRENT ROW
                fstart = self._frame_bound()
            t = self.peek()
            if fstart[0] == "unbounded_following":
                raise SqlSyntaxError(
                    "frame start cannot be UNBOUNDED FOLLOWING",
                    t.line, t.col)
            if fend[0] == "unbounded_preceding":
                raise SqlSyntaxError(
                    "frame end cannot be UNBOUNDED PRECEDING",
                    t.line, t.col)
            order_rank = {"unbounded_preceding": 0, "preceding": 1,
                          "current_row": 2, "following": 3,
                          "unbounded_following": 4}
            if order_rank[fstart[0]] > order_rank[fend[0]]:
                raise SqlSyntaxError("frame start cannot follow frame end",
                                     t.line, t.col)
        self.expect_op(")")
        return A.WindowFunction(call, tuple(partition), order_by, frame,
                                fstart, fend)

    def _frame_bound(self) -> tuple:
        if self.accept_kw("unbounded"):
            if self.accept_kw("preceding"):
                return ("unbounded_preceding", 0)
            self.expect_kw("following")
            return ("unbounded_following", 0)
        if self.accept_kw("current"):
            self.expect_kw("row")
            return ("current_row", 0)
        tok = self.peek()
        if tok.kind != "INTEGER":
            raise SqlSyntaxError("frame offset must be an integer literal",
                                 tok.line, tok.col)
        n = int(tok.text)
        self.next()
        if self.accept_kw("preceding"):
            return ("preceding", n)
        self.expect_kw("following")
        return ("following", n)

    def _try_lambda_params(self) -> Optional[Tuple[str, ...]]:
        """Consume '(a, b, ...) ->' if present; None (no consumption)
        otherwise."""
        save = self.i
        if not self.accept_op("("):
            return None
        names: List[str] = []
        while self.peek().kind in ("IDENT", "QIDENT"):
            names.append(self.identifier())
            if self.accept_op(","):
                continue
            break
        if names and self.accept_op(")") and self.accept_op("->"):
            return tuple(names)
        self.i = save
        return None

    def _postfix(self, e: A.Expression) -> A.Expression:
        while True:
            if self.at_op(".") and (
                    self.peek(1).kind in ("IDENT", "QIDENT")
                    or (self.peek(1).kind == "KEYWORD"
                        and self.peek(1).text in NON_RESERVED)):
                self.next()
                e = A.DereferenceExpression(e, A.Identifier(self.identifier()))
                continue
            if self.at_op("["):
                self.next()
                idx = self.expression()
                self.expect_op("]")
                e = A.Subscript(e, idx)
                continue
            return e

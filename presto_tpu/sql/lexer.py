"""SQL lexer.

Hand-written replacement for the reference's ANTLR-generated lexer
(reference presto-parser/src/main/antlr4/io/prestosql/sql/parser/
SqlBase.g4 lexer rules) — the TPU build avoids parser-generator codegen
(SURVEY.md §2c item 5).
"""
from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional


class SqlSyntaxError(ValueError):
    def __init__(self, message: str, line: int = 0, col: int = 0):
        super().__init__(f"line {line}:{col}: {message}" if line else message)
        self.line = line
        self.col = col


@dataclasses.dataclass(frozen=True)
class Token:
    kind: str          # IDENT QIDENT STRING NUMBER INTEGER OP KEYWORD EOF
    text: str          # raw text (keywords/idents lowercased; QIDENT unquoted)
    line: int
    col: int

    def __repr__(self) -> str:
        return f"{self.kind}({self.text!r})"


# Multi-char operators first (longest match wins)
_OPERATORS = ("<>", "!=", ">=", "<=", "||", "->", "=", "<", ">", "+", "-",
              "*", "/", "%", "(", ")", ",", ".", ";", "?", "[", "]")

KEYWORDS = frozenset("""
    select from where group by having order limit offset distinct all as on
    join inner left right full outer cross natural using and or not in like
    escape between is null true false case when then else end cast try_cast
    exists union intersect except with recursive asc desc nulls first last
    interval year month day hour minute second date time timestamp extract
    count sum avg min max coalesce nullif
    create table drop insert into values if show session set reset explain
    analyze describe catalogs schemas tables columns functions
    over partition rows range preceding following unbounded current row
    start transaction commit rollback work isolation level only
    grant revoke role roles grants to option
""".split())

# Keywords that can still be used as identifiers in non-ambiguous positions
# (mirrors SqlBase.g4 nonReserved rule)
NON_RESERVED = frozenset("""
    date time timestamp year month day hour minute second catalogs schemas
    tables columns functions session analyze show if first last nulls
    count sum avg min max coalesce nullif interval
    over partition rows range preceding following unbounded current row
    start transaction commit rollback work isolation level only
    role roles grants option
""".split())


def tokenize(sql: str) -> List[Token]:
    out: List[Token] = []
    i, n = 0, len(sql)
    line, line_start = 1, 0

    def pos(idx: int):
        return line, idx - line_start + 1

    while i < n:
        c = sql[i]
        if c == "\n":
            line += 1
            line_start = i + 1
            i += 1
            continue
        if c in " \t\r":
            i += 1
            continue
        if sql.startswith("--", i):
            j = sql.find("\n", i)
            i = n if j < 0 else j
            continue
        if sql.startswith("/*", i):
            j = sql.find("*/", i)
            if j < 0:
                raise SqlSyntaxError("unterminated comment", *pos(i))
            line += sql.count("\n", i, j)
            i = j + 2
            continue
        ln, col = pos(i)
        if c == "'":
            # string literal, '' escapes a quote
            j = i + 1
            buf = []
            while True:
                if j >= n:
                    raise SqlSyntaxError("unterminated string", ln, col)
                if sql[j] == "'":
                    if j + 1 < n and sql[j + 1] == "'":
                        buf.append("'")
                        j += 2
                        continue
                    break
                buf.append(sql[j])
                j += 1
            out.append(Token("STRING", "".join(buf), ln, col))
            i = j + 1
            continue
        if c == '"':
            j = sql.find('"', i + 1)
            if j < 0:
                raise SqlSyntaxError("unterminated quoted identifier", ln, col)
            out.append(Token("QIDENT", sql[i + 1:j], ln, col))
            i = j + 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            is_float = False
            while j < n and sql[j].isdigit():
                j += 1
            if j < n and sql[j] == ".":
                is_float = True
                j += 1
                while j < n and sql[j].isdigit():
                    j += 1
            if j < n and sql[j] in "eE":
                k = j + 1
                if k < n and sql[k] in "+-":
                    k += 1
                if k < n and sql[k].isdigit():
                    is_float = True
                    j = k
                    while j < n and sql[j].isdigit():
                        j += 1
            text = sql[i:j]
            out.append(Token("NUMBER" if is_float else "INTEGER", text, ln, col))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            word = sql[i:j].lower()
            kind = "KEYWORD" if word in KEYWORDS else "IDENT"
            out.append(Token(kind, word, ln, col))
            i = j
            continue
        for op in _OPERATORS:
            if sql.startswith(op, i):
                out.append(Token("OP", op, ln, col))
                i += len(op)
                break
        else:
            raise SqlSyntaxError(f"unexpected character {c!r}", ln, col)
    out.append(Token("EOF", "", line, n - line_start + 1))
    return out

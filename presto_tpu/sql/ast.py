"""SQL abstract syntax tree.

Conceptual parity with Presto's AST (reference presto-parser/src/main/java/
io/prestosql/sql/tree/ — 169 node classes); this is the subset needed for
the TPC-H/TPC-DS query language plus the session/DDL-lite statements the
engine serves. Nodes are frozen dataclasses: hashable, comparable,
printable — the analyzer annotates types out-of-band keyed by node
identity, like Presto's Analysis maps (reference
presto-main/.../sql/analyzer/Analysis.java).
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from decimal import Decimal


class Node:
    pass


# ---------------------------------------------------------------------------
# Expressions (reference sql/tree/Expression.java subclasses)
# ---------------------------------------------------------------------------

class Expression(Node):
    pass


@dataclasses.dataclass(frozen=True)
class Identifier(Expression):
    name: str                      # lowercased unless quoted
    quoted: bool = False


@dataclasses.dataclass(frozen=True)
class DereferenceExpression(Expression):
    """Qualified name a.b (table.column)."""
    base: Expression
    field: Identifier


@dataclasses.dataclass(frozen=True)
class NullLiteral(Expression):
    pass


@dataclasses.dataclass(frozen=True)
class BooleanLiteral(Expression):
    value: bool


@dataclasses.dataclass(frozen=True)
class LongLiteral(Expression):
    value: int


@dataclasses.dataclass(frozen=True)
class DecimalLiteral(Expression):
    value: Decimal


@dataclasses.dataclass(frozen=True)
class DoubleLiteral(Expression):
    value: float


@dataclasses.dataclass(frozen=True)
class StringLiteral(Expression):
    value: str


@dataclasses.dataclass(frozen=True)
class DateLiteral(Expression):
    """DATE 'yyyy-mm-dd' (reference sql/tree/GenericLiteral.java)."""
    value: str


@dataclasses.dataclass(frozen=True)
class IntervalLiteral(Expression):
    """INTERVAL '3' MONTH — sign, value text, unit."""
    value: str
    unit: str                      # year|month|day|hour|minute|second
    sign: int = 1


@dataclasses.dataclass(frozen=True)
class ArithmeticBinary(Expression):
    op: str                        # + - * / %
    left: Expression
    right: Expression


@dataclasses.dataclass(frozen=True)
class ArithmeticUnary(Expression):
    op: str                        # + -
    value: Expression


@dataclasses.dataclass(frozen=True)
class Comparison(Expression):
    op: str                        # = <> < <= > >=
    left: Expression
    right: Expression


@dataclasses.dataclass(frozen=True)
class LogicalBinary(Expression):
    op: str                        # and | or
    left: Expression
    right: Expression


@dataclasses.dataclass(frozen=True)
class Not(Expression):
    value: Expression


@dataclasses.dataclass(frozen=True)
class Between(Expression):
    value: Expression
    min: Expression
    max: Expression
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class InList(Expression):
    value: Expression
    items: Tuple[Expression, ...]
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class InSubquery(Expression):
    value: Expression
    query: "Query"
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class Exists(Expression):
    query: "Query"
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class ScalarSubquery(Expression):
    query: "Query"


@dataclasses.dataclass(frozen=True)
class Like(Expression):
    value: Expression
    pattern: Expression
    escape: Optional[Expression] = None
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class IsNull(Expression):
    value: Expression
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class FunctionCall(Expression):
    name: str                      # lowercased
    args: Tuple[Expression, ...]
    distinct: bool = False
    is_star: bool = False          # count(*)


@dataclasses.dataclass(frozen=True)
class Parameter(Expression):
    """Positional ? parameter in a prepared statement
    (reference sql/tree/Parameter.java)."""
    index: int


@dataclasses.dataclass(frozen=True)
class TypedParameter(Expression):
    """Literal hole in a plan-template fingerprint (serving/template.py):
    position plus the literal's TYPE KIND, never its value — two
    statements differing only in hole-punched literal values hash to
    the same template. Never planned; exists only to be hashed."""
    index: int
    kind: str                      # bigint | double | date | decimal(p,s)


# Slot-marked literals: value-carrying literals the template
# parameterizer has assigned a binding slot. They subclass their plain
# forms, so every analysis/validation isinstance check keeps working,
# but the analyzer lowers them to runtime-bound ir.Param nodes instead
# of baked constants (see analyzer._Slot*Literal).

@dataclasses.dataclass(frozen=True)
class SlotLongLiteral(LongLiteral):
    slot: int = -1


@dataclasses.dataclass(frozen=True)
class SlotDoubleLiteral(DoubleLiteral):
    slot: int = -1


@dataclasses.dataclass(frozen=True)
class SlotDecimalLiteral(DecimalLiteral):
    slot: int = -1


@dataclasses.dataclass(frozen=True)
class SlotDateLiteral(DateLiteral):
    slot: int = -1


@dataclasses.dataclass(frozen=True)
class ArrayLiteral(Expression):
    """ARRAY[e1, e2, ...] (reference sql/tree/ArrayConstructor.java)."""
    items: Tuple[Expression, ...]


@dataclasses.dataclass(frozen=True)
class Subscript(Expression):
    """base[index] — 1-based array subscript / map key lookup
    (reference sql/tree/SubscriptExpression.java)."""
    base: Expression
    index: Expression


@dataclasses.dataclass(frozen=True)
class Lambda(Expression):
    """x -> expr / (x, y) -> expr (reference sql/tree/LambdaExpression.java)."""
    params: Tuple[str, ...]
    body: Expression


@dataclasses.dataclass(frozen=True)
class WindowFunction(Expression):
    """fn(...) OVER (PARTITION BY ... ORDER BY ... [frame]) (reference
    sql/tree/FunctionCall window + Window.java + WindowFrame.java).
    Frame bounds are (kind, offset) with kind in unbounded_preceding |
    preceding | current_row | following | unbounded_following."""
    call: "FunctionCall"
    partition_by: Tuple[Expression, ...] = ()
    order_by: Tuple["SortItem", ...] = ()
    frame: str = "range"           # frame unit: RANGE | ROWS
    frame_start: Tuple[str, int] = ("unbounded_preceding", 0)
    frame_end: Tuple[str, int] = ("current_row", 0)


@dataclasses.dataclass(frozen=True)
class Cast(Expression):
    value: Expression
    type_name: str                 # e.g. "decimal(12,2)"
    try_cast: bool = False


@dataclasses.dataclass(frozen=True)
class Extract(Expression):
    field: str                     # year|month|day|...
    value: Expression


@dataclasses.dataclass(frozen=True)
class WhenClause(Node):
    condition: Expression
    result: Expression


@dataclasses.dataclass(frozen=True)
class SearchedCase(Expression):
    whens: Tuple[WhenClause, ...]
    default: Optional[Expression] = None


@dataclasses.dataclass(frozen=True)
class SimpleCase(Expression):
    operand: Expression
    whens: Tuple[WhenClause, ...]
    default: Optional[Expression] = None


@dataclasses.dataclass(frozen=True)
class Coalesce(Expression):
    args: Tuple[Expression, ...]


@dataclasses.dataclass(frozen=True)
class NullIf(Expression):
    first: Expression
    second: Expression


@dataclasses.dataclass(frozen=True)
class Star(Expression):
    """SELECT * or t.*"""
    qualifier: Optional[str] = None


# ---------------------------------------------------------------------------
# Relations (reference sql/tree/Relation.java subclasses)
# ---------------------------------------------------------------------------

class Relation(Node):
    pass


@dataclasses.dataclass(frozen=True)
class Table(Relation):
    """Possibly-qualified table name: [catalog.][schema.]table"""
    name: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class AliasedRelation(Relation):
    relation: Relation
    alias: str
    column_names: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class SubqueryRelation(Relation):
    query: "Query"


@dataclasses.dataclass(frozen=True)
class Unnest(Relation):
    """UNNEST(expr, ...) [WITH ORDINALITY] — lateral array expansion
    (reference sql/tree/Unnest.java). Expressions may reference columns
    of relations earlier in the FROM list."""
    exprs: Tuple[Expression, ...]
    ordinality: bool = False


@dataclasses.dataclass(frozen=True)
class Join(Relation):
    join_type: str                 # inner|left|right|full|cross|implicit
    left: Relation
    right: Relation
    condition: Optional[Expression] = None   # ON expr (None for cross)


# ---------------------------------------------------------------------------
# Query structure (reference sql/tree/Query.java, QuerySpecification.java)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class SelectItem(Node):
    value: Expression
    alias: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class SortItem(Node):
    key: Expression
    ascending: bool = True
    nulls_first: Optional[bool] = None     # None = type default (last for asc)


@dataclasses.dataclass(frozen=True)
class QuerySpecification(Node):
    select: Tuple[SelectItem, ...]
    distinct: bool = False
    from_: Optional[Relation] = None
    where: Optional[Expression] = None
    group_by: Tuple[Expression, ...] = ()
    # GROUP BY ROLLUP/CUBE/GROUPING SETS desugar to index tuples into
    # group_by (reference sql/tree/GroupingSets.java); None = plain GROUP BY
    grouping_sets: Optional[Tuple[Tuple[int, ...], ...]] = None
    having: Optional[Expression] = None
    order_by: Tuple[SortItem, ...] = ()
    limit: Optional[int] = None
    offset: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class ValuesQuery(Node):
    """VALUES (e, ...), ... as a query body (reference
    sql/tree/Values.java — the inlineTable rule)."""
    rows: Tuple[Tuple[Expression, ...], ...]


@dataclasses.dataclass(frozen=True)
class Query(Node):
    """Top-level query: body plus WITH bindings."""
    body: Node                     # QuerySpecification | SetOperation | ValuesQuery
    with_: Tuple[Tuple[str, "Query"], ...] = ()


@dataclasses.dataclass(frozen=True)
class SetOperation(Node):
    op: str                        # union|intersect|except
    distinct: bool                 # False = ALL
    left: Node
    right: Node
    order_by: Tuple[SortItem, ...] = ()
    limit: Optional[int] = None


# ---------------------------------------------------------------------------
# Statements beyond queries (reference sql/tree/Statement.java subclasses)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Explain(Node):
    statement: Node
    analyze: bool = False
    type: str = "logical"          # logical|distributed|validate|io
    format: str = "text"           # text|json|graphviz


@dataclasses.dataclass(frozen=True)
class ShowTables(Node):
    schema: Optional[Tuple[str, ...]] = None


@dataclasses.dataclass(frozen=True)
class ShowColumns(Node):
    table: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class ShowCatalogs(Node):
    pass


@dataclasses.dataclass(frozen=True)
class ShowSession(Node):
    pass


@dataclasses.dataclass(frozen=True)
class SetSession(Node):
    name: str
    value: Expression


@dataclasses.dataclass(frozen=True)
class ResetSession(Node):
    name: str


@dataclasses.dataclass(frozen=True)
class StartTransaction(Node):
    isolation: str = "READ COMMITTED"
    read_only: bool = False


@dataclasses.dataclass(frozen=True)
class Commit(Node):
    pass


@dataclasses.dataclass(frozen=True)
class Rollback(Node):
    pass


@dataclasses.dataclass(frozen=True)
class CreateTableAsSelect(Node):
    name: Tuple[str, ...]
    query: Query
    if_not_exists: bool = False
    #: WITH (k = v, ...) table properties (reference
    #: sql/tree/CreateTableAsSelect.java properties; e.g. partitioned_by)
    properties: Tuple[Tuple[str, object], ...] = ()


@dataclasses.dataclass(frozen=True)
class DropTable(Node):
    name: Tuple[str, ...]
    if_exists: bool = False


@dataclasses.dataclass(frozen=True)
class CreateView(Node):
    """CREATE [OR REPLACE] VIEW name AS query (reference
    sql/tree/CreateView.java; the parsed query is the stored
    ConnectorViewDefinition analogue)."""
    name: Tuple[str, ...]
    query: "Query"
    or_replace: bool = False


@dataclasses.dataclass(frozen=True)
class DropView(Node):
    name: Tuple[str, ...]
    if_exists: bool = False


@dataclasses.dataclass(frozen=True)
class Prepare(Node):
    """PREPARE name FROM statement (reference sql/tree/Prepare.java)."""
    name: str
    statement: Node


@dataclasses.dataclass(frozen=True)
class ExecuteStmt(Node):
    """EXECUTE name [USING expr, ...] (reference sql/tree/Execute.java)."""
    name: str
    args: Tuple[Expression, ...] = ()


@dataclasses.dataclass(frozen=True)
class Deallocate(Node):
    name: str


@dataclasses.dataclass(frozen=True)
class DescribeOutput(Node):
    name: str


@dataclasses.dataclass(frozen=True)
class DescribeInput(Node):
    name: str


# ---------------------------------------------------------------------------
# Prepared-statement parameter binding (reference
# sql/planner/ParameterRewriter.java over sql/tree nodes)
# ---------------------------------------------------------------------------

def substitute_parameters(node, values):
    """Replace Parameter(i) nodes with the i-th bound expression,
    rebuilding the immutable AST."""
    def walk(n):
        if isinstance(n, Parameter):
            if n.index >= len(values):
                raise ValueError(
                    "Incorrect number of parameters: expected at least "
                    f"{n.index + 1} but found {len(values)}")
            return values[n.index]
        if dataclasses.is_dataclass(n) and not isinstance(n, type):
            changes = {}
            for f in dataclasses.fields(n):
                v = getattr(n, f.name)
                nv = walk(v)
                if nv is not v:
                    changes[f.name] = nv
            return dataclasses.replace(n, **changes) if changes else n
        if isinstance(n, tuple):
            out = tuple(walk(x) for x in n)
            return out if any(a is not b for a, b in zip(out, n)) else n
        if isinstance(n, list):
            return [walk(x) for x in n]
        return n
    return walk(node)


def count_parameters(node) -> int:
    """Highest parameter ordinal + 1 in a statement AST."""
    best = 0

    def walk(n):
        nonlocal best
        if isinstance(n, Parameter):
            best = max(best, n.index + 1)
        if dataclasses.is_dataclass(n) and not isinstance(n, type):
            for f in dataclasses.fields(n):
                walk(getattr(n, f.name))
        elif isinstance(n, (tuple, list)):
            for x in n:
                walk(x)
    walk(node)
    return best


@dataclasses.dataclass(frozen=True)
class InsertInto(Node):
    name: Tuple[str, ...]
    query: Query
    columns: Tuple[str, ...] = ()


# -- roles & privileges (reference sql/tree/CreateRole.java, Grant.java,
# -- Revoke.java, SetRole.java, ShowGrants.java; spi/security/RoleGrant)


@dataclasses.dataclass(frozen=True)
class CreateRole(Node):
    name: str


@dataclasses.dataclass(frozen=True)
class DropRole(Node):
    name: str


@dataclasses.dataclass(frozen=True)
class GrantRoles(Node):
    roles: Tuple[str, ...]
    grantees: Tuple[str, ...]
    admin_option: bool = False


@dataclasses.dataclass(frozen=True)
class RevokeRoles(Node):
    roles: Tuple[str, ...]
    grantees: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class GrantPrivileges(Node):
    privileges: Tuple[str, ...]          # SELECT/INSERT/DELETE or ALL
    table: Tuple[str, ...]
    grantee: str
    grant_option: bool = False


@dataclasses.dataclass(frozen=True)
class RevokePrivileges(Node):
    privileges: Tuple[str, ...]
    table: Tuple[str, ...]
    grantee: str


@dataclasses.dataclass(frozen=True)
class SetRole(Node):
    role: str                            # a role name, or ALL / NONE


@dataclasses.dataclass(frozen=True)
class ShowRoles(Node):
    pass


@dataclasses.dataclass(frozen=True)
class ShowGrants(Node):
    table: Tuple[str, ...] = ()

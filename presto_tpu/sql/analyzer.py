"""Name/type resolution: AST expressions -> typed kernel IR.

Conceptual parity with the reference's ExpressionAnalyzer + scope machinery
(reference presto-main/.../sql/analyzer/ExpressionAnalyzer.java, Scope.java,
and the AST->RowExpression lowering in sql/relational/SqlToRowExpression-
Translator.java) collapsed into one pass: resolving a column yields its
input index, inferring a type yields the IR node, so analysis produces the
compile-ready expression directly.

Aggregate calls are NOT handled here — the query planner rewrites them to
input references before lowering (reference sql/analyzer/
AggregationAnalyzer.java + planner/QueryPlanner.java split).
"""
from __future__ import annotations

import dataclasses
import math
from decimal import Decimal
from typing import Dict, List, Optional, Sequence, Tuple

from .. import types as T
from ..expr import ir
from ..expr.functions import infer_call_type
from . import ast as A
from .lexer import SqlSyntaxError


class AnalysisError(ValueError):
    pass


class UnresolvedColumnError(AnalysisError):
    """A name did not resolve in any visible scope — the signal the
    planner's decorrelation uses to distinguish a correlated subquery
    from one that fails for unrelated reasons."""


AGGREGATE_FUNCTIONS = frozenset(
    ["count", "sum", "avg", "min", "max", "stddev", "stddev_samp",
     "stddev_pop", "variance", "var_samp", "var_pop", "approx_distinct",
     "any_value", "arbitrary", "bool_and", "bool_or",
     "approx_percentile"])

# SQL surface name -> kernel registry name
_FUNCTION_ALIASES = {
    "substring": "substr", "mod": "modulus", "pow": "power",
    "ceiling": "ceil", "char_length": "length",
    "stddev": "stddev_samp", "variance": "var_samp",
    "var": "var_samp", "every": "bool_and",
    "dow": "day_of_week", "doy": "day_of_year",
    "day_of_month": "day",
    "week_of_year": "week", "yow": "year_of_week",
}

#: zero-argument functions folded to literals at analysis time
_NILADIC = {
    "pi": (math.pi, T.DOUBLE),
    "e": (math.e, T.DOUBLE),
    "nan": (float("nan"), T.DOUBLE),
    "infinity": (float("inf"), T.DOUBLE),
}

_ARITH_OPS = {"+": "add", "-": "subtract", "*": "multiply", "/": "divide",
              "%": "modulus"}
_CMP_OPS = {"=": "eq", "<>": "ne", "<": "lt", "<=": "le", ">": "gt",
            ">=": "ge"}


@dataclasses.dataclass(frozen=True)
class Field:
    """One resolvable output column of a relation (reference
    sql/analyzer/Field.java): name plus originating relation alias."""

    name: str
    type: T.Type
    relation: Optional[str] = None   # alias or table name, lowercased


class Scope:
    """Visible fields during expression analysis (reference Scope.java).

    Resolution is positional: a resolved column is its index in the
    underlying relation's output — the IR InputRef index.
    """

    def __init__(self, fields: Sequence[Field],
                 parent: Optional["Scope"] = None):
        self.fields: Tuple[Field, ...] = tuple(fields)
        self.parent = parent

    def resolve(self, name: str, qualifier: Optional[str] = None) -> int:
        matches = [
            i for i, f in enumerate(self.fields)
            if f.name == name and (qualifier is None or f.relation == qualifier)
        ]
        if not matches:
            # identifiers match case-insensitively (the reference engine
            # lowercases unquoted identifiers and resolves quoted ones
            # case-insensitively too — its own TPC-DS SQL aliases "YEAR"
            # and references "year")
            low = name.lower()
            lq = qualifier.lower() if qualifier else None
            matches = [
                i for i, f in enumerate(self.fields)
                if f.name.lower() == low
                and (lq is None or (f.relation or "").lower() == lq)
            ]
        if len(matches) == 1:
            return matches[0]
        if len(matches) > 1:
            raise AnalysisError(f"column {name!r} is ambiguous")
        if self.parent is not None:
            # correlated reference into an outer query — not yet planned
            try:
                self.parent.resolve(name, qualifier)
            except AnalysisError:
                pass
            else:
                raise UnresolvedColumnError(
                    f"correlated reference to outer column {name!r} is not "
                    "supported yet")
        q = f"{qualifier}." if qualifier else ""
        raise UnresolvedColumnError(f"column {q}{name} cannot be resolved")

    def field(self, index: int) -> Field:
        return self.fields[index]

    def __len__(self) -> int:
        return len(self.fields)


def literal_type(node: A.Expression) -> T.Type:
    if isinstance(node, A.LongLiteral):
        return T.BIGINT
    if isinstance(node, A.DecimalLiteral):
        d = node.value.as_tuple()
        scale = max(0, -int(d.exponent))
        precision = max(len(d.digits), scale)
        # literals past 38 digits would silently round; refuse like the
        # reference parser (Decimals.parse overflow)
        if precision > 38:
            raise AnalysisError(
                f"DECIMAL literal exceeds 38 digits: {node.value}")
        return T.DecimalType(precision, scale)
    if isinstance(node, A.DoubleLiteral):
        return T.DOUBLE
    if isinstance(node, A.StringLiteral):
        return T.VarcharType(len(node.value))
    if isinstance(node, A.BooleanLiteral):
        return T.BOOLEAN
    if isinstance(node, A.DateLiteral):
        return T.DATE
    if isinstance(node, A.NullLiteral):
        return T.UNKNOWN
    raise AnalysisError(f"not a literal: {node}")


def coerce(e: ir.Expr, to: T.Type) -> ir.Expr:
    if e.type == to:
        return e
    if isinstance(e, ir.Literal):
        # fold literal casts at analysis time (constant folding, reference
        # sql/planner/ExpressionInterpreter.java role)
        v = e.value
        if v is None:
            return ir.lit(None, to)
        if isinstance(to, (T.DoubleType, T.RealType)):
            return ir.lit(float(v), to)
        if T.is_integral(to):
            # Presto integral casts round half-up and range-check; an
            # out-of-range constant falls through to the runtime cast,
            # which raises through the row error channel
            import decimal as _d
            with _d.localcontext() as ctx:
                ctx.prec = 60
                iv = int(Decimal(str(v)).quantize(
                    0, rounding=_d.ROUND_HALF_UP))
            bits = {"tinyint": 7, "smallint": 15, "integer": 31,
                    "bigint": 63}[to.name]
            if -(1 << bits) <= iv < (1 << bits):
                return ir.lit(iv, to)
            return ir.cast(e, to)
        if isinstance(to, T.DecimalType):
            if abs(Decimal(str(v))) < Decimal(10) ** (to.precision - to.scale):
                return ir.lit(Decimal(str(v)), to)
            return ir.cast(e, to)
        if isinstance(to, (T.VarcharType, T.CharType)):
            return ir.lit(str(v), to)
    return ir.cast(e, to)


def unify(a: ir.Expr, b: ir.Expr) -> Tuple[ir.Expr, ir.Expr, T.Type]:
    t = T.common_super_type(a.type, b.type)
    if t is None:
        raise AnalysisError(
            f"cannot compare/combine {a.type.display()} and {b.type.display()}")
    return coerce(a, t), coerce(b, t), t


class ExpressionAnalyzer:
    """Lowers one AST expression against a scope.

    ``replacements`` maps AST subtrees (by structural equality) to
    pre-computed input references — how the planner routes aggregate
    results and group keys through post-aggregation expressions.
    """

    def __init__(self, scope: Scope,
                 replacements: Optional[Dict[A.Expression, ir.Expr]] = None):
        self.scope = scope
        self.replacements = replacements or {}
        # innermost-last stack of {param_name: (position, type)} frames
        # for lambda bodies (reference analyzer LambdaArgumentDeclaration)
        self.lambda_scopes: List[Dict[str, Tuple[int, T.Type]]] = []

    def analyze(self, node: A.Expression) -> ir.Expr:
        hit = self.replacements.get(node)
        if hit is not None:
            return hit
        m = getattr(self, "_" + type(node).__name__, None)
        if m is None:
            raise AnalysisError(f"unsupported expression {type(node).__name__}")
        return m(node)

    # -- leaves --------------------------------------------------------------
    def _Identifier(self, node: A.Identifier) -> ir.Expr:
        low = node.name.lower()
        for lvl in range(len(self.lambda_scopes) - 1, -1, -1):
            frame = self.lambda_scopes[lvl]
            if low in frame:
                pos, typ = frame[low]
                return ir.LambdaRef(type=typ, index=pos, level=lvl)
        idx = self.scope.resolve(node.name)
        return ir.input_ref(idx, self.scope.field(idx).type)

    def _DereferenceExpression(self, node: A.DereferenceExpression) -> ir.Expr:
        if not isinstance(node.base, A.Identifier):
            raise AnalysisError("only table.column dereference is supported")
        idx = self.scope.resolve(node.field.name, node.base.name)
        return ir.input_ref(idx, self.scope.field(idx).type)

    def _NullLiteral(self, node):
        return ir.lit(None, T.UNKNOWN)

    def _BooleanLiteral(self, node):
        return ir.lit(node.value, T.BOOLEAN)

    def _LongLiteral(self, node):
        return ir.lit(node.value, T.BIGINT)

    def _DecimalLiteral(self, node):
        return ir.lit(node.value, literal_type(node))

    def _DoubleLiteral(self, node):
        return ir.lit(node.value, T.DOUBLE)

    def _StringLiteral(self, node):
        return ir.lit(node.value, T.VarcharType(len(node.value)))

    def _DateLiteral(self, node):
        return ir.lit(node.value, T.DATE)

    # -- slot-marked literals (plan templates, serving/template.py):
    # -- lowered to runtime-bound parameters instead of baked constants.
    # -- Types match the plain literal forms exactly, and are value-
    # -- independent for every parameterizable kind (a DecimalLiteral's
    # -- inferred precision/scale is part of the template key).
    def _SlotLongLiteral(self, node):
        return ir.param(node.slot, node.value, T.BIGINT)

    def _SlotDoubleLiteral(self, node):
        return ir.param(node.slot, node.value, T.DOUBLE)

    def _SlotDecimalLiteral(self, node):
        return ir.param(node.slot, node.value, literal_type(node))

    def _SlotDateLiteral(self, node):
        return ir.param(node.slot, node.value, T.DATE)

    def _IntervalLiteral(self, node):
        raise AnalysisError(
            "interval literal only supported in date +/- interval")

    # -- operators -----------------------------------------------------------
    def _ArithmeticBinary(self, node: A.ArithmeticBinary) -> ir.Expr:
        # date +/- interval  ->  date_add_*
        if isinstance(node.right, A.IntervalLiteral) and node.op in "+-":
            left = self.analyze(node.left)
            iv = node.right
            amount = int(iv.value) * iv.sign * (1 if node.op == "+" else -1)
            unit_fn = {"day": "date_add_days", "month": "date_add_months",
                       "year": "date_add_years"}.get(iv.unit)
            if unit_fn is None or not isinstance(left.type, (T.DateType, T.TimestampType)):
                raise AnalysisError(f"unsupported interval arithmetic {iv}")
            return ir.call(unit_fn, left.type, left,
                           ir.lit(amount, T.BIGINT))
        left = self.analyze(node.left)
        right = self.analyze(node.right)
        name = _ARITH_OPS[node.op]
        out = infer_call_type(name, [left.type, right.type])
        # operands coerce toward the output domain (decimal args keep their
        # scales: the kernel handles rescaling; float args widen)
        if not isinstance(out, T.DecimalType):
            left, right = coerce(left, out), coerce(right, out)
        return ir.call(name, out, left, right)

    def _ArithmeticUnary(self, node: A.ArithmeticUnary) -> ir.Expr:
        v = self.analyze(node.value)
        if node.op == "+":
            return v
        return ir.call("negate", v.type, v)

    def _Comparison(self, node: A.Comparison) -> ir.Expr:
        left = self.analyze(node.left)
        right = self.analyze(node.right)
        left, right, _ = unify(left, right)
        return ir.call(_CMP_OPS[node.op], T.BOOLEAN, left, right)

    def _LogicalBinary(self, node: A.LogicalBinary) -> ir.Expr:
        # flatten chains into one n-ary special form
        form = ir.Form.AND if node.op == "and" else ir.Form.OR
        args: List[ir.Expr] = []

        def walk(n: A.Expression):
            if isinstance(n, A.LogicalBinary) and n.op == node.op:
                walk(n.left)
                walk(n.right)
            else:
                args.append(self._to_bool(self.analyze(n)))
        walk(node)
        return ir.special(form, T.BOOLEAN, *args)

    def _to_bool(self, e: ir.Expr) -> ir.Expr:
        if not isinstance(e.type, T.BooleanType):
            raise AnalysisError(
                f"expected boolean, got {e.type.display()}")
        return e

    def _Not(self, node: A.Not) -> ir.Expr:
        return ir.call("not", T.BOOLEAN, self._to_bool(self.analyze(node.value)))

    def _Between(self, node: A.Between) -> ir.Expr:
        v = self.analyze(node.value)
        lo = self.analyze(node.min)
        hi = self.analyze(node.max)
        v1, lo, _ = unify(v, lo)
        v2, hi, _ = unify(v, hi)
        # coerce v to the wider of both unifications
        v = v1 if v1.type == v2.type else (
            v1 if T.common_super_type(v1.type, v2.type) == v1.type else v2)
        lo = coerce(lo, v.type)
        hi = coerce(hi, v.type)
        e = ir.special(ir.Form.BETWEEN, T.BOOLEAN, v, lo, hi)
        return ir.call("not", T.BOOLEAN, e) if node.negated else e

    def _InList(self, node: A.InList) -> ir.Expr:
        v = self.analyze(node.value)
        items = [self.analyze(i) for i in node.items]
        for i, it in enumerate(items):
            v2, it2, _ = unify(v, it)
            v, items[i] = v2, it2
        items = [coerce(it, v.type) for it in items]
        e = ir.special(ir.Form.IN, T.BOOLEAN, v, *items)
        return ir.call("not", T.BOOLEAN, e) if node.negated else e

    def _Like(self, node: A.Like) -> ir.Expr:
        v = self.analyze(node.value)
        if not isinstance(node.pattern, A.StringLiteral):
            raise AnalysisError("LIKE pattern must be a string literal")
        escape = None
        if node.escape is not None:
            if not isinstance(node.escape, A.StringLiteral):
                raise AnalysisError("LIKE escape must be a string literal")
            escape = node.escape.value
        pat = ir.lit(node.pattern.value, T.VarcharType(len(node.pattern.value)))
        args = [v, pat]
        if escape is not None:
            args.append(ir.lit(escape, T.VarcharType(len(escape))))
        e = ir.call("like", T.BOOLEAN, *args)
        return ir.call("not", T.BOOLEAN, e) if node.negated else e

    def _IsNull(self, node: A.IsNull) -> ir.Expr:
        e = ir.special(ir.Form.IS_NULL, T.BOOLEAN, self.analyze(node.value))
        return ir.call("not", T.BOOLEAN, e) if node.negated else e

    def _Cast(self, node: A.Cast) -> ir.Expr:
        v = self.analyze(node.value)
        to = T.parse_type(node.type_name)
        return coerce(v, to)

    def _Extract(self, node: A.Extract) -> ir.Expr:
        v = self.analyze(node.value)
        field = node.field.lower()
        field = {"dow": "day_of_week", "doy": "day_of_year",
                 "yow": "year_of_week"}.get(field, field)
        if field not in ("year", "month", "day", "quarter", "day_of_week",
                         "day_of_year", "week", "year_of_week", "hour",
                         "minute", "second", "millisecond"):
            raise AnalysisError(f"EXTRACT({field}) not supported")
        return ir.call(field, T.BIGINT, v)

    def _WhenList(self, whens, default, operand=None):
        args: List[ir.Expr] = []
        results: List[ir.Expr] = []
        conds: List[ir.Expr] = []
        for w in whens:
            if operand is not None:
                op_e = self.analyze(operand)
                val_e = self.analyze(w.condition)
                a, b, _ = unify(op_e, val_e)
                conds.append(ir.call("eq", T.BOOLEAN, a, b))
            else:
                conds.append(self._to_bool(self.analyze(w.condition)))
            results.append(self.analyze(w.result))
        d = self.analyze(default) if default is not None else ir.lit(None, T.UNKNOWN)
        out_t = d.type
        for r in results:
            t = T.common_super_type(out_t, r.type)
            if t is None:
                raise AnalysisError("CASE branches have incompatible types")
            out_t = t
        results = [coerce(r, out_t) for r in results]
        d = coerce(d, out_t)
        for c, r in zip(conds, results):
            args.extend([c, r])
        args.append(d)
        return ir.special(ir.Form.SWITCH, out_t, *args)

    def _SearchedCase(self, node: A.SearchedCase) -> ir.Expr:
        return self._WhenList(node.whens, node.default)

    def _SimpleCase(self, node: A.SimpleCase) -> ir.Expr:
        return self._WhenList(node.whens, node.default, operand=node.operand)

    def _Coalesce(self, node: A.Coalesce) -> ir.Expr:
        args = [self.analyze(a) for a in node.args]
        out_t = args[0].type
        for a in args[1:]:
            t = T.common_super_type(out_t, a.type)
            if t is None:
                raise AnalysisError("COALESCE args have incompatible types")
            out_t = t
        args = [coerce(a, out_t) for a in args]
        return ir.special(ir.Form.COALESCE, out_t, *args)

    def _NullIf(self, node: A.NullIf) -> ir.Expr:
        a = self.analyze(node.first)
        b = self.analyze(node.second)
        a2, b2, _ = unify(a, b)
        return ir.special(ir.Form.NULL_IF, a.type, a2, b2)

    def _FunctionCall(self, node: A.FunctionCall) -> ir.Expr:
        name = _FUNCTION_ALIASES.get(node.name, node.name)
        if name in _NILADIC and not node.args:
            value, typ = _NILADIC[name]
            return ir.lit(value, typ)
        if name == "parse_timestamp_literal":
            # TIMESTAMP '...' — folded to a literal here
            s = node.args[0]
            if not isinstance(s, A.StringLiteral):
                raise AnalysisError("TIMESTAMP literal must be a string")
            T.TIMESTAMP.to_storage(s.value)    # validate now
            return ir.lit(s.value, T.TIMESTAMP)
        if name == "try":
            # TRY(expr): row-level evaluation errors become NULL
            # (reference operator/scalar/TryFunction.java)
            if len(node.args) != 1:
                raise AnalysisError("try() takes exactly one argument")
            arg = self.analyze(node.args[0])
            return ir.special(ir.Form.TRY, arg.type, arg)
        if name == "if":
            # IF(cond, then [, else]) function spelling of CASE
            if len(node.args) not in (2, 3):
                raise AnalysisError("if() takes two or three arguments")
            cond = self._to_bool(self.analyze(node.args[0]))
            then = self.analyze(node.args[1])
            els = (self.analyze(node.args[2]) if len(node.args) == 3
                   else ir.lit(None, then.type))
            out_t = T.common_super_type(then.type, els.type)
            if out_t is None:
                raise AnalysisError("IF branches have incompatible types")
            return ir.special(ir.Form.IF, out_t, cond,
                              coerce(then, out_t), coerce(els, out_t))
        if name in AGGREGATE_FUNCTIONS:
            raise AnalysisError(
                f"aggregate function {name}() in scalar context (missing "
                "GROUP BY rewrite?)")
        if name in ("transform", "filter", "reduce", "any_match",
                    "all_match", "none_match") \
                and node.args and any(isinstance(a, A.Lambda)
                                      for a in node.args):
            return self._higher_order(name, node)
        args = [self.analyze(a) for a in node.args]
        array_t = self._array_fn_type(name, args)
        if array_t is not None:
            fn = "array_concat" if (name == "concat" and
                                    isinstance(args[0].type, T.ArrayType)) \
                else name
            return ir.call(fn, array_t, *args)
        try:
            out = infer_call_type(name, [a.type for a in args])
        except KeyError:
            raise AnalysisError(f"unknown function {node.name!r}")
        return ir.call(name, out, *args)

    def _ArrayLiteral(self, node: A.ArrayLiteral) -> ir.Expr:
        if not node.items:
            raise AnalysisError("empty ARRAY[] literal needs a cast")
        items = [self.analyze(a) for a in node.items]
        el: T.Type = T.UNKNOWN
        for a in items:
            nxt = T.common_super_type(el, a.type)
            if nxt is None:
                raise AnalysisError("ARRAY elements have incompatible types")
            el = nxt
        items = [coerce(a, el) for a in items]
        return ir.call("array_constructor", T.ArrayType(el), *items)

    def _Subscript(self, node: A.Subscript) -> ir.Expr:
        base = self.analyze(node.base)
        idx = self.analyze(node.index)
        if isinstance(base.type, T.ArrayType):
            if not T.is_integral(idx.type):
                raise AnalysisError("array subscript must be an integer")
            return ir.call("subscript", base.type.element, base, idx)
        if isinstance(base.type, T.MapType):
            return ir.call("subscript", base.type.value, base,
                           coerce(idx, base.type.key))
        raise AnalysisError(
            f"cannot subscript {base.type.display()}")

    def _Lambda(self, node):
        raise AnalysisError(
            "lambda expressions are only valid as arguments of "
            "higher-order functions (transform, filter, reduce, ...)")

    def _analyze_lambda(self, lam: A.Lambda,
                        param_types: Sequence[T.Type]) -> ir.LambdaExpr:
        if len(lam.params) != len(param_types):
            raise AnalysisError(
                f"lambda takes {len(lam.params)} arguments, expected "
                f"{len(param_types)}")
        frame = {p.lower(): (i, t)
                 for i, (p, t) in enumerate(zip(lam.params, param_types))}
        self.lambda_scopes.append(frame)
        try:
            body = self.analyze(lam.body)
        finally:
            self.lambda_scopes.pop()
        return ir.LambdaExpr(type=body.type, body=body,
                             n_params=len(lam.params))

    def _higher_order(self, name: str, node: A.FunctionCall) -> ir.Expr:
        args = list(node.args)
        arr = self.analyze(args[0])
        if not isinstance(arr.type, T.ArrayType):
            raise AnalysisError(f"{name}() expects an array argument")
        et = arr.type.element
        if name == "reduce":
            if len(args) != 4:
                raise AnalysisError(
                    "reduce(array, init, (s, x) -> ..., s -> ...) "
                    "takes four arguments")
            init = self.analyze(args[1])
            if not isinstance(args[2], A.Lambda) \
                    or not isinstance(args[3], A.Lambda):
                raise AnalysisError("reduce() needs lambda arguments")
            step = self._analyze_lambda(args[2], [init.type, et])
            step_body = coerce(step.body, init.type)
            step = ir.LambdaExpr(type=init.type, body=step_body, n_params=2)
            out_lam = self._analyze_lambda(args[3], [init.type])
            return ir.call("reduce", out_lam.type, arr, init, step, out_lam)
        if len(args) != 2 or not isinstance(args[1], A.Lambda):
            raise AnalysisError(f"{name}(array, lambda) takes a lambda")
        lam = self._analyze_lambda(args[1], [et])
        if name == "transform":
            return ir.call(name, T.ArrayType(lam.type), arr, lam)
        if not isinstance(lam.type, T.BooleanType):
            raise AnalysisError(f"{name}() lambda must return boolean")
        if name == "filter":
            return ir.call(name, arr.type, arr, lam)
        return ir.call(name, T.BOOLEAN, arr, lam)

    def _array_fn_type(self, name: str,
                       args: List[ir.Expr]) -> Optional[T.Type]:
        """Structural return types for array/map builtins (these need the
        argument's element types, which name-only infer_call_type can't
        see)."""
        ts = [a.type for a in args]
        if name == "cardinality" and isinstance(ts[0], (T.ArrayType,
                                                        T.MapType)):
            return T.BIGINT
        if name == "element_at":
            if isinstance(ts[0], T.ArrayType):
                return ts[0].element
            if isinstance(ts[0], T.MapType):
                return ts[0].value
        if not any(isinstance(t, (T.ArrayType, T.MapType)) for t in ts) \
                and name not in ("repeat", "sequence", "split", "map"):
            return None
        if name == "contains":
            return T.BOOLEAN
        if name == "array_position":
            return T.BIGINT
        if name in ("array_min", "array_max"):
            return ts[0].element
        if name in ("array_distinct", "array_sort"):
            return ts[0]
        if name == "array_concat" or (name == "concat" and
                                      isinstance(ts[0], T.ArrayType)):
            out = ts[0]
            for t in ts[1:]:
                out = T.common_super_type(out, t)
                if out is None:
                    raise AnalysisError("cannot concat incompatible arrays")
            return out
        if name == "repeat" and len(ts) == 2:
            return T.ArrayType(ts[0])
        if name == "sequence":
            return T.ArrayType(T.BIGINT)
        if name == "split" and ts and ts[0].is_string:
            return T.ArrayType(T.VARCHAR)
        if name == "map" and len(ts) == 2 \
                and all(isinstance(t, T.ArrayType) for t in ts):
            return T.MapType(ts[0].element, ts[1].element)
        if name == "map_keys" and isinstance(ts[0], T.MapType):
            return T.ArrayType(ts[0].key)
        if name == "map_values" and isinstance(ts[0], T.MapType):
            return T.ArrayType(ts[0].value)
        return None

    def _Parameter(self, node):
        raise AnalysisError(
            "unbound ? parameter (only valid inside PREPARE; bind with "
            "EXECUTE ... USING)")

    def _ScalarSubquery(self, node):
        raise AnalysisError("scalar subquery must be planned (init plan)")

    def _InSubquery(self, node):
        raise AnalysisError("IN subquery must be planned (semi join)")

    def _Exists(self, node):
        raise AnalysisError("EXISTS must be planned (semi join)")

    def _WindowFunction(self, node):
        raise AnalysisError(
            "window function in invalid context (only SELECT items and "
            "ORDER BY may contain OVER)")

    def _Star(self, node):
        raise AnalysisError("* only allowed at the top of SELECT")

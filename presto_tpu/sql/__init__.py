from .parser import parse_statement  # noqa: F401

"""Iterative rule engine: memo + pattern DSL + the load-bearing rewrite
rules.

Conceptual parity with the reference's exploratory optimizer (reference
sql/planner/iterative/IterativeOptimizer.java, Memo.java, Rule.java,
pattern DSL presto-matching/.../matching/Pattern.java, rule catalog
sql/planner/iterative/rule/ — each rule below names the file it ports
the concept of). The memo stores one group per plan position; rules fire
over groups to a fixpoint with an exploration budget, so rewrites
compose across levels without manual pass ordering — the property the
round-2 fixed pipeline could not express.

Rules here are the simplify/merge/push family; field order of every
rewritten node is preserved, so parent expressions never need remapping.
"""
from __future__ import annotations

import dataclasses
import itertools
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Type

from ..expr import ir
from ..expr.rewrite import (
    combine_conjuncts, conjuncts, referenced_inputs, remap_inputs,
)
from .plan import (
    DistinctNode, FilterNode, LimitNode, PlanNode, ProjectNode, SortNode,
    TopNNode, UnionNode, ValuesNode,
)

MAX_ITERATIONS = 100


# -- pattern DSL (the presto-matching role) ---------------------------------

@dataclasses.dataclass(frozen=True)
class Pattern:
    """Structural matcher: node class + optional predicate + child
    patterns (by position for single-child chains)."""

    node_type: type
    where: Optional[Callable[[PlanNode], bool]] = None
    child: Optional["Pattern"] = None

    def matches(self, node: PlanNode) -> bool:
        if not isinstance(node, self.node_type):
            return False
        if self.where is not None and not self.where(node):
            return False
        if self.child is not None:
            kids = node.children
            if len(kids) != 1 or not self.child.matches(kids[0]):
                return False
        return True


def pattern(node_type: type, *, where=None, child: Optional[Pattern] = None
            ) -> Pattern:
    return Pattern(node_type, where, child)


class Rule:
    """One rewrite (reference iterative/Rule.java): fires when ``pattern``
    matches; ``apply`` returns the replacement or None to decline."""

    pattern: Pattern

    def apply(self, node: PlanNode, lookup) -> Optional[PlanNode]:
        """``lookup`` resolves a _GroupRef child to its current node
        (reference iterative/Lookup.java)."""
        raise NotImplementedError


# -- memo -------------------------------------------------------------------

class Memo:
    """Group table (reference iterative/Memo.java): each plan position
    becomes a group holding its current best expression; rewrites replace
    group contents without touching parents (children are referenced by
    group id)."""

    def __init__(self, root: PlanNode):
        self._groups: Dict[int, PlanNode] = {}
        self._next = itertools.count()
        self.root_group = self._insert(root)

    def _insert(self, node: PlanNode) -> int:
        if isinstance(node, _GroupRef):
            return node.gid
        gid = next(self._next)
        kids = tuple(self._insert(c) for c in node.children)
        self._groups[gid] = _GroupRef.strip(node, kids)
        return gid

    def node(self, gid: int) -> PlanNode:
        return self._groups[gid]

    def replace(self, gid: int, node: PlanNode) -> None:
        """Replace a group's expression; new children become new groups."""
        kids = tuple(self._insert(c) if not isinstance(c, _GroupRef)
                     else c.gid for c in node.children)
        self._groups[gid] = _GroupRef.strip(node, kids)

    def extract(self, gid: Optional[int] = None) -> PlanNode:
        node = self._groups[self.root_group if gid is None else gid]
        return self._resolve(node)

    def _resolve(self, node: PlanNode) -> PlanNode:
        kids = [self._resolve(self._groups[c.gid])
                if isinstance(c, _GroupRef) else self._resolve(c)
                for c in node.children]
        return node.with_children(kids) if kids else node

    def groups(self) -> List[int]:
        return list(self._groups)


@dataclasses.dataclass(frozen=True)
class _GroupRef(PlanNode):
    """Leaf standing for a memo group (reference iterative/GroupReference
    .java)."""

    gid: int = -1
    fields: Tuple = ()

    @property
    def children(self) -> Tuple[PlanNode, ...]:
        return ()

    @staticmethod
    def strip(node: PlanNode, kid_gids: Tuple[int, ...]) -> PlanNode:
        if not node.children:
            return node
        refs = [_GroupRef(gid=g, fields=c.fields)
                for g, c in zip(kid_gids, node.children)]
        return node.with_children(refs)


class IterativeOptimizer:
    """Fixpoint driver (reference IterativeOptimizer.java:exploreGroup):
    resolve each group one level deep, offer it to every matching rule,
    and loop until no rule fires or the budget runs out."""

    def __init__(self, rules: Sequence[Rule]):
        self.rules = list(rules)

    def run(self, root: PlanNode) -> PlanNode:
        memo = Memo(root)
        for _ in range(MAX_ITERATIONS):
            fired = False
            for gid in memo.groups():
                node = memo.node(gid)
                if isinstance(node, _GroupRef):
                    continue
                # rules see children one level deep (resolved)
                shallow = node.with_children([
                    memo.node(c.gid) if isinstance(c, _GroupRef) else c
                    for c in node.children]) if node.children else node
                def lookup(n: PlanNode) -> PlanNode:
                    return (memo.node(n.gid)
                            if isinstance(n, _GroupRef) else n)

                for rule in self.rules:
                    if not rule.pattern.matches(shallow):
                        continue
                    out = rule.apply(shallow, lookup)
                    if out is not None and out is not shallow:
                        memo.replace(gid, out)
                        fired = True
                        break
            if not fired:
                break
        return memo.extract()


# -- rule catalog -----------------------------------------------------------

def _empty(fields) -> ValuesNode:
    return ValuesNode(fields=tuple(fields), rows=())


class MergeLimits(Rule):
    """Limit(a, Limit(b, x)) -> Limit(min(a,b), x) (reference
    iterative/rule/MergeLimits.java)."""

    pattern = pattern(LimitNode, child=pattern(LimitNode))

    def apply(self, node: LimitNode, lookup):
        inner: LimitNode = node.child
        return LimitNode(child=inner.child,
                         count=min(node.count, inner.count),
                         fields=node.fields)


class MergeLimitWithSort(Rule):
    """Limit(n, Sort(x)) -> TopN(n, x) (reference
    iterative/rule/MergeLimitWithSort.java)."""

    pattern = pattern(LimitNode, child=pattern(SortNode))

    def apply(self, node: LimitNode, lookup):
        inner: SortNode = node.child
        return TopNNode(child=inner.child, keys=inner.keys,
                        count=node.count, fields=node.fields)


class MergeLimitWithTopN(Rule):
    """Limit(a, TopN(b, x)) -> TopN(min(a,b), x) (reference
    iterative/rule/MergeLimitWithTopN.java)."""

    pattern = pattern(LimitNode, child=pattern(TopNNode))

    def apply(self, node: LimitNode, lookup):
        inner: TopNNode = node.child
        return TopNNode(child=inner.child, keys=inner.keys,
                        count=min(node.count, inner.count),
                        fields=node.fields)


class MergeLimitOverDistinct(Rule):
    """Limit(Distinct(Limit? ...)) stays; but Distinct(Distinct(x)) ->
    Distinct(x) (reference iterative/rule/RemoveRedundantDistinct
    shape)."""

    pattern = pattern(DistinctNode, child=pattern(DistinctNode))

    def apply(self, node: DistinctNode, lookup):
        return DistinctNode(child=node.child.child, fields=node.fields)


class EvaluateZeroLimit(Rule):
    """Limit(0, x) -> empty Values (reference
    iterative/rule/EvaluateEmptyIntersect / RemoveRedundant* family)."""

    pattern = pattern(LimitNode, where=lambda n: n.count == 0)

    def apply(self, node: LimitNode, lookup):
        return _empty(node.fields)


class EvaluateZeroTopN(Rule):
    pattern = pattern(TopNNode, where=lambda n: n.count == 0)

    def apply(self, node: TopNNode, lookup):
        return _empty(node.fields)


class MergeFilters(Rule):
    """Filter(p, Filter(q, x)) -> Filter(p AND q, x) (reference
    iterative/rule/MergeFilters.java)."""

    pattern = pattern(FilterNode, child=pattern(FilterNode))

    def apply(self, node: FilterNode, lookup):
        inner: FilterNode = node.child
        return FilterNode(
            child=inner.child,
            predicate=combine_conjuncts(
                conjuncts(inner.predicate) + conjuncts(node.predicate)),
            fields=node.fields)


def _is_true(e: ir.Expr) -> bool:
    return isinstance(e, ir.Literal) and e.value is True


def _is_false_or_null(e: ir.Expr) -> bool:
    return isinstance(e, ir.Literal) and (e.value is False
                                          or e.value is None)


class RemoveTrivialFilters(Rule):
    """Filter(true, x) -> x; Filter(false|null, x) -> empty (reference
    iterative/rule/RemoveTrivialFilters.java)."""

    pattern = pattern(FilterNode,
                      where=lambda n: _is_true(n.predicate)
                      or _is_false_or_null(n.predicate))

    def apply(self, node: FilterNode, lookup):
        if _is_true(node.predicate):
            return node.child
        return _empty(node.fields)


class PushLimitThroughProject(Rule):
    """Limit(Project(x)) -> Project(Limit(x)) (reference
    iterative/rule/PushLimitThroughProject.java)."""

    pattern = pattern(LimitNode, child=pattern(ProjectNode))

    def apply(self, node: LimitNode, lookup):
        proj: ProjectNode = node.child
        return ProjectNode(
            child=LimitNode(child=proj.child, count=node.count),
            exprs=proj.exprs, fields=proj.fields)


class PushLimitThroughUnion(Rule):
    """Limit(n, Union(a, b)) -> Limit(n, Union(Limit(n,a), Limit(n,b)))
    (reference iterative/rule/PushLimitThroughUnion.java). Guarded so it
    fires once (children not already limits)."""

    pattern = pattern(
        LimitNode,
        child=pattern(UnionNode, where=lambda u: not u.distinct))

    def apply(self, node: LimitNode, lookup):
        union: UnionNode = node.child
        resolved = [lookup(c) for c in union.children]
        if all(isinstance(rc, LimitNode) and rc.count <= node.count
               for rc in resolved):
            return None
        limited = tuple(
            c if isinstance(rc, LimitNode) and rc.count <= node.count
            else LimitNode(child=c, count=node.count)
            for c, rc in zip(union.children, resolved))
        return LimitNode(
            child=dataclasses.replace(union, children_=limited),
            count=node.count, fields=node.fields)


class LimitOverValues(Rule):
    """Limit(n, Values) -> Values[:n] (reference
    iterative/rule/EvaluateLimitOverValues shape)."""

    pattern = pattern(LimitNode, child=pattern(ValuesNode))

    def apply(self, node: LimitNode, lookup):
        vals: ValuesNode = node.child
        if len(vals.rows) <= node.count:
            return vals
        return ValuesNode(fields=vals.fields,
                          rows=vals.rows[:node.count])


def _identity_projection(node: ProjectNode) -> bool:
    if len(node.exprs) != len(node.child.fields):
        return False
    for i, e in enumerate(node.exprs):
        if not isinstance(e, ir.InputRef) or e.index != i:
            return False
        if node.fields[i].name != node.child.fields[i].name:
            return False
    return True


class RemoveRedundantIdentityProjection(Rule):
    """Project(identity, x) -> x (reference
    iterative/rule/RemoveRedundantIdentityProjections.java)."""

    pattern = pattern(ProjectNode, where=_identity_projection)

    def apply(self, node: ProjectNode, lookup):
        return node.child


def _inline_into(outer: ir.Expr, inner: Sequence[ir.Expr]) -> ir.Expr:
    from ..expr.rewrite import rewrite

    def repl(e: ir.Expr):
        if isinstance(e, ir.InputRef):
            return inner[e.index]
        return e

    return rewrite(outer, repl)


class InlineProjections(Rule):
    """Project(Project(x)) -> Project(x) when the inner exprs are cheap
    to inline (input refs / literals, or referenced once) (reference
    iterative/rule/InlineProjections.java)."""

    pattern = pattern(ProjectNode, child=pattern(ProjectNode))

    def apply(self, node: ProjectNode, lookup):
        inner: ProjectNode = node.child
        uses: Dict[int, int] = {}
        for e in node.exprs:
            for r in referenced_inputs(e):
                uses[r] = uses.get(r, 0) + 1
        for i, e in enumerate(inner.exprs):
            simple = isinstance(e, (ir.InputRef, ir.Literal))
            if not simple and uses.get(i, 0) > 1:
                return None          # would duplicate computation
        exprs = tuple(_inline_into(e, inner.exprs) for e in node.exprs)
        return ProjectNode(child=inner.child, exprs=exprs,
                           fields=node.fields)


class PushFilterThroughProject(Rule):
    """Filter(Project(x)) -> Project(Filter(x)) when the predicate
    rewrites through the projection (reference the PredicatePushDown
    visitor's project case; iterative/rule shape
    PushDownFilterThroughProject)."""

    pattern = pattern(FilterNode, child=pattern(ProjectNode))

    def apply(self, node: FilterNode, lookup):
        proj: ProjectNode = node.child
        # cost guard (same stance as InlineProjections): only push when
        # every projection expr the predicate references is trivial —
        # otherwise the expression would be evaluated twice
        for r in referenced_inputs(node.predicate):
            if not isinstance(proj.exprs[r], (ir.InputRef, ir.Literal)):
                return None
        pred = _inline_into(node.predicate, proj.exprs)
        return ProjectNode(
            child=FilterNode(child=proj.child, predicate=pred),
            exprs=proj.exprs, fields=proj.fields)


DEFAULT_RULES: Tuple[Rule, ...] = (
    MergeLimits(),
    MergeLimitWithSort(),
    MergeLimitWithTopN(),
    MergeLimitOverDistinct(),
    EvaluateZeroLimit(),
    EvaluateZeroTopN(),
    MergeFilters(),
    RemoveTrivialFilters(),
    PushLimitThroughProject(),
    PushLimitThroughUnion(),
    LimitOverValues(),
    RemoveRedundantIdentityProjection(),
    InlineProjections(),
    PushFilterThroughProject(),
)


def iterative_optimize(root: PlanNode,
                       rules: Sequence[Rule] = DEFAULT_RULES) -> PlanNode:
    return IterativeOptimizer(rules).run(root)

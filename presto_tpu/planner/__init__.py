from .plan import (  # noqa: F401
    AggregationNode, DistinctNode, FilterNode, JoinNode, LimitNode,
    OutputNode, PlanAgg, PlanNode, ProjectNode, SemiJoinNode, SortKeySpec,
    SortNode, TableScanNode, TopNNode, UnionNode, ValuesNode,
)
from .planner import plan_query  # noqa: F401

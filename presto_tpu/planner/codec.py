"""Plan (de)serialization for shipping fragments to workers.

The role of the reference's JSON-serialized PlanFragment (reference
presto-main/.../sql/planner/PlanFragment.java is Jackson-annotated and
travels in the TaskUpdateRequest body,
server/TaskUpdateRequest.java): every plan node, expression, and helper
is a frozen dataclass, so one generic walker covers the whole tree —
class name tag + encoded fields. Types round-trip through
``display()``/``parse_type``; sequences always decode to tuples (plan
fields are tuples by construction).
"""
from __future__ import annotations

import dataclasses
import datetime
import decimal
from typing import Any, Dict

from .. import types as T
from ..connectors.spi import Split, TableHandle
from ..expr import ir
from ..sql.analyzer import Field
from . import plan as plan_mod

_CLASSES: Dict[str, type] = {}
for _mod in (plan_mod, ir):
    for _name in dir(_mod):
        _obj = getattr(_mod, _name)
        if isinstance(_obj, type) and dataclasses.is_dataclass(_obj):
            _CLASSES[_obj.__name__] = _obj
_CLASSES["TableHandle"] = TableHandle
_CLASSES["Field"] = Field
_CLASSES["Split"] = Split


def _register_late() -> None:
    # planner imports this module's siblings; avoid the cycle by
    # resolving InitPlanRef on first use
    if "InitPlanRef" not in _CLASSES:
        from .planner import InitPlanRef
        _CLASSES["InitPlanRef"] = InitPlanRef


def encode(obj: Any) -> Any:
    """Plan tree -> JSON-able document."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, T.Type):
        return {"$t": obj.display()}
    if isinstance(obj, ir.Form):
        return {"$form": obj.value}
    if isinstance(obj, decimal.Decimal):
        return {"$dec": str(obj)}
    if isinstance(obj, datetime.datetime):
        return {"$ts": obj.isoformat()}
    if isinstance(obj, datetime.date):
        return {"$date": obj.isoformat()}
    if isinstance(obj, (tuple, list)):
        return [encode(v) for v in obj]
    if dataclasses.is_dataclass(obj):
        _register_late()
        cls = type(obj)
        if cls.__name__ not in _CLASSES:
            raise TypeError(f"unregistered plan class {cls.__name__}")
        doc = {"$": cls.__name__}
        for f in dataclasses.fields(obj):
            doc[f.name] = encode(getattr(obj, f.name))
        return doc
    raise TypeError(f"cannot encode {type(obj).__name__}: {obj!r}")


def decode(doc: Any) -> Any:
    """JSON-able document -> plan tree."""
    if doc is None or isinstance(doc, (bool, int, float, str)):
        return doc
    if isinstance(doc, list):
        return tuple(decode(v) for v in doc)
    if isinstance(doc, dict):
        if "$t" in doc:
            return T.parse_type(doc["$t"])
        if "$form" in doc:
            return ir.Form(doc["$form"])
        if "$dec" in doc:
            return decimal.Decimal(doc["$dec"])
        if "$ts" in doc:
            return datetime.datetime.fromisoformat(doc["$ts"])
        if "$date" in doc:
            return datetime.date.fromisoformat(doc["$date"])
        _register_late()
        cls = _CLASSES[doc["$"]]
        kwargs = {k: decode(v) for k, v in doc.items() if k != "$"}
        return cls(**kwargs)
    raise TypeError(f"cannot decode {doc!r}")

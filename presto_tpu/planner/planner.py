"""AST -> logical plan lowering.

Conceptual parity with the reference's LogicalPlanner / QueryPlanner /
RelationPlanner / SubqueryPlanner stack (reference presto-main/.../sql/
planner/LogicalPlanner.java:156, QueryPlanner.java, RelationPlanner.java,
SubqueryPlanner.java): relations become plan nodes, SELECT decomposes into
project/aggregate/filter/sort layers, and subqueries lower to semi joins
(IN/EXISTS) or init plans (uncorrelated scalar subqueries, executed before
the main plan like reference ExchangeClient-fed index lookups).
"""
from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, List, Optional, Sequence, Tuple

from .. import types as T
from ..connectors.spi import CatalogManager, TableHandle
from ..expr import ir
from ..sql import ast as A
from ..sql.analyzer import (
    AGGREGATE_FUNCTIONS, AnalysisError, ExpressionAnalyzer, Field, Scope,
    UnresolvedColumnError, _FUNCTION_ALIASES, coerce,
)
from .plan import (
    AggregationNode, DistinctNode, FilterNode, JoinNode, LimitNode,
    OutputNode, PlanAgg, PlanNode, ProjectNode, SemiJoinNode, SortKeySpec,
    SortNode, TableScanNode, TopNNode, UnionNode, ValuesNode,
)


@dataclasses.dataclass(frozen=True)
class InitPlanRef:
    """Placeholder literal value for an uncorrelated scalar subquery;
    the executor runs the init plan and substitutes the scalar."""

    index: int


@dataclasses.dataclass
class LogicalPlan:
    root: OutputNode
    init_plans: List[PlanNode]


@dataclasses.dataclass
class Session:
    """Query session context (reference Session.java essentials)."""

    catalogs: CatalogManager
    catalog: str = "tpch"
    schema: str = "default"
    properties: Dict[str, object] = dataclasses.field(default_factory=dict)
    # logical views: (catalog, schema, name) -> stored A.Query, expanded
    # at plan time (reference metadata views / ConnectorViewDefinition)
    views: Dict[Tuple[str, str, str], object] = dataclasses.field(
        default_factory=dict)
    # prepared statements: name -> statement AST (reference
    # Session.preparedStatements + PrepareTask)
    prepared: Dict[str, object] = dataclasses.field(default_factory=dict)
    # filled by the executor: memory.MemoryStats of the last query
    last_memory_stats: object = None
    # serving-plane context (serving/groups.QueryServingContext) set on
    # the per-query overlay by LocalRunner.execute when the query was
    # admitted through a resource group: carries the group path /
    # scheduling weight for the device scheduler and the group memory
    # account for the query pool
    serving: object = None
    # plan-template bindings {slot: value} set on the per-query overlay
    # when the plan came from serving/template.py: the executor opens an
    # expr/params binding scope around the drain so ir.Param kernels
    # read THIS query's literals as traced scalars
    param_bindings: Optional[Dict[int, object]] = None


def _schema_exists(session: "Session", schema: str) -> bool:
    """True when the session catalog exposes ``schema`` (or a view is
    registered under it) — the gate for reference-style schema-first
    two-part name resolution (ADVICE r5: a schema named like a mounted
    catalog must not be silently shadowed)."""
    try:
        conn = session.catalogs.get(session.catalog)
        if schema in conn.metadata.list_schemas():
            return True
    except Exception:
        pass
    return any(k[0] == session.catalog and k[1] == schema
               for k in session.views)


def bool_property(session: "Session", name: str, default: bool) -> bool:
    """Session properties arrive as strings from SET SESSION / HTTP
    headers; parse the usual spellings instead of trusting truthiness.
    Shared by the executor's and the optimizer's feature gates."""
    v = session.properties.get(name, default)
    if isinstance(v, str):
        return v.strip().lower() not in ("false", "0", "off", "no", "")
    return bool(v)


def _const_value(e: ir.Expr):
    """Evaluate a constant expression to its python value (VALUES cells,
    which may be arbitrary constant expressions: casts, arithmetic,
    ARRAY[...] constructors — reference ExpressionInterpreter's role)."""
    if isinstance(e, ir.Literal):
        return e.value
    import jax.numpy as jnp

    from ..batch import Batch, Column, Schema
    from ..errors import QueryError
    from ..expr.compiler import eval_expr
    from ..expr.functions import Val

    carrier = Val(jnp.ones(1, dtype=bool), jnp.ones(1, dtype=bool),
                  T.BOOLEAN)
    try:
        v = eval_expr(e, [carrier])
    except NotImplementedError as exc:
        # an engine limitation, not a user error — say so
        raise NotImplementedError(
            f"cannot evaluate VALUES cell {e!r}: {exc}")
    if v.err is not None:
        code = int(jnp.max(v.err))
        if code:
            raise QueryError(code)
    mask = jnp.ones(v.valid.shape[0], dtype=bool)
    b = Batch(Schema([("c", e.type)]),
              [Column(e.type, v.data, v.valid, v.dictionary)], mask)
    out = b.to_pylist()[0][0]
    # plan nodes are hashable dataclasses: array values ride as tuples
    return tuple(out) if isinstance(out, list) else out


def plan_query(query: A.Query, session: Session) -> LogicalPlan:
    planner = _Planner(session)
    root = planner.plan_root(query)
    return LogicalPlan(root, planner.init_plans)


class _Planner:
    def __init__(self, session: Session):
        self.session = session
        self.ctes: Dict[str, PlanNode] = {}
        self.init_plans: List[PlanNode] = []
        self._ids = itertools.count()
        self._view_stack: List[Tuple[str, str, str]] = []

    # -- entry ---------------------------------------------------------------
    def plan_root(self, query: A.Query) -> OutputNode:
        node = self.plan_query_node(query)
        if isinstance(node, OutputNode):
            return node
        return OutputNode(child=node, fields=node.fields)

    def plan_query_node(self, query: A.Query) -> PlanNode:
        saved = dict(self.ctes)
        try:
            for name, cte_q in query.with_:
                cte_plan = self.plan_query_node(cte_q)
                # alias fields with the CTE name
                self.ctes[name] = _realias(cte_plan, name)
            return self.plan_body(query.body)
        finally:
            self.ctes = saved

    def plan_body(self, body: A.Node) -> PlanNode:
        if isinstance(body, A.QuerySpecification):
            return self.plan_query_spec(body)
        if isinstance(body, A.SetOperation):
            return self.plan_set_op(body)
        if isinstance(body, A.Query):   # parenthesized query term
            return self.plan_query_node(body)
        if isinstance(body, A.ValuesQuery):
            return self.plan_values(body)
        raise AnalysisError(f"unsupported query body {type(body).__name__}")

    def plan_values(self, v: A.ValuesQuery) -> PlanNode:
        """VALUES rows -> ValuesNode: cells analyze in an empty scope and
        must fold to constants (reference sql/tree/Values.java + the
        analyzer's row-type derivation)."""
        if not v.rows:
            raise AnalysisError("VALUES needs at least one row")
        n_cols = len(v.rows[0])
        analyzer = ExpressionAnalyzer(Scope(()))
        cells: List[List[ir.Expr]] = []
        for row in v.rows:
            if len(row) != n_cols:
                raise AnalysisError("VALUES rows differ in arity")
            cells.append([analyzer.analyze(e) for e in row])
        col_types: List[T.Type] = []
        for c in range(n_cols):
            t: T.Type = T.UNKNOWN
            for row in cells:
                nxt = T.common_super_type(t, row[c].type)
                if nxt is None:
                    raise AnalysisError(
                        f"VALUES column {c + 1} has incompatible types")
                t = nxt
            col_types.append(t)
        out_rows = []
        for row in cells:
            vals = []
            for c in range(n_cols):
                vals.append(_const_value(coerce(row[c], col_types[c])))
            out_rows.append(tuple(vals))
        fields = tuple(Field(f"_col{c}", col_types[c])
                       for c in range(n_cols))
        return ValuesNode(fields=fields, rows=tuple(out_rows))

    def plan_set_op(self, op: A.SetOperation) -> PlanNode:
        left = self.plan_body(op.left)
        right = self.plan_body(op.right)
        if len(left.fields) != len(right.fields):
            raise AnalysisError(
                f"{op.op.upper()} inputs have different column counts")
        # coerce each side to common types
        out_fields = []
        for lf, rf in zip(left.fields, right.fields):
            t = T.common_super_type(lf.type, rf.type)
            if t is None:
                raise AnalysisError(
                    f"{op.op.upper()} column {lf.name}: incompatible types "
                    f"{lf.type.display()} vs {rf.type.display()}")
            out_fields.append(Field(lf.name, t))
        left = _coerce_to(left, [f.type for f in out_fields])
        right = _coerce_to(right, [f.type for f in out_fields])
        if op.op == "union":
            node: PlanNode = UnionNode(
                children_=(left, right), fields=tuple(out_fields),
                distinct=op.distinct)
            if op.distinct:
                node = DistinctNode(child=node)
        else:
            node = self._plan_intersect_except(op, left, right, out_fields)
        if op.order_by:
            scope = Scope(node.fields)
            keys = self._sort_keys(op.order_by, node, scope, {})
            if op.limit is not None:
                return TopNNode(child=node, keys=tuple(keys), count=op.limit)
            node = SortNode(child=node, keys=tuple(keys))
        if op.limit is not None:
            node = LimitNode(child=node, count=op.limit)
        return node

    def _plan_intersect_except(self, op: A.SetOperation, left: PlanNode,
                               right: PlanNode,
                               out_fields: List[Field]) -> PlanNode:
        """Lower INTERSECT/EXCEPT to union-all + marker aggregation
        (reference iterative/rule/ImplementIntersectAsUnion.java,
        ImplementExceptAsUnion.java): tag each source's rows with
        per-source presence markers, union, count markers per distinct
        row value, then keep rows by marker counts."""
        if not op.distinct:
            raise AnalysisError(
                f"{op.op.upper()} ALL is not supported")
        n = len(out_fields)
        m1 = Field("$m1", T.BIGINT)
        m2 = Field("$m2", T.BIGINT)

        def tagged(side: PlanNode, first: int) -> PlanNode:
            exprs = [ir.input_ref(i, f.type)
                     for i, f in enumerate(out_fields)]
            exprs.append(ir.lit(first, T.BIGINT))
            exprs.append(ir.lit(1 - first, T.BIGINT))
            return ProjectNode(child=side, exprs=tuple(exprs),
                               fields=tuple(out_fields) + (m1, m2))

        u = UnionNode(children_=(tagged(left, 1), tagged(right, 0)),
                      fields=tuple(out_fields) + (m1, m2), distinct=False)
        agg = AggregationNode(
            child=u, group_indices=tuple(range(n)),
            aggs=(PlanAgg("sum", n, T.BIGINT, "$c1"),
                  PlanAgg("sum", n + 1, T.BIGINT, "$c2")),
            fields=tuple(out_fields) + (Field("$c1", T.BIGINT),
                                        Field("$c2", T.BIGINT)))
        zero = ir.lit(0, T.BIGINT)
        in_left = ir.call("gt", T.BOOLEAN,
                          ir.input_ref(n, T.BIGINT), zero)
        if op.op == "intersect":
            in_right = ir.call("gt", T.BOOLEAN,
                               ir.input_ref(n + 1, T.BIGINT), zero)
        else:     # except
            in_right = ir.call("eq", T.BOOLEAN,
                               ir.input_ref(n + 1, T.BIGINT), zero)
        from ..expr.rewrite import combine_conjuncts
        filt = FilterNode(child=agg,
                          predicate=combine_conjuncts([in_left, in_right]))
        return ProjectNode(
            child=filt,
            exprs=tuple(ir.input_ref(i, f.type)
                        for i, f in enumerate(out_fields)),
            fields=tuple(out_fields))

    # -- relations -----------------------------------------------------------
    def plan_relation(self, rel: A.Relation) -> PlanNode:
        if isinstance(rel, A.Table):
            return self.plan_table(rel)
        if isinstance(rel, A.AliasedRelation):
            inner = self.plan_relation(rel.relation)
            return _realias(inner, rel.alias, rel.column_names)
        if isinstance(rel, A.SubqueryRelation):
            return self.plan_query_node(rel.query)
        if isinstance(rel, A.Join):
            return self.plan_join(rel)
        if isinstance(rel, A.Unnest):
            # standalone FROM UNNEST(...): expand over a one-row input
            return self.plan_unnest(
                ValuesNode(fields=(), rows=((),)), rel, None, ())
        raise AnalysisError(f"unsupported relation {type(rel).__name__}")

    def plan_unnest(self, left: PlanNode, un: A.Unnest,
                    alias: Optional[str],
                    col_names: Tuple[str, ...]) -> PlanNode:
        """Lateral UNNEST: expressions resolve against the relations to
        the LEFT in the FROM list (reference RelationPlanner.visitUnnest +
        plan/UnnestNode.java)."""
        from .plan import UnnestNode
        scope = Scope(left.fields)
        analyzer = ExpressionAnalyzer(scope)
        exprs = []
        elem_fields: List[Field] = []
        for i, e in enumerate(un.exprs):
            x = analyzer.analyze(e)
            if not isinstance(x.type, T.ArrayType):
                raise AnalysisError("UNNEST argument must be an array")
            exprs.append(x)
            name = col_names[len(elem_fields)] \
                if len(elem_fields) < len(col_names) else f"_unnest{i}"
            elem_fields.append(Field(name, x.type.element,
                                     relation=alias or ""))
        if un.ordinality:
            name = col_names[len(elem_fields)] \
                if len(elem_fields) < len(col_names) else "ordinality"
            elem_fields.append(Field(name, T.BIGINT, relation=alias or ""))
        fields = tuple(left.fields) + tuple(elem_fields)
        return UnnestNode(child=left, exprs=tuple(exprs),
                          ordinality=un.ordinality, fields=fields)

    def plan_table(self, rel: A.Table) -> PlanNode:
        name = rel.name
        if len(name) == 1 and name[0] in self.ctes:
            return self.ctes[name[0]]
        if len(name) == 1:
            catalog, schema, table = (self.session.catalog,
                                      self.session.schema, name[0])
        elif len(name) == 2:
            if (self.session.catalogs.exists(name[0])
                    and not _schema_exists(self.session, name[0])):
                # the qualifier names a mounted catalog AND no schema of
                # the session catalog shadows it: resolve catalog-first
                # (catalog.table in its default schema) — same rule as
                # the write path (_writable), so the same name reads and
                # writes one table
                catalog, schema, table = name[0], "default", name[1]
            else:
                # reference semantics (StatementAnalyzer name
                # resolution): x.y is schema x in the session catalog
                catalog, schema, table = (self.session.catalog, name[0],
                                          name[1])
        else:
            catalog, schema, table = name[-3], name[-2], name[-1]
        view_key = (catalog, schema, table)
        view = self.session.views.get(view_key)
        if view is not None:
            # view expansion (reference StatementAnalyzer view handling):
            # plan the stored query, alias columns under the view name
            if view_key in self._view_stack:
                raise AnalysisError(
                    f"view {'.'.join(view_key)} is recursive")
            self._view_stack.append(view_key)
            # the view body resolves names in ITS OWN scope: the caller's
            # WITH aliases must not capture tables inside the view
            outer_ctes, self.ctes = self.ctes, {}
            try:
                inner = self.plan_query_node(view)
            finally:
                self.ctes = outer_ctes
                self._view_stack.pop()
            return _realias(inner, table, ())
        conn = self.session.catalogs.get(catalog)
        handle = TableHandle(catalog, schema, table)
        table_schema = conn.metadata.table_schema(handle)
        fields = tuple(
            Field(f.name, f.type, relation=table) for f in table_schema.fields)
        return TableScanNode(
            catalog=catalog, table=handle,
            columns=tuple(table_schema.names), fields=fields)

    def plan_join(self, rel: A.Join) -> PlanNode:
        left = self.plan_relation(rel.left)
        # lateral UNNEST as the right side of an (implicit) cross join
        right_rel, un_alias, un_cols = rel.right, None, ()
        if isinstance(right_rel, A.AliasedRelation) \
                and isinstance(right_rel.relation, A.Unnest):
            un_alias, un_cols = right_rel.alias, right_rel.column_names
            right_rel = right_rel.relation
        if isinstance(right_rel, A.Unnest):
            if rel.join_type not in ("cross", "implicit"):
                raise AnalysisError(
                    "UNNEST only joins as CROSS JOIN / FROM-list item")
            return self.plan_unnest(left, right_rel, un_alias, un_cols)
        right = self.plan_relation(rel.right)
        combined = left.fields + right.fields
        if rel.join_type in ("cross", "implicit"):
            return JoinNode(
                join_type="cross", left=left, right=right,
                left_keys=(), right_keys=(), fields=combined)
        join_type = rel.join_type
        swapped = False
        if join_type == "right":
            left, right = right, left
            combined = left.fields + right.fields
            join_type = "left"
            swapped = True
        scope = Scope(combined)
        analyzer = ExpressionAnalyzer(scope)
        cond = analyzer.analyze(rel.condition) if rel.condition is not None \
            else None
        left_keys, right_keys, residual = _extract_equi_keys(
            cond, len(left.fields))
        if not left_keys:
            raise AnalysisError(
                "non-equi join conditions require at least one equality "
                "conjunct")
        if residual is not None and join_type == "left":
            # ON predicates touching only the build side filter the build
            # input (valid for LEFT: they decide matching, not probe rows)
            from ..expr.rewrite import (
                combine_conjuncts, conjuncts as split_conj, referenced_inputs,
                remap_inputs)
            n_left = len(left.fields)
            right_only, rest = [], []
            for c in split_conj(residual):
                refs = referenced_inputs(c)
                if refs and all(r >= n_left for r in refs):
                    right_only.append(
                        remap_inputs(c, {r: r - n_left for r in refs}))
                else:
                    rest.append(c)
            if right_only:
                right = FilterNode(child=right,
                                   predicate=combine_conjuncts(right_only))
            residual = combine_conjuncts(rest)
        # RIGHT was swapped above (key sides were extracted against the
        # swapped order, since the scope was built after the swap); restore
        # the WRITTEN column order for parents per SQL semantics
        node: PlanNode = JoinNode(
            join_type=join_type, left=left, right=right,
            left_keys=tuple(left_keys), right_keys=tuple(right_keys),
            fields=combined, residual=residual)
        if swapped:
            n_probe = len(left.fields)
            order = list(range(n_probe, len(combined))) + list(range(n_probe))
            node = ProjectNode(
                child=node,
                exprs=tuple(ir.input_ref(i, combined[i].type) for i in order),
                fields=tuple(combined[i] for i in order))
        return node

    # -- SELECT decomposition -----------------------------------------------
    def plan_query_spec(self, spec: A.QuerySpecification) -> PlanNode:
        spec = self._decorrelate_scalar_aggs(spec)
        if spec.from_ is not None:
            node = self.plan_relation(spec.from_)
        else:
            node = ValuesNode(fields=(), rows=((),))
        scope = Scope(node.fields)

        # WHERE: plain conjuncts filter first (directly above the join tree
        # so the optimizer's join-graph pass sees them), then subquery
        # conjuncts become semi joins above the filter
        if spec.where is not None:
            subquery_conjs, where = _split_subquery_conjuncts(spec.where)
            if where is not None:
                analyzer = ExpressionAnalyzer(scope)
                node = FilterNode(
                    child=node,
                    predicate=self._analyze_with_subqueries(where, analyzer))
            for kind, value, query, negated in subquery_conjs:
                if kind == "in":
                    node = self._plan_semi_join(node, value, query, negated)
                else:
                    node = self._plan_exists(node, query, negated)
            scope = Scope(node.fields)

        select_items = self._expand_stars(spec.select, scope)
        agg_calls = _collect_aggs(
            [it.value for it in select_items]
            + ([spec.having] if spec.having else [])
            + [s.key for s in spec.order_by])
        window_calls = _collect_windows(
            [it.value for it in select_items] + [s.key for s in spec.order_by])

        if agg_calls or spec.group_by:
            node, replacements = self._plan_aggregation(
                node, scope, spec, select_items, agg_calls)
            scope = Scope(node.fields)
        else:
            replacements = {}
        if window_calls:
            # windows over aggregated queries evaluate AFTER grouping
            # (reference QueryPlanner.window over the aggregation plan):
            # the agg replacements map sum(x)-style window inputs to the
            # aggregation's output columns
            node, win_repl = self._plan_windows(node, scope, window_calls,
                                                replacements)
            scope = Scope(node.fields)
            replacements.update(win_repl)

        # HAVING (after aggregation)
        if spec.having is not None:
            analyzer = ExpressionAnalyzer(scope, replacements)
            node = FilterNode(
                child=node,
                predicate=self._analyze_with_subqueries(spec.having, analyzer))

        # SELECT projection (+ hidden sort keys)
        analyzer = ExpressionAnalyzer(scope, replacements)
        out_exprs: List[ir.Expr] = []
        out_fields: List[Field] = []
        for i, item in enumerate(select_items):
            e = self._analyze_with_subqueries(item.value, analyzer)
            name = item.alias or _derive_name(item.value, i)
            out_exprs.append(e)
            out_fields.append(Field(name, e.type))
        project = ProjectNode(child=node, exprs=tuple(out_exprs),
                              fields=tuple(out_fields))

        result: PlanNode = project
        if spec.distinct:
            result = DistinctNode(child=result)

        if spec.order_by:
            out_scope = Scope(result.fields)
            keys, result = self._sort_keys_with_hidden(
                spec.order_by, result, out_scope, select_items, analyzer)
            if spec.limit is not None and not spec.distinct:
                result = TopNNode(child=result, keys=tuple(keys),
                                  count=spec.limit)
            else:
                result = SortNode(child=result, keys=tuple(keys))
                if spec.limit is not None:
                    result = LimitNode(child=result, count=spec.limit)
        elif spec.limit is not None:
            result = LimitNode(child=result, count=spec.limit)

        # drop hidden sort columns if any were added
        if len(result.fields) > len(out_fields):
            keep = list(range(len(out_fields)))
            result = ProjectNode(
                child=result,
                exprs=tuple(ir.input_ref(i, result.fields[i].type)
                            for i in keep),
                fields=tuple(result.fields[i] for i in keep))
        return result

    # -- subqueries -----------------------------------------------------------
    def _plan_semi_join(self, source: PlanNode, value: A.Expression,
                        query: A.Query, negated: bool) -> PlanNode:
        filtering = self.plan_query_node(query)
        if len(filtering.fields) != 1:
            raise AnalysisError("IN subquery must return one column")
        analyzer = ExpressionAnalyzer(Scope(source.fields))
        key = analyzer.analyze(value)
        if not isinstance(key, ir.InputRef):
            # project the key expression as a hidden column
            exprs = tuple(
                ir.input_ref(i, f.type)
                for i, f in enumerate(source.fields)) + (key,)
            fields = source.fields + (Field("$semikey", key.type),)
            source = ProjectNode(child=source, exprs=exprs, fields=fields)
            key_index = len(fields) - 1
        else:
            key_index = key.index
        node: PlanNode = SemiJoinNode(
            source=source, filtering=filtering, source_keys=(key_index,),
            filtering_keys=(0,), fields=source.fields, negated=negated)
        if source.fields and source.fields[-1].name == "$semikey":
            keep = list(range(len(source.fields) - 1))
            node = ProjectNode(
                child=node,
                exprs=tuple(ir.input_ref(i, source.fields[i].type)
                            for i in keep),
                fields=tuple(source.fields[i] for i in keep))
        return node

    def _plan_exists(self, source: PlanNode, query: A.Query,
                     negated: bool) -> PlanNode:
        """Decorrelate [NOT] EXISTS into a semi/anti join: correlated
        equality conjuncts become join keys, inner-only conjuncts filter
        the filtering side, any other correlated conjunct becomes the
        join's residual (mark-join; reference iterative/rule/
        TransformExistsApplyToCorrelatedJoin.java)."""
        body = query.body
        if query.with_ or not isinstance(body, A.QuerySpecification):
            raise AnalysisError("unsupported EXISTS subquery shape")
        if body.group_by or body.having or body.limit is not None \
                or body.from_ is None:
            raise AnalysisError("unsupported EXISTS subquery shape")
        if _collect_aggs([it.value for it in body.select
                          if not isinstance(it.value, A.Star)]):
            # an ungrouped aggregate subquery always returns exactly one
            # row, so EXISTS over it is constant TRUE — not a semi join
            raise AnalysisError(
                "EXISTS over an aggregate subquery is not supported")
        inner = self.plan_relation(body.from_)
        inner_scope = Scope(inner.fields)
        outer_scope = Scope(source.fields)
        combined_scope = Scope(source.fields + inner.fields)

        inner_filters: List[ir.Expr] = []
        skeys: List[int] = []
        fkeys: List[int] = []
        residuals: List[ir.Expr] = []
        conjs = _split_conjuncts(body.where) if body.where is not None else []
        for c in conjs:
            try:
                inner_filters.append(
                    ExpressionAnalyzer(inner_scope).analyze(c))
                continue
            except AnalysisError:
                pass
            pair = None
            if isinstance(c, A.Comparison) and c.op == "=":
                for o_ast, i_ast in ((c.left, c.right), (c.right, c.left)):
                    try:
                        oe = ExpressionAnalyzer(outer_scope).analyze(o_ast)
                        ie = ExpressionAnalyzer(inner_scope).analyze(i_ast)
                    except AnalysisError:
                        continue
                    if isinstance(oe, ir.InputRef) and isinstance(
                            ie, ir.InputRef):
                        pair = (oe.index, ie.index)
                        break
            if pair is not None:
                skeys.append(pair[0])
                fkeys.append(pair[1])
            else:
                # general correlated conjunct -> residual over
                # (source fields, filtering fields)
                residuals.append(
                    ExpressionAnalyzer(combined_scope).analyze(c))
        if not skeys:
            raise AnalysisError(
                "EXISTS must correlate on at least one equality")
        if len(skeys) > 2:
            raise AnalysisError("EXISTS on >2 correlation keys")
        from ..expr.rewrite import combine_conjuncts
        filtering: PlanNode = inner
        if inner_filters:
            filtering = FilterNode(child=inner,
                                   predicate=combine_conjuncts(inner_filters))
        residual = combine_conjuncts(residuals) if residuals else None
        return SemiJoinNode(
            source=source, filtering=filtering, source_keys=tuple(skeys),
            filtering_keys=tuple(fkeys), fields=source.fields,
            negated=negated, residual=residual, null_aware=False)

    # -- correlated scalar aggregates (AST pre-pass) --------------------------
    def _decorrelate_scalar_aggs(
            self, spec: A.QuerySpecification) -> A.QuerySpecification:
        """Rewrite `expr CMP (SELECT agg(..) FROM t WHERE t.k = outer.k
        AND ..)` conjuncts into a LEFT JOIN against a grouped derived table
        (reference iterative/rule/
        TransformCorrelatedScalarAggregationToJoin.java). Missing groups
        yield NULL, which fails the comparison — exactly the scalar
        subquery's empty-result semantics for min/max/sum/avg (count is
        rejected: empty groups must yield 0, which a join cannot)."""
        if spec.where is None or spec.from_ is None:
            return spec
        conjs = _split_conjuncts(spec.where)
        if not any(_find_scalar_subqueries(c) for c in conjs):
            return spec
        outer_scope: Optional[Scope] = None
        new_from = spec.from_
        new_conjs: List[A.Expression] = []
        changed = False
        for c in conjs:
            subs = _find_scalar_subqueries(c)
            if len(subs) != 1 or not self._is_correlated(subs[0].query):
                new_conjs.append(c)
                continue
            sub = subs[0]
            body = sub.query.body
            if (sub.query.with_ or not isinstance(body, A.QuerySpecification)
                    or body.group_by or body.having
                    or body.limit is not None or len(body.select) != 1
                    or body.from_ is None):
                raise AnalysisError("unsupported correlated subquery shape")
            value_expr = body.select[0].value
            if any(_FUNCTION_ALIASES.get(a.name, a.name) == "count"
                   for a in _collect_aggs([value_expr])):
                raise AnalysisError(
                    "correlated count() subquery is not supported yet")
            if not _collect_aggs([value_expr]):
                raise AnalysisError(
                    "correlated non-aggregate subquery is not supported yet")
            if outer_scope is None:
                saved = list(self.init_plans)
                outer_scope = Scope(self.plan_relation(spec.from_).fields)
                self.init_plans = saved
            saved = list(self.init_plans)
            inner_scope = Scope(self.plan_relation(body.from_).fields)
            self.init_plans = saved
            inner_only: List[A.Expression] = []
            corr_pairs: List[Tuple[A.Expression, A.Expression]] = []
            for ic in (_split_conjuncts(body.where)
                       if body.where is not None else []):
                try:
                    ExpressionAnalyzer(inner_scope).analyze(ic)
                    inner_only.append(ic)
                    continue
                except AnalysisError:
                    pass
                pair = None
                if isinstance(ic, A.Comparison) and ic.op == "=":
                    for o_ast, i_ast in ((ic.left, ic.right),
                                         (ic.right, ic.left)):
                        try:
                            ExpressionAnalyzer(outer_scope).analyze(o_ast)
                            ExpressionAnalyzer(inner_scope).analyze(i_ast)
                            pair = (o_ast, i_ast)
                            break
                        except AnalysisError:
                            continue
                if pair is None:
                    raise AnalysisError(
                        "cannot decorrelate subquery predicate")
                corr_pairs.append(pair)
            if not corr_pairs:
                raise AnalysisError("cannot decorrelate subquery")
            n = next(self._ids)
            alias = f"__corr{n}"
            knames = [f"__ck{i}" for i in range(len(corr_pairs))]
            vname = "__cv"
            derived_spec = A.QuerySpecification(
                select=tuple(
                    A.SelectItem(i_ast, kn)
                    for (_, i_ast), kn in zip(corr_pairs, knames)
                ) + (A.SelectItem(value_expr, vname),),
                from_=body.from_,
                where=_and_all(inner_only),
                group_by=tuple(i_ast for (_, i_ast) in corr_pairs))
            derived = A.AliasedRelation(
                A.SubqueryRelation(A.Query(body=derived_spec)),
                alias, tuple(knames) + (vname,))
            on = _and_all([
                A.Comparison("=", o_ast,
                             A.DereferenceExpression(
                                 A.Identifier(alias), A.Identifier(kn)))
                for (o_ast, _), kn in zip(corr_pairs, knames)])
            new_from = A.Join("left", new_from, derived, on)
            new_conjs.append(_replace_node(
                c, sub,
                A.DereferenceExpression(A.Identifier(alias),
                                        A.Identifier(vname))))
            changed = True
        if not changed:
            return spec
        return dataclasses.replace(spec, from_=new_from,
                                   where=_and_all(new_conjs))

    def _is_correlated(self, query: A.Query) -> bool:
        """A subquery is correlated iff standalone planning fails on an
        UNRESOLVED COLUMN specifically — any other failure is a genuine
        error in the subquery and must surface as-is, not be misreported
        as a decorrelation failure."""
        saved_init = list(self.init_plans)
        saved_ctes = dict(self.ctes)
        try:
            self.plan_query_node(query)
            return False
        except UnresolvedColumnError:
            return True
        finally:
            self.init_plans = saved_init
            self.ctes = saved_ctes

    def _analyze_with_subqueries(self, expr: A.Expression,
                                 analyzer: ExpressionAnalyzer) -> ir.Expr:
        """Lower an expression, turning uncorrelated scalar subqueries into
        init-plan literal placeholders."""
        rewritten = self._rewrite_scalar_subqueries(expr, analyzer)
        return analyzer.analyze(rewritten)

    def _rewrite_scalar_subqueries(self, expr: A.Expression,
                                   analyzer: ExpressionAnalyzer):
        if isinstance(expr, A.ScalarSubquery):
            sub = self.plan_query_node(expr.query)
            if len(sub.fields) != 1:
                raise AnalysisError("scalar subquery must return one column")
            idx = len(self.init_plans)
            self.init_plans.append(sub)
            placeholder = ir.lit(InitPlanRef(idx), sub.fields[0].type)
            # stash under a synthetic replacement key
            analyzer.replacements[expr] = placeholder
            return expr
        for child_name in ("left", "right", "value", "min", "max", "first",
                           "second", "operand", "default"):
            child = getattr(expr, child_name, None)
            if isinstance(child, A.Expression):
                self._rewrite_scalar_subqueries(child, analyzer)
        for seq_name in ("args", "items", "whens"):
            seq = getattr(expr, seq_name, None)
            if seq:
                for c in seq:
                    if isinstance(c, A.WhenClause):
                        self._rewrite_scalar_subqueries(c.condition, analyzer)
                        self._rewrite_scalar_subqueries(c.result, analyzer)
                    elif isinstance(c, A.Expression):
                        self._rewrite_scalar_subqueries(c, analyzer)
        return expr

    # -- aggregation ----------------------------------------------------------
    def _plan_aggregation(self, node: PlanNode, scope: Scope,
                          spec: A.QuerySpecification,
                          select_items: Sequence[A.SelectItem],
                          agg_calls: List[A.FunctionCall]):
        analyzer = ExpressionAnalyzer(scope)
        # group keys (ordinals supported)
        group_exprs: List[A.Expression] = []
        for g in spec.group_by:
            if isinstance(g, A.LongLiteral):
                ordinal = g.value
                if not (1 <= ordinal <= len(select_items)):
                    raise AnalysisError(f"GROUP BY ordinal {ordinal} out of range")
                group_exprs.append(select_items[ordinal - 1].value)
            else:
                group_exprs.append(g)

        pre_exprs: List[ir.Expr] = []
        pre_fields: List[Field] = []
        for i, g in enumerate(group_exprs):
            e = analyzer.analyze(g)
            name = _derive_name(g, i)
            pre_exprs.append(e)
            pre_fields.append(Field(name, e.type))

        aggs: List[PlanAgg] = []
        agg_fields: List[Field] = []
        # dedupe structurally identical aggregate calls
        seen: Dict[A.FunctionCall, int] = {}
        uniq_aggs: List[A.FunctionCall] = []
        for call in agg_calls:
            if call not in seen:
                seen[call] = len(uniq_aggs)
                uniq_aggs.append(call)
        for j, call in enumerate(uniq_aggs):
            fn = _FUNCTION_ALIASES.get(call.name, call.name)
            distinct = call.distinct
            if fn == "approx_distinct" and group_exprs:
                # grouped approx_distinct: HLL registers are a dense
                # [groups, m] tile on device, so an unbounded group count
                # would be unbounded state; without tight group-domain
                # statistics the engine keeps the EXACT lowering (a
                # strictly tighter error bound; the reference's sketch
                # exists to bound per-group memory, which the sort-based
                # mark-distinct path bounds differently).  The global
                # form below carries real bounded HLL state through
                # partial -> exchange -> final.
                if len(call.args) == 2:
                    # validate-and-drop the standard-error argument: the
                    # exact lowering satisfies any error bound
                    _parse_approx_distinct_error(analyzer, call)
                    call = dataclasses.replace(call,
                                               args=(call.args[0],))
                elif len(call.args) != 1:
                    raise AnalysisError(
                        "approx_distinct takes one or two arguments")
                fn, distinct = "count", True
            # ARBITRARY allows any live value; max picks one branch-free
            if fn in ("any_value", "arbitrary"):
                fn = "max"
            if fn not in ("count", "sum", "avg", "min", "max", "var_samp",
                          "var_pop", "stddev_samp", "stddev_pop",
                          "bool_and", "bool_or", "approx_percentile",
                          "approx_distinct"):
                raise AnalysisError(f"aggregate {fn}() not supported yet")
            if call.is_star or not call.args:
                if fn != "count":
                    raise AnalysisError(f"{fn}(*) is not valid")
                aggs.append(PlanAgg("count_star", None, T.BIGINT,
                                    f"_agg{j}", distinct=False))
                agg_fields.append(Field(f"_agg{j}", T.BIGINT))
                continue
            param = None
            if fn == "approx_distinct":
                # approx_distinct(x[, e]): bounded-memory HLL sketch with
                # standard error e (reference
                # ApproximateCountDistinctAggregations.java); state =
                # one register vector, mergeable across exchanges
                if len(call.args) == 2:
                    param = _parse_approx_distinct_error(analyzer, call)
                elif len(call.args) != 1:
                    raise AnalysisError(
                        "approx_distinct takes one or two arguments")
                arg = analyzer.analyze(call.args[0])
                arg_index = len(pre_exprs)
                pre_exprs.append(arg)
                pre_fields.append(Field(f"_aggarg{j}", arg.type))
                aggs.append(PlanAgg(fn, arg_index, T.BIGINT, f"_agg{j}",
                                    distinct=False, param=param))
                agg_fields.append(Field(f"_agg{j}", T.BIGINT))
                continue
            if fn == "approx_percentile":
                # approx_percentile(x, p): p must be a constant in [0, 1]
                # (reference ApproximateLongPercentileAggregations)
                if len(call.args) != 2:
                    raise AnalysisError(
                        "approx_percentile(x, p) takes two arguments "
                        "(the weighted form is not supported)")
                p_expr = analyzer.analyze(call.args[1])
                if not isinstance(p_expr, ir.Literal) \
                        or p_expr.value is None:
                    raise AnalysisError(
                        "approx_percentile percentage must be a constant")
                param = float(p_expr.value)
                if not 0.0 <= param <= 1.0:
                    raise AnalysisError(
                        "percentile must be between 0 and 1")
            elif len(call.args) != 1:
                raise AnalysisError(f"{fn}() takes one argument")
            arg = analyzer.analyze(call.args[0])
            arg_index = len(pre_exprs)
            pre_exprs.append(arg)
            pre_fields.append(Field(f"_aggarg{j}", arg.type))
            out_t = _agg_output_type(fn, arg.type)
            aggs.append(PlanAgg(fn, arg_index, out_t, f"_agg{j}",
                                distinct=distinct, param=param))
            agg_fields.append(Field(f"_agg{j}", out_t))

        pre = ProjectNode(child=node, exprs=tuple(pre_exprs),
                          fields=tuple(pre_fields))
        out_fields = tuple(pre_fields[:len(group_exprs)]) + tuple(agg_fields)
        nk = len(group_exprs)
        if spec.grouping_sets is not None:
            return self._plan_grouping_sets(
                spec, pre, pre_fields, nk, aggs, agg_fields, group_exprs,
                select_items, seen)
        if any(a.distinct for a in aggs):
            args = {a.arg for a in aggs}
            if all(a.distinct for a in aggs) and len(args) == 1 \
                    and None not in args:
                # all-distinct, one argument: distinct rows of
                # (keys, arg) first, then plain aggregation (reference
                # iterative/rule/SingleDistinctAggregationToGroupBy.java)
                arg0 = aggs[0].arg
                sel = list(range(nk)) + [arg0]
                dproj = ProjectNode(
                    child=pre,
                    exprs=tuple(ir.input_ref(i, pre_fields[i].type)
                                for i in sel),
                    fields=tuple(pre_fields[i] for i in sel))
                dnode = DistinctNode(child=dproj)
                aggs = [dataclasses.replace(a, arg=nk, distinct=False)
                        for a in aggs]
                agg_node = AggregationNode(
                    child=dnode, group_indices=tuple(range(nk)),
                    aggs=tuple(aggs), fields=out_fields)
            else:
                # mixed / multi-argument: one MarkDistinct mask channel
                # per distinct argument (reference MarkDistinctNode +
                # AggregationNode mask symbols via
                # rule/MultipleDistinctAggregationToMarkDistinct.java)
                from .plan import MarkDistinctNode
                if any(a.distinct and a.arg is None for a in aggs):
                    raise AnalysisError(
                        "count(DISTINCT *) is not valid")
                child: PlanNode = pre
                fields = list(pre_fields)
                mask_idx: Dict[int, int] = {}
                for arg in sorted({a.arg for a in aggs if a.distinct}):
                    mark = Field(f"$distinct{arg}", T.BOOLEAN)
                    child = MarkDistinctNode(
                        child=child,
                        cols=tuple(range(nk)) + (arg,),
                        partition_cols=tuple(range(nk)),
                        fields=tuple(fields) + (mark,))
                    mask_idx[arg] = len(fields)
                    fields.append(mark)
                aggs = [dataclasses.replace(a, distinct=False,
                                            mask=mask_idx[a.arg])
                        if a.distinct else a for a in aggs]
                agg_node = AggregationNode(
                    child=child, group_indices=tuple(range(nk)),
                    aggs=tuple(aggs), fields=out_fields)
        else:
            agg_node = AggregationNode(
                child=pre, group_indices=tuple(range(nk)),
                aggs=tuple(aggs), fields=out_fields)

        replacements: Dict[A.Expression, ir.Expr] = {}
        for i, g in enumerate(group_exprs):
            replacements[g] = ir.input_ref(i, pre_fields[i].type)
        for call, j in seen.items():
            replacements[call] = ir.input_ref(
                len(group_exprs) + j, agg_fields[j].type)
        return agg_node, replacements

    def _plan_grouping_sets(self, spec, pre, pre_fields, nk, aggs,
                            agg_fields, group_exprs, select_items, seen):
        """GROUP BY ROLLUP/CUBE/GROUPING SETS, lowered single-pass via
        GroupIdNode (reference plan/GroupIdNode.java +
        operator/GroupIdOperator.java): replicate rows per grouping set
        with absent keys nulled, aggregate ONCE over (keys..., $group_id)
        — empty sets (the ROLLUP grand-total row) included, so the whole
        input pipeline runs exactly once — and compute GROUPING() values
        by SWITCH on $group_id. Empty sets' grand-total rows over EMPTY
        input come from AggregationNode.default_gids (reference
        AggregationNode.hasDefaultOutput): the executor synthesizes the
        default rows when the aggregation produced no groups."""
        from .plan import GroupIdNode, UnionNode

        if any(a.distinct for a in aggs):
            raise AnalysisError(
                "DISTINCT aggregates with grouping sets are not supported")
        grouping_calls: List[A.FunctionCall] = []
        exprs_to_scan = ([it.value for it in select_items]
                         + ([spec.having] if spec.having else [])
                         + [s.key for s in spec.order_by])
        for c in _collect_calls_named(exprs_to_scan, "grouping"):
            if c not in grouping_calls:
                grouping_calls.append(c)

        def gidx(e: A.Expression) -> int:
            for i, g in enumerate(group_exprs):
                if g == e:
                    return i
            raise AnalysisError(
                "GROUPING() arguments must be grouping columns")

        call_arg_idx = [[gidx(a) for a in c.args] for c in grouping_calls]

        def grouping_val(s: Tuple[int, ...], idxs: List[int]) -> int:
            m = len(idxs)
            return sum((0 if idxs[a] in s else 1) << (m - 1 - a)
                       for a in range(m))

        all_sets = list(spec.grouping_sets)
        nonempty = [s for s in all_sets if s]
        out_fields = (tuple(pre_fields[:nk]) + tuple(agg_fields)
                      + tuple(Field(f"_grouping{k}", T.BIGINT)
                              for k in range(len(grouping_calls))))

        branches: List[PlanNode] = []
        if nonempty:
            gid_field = Field("$group_id", T.BIGINT)
            gid_node = GroupIdNode(
                child=pre, grouping_sets=tuple(all_sets), n_keys=nk,
                fields=tuple(pre_fields) + (gid_field,))
            gid_idx = len(pre_fields)
            agg_node = AggregationNode(
                child=gid_node,
                group_indices=tuple(range(nk)) + (gid_idx,),
                aggs=tuple(aggs),
                fields=(tuple(pre_fields[:nk]) + (gid_field,)
                        + tuple(agg_fields)),
                default_gids=tuple(g for g, s in enumerate(all_sets)
                                   if not s))
            # agg layout: [keys..., $group_id, aggs...]
            exprs: List[ir.Expr] = [
                ir.input_ref(i, pre_fields[i].type) for i in range(nk)]
            exprs += [ir.input_ref(nk + 1 + j, af.type)
                      for j, af in enumerate(agg_fields)]
            gid_ref = ir.input_ref(nk, T.BIGINT)
            for idxs in call_arg_idx:
                vals = [grouping_val(s, idxs) for s in all_sets]
                if len(set(vals)) == 1:
                    exprs.append(ir.lit(vals[0], T.BIGINT))
                    continue
                ops: List[ir.Expr] = []
                for g, v in enumerate(vals[:-1]):
                    ops.append(ir.call("eq", T.BOOLEAN, gid_ref,
                                       ir.lit(g, T.BIGINT)))
                    ops.append(ir.lit(v, T.BIGINT))
                ops.append(ir.lit(vals[-1], T.BIGINT))
                exprs.append(ir.special(ir.Form.SWITCH, T.BIGINT, *ops))
            branches.append(ProjectNode(child=agg_node, exprs=tuple(exprs),
                                        fields=out_fields))
        else:
            # only empty sets (GROUPING SETS ((), ...)): plain global
            # aggregation branches, one row each
            for _ in all_sets:
                g_agg = AggregationNode(
                    child=pre, group_indices=(), aggs=tuple(aggs),
                    fields=tuple(agg_fields))
                exprs = [ir.lit(None, pre_fields[i].type)
                         for i in range(nk)]
                exprs += [ir.input_ref(j, af.type)
                          for j, af in enumerate(agg_fields)]
                for idxs in call_arg_idx:
                    exprs.append(ir.lit(grouping_val((), idxs), T.BIGINT))
                branches.append(ProjectNode(child=g_agg,
                                            exprs=tuple(exprs),
                                            fields=out_fields))

        node: PlanNode = (branches[0] if len(branches) == 1 else
                          UnionNode(children_=tuple(branches),
                                    fields=out_fields))
        replacements: Dict[A.Expression, ir.Expr] = {}
        for i, g in enumerate(group_exprs):
            replacements[g] = ir.input_ref(i, pre_fields[i].type)
        for call, j in seen.items():
            replacements[call] = ir.input_ref(nk + j, agg_fields[j].type)
        for k, c in enumerate(grouping_calls):
            replacements[c] = ir.input_ref(nk + len(agg_fields) + k,
                                           T.BIGINT)
        return node, replacements

    # -- windows --------------------------------------------------------------
    def _plan_windows(self, node: PlanNode, scope: Scope,
                      window_calls: List[A.WindowFunction],
                      agg_replacements: Optional[Dict] = None):
        """One WindowNode per distinct (PARTITION BY, ORDER BY) window;
        shared windows evaluate together (reference plan/WindowNode.java
        groups functions under one window). ``agg_replacements`` resolves
        group-aggregate subexpressions inside window specs against the
        aggregation output (windows over aggregated queries)."""
        from .plan import WindowFnSpec, WindowNode
        replacements: Dict[A.Expression, ir.Expr] = {}
        groups: Dict[Tuple, List[A.WindowFunction]] = {}
        for w in window_calls:
            groups.setdefault((w.partition_by, w.order_by), []).append(w)
        for (partition_by, order_by), wins in groups.items():
            analyzer = ExpressionAnalyzer(Scope(node.fields),
                                          agg_replacements or {})
            base = len(node.fields)
            extra_exprs: List[ir.Expr] = []
            extra_fields: List[Field] = []

            def col_of(ast_expr: A.Expression):
                e = analyzer.analyze(ast_expr)
                if isinstance(e, ir.InputRef):
                    return e.index, e.type
                extra_exprs.append(e)
                extra_fields.append(
                    Field(f"$w{base + len(extra_exprs) - 1}", e.type))
                return base + len(extra_exprs) - 1, e.type

            part_idx = [col_of(p)[0] for p in partition_by]
            okeys = [SortKeySpec(col_of(s.key)[0], s.ascending, s.nulls_first)
                     for s in order_by]
            fn_specs: List[WindowFnSpec] = []
            out_fields: List[Field] = []
            for j, w in enumerate(wins):
                spec = self._window_fn_spec(w, col_of, f"_win{j}",
                                            bool(order_by))
                if (w.frame != "range"
                        or w.frame_start != ("unbounded_preceding", 0)
                        or w.frame_end != ("current_row", 0)):
                    if (w.frame == "range"
                            and (w.frame_start[0] in ("preceding",
                                                      "following")
                                 or w.frame_end[0] in ("preceding",
                                                       "following"))):
                        if len(order_by) != 1:
                            raise AnalysisError(
                                "RANGE frames with offsets require "
                                "exactly one ORDER BY key")
                        key_t = col_of(order_by[0].key)[1]
                        if not isinstance(key_t, (
                                T.BigintType, T.IntegerType,
                                T.SmallintType, T.TinyintType,
                                T.DoubleType, T.RealType, T.DateType,
                                T.DecimalType)):
                            raise AnalysisError(
                                "RANGE frames with offsets require a "
                                "numeric or date ORDER BY key, got "
                                f"{key_t.display()}")
                    spec = dataclasses.replace(
                        spec, frame=w.frame, frame_start=w.frame_start,
                        frame_end=w.frame_end)
                fn_specs.append(spec)
                out_fields.append(Field(spec.name, spec.output_type))
            if extra_exprs:
                exprs = tuple(ir.input_ref(i, f.type)
                              for i, f in enumerate(node.fields)
                              ) + tuple(extra_exprs)
                fields = node.fields + tuple(extra_fields)
                node = ProjectNode(child=node, exprs=exprs, fields=fields)
            win_out = node.fields + tuple(out_fields)
            node = WindowNode(
                child=node, partition_indices=tuple(part_idx),
                order_keys=tuple(okeys), functions=tuple(fn_specs),
                fields=win_out)
            for j, w in enumerate(wins):
                replacements[w] = ir.input_ref(
                    len(node.fields) - len(wins) + j,
                    fn_specs[j].output_type)
        return node, replacements

    def _window_fn_spec(self, w: A.WindowFunction, col_of, name: str,
                        has_order: bool):
        from .plan import WindowFnSpec
        from ..ops.window import AGG_FNS, RANKING, VALUE_FNS
        call = w.call
        fn = _FUNCTION_ALIASES.get(call.name, call.name)
        if fn in ("rank", "dense_rank", "row_number", "percent_rank",
                  "cume_dist") and not has_order:
            raise AnalysisError(f"{fn}() requires window ORDER BY")
        offset = 1
        args: List[int] = []
        if fn == "ntile":
            if len(call.args) != 1 or not isinstance(call.args[0],
                                                     A.LongLiteral):
                raise AnalysisError("ntile(n) takes a literal bucket count")
            offset = call.args[0].value
            return WindowFnSpec("ntile", (), T.BIGINT, name, offset)
        if fn in ("row_number", "rank", "dense_rank"):
            return WindowFnSpec(fn, (), T.BIGINT, name)
        if fn in ("percent_rank", "cume_dist"):
            return WindowFnSpec(fn, (), T.DOUBLE, name)
        if fn in ("lag", "lead", "nth_value"):
            if not call.args:
                raise AnalysisError(f"{fn}() needs an argument")
            arg, arg_t = col_of(call.args[0])
            if len(call.args) > 1:
                if not isinstance(call.args[1], A.LongLiteral):
                    raise AnalysisError(f"{fn} offset must be a literal")
                offset = call.args[1].value
            if len(call.args) > 2:
                raise AnalysisError(
                    f"{fn} default argument is not supported yet")
            return WindowFnSpec(fn, (arg,), arg_t, name, offset)
        if fn in ("first_value", "last_value"):
            arg, arg_t = col_of(call.args[0])
            return WindowFnSpec(fn, (arg,), arg_t, name)
        if fn in ("count",) and (call.is_star or not call.args):
            return WindowFnSpec("count_star", (), T.BIGINT, name,
                                ignore_order=not has_order)
        if fn in ("sum", "avg", "min", "max", "count"):
            arg, arg_t = col_of(call.args[0])
            if isinstance(arg_t, T.DecimalType) and arg_t.is_long:
                raise AnalysisError(
                    "window aggregates over decimal(>18) are not "
                    "supported yet (cast to decimal(18,s) or double)")
            if fn == "sum" and isinstance(arg_t, T.DecimalType):
                # the window kernel runs i64 cumsum differences, which
                # are exact for short-decimal inputs; keep the short
                # output type here (the group-by path widens to
                # decimal(38) like the reference)
                out_t: T.Type = T.DecimalType(18, arg_t.scale)
            else:
                out_t = (T.BIGINT if fn == "count" else
                         T.DOUBLE if fn == "avg" else
                         _agg_output_type(fn, arg_t))
            return WindowFnSpec(fn, (arg,), out_t, name,
                                ignore_order=not has_order)
        raise AnalysisError(f"window function {fn}() is not supported")

    # -- ORDER BY -------------------------------------------------------------
    def _sort_keys(self, order_by, node: PlanNode, scope: Scope,
                   replacements) -> List[SortKeySpec]:
        keys = []
        for s in order_by:
            if isinstance(s.key, A.LongLiteral):
                idx = s.key.value - 1
                if not (0 <= idx < len(node.fields)):
                    raise AnalysisError("ORDER BY ordinal out of range")
            else:
                analyzer = ExpressionAnalyzer(scope, replacements)
                e = analyzer.analyze(s.key)
                if not isinstance(e, ir.InputRef):
                    raise AnalysisError(
                        "ORDER BY expression must be an output column here")
                idx = e.index
            keys.append(SortKeySpec(idx, s.ascending, s.nulls_first))
        return keys

    def _sort_keys_with_hidden(self, order_by, project: PlanNode,
                               out_scope: Scope, select_items, analyzer):
        """Resolve sort keys against select outputs; unmatched expressions
        become hidden projected columns."""
        keys: List[SortKeySpec] = []
        extra_exprs: List[ir.Expr] = []
        extra_fields: List[Field] = []
        n_out = len(project.fields)
        # map: select item AST -> output index; alias -> index
        by_ast = {it.value: i for i, it in enumerate(select_items)}
        by_alias = {it.alias: i for i, it in enumerate(select_items)
                    if it.alias}
        for s in order_by:
            k = s.key
            if isinstance(k, A.LongLiteral):
                idx = k.value - 1
                if not (0 <= idx < n_out):
                    raise AnalysisError("ORDER BY ordinal out of range")
            elif isinstance(k, A.Identifier) and k.name in by_alias:
                idx = by_alias[k.name]
            elif k in by_ast:
                idx = by_ast[k]
            else:
                # SQL lets ORDER BY expressions reference SELECT aliases
                # (reference StatementAnalyzer orderBy scope): substitute
                # alias identifiers with their select expressions before
                # analyzing (q36-style 'case when lochierarchy = 0 ...');
                # source columns of the same name take precedence
                def resolves_in_input(name: str) -> bool:
                    try:
                        analyzer.scope.resolve(name)
                        return True
                    except Exception:
                        return False
                k = _subst_select_aliases(k, by_alias, select_items,
                                          resolves_in_input)
                e = analyzer.analyze(k)
                if isinstance(e, ir.InputRef) and isinstance(
                        project, ProjectNode):
                    # column of the pre-projection input: check if it is
                    # already projected unchanged
                    match = [i for i, pe in enumerate(project.exprs)
                             if pe == e]
                    if match:
                        idx = match[0]
                    else:
                        idx = n_out + len(extra_exprs)
                        extra_exprs.append(e)
                        extra_fields.append(
                            Field(f"$sort{len(extra_exprs)}", e.type))
                else:
                    idx = n_out + len(extra_exprs)
                    extra_exprs.append(e)
                    extra_fields.append(
                        Field(f"$sort{len(extra_exprs)}", e.type))
            keys.append(SortKeySpec(idx, s.ascending, s.nulls_first))
        if extra_exprs and isinstance(project, ProjectNode):
            project = ProjectNode(
                child=project.child,
                exprs=project.exprs + tuple(extra_exprs),
                fields=project.fields + tuple(extra_fields))
        elif extra_exprs:
            raise AnalysisError(
                "ORDER BY expression not derivable from output columns")
        return keys, project

    # -- stars ----------------------------------------------------------------
    def _expand_stars(self, items, scope: Scope) -> List[A.SelectItem]:
        out: List[A.SelectItem] = []
        for it in items:
            if isinstance(it.value, A.Star):
                q = it.value.qualifier
                matched = 0
                for f in scope.fields:
                    if q is None or f.relation == q:
                        ref = (A.Identifier(f.name) if q is None
                               else A.DereferenceExpression(
                                   A.Identifier(q), A.Identifier(f.name)))
                        out.append(A.SelectItem(ref, f.name))
                        matched += 1
                if not matched:
                    raise AnalysisError(f"no columns match {q}.*")
            else:
                out.append(it)
        return out


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _realias(node: PlanNode, alias: str,
             column_names: Tuple[str, ...] = ()) -> PlanNode:
    names = list(column_names) or [f.name for f in node.fields]
    fields = tuple(Field(n, f.type, relation=alias)
                   for n, f in zip(names, node.fields))
    if isinstance(node, OutputNode):
        node = node.child
    return _Realiased(node, fields)


def _Realiased(node: PlanNode, fields) -> PlanNode:
    # identity projection carrying the new field names/relations
    return ProjectNode(
        child=node,
        exprs=tuple(ir.input_ref(i, f.type) for i, f in enumerate(fields)),
        fields=fields)


def _coerce_to(node: PlanNode, types: List[T.Type]) -> PlanNode:
    if [f.type for f in node.fields] == types:
        return node
    exprs = tuple(
        coerce(ir.input_ref(i, f.type), t)
        for i, (f, t) in enumerate(zip(node.fields, types)))
    fields = tuple(Field(f.name, t, f.relation)
                   for f, t in zip(node.fields, types))
    return ProjectNode(child=node, exprs=exprs, fields=fields)


def _split_conjuncts(e: A.Expression) -> List[A.Expression]:
    if isinstance(e, A.LogicalBinary) and e.op == "and":
        return _split_conjuncts(e.left) + _split_conjuncts(e.right)
    return [e]


def _split_subquery_conjuncts(where: A.Expression):
    """Separate IN-subquery and [NOT] EXISTS conjuncts (-> semi joins)
    from plain ones. Entries: ("in", value, query, negated) or
    ("exists", None, query, negated)."""
    subqueries = []
    remaining: List[A.Expression] = []
    for c in _split_conjuncts(where):
        neg = False
        inner = c
        if isinstance(inner, A.Not):
            neg = True
            inner = inner.value
        if isinstance(inner, A.InSubquery):
            subqueries.append(
                ("in", inner.value, inner.query, neg != inner.negated))
            continue
        if isinstance(inner, A.Exists):
            subqueries.append(
                ("exists", None, inner.query, neg != inner.negated))
            continue
        remaining.append(c)
    return subqueries, _and_all(remaining)


def _and_all(conjuncts: List[A.Expression]) -> Optional[A.Expression]:
    if not conjuncts:
        return None
    out = conjuncts[0]
    for c in conjuncts[1:]:
        out = A.LogicalBinary("and", out, c)
    return out


def _walk_ast(exprs: Sequence[A.Expression], visit) -> None:
    """Generic AST walk (no descent into subquery bodies). ``visit``
    returns True to stop descending below a node."""

    def walk(n):
        if isinstance(n, (A.ScalarSubquery, A.InSubquery, A.Exists)):
            return
        if visit(n):
            return
        if dataclasses.is_dataclass(n) and not isinstance(n, type):
            for f in dataclasses.fields(n):
                v = getattr(n, f.name)
                if isinstance(v, tuple):
                    for x in v:
                        if dataclasses.is_dataclass(x):
                            walk(x)
                elif dataclasses.is_dataclass(v):
                    walk(v)
    for e in exprs:
        if e is not None:
            walk(e)


def _subst_select_aliases(k, by_alias, select_items, resolves_in_input):
    """Replace SELECT-alias identifiers inside an expression with their
    select expressions (no descent into subquery bodies). SQL scoping:
    a source column of the same name WINS over the alias (the reference
    resolves ORDER BY expression identifiers against the source relation
    first), so only identifiers that do NOT resolve in the input scope
    substitute. Dereference member names (x.field) are not free
    identifiers and never substitute."""
    def sub(n):
        if isinstance(n, (A.ScalarSubquery, A.InSubquery, A.Exists)):
            return n
        if isinstance(n, A.Identifier) and n.name in by_alias \
                and not resolves_in_input(n.name):
            return select_items[by_alias[n.name]].value
        if isinstance(n, A.DereferenceExpression):
            if isinstance(n.base, A.Identifier):
                return n      # qualified column ref: both parts are names
            base = sub(n.base)
            return (dataclasses.replace(n, base=base)
                    if base is not n.base else n)
        if dataclasses.is_dataclass(n) and not isinstance(n, type):
            changed = {}
            for f in dataclasses.fields(n):
                v = getattr(n, f.name)
                if isinstance(v, tuple):
                    nv = tuple(sub(x) if dataclasses.is_dataclass(x)
                               and not isinstance(x, type) else x
                               for x in v)
                    if nv != v:
                        changed[f.name] = nv
                elif dataclasses.is_dataclass(v) and not isinstance(v, type):
                    nv = sub(v)
                    if nv is not v:
                        changed[f.name] = nv
            return dataclasses.replace(n, **changed) if changed else n
        return n
    return sub(k)


def _collect_aggs(exprs: Sequence[A.Expression]) -> List[A.FunctionCall]:
    found: List[A.FunctionCall] = []

    def visit(n):
        if isinstance(n, A.WindowFunction):
            # the window call itself is not a group agg, but group aggs
            # may appear INSIDE it: avg(sum(x)) over (order by sum(y))
            # runs sum() in GROUP BY and avg() over the grouped rows
            # (reference AggregationAnalyzer's windowed-aggregate rules)
            _walk_ast(list(n.call.args) + list(n.partition_by)
                      + [s.key for s in n.order_by], visit)
            return True
        if isinstance(n, A.FunctionCall):
            fn = _FUNCTION_ALIASES.get(n.name, n.name)
            if fn in AGGREGATE_FUNCTIONS or n.is_star and fn == "count":
                found.append(n)
                return True  # don't descend into agg args
        return False
    _walk_ast(exprs, visit)
    return found


def _collect_calls_named(exprs: Sequence[A.Expression],
                         name: str) -> List[A.FunctionCall]:
    """All FunctionCall nodes with the given (unaliased) name, no descent
    into subqueries."""
    found: List[A.FunctionCall] = []

    def visit(n):
        if isinstance(n, A.FunctionCall) and n.name == name:
            found.append(n)
            return True
        return False
    _walk_ast(exprs, visit)
    return found


def _find_scalar_subqueries(e: A.Expression) -> List[A.ScalarSubquery]:
    """Top-level scalar subqueries of an expression (no descent into
    nested subquery bodies)."""
    found: List[A.ScalarSubquery] = []

    def walk(n):
        if isinstance(n, A.ScalarSubquery):
            found.append(n)
            return
        if isinstance(n, (A.InSubquery, A.Exists)):
            if isinstance(n, A.InSubquery):
                walk(n.value)
            return
        if dataclasses.is_dataclass(n) and not isinstance(n, type):
            for f in dataclasses.fields(n):
                v = getattr(n, f.name)
                if isinstance(v, tuple):
                    for x in v:
                        if dataclasses.is_dataclass(x):
                            walk(x)
                elif dataclasses.is_dataclass(v):
                    walk(v)
    walk(e)
    return found


def _replace_node(root, target, replacement):
    """Structurally replace ``target`` with ``replacement`` in an AST."""
    if root == target:
        return replacement
    if not (dataclasses.is_dataclass(root) and not isinstance(root, type)):
        return root
    changed = {}
    for f in dataclasses.fields(root):
        v = getattr(root, f.name)
        if isinstance(v, tuple):
            nv = tuple(
                _replace_node(x, target, replacement)
                if dataclasses.is_dataclass(x) else x for x in v)
            if nv != v:
                changed[f.name] = nv
        elif dataclasses.is_dataclass(v):
            nv = _replace_node(v, target, replacement)
            if nv != v:
                changed[f.name] = nv
    return dataclasses.replace(root, **changed) if changed else root


def _collect_windows(exprs: Sequence[A.Expression]
                     ) -> List[A.WindowFunction]:
    found: List[A.WindowFunction] = []

    def walk(n):
        if isinstance(n, (A.ScalarSubquery, A.InSubquery, A.Exists)):
            return
        if isinstance(n, A.WindowFunction):
            found.append(n)
            return
        if dataclasses.is_dataclass(n) and not isinstance(n, type):
            for f in dataclasses.fields(n):
                v = getattr(n, f.name)
                if isinstance(v, tuple):
                    for x in v:
                        if dataclasses.is_dataclass(x):
                            walk(x)
                elif dataclasses.is_dataclass(v):
                    walk(v)
    for e in exprs:
        if e is not None:
            walk(e)
    return found


def _parse_approx_distinct_error(analyzer, call) -> float:
    """Validate approx_distinct's optional max-standard-error argument
    (must be a constant within the reference's supported range)."""
    e_expr = analyzer.analyze(call.args[1])
    if not isinstance(e_expr, ir.Literal) or e_expr.value is None:
        raise AnalysisError(
            "approx_distinct standard error must be a constant")
    param = float(e_expr.value)
    from ..ops.sketch import MAX_STANDARD_ERROR, MIN_STANDARD_ERROR
    if not (MIN_STANDARD_ERROR <= param <= MAX_STANDARD_ERROR):
        raise AnalysisError(
            "approx_distinct standard error must be in "
            f"[{MIN_STANDARD_ERROR}, {MAX_STANDARD_ERROR}]")
    return param


def _derive_name(e: A.Expression, i: int) -> str:
    if isinstance(e, A.Identifier):
        return e.name
    if isinstance(e, A.DereferenceExpression):
        return e.field.name
    if isinstance(e, A.FunctionCall):
        return e.name
    return f"_col{i}"


def _agg_output_type(fn: str, arg: T.Type) -> T.Type:
    if fn == "count":
        return T.BIGINT
    if fn == "sum":
        if isinstance(arg, T.DecimalType):
            # reference DecimalSumAggregation: sum(decimal) is always
            # decimal(38, s) with Int128 state
            return T.DecimalType(38, arg.scale)
        if T.is_integral(arg):
            return T.BIGINT
        return T.DOUBLE if isinstance(arg, (T.DoubleType, T.RealType)) \
            else T.DOUBLE
    if fn == "avg":
        if isinstance(arg, T.DecimalType):
            return arg
        return T.DOUBLE
    if fn in ("var_samp", "var_pop", "stddev_samp", "stddev_pop"):
        return T.DOUBLE
    if fn in ("bool_and", "bool_or"):
        return T.BOOLEAN
    # min/max
    return arg


def _extract_equi_keys(cond: Optional[ir.Expr], n_left: int):
    """Split an ON condition into equi-key pairs + residual.

    Mirrors the reference's join-criteria extraction (reference
    sql/planner/optimizations/PredicatePushDown.java + EqualityInference).
    """
    left_keys: List[int] = []
    right_keys: List[int] = []
    residual: List[ir.Expr] = []
    conjuncts: List[ir.Expr] = []

    def split(e: ir.Expr):
        if isinstance(e, ir.SpecialForm) and e.form == ir.Form.AND:
            for a in e.args:
                split(a)
        else:
            conjuncts.append(e)
    if cond is not None:
        split(cond)
    for c in conjuncts:
        if (isinstance(c, ir.Call) and c.name == "eq"
                and isinstance(c.args[0], ir.InputRef)
                and isinstance(c.args[1], ir.InputRef)):
            a, b = c.args
            if a.index < n_left <= b.index:
                left_keys.append(a.index)
                right_keys.append(b.index - n_left)
                continue
            if b.index < n_left <= a.index:
                left_keys.append(b.index)
                right_keys.append(a.index - n_left)
                continue
        residual.append(c)
    res = None
    if residual:
        res = residual[0] if len(residual) == 1 else ir.special(
            ir.Form.AND, T.BOOLEAN, *residual)
    return left_keys, right_keys, res

"""Logical plan nodes.

Conceptual parity with the reference's PlanNode tree (reference
presto-main/.../sql/planner/plan/ — 39 node types; this is the load-bearing
subset per SURVEY.md §7 step 5). Columns are positional: every node exposes
``fields`` (name, type) and expressions inside a node index its child's
fields — the Symbol allocator is replaced by positions, which is also what
the batch kernels consume.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from .. import types as T
from ..expr import ir
from ..sql.analyzer import Field
from ..connectors.spi import TableHandle


class PlanNode:
    fields: Tuple[Field, ...]

    @property
    def children(self) -> Tuple["PlanNode", ...]:
        return ()

    def with_children(self, children: Sequence["PlanNode"]) -> "PlanNode":
        assert not children
        return self

    @property
    def names(self) -> List[str]:
        return [f.name for f in self.fields]

    @property
    def types(self) -> List[T.Type]:
        return [f.type for f in self.fields]


def _one_child(cls):
    """Mixin-free helper: single-child with_children via dataclasses.replace."""
    def children(self):
        return (self.child,)

    def with_children(self, ch):
        (c,) = ch
        return dataclasses.replace(self, child=c)
    cls.children = property(children)
    cls.with_children = with_children
    return cls


@dataclasses.dataclass(frozen=True)
class TableScanNode(PlanNode):
    """Scan of a connector table (reference plan/TableScanNode.java).
    ``columns`` are the connector column names actually read; predicate
    pushdown attaches later (TupleDomain analogue)."""

    catalog: str
    table: TableHandle
    columns: Tuple[str, ...]
    fields: Tuple[Field, ...] = ()
    # advisory per-column [lo, hi] bounds in storage domain for connector
    # pruning (TupleDomain-lite): ((column_name, lo, hi), ...)
    pushdown: Tuple[Tuple[str, Optional[int], Optional[int]], ...] = ()


@dataclasses.dataclass(frozen=True)
class ValuesNode(PlanNode):
    fields: Tuple[Field, ...]
    rows: Tuple[Tuple[object, ...], ...]


@dataclasses.dataclass(frozen=True)
class RemoteSourceNode(PlanNode):
    """Leaf of a plan fragment: pages pulled from every task of an
    upstream fragment (reference plan/RemoteSourceNode.java +
    operator/ExchangeOperator.java). ``fragment_ids`` lists the upstream
    fragments feeding this exchange (several for UNION)."""

    fragment_ids: Tuple[int, ...]
    fields: Tuple[Field, ...]


@_one_child
@dataclasses.dataclass(frozen=True)
class FilterNode(PlanNode):
    child: PlanNode
    predicate: ir.Expr
    fields: Tuple[Field, ...] = ()

    def __post_init__(self):
        if not self.fields:
            object.__setattr__(self, "fields", self.child.fields)


@_one_child
@dataclasses.dataclass(frozen=True)
class ProjectNode(PlanNode):
    child: PlanNode
    exprs: Tuple[ir.Expr, ...]
    fields: Tuple[Field, ...]


@dataclasses.dataclass(frozen=True)
class PlanAgg:
    """One aggregate call: fn(input_index) with optional DISTINCT
    (reference plan/AggregationNode.Aggregation)."""

    fn: str
    arg: Optional[int]            # child column index; None for count(*)
    output_type: T.Type
    name: str
    distinct: bool = False
    # mask channel produced by MarkDistinctNode (reference
    # AggregationNode.Aggregation mask symbol)
    mask: Optional[int] = None
    # static scalar parameter (approx_percentile's p)
    param: Optional[float] = None


@_one_child
@dataclasses.dataclass(frozen=True)
class AggregationNode(PlanNode):
    """Group-by aggregation; output = [group keys..., agg outputs...]
    (reference plan/AggregationNode.java). step is assigned during
    fragmentation (SINGLE until exchanges split it)."""

    child: PlanNode
    group_indices: Tuple[int, ...]
    aggs: Tuple[PlanAgg, ...]
    fields: Tuple[Field, ...]
    step: str = "single"
    # stats-derived static [lo, hi] per group key (aligned with
    # group_indices; None per key when unknown). When every key's domain
    # is host-known and the composite product is small, the executor
    # composes a dense i32 group code and takes the scatter path of
    # ops/scatter_agg.py instead of the multi-operand lax.sort path —
    # the planner side of the reference BigintGroupByHash dense-array
    # mode. Attached by optimizer._attach_group_bounds.
    key_bounds: Tuple[Optional[Tuple[int, int]], ...] = ()
    # grouping-sets support (reference AggregationNode.groupIdSymbol +
    # hasDefaultOutput): $group_id values — indexes into the feeding
    # GroupIdNode's sets — that must still emit a default row (count=0,
    # other aggs NULL, keys NULL) when the input is empty; these are the
    # ROLLUP/CUBE empty sets, whose grand-total row exists even over
    # empty input
    default_gids: Tuple[int, ...] = ()


@dataclasses.dataclass(frozen=True)
class JoinNode(PlanNode):
    """Equi-join (reference plan/JoinNode.java). Output = left fields +
    right fields. ``residual`` filters post-join rows (over the combined
    schema)."""

    join_type: str                # inner | left | cross
    left: PlanNode
    right: PlanNode
    left_keys: Tuple[int, ...]
    right_keys: Tuple[int, ...]
    fields: Tuple[Field, ...]
    residual: Optional[ir.Expr] = None
    # execution hints (filled by the optimizer)
    distribution: str = "partitioned"   # partitioned | replicated
    build_unique: bool = False          # build keys known unique (PK)
    # stats-derived hard [lo, hi] per BUILD key (aligned with
    # right_keys; () = no planner bounds). When attached, every key's
    # domain is statistics-proven and the mixed-radix composite product
    # is small, so the executor builds a multi-key direct-address table
    # (ops/join.prepare_direct_keyed) with plan-time-known capacity —
    # the join-side twin of AggregationNode.key_bounds. The executor
    # cross-checks every build batch through the row-error channel
    # (STATS_BOUND_VIOLATION), so an overclaiming connector fails the
    # query instead of dropping matches. Attached by
    # optimizer._attach_join_strategy.
    key_bounds: Tuple[Optional[Tuple[int, int]], ...] = ()

    @property
    def children(self) -> Tuple[PlanNode, ...]:
        return (self.left, self.right)

    def with_children(self, ch):
        l, r = ch
        return dataclasses.replace(self, left=l, right=r)


@dataclasses.dataclass(frozen=True)
class SemiJoinNode(PlanNode):
    """Filters source rows by key membership in the filtering subplan
    (reference plan/SemiJoinNode.java; executed like SetBuilder +
    HashSemiJoin). Output = source fields.

    ``residual`` (over source fields + filtering fields) restricts which
    matches count — the decorrelated-EXISTS mark-join shape (reference
    iterative/rule/TransformExistsApplyToCorrelatedJoin.java).
    ``null_aware`` selects NOT IN semantics (NULL build key poisons the
    anti side) vs NOT EXISTS semantics (NULLs simply never match)."""

    source: PlanNode
    filtering: PlanNode
    source_keys: Tuple[int, ...]
    filtering_keys: Tuple[int, ...]
    fields: Tuple[Field, ...]
    negated: bool = False
    residual: Optional[ir.Expr] = None
    null_aware: bool = True
    # stats-driven distribution (optimizer._attach_join_strategy):
    # "replicated" broadcasts the filtering set to every source task
    # (membership-everywhere — mandatory for NULL-aware anti joins,
    # whose build_has_null/build_empty facts are global); "partitioned"
    # hashes BOTH sides by key so a huge filtering set never replicates
    # (reference DetermineSemiJoinDistributionType.java).
    distribution: str = "replicated"
    # stats-derived hard [lo, hi] per FILTERING key (see
    # JoinNode.key_bounds — enables the direct-address membership table)
    key_bounds: Tuple[Optional[Tuple[int, int]], ...] = ()

    @property
    def children(self) -> Tuple[PlanNode, ...]:
        return (self.source, self.filtering)

    def with_children(self, ch):
        s, f = ch
        return dataclasses.replace(self, source=s, filtering=f)


@dataclasses.dataclass(frozen=True)
class WindowFnSpec:
    """One window function over the node's shared window
    (reference plan/WindowNode.Function)."""

    fn: str
    args: Tuple[int, ...]          # child column indices
    output_type: T.Type
    name: str
    offset: int = 1                # lag/lead/ntile/nth_value parameter
    ignore_order: bool = False
    frame: str = "range"           # frame unit: RANGE | ROWS
    # frame bounds (kind, offset), reference operator/window/FrameInfo.java
    frame_start: Tuple[str, int] = ("unbounded_preceding", 0)
    frame_end: Tuple[str, int] = ("current_row", 0)


@_one_child
@dataclasses.dataclass(frozen=True)
class WindowNode(PlanNode):
    """Window evaluation (reference plan/WindowNode.java). Output =
    child fields + one column per function; rows re-ordered by
    (partition, order)."""

    child: PlanNode
    partition_indices: Tuple[int, ...]
    order_keys: Tuple["SortKeySpec", ...]
    functions: Tuple[WindowFnSpec, ...]
    fields: Tuple[Field, ...]


@dataclasses.dataclass(frozen=True)
class SortKeySpec:
    index: int
    ascending: bool = True
    nulls_first: Optional[bool] = None


@_one_child
@dataclasses.dataclass(frozen=True)
class SortNode(PlanNode):
    child: PlanNode
    keys: Tuple[SortKeySpec, ...]
    fields: Tuple[Field, ...] = ()

    def __post_init__(self):
        if not self.fields:
            object.__setattr__(self, "fields", self.child.fields)


@_one_child
@dataclasses.dataclass(frozen=True)
class TopNNode(PlanNode):
    child: PlanNode
    keys: Tuple[SortKeySpec, ...]
    count: int
    fields: Tuple[Field, ...] = ()

    def __post_init__(self):
        if not self.fields:
            object.__setattr__(self, "fields", self.child.fields)


@_one_child
@dataclasses.dataclass(frozen=True)
class LimitNode(PlanNode):
    child: PlanNode
    count: int
    fields: Tuple[Field, ...] = ()

    def __post_init__(self):
        if not self.fields:
            object.__setattr__(self, "fields", self.child.fields)


@_one_child
@dataclasses.dataclass(frozen=True)
class DistinctNode(PlanNode):
    """SELECT DISTINCT — group by every output column
    (reference rule SingleDistinctAggregationToGroupBy shape)."""

    child: PlanNode
    fields: Tuple[Field, ...] = ()
    # stats-derived static [lo, hi] per output column (see
    # AggregationNode.key_bounds — DISTINCT groups by every column)
    key_bounds: Tuple[Optional[Tuple[int, int]], ...] = ()

    def __post_init__(self):
        if not self.fields:
            object.__setattr__(self, "fields", self.child.fields)


@_one_child
@dataclasses.dataclass(frozen=True)
class UnnestNode(PlanNode):
    """Lateral array expansion (reference plan/UnnestNode.java +
    operator/unnest/UnnestOperator.java): output = child fields, then one
    element column per array expression, then optional ordinality. Each
    child row replicates once per element of its (longest) array."""

    child: PlanNode
    exprs: Tuple[object, ...]      # ir.Expr of ArrayType over child schema
    ordinality: bool
    fields: Tuple[Field, ...]


@_one_child
@dataclasses.dataclass(frozen=True)
class MarkDistinctNode(PlanNode):
    """Appends one boolean column that is true at the first occurrence
    of each distinct tuple of ``cols`` (reference plan/MarkDistinctNode
    + operator/MarkDistinctOperator.java) — the mask-channel lowering of
    mixed DISTINCT aggregates. ``partition_cols`` (the group keys) tell
    distributed executors how to colocate rows so first-occurrence is
    global, not per-shard."""

    child: PlanNode
    cols: Tuple[int, ...]
    partition_cols: Tuple[int, ...]
    fields: Tuple[Field, ...]


@_one_child
@dataclasses.dataclass(frozen=True)
class GroupIdNode(PlanNode):
    """Replicates each input row once per grouping set, nulling out group
    keys absent from that set and appending a $group_id column (reference
    plan/GroupIdNode.java + operator/GroupIdOperator.java) — the
    single-pass lowering of GROUP BY GROUPING SETS. Input layout =
    [group keys..., agg args...]; output = input fields + $group_id."""

    child: PlanNode
    grouping_sets: Tuple[Tuple[int, ...], ...]
    n_keys: int
    fields: Tuple[Field, ...]


@dataclasses.dataclass(frozen=True)
class UnionNode(PlanNode):
    children_: Tuple[PlanNode, ...]
    fields: Tuple[Field, ...]
    distinct: bool = False

    @property
    def children(self) -> Tuple[PlanNode, ...]:
        return self.children_

    def with_children(self, ch):
        return dataclasses.replace(self, children_=tuple(ch))


@_one_child
@dataclasses.dataclass(frozen=True)
class OutputNode(PlanNode):
    """Final client-visible columns (reference plan/OutputNode.java)."""

    child: PlanNode
    fields: Tuple[Field, ...]

"""Plan optimizer: the load-bearing visitor passes.

Conceptual parity with the reference's optimizer pipeline (reference
presto-main/.../sql/planner/PlanOptimizers.java:252-412). Round-1 passes:

1. join graph construction — flattens cross-join trees + filters into
   relations/conjuncts, pushes single-relation predicates down, orders
   equi-joins greedily by estimated size (reference EliminateCrossJoins.java,
   PredicatePushDown.java, ReorderJoins.java collapsed into one pass over
   the positional plan);
2. column pruning — scans read only referenced columns (reference the 18
   Prune*.java rules + PushProjectionIntoTableScan);
3. join implementation — picks build side (unique-key side, smaller on
   ties) and distribution (replicated when the build side is small),
   reference DetermineJoinDistributionType.java.

Passes keep output field order stable by appending restoring projections,
so parent expressions never need rewriting.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .. import types as T
from ..expr import ir
from ..expr.rewrite import (
    combine_conjuncts, conjuncts, referenced_inputs, remap_inputs,
)
from ..sql.analyzer import Field
from .plan import (
    AggregationNode, DistinctNode, FilterNode, JoinNode, LimitNode,
    OutputNode, PlanNode, ProjectNode, SemiJoinNode, SortNode,
    TableScanNode, TopNNode, UnionNode, ValuesNode,
)
from .planner import LogicalPlan, Session, bool_property

BROADCAST_ROW_LIMIT = 2_000_000


def optimize(plan: LogicalPlan, session: Session) -> LogicalPlan:
    from .rules import iterative_optimize
    from .stats import StatsCalculator

    def pipeline(node: PlanNode) -> PlanNode:
        # iterative simplify/merge/push rules to a fixpoint (reference
        # IterativeOptimizer over the rule catalog), then the structural
        # visitor passes (reference PlanOptimizers.java:252-412 ordering)
        node = iterative_optimize(node)
        node = _rewrite_joins(node, session)
        node, _ = _prune(node, list(range(len(node.fields))))
        node = _implement_joins(node, session)
        if bool_property(session, "push_partial_aggregation_through_join",
                         True):
            node = _push_partial_agg_through_join(node, session)
        if bool_property(session, "stats_bounded_grouping", True):
            node = _attach_group_bounds(node, session)
        node = _attach_join_strategy(
            node, session,
            dense=bool_property(session, "join_dense_path", True))
        return _attach_scan_pushdown(node)
    # one memoized StatsCalculator for the whole pass: join ordering,
    # distribution choice, and the eager-agg gate all estimate the same
    # subtrees, and connector table_stats can be full-scan priced
    # (sqlite) — per-call calculators re-derived everything (ADVICE r5)
    token = _PASS_CALC.set(StatsCalculator(session))
    try:
        root = pipeline(plan.root)
        init = [pipeline(p) for p in plan.init_plans]
    finally:
        _PASS_CALC.reset(token)
    return LogicalPlan(root, init)


# ---------------------------------------------------------------------------
# Scan pushdown: advisory min/max bounds for connector pruning
# ---------------------------------------------------------------------------

_BOUNDABLE = (T.BigintType, T.IntegerType, T.SmallintType, T.TinyintType,
              T.DateType)


def _attach_scan_pushdown(node: PlanNode) -> PlanNode:
    """Filter directly over a scan: extract per-column [lo, hi] integer
    bounds from its conjuncts and attach them to the scan (the
    TupleDomain-lite handoff of reference
    sql/planner/iterative/rule/PushPredicateIntoTableScan.java +
    spi/predicate/TupleDomain.java). The filter stays — the bounds only
    let connectors prune files/stripes on statistics."""
    if (isinstance(node, FilterNode)
            and isinstance(node.child, TableScanNode)):
        bounds = _extract_bounds(node.predicate, node.child)
        if bounds:
            return dataclasses.replace(
                node, child=dataclasses.replace(node.child,
                                                pushdown=bounds))
        return node
    return node.with_children([_attach_scan_pushdown(c)
                               for c in node.children])


def _extract_bounds(pred: ir.Expr,
                    scan: TableScanNode
                    ) -> Tuple[Tuple[str, Optional[int], Optional[int]], ...]:
    INF = (1 << 62)
    bounds: Dict[str, List[int]] = {}

    def note(idx: int, lo, hi) -> None:
        t = scan.fields[idx].type
        if not isinstance(t, _BOUNDABLE):
            return
        name = scan.columns[idx]
        b = bounds.setdefault(name, [-INF, INF])
        b[0] = max(b[0], lo if lo is not None else -INF)
        b[1] = min(b[1], hi if hi is not None else INF)

    def ref_of(e: ir.Expr):
        if isinstance(e, ir.Cast):
            e = e.arg
        return e.index if isinstance(e, ir.InputRef) else None

    def lit_of(e: ir.Expr, allow_param: bool = False):
        """(storage int, param-or-None) for a boundable constant; param
        is the ir.Param the value came from (plan templates). Params
        are only consultable for RANGE comparisons: baking a bound from
        them records a value-equality reuse guard (expr/params.consult)
        — acceptable for fleet-constant range windows, but an eq bound
        on the fleet's VARYING slot (user_id = ?) would turn every
        binding into a guard fallback, so eq never consults."""
        if isinstance(e, ir.Cast):
            e = e.arg
        # only literals whose own domain is integer-like convert safely:
        # a decimal/double literal's storage (unscaled / float) is NOT in
        # the column's integer domain, and a wrong bound silently prunes
        # live data
        if (isinstance(e, ir.Literal) and e.value is not None
                and isinstance(e.type, _BOUNDABLE)):
            try:
                return int(e.type.to_storage(e.value)), None
            except (TypeError, ValueError):
                return None, None
        if (allow_param and isinstance(e, ir.Param)
                and e.bound is not None
                and isinstance(e.type, _BOUNDABLE)):
            try:
                return int(e.type.to_storage(e.bound)), e
            except (TypeError, ValueError):
                return None, None
        return None, None

    def guarded(idx: int, *ps) -> bool:
        """Record consultation guards for the params feeding a bound —
        only when the bound will actually attach (boundable column)."""
        if not isinstance(scan.fields[idx].type, _BOUNDABLE):
            return False
        from ..expr import params as _params
        for p in ps:
            if p is not None:
                _params.consult(p)
        return True

    for c in conjuncts(pred):
        if isinstance(c, ir.SpecialForm) and c.form == ir.Form.BETWEEN:
            i = ref_of(c.args[0])
            (lo, plo), (hi, phi) = (lit_of(c.args[1], True),
                                    lit_of(c.args[2], True))
            if i is not None and lo is not None and hi is not None \
                    and guarded(i, plo, phi):
                note(i, lo, hi)
            continue
        if not isinstance(c, ir.Call) or len(c.args) != 2:
            continue
        op = c.name
        range_op = op in ("lt", "le", "gt", "ge")
        a, b = c.args
        ia, ib = ref_of(a), ref_of(b)
        la, pa = lit_of(a, range_op)
        lb, pb = lit_of(b, range_op)
        if ia is not None and lb is not None:
            idx, v, p = ia, lb, pb
        elif ib is not None and la is not None:
            # flip the comparison: lit OP col == col FLIP(op) lit
            idx, v, p = ib, la, pa
            op = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le",
                  "eq": "eq"}.get(op, "")
        else:
            continue
        if op == "eq":
            if guarded(idx, p):
                note(idx, v, v)
        elif op in ("lt", "le"):
            if guarded(idx, p):
                note(idx, None, v)
        elif op in ("gt", "ge"):
            if guarded(idx, p):
                note(idx, v, None)
    # unbounded sides stay None: a finite sentinel would be compared
    # against real column statistics and could prune live data
    return tuple((n, lo if lo > -INF else None, hi if hi < INF else None)
                 for n, (lo, hi) in sorted(bounds.items())
                 if lo > -INF or hi < INF)


# ---------------------------------------------------------------------------
# Pass 1: join graph (cross-join elimination + predicate pushdown + ordering)
# ---------------------------------------------------------------------------

def _rewrite_joins(node: PlanNode, session: Session) -> PlanNode:
    # top-down: a filter directly above a join tree contributes its
    # conjuncts to the join graph BEFORE the tree is reordered; leaves of
    # the graph are rewritten recursively inside _plan_join_graph
    if (isinstance(node, FilterNode) and isinstance(node.child, JoinNode)
            and node.child.join_type in ("cross", "inner")):
        return _plan_join_graph(node.child, [node.predicate], session)
    if (isinstance(node, FilterNode) and isinstance(node.child, JoinNode)
            and node.child.join_type == "left"):
        # WHERE conjuncts that touch only the probe side of a LEFT JOIN
        # push below it (they cannot change match semantics; reference
        # optimizations/PredicatePushDown.java outer-join handling), which
        # lets the probe side's own join graph form.
        j = node.child
        n_left = len(j.left.fields)
        push, keep = [], []
        for c in conjuncts(node.predicate):
            refs = referenced_inputs(c)
            if refs and all(r < n_left for r in refs):
                push.append(c)
            else:
                keep.append(c)
        if push:
            j = dataclasses.replace(
                j, left=FilterNode(child=j.left,
                                   predicate=combine_conjuncts(push)))
            rebuilt: PlanNode = j
            if keep:
                rebuilt = FilterNode(child=j,
                                     predicate=combine_conjuncts(keep))
            return _rewrite_joins(rebuilt, session)
    if isinstance(node, JoinNode) and node.join_type in ("cross", "inner"):
        return _plan_join_graph(node, [], session)
    return node.with_children([_rewrite_joins(c, session)
                               for c in node.children])


def _flatten_join_tree(node: PlanNode, leaves: List[PlanNode],
                       preds: List[ir.Expr], offset: int) -> None:
    """Collect leaves + predicates of an inner/cross join tree.

    Positions: the tree's output = concatenation of leaf fields in visit
    order, so conjuncts lifted from ON clauses keep their global indices.
    """
    if isinstance(node, JoinNode) and node.join_type in ("cross", "inner"):
        _flatten_join_tree(node.left, leaves, preds, offset)
        right_off = offset + len(node.left.fields)
        _flatten_join_tree(node.right, leaves, preds, right_off)
        n_left = len(node.left.fields)
        for lk, rk in zip(node.left_keys, node.right_keys):
            lt = node.left.fields[lk].type
            rt = node.right.fields[rk].type
            t = T.common_super_type(lt, rt) or lt
            preds.append(ir.call(
                "eq", T.BOOLEAN,
                _coerce_ref(offset + lk, lt, t),
                _coerce_ref(right_off + rk, rt, t)))
        if node.residual is not None:
            shift = {i: offset + i for i in
                     range(len(node.left.fields) + len(node.right.fields))}
            preds.append(remap_inputs(node.residual, shift))
        return
    if isinstance(node, FilterNode):
        # filter inside the join tree: lift its conjuncts
        _flatten_join_tree(node.child, leaves, preds, offset)
        shift = {i: offset + i for i in range(len(node.child.fields))}
        preds.append(remap_inputs(node.predicate, shift))
        return
    leaves.append(node)


def _factor_or(p: ir.Expr) -> ir.Expr:
    """Factor conjuncts common to every OR disjunct out of the OR:
    (a AND x) OR (a AND y) -> a AND (x OR y). Exposes join keys hidden
    inside disjunctions — TPC-H Q19's shape (reference sql/
    ExpressionUtils + ExtractCommonPredicatesExpressionRewriter)."""
    if not (isinstance(p, ir.SpecialForm) and p.form == ir.Form.OR):
        return p
    disjunct_conjs = [list(conjuncts(d)) for d in p.args]
    common = [c for c in disjunct_conjs[0]
              if all(c in dc for dc in disjunct_conjs[1:])]
    if not common:
        return p
    rest = []
    for dc in disjunct_conjs:
        left = [c for c in dc if c not in common]
        rest.append(combine_conjuncts(left) or ir.lit(True, T.BOOLEAN))
    new_or = rest[0] if len(rest) == 1 else ir.special(
        ir.Form.OR, T.BOOLEAN, *rest)
    return combine_conjuncts(common + [new_or])


def _coerce_ref(idx: int, t: T.Type, to: T.Type) -> ir.Expr:
    r = ir.input_ref(idx, t)
    return r if t == to else ir.cast(r, to)


import contextvars

#: the optimization pass's shared StatsCalculator (set by optimize());
#: estimates outside a pass fall back to a throwaway calculator
_PASS_CALC: contextvars.ContextVar = contextvars.ContextVar(
    "presto_tpu_stats_calc", default=None)


def _stats_calc(session: Session):
    calc = _PASS_CALC.get()
    if calc is not None and calc.session is session:
        return calc
    from .stats import StatsCalculator
    return StatsCalculator(session)


def _estimate_rows(node: PlanNode, session: Session) -> float:
    """Row estimate via the stats calculus (planner/stats.py): scan
    statistics propagated through filter selectivities (range/NDV math),
    join containment, and group NDV products — the reference's
    cost/StatsCalculator.java role. Memoized across the optimization
    pass via _PASS_CALC."""
    return _stats_calc(session).rows(node)


def _plan_join_graph(join: JoinNode, extra_preds: List[ir.Expr],
                     session: Session) -> PlanNode:
    leaves: List[PlanNode] = []
    preds: List[ir.Expr] = []
    _flatten_join_tree(join, leaves, preds, 0)
    leaves = [_rewrite_joins(lf, session) for lf in leaves]
    for p in extra_preds:
        preds.extend(conjuncts(p))
    preds = [c for p in preds for c in conjuncts(_factor_or(p))]

    # global position ranges per leaf
    offsets: List[int] = []
    off = 0
    for lf in leaves:
        offsets.append(off)
        off += len(lf.fields)
    total = off

    def leaf_of(pos: int) -> int:
        for i in range(len(leaves) - 1, -1, -1):
            if pos >= offsets[i]:
                return i
        raise AssertionError

    # push single-leaf predicates into the leaf
    leaf_preds: Dict[int, List[ir.Expr]] = {i: [] for i in range(len(leaves))}
    edges: List[Tuple[int, int, ir.Expr, ir.Expr]] = []  # (li, lj, lref, rref)
    multi: List[ir.Expr] = []
    for p in preds:
        refs = referenced_inputs(p)
        ls = {leaf_of(r) for r in refs}
        if len(ls) == 1:
            (li,) = ls
            shift = {r: r - offsets[li] for r in refs}
            leaf_preds[li].append(remap_inputs(p, shift))
        elif (len(ls) == 2 and isinstance(p, ir.Call) and p.name == "eq"
                and all(_is_col(a) for a in p.args)):
            a, b = p.args
            la, lb = leaf_of(_col_index(a)), leaf_of(_col_index(b))
            if la != lb:
                edges.append((la, lb, a, b))
            else:
                multi.append(p)
        else:
            multi.append(p)

    new_leaves = [
        FilterNode(child=lf, predicate=combine_conjuncts(ps))
        if ps else lf
        for lf, ps in ((leaves[i], leaf_preds[i]) for i in range(len(leaves)))
    ]
    sizes = [_estimate_rows(nl, session) for nl in new_leaves]

    # greedy join order: start from the largest leaf (fact table), repeatedly
    # join the smallest connected leaf (dimension-first probe keeps the
    # build sides small) — the heuristic core of ReorderJoins
    remaining = set(range(len(leaves)))
    start = max(remaining, key=lambda i: sizes[i])
    joined = [start]
    remaining.remove(start)
    # current node: global positions of its output
    current: PlanNode = new_leaves[start]
    cur_pos: List[int] = [offsets[start] + k
                          for k in range(len(leaves[start].fields))]

    def edges_between(done: Sequence[int], cand: int):
        out = []
        for (la, lb, a, b) in edges:
            if la in done and lb == cand:
                out.append((a, b))
            elif lb in done and la == cand:
                out.append((b, a))
        return out

    while remaining:
        cands = [i for i in remaining if edges_between(joined, i)]
        if not cands:
            # disconnected: only allowed for 1-row-ish sides (cross join)
            i = min(remaining, key=lambda i: sizes[i])
            pairs = []
        else:
            # prefer candidates the unique-key join kernel can execute:
            # either the candidate's keys or the tree's keys must be unique
            # (the tree side can be swapped by _implement_joins)
            def viable(i: int) -> bool:
                ps = edges_between(joined, i)
                rmap_l = {g: k for k, g in enumerate(cur_pos)}
                cand_keys = []
                tree_keys = []
                for (a, b) in ps:
                    off = offsets[i]
                    cand_keys.append(_col_index(b) - off)
                    tree_keys.append(rmap_l[_col_index(a)])
                return (_key_unique(new_leaves[i], cand_keys, session)
                        or _key_unique(current, tree_keys, session))

            def selectivity(i: int) -> float:
                """Estimated fraction of the current tree's rows that
                survive joining candidate i — the containment formula of
                _JoinNode (rows = L*R/max(ndv)) divided by L. Star chains
                then join the MOST SELECTIVE dimension first, so a fused
                probe pipeline's first join prunes the fact table instead
                of merely widening it (a filtered dimension can be far
                more selective than a small-but-unfiltered one — ranking
                by build size alone puts a 12-row store table ahead of a
                1/70-selective customer_demographics filter)."""
                ps = edges_between(joined, i)
                if not ps:
                    return 1.0
                calc = _stats_calc(session)
                cand_est = calc.estimate(new_leaves[i])
                cur_est = calc.estimate(current)
                rmap_l = {g: k for k, g in enumerate(cur_pos)}
                ndv = 1.0
                for (a, b) in ps:
                    ln = cur_est.column(rmap_l[_col_index(a)]).distinct
                    rn = cand_est.column(_col_index(b)
                                         - offsets[i]).distinct
                    cap = max(filter(None, (ln, rn)), default=None)
                    if cap:
                        ndv = max(ndv, cap)
                if ndv <= 1.0:
                    ndv = max(cur_est.rows, cand_est.rows)
                return min(1.0, cand_est.rows / max(ndv, 1.0))

            ranked = sorted(cands, key=lambda i: (not viable(i),
                                                  selectivity(i), sizes[i]))
            i = ranked[0]
            pairs = edges_between(joined, i)
        right = new_leaves[i]
        right_pos = [offsets[i] + k for k in range(len(leaves[i].fields))]
        lmap = {g: k for k, g in enumerate(cur_pos)}
        rmap = {g: k for k, g in enumerate(right_pos)}
        lkeys, rkeys = [], []
        for (a, b) in pairs:
            ia, ib = _col_index(a), _col_index(b)
            lkeys.append(lmap[ia])
            rkeys.append(rmap[ib])
        if not pairs and not (sizes[i] <= 2 or len(right.fields) == 0):
            raise ValueError(
                "cartesian product between large relations is not supported")
        current = JoinNode(
            join_type="inner" if pairs else "cross",
            left=current, right=right,
            left_keys=tuple(lkeys), right_keys=tuple(rkeys),
            fields=current.fields + right.fields,
            build_unique=_key_unique(right, rkeys, session))
        cur_pos = cur_pos + right_pos
        joined.append(i)
        remaining.remove(i)
        # apply any multi-leaf residuals that are now fully available
        avail = set(cur_pos)
        ready = [p for p in multi if referenced_inputs(p) <= avail]
        if ready:
            gmap = {g: k for k, g in enumerate(cur_pos)}
            pred = combine_conjuncts(
                [remap_inputs(p, {r: gmap[r] for r in referenced_inputs(p)})
                 for p in ready])
            current = FilterNode(child=current, predicate=pred)
            multi = [p for p in multi if p not in ready]

    if multi:
        raise ValueError("unapplied join predicates remain")

    # restore original global field order
    gmap = {g: k for k, g in enumerate(cur_pos)}
    exprs = tuple(
        ir.input_ref(gmap[g], _field_at(leaves, offsets, g).type)
        for g in range(total))
    fields = tuple(_field_at(leaves, offsets, g) for g in range(total))
    return ProjectNode(child=current, exprs=exprs, fields=fields)


def _field_at(leaves, offsets, g: int) -> Field:
    for i in range(len(leaves) - 1, -1, -1):
        if g >= offsets[i]:
            return leaves[i].fields[g - offsets[i]]
    raise AssertionError


def _is_col(e: ir.Expr) -> bool:
    """Join-key edge endpoint: a raw column, or a cast the join kernel can
    drop safely. _join_key compares keys in the int64 domain, so an
    int-stored widening cast (integral->integral, date->integral) is
    value-exact without the cast; decimal rescales and float casts are NOT
    and must stay residual filters."""
    if isinstance(e, ir.InputRef):
        return True
    if isinstance(e, ir.Cast) and isinstance(e.arg, ir.InputRef):
        src, dst = e.arg.type, e.type
        int_stored = lambda t: T.is_integral(t) or isinstance(t, T.DateType)
        return int_stored(src) and int_stored(dst)
    return False


def _col_index(e: ir.Expr) -> int:
    if isinstance(e, ir.InputRef):
        return e.index
    return e.arg.index


# ---------------------------------------------------------------------------
# Pass 2: column pruning
# ---------------------------------------------------------------------------

def _prune(node: PlanNode, required: List[int]) -> Tuple[PlanNode, Dict[int, int]]:
    """Rewrite the subtree to produce exactly ``required`` (in order);
    returns the new node + mapping old index -> new index."""
    req = sorted(set(required))
    mapping = {old: new for new, old in enumerate(req)}

    if isinstance(node, TableScanNode):
        cols = tuple(node.columns[i] for i in req)
        fields = tuple(node.fields[i] for i in req)
        return (dataclasses.replace(node, columns=cols, fields=fields),
                mapping)

    if isinstance(node, ProjectNode):
        child_req: Set[int] = set()
        for i in req:
            child_req |= referenced_inputs(node.exprs[i])
        child, cmap = _prune(node.child, sorted(child_req))
        exprs = tuple(remap_inputs(node.exprs[i], cmap) for i in req)
        fields = tuple(node.fields[i] for i in req)
        return ProjectNode(child=child, exprs=exprs, fields=fields), mapping

    if isinstance(node, FilterNode):
        need = set(req) | referenced_inputs(node.predicate)
        child, cmap = _prune(node.child, sorted(need))
        pred = remap_inputs(node.predicate, cmap)
        inner = FilterNode(child=child, predicate=pred)
        return _narrow(inner, [cmap[i] for i in req],
                       [node.fields[i] for i in req]), mapping

    if isinstance(node, JoinNode):
        n_left = len(node.left.fields)
        need = set(req) | set(node.left_keys) | {
            n_left + k for k in node.right_keys}
        if node.residual is not None:
            need |= referenced_inputs(node.residual)
        lneed = sorted(i for i in need if i < n_left)
        rneed = sorted(i - n_left for i in need if i >= n_left)
        left, lmap = _prune(node.left, lneed)
        right, rmap = _prune(node.right, rneed)
        both = {i: lmap[i] for i in lneed}
        both.update({n_left + i: len(left.fields) + rmap[i] for i in rneed})
        fields = tuple(node.left.fields[i] for i in lneed) + tuple(
            node.right.fields[i] for i in rneed)
        inner = JoinNode(
            join_type=node.join_type, left=left, right=right,
            left_keys=tuple(lmap[k] for k in node.left_keys),
            right_keys=tuple(rmap[k] for k in node.right_keys),
            fields=fields,
            residual=(remap_inputs(node.residual, both)
                      if node.residual is not None else None),
            distribution=node.distribution, build_unique=node.build_unique)
        return _narrow(inner, [both[i] for i in req],
                       [node.fields[i] for i in req]), mapping

    if isinstance(node, SemiJoinNode):
        n_src = len(node.source.fields)
        res_refs = (referenced_inputs(node.residual)
                    if node.residual is not None else set())
        src_res = {i for i in res_refs if i < n_src}
        flt_res = {i - n_src for i in res_refs if i >= n_src}
        need = set(req) | set(node.source_keys) | src_res
        source, smap = _prune(node.source, sorted(need))
        fneed = sorted(set(node.filtering_keys) | flt_res)
        filtering, fmap = _prune(node.filtering, fneed)
        residual = None
        if node.residual is not None:
            both = {i: smap[i] for i in src_res}
            both.update({n_src + i: len(source.fields) + fmap[i]
                         for i in flt_res})
            residual = remap_inputs(node.residual, both)
        inner = SemiJoinNode(
            source=source, filtering=filtering,
            source_keys=tuple(smap[k] for k in node.source_keys),
            filtering_keys=tuple(fmap[k] for k in node.filtering_keys),
            fields=source.fields, negated=node.negated,
            residual=residual, null_aware=node.null_aware)
        return _narrow(inner, [smap[i] for i in req],
                       [node.fields[i] for i in req]), mapping

    if isinstance(node, AggregationNode):
        # group keys always kept; aggs only if required
        n_keys = len(node.group_indices)
        child_req = set(node.group_indices)
        kept_aggs = [j for j in range(len(node.aggs))
                     if (n_keys + j) in mapping or not req]
        # keys must stay even if not required (they define grouping)
        for j in kept_aggs:
            if node.aggs[j].arg is not None:
                child_req.add(node.aggs[j].arg)
        child, cmap = _prune(node.child, sorted(child_req))
        aggs = tuple(
            dataclasses.replace(node.aggs[j],
                                arg=(cmap[node.aggs[j].arg]
                                     if node.aggs[j].arg is not None else None))
            for j in kept_aggs)
        fields = tuple(node.fields[i] for i in range(n_keys)) + tuple(
            node.fields[n_keys + j] for j in kept_aggs)
        inner = AggregationNode(
            child=child,
            group_indices=tuple(cmap[g] for g in node.group_indices),
            aggs=aggs, fields=fields, step=node.step,
            default_gids=node.default_gids)
        # remap required through (keys keep positions, aggs shift)
        agg_pos = {n_keys + j: n_keys + k for k, j in enumerate(kept_aggs)}
        inner_map = {**{i: i for i in range(n_keys)}, **agg_pos}
        return _narrow(inner, [inner_map[i] for i in req],
                       [node.fields[i] for i in req]), mapping

    if isinstance(node, (SortNode, TopNNode)):
        need = set(req) | {k.index for k in node.keys}
        child, cmap = _prune(node.child, sorted(need))
        keys = tuple(dataclasses.replace(k, index=cmap[k.index])
                     for k in node.keys)
        inner = dataclasses.replace(node, child=child, keys=keys,
                                    fields=child.fields)
        return _narrow(inner, [cmap[i] for i in req],
                       [node.fields[i] for i in req]), mapping

    if isinstance(node, LimitNode):
        child, cmap = _prune(node.child, req)
        return (LimitNode(child=child, count=node.count, fields=child.fields),
                mapping)

    if isinstance(node, DistinctNode):
        # distinct is over ALL columns: cannot prune through it
        child, cmap = _prune(node.child,
                             list(range(len(node.child.fields))))
        inner = DistinctNode(child=child)
        return _narrow(inner, [cmap[i] for i in req],
                       [node.fields[i] for i in req]), mapping

    if isinstance(node, UnionNode):
        new_children = []
        for c in node.children:
            nc, _ = _prune(c, req)
            new_children.append(nc)
        fields = tuple(node.fields[i] for i in req)
        return (UnionNode(children_=tuple(new_children), fields=fields,
                          distinct=node.distinct), mapping)

    if isinstance(node, ValuesNode):
        rows = tuple(tuple(r[i] for i in req) for r in node.rows)
        fields = tuple(node.fields[i] for i in req)
        return ValuesNode(fields=fields, rows=rows), mapping

    if isinstance(node, OutputNode):
        child, cmap = _prune(node.child, req)
        narrowed = _narrow(child, [cmap[i] for i in req],
                           [node.fields[i] for i in req])
        return OutputNode(child=narrowed,
                          fields=tuple(node.fields[i] for i in req)), mapping

    from .plan import MarkDistinctNode
    if isinstance(node, MarkDistinctNode):
        # mask channels read (keys, arg): keep all child columns live but
        # recurse so the subtree below still prunes
        child_req = list(range(len(node.child.fields)))
        child, cmap = _prune(node.child, child_req)
        child = _narrow(child, [cmap[i] for i in child_req],
                        list(node.child.fields))
        return (dataclasses.replace(node, child=child),
                {i: i for i in range(len(node.fields))})

    from .plan import GroupIdNode
    if isinstance(node, GroupIdNode):
        # all child columns stay live (keys feed the grouping sets, the
        # rest are agg args), but recurse so the subtree below still prunes
        child_req = list(range(len(node.child.fields)))
        child, cmap = _prune(node.child, child_req)
        child = _narrow(child, [cmap[i] for i in child_req],
                        list(node.child.fields))
        return (dataclasses.replace(node, child=child),
                {i: i for i in range(len(node.fields))})

    # unknown node: don't prune through
    return node, {i: i for i in range(len(node.fields))}


def _narrow(node: PlanNode, indices: List[int],
            fields: List[Field]) -> PlanNode:
    """Project the node down to ``indices`` unless it already matches."""
    if indices == list(range(len(node.fields))):
        return node
    return ProjectNode(
        child=node,
        exprs=tuple(ir.input_ref(i, node.fields[i].type) for i in indices),
        fields=tuple(fields))


# ---------------------------------------------------------------------------
# Pass 3: join implementation (build side + distribution)
# ---------------------------------------------------------------------------

def _key_unique(node: PlanNode, keys: Sequence[int],
                session: Session) -> bool:
    """Conservatively: are these key columns unique in this relation?"""
    if isinstance(node, AggregationNode):
        return set(keys) == set(range(len(node.group_indices)))
    if isinstance(node, DistinctNode):
        return set(keys) == set(range(len(node.fields)))
    if isinstance(node, (FilterNode, SortNode, TopNNode, LimitNode)):
        return _key_unique(node.child, keys, session)
    if isinstance(node, ProjectNode):
        src = []
        for k in keys:
            e = node.exprs[k]
            if not isinstance(e, ir.InputRef):
                return False
            src.append(e.index)
        return _key_unique(node.child, src, session)
    if isinstance(node, TableScanNode):
        conn = session.catalogs.get(node.catalog)
        stats = conn.metadata.table_stats(node.table)
        names = {node.columns[k] for k in keys}
        if stats.primary_key and set(stats.primary_key) <= names:
            return True
        if stats.row_count is None:
            return False
        for k in keys:
            cs = stats.columns.get(node.columns[k])
            if cs is not None and cs.distinct_count is not None \
                    and cs.distinct_count >= 0.999 * stats.row_count:
                return True  # any single unique column makes the tuple unique
        return False
    if isinstance(node, JoinNode):
        # keys on the probe side of a PK-FK join stay unique
        n_left = len(node.left.fields)
        lkeys = [k for k in keys if k < n_left]
        if len(lkeys) == len(keys) and node.build_unique:
            return _key_unique(node.left, lkeys, session)
        return False
    return False


def _implement_joins(node: PlanNode, session: Session) -> PlanNode:
    node = node.with_children([_implement_joins(c, session)
                               for c in node.children])
    if not isinstance(node, JoinNode) or node.join_type == "cross":
        return node
    left_unique = _key_unique(node.left, node.left_keys, session)
    right_unique = _key_unique(node.right, node.right_keys, session)
    lrows = _estimate_rows(node.left, session)
    rrows = _estimate_rows(node.right, session)

    swap = False
    if node.join_type == "inner":
        if right_unique and left_unique:
            swap = rrows > lrows
        elif left_unique:
            swap = True
        elif not right_unique:
            # many-to-many: expansion join; build on the smaller side
            swap = lrows < rrows
    # left outer: probe must stay on the left (expansion join handles a
    # non-unique build side)
    if swap:
        n_left, n_right = len(node.left.fields), len(node.right.fields)
        # old global index -> index in the swapped join's output
        remap = {i: n_right + i for i in range(n_left)}
        remap.update({n_left + j: j for j in range(n_right)})
        inner = JoinNode(
            join_type="inner", left=node.right, right=node.left,
            left_keys=node.right_keys, right_keys=node.left_keys,
            fields=node.right.fields + node.left.fields,
            residual=(remap_inputs(node.residual, remap)
                      if node.residual is not None else None),
            build_unique=True,
            distribution=_distribution(node.left, lrows, session))
        # restore the original left+right field order for parents
        return ProjectNode(
            child=inner,
            exprs=tuple(ir.input_ref(remap[i], f.type)
                        for i, f in enumerate(node.fields)),
            fields=node.fields)
    if node.join_type == "full":
        # a replicated build would emit its unmatched-row tail once per
        # shard; FULL OUTER must hash-partition both sides (reference
        # DetermineJoinDistributionType.java mustPartition for FULL)
        return dataclasses.replace(node, build_unique=right_unique,
                                   distribution="partitioned")
    return dataclasses.replace(
        node, build_unique=right_unique,
        distribution=_distribution(node.right, rrows, session))


def _distribution(build: PlanNode, rows: float, session: Session) -> str:
    limit = session.properties.get("broadcast_join_row_limit",
                                   BROADCAST_ROW_LIMIT)
    return "replicated" if rows <= limit else "partitioned"


# ---------------------------------------------------------------------------
# Pass 4: eager aggregation — partial agg pushed through an inner join
# ---------------------------------------------------------------------------

#: aggregate functions with mergeable partial states the push understands
_PUSHABLE_AGG_FNS = ("sum", "count", "count_star", "min", "max", "avg")


def _column_distinct(node: PlanNode, idx: int,
                     session: Session) -> Optional[float]:
    """Distinct-count estimate for one output column via the stats
    calculus (NDV propagated from scan statistics, capped by filtered
    row counts) — the eager-aggregation gate's input."""
    calc = _stats_calc(session)
    d = calc.estimate(node).column(idx).distinct
    return min(d, calc.rows(node)) if d is not None else None


def _push_partial_agg_through_join(node: PlanNode,
                                   session: Session) -> PlanNode:
    """Rewrite Agg(Project*(Join(L, R))) into
    Final(Project(Join(Partial(Project(L)), R))) when every aggregate
    input comes from the probe (left) side — the reference's
    iterative/rule/PushPartialAggregationThroughJoin.java (+ the
    PushPartialAggregationThroughExchange state-split machinery).

    Correct for INNER joins regardless of build-key multiplicity: a
    partial-state row replicated by k matches merges identically to its
    k underlying rows (sum/count/min/max/avg states are replication-
    linear), and whole partial groups match-or-drop together because the
    left join keys are part of the partial grouping key. The win on this
    hardware: the probe side shrinks to one state row per group BEFORE
    the join, so probe gathers and the post-join group-by touch
    group-count rows, not input rows."""
    node = node.with_children(
        [_push_partial_agg_through_join(c, session)
         for c in node.children])
    if not isinstance(node, AggregationNode) or node.step != "single":
        return node
    out = _try_eager_agg(node, session)
    return out if out is not None else node


def _try_eager_agg(agg: AggregationNode,
                   session: Session) -> Optional[PlanNode]:
    from .rules import _inline_into

    if not agg.group_indices:
        return None                  # global agg: partial is one row; no win
    for a in agg.aggs:
        if a.distinct or a.mask is not None \
                or a.fn not in _PUSHABLE_AGG_FNS:
            return None
    chain: List[ProjectNode] = []
    cur = agg.child
    while isinstance(cur, ProjectNode):
        chain.append(cur)
        cur = cur.child
    if not isinstance(cur, JoinNode) or cur.join_type != "inner" \
            or cur.residual is not None:
        return None
    join = cur
    # compose the project chain: agg-child column i as an expr over the
    # join's output schema
    exprs: Optional[List[ir.Expr]] = None
    for p in chain:
        exprs = list(p.exprs) if exprs is None \
            else [_inline_into(e, p.exprs) for e in exprs]
    if exprs is None:
        exprs = [ir.input_ref(i, f.type)
                 for i, f in enumerate(join.fields)]
    nL = len(join.left.fields)

    def left_only(e: ir.Expr) -> bool:
        refs = referenced_inputs(e)
        return all(r < nL for r in refs)

    # classify group keys: left-side exprs join the partial grouping key;
    # right-side keys must be bare column refs (still available above)
    left_group: List[Tuple[int, ir.Expr]] = []
    right_group: List[Tuple[int, int]] = []
    for pos in range(len(agg.group_indices)):
        e = exprs[agg.group_indices[pos]]
        if left_only(e):
            left_group.append((pos, e))
        elif isinstance(e, ir.InputRef) and e.index >= nL:
            right_group.append((pos, e.index - nL))
        else:
            return None
    for a in agg.aggs:
        if a.arg is not None and not left_only(exprs[a.arg]):
            return None

    # below-projection over the left side: join keys + left group keys +
    # aggregate inputs (deduplicated by structural equality)
    Lf = join.left.fields
    below: List[ir.Expr] = []
    below_fields: List[Field] = []
    index_of: Dict[ir.Expr, int] = {}

    def add(e: ir.Expr, name: str) -> int:
        if e in index_of:
            return index_of[e]
        index_of[e] = len(below)
        below.append(e)
        below_fields.append(Field(name, e.type))
        return len(below) - 1

    jk_below = [add(ir.input_ref(k, Lf[k].type), Lf[k].name)
                for k in join.left_keys]
    n_keys = len(agg.group_indices)
    gk_below = [(pos, add(e, agg.fields[pos].name))
                for pos, e in left_group]
    agg_below = [None if a.arg is None
                 else add(exprs[a.arg], f"$aggin{i}")
                 for i, a in enumerate(agg.aggs)]

    partial_group: List[int] = list(dict.fromkeys(
        jk_below + [b for _, b in gk_below]))
    if len(partial_group) > 4:
        # the pushed partial sorts by (dead, null, data) per key: TPU
        # variadic-sort compile time grows superlinearly with operand
        # count (measured minutes at ~10 operands), so wide grouping
        # keys stay above the join
        return None
    # cardinality gate (the reference rule is cost-based): decline when
    # statistics PROVE the partial cannot shrink its input — the push
    # would add a full sort-based aggregation pass for nothing. When any
    # key's distinct count is unknown, push optimistically: the worst
    # case is one extra aggregation pass over rows the plan was already
    # aggregating, while the win (q3/q55-shaped plans) is an order of
    # magnitude.
    distincts = [_column_distinct(
        ProjectNode(child=join.left, exprs=tuple(below),
                    fields=tuple(below_fields)), b, session)
        for b in partial_group]
    if all(d is not None for d in distincts):
        groups_est = 1.0
        for d in distincts:
            groups_est *= max(d, 1.0)
        left_rows = _estimate_rows(join.left, session)
        if groups_est >= 0.5 * left_rows:
            return None
    below_proj = ProjectNode(child=join.left, exprs=tuple(below),
                             fields=tuple(below_fields))
    partial_aggs = tuple(
        dataclasses.replace(a, arg=agg_below[i])
        for i, a in enumerate(agg.aggs))
    partial = AggregationNode(
        child=below_proj, group_indices=tuple(partial_group),
        aggs=partial_aggs, fields=(), step="partial")
    from .fragmenter import _agg_state_fields
    partial = dataclasses.replace(partial,
                                  fields=_agg_state_fields(partial))
    # the rewritten join: partial states probe the unchanged build side
    new_left_keys = tuple(partial_group.index(b) for b in jk_below)
    new_join = dataclasses.replace(
        join, left=partial, left_keys=new_left_keys,
        fields=tuple(partial.fields) + tuple(join.right.fields))
    # above-projection: [final group keys..., state columns...] — the
    # final step consumes states positionally after the keys
    np_fields = len(partial.fields)
    key_ref: Dict[int, ir.Expr] = {}
    for pos, e in left_group:
        b = index_of[e]
        key_ref[pos] = ir.input_ref(partial_group.index(b),
                                    below_fields[b].type)
    for pos, rcol in right_group:
        key_ref[pos] = ir.input_ref(np_fields + rcol,
                                    join.right.fields[rcol].type)
    above_exprs: List[ir.Expr] = [key_ref[pos] for pos in range(n_keys)]
    above_fields: List[Field] = [agg.fields[pos] for pos in range(n_keys)]
    from ..ops.aggregation import AggSpec
    st = len(partial_group)
    state_args: List[int] = []
    for a in agg.aggs:
        spec = AggSpec(a.fn, a.arg, a.output_type, a.name)
        state_args.append(len(above_exprs))
        for sn, stype in spec.state_types():
            above_exprs.append(
                ir.input_ref(st, stype))
            above_fields.append(Field(sn, stype))
            st += 1
    above = ProjectNode(child=new_join, exprs=tuple(above_exprs),
                        fields=tuple(above_fields))
    final_aggs = tuple(
        dataclasses.replace(a, arg=state_args[i])
        for i, a in enumerate(agg.aggs))
    return AggregationNode(
        child=above, group_indices=tuple(range(n_keys)),
        aggs=final_aggs, fields=agg.fields, step="final",
        default_gids=agg.default_gids)


# ---------------------------------------------------------------------------
# Pass 5: stats-bounded dense grouping (the rewrite gate for the
# ops/scatter_agg.py digit-scatter group-by path)
# ---------------------------------------------------------------------------

from ..ops.aggregation import DENSE_SCATTER_LIMIT  # noqa: E402


def _group_key_bound(node: PlanNode, idx: int, session: Session
                     ) -> Optional[Tuple[int, int]]:
    """Static [lo, hi] for one group-key column when statistics prove it:
    integer-family storage with both range ends known. Bounds must be
    TRUE bounds, not estimates — the stats calculus only ever narrows
    ranges from connector min/max (filters keep ranges, joins/projections
    pass them through), so a connector publishing exact min/max yields
    hard bounds. The executor still cross-checks every batch through the
    row-error channel (exec/local.py), so a connector overclaiming its
    statistics fails the query instead of corrupting groups."""
    t = node.fields[idx].type
    if not isinstance(t, _BOUNDABLE):
        return None
    ce = _stats_calc(session).estimate(node).column(idx)
    if ce.lo is None or ce.hi is None or ce.hi < ce.lo:
        return None
    import math
    lo, hi = math.floor(ce.lo), math.ceil(ce.hi)
    if hi - lo + 1 > DENSE_SCATTER_LIMIT:
        return None
    return int(lo), int(hi)


def _bounds_for_keys(child: PlanNode, key_cols: Sequence[int],
                     session: Session
                     ) -> Tuple[Optional[Tuple[int, int]], ...]:
    """key_bounds tuple for a grouping over ``key_cols`` of ``child``, or
    () when the dense composite code cannot engage. The gate mirrors the
    kernel's dispatch (ops/aggregation.py dense_group_plan): every key
    needs a host-known domain — integer stats bounds here, dictionary /
    boolean domains at trace time — and the composite product must stay
    under DENSE_SCATTER_LIMIT. Unknown string/bool domains contribute
    their NDV estimate (the kernel re-gates with the true dictionary
    size, so an optimistic pass here costs nothing)."""
    calc = _stats_calc(session)
    bounds: List[Optional[Tuple[int, int]]] = []
    domain = 1.0
    any_bound = False
    for k in key_cols:
        t = child.fields[k].type
        if isinstance(t, _BOUNDABLE):
            b = _group_key_bound(child, k, session)
            if b is None:
                return ()
            bounds.append(b)
            domain *= b[1] - b[0] + 2          # + NULL component
            any_bound = True
        elif t.is_string or isinstance(t, T.BooleanType):
            # domain known only at trace time (dictionary size); gate on
            # the NDV estimate when stats offer one
            bounds.append(None)
            d = calc.estimate(child).column(k).distinct
            if d is not None:
                domain *= max(d, 1.0) + 1
        else:
            return ()
    if not any_bound or domain > DENSE_SCATTER_LIMIT:
        return ()
    return tuple(bounds)


# ---------------------------------------------------------------------------
# Pass 6: stats-driven join strategy (direct-address builds + semi-join
# distribution) — the rewrite gate for ops/join.prepare_direct_keyed
# ---------------------------------------------------------------------------

def _join_key_bounds(node: PlanNode, keys: Sequence[int],
                     session: Session
                     ) -> Tuple[Optional[Tuple[int, int]], ...]:
    """Hard [lo, hi] per build/filtering key when statistics prove them
    all, or () when the direct-address table cannot engage. Bounds must
    be TRUE bounds (the _group_key_bound contract): the stats calculus
    only narrows ranges from connector min/max, and the executor
    cross-checks every build batch through the row-error channel
    (STATS_BOUND_VIOLATION), so an overclaiming connector fails the
    query instead of dropping matches. The composite mixed-radix
    product gates against ops/join.DIRECT_KEYED_LIMIT — the same
    dispatch shape as dense grouping's DENSE_SCATTER_LIMIT."""
    from ..ops.join import direct_keyed_plan
    import math
    if not keys:
        return ()
    calc = _stats_calc(session)
    bounds: List[Tuple[int, int]] = []
    for k in keys:
        t = node.fields[k].type
        if not isinstance(t, _BOUNDABLE):
            return ()
        ce = calc.estimate(node).column(k)
        if ce.lo is None or ce.hi is None or ce.hi < ce.lo:
            return ()
        bounds.append((int(math.floor(ce.lo)), int(math.ceil(ce.hi))))
    if direct_keyed_plan(tuple(bounds)) is None:
        return ()
    return tuple(bounds)


def _attach_join_strategy(node: PlanNode, session: Session,
                          dense: bool = True) -> PlanNode:
    """Attach stats-derived build-key bounds to joins whose composite
    key domain is provably small — the planner side of the dense-key
    direct-address join (ops/join.prepare_direct_keyed: a bounded key
    tuple answers in TWO gathers independent of build size, where the
    sorted fallback pays O(log n) gathers per probe lane) — and pick
    semi-join distribution from the estimated filtering size instead of
    broadcast-membership-everywhere. Runs AFTER _implement_joins /
    the eager-agg push, so build sides are final. ``dense`` is the
    `join_dense_path` escape hatch — it gates ONLY the direct-address
    bounds; distribution selection is an independent decision and stays
    on either way."""
    node = node.with_children([_attach_join_strategy(c, session, dense)
                               for c in node.children])
    if dense and isinstance(node, JoinNode) and node.join_type != "cross" \
            and node.right_keys:
        kb = _join_key_bounds(node.right, node.right_keys, session)
        if kb:
            node = dataclasses.replace(node, key_bounds=kb)
    if isinstance(node, SemiJoinNode):
        if dense:
            kb = _join_key_bounds(node.filtering, node.filtering_keys,
                                  session)
            if kb:
                node = dataclasses.replace(node, key_bounds=kb)
        if not (node.negated and node.null_aware):
            # NULL-aware anti joins (NOT IN) must see the GLOBAL
            # filtering set (any NULL build key poisons every shard's
            # verdict; an empty set passes everything) — they stay
            # replicated. Everything else partitions when the
            # filtering set is too large to broadcast.
            rows = _estimate_rows(node.filtering, session)
            node = dataclasses.replace(
                node,
                distribution=_distribution(node.filtering, rows,
                                           session))
    return node


def _attach_group_bounds(node: PlanNode, session: Session) -> PlanNode:
    """Attach stats-derived static key bounds to aggregations and
    DISTINCTs whose composite key domain is provably small — the
    planner-side gate that routes multi-key GROUP BYs onto the dense i32
    scatter path (the reference BigintGroupByHash dense-array mode,
    generalized to mixed-radix composite keys)."""
    node = node.with_children([_attach_group_bounds(c, session)
                               for c in node.children])
    if isinstance(node, AggregationNode) and node.group_indices:
        kb = _bounds_for_keys(node.child, node.group_indices, session)
        if kb:
            return dataclasses.replace(node, key_bounds=kb)
    if isinstance(node, DistinctNode) and node.fields:
        kb = _bounds_for_keys(node.child,
                              tuple(range(len(node.fields))), session)
        if kb:
            return dataclasses.replace(node, key_bounds=kb)
    return node

"""Plan fragmenter: cut an optimized plan into exchange-separated stages.

The role of the reference's PlanFragmenter (reference
presto-main/.../sql/planner/PlanFragmenter.java:88,106 — SubPlan tree of
PlanFragments; exchange placement decided earlier by
optimizations/AddExchanges.java). Here both jobs collapse into one
bottom-up pass: each relational operator decides whether it can run
where its child runs or must cut a fragment boundary, and aggregations
split into PARTIAL (upstream, emits states) + FINAL (downstream, over a
RemoteSourceNode) exactly like AddExchanges' partial-aggregation rewrite.

Fragment partitioning handles (reference SystemPartitioningHandle):

- ``source``  — one task per split subset; the fragment contains the
  (single) TableScanNode chain,
- ``fixed``   — hash-partitioned intermediate stage, one task per worker,
- ``single``  — one task; final merges / sorts / limits / output.

Output specs (reference PartitioningScheme): ``partition(keys)``,
``broadcast``, ``single``.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from ..expr import ir
from ..ops.aggregation import AggSpec
from ..sql.analyzer import Field
from .plan import (
    AggregationNode, DistinctNode, FilterNode, GroupIdNode, JoinNode,
    LimitNode, OutputNode, PlanAgg, PlanNode, ProjectNode,
    RemoteSourceNode, SemiJoinNode, SortNode, TableScanNode, TopNNode,
    UnionNode, ValuesNode, WindowNode,
)


@dataclasses.dataclass(frozen=True)
class OutputSpec:
    """How a fragment's rows leave it (reference PartitioningScheme)."""

    kind: str                      # partition | broadcast | single
    keys: Tuple[int, ...] = ()     # partition key positions in output


@dataclasses.dataclass
class PlanFragment:
    id: int
    root: PlanNode
    partitioning: str              # source | fixed | single
    output: Optional[OutputSpec] = None   # None until the consumer fixes it


class FragmentedPlan:
    """Fragments in creation order; the last one is the root (single)."""

    def __init__(self, fragments: List[PlanFragment]):
        self.fragments = fragments

    @property
    def root(self) -> PlanFragment:
        return self.fragments[-1]

    def by_id(self) -> Dict[int, PlanFragment]:
        return {f.id: f for f in self.fragments}


def fragment_plan(root: PlanNode) -> FragmentedPlan:
    fr = _Fragmenter()
    node, loc = fr.visit(root)
    if loc != "single":
        node = fr.cut(node, loc, OutputSpec("single"))
    fr.fragments.append(PlanFragment(fr.next_id(), node, "single"))
    return FragmentedPlan(fr.fragments)


# -- mesh stages --------------------------------------------------------------
# The same exchange-placement pass, read as an SPMD stage recipe: on a
# device mesh a fragment is not a set of worker tasks but one shard_map
# program per shard, and the fragment boundaries name the collectives
# between them (partition -> all_to_all, broadcast -> all_gather,
# single -> gather/replicated finalize). exec/distributed.py implements
# the stages inline per operator; this pass is the *selector's* view:
# whether a plan cuts cleanly into mesh stages (anything the fragmenter
# cannot place cannot run SPMD) and what the stage DAG looks like, for
# auto-routing, EXPLAIN surfaces and the profiler.

@dataclasses.dataclass(frozen=True)
class MeshStage:
    """One SPMD stage: ``kind`` is the fragment partitioning mapped to
    its mesh form (``scan-shard`` = data-parallel over splits, ``hash``
    = hash-partitioned on the owning shard, ``single`` = replicated /
    gathered finalize), ``exchange`` how its rows leave (``partition``,
    ``broadcast``, ``single`` or None for the root). ``fused`` marks a
    partition exchange the executor collapses into its consumer's
    shard_map program (compute + bucket-count + ship as one dispatch);
    one-shot whole-table shuffles — window, distinct, mark-distinct,
    percentile finalize — stay unfused because a tight per-round quota
    beats saving a single sync there."""

    id: int
    kind: str
    exchange: Optional[str]
    keys: Tuple[int, ...]
    ops: Tuple[str, ...]
    fused: bool = False


@dataclasses.dataclass
class MeshPlan:
    stages: List[MeshStage]
    supported: bool
    reason: str = ""


_MESH_STAGE_KIND = {"source": "scan-shard", "fixed": "hash",
                    "single": "single"}


def _stage_ops(node: PlanNode) -> Tuple[str, ...]:
    """Operator kinds inside one fragment, leaf-last, stopping at the
    RemoteSourceNodes that stand in for upstream stages."""
    out: List[str] = []

    def walk(n: PlanNode) -> None:
        if isinstance(n, RemoteSourceNode):
            return
        out.append(type(n).__name__.replace("Node", ""))
        for c in n.children:
            walk(c)

    walk(node)
    return tuple(out)


#: partition-exchange consumers whose shard_map program absorbs the
#: shuffle (exec/distributed.py fuses repartition into these); window /
#: distinct / mark-distinct / sort gather the whole table in one round
#: and stay on the quota-tight unfused path.
_FUSABLE_CONSUMERS = frozenset({"Aggregation", "Join", "SemiJoin"})


def _partition_consumers(fragments: List[PlanFragment]) -> Dict[int, str]:
    """Map upstream fragment id -> op name of the nearest operator above
    the RemoteSourceNode that pulls from it in the consuming fragment."""
    out: Dict[int, str] = {}

    def walk(n: PlanNode, above: str) -> None:
        name = type(n).__name__.replace("Node", "")
        if isinstance(n, RemoteSourceNode):
            for fid in n.fragment_ids:
                out[fid] = above
            return
        for c in n.children:
            walk(c, name)

    for f in fragments:
        walk(f.root, "Output")
    return out


def plan_mesh_stages(root: PlanNode) -> MeshPlan:
    """Cut a plan into mesh stages, or say why it cannot be cut. A plan
    the fragmenter cannot place (an operator with no exchange rule) has
    no SPMD form and must stay on the single-device path — the mesh
    auto-router treats ``supported=False`` as a local fallback, never
    an error."""
    try:
        fragmented = fragment_plan(root)
    except NotImplementedError as e:
        return MeshPlan([], False, str(e))
    consumers = _partition_consumers(fragmented.fragments)
    stages = [
        MeshStage(f.id, _MESH_STAGE_KIND.get(f.partitioning, "single"),
                  f.output.kind if f.output is not None else None,
                  tuple(f.output.keys) if f.output is not None else (),
                  _stage_ops(f.root),
                  fused=(f.output is not None
                         and f.output.kind == "partition"
                         and consumers.get(f.id) in _FUSABLE_CONSUMERS))
        for f in fragmented.fragments
    ]
    return MeshPlan(stages, True)


class _Fragmenter:
    def __init__(self) -> None:
        self.fragments: List[PlanFragment] = []
        self._seq = 0

    def next_id(self) -> int:
        self._seq += 1
        return self._seq - 1

    def cut(self, node: PlanNode, loc: str, output: OutputSpec,
            partitioning: Optional[str] = None) -> RemoteSourceNode:
        """Close ``node``'s fragment with the given output spec and
        return the RemoteSourceNode the consumer reads instead."""
        f = PlanFragment(self.next_id(), node,
                         partitioning or ("fixed" if loc == "fixed"
                                          else "source"),
                         output)
        self.fragments.append(f)
        return RemoteSourceNode(fragment_ids=(f.id,), fields=node.fields)

    # -- dispatch ------------------------------------------------------------
    def visit(self, node: PlanNode) -> Tuple[PlanNode, str]:
        """Returns (embedded node, location) where location says which
        partitioning the current (open) fragment needs: source / fixed /
        single / any (location-free leaves like VALUES)."""
        return getattr(self, "_" + type(node).__name__, self._default)(node)

    def _default(self, node: PlanNode):
        raise NotImplementedError(
            f"cannot fragment {type(node).__name__}")

    # -- leaves --------------------------------------------------------------
    def _TableScanNode(self, node: TableScanNode):
        return node, "source"

    def _ValuesNode(self, node: ValuesNode):
        return node, "any"

    # -- elementwise: stay in the child's fragment ---------------------------
    def _FilterNode(self, node: FilterNode):
        child, loc = self.visit(node.child)
        return dataclasses.replace(node, child=child), loc

    def _ProjectNode(self, node: ProjectNode):
        child, loc = self.visit(node.child)
        return dataclasses.replace(node, child=child), loc

    def _GroupIdNode(self, node: GroupIdNode):
        child, loc = self.visit(node.child)
        return dataclasses.replace(node, child=child), loc

    def _OutputNode(self, node: OutputNode):
        child, loc = self.visit(node.child)
        if loc not in ("single", "any"):
            child = self.cut(child, loc, OutputSpec("single"))
            loc = "single"
        return dataclasses.replace(node, child=child), "single"

    # -- aggregation: PARTIAL upstream + FINAL after the exchange ------------
    def _AggregationNode(self, node: AggregationNode):
        child, loc = self.visit(node.child)
        if loc in ("single", "any"):
            return dataclasses.replace(node, child=child), loc
        if node.step == "partial":
            # already split by the optimizer (partial-agg pushed through a
            # join): states merge downstream, leave it in place
            return dataclasses.replace(node, child=child), loc
        if node.step == "final":
            # pre-split final: hash-exchange the states by group key and
            # finalize in a fixed stage (global finals gather to one task)
            if node.group_indices:
                src = self.cut(child, loc,
                               OutputSpec("partition",
                                          tuple(node.group_indices)))
                return dataclasses.replace(node, child=src), "fixed"
            src = self.cut(child, loc, OutputSpec("single"))
            return dataclasses.replace(node, child=src), "single"
        from ..ops.aggregation import percentile_drains
        if percentile_drains(node.aggs, [f.type for f in child.fields],
                             bool(node.group_indices)):
            if node.group_indices:
                # grouped approx_percentile: colocate each group's raw
                # rows by key hash and run the exact single-step
                # aggregation per task — parallel across tasks, unlike
                # the reference's mergeable-sketch route but with the
                # same exchange shape (partition by group keys)
                src = self.cut(child, loc,
                               OutputSpec("partition",
                                          tuple(node.group_indices)))
                return dataclasses.replace(node, child=src), "fixed"
            # global string percentile: exact pass needs all rows in one
            # task (dictionary ranks are batch-local)
            src = self.cut(child, loc, OutputSpec("single"))
            return dataclasses.replace(node, child=src), "single"
        keys = list(node.group_indices)
        partial_fields = _agg_state_fields(node)
        partial = dataclasses.replace(
            node, child=child, step="partial", fields=partial_fields)
        if keys:
            src = self.cut(partial, loc,
                           OutputSpec("partition",
                                      tuple(range(len(keys)))))
            final = dataclasses.replace(
                node, child=src, step="final",
                group_indices=tuple(range(len(keys))))
            return final, "fixed"
        src = self.cut(partial, loc, OutputSpec("single"))
        final = dataclasses.replace(node, child=src, step="final")
        return final, "single"

    def _DistinctNode(self, node: DistinctNode):
        child, loc = self.visit(node.child)
        if loc in ("single", "any"):
            return dataclasses.replace(node, child=child), loc
        cols = tuple(range(len(node.fields)))
        partial = AggregationNode(child=child, group_indices=cols,
                                  aggs=(), fields=node.fields,
                                  step="partial",
                                  key_bounds=node.key_bounds)
        src = self.cut(partial, loc, OutputSpec("partition", cols))
        final = dataclasses.replace(node, child=src)
        return final, "fixed"

    # -- joins ---------------------------------------------------------------
    def _JoinNode(self, node: JoinNode):
        left, lloc = self.visit(node.left)
        right, rloc = self.visit(node.right)
        if lloc in ("single", "any") and rloc in ("single", "any"):
            return dataclasses.replace(node, left=left, right=right), \
                ("single" if "single" in (lloc, rloc) else "any")
        if node.distribution == "replicated" or node.join_type == "cross":
            # build side broadcast to every probe task; probe stays put
            if rloc not in ("any",):
                right = self.cut(right, rloc, OutputSpec("broadcast"))
            if lloc == "any":
                lloc = "single"
            return dataclasses.replace(node, left=left, right=right), lloc
        # partitioned: hash both sides by join keys into a fixed stage
        left = self.cut(left, lloc if lloc != "any" else "single",
                        OutputSpec("partition", tuple(node.left_keys)))
        right = self.cut(right, rloc if rloc != "any" else "single",
                         OutputSpec("partition", tuple(node.right_keys)))
        return dataclasses.replace(node, left=left, right=right), "fixed"

    def _SemiJoinNode(self, node: SemiJoinNode):
        source, sloc = self.visit(node.source)
        filtering, floc = self.visit(node.filtering)
        if sloc in ("single", "any") and floc in ("single", "any"):
            return dataclasses.replace(node, source=source,
                                       filtering=filtering), \
                ("single" if "single" in (sloc, floc) else "any")
        if node.distribution == "partitioned" \
                and not (node.negated and node.null_aware):
            # stats said the filtering set is too large to broadcast
            # (optimizer._attach_join_strategy): hash BOTH sides by key
            # into a fixed stage — matching keys colocate, so the
            # per-partition membership verdicts compose exactly.
            # NULL-aware anti joins never take this branch (their
            # build_has_null / build_empty facts are global).
            source = self.cut(source, sloc if sloc != "any" else "single",
                              OutputSpec("partition",
                                         tuple(node.source_keys)))
            filtering = self.cut(
                filtering, floc if floc != "any" else "single",
                OutputSpec("partition", tuple(node.filtering_keys)))
            return dataclasses.replace(node, source=source,
                                       filtering=filtering), "fixed"
        # the filtering set broadcasts: every source task needs every key
        # (and NULL-aware anti semantics need global NULL knowledge)
        if floc != "any":
            filtering = self.cut(filtering, floc, OutputSpec("broadcast"))
        if sloc == "any":
            sloc = "single"
        return dataclasses.replace(node, source=source,
                                   filtering=filtering), sloc

    # -- order/limit: partial upstream, merge in a single stage --------------
    def _SortNode(self, node: SortNode):
        child, loc = self.visit(node.child)
        if loc in ("single", "any"):
            return dataclasses.replace(node, child=child), loc
        partial = dataclasses.replace(node, child=child)
        src = self.cut(partial, loc, OutputSpec("single"))
        return dataclasses.replace(node, child=src), "single"

    def _TopNNode(self, node: TopNNode):
        child, loc = self.visit(node.child)
        if loc in ("single", "any"):
            return dataclasses.replace(node, child=child), loc
        partial = dataclasses.replace(node, child=child)
        src = self.cut(partial, loc, OutputSpec("single"))
        return dataclasses.replace(node, child=src), "single"

    def _LimitNode(self, node: LimitNode):
        child, loc = self.visit(node.child)
        if loc in ("single", "any"):
            return dataclasses.replace(node, child=child), loc
        partial = dataclasses.replace(node, child=child)
        src = self.cut(partial, loc, OutputSpec("single"))
        return dataclasses.replace(node, child=src), "single"

    def _MarkDistinctNode(self, node):
        """First-occurrence flags need all rows of a group in one task:
        partition by the group keys (or gather when there are none)."""
        child, loc = self.visit(node.child)
        if loc in ("single", "any"):
            return dataclasses.replace(node, child=child), loc
        if node.partition_cols:
            src = self.cut(child, loc,
                           OutputSpec("partition",
                                      tuple(node.partition_cols)))
            return dataclasses.replace(node, child=src), "fixed"
        src = self.cut(child, loc, OutputSpec("single"))
        return dataclasses.replace(node, child=src), "single"

    def _UnnestNode(self, node):
        # row-local expansion: runs wherever its child runs
        child, loc = self.visit(node.child)
        return dataclasses.replace(node, child=child), loc

    def _WindowNode(self, node: WindowNode):
        child, loc = self.visit(node.child)
        if loc in ("single", "any"):
            return dataclasses.replace(node, child=child), loc
        if node.partition_indices:
            src = self.cut(child, loc,
                           OutputSpec("partition",
                                      tuple(node.partition_indices)))
            return dataclasses.replace(node, child=src), "fixed"
        src = self.cut(child, loc, OutputSpec("single"))
        return dataclasses.replace(node, child=src), "single"

    def _UnionNode(self, node: UnionNode):
        ids: List[int] = []
        embedded: List[PlanNode] = []
        locs: List[str] = []
        for c in node.children:
            n, loc = self.visit(c)
            embedded.append(n)
            locs.append(loc)
        if all(l in ("single", "any") for l in locs):
            return node.with_children(embedded), \
                ("single" if "single" in locs else "any")
        for n, loc in zip(embedded, locs):
            src = self.cut(n, loc if loc != "any" else "single",
                           OutputSpec("single"))
            ids.extend(src.fragment_ids)
        return RemoteSourceNode(fragment_ids=tuple(ids),
                                fields=node.fields), "single"


def _agg_state_fields(node: AggregationNode) -> Tuple[Field, ...]:
    """Output schema of the PARTIAL step: group keys + state columns."""
    child = node.child
    fields: List[Field] = [child.fields[i] for i in node.group_indices]
    for a in node.aggs:
        spec = AggSpec(a.fn, a.arg, a.output_type, a.name)
        fields.extend(Field(n, t) for n, t in spec.state_types())
    return tuple(fields)

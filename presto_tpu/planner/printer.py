"""Plan printer for EXPLAIN.

Conceptual parity with the reference's text plan printer (reference
presto-main/.../sql/planner/planprinter/PlanPrinter.java, textLogicalPlan).
"""
from __future__ import annotations

from typing import List

from .plan import (
    AggregationNode, DistinctNode, FilterNode, JoinNode, LimitNode,
    OutputNode, PlanNode, ProjectNode, SemiJoinNode, SortNode,
    TableScanNode, TopNNode, UnionNode, ValuesNode,
)
from .planner import LogicalPlan


def print_plan(plan: LogicalPlan) -> str:
    lines: List[str] = []
    _walk(plan.root, 0, lines)
    for i, init in enumerate(plan.init_plans):
        lines.append(f"InitPlan[{i}]:")
        _walk(init, 1, lines)
    return "\n".join(lines)


def _label(n: PlanNode) -> str:
    cols = ", ".join(f"{f.name}:{f.type.display()}" for f in n.fields)
    if isinstance(n, TableScanNode):
        return f"TableScan[{n.table}] => [{cols}]"
    if isinstance(n, FilterNode):
        return f"Filter[{n.predicate!r}]"
    if isinstance(n, ProjectNode):
        return f"Project => [{cols}]"
    if isinstance(n, AggregationNode):
        aggs = ", ".join(f"{a.name}:={a.fn}({a.arg})" for a in n.aggs)
        return (f"Aggregate[{n.step}, keys={list(n.group_indices)}] "
                f"=> [{aggs}]")
    if isinstance(n, JoinNode):
        return (f"Join[{n.join_type}, {n.distribution}, "
                f"L{list(n.left_keys)}=R{list(n.right_keys)}"
                f"{', unique' if n.build_unique else ''}]")
    if isinstance(n, SemiJoinNode):
        res = ", residual" if n.residual is not None else ""
        return (f"SemiJoin[{'anti' if n.negated else 'semi'}, "
                f"keys={list(n.source_keys)}{res}]")
    if isinstance(n, SortNode):
        return f"Sort[{[(k.index, 'asc' if k.ascending else 'desc') for k in n.keys]}]"
    if isinstance(n, TopNNode):
        return f"TopN[{n.count}, {[(k.index, 'asc' if k.ascending else 'desc') for k in n.keys]}]"
    if isinstance(n, LimitNode):
        return f"Limit[{n.count}]"
    if isinstance(n, DistinctNode):
        return "Distinct"
    if isinstance(n, UnionNode):
        return f"Union[{'distinct' if n.distinct else 'all'}]"
    if isinstance(n, ValuesNode):
        return f"Values[{len(n.rows)} rows]"
    if isinstance(n, OutputNode):
        return f"Output => [{cols}]"
    return type(n).__name__


def _walk(n: PlanNode, depth: int, lines: List[str]) -> None:
    lines.append("  " * depth + "- " + _label(n))
    for c in n.children:
        _walk(c, depth + 1, lines)

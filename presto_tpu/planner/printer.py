"""Plan printer for EXPLAIN.

Conceptual parity with the reference's text plan printer (reference
presto-main/.../sql/planner/planprinter/PlanPrinter.java, textLogicalPlan).
"""
from __future__ import annotations

from typing import List

from .plan import (
    AggregationNode, DistinctNode, FilterNode, GroupIdNode, JoinNode,
    LimitNode, OutputNode, PlanNode, ProjectNode, SemiJoinNode, SortNode,
    TableScanNode, TopNNode, UnionNode, ValuesNode,
)
from .planner import LogicalPlan


def plan_json(plan: LogicalPlan) -> dict:
    """Plan tree as a JSON-able dict — EXPLAIN (FORMAT JSON) (reference
    planprinter/JsonRenderer.java)."""
    def node_doc(n: PlanNode) -> dict:
        return {
            "name": type(n).__name__.replace("Node", ""),
            "label": _label(n),
            "outputs": [{"symbol": f.name, "type": f.type.display()}
                        for f in n.fields],
            "children": [node_doc(c) for c in n.children],
        }
    doc = node_doc(plan.root)
    if plan.init_plans:
        doc["initPlans"] = [node_doc(p) for p in plan.init_plans]
    return doc


def plan_graphviz(plan: LogicalPlan) -> str:
    """dot digraph — EXPLAIN (FORMAT GRAPHVIZ) (reference
    planprinter/GraphvizPrinter.java)."""
    lines = ["digraph logical_plan {", "  node [shape=box];"]
    counter = [0]

    def walk(n: PlanNode) -> int:
        my_id = counter[0]
        counter[0] += 1
        label = _label(n).replace('"', "'")
        lines.append(f'  n{my_id} [label="{label}"];')
        for c in n.children:
            cid = walk(c)
            lines.append(f"  n{my_id} -> n{cid};")
        return my_id

    walk(plan.root)
    for p in plan.init_plans:
        walk(p)
    lines.append("}")
    return "\n".join(lines)


def print_distributed_plan(plan: LogicalPlan) -> str:
    """Fragmented plan with per-fragment partitioning and output spec —
    EXPLAIN (TYPE DISTRIBUTED) (reference PlanPrinter.textDistributedPlan
    over PlanFragmenter output)."""
    from .fragmenter import fragment_plan
    lines: List[str] = []

    def render(root: PlanNode) -> None:
        fp = fragment_plan(root)
        for frag in fp.fragments:
            out = frag.output
            spec = "" if out is None else (
                f" => {out.kind}" + (f"{list(out.keys)}"
                                     if out.kind == "partition" else ""))
            lines.append(f"Fragment {frag.id} [{frag.partitioning}]{spec}")
            _walk(frag.root, 1, lines)
            lines.append("")

    render(plan.root)
    for i, init in enumerate(plan.init_plans):
        lines.append(f"InitPlan[{i}]:")
        render(init)
    return "\n".join(lines).rstrip()


def plan_io(plan: LogicalPlan) -> dict:
    """Catalog/table access summary — EXPLAIN (TYPE IO) (reference
    planprinter/IoPlanPrinter.java)."""
    tables = []

    def walk(n: PlanNode) -> None:
        if isinstance(n, TableScanNode):
            tables.append({
                "catalog": n.catalog,
                "schema": n.table.schema,
                "table": n.table.table,
                "columns": list(n.columns)})
        for c in n.children:
            walk(c)

    walk(plan.root)
    for p in plan.init_plans:
        walk(p)
    return {"inputTableColumnInfos": tables}


def print_plan(plan: LogicalPlan, stats=None) -> str:
    """Text plan; with a StatsCollector, annotates each node with runtime
    stats — EXPLAIN ANALYZE (reference planprinter/PlanPrinter.java
    textDistributedPlan with ExplainAnalyzeOperator stats)."""
    lines: List[str] = []
    _walk(plan.root, 0, lines, stats)
    for i, init in enumerate(plan.init_plans):
        lines.append(f"InitPlan[{i}]:")
        _walk(init, 1, lines, stats)
    if stats is not None:
        lines.append(
            f"Total: {stats.total_wall_s * 1e3:,.0f}ms "
            f"(planning {stats.planning_s * 1e3:,.0f}ms)")
    return "\n".join(lines)


def format_trace_summary(spans) -> str:
    """Trace section appended to EXPLAIN ANALYZE when the tracer is on:
    spans aggregated by name (count, total/max ms), compile and
    device-sync work called out the way the reference's query stats
    separate blocked/compile time from operator wall."""
    agg = {}
    for s in spans:
        name = s.get("name", "?")
        dur = (float(s.get("end", 0.0)) - float(s.get("start", 0.0)))
        st = agg.setdefault(name, [0, 0.0, 0.0])
        st[0] += 1
        st[1] += dur
        st[2] = max(st[2], dur)
    lines = ["Trace (spans by name):"]
    for name in sorted(agg, key=lambda n: -agg[n][1]):
        n, total, peak = agg[name]
        lines.append(f"  {name:<32} x{n:<5} total "
                     f"{total * 1e3:,.1f}ms, max {peak * 1e3:,.1f}ms")
    return "\n".join(lines)


def format_skew_summary(stats, straggler_ratio: float = 3.0,
                        min_wall_ms: float = 10.0) -> str:
    """Skew section appended to EXPLAIN ANALYZE: per-table split
    wall-time and batch-count spread, flagging splits whose wall time
    exceeds ``straggler_ratio`` x the median of the table's other
    splits — the single-process analogue of the coordinator's
    straggler detection (exec/cluster.StageMonitor). Empty string when
    there is nothing to compare (fewer than two splits everywhere)."""
    import statistics
    by_table: dict = {}
    for s in stats.splits:
        by_table.setdefault(s["table"], []).append(s)
    lines = []
    for table in sorted(by_table):
        splits = by_table[table]
        if len(splits) < 2:
            continue
        walls = [float(s["wallMs"]) for s in splits]
        batches = [int(s["batches"]) for s in splits]
        med = statistics.median(walls)
        ratio = max(walls) / med if med > 0 else float("inf")
        stragglers = []
        for i, w in enumerate(walls):
            others = walls[:i] + walls[i + 1:]
            omed = statistics.median(others)
            if omed >= min_wall_ms and w > straggler_ratio * omed:
                stragglers.append(splits[i]["split"])
        line = (f"  {table}: {len(splits)} splits, wall med "
                f"{med:,.1f}ms max {max(walls):,.1f}ms (x{ratio:,.1f}), "
                f"batches {min(batches)}..{max(batches)}")
        if stragglers:
            line += (" STRAGGLER split"
                     f"{'s' if len(stragglers) > 1 else ''} "
                     f"{sorted(stragglers)}")
        lines.append(line)
    if not lines:
        return ""
    return "\n".join(["Skew (splits per table):"] + lines)


def format_scan_cache_summary(stats) -> str:
    """Scan-cache section appended to EXPLAIN ANALYZE: split-level
    device-cache outcomes for THIS query, the process-wide resident
    set, and how long the consumer stalled waiting on the prefetcher
    (input-bound queries show a large stall; compute-bound show ~0).
    Empty string when the query touched no cacheable scans."""
    hits = getattr(stats, "cache_hits", 0)
    misses = getattr(stats, "cache_misses", 0)
    stall_s = getattr(stats, "prefetch_stall_s", 0.0)
    # stall alone still reports: the input-bound diagnostic is
    # independent of cacheability (uncacheable sources, scan_cache=false)
    if not hits and not misses and stall_s < 1e-4:
        return ""
    from ..exec.scancache import CACHE
    return (f"Scan cache: {hits} split hit{'s' if hits != 1 else ''} / "
            f"{misses} miss{'es' if misses != 1 else ''}, resident "
            f"{CACHE.resident_bytes / 1048576.0:,.1f} MiB; "
            f"prefetch stall {stall_s * 1e3:,.1f}ms")


def format_result_cache_summary(stats) -> str:
    """Result-cache section appended to EXPLAIN ANALYZE: this query's
    outcome (hit / partial / miss — on plain queries; EXPLAIN ANALYZE
    always runs, so it reports whether a resident entry would serve)
    plus the process-wide resident set. Empty string when the result
    cache never engaged (``result_cache`` off)."""
    outcome = getattr(stats, "result_cache", None)
    probe = getattr(stats, "result_cache_probe", ())
    totals = getattr(stats, "result_cache_stats", None)
    if outcome is None and probe == () and totals is None:
        return ""
    if totals is None:
        from ..serving.resultcache import RESULTS
        totals = RESULTS.stats()
    if outcome is None:
        outcome = ("miss" if probe is None else
                   f"cached ({probe[0]} rows"
                   + (", incremental)" if probe[2] else ")"))
    return (f"Result cache: {outcome}; resident "
            f"{totals['entries']} entr"
            f"{'y' if totals['entries'] == 1 else 'ies'}, "
            f"{totals['resident_bytes'] / 1048576.0:,.1f} MiB")


#: per-round table cap in the EXPLAIN ANALYZE mesh section (the full
#: timeline stays queryable via system.runtime.mesh_rounds)
_MESH_ROUND_ROWS = 48


def format_mesh_rounds(stats) -> str:
    """Mesh-rounds section appended to EXPLAIN ANALYZE on mesh-path
    queries: the flight recorder's wall-clock attribution (bucket
    seconds + share of wall), the per-shard critical path, and the
    per-round table — rendered from the SAME row shape as
    ``system.runtime.mesh_rounds`` (obs/flight.round_rows), so the two
    surfaces cannot drift. Closes with the dominant-bucket verdict the
    exchange-overhaul work tunes against. Empty when the query never
    flew (single-device path or ``mesh_flight=off``)."""
    fl = getattr(stats, "mesh_flight", None)
    if fl is None or fl.attribution is None:
        return ""
    from ..obs.flight import BUCKETS, round_rows
    a = fl.attribution
    wall = max(a["wall_s"], 1e-9)
    lines = [
        f"Mesh rounds: {a['rounds']} rounds on {a['n_devices']} "
        f"device{'s' if a['n_devices'] != 1 else ''}, wall "
        f"{a['wall_s'] * 1e3:,.1f}ms, {a['reconciled_pct']:.1f}% "
        f"attributed"]
    for b in BUCKETS:
        s = a["buckets"][b]
        if s:
            lines.append(f"  {b:<18} {s * 1e3:>10,.1f}ms "
                         f"{s / wall * 100.0:5.1f}%")
    cp = a["critical_path"]
    if cp["per_shard_s"]:
        lines.append(f"  critical path: shard {cp['slowest_shard']} "
                     f"({max(cp['per_shard_s']) * 1e3:,.1f}ms)")
    rows = round_rows(fl.query_id, fl.records())
    if rows:
        lines.append("  round stage kind         bucket             "
                     "wall_ms       rows      bytes loads  dev_rounds")
        for r in rows[:_MESH_ROUND_ROWS]:
            (_qid, rnd, stage, kind, bucket, _t, wall_s, nrows,
             nbytes, loads, _blocking, dev_rounds) = r
            lines.append(
                f"  {rnd:>5} {stage:>5} {kind:<12} {bucket:<18} "
                f"{wall_s * 1e3:>7,.1f} {nrows:>10} {nbytes:>10} "
                f"{loads} {dev_rounds:>3}")
        if len(rows) > _MESH_ROUND_ROWS:
            lines.append(
                f"  ... {len(rows) - _MESH_ROUND_ROWS} more rounds "
                f"(system.runtime.mesh_rounds has the full timeline)")
    lines.append(
        f"Mesh verdict: {a['dominant_bucket']} dominates "
        f"({a['buckets'][a['dominant_bucket']] / wall * 100.0:.0f}% "
        f"of wall)")
    return "\n".join(lines)


def format_retry_summary(info) -> str:
    """Fault-tolerance section appended to cluster EXPLAIN ANALYZE:
    task retries, speculative attempts, and the per-event detail the
    recovery layer recorded (exec/cluster._QueryExecution.summary()).
    Empty string when the query ran clean — the common case must not
    grow the plan output."""
    retries = int(info.get("retries") or 0)
    q_retries = int(info.get("query_retries") or 0)
    launched = int(info.get("speculative_launched") or 0)
    won = int(info.get("speculative_won") or 0)
    replays = sum(1 for ev in info.get("events") or ()
                  if ev.get("kind") == "spool_replay")
    if not (retries or q_retries or launched or won or replays):
        return ""
    head = (f"Fault tolerance [{info.get('policy', 'TASK')}]: "
            f"{retries} task retr{'y' if retries == 1 else 'ies'}, "
            f"{launched} speculative launched, {won} won"
            + (f", {q_retries} query rerun"
               f"{'' if q_retries == 1 else 's'}" if q_retries else "")
            + (f", {replays} spool replay"
               f"{'' if replays == 1 else 's'}" if replays else ""))
    lines = [head]
    for ev in info.get("events") or ():
        kind = ev.get("kind", "")
        if kind == "task_retry":
            lines.append(
                f"  retry {ev.get('task')} (attempt "
                f"{ev.get('attempt')}) {ev.get('from')} -> "
                f"{ev.get('to')}: {str(ev.get('reason', ''))[:120]}")
        elif kind == "speculative_launched":
            lines.append(f"  speculate {ev.get('task')} on "
                         f"{ev.get('worker')} (straggler "
                         f"{ev.get('straggler')})")
        elif kind == "speculative_won":
            lines.append(f"  speculative win {ev.get('task')} on "
                         f"{ev.get('worker')}")
        elif kind == "spool_replay":
            lines.append(f"  spool replay {ev.get('task')} "
                         f"(worker {ev.get('worker')} gone, output "
                         f"served from spool — not re-run)")
    return "\n".join(lines)


def format_executables_summary(stats, max_rows: int = 12) -> str:
    """Executables section appended to EXPLAIN ANALYZE under profile
    mode: the query's compiled XLA executables ranked by device time,
    with compile seconds and per-invocation cost-analysis estimates
    (obs/profiler.EXECUTABLES holds the process-lifetime view as
    ``system.runtime.executables``). Empty when nothing was profiled."""
    used = (stats.executables_used()
            if hasattr(stats, "executables_used") else [])
    if not used:
        return ""
    lines = ["Executables (this query, by device time):"]
    for e in used[:max_rows]:
        flops = e.get("flops")
        hbm = e.get("bytes_accessed")
        cost = ""
        if flops is not None or hbm is not None:
            cost = (f", {_si(flops or 0.0)}FLOP"
                    f"/{_si(hbm or 0.0)}B per call")
        lines.append(
            f"  {e['name']:<24} x{e['invocations']:<5} device "
            f"{e['device_time_s'] * 1e3:,.1f}ms, compile "
            f"{e['compile_seconds']:,.2f}s{cost}")
    if len(used) > max_rows:
        lines.append(f"  ... and {len(used) - max_rows} more "
                     "(system.runtime.executables)")
    return "\n".join(lines)


def format_executables_registry(max_rows: int = 12) -> str:
    """Process-lifetime executables section (cluster EXPLAIN ANALYZE,
    where per-query attribution lives on the workers): the registry's
    records ranked by cumulative device time, compile-heavy entries
    surfacing even when never profiled. Empty when nothing compiled."""
    from ..obs.profiler import EXECUTABLES
    rows = [e for e in EXECUTABLES.snapshot(analyze=False)
            if e["invocations"]]
    if not rows:
        return ""
    lines = ["Executables (process lifetime, by device time):"]
    for e in rows[:max_rows]:
        lines.append(
            f"  {e['name']:<24} x{e['invocations']:<6} device "
            f"{e['device_time_s'] * 1e3:,.1f}ms, compile "
            f"{e['compile_seconds']:,.2f}s")
    return "\n".join(lines)


def format_cost_verdict(stats) -> str:
    """Closing EXPLAIN ANALYZE line: tf.data's framing — is the query
    input-bound (scan decode/staging + prefetch stall dominates) or
    compute-bound (attributed device time dominates)? Empty when
    nothing was profiled."""
    from ..obs.profiler import cost_verdict
    v = cost_verdict(stats)
    if v is None:
        return ""
    return (f"Verdict: {v['verdict']} "
            f"(device compute {v['compute_s'] * 1e3:,.1f}ms vs input "
            f"{v['input_s'] * 1e3:,.1f}ms scan+stall)")


def _label(n: PlanNode) -> str:
    cols = ", ".join(f"{f.name}:{f.type.display()}" for f in n.fields)
    if isinstance(n, TableScanNode):
        return f"TableScan[{n.table}] => [{cols}]"
    if isinstance(n, FilterNode):
        return f"Filter[{n.predicate!r}]"
    if isinstance(n, ProjectNode):
        return f"Project => [{cols}]"
    if isinstance(n, AggregationNode):
        aggs = ", ".join(f"{a.name}:={a.fn}({a.arg})" for a in n.aggs)
        dense = ""
        if n.key_bounds:
            spans = ["?" if b is None else f"{b[0]}..{b[1]}"
                     for b in n.key_bounds]
            dense = f", bounds=[{', '.join(spans)}]"
        return (f"Aggregate[{n.step}, keys={list(n.group_indices)}"
                f"{dense}] => [{aggs}]")
    if isinstance(n, JoinNode):
        return (f"Join[{n.join_type}, {n.distribution}, "
                f"L{list(n.left_keys)}=R{list(n.right_keys)}"
                f"{', unique' if n.build_unique else ''}"
                f"{_bounds_label(n.key_bounds)}]")
    if isinstance(n, SemiJoinNode):
        res = ", residual" if n.residual is not None else ""
        return (f"SemiJoin[{'anti' if n.negated else 'semi'}, "
                f"{n.distribution}, keys={list(n.source_keys)}{res}"
                f"{_bounds_label(n.key_bounds)}]")
    if isinstance(n, SortNode):
        return f"Sort[{[(k.index, 'asc' if k.ascending else 'desc') for k in n.keys]}]"
    if isinstance(n, TopNNode):
        return f"TopN[{n.count}, {[(k.index, 'asc' if k.ascending else 'desc') for k in n.keys]}]"
    if isinstance(n, LimitNode):
        return f"Limit[{n.count}]"
    if isinstance(n, DistinctNode):
        return "Distinct"
    if isinstance(n, UnionNode):
        return f"Union[{'distinct' if n.distinct else 'all'}]"
    if isinstance(n, ValuesNode):
        return f"Values[{len(n.rows)} rows]"
    if isinstance(n, GroupIdNode):
        return f"GroupId[sets={list(map(list, n.grouping_sets))}]"
    if isinstance(n, OutputNode):
        return f"Output => [{cols}]"
    return type(n).__name__


def _bounds_label(key_bounds) -> str:
    """Planner-promised build-key bounds on a join row: the EXPLAIN
    signal that the dense-key direct-address strategy was selected
    (optimizer._attach_join_strategy), mirroring the Aggregate
    ``bounds=[...]`` label of the dense-grouping gate."""
    if not key_bounds:
        return ""
    spans = ["?" if b is None else f"{b[0]}..{b[1]}" for b in key_bounds]
    return f", direct bounds=[{', '.join(spans)}]"


def _si(v: float) -> str:
    """Compact engineering notation for FLOP/byte totals."""
    for thresh, unit in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if abs(v) >= thresh:
            return f"{v / thresh:,.2f}{unit}"
    return f"{v:,.0f}"


def _walk(n: PlanNode, depth: int, lines: List[str], stats=None) -> None:
    suffix = ""
    if stats is not None:
        st = stats.stats_for(n)
        if st is not None:
            child_wall = sum(
                (stats.stats_for(c).wall_s
                 if stats.stats_for(c) is not None else 0.0)
                for c in n.children)
            self_ms = max(st.wall_s - child_wall, 0.0) * 1e3
            suffix = (f"   [self {self_ms:,.1f}ms, wall "
                      f"{st.wall_s * 1e3:,.1f}ms, {st.rows:,} rows, "
                      f"{st.batches} batches]")
            # device truth (profile mode / EXPLAIN ANALYZE): seconds the
            # device actually spent in this operator's executables, plus
            # cost-analysis FLOP / HBM-traffic estimates — host wall
            # lies under async dispatch, these don't
            dev = (stats.device_for(n)
                   if hasattr(stats, "device_for") else None)
            if dev is not None:
                suffix += (f" [device {dev['device_time_s'] * 1e3:,.1f}ms"
                           f", {_si(dev['flops'])}FLOP"
                           f", {_si(dev['hbm_bytes'])}B hbm]")
            # executed join dispatch (strategy x distribution): the
            # runtime verdict next to the planner's promised bounds
            js = (stats.join_strategy_for(n)
                  if hasattr(stats, "join_strategy_for") else None)
            if js is not None:
                suffix += f" [strategy {js[0]}/{js[1]}]"
        elif not isinstance(n, OutputNode):
            suffix = "   [not executed]"
    lines.append("  " * depth + "- " + _label(n) + suffix)
    for c in n.children:
        _walk(c, depth + 1, lines, stats)

"""Statistics calculus: row/NDV/range estimates propagated per plan node.

The TPU build's counterpart of the reference cost framework (reference
presto-main/.../cost/StatsCalculator.java:1, FilterStatsCalculator.java:1,
JoinStatsRule.java:1): every node gets a PlanEstimate —
row count plus per-output-column NDV / numeric range / null fraction —
derived from connector table statistics and propagated through filters
(range arithmetic + equality-by-NDV), joins (containment by the smaller
key NDV), aggregations (group NDV product), and the rest. The optimizer
consumes it for join ordering, broadcast-vs-partitioned distribution, and
the eager-aggregation gate.

Estimates are upper-bound-biased (like the reference's
UNKNOWN_FILTER_COEFFICIENT = 0.9 treatment of unestimatable conjuncts):
an overestimate costs performance, an underestimate can pick a broadcast
join that OOMs — same asymmetry the reference encodes.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional

from .. import types as T
from ..expr import ir
from .plan import (
    AggregationNode, DistinctNode, FilterNode, GroupIdNode, JoinNode,
    LimitNode, MarkDistinctNode, OutputNode, PlanNode, ProjectNode,
    SemiJoinNode, SortNode, TableScanNode, TopNNode, UnionNode, UnnestNode,
    ValuesNode, WindowNode,
)

#: selectivity charged to a conjunct the calculus can't evaluate
#: (reference cost/FilterStatsCalculator.java UNKNOWN_FILTER_COEFFICIENT)
UNKNOWN_FILTER_COEFFICIENT = 0.9

#: fallback row count for a scan with no connector statistics
UNKNOWN_SCAN_ROWS = 1e9


@dataclasses.dataclass(frozen=True)
class ColumnEstimate:
    """Range/NDV estimate for one output column (reference
    spi/statistics/ColumnStatistics + cost/SymbolStatsEstimate)."""
    distinct: Optional[float] = None
    lo: Optional[float] = None         # numeric/date range (storage repr)
    hi: Optional[float] = None
    null_fraction: float = 0.0

    def capped(self, rows: float) -> "ColumnEstimate":
        """NDV capped by the owning relation's row count (ranges survive
        selection unchanged — upper bound)."""
        if self.distinct is None or self.distinct <= rows:
            return self
        return dataclasses.replace(self, distinct=max(1.0, rows))


@dataclasses.dataclass(frozen=True)
class PlanEstimate:
    rows: float
    columns: Dict[int, ColumnEstimate] = dataclasses.field(
        default_factory=dict)

    def column(self, i: int) -> ColumnEstimate:
        return self.columns.get(i, ColumnEstimate())


def _lit_num(e: ir.Expr) -> Optional[float]:
    if isinstance(e, ir.Literal) and isinstance(e.value, (int, float)) \
            and not isinstance(e.value, bool):
        return float(e.value)
    if isinstance(e, ir.Cast) :
        return _lit_num(e.arg)
    return None


def _ref_idx(e: ir.Expr) -> Optional[int]:
    if isinstance(e, ir.InputRef):
        return e.index
    if isinstance(e, ir.Cast):
        return _ref_idx(e.arg)
    return None


def _conjuncts(p: ir.Expr):
    if isinstance(p, ir.Call) and p.name == "and":
        for a in p.args:
            yield from _conjuncts(a)
    else:
        yield p


def _range_fraction(ce: ColumnEstimate, lo: Optional[float],
                    hi: Optional[float]) -> Optional[float]:
    """Fraction of the column's [lo, hi] range kept by a predicate range
    (reference FilterStatsCalculator range arithmetic)."""
    if ce.lo is None or ce.hi is None or ce.hi <= ce.lo:
        return None
    span = ce.hi - ce.lo
    keep_lo = ce.lo if lo is None else max(ce.lo, lo)
    keep_hi = ce.hi if hi is None else min(ce.hi, hi)
    if keep_hi <= keep_lo:
        return 0.0
    return min(1.0, (keep_hi - keep_lo) / span)


def _conjunct_selectivity(c: ir.Expr, cols: Dict[int, ColumnEstimate]
                          ) -> float:
    """Selectivity of one conjunct against the child's column estimates."""
    if isinstance(c, ir.Call) and c.name in ("eq", "lt", "le", "gt", "ge",
                                           "between", "ne"):
        a = c.args
        op = c.name
        idx = _ref_idx(a[0])
        if idx is None and len(a) >= 2:
            idx = _ref_idx(a[1])
            if idx is not None:
                # literal-first comparison: swap operands AND mirror the
                # operator (90 < x  ==  x > 90)
                a = (a[1], a[0])
                op = {"lt": "gt", "le": "ge",
                      "gt": "lt", "ge": "le"}.get(op, op)
        if idx is not None:
            ce = cols.get(idx, ColumnEstimate())
            if op == "eq":
                if ce.distinct and ce.distinct > 0:
                    return min(1.0, 1.0 / ce.distinct)
            elif op == "ne":
                if ce.distinct and ce.distinct > 0:
                    return max(0.0, 1.0 - 1.0 / ce.distinct)
            elif op == "between" and len(a) == 3:
                lo, hi = _lit_num(a[1]), _lit_num(a[2])
                f = _range_fraction(ce, lo, hi)
                if f is not None:
                    return f
            else:
                v = _lit_num(a[1]) if len(a) > 1 else None
                if v is not None:
                    f = _range_fraction(
                        ce,
                        v if op in ("gt", "ge") else None,
                        v if op in ("lt", "le") else None)
                    if f is not None:
                        return f
    if isinstance(c, ir.Call) and c.name == "in" and len(c.args) >= 2:
        idx = _ref_idx(c.args[0])
        ce = cols.get(idx, ColumnEstimate()) if idx is not None else None
        if ce is not None and ce.distinct and ce.distinct > 0:
            return min(1.0, (len(c.args) - 1) / ce.distinct)
    if isinstance(c, ir.Call) and c.name == "or":
        s = 0.0
        for d in c.args:
            s += _conjunct_selectivity(d, cols)
        return min(1.0, s)
    return UNKNOWN_FILTER_COEFFICIENT


class StatsCalculator:
    """Memoized per-node estimates for one optimization pass."""

    def __init__(self, session):
        self.session = session
        # memo holds the node alongside its estimate: entries are keyed
        # by id(), and keeping the reference pins the node so a
        # garbage-collected node's id can't be reused by a new node
        # within the same (now pass-long-lived) calculator
        self._memo: Dict[int, tuple] = {}

    def estimate(self, node: PlanNode) -> PlanEstimate:
        key = id(node)
        got = self._memo.get(key)
        if got is not None and got[0] is node:
            return got[1]
        est = self._compute(node)
        self._memo[key] = (node, est)
        return est

    def rows(self, node: PlanNode) -> float:
        return self.estimate(node).rows

    # -- per-node rules ------------------------------------------------------
    def _compute(self, node: PlanNode) -> PlanEstimate:
        m = getattr(self, "_" + type(node).__name__, None)
        if m is not None:
            return m(node)
        # default: pass the first child through (Output, Sort, Window...)
        if node.children:
            child = self.estimate(node.children[0])
            return PlanEstimate(child.rows, {})
        return PlanEstimate(1.0, {})

    def _TableScanNode(self, node: TableScanNode) -> PlanEstimate:
        conn = self.session.catalogs.get(node.catalog)
        stats = conn.metadata.table_stats(node.table)
        rows = stats.row_count if stats.row_count is not None \
            else UNKNOWN_SCAN_ROWS
        cols: Dict[int, ColumnEstimate] = {}
        for i, name in enumerate(node.columns):
            cs = stats.columns.get(name)
            if cs is None:
                continue
            lo = cs.min_value if isinstance(cs.min_value, (int, float)) \
                else None
            hi = cs.max_value if isinstance(cs.max_value, (int, float)) \
                else None
            cols[i] = ColumnEstimate(
                distinct=cs.distinct_count,
                lo=float(lo) if lo is not None else None,
                hi=float(hi) if hi is not None else None,
                null_fraction=cs.null_fraction or 0.0)
        # pushdown bounds are NOT discounted here: the planner always
        # keeps the exact FilterNode above the scan (connectors prune at
        # chunk granularity only), and that filter's selectivity already
        # charges the same predicate — scaling both would double-count
        return PlanEstimate(max(rows, 1.0), cols)

    def _ValuesNode(self, node: ValuesNode) -> PlanEstimate:
        return PlanEstimate(float(max(len(node.rows), 1)), {})

    def _FilterNode(self, node: FilterNode) -> PlanEstimate:
        child = self.estimate(node.child)
        sel = 1.0
        for c in _conjuncts(node.predicate):
            sel *= _conjunct_selectivity(c, child.columns)
        rows = max(child.rows * sel, 1.0)
        cols = {i: ce.capped(rows) for i, ce in child.columns.items()}
        return PlanEstimate(rows, cols)

    def _ProjectNode(self, node: ProjectNode) -> PlanEstimate:
        child = self.estimate(node.child)
        cols: Dict[int, ColumnEstimate] = {}
        for out_i, e in enumerate(node.exprs):
            idx = _ref_idx(e)
            if idx is not None and idx in child.columns:
                cols[out_i] = child.columns[idx]
        return PlanEstimate(child.rows, cols)

    def _JoinNode(self, node: JoinNode) -> PlanEstimate:
        left = self.estimate(node.left)
        right = self.estimate(node.right)
        if node.join_type == "cross" or not node.left_keys:
            rows = left.rows * right.rows
        else:
            # containment: |L >< R| = |L|*|R| / max(ndv(lk), ndv(rk))
            # (reference cost/JoinStatsRule.java)
            ndv = 1.0
            for lk, rk in zip(node.left_keys, node.right_keys):
                ln = left.column(lk).distinct
                rn = right.column(rk).distinct
                cand = max(filter(None, (ln, rn)), default=None)
                if cand:
                    ndv = max(ndv, cand)
            if ndv <= 1.0:
                ndv = max(left.rows, right.rows)
            rows = left.rows * right.rows / max(ndv, 1.0)
            if node.build_unique:
                # PK side: at most one match per probe row
                rows = min(rows, left.rows)
        if node.join_type in ("left", "full"):
            rows = max(rows, left.rows)
        if node.join_type == "full":
            rows = max(rows, right.rows)
        nl = len(node.left.fields)
        cols = dict(left.columns)
        for i, ce in right.columns.items():
            cols[nl + i] = ce
        return PlanEstimate(max(rows, 1.0), cols)

    def _SemiJoinNode(self, node: SemiJoinNode) -> PlanEstimate:
        """Containment selectivity (the JoinStatsRule formula applied to
        membership): the fraction of source rows with a match is bounded
        by ndv(filtering key) / ndv(source key). Feeds the semi-join
        distribution choice (optimizer._attach_join_strategy) and join
        ordering above; falls back to the old flat 0.5 when NDVs are
        unknown. Anti joins invert, floored to stay upper-bound-biased."""
        src = self.estimate(node.source)
        filt = self.estimate(node.filtering)
        sel: Optional[float] = None
        for sk, fk in zip(node.source_keys, node.filtering_keys):
            sn = src.column(sk).distinct
            fn = filt.column(fk).distinct
            if sn and fn and sn > 0:
                frac = min(1.0, fn / sn)
                sel = frac if sel is None else min(sel, frac)
        if sel is None:
            sel = 0.5
        if node.negated:
            sel = max(1.0 - sel, 0.1)
        rows = max(src.rows * sel, 1.0)
        cols = {i: ce.capped(rows) for i, ce in src.columns.items()}
        return PlanEstimate(rows, cols)

    def _AggregationNode(self, node: AggregationNode) -> PlanEstimate:
        child = self.estimate(node.child)
        if not node.group_indices:
            return PlanEstimate(1.0, {})
        groups = 1.0
        known = True
        for k in node.group_indices:
            d = child.column(k).distinct
            if d is None:
                known = False
                continue
            groups *= max(d, 1.0)
        if not known:
            groups = max(groups, math.sqrt(child.rows))
        rows = min(groups, child.rows)
        cols = {i: child.column(k)
                for i, k in enumerate(node.group_indices)
                if k in child.columns}
        return PlanEstimate(max(rows, 1.0), cols)

    def _DistinctNode(self, node: DistinctNode) -> PlanEstimate:
        child = self.estimate(node.child)
        groups = 1.0
        for i in range(len(node.fields)):
            d = child.column(i).distinct
            groups *= max(d, 1.0) if d else math.sqrt(child.rows)
        return PlanEstimate(max(min(groups, child.rows), 1.0),
                            child.columns)

    def _GroupIdNode(self, node: GroupIdNode) -> PlanEstimate:
        child = self.estimate(node.child)
        nsets = max(len(node.grouping_sets), 1)
        # child columns pass through (keys are nulled per set, which only
        # raises the null fraction — ranges survive); the appended
        # $group_id column has the exact static domain [0, nsets) — the
        # bound that lets ROLLUP/CUBE aggregations compose a dense group
        # code over it (optimizer._attach_group_bounds)
        cols = dict(child.columns)
        cols[len(node.child.fields)] = ColumnEstimate(
            distinct=float(nsets), lo=0.0, hi=float(nsets - 1))
        return PlanEstimate(child.rows * nsets, cols)

    def _LimitNode(self, node: LimitNode) -> PlanEstimate:
        child = self.estimate(node.child)
        return PlanEstimate(min(float(node.count), child.rows),
                            child.columns)

    def _TopNNode(self, node: TopNNode) -> PlanEstimate:
        child = self.estimate(node.child)
        return PlanEstimate(min(float(node.count), child.rows),
                            child.columns)

    def _SortNode(self, node: SortNode) -> PlanEstimate:
        child = self.estimate(node.child)
        return PlanEstimate(child.rows, child.columns)

    def _WindowNode(self, node: WindowNode) -> PlanEstimate:
        child = self.estimate(node.child)
        return PlanEstimate(child.rows, child.columns)

    def _MarkDistinctNode(self, node: MarkDistinctNode) -> PlanEstimate:
        child = self.estimate(node.child)
        return PlanEstimate(child.rows, child.columns)

    def _UnnestNode(self, node: UnnestNode) -> PlanEstimate:
        child = self.estimate(node.child)
        return PlanEstimate(child.rows * 8.0, {})

    def _UnionNode(self, node: UnionNode) -> PlanEstimate:
        return PlanEstimate(
            sum(self.estimate(c).rows for c in node.children), {})

    def _OutputNode(self, node: OutputNode) -> PlanEstimate:
        return self.estimate(node.child)

"""Transactions: session-scoped atomic writes over transactional catalogs.

The role of the reference's transaction layer (reference
presto-main/.../transaction/InMemoryTransactionManager.java:168,174 —
transaction scoping across connectors, isolation level + read-only
modes, auto-commit for single statements; SPI
spi/transaction/ConnectorTransactionHandle). Re-designed for the
snapshot-friendly in-memory catalog: BEGIN snapshots a transactional
connector on first write, writes apply eagerly (read-your-writes),
ROLLBACK restores the snapshot, COMMIT discards it. Connectors opt in by
implementing ``transaction_snapshot()`` / ``transaction_restore(snap)``;
writing to a non-transactional catalog inside an explicit transaction
fails, exactly like the reference's single-writable-catalog check.
"""
from __future__ import annotations

import secrets
from typing import Dict, Optional


class TransactionError(RuntimeError):
    pass


class Transaction:
    def __init__(self, tx_id: str, isolation: str, read_only: bool):
        self.id = tx_id
        self.isolation = isolation
        self.read_only = read_only
        # catalog name -> (connector, snapshot taken before first write)
        self.snapshots: Dict[str, tuple] = {}


class TransactionManager:
    """One explicit transaction per session key (the user on a shared
    server; "" for the embedded single-session runner — the CLI/JDBC
    model); every statement outside an explicit transaction
    auto-commits. One user's BEGIN must never scope another user's
    writes."""

    def __init__(self) -> None:
        self._current: Dict[str, Transaction] = {}

    def active(self, user: str = "") -> bool:
        return user in self._current

    def begin(self, isolation: str = "READ COMMITTED",
              read_only: bool = False, user: str = "") -> str:
        if user in self._current:
            raise TransactionError("transaction already in progress")
        tx = Transaction(f"tx_{secrets.token_hex(8)}", isolation,
                         read_only)
        self._current[user] = tx
        return tx.id

    def touch_for_write(self, catalog: str, connector,
                        user: str = "") -> None:
        """Before the first write to ``catalog`` in this user's
        transaction: check writability and capture the connector
        snapshot that ROLLBACK restores."""
        tx = self._current.get(user)
        if tx is None:
            return                       # auto-commit statement
        if tx.read_only:
            raise TransactionError("read-only transaction")
        if catalog in tx.snapshots:
            return
        snap_fn = getattr(connector, "transaction_snapshot", None)
        if snap_fn is None:
            raise TransactionError(
                f"catalog {catalog!r} does not support transactions")
        tx.snapshots[catalog] = (connector, snap_fn())

    def commit(self, user: str = "") -> None:
        if user not in self._current:
            raise TransactionError("no transaction in progress")
        del self._current[user]          # writes already applied

    def rollback(self, user: str = "") -> None:
        tx = self._current.get(user)
        if tx is None:
            raise TransactionError("no transaction in progress")
        for connector, snap in tx.snapshots.values():
            connector.transaction_restore(snap)
        del self._current[user]

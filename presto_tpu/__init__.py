"""presto_tpu: a TPU-native distributed SQL query engine.

A from-scratch re-design of the capabilities of Presto (reference:
yen-von/presto, Java) for TPU hardware: columnar batches are device-resident
struct-of-arrays with static padded shapes, query expressions compile through
JAX tracing to XLA (the analogue of Presto's runtime bytecode generation,
reference presto-main/.../sql/gen/), relational operators are sort/segment
kernels on the VPU/MXU, and distributed execution is SPMD ``shard_map`` over a
``jax.sharding.Mesh`` with ICI collectives standing in for Presto's HTTP page
shuffle.
"""
import jax

# SQL semantics need real int64/float64 (BIGINT/DOUBLE); enable before any
# array is created anywhere in the package.
jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"

from . import types  # noqa: E402,F401
from .batch import Batch, Column, Schema, bucket_capacity  # noqa: E402,F401

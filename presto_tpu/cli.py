"""Interactive SQL shell over the statement protocol.

Minimal terminal client in the spirit of the reference CLI (reference
presto-cli/.../Console.java + AlignedTablePrinter): reads statements
(``;``-terminated), runs them via the HTTP protocol, prints aligned
tables. ``--execute`` runs one statement and exits; ``--server`` may be
omitted to run an in-process server (handy on a TPU host).

Usage:
    python -m presto_tpu.cli [--server http://host:port]
                             [--catalog tpch] [--schema default]
                             [--execute SQL] [--sf 0.01]
"""
from __future__ import annotations

import argparse
import sys

from .client import QueryFailed, StatementClient


def format_aligned(columns, rows) -> str:
    headers = [c[0] for c in columns]
    cells = [["NULL" if v is None else str(v) for v in r] for r in rows]
    widths = [len(h) for h in headers]
    for r in cells:
        for i, v in enumerate(r):
            widths[i] = max(widths[i], len(v))
    numeric = [t in ("bigint", "integer", "double", "real", "smallint",
                     "tinyint") or t.startswith("decimal")
               for _, t in columns]

    def fmt_row(vals):
        out = []
        for v, w, num in zip(vals, widths, numeric):
            out.append(v.rjust(w) if num else v.ljust(w))
        return " | ".join(out)

    lines = [fmt_row(headers),
             "-+-".join("-" * w for w in widths)]
    lines += [fmt_row(r) for r in cells]
    return "\n".join(lines)


def format_separated(columns, rows, sep: str, header: bool) -> str:
    """CSV/TSV output (reference presto-cli OutputFormat CSV/TSV[_HEADER]):
    CSV quotes every field, TSV escapes separators."""
    def cell(v) -> str:
        if v is None:
            return ""
        s = str(v)
        if sep == ",":
            return '"' + s.replace('"', '""') + '"'
        return (s.replace("\\", "\\\\").replace("\t", "\\t")
                .replace("\n", "\\n"))

    lines = []
    if header:
        lines.append(sep.join(cell(c[0]) for c in columns))
    lines += [sep.join(cell(v) for v in r) for r in rows]
    return "\n".join(lines)


def format_json(columns, rows) -> str:
    import json
    names = [c[0] for c in columns]
    return "\n".join(
        json.dumps(dict(zip(names, r)), default=str) for r in rows)


def format_rows(columns, rows, output_format: str) -> str:
    f = output_format.upper()
    if f == "ALIGNED":
        return format_aligned(columns, rows)
    if f in ("CSV", "CSV_HEADER"):
        return format_separated(columns, rows, ",", f.endswith("HEADER"))
    if f in ("TSV", "TSV_HEADER"):
        return format_separated(columns, rows, "\t", f.endswith("HEADER"))
    if f == "JSON":
        return format_json(columns, rows)
    raise ValueError(f"unknown output format {output_format!r}")


def run_statement(client: StatementClient, sql: str,
                  out=None, output_format: str = "ALIGNED") -> None:
    out = out if out is not None else sys.stdout
    try:
        res = client.execute(sql)
    except QueryFailed as e:
        print(f"Query failed: {e}", file=sys.stderr)
        return
    if res.columns:
        print(format_rows(res.columns, res.rows, output_format), file=out)
    if output_format.upper() == "ALIGNED":
        print(f"({len(res.rows)} row{'s' if len(res.rows) != 1 else ''})",
              file=out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="presto-tpu")
    ap.add_argument("--server", default=None,
                    help="server URL; omitted = embedded in-process server")
    ap.add_argument("--catalog", default="tpch")
    ap.add_argument("--schema", default="default")
    ap.add_argument("--user", default="presto")
    ap.add_argument("--execute", "-e", default=None,
                    help="run this statement and exit")
    ap.add_argument("--output-format", default="ALIGNED",
                    choices=["ALIGNED", "CSV", "CSV_HEADER", "TSV",
                             "TSV_HEADER", "JSON"],
                    help="result rendering (reference presto-cli "
                         "OutputFormat)")
    ap.add_argument("--password", default=None,
                    help="password for HTTP basic authentication")
    ap.add_argument("--sf", type=float, default=0.01,
                    help="tpch scale factor for the embedded server")
    ap.add_argument("--trace-out", default=None, metavar="PATH",
                    help="enable span tracing and write a Chrome-trace "
                         "(chrome://tracing / Perfetto) JSON file on "
                         "exit; in-process spans only — point a remote "
                         "worker at the same trace with "
                         "PRESTO_TPU_TRACE=1")
    ap.add_argument("--profile-out", default=None, metavar="DIR",
                    help="deep-profile mode: enable span tracing AND "
                         "the `profile` session property (device-time "
                         "attribution), capture a jax.profiler trace "
                         "of the executed statements, and write "
                         "DIR/merged_trace.json with host spans and "
                         "XLA device tracks on one Perfetto timeline; "
                         "embedded server only — with --server the "
                         "device runs in the server process")
    ap.add_argument("--history-out", default=None, metavar="PATH",
                    help="append one JSON line per completed query "
                         "(the system.runtime.completed_queries "
                         "record) to this file; embedded server only — "
                         "with --server, configure HISTORY in the "
                         "server process")
    ap.add_argument("--history-max-bytes", type=int, default=None,
                    metavar="N",
                    help="rotate the --history-out file past N bytes "
                         "(one .1 generation kept; default 64 MiB, "
                         "0 = unbounded). Dropped records count in "
                         "history_records_dropped_total")
    ap.add_argument("--slow-query-log", type=float, default=None,
                    metavar="SECONDS",
                    help="emit the full history record of queries "
                         "slower than this through the structured "
                         "JSON-lines logger (stderr unless "
                         "PRESTO_TPU_LOG points elsewhere); embedded "
                         "server only, like --history-out")
    args = ap.parse_args(argv)

    if args.trace_out or args.profile_out:
        from .obs.trace import TRACER
        TRACER.enable(True)
    if args.history_out or args.slow_query_log is not None:
        from .obs.history import HISTORY
        HISTORY.configure(sink_path=args.history_out,
                          slow_threshold_s=args.slow_query_log,
                          max_sink_bytes=args.history_max_bytes)
        if args.slow_query_log is not None:
            from .obs.log import LOG
            if not LOG.enabled:
                LOG.configure(stream=sys.stderr)
    profiling = False
    if args.profile_out:
        import os
        os.makedirs(args.profile_out, exist_ok=True)
        try:
            import jax
            jax.profiler.start_trace(args.profile_out)
            profiling = True
        except Exception as e:   # profile capture must not block queries
            print(f"device profiler unavailable: {e}", file=sys.stderr)

    embedded = None
    url = args.server
    if url is None:
        from .exec.runner import LocalRunner
        from .server import PrestoTpuServer
        embedded = PrestoTpuServer(LocalRunner(tpch_sf=args.sf))
        embedded.start()
        url = f"http://127.0.0.1:{embedded.port}"
        print(f"embedded server at {url}", file=sys.stderr)

    client = StatementClient(url, user=args.user, catalog=args.catalog,
                             schema=args.schema, password=args.password)
    try:
        if args.profile_out:
            # device-time attribution for everything this session runs
            # (ops/jitcache bracketing + per-operator charges)
            client.execute("SET SESSION profile = true")
        if args.execute is not None:
            for stmt in args.execute.split(";"):
                if stmt.strip():
                    run_statement(client, stmt,
                                  output_format=args.output_format)
            return 0
        buf = ""
        while True:
            try:
                prompt = "presto-tpu> " if not buf else "        ...> "
                line = input(prompt)
            except EOFError:
                break
            buf += ("\n" if buf else "") + line
            while ";" in buf:
                stmt, buf = buf.split(";", 1)
                if stmt.strip():
                    if stmt.strip().lower() in ("quit", "exit"):
                        return 0
                    run_statement(client, stmt,
                                  output_format=args.output_format)
        return 0
    finally:
        if profiling:
            import os

            import jax

            from .obs.profiler import write_merged_trace
            from .obs.trace import TRACER
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            merged = os.path.join(args.profile_out, "merged_trace.json")
            try:
                write_merged_trace(merged, TRACER.export(),
                                   args.profile_out)
                print(f"wrote merged host+device trace to {merged} "
                      "(open in ui.perfetto.dev)", file=sys.stderr)
            except Exception as e:   # must not mask the query outcome
                print(f"merged-trace write failed: {e}",
                      file=sys.stderr)
        if args.trace_out:
            from .obs.trace import TRACER, write_chrome_trace
            write_chrome_trace(args.trace_out, TRACER.export())
            print(f"wrote trace to {args.trace_out} "
                  "(open in chrome://tracing or ui.perfetto.dev)",
                  file=sys.stderr)
        if embedded is not None:
            embedded.stop()


if __name__ == "__main__":
    sys.exit(main())

"""Query error codes raised by device-side kernels.

The analogue of Presto's StandardErrorCode + PrestoException (reference
presto-spi/.../spi/StandardErrorCode.java): kernels cannot raise inside a
jitted program, so scalar functions record a per-row int32 error code on the
evaluated value (0 = ok), compiled filter/projection kernels reduce it to a
per-batch scalar (max over live rows), and the executor checks the collected
scalars once per query — one host sync — raising ``QueryError`` with the
Presto error name. ``TRY(expr)`` clears the codes and yields NULL for the
failed rows (reference operator/scalar/TryFunction.java).
"""
from __future__ import annotations

DIVISION_BY_ZERO = 1
NUMERIC_VALUE_OUT_OF_RANGE = 2
INVALID_FUNCTION_ARGUMENT = 3
GENERIC_USER_ERROR = 4
# a group key fell outside the range its connector statistics promised
# (stats-bounded dense grouping, optimizer._attach_group_bounds): the
# dense slot code would be garbage, so the query fails loudly instead of
# returning misgrouped rows
STATS_BOUND_VIOLATION = 5

ERROR_NAMES = {
    DIVISION_BY_ZERO: "DIVISION_BY_ZERO",
    NUMERIC_VALUE_OUT_OF_RANGE: "NUMERIC_VALUE_OUT_OF_RANGE",
    INVALID_FUNCTION_ARGUMENT: "INVALID_FUNCTION_ARGUMENT",
    GENERIC_USER_ERROR: "GENERIC_USER_ERROR",
    STATS_BOUND_VIOLATION: "STATS_BOUND_VIOLATION",
}


class QueryError(RuntimeError):
    """A row-level evaluation error surfaced at query granularity."""

    def __init__(self, code: int, message: str | None = None):
        self.code = code
        self.name = ERROR_NAMES.get(code, f"ERROR_{code}")
        super().__init__(message or self.name)


class QueryCancelledError(RuntimeError):
    """Raised by the executor when a cancel request interrupts a running
    query between batch quanta (the role of the reference's
    dispatcher/DispatchManager.java:134 cancel semantics: a DELETE on the
    statement URI must stop in-flight work, not just mark state)."""

    def __init__(self, message: str = "Query was canceled"):
        super().__init__(message)

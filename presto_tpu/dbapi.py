"""PEP 249 (DB-API 2.0) interface over the statement protocol.

The role of the reference's JDBC driver (reference presto-jdbc/
PrestoConnection.java, PrestoStatement, PrestoResultSet wrapping the
REST protocol): standard cursor semantics over StatementClient, so any
DB-API tool (ORMs, notebook %sql magics, pandas.read_sql) can speak to
the engine. ``paramstyle`` is qmark; parameters bind client-side with
SQL-literal escaping (the reference's python client interpolates the
same way).
"""
from __future__ import annotations

import datetime
from typing import Any, Iterator, List, Optional, Sequence, Tuple

from .client import QueryFailed, StatementClient

apilevel = "2.0"
threadsafety = 1           # threads may share the module, not connections
paramstyle = "qmark"


class Error(Exception):
    pass


class InterfaceError(Error):
    pass


class DatabaseError(Error):
    pass


class ProgrammingError(DatabaseError):
    pass


def _quote(value: Any) -> str:
    if value is None:
        return "NULL"
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, (int, float)):
        return repr(value)
    if isinstance(value, datetime.datetime):
        return f"timestamp '{value.strftime('%Y-%m-%d %H:%M:%S.%f')}'"
    if isinstance(value, datetime.date):
        return f"date '{value.isoformat()}'"
    if isinstance(value, (list, tuple)):
        return "array[" + ", ".join(_quote(v) for v in value) + "]"
    s = str(value).replace("'", "''")
    return f"'{s}'"


def _bind(operation: str, parameters: Optional[Sequence[Any]]) -> str:
    """qmark substitution outside string literals, quoted identifiers,
    and comments (the lexer accepts --, /* */ and \"...\")."""
    if parameters is None:
        return operation
    out: List[str] = []
    it = iter(parameters)
    used = 0
    i = 0
    n = len(operation)
    while i < n:
        ch = operation[i]
        if ch == "'" or ch == '"':
            q = ch
            j = i + 1
            while j < n:
                if operation[j] == q:
                    if q == "'" and j + 1 < n and operation[j + 1] == "'":
                        j += 2          # escaped '' inside a string
                        continue
                    break
                j += 1
            out.append(operation[i:j + 1])
            i = j + 1
        elif ch == "-" and operation[i:i + 2] == "--":
            j = operation.find("\n", i)
            j = n if j < 0 else j
            out.append(operation[i:j])
            i = j
        elif ch == "/" and operation[i:i + 2] == "/*":
            j = operation.find("*/", i)
            j = n if j < 0 else j + 2
            out.append(operation[i:j])
            i = j
        elif ch == "?":
            try:
                out.append(_quote(next(it)))
                used += 1
            except StopIteration:
                raise ProgrammingError(
                    "not enough parameters for placeholders")
            i += 1
        else:
            out.append(ch)
            i += 1
    if used != len(parameters):
        raise ProgrammingError(
            f"{len(parameters)} parameters for {used} placeholders")
    return "".join(out)


class Cursor:
    arraysize = 1

    def __init__(self, conn: "Connection"):
        self._conn = conn
        self._rows: List[tuple] = []
        self._pos = 0
        self.description: Optional[List[Tuple]] = None
        self.rowcount = -1
        self._closed = False

    # -- execution -----------------------------------------------------------
    def execute(self, operation: str,
                parameters: Optional[Sequence[Any]] = None) -> "Cursor":
        self._check_open()
        sql = _bind(operation, parameters)
        try:
            res = self._conn._client.execute(sql)
        except QueryFailed as e:
            raise DatabaseError(str(e)) from e
        self._rows = [tuple(r) for r in res.rows]
        self._pos = 0
        self.rowcount = len(self._rows)
        # PEP 249 7-tuples: (name, type_code, None, None, None, None, None)
        self.description = [(name, type_code, None, None, None, None, None)
                            for name, type_code in res.columns] or None
        return self

    def executemany(self, operation: str,
                    seq_of_parameters: Sequence[Sequence[Any]]) -> "Cursor":
        for params in seq_of_parameters:
            self.execute(operation, params)
        return self

    # -- fetch ---------------------------------------------------------------
    def fetchone(self) -> Optional[tuple]:
        self._check_open()
        if self._pos >= len(self._rows):
            return None
        row = self._rows[self._pos]
        self._pos += 1
        return row

    def fetchmany(self, size: Optional[int] = None) -> List[tuple]:
        self._check_open()
        size = size or self.arraysize
        out = self._rows[self._pos:self._pos + size]
        self._pos += len(out)
        return out

    def fetchall(self) -> List[tuple]:
        self._check_open()
        out = self._rows[self._pos:]
        self._pos = len(self._rows)
        return out

    def __iter__(self) -> Iterator[tuple]:
        while True:
            row = self.fetchone()
            if row is None:
                return
            yield row

    # -- misc ----------------------------------------------------------------
    def setinputsizes(self, sizes) -> None:
        pass

    def setoutputsize(self, size, column=None) -> None:
        pass

    def close(self) -> None:
        self._closed = True
        self._rows = []

    def _check_open(self) -> None:
        if self._closed or self._conn._closed:
            raise InterfaceError("cursor is closed")

    def __enter__(self) -> "Cursor":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class Connection:
    def __init__(self, host: str = "127.0.0.1", port: int = 8080,
                 user: str = "presto", catalog: Optional[str] = None,
                 schema: Optional[str] = None, scheme: str = "http",
                 password: Optional[str] = None):
        url = f"{scheme}://{host}:{port}"
        self._client = StatementClient(url, user=user, catalog=catalog,
                                       schema=schema, password=password)
        self._closed = False

    def cursor(self) -> Cursor:
        if self._closed:
            raise InterfaceError("connection is closed")
        return Cursor(self)

    def commit(self) -> None:
        self._exec_tx("commit")

    def rollback(self) -> None:
        self._exec_tx("rollback")

    def _exec_tx(self, stmt: str) -> None:
        if self._closed:
            raise InterfaceError("connection is closed")
        try:
            self._client.execute(stmt)
        except QueryFailed as e:
            # auto-commit mode: "no transaction in progress" is fine;
            # a real COMMIT/ROLLBACK failure must surface
            if "no transaction" in str(e).lower():
                return
            raise DatabaseError(str(e)) from e

    def close(self) -> None:
        self._closed = True

    def __enter__(self) -> "Connection":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def connect(host: str = "127.0.0.1", port: int = 8080, **kwargs
            ) -> Connection:
    """DB-API 2.0 module entry (reference PrestoDriver.connect)."""
    return Connection(host=host, port=port, **kwargs)

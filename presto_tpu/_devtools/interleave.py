"""Deterministic interleaving explorer (the dynamic half of the
concurrency verification plane).

Every cache TOCTOU in this repo's history (PR 4's stale scan-cache
insert, PR 8's plan-cache write-epoch veto, PR 12's result-cache
partial-hit double-apply) was a specific interleaving of a handful of
steps — found by review, not by tests, because plain threaded tests
sample ONE schedule per run. This module turns those races into pinned
red/green tests by running a scenario's threads under a cooperative
scheduler that serializes them onto one runnable-at-a-time schedule and
then systematically enumerates the schedules (CHESS-style stateless
search: bounded, optionally preemption-bounded, or seeded random
sampling past the bound).

How a scenario yields control:

- **explicit points** — scenario code calls :func:`point` (module
  level; a no-op for threads no active exploration owns, so the same
  call is safe in helpers shared with normal tests);
- **failpoint sites** — :func:`failpoints_as_points` arms ``callback``
  rules on declared engine sites (``plancache.plan``,
  ``resultcache.stamp``, ``resultcache.partial``, ...) that forward to
  :func:`point`, so REAL engine paths become schedulable without
  monkeypatching;
- **checked locks** — while an exploration is active, registered
  threads' ``checked_lock`` acquires route through the scheduler
  (lockcheck's scheduler hook): an acquire that would block marks the
  thread BLOCKED instead of deadlocking the exploration, and a state
  where every live thread is blocked is reported as a **deadlock
  finding** rather than a hang. Lock acquisition is deliberately NOT a
  scheduling point — schedules branch only at explicit points, keeping
  the search space proportional to the scenario, not to the engine's
  lock traffic.

Only one scenario thread ever runs at a time, so each segment between
points executes atomically and a schedule (a decision list) replays
bit-for-bit — the determinism contract that lets a failing interleaving
be committed as a regression test.
"""
from __future__ import annotations

import contextlib
import dataclasses
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from . import lockcheck

__all__ = ["Exploration", "Interleaver", "Schedule", "explore",
           "failpoints_as_points", "point", "sample"]

#: the interleaver currently driving threads (explorations are serial)
_ACTIVE: Optional["Interleaver"] = None

_NEW, _READY, _RUNNING, _BLOCKED, _DONE = range(5)


class _Abort(BaseException):
    """Raised inside a scenario thread to unwind it when the
    exploration is torn down (deadlock or hang) — BaseException so
    scenario ``except Exception`` blocks can't swallow it."""


class _TState:
    __slots__ = ("index", "sem", "state", "label", "blocked_on",
                 "error", "thread")

    def __init__(self, index: int):
        self.index = index
        self.sem = threading.Semaphore(0)
        self.state = _NEW
        self.label = "start"
        self.blocked_on: Optional[str] = None
        self.error: Optional[BaseException] = None
        self.thread: Optional[threading.Thread] = None


class Interleaver:
    """One schedule's cooperative scheduler. ``decisions`` replays a
    prefix (each entry is a POSITION in that step's sorted runnable
    set); steps past the prefix pick position 0, or a seeded-random
    position when ``rng`` is given. :meth:`run` drives the threads to
    completion and leaves the evidence on the instance (``trace``,
    ``choices``, ``deadlocked``, per-thread errors)."""

    def __init__(self, decisions: Optional[Sequence[int]] = None,
                 rng: Optional[random.Random] = None,
                 step_timeout: float = 20.0):
        self._decisions = list(decisions or [])
        self._rng = rng
        self._step_timeout = step_timeout
        self._threads: List[_TState] = []
        self._by_ident: Dict[int, _TState] = {}
        self._ctl = threading.Semaphore(0)
        self._mu = threading.Lock()
        self._aborted = False
        #: (thread_index, label) per observed event
        self.trace: List[Tuple[int, str]] = []
        #: (chosen_pos, runnable thread indices, prev thread index)
        self.choices: List[Tuple[int, Tuple[int, ...], int]] = []
        #: positions actually taken (prefix + defaults/rng)
        self.decisions_taken: List[int] = []
        self.deadlocked = False
        self.hung = False

    # -- thread side ----------------------------------------------------------
    def _me(self) -> Optional[_TState]:
        return self._by_ident.get(threading.get_ident())

    def owns_current_thread(self) -> bool:
        return threading.get_ident() in self._by_ident

    def point(self, label: str) -> None:
        st = self._me()
        if st is None or self._aborted:
            return
        with self._mu:
            st.state = _READY
            st.label = label
        self._ctl.release()
        st.sem.acquire()
        if self._aborted:
            raise _Abort()

    def checked_acquire(self, inner, name: str) -> bool:
        """lockcheck hook: blocking acquire of a checked lock's inner
        primitive by a registered thread. Not a scheduling point — but
        a failed probe parks the thread as BLOCKED so the controller
        can schedule someone else (or call deadlock)."""
        st = self._me()
        if st is None:
            return inner.acquire()
        while True:
            if inner.acquire(False):
                return True
            if self._aborted:
                raise _Abort()
            with self._mu:
                st.state = _BLOCKED
                st.blocked_on = name
                self.trace.append((st.index, f"blocked:{name}"))
            self._ctl.release()
            st.sem.acquire()
            if self._aborted:
                raise _Abort()

    def lock_released(self, name: str) -> None:
        """lockcheck hook: any release of a checked lock makes threads
        blocked on that name probe-worthy again."""
        with self._mu:
            for st in self._threads:
                if st.state == _BLOCKED and st.blocked_on == name:
                    st.state = _READY
                    st.blocked_on = None

    # -- controller -----------------------------------------------------------
    def _wrap(self, st: _TState, fn: Callable[[], None]) -> None:
        self._by_ident[threading.get_ident()] = st
        st.sem.acquire()
        try:
            if not self._aborted:
                fn()
        except _Abort:
            pass
        except BaseException as e:          # noqa: BLE001 — reported
            st.error = e
        finally:
            with self._mu:
                st.state = _DONE
            self._ctl.release()

    def run(self, fns: Sequence[Callable[[], None]]) -> None:
        global _ACTIVE
        if not fns:
            return
        self._threads = [_TState(i) for i in range(len(fns))]
        _ACTIVE = self
        lockcheck.set_scheduler(self)
        try:
            for st, fn in zip(self._threads, fns):
                st.thread = threading.Thread(
                    target=self._wrap, args=(st, fn), daemon=True)
                st.thread.start()
            # wait until every wrapper registered (first thing it does
            # is park on its semaphore, so no event is needed beyond
            # ident-map size)
            deadline = time.monotonic() + 10.0
            while len(self._by_ident) < len(fns) \
                    and time.monotonic() < deadline:
                time.sleep(0.0005)
            for st in self._threads:
                if st.state == _NEW:
                    st.state = _READY
            self._loop()
        finally:
            lockcheck.set_scheduler(None)
            _ACTIVE = None
            if self._aborted:
                for st in self._threads:
                    st.sem.release()
            for st in self._threads:
                if st.thread is not None:
                    st.thread.join(timeout=5.0)

    def _abort(self) -> None:
        self._aborted = True
        for st in self._threads:
            st.sem.release()

    def _loop(self) -> None:
        prev = -1
        step = 0
        while True:
            with self._mu:
                if all(st.state == _DONE for st in self._threads):
                    return
                runnable = tuple(st.index for st in self._threads
                                 if st.state == _READY)
                live = [st for st in self._threads
                        if st.state != _DONE]
            if not runnable:
                # every live thread is blocked on a lock: a REAL
                # deadlock this schedule executed — report, abort
                self.deadlocked = all(st.state == _BLOCKED
                                      for st in live)
                self._abort()
                return
            if step < len(self._decisions):
                pos = self._decisions[step]
                if pos >= len(runnable):
                    pos = len(runnable) - 1
            elif self._rng is not None:
                pos = self._rng.randrange(len(runnable))
            else:
                pos = 0
            chosen = self._threads[runnable[pos]]
            self.choices.append((pos, runnable, prev))
            self.decisions_taken.append(pos)
            self.trace.append((chosen.index, chosen.label))
            with self._mu:
                chosen.state = _RUNNING
            chosen.sem.release()
            if not self._ctl.acquire(timeout=self._step_timeout):
                # a scenario segment hung (blocked on something the
                # scheduler can't see): fail the schedule loudly
                self.hung = True
                self._abort()
                return
            prev = chosen.index
            step += 1

    # -- results --------------------------------------------------------------
    def errors(self) -> List[BaseException]:
        return [st.error for st in self._threads
                if st.error is not None]


def point(label: str) -> None:
    """Yield control to the active exploration's scheduler; a no-op on
    threads no exploration owns (production, plain tests)."""
    sched = _ACTIVE
    if sched is not None:
        sched.point(label)


@contextlib.contextmanager
def failpoints_as_points(sites: Sequence[str], registry=None):
    """Arm ``callback`` rules on the given declared failpoint sites
    that forward each hit into :func:`point` — the bridge that makes
    real engine seams (serving-cache epoch windows, scan decode, spool
    I/O) schedulable without touching engine code."""
    from ..exec.failpoints import FAILPOINTS
    reg = registry if registry is not None else FAILPOINTS

    def _cb(site):
        def cb(key: str = "", **_ctx):
            point(site)
        return cb

    for s in sites:
        reg.configure(s, action="callback", times=None, callback=_cb(s))
    try:
        yield
    finally:
        for s in sites:
            reg.clear(s)


# -- systematic exploration ---------------------------------------------------

@dataclasses.dataclass
class Schedule:
    """One executed schedule: its decision list, the event trace, and
    what went wrong (None = clean)."""
    decisions: List[int]
    trace: List[Tuple[int, str]]
    choices: List[Tuple[int, Tuple[int, ...], int]]
    error: Optional[str]
    deadlocked: bool = False

    def describe(self) -> str:
        steps = " -> ".join(f"T{i}:{lbl}" for i, lbl in self.trace)
        return f"[{','.join(map(str, self.decisions))}] {steps}"


@dataclasses.dataclass
class Exploration:
    """Every schedule an :func:`explore`/:func:`sample` run executed.
    ``exhausted`` is True when the bounded DFS enumerated the whole
    (preemption-bounded) schedule space."""
    schedules: List[Schedule]
    exhausted: bool = True

    @property
    def failures(self) -> List[Schedule]:
        return [s for s in self.schedules if s.error is not None]

    @property
    def deadlocks(self) -> List[Schedule]:
        return [s for s in self.schedules if s.deadlocked]

    def assert_clean(self) -> None:
        if self.failures:
            raise AssertionError(
                f"{len(self.failures)}/{len(self.schedules)} "
                f"schedule(s) failed; first: "
                f"{self.failures[0].error} at "
                f"{self.failures[0].describe()}")


def _run_one(make_scenario, decisions: Sequence[int],
             rng: Optional[random.Random] = None,
             step_timeout: float = 20.0) -> Schedule:
    threads, check = _scenario(make_scenario)
    sch = Interleaver(decisions=decisions, rng=rng,
                      step_timeout=step_timeout)
    sch.run(threads)
    error: Optional[str] = None
    if sch.deadlocked:
        error = "deadlock: every live thread blocked on a checked lock"
    elif sch.hung:
        error = "hang: a scenario segment never returned to the scheduler"
    else:
        errs = sch.errors()
        if errs:
            error = f"thread raised {errs[0]!r}"
        elif check is not None:
            error = check()
    return Schedule(decisions=list(sch.decisions_taken),
                    trace=list(sch.trace), choices=list(sch.choices),
                    error=error, deadlocked=sch.deadlocked)


def _scenario(make_scenario):
    made = make_scenario()
    if isinstance(made, tuple):
        threads, check = made
    else:
        threads, check = made, None
    return list(threads), check


def _preemptions(choices, decisions: List[int]) -> int:
    """Preemption count of a decision list against the recorded
    runnable sets: choosing a thread other than the previous one while
    the previous one was still runnable."""
    count = 0
    for pos, (_recorded, runnable, prev) in zip(decisions, choices):
        chosen = runnable[min(pos, len(runnable) - 1)]
        if prev >= 0 and prev in runnable and chosen != prev:
            count += 1
    return count


def explore(make_scenario, max_schedules: int = 256,
            preemption_bound: Optional[int] = None,
            step_timeout: float = 20.0) -> Exploration:
    """Bounded exhaustive DFS over the scenario's schedules.

    ``make_scenario()`` returns ``(thread_fns, check)`` — fresh state
    per call (each schedule is a fresh run); ``check()`` runs after all
    threads finish and returns an error string or None. Every schedule
    executed exactly once: a run with prefix P branches only at steps
    past ``len(P)``, pushing one new prefix per unexplored alternative
    (deepest-first). ``preemption_bound`` prunes prefixes whose forced
    context switches exceed the bound — the CHESS result that most
    races need very few."""
    stack: List[List[int]] = [[]]
    schedules: List[Schedule] = []
    while stack:
        if len(schedules) >= max_schedules:
            return Exploration(schedules, exhausted=False)
        prefix = stack.pop()
        sched = _run_one(make_scenario, prefix,
                         step_timeout=step_timeout)
        schedules.append(sched)
        for i in range(len(sched.choices) - 1, len(prefix) - 1, -1):
            chosen_pos, runnable, _prev = sched.choices[i]
            for alt in range(len(runnable)):
                if alt == sched.decisions[i]:
                    continue
                cand = sched.decisions[:i] + [alt]
                if preemption_bound is not None and _preemptions(
                        sched.choices, cand) > preemption_bound:
                    continue
                stack.append(cand)
    return Exploration(schedules, exhausted=True)


def sample(make_scenario, n: int = 64, seed: int = 0,
           step_timeout: float = 20.0) -> Exploration:
    """Seeded random sampling for scenarios whose exhaustive space is
    out of reach: ``n`` schedules drawn by one ``random.Random(seed)``
    — replayable bit-for-bit, like the failpoint registry's
    probabilistic rules."""
    rng = random.Random(seed)
    schedules = [_run_one(make_scenario, [], rng=rng,
                          step_timeout=step_timeout)
                 for _ in range(n)]
    return Exploration(schedules, exhausted=False)

"""Runtime lock-order validator (the dynamic half of tools/analyze/locks.py).

The engine runs five cooperating thread pools (scan prefetcher,
local-exchange producers, taskexec fair scheduler, cluster retry loop,
metrics/history sinks) whose lock discipline the static checker can only
approximate — aliasing and cross-module call chains hide orders from the
AST. This module records the ACTUAL acquisition edges taken at runtime:
every instrumented lock pushes itself onto a per-thread held-stack, and
acquiring lock B while holding lock A records the edge A->B. ``check()``
then fails on

- **cycles** in the observed edge graph (a real AB/BA inversion was
  executed, even if the two orders ran on different threads and never
  deadlocked in this run),
- **locks held across a jit dispatch** (``ops/jitcache._TimedEntry``
  calls :func:`note_dispatch` before every cached-executable call; a
  lock held there serializes every other query behind one query's
  device work — the exact stall the fair scheduler exists to prevent),
  and
- **guarded-field violations**: an attribute declared
  ``x = guarded_by("lock.name")`` fails FAST (raises
  :class:`GuardedFieldError` and records a violation) when read or
  written by a thread not holding that checked lock — the runtime half
  of the cache-contract checker (tools/analyze/caches.py). The first
  write is exempt so ``__init__`` can seed the field before the object
  is published.

The interleaving explorer (``presto_tpu/_devtools/interleave.py``)
additionally installs a **scheduler hook** here: while an exploration
is active, threads registered with the active scheduler route their
``checked_lock`` acquires through it (non-blocking probe + blocked
bookkeeping) so a thread descheduled while holding a lock can never
silently deadlock the exploration — the scheduler sees the block and
reports real deadlocks as findings.

Gating: instrumentation is decided once at import via the
``PRESTO_TPU_LOCKCHECK`` env var (``1``/``0``); when unset it is ON
under pytest ("pytest" already imported) and OFF otherwise, so
production lock sites (``checked_lock``/``checked_rlock``) cost exactly
a plain ``threading.Lock``. The chaos/taskexec suites assert
``GRAPH.check() == []`` after exercising the thread pools.
"""
from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Tuple

__all__ = ["ENABLED", "GRAPH", "GuardedFieldError", "LockGraph",
           "checked_lock", "checked_rlock", "guarded_by",
           "note_dispatch", "set_scheduler"]

_env = os.environ.get("PRESTO_TPU_LOCKCHECK")
if _env is None:
    #: on by default under pytest, off everywhere else
    ENABLED = "pytest" in sys.modules
else:
    ENABLED = _env.strip().lower() not in ("0", "false", "off", "")

#: active interleaving scheduler (presto_tpu/_devtools/interleave.py)
#: or None — consulted per checked-lock acquire/release; only threads
#: the scheduler registered are routed through it
_SCHEDULER = None


def set_scheduler(sched) -> None:
    """Install (or, with None, remove) the interleaving scheduler the
    checked locks report to. Exploration runs are serial, so a plain
    module global is enough."""
    global _SCHEDULER
    _SCHEDULER = sched


class LockGraph:
    """Observed lock-acquisition edges + violations, per graph instance
    (the process uses :data:`GRAPH`; tests build private ones so seeded
    inversions don't fail the suite-wide clean check)."""

    def __init__(self):
        self._local = threading.local()
        # raw primitive lock: the graph guards itself and must never
        # recurse into its own instrumentation
        self._mu = threading.Lock()
        #: (held_name, acquired_name) -> first-seen description
        self.edges: Dict[Tuple[str, str], str] = {}
        #: dispatch-under-lock records, appended as they happen
        self.violations: List[str] = []

    # -- held-stack plumbing (called from _CheckedLock) ----------------------
    def _stack(self) -> List[str]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _acquired(self, name: str) -> None:
        st = self._stack()
        for held in st:
            if held != name and (held, name) not in self.edges:
                with self._mu:
                    self.edges.setdefault(
                        (held, name), f"{held} -> {name}")
        st.append(name)

    def _released(self, name: str) -> None:
        st = self._stack()
        # remove the innermost occurrence (re-entrant RLocks push twice)
        for i in range(len(st) - 1, -1, -1):
            if st[i] == name:
                del st[i]
                return

    # -- public API ----------------------------------------------------------
    def lock(self, name: str) -> "_CheckedLock":
        return _CheckedLock(name, threading.Lock(), self)

    def rlock(self, name: str) -> "_CheckedLock":
        return _CheckedLock(name, threading.RLock(), self)

    def held(self) -> List[str]:
        return list(self._stack())

    def note_dispatch(self, what: str) -> None:
        held = self._stack()
        if held:
            with self._mu:
                self.violations.append(
                    f"jit dispatch {what!r} while holding "
                    f"lock(s) {sorted(set(held))} — device work must "
                    f"never run under an engine lock")

    def check(self) -> List[str]:
        """Violation strings: recorded dispatch-under-lock events plus
        every cycle in the observed acquisition-order graph."""
        with self._mu:
            out = list(self.violations)
            adj: Dict[str, List[str]] = {}
            for a, b in self.edges:
                adj.setdefault(a, []).append(b)
        state: Dict[str, int] = {}   # 0=visiting, 1=done
        path: List[str] = []

        def visit(n: str) -> Optional[List[str]]:
            state[n] = 0
            path.append(n)
            for m in adj.get(n, ()):
                if state.get(m) == 0:
                    return path[path.index(m):] + [m]
                if m not in state:
                    cyc = visit(m)
                    if cyc:
                        return cyc
            path.pop()
            state[n] = 1
            return None

        seen_cycles = set()
        for n in sorted(adj):
            if n not in state:
                cyc = visit(n)
                if cyc:
                    key = frozenset(cyc)
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        out.append("lock-order cycle: "
                                   + " -> ".join(cyc))
                    # keep scanning other components: everything still
                    # on the aborted DFS path counts as finished so a
                    # later visit can't index a cleared path
                    state.update({k: 1 for k in path})
                    state.update({k: 1 for k in cyc})
                    path.clear()
        return out

    def reset(self) -> None:
        with self._mu:
            self.edges.clear()
            self.violations.clear()


class _CheckedLock:
    """Lock/RLock wrapper feeding a :class:`LockGraph`. Supports the
    subset of the lock protocol the engine (and ``threading.Condition``
    over it) uses: acquire/release/context manager."""

    __slots__ = ("name", "_inner", "_graph")

    def __init__(self, name: str, inner, graph: LockGraph):
        self.name = name
        self._inner = inner
        self._graph = graph

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        sched = _SCHEDULER
        if sched is not None and blocking and timeout == -1 \
                and sched.owns_current_thread():
            # interleaving exploration: the scheduler serializes
            # registered threads, so a blocking acquire from one must
            # go through it (non-blocking probe + blocked bookkeeping)
            # or a descheduled holder would deadlock the exploration
            got = sched.checked_acquire(self._inner, self.name)
        else:
            got = self._inner.acquire(blocking, timeout)
        if got:
            self._graph._acquired(self.name)
        return got

    def release(self) -> None:
        self._inner.release()
        self._graph._released(self.name)
        sched = _SCHEDULER
        if sched is not None:
            sched.lock_released(self.name)

    def __enter__(self) -> "_CheckedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        fn = getattr(self._inner, "locked", None)
        if fn is not None:
            return fn()
        # RLock before Python 3.12 has no locked(): probe with a
        # non-blocking acquire on the raw primitive (no graph edges —
        # this is introspection, not an acquisition)
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True


#: the process-wide graph instrumented engine locks feed
GRAPH = LockGraph()


def checked_lock(name: str):
    """A ``threading.Lock`` — instrumented into :data:`GRAPH` when the
    validator is enabled, a plain primitive lock otherwise."""
    if not ENABLED:
        return threading.Lock()
    return GRAPH.lock(name)


def checked_rlock(name: str):
    if not ENABLED:
        return threading.RLock()
    return GRAPH.rlock(name)


def note_dispatch(what: str) -> None:
    """Called by ops/jitcache._TimedEntry before each cached-executable
    dispatch; records a violation when any instrumented lock is held."""
    GRAPH.note_dispatch(what)


# -- guarded fields -----------------------------------------------------------

class GuardedFieldError(RuntimeError):
    """A ``guarded_by`` field was touched without its lock held."""


class _GuardedField:
    """Data descriptor enforcing a guarded-by contract on one attribute.
    Values live in the instance ``__dict__`` under a mangled key (a data
    descriptor wins the lookup, so the public name stays clean); every
    read and every write after the first checks the current thread's
    held-lock stack. ``check=False`` (production) keeps the storage
    protocol with zero validation."""

    __slots__ = ("lock_name", "lock_attr", "name", "slot", "check",
                 "_graph")

    def __init__(self, lock_name: Optional[str], lock_attr: Optional[str],
                 check: bool, graph=None):
        self.lock_name = lock_name
        self.lock_attr = lock_attr
        self.check = check
        self._graph = graph
        self.name = "<unbound>"
        self.slot = "_guarded__<unbound>"

    def __set_name__(self, owner, name: str) -> None:
        self.name = f"{owner.__name__}.{name}"
        self.slot = f"_guarded__{name}"

    def _required_name(self, obj) -> Optional[str]:
        if self.lock_name is not None:
            return self.lock_name
        lock = getattr(obj, self.lock_attr, None)
        # the instance's lock should be a _CheckedLock (the static
        # cache checker enforces that); a foreign primitive has no
        # name for the held-stack to carry, so nothing to verify
        return getattr(lock, "name", None)

    def _validate(self, obj, op: str) -> None:
        required = self._required_name(obj)
        if required is None:
            return
        graph = self._graph if self._graph is not None else GRAPH
        if required in graph._stack():
            return
        msg = (f"guarded field {self.name} {op} without holding "
               f"checked lock {required!r} (held: "
               f"{sorted(set(graph._stack()))})")
        with graph._mu:
            graph.violations.append(msg)
        raise GuardedFieldError(msg)

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        try:
            value = obj.__dict__[self.slot]
        except KeyError:
            raise AttributeError(self.name) from None
        if self.check:
            self._validate(obj, "read")
        return value

    def __set__(self, obj, value) -> None:
        if self.check and self.slot in obj.__dict__:
            # first write (``__init__`` seeding, pre-publication) is
            # exempt; every re-bind afterwards needs the lock
            self._validate(obj, "write")
        obj.__dict__[self.slot] = value

    def __delete__(self, obj) -> None:
        if self.check:
            self._validate(obj, "delete")
        obj.__dict__.pop(self.slot, None)


def guarded_by(lock_name: Optional[str] = None, *,
               attr: Optional[str] = None, graph=None) -> _GuardedField:
    """Class-level annotation: ``_entries = guarded_by("cache.lock")``
    makes every read/write of ``self._entries`` (after the ``__init__``
    seed) fail fast unless the named :func:`checked_lock` is held by the
    current thread. ``guarded_by(attr="_lock")`` resolves the required
    name from the INSTANCE's lock instead — for classes whose lock name
    is a constructor parameter (PlanCache serves both the plan and the
    template cache under different names). Name-granular like the rest
    of the validator: two instances sharing a lock NAME satisfy each
    other's guard, which matches how the engine names its locks (one
    name per subsystem lock). No-op (plain storage) when the validator
    is disabled."""
    if (lock_name is None) == (attr is None):
        raise TypeError("guarded_by takes exactly one of a lock name "
                        "or attr=")
    return _GuardedField(lock_name, attr, check=ENABLED, graph=graph)

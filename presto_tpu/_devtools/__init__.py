"""Developer-facing runtime checkers (never active in production paths
unless explicitly enabled; see lockcheck.ENABLED)."""

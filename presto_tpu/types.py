"""SQL type system for the TPU-native engine.

Conceptual parity with Presto's type layer (reference:
presto-spi/src/main/java/io/prestosql/spi/type/ and
presto-main/src/main/java/io/prestosql/type/InternalTypeManager.java), but
designed around XLA storage: every SQL type maps to a fixed-width device dtype
so columns are flat jnp arrays that tile onto the VPU/MXU.

Storage mapping (TPU-first):
  BOOLEAN     -> bool_
  TINYINT     -> int8   (stored as int32 on device for VPU friendliness)
  SMALLINT    -> int16  (stored int32)
  INTEGER     -> int32
  BIGINT      -> int64
  DOUBLE      -> float64 (jax x64 enabled by the package __init__)
  REAL        -> float32
  DECIMAL(p<=18, s) -> int64 scaled by 10**s  (Presto's "short decimal",
                       reference spi/type/DecimalType.java)
  DATE        -> int32 days since epoch
  TIMESTAMP   -> int64 microseconds since epoch
  VARCHAR/CHAR -> int32 dictionary codes + host-side vocabulary
                  (strings never live on device as bytes; mirrors
                  DictionaryBlock, reference spi/block/DictionaryBlock.java)

Null handling is out-of-band: a per-column boolean validity mask (see
batch.Column), like Presto's per-Block isNull arrays.
"""
from __future__ import annotations

import dataclasses
from typing import Any, ClassVar, Optional, Tuple

import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class Type:
    """Base class for SQL types."""

    #: canonical lowercase SQL name, e.g. "bigint"
    name: ClassVar[str] = "unknown"

    @property
    def storage_dtype(self):
        raise NotImplementedError

    @property
    def is_string(self) -> bool:
        return False

    @property
    def is_orderable(self) -> bool:
        return True

    @property
    def is_comparable(self) -> bool:
        return True

    def display(self) -> str:
        return self.name

    def __str__(self) -> str:  # pragma: no cover - debugging aid
        return self.display()

    # -- value conversion ---------------------------------------------------
    def to_storage(self, value: Any):
        """Convert a python literal to its device storage representation."""
        return value

    def from_storage(self, value: Any):
        """Convert a device storage value back to a python value."""
        return value

    def null_storage(self):
        """Padding value used in storage slots whose validity bit is off."""
        return 0


@dataclasses.dataclass(frozen=True)
class BooleanType(Type):
    name: ClassVar[str] = "boolean"

    @property
    def storage_dtype(self):
        return jnp.bool_

    def null_storage(self):
        return False


@dataclasses.dataclass(frozen=True)
class IntegerLikeType(Type):
    @property
    def storage_dtype(self):
        return jnp.int32


@dataclasses.dataclass(frozen=True)
class TinyintType(IntegerLikeType):
    name: ClassVar[str] = "tinyint"


@dataclasses.dataclass(frozen=True)
class SmallintType(IntegerLikeType):
    name: ClassVar[str] = "smallint"


@dataclasses.dataclass(frozen=True)
class IntegerType(IntegerLikeType):
    name: ClassVar[str] = "integer"


@dataclasses.dataclass(frozen=True)
class BigintType(Type):
    name: ClassVar[str] = "bigint"

    @property
    def storage_dtype(self):
        return jnp.int64


@dataclasses.dataclass(frozen=True)
class DoubleType(Type):
    """IEEE double. On TPU, f64 is double-double emulation: full f64
    precision but only f32 exponent range (|x| <~ 3.4e38 on device)."""

    name: ClassVar[str] = "double"

    @property
    def storage_dtype(self):
        return jnp.float64

    def null_storage(self):
        return 0.0


@dataclasses.dataclass(frozen=True)
class RealType(Type):
    name: ClassVar[str] = "real"

    @property
    def storage_dtype(self):
        return jnp.float32

    def null_storage(self):
        return 0.0


@dataclasses.dataclass(frozen=True)
class DecimalType(Type):
    """DECIMAL(p, s): unscaled-integer storage scaled by 10**scale.

    p <= 18 ("short") stores one i64 per value; p in 19..38 ("long")
    stores a two-limb [capacity, 2] i64 tile — value = hi * 2**64 +
    (lo mod 2**64), the TPU-columnar shape of the reference's Int128
    (reference spi/type/DecimalType.java MAX_PRECISION = 38,
    spi/block/Int128ArrayBlock.java; limb kernels in ops/int128.py).
    """

    precision: int = 18
    scale: int = 0
    name: ClassVar[str] = "decimal"

    def __post_init__(self):
        if not (1 <= self.precision <= 38):
            raise ValueError(f"unsupported decimal precision {self.precision}")
        if not (0 <= self.scale <= self.precision):
            raise ValueError(f"bad decimal scale {self.scale}")

    @property
    def is_long(self) -> bool:
        return self.precision > 18

    @property
    def storage_dtype(self):
        return jnp.int64

    @property
    def storage_width(self):
        # None (absent) for short decimals keeps their 1-D columns
        return 2 if self.is_long else None

    def display(self) -> str:
        return f"decimal({self.precision},{self.scale})"

    def null_storage(self):
        return (0, 0) if self.is_long else 0

    def to_storage(self, value: Any):
        # round-half-up like Presto's Decimals.encodeScaledValue
        import decimal
        from decimal import Decimal, ROUND_HALF_UP

        with decimal.localcontext() as ctx:
            ctx.prec = 60                   # enough for 38-digit values
            d = Decimal(str(value)).quantize(
                Decimal(1).scaleb(-self.scale), rounding=ROUND_HALF_UP
            )
            unscaled = int(d.scaleb(self.scale))
        if abs(unscaled) >= 10 ** self.precision:
            raise ValueError(
                f"value {value!r} out of range for {self.display()}"
            )
        if self.is_long:
            from .ops.int128 import limbs_of
            return limbs_of(unscaled)
        return unscaled

    def from_storage(self, value: Any):
        import decimal
        from decimal import Decimal

        with decimal.localcontext() as ctx:
            ctx.prec = 60
            if self.is_long:
                from .ops.int128 import int_of
                h, l = (int(value[0]), int(value[1]))
                if h == -(1 << 63) and l == 1:
                    # ops/int128.py OVERFLOW_SENTINEL: a decimal
                    # aggregate exceeded 38 digits (deferred raise,
                    # reference DecimalSumAggregation overflow throw)
                    from .errors import NUMERIC_VALUE_OUT_OF_RANGE, QueryError
                    raise QueryError(
                        NUMERIC_VALUE_OUT_OF_RANGE,
                        "decimal aggregate overflowed 38 digits")
                unscaled = int_of(h, l)
                if unscaled >= 1 << 127:
                    unscaled -= 1 << 128
                return Decimal(unscaled).scaleb(-self.scale)
            return Decimal(int(value)).scaleb(-self.scale)


@dataclasses.dataclass(frozen=True)
class DateType(Type):
    """Days since 1970-01-01 (matches Presto DateType semantics)."""

    name: ClassVar[str] = "date"

    @property
    def storage_dtype(self):
        return jnp.int32

    def to_storage(self, value: Any) -> int:
        import datetime

        if isinstance(value, (int, np.integer)):
            return int(value)
        if isinstance(value, str):
            value = datetime.date.fromisoformat(value)
        if isinstance(value, datetime.date):
            return (value - datetime.date(1970, 1, 1)).days
        raise TypeError(f"cannot convert {value!r} to date")

    def from_storage(self, value: Any):
        import datetime

        return datetime.date(1970, 1, 1) + datetime.timedelta(days=int(value))


@dataclasses.dataclass(frozen=True)
class TimestampType(Type):
    """Microseconds since epoch."""

    name: ClassVar[str] = "timestamp"

    @property
    def storage_dtype(self):
        return jnp.int64

    def to_storage(self, value: Any) -> int:
        import datetime

        if isinstance(value, (int, np.integer)):
            return int(value)
        if isinstance(value, str):
            s = value.strip().replace("T", " ")
            value = datetime.datetime.fromisoformat(s)
        if isinstance(value, datetime.datetime):
            epoch = datetime.datetime(1970, 1, 1)
            return round((value - epoch).total_seconds() * 1_000_000)
        if isinstance(value, datetime.date):
            return (value - datetime.date(1970, 1, 1)).days * 86_400_000_000
        raise TypeError(f"cannot convert {value!r} to timestamp")

    def from_storage(self, value: Any):
        import datetime

        return (datetime.datetime(1970, 1, 1)
                + datetime.timedelta(microseconds=int(value)))


@dataclasses.dataclass(frozen=True)
class VarcharType(Type):
    """Dictionary-encoded string: int32 codes into a host-side vocabulary."""

    length: Optional[int] = None  # None = unbounded
    name: ClassVar[str] = "varchar"

    @property
    def storage_dtype(self):
        return jnp.int32

    @property
    def is_string(self) -> bool:
        return True

    def display(self) -> str:
        return "varchar" if self.length is None else f"varchar({self.length})"

    def null_storage(self):
        return -1


@dataclasses.dataclass(frozen=True)
class CharType(Type):
    length: int = 1
    name: ClassVar[str] = "char"

    @property
    def storage_dtype(self):
        return jnp.int32

    @property
    def is_string(self) -> bool:
        return True

    def display(self) -> str:
        return f"char({self.length})"

    def null_storage(self):
        return -1


@dataclasses.dataclass(frozen=True)
class VarbinaryType(Type):
    """Binary strings, dictionary-encoded like varchar: int32 codes into
    a host-side vocabulary of bytes values (reference
    spi/type/VarbinaryType.java; the device representation reuses the
    string plan — binary payloads are metadata-heavy, compute-light)."""

    name: ClassVar[str] = "varbinary"

    @property
    def storage_dtype(self):
        return jnp.int32

    @property
    def is_string(self) -> bool:
        return True

    def display(self) -> str:
        return "varbinary"

    def null_storage(self):
        return -1


@dataclasses.dataclass(frozen=True)
class ArrayType(Type):
    """ARRAY(T): padded dense device representation (reference
    spi/type/ArrayType.java + block/ArrayBlock.java's offsets+values,
    re-designed TPU-first as a [capacity, max_len] tile + per-row lengths
    so every array op is a static-shape vectorized 2D kernel).

    Column layout for an array column: ``data`` is the tuple
    (values[cap, L], lengths[cap] int32, elem_valid[cap, L] bool);
    ``validity`` stays the row-level null mask; ``dictionary`` holds the
    element vocabulary when the element type is a string."""

    element: Type = None  # type: ignore[assignment]
    name: ClassVar[str] = "array"

    @property
    def storage_dtype(self):
        return self.element.storage_dtype

    def display(self) -> str:
        return f"array({self.element.display()})"


@dataclasses.dataclass(frozen=True)
class MapType(Type):
    """MAP(K, V): padded dense like ArrayType. Column ``data`` is
    (keys[cap, L], values[cap, L], lengths[cap], val_valid[cap, L]);
    keys are never null (SQL map semantics). ``dictionary`` is the tuple
    (key_vocab, value_vocab) when either side is a string (reference
    spi/type/MapType.java + block/MapBlock.java)."""

    key: Type = None      # type: ignore[assignment]
    value: Type = None    # type: ignore[assignment]
    name: ClassVar[str] = "map"

    @property
    def storage_dtype(self):
        return self.value.storage_dtype

    def display(self) -> str:
        return f"map({self.key.display()}, {self.value.display()})"


@dataclasses.dataclass(frozen=True)
class HllStateType(Type):
    """HyperLogLog register-vector state for approx_distinct partials
    (reference presto-main/.../operator/aggregation/state/
    HyperLogLogState.java + airlift HyperLogLog). Column ``data`` is a
    dense i32 tile [capacity, m] of per-bucket max-rank registers — a
    fixed-width vector per group, so partial states merge with one
    vectorized segment_max and ship through exchanges as ordinary
    fixed-width columns (``storage_width`` tells the wire format the
    trailing dimension)."""

    m: int = 2048
    name: ClassVar[str] = "hllstate"

    @property
    def storage_dtype(self):
        return jnp.int32

    @property
    def storage_width(self) -> int:
        return self.m

    def display(self) -> str:
        return f"hllstate({self.m})"


@dataclasses.dataclass(frozen=True)
class QdigestStateType(Type):
    """Quantile-histogram state for approx_percentile partials
    (reference presto-main/.../operator/aggregation/state/
    DigestAndPercentileState.java + airlift QuantileDigest). Column
    ``data`` is a dense i64 tile [capacity, bins] of log-linear bin
    counts (ops/sketch.py qd_*): fixed-size regardless of input rows,
    merged with one vector add, shipped through exchanges as an
    ordinary fixed-width column. ``bins`` must equal ops/sketch.py
    QD_BINS (the layout constant lives there; callers pass it in)."""

    bins: int
    name: ClassVar[str] = "qdigeststate"

    @property
    def storage_dtype(self):
        return jnp.int64

    @property
    def storage_width(self) -> int:
        return self.bins

    def display(self) -> str:
        return f"qdigeststate({self.bins})"


@dataclasses.dataclass(frozen=True)
class RowType(Type):
    """ROW(f1 T1, ...): struct of child columns. Column ``data`` is a
    tuple of (child_data, child_valid) pairs; ``dictionary`` is a tuple
    of per-field vocabularies (reference spi/type/RowType.java)."""

    field_types: Tuple[Type, ...] = ()
    field_names: Tuple[str, ...] = ()
    name: ClassVar[str] = "row"

    @property
    def storage_dtype(self):
        return jnp.int32   # unused; children carry their own dtypes

    def display(self) -> str:
        inner = ", ".join(
            (f"{n} {t.display()}" if n else t.display())
            for n, t in zip(self.field_names or [""] * len(self.field_types),
                            self.field_types))
        return f"row({inner})"


@dataclasses.dataclass(frozen=True)
class UnknownType(Type):
    """Type of a bare NULL literal."""

    name: ClassVar[str] = "unknown"

    @property
    def storage_dtype(self):
        return jnp.int32


# Singletons (Presto style: BIGINT, DOUBLE, ... constants)
BOOLEAN = BooleanType()
TINYINT = TinyintType()
SMALLINT = SmallintType()
INTEGER = IntegerType()
BIGINT = BigintType()
DOUBLE = DoubleType()
REAL = RealType()
DATE = DateType()
TIMESTAMP = TimestampType()
VARCHAR = VarcharType()
VARBINARY = VarbinaryType()
UNKNOWN = UnknownType()


def decimal(precision: int, scale: int) -> DecimalType:
    return DecimalType(precision, scale)


def varchar(length: Optional[int] = None) -> VarcharType:
    return VarcharType(length)


def char(length: int) -> CharType:
    return CharType(length)


_NUMERIC = (TinyintType, SmallintType, IntegerType, BigintType, RealType,
            DoubleType, DecimalType)
_INTEGRAL = (TinyintType, SmallintType, IntegerType, BigintType)


def is_numeric(t: Type) -> bool:
    return isinstance(t, _NUMERIC)


def is_integral(t: Type) -> bool:
    return isinstance(t, _INTEGRAL)


def is_floating(t: Type) -> bool:
    return isinstance(t, (RealType, DoubleType))


def is_string_type(t: Type) -> bool:
    return t.is_string


_INTEGRAL_RANK = {"tinyint": 0, "smallint": 1, "integer": 2, "bigint": 3}


def common_super_type(a: Type, b: Type) -> Optional[Type]:
    """Least-common supertype for implicit coercion.

    Mirrors the coercion lattice in Presto's TypeCoercion/FunctionRegistry
    (reference presto-main/.../type/TypeCoercion.java concept): integral
    widening, integral->decimal->double, varchar/char unification.
    """
    if a == b:
        return a
    if isinstance(a, UnknownType):
        return b
    if isinstance(b, UnknownType):
        return a
    if is_integral(a) and is_integral(b):
        return a if _INTEGRAL_RANK[a.name] >= _INTEGRAL_RANK[b.name] else b
    if is_numeric(a) and is_numeric(b):
        if isinstance(a, DoubleType) or isinstance(b, DoubleType):
            return DOUBLE
        if isinstance(a, RealType) or isinstance(b, RealType):
            # decimal + real -> real in Presto
            return REAL
        if isinstance(a, DecimalType) and isinstance(b, DecimalType):
            # widen to long decimal past 18 digits like the reference
            # (TypeCoercion over Int128-backed DecimalType; precision
            # saturates at 38 keeping the wider scale)
            scale = max(a.scale, b.scale)
            int_digits = max(a.precision - a.scale, b.precision - b.scale)
            return DecimalType(min(int_digits + scale, 38), scale)
        if isinstance(a, DecimalType) and is_integral(b):
            int_digits = {"tinyint": 3, "smallint": 5, "integer": 10, "bigint": 19}[b.name]
            return common_super_type(a, DecimalType(int_digits, 0))
        if isinstance(b, DecimalType) and is_integral(a):
            return common_super_type(b, a)
    if isinstance(a, ArrayType) and isinstance(b, ArrayType):
        e = common_super_type(a.element, b.element)
        return ArrayType(e) if e is not None else None
    if a.is_string and b.is_string:
        # varbinary never unifies with character strings (the reference
        # rejects varchar<->varbinary comparison/coercion at analysis)
        if isinstance(a, VarbinaryType) != isinstance(b, VarbinaryType):
            return None
        if isinstance(a, VarbinaryType):
            return VARBINARY
        return VARCHAR
    if isinstance(a, DateType) and isinstance(b, TimestampType):
        return TIMESTAMP
    if isinstance(b, DateType) and isinstance(a, TimestampType):
        return TIMESTAMP
    return None


def parse_type(text: str) -> Type:
    """Parse a SQL type name like 'decimal(12,2)' or 'varchar(25)'."""
    s = text.strip().lower()
    if "(" in s:
        base, _, rest = s.partition("(")
        base = base.strip()
        inner = rest.rstrip()
        assert inner.endswith(")"), text
        inner = inner[:-1]
        if base == "array":
            return ArrayType(parse_type(inner))
        if base == "map":
            depth = 0
            for i, ch in enumerate(inner):
                if ch == "(":
                    depth += 1
                elif ch == ")":
                    depth -= 1
                elif ch == "," and depth == 0:
                    return MapType(parse_type(inner[:i]),
                                   parse_type(inner[i + 1:]))
            raise ValueError(f"bad map type {text!r}")
        args = [int(x) for x in inner.split(",")]
        if base == "decimal":
            return DecimalType(*args)
        if base == "varchar":
            return VarcharType(args[0])
        if base == "char":
            return CharType(args[0])
        if base == "hllstate":
            return HllStateType(args[0])
        if base == "qdigeststate":
            return QdigestStateType(args[0])
        raise ValueError(f"unknown parametric type {text!r}")
    simple = {
        "boolean": BOOLEAN,
        "tinyint": TINYINT,
        "smallint": SMALLINT,
        "integer": INTEGER,
        "int": INTEGER,
        "bigint": BIGINT,
        "double": DOUBLE,
        "real": REAL,
        "date": DATE,
        "timestamp": TIMESTAMP,
        "varchar": VARCHAR,
        "varbinary": VARBINARY,
        "unknown": UNKNOWN,
    }
    if s in simple:
        return simple[s]
    raise ValueError(f"unknown type {text!r}")

"""Query event listeners.

The role of the reference's event-listener plugin point (reference
eventlistener/EventListenerManager.java + event/QueryMonitor.java
publishing spi/eventlistener/QueryCompletedEvent.java): the runner
publishes created/completed events to registered listeners; audit
loggers, metrics sinks, and the verifier's query log all hang off this.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Callable, List, Optional


@dataclasses.dataclass(frozen=True)
class QueryCompletedEvent:
    query_id: str
    query: str
    user: str
    state: str                  # FINISHED | FAILED
    elapsed_ms: float
    error: Optional[str] = None
    create_time: float = 0.0    # epoch seconds
    #: rich final record (plan summary, per-operator stats, peak
    #: memory, cpu/device-sync time) — the publisher-built payload the
    #: query-history listener (obs.history) persists verbatim
    history: Optional[dict] = None


@dataclasses.dataclass(frozen=True)
class SplitCompletedEvent:
    """Per-split completion (reference event/SplitMonitor.java +
    spi/eventlistener/SplitCompletedEvent.java)."""
    query_id: str
    table: str
    split: int
    wall_ms: float
    batches: int


class EventListenerManager:
    def __init__(self) -> None:
        self._listeners: List[Callable[[QueryCompletedEvent], None]] = []
        self._split_listeners: List[
            Callable[[SplitCompletedEvent], None]] = []

    def register(self,
                 listener: Callable[[QueryCompletedEvent], None]) -> None:
        self._listeners.append(listener)

    def register_split_listener(
            self, listener: Callable[[SplitCompletedEvent], None]) -> None:
        self._split_listeners.append(listener)

    def query_completed(self, event: QueryCompletedEvent) -> None:
        for listener in self._listeners:
            try:
                listener(event)
            except Exception:   # listeners must not break queries
                pass

    def split_completed(self, event: SplitCompletedEvent) -> None:
        for listener in self._split_listeners:
            try:
                listener(event)
            except Exception:
                pass


def completed_event(query_id: str, query: str, user: str, state: str,
                    started_at: float, error: Optional[str] = None,
                    history: Optional[dict] = None) -> QueryCompletedEvent:
    return QueryCompletedEvent(
        query_id=query_id, query=query, user=user, state=state,
        elapsed_ms=(time.perf_counter() - started_at) * 1e3,
        error=error, create_time=time.time(), history=history)

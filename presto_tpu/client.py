"""Statement protocol client: POST /v1/statement, follow nextUri.

The Python analogue of the reference client (reference
presto-client/.../StatementClientV1.java:86 — execute():147 POSTs the
statement, advance():339 follows ``nextUri`` until it is absent; session
mutations arrive via X-Presto-Set-Session / X-Presto-Clear-Session
response headers, client/PrestoHeaders.java:30-31). Uses only the
standard library (urllib) — the role OkHttp plays for the reference.
"""
from __future__ import annotations

import dataclasses
import json
import urllib.parse
import urllib.request
from typing import Dict, Iterator, List, Optional, Tuple


class QueryFailed(Exception):
    def __init__(self, error: Dict):
        super().__init__(error.get("message", "query failed"))
        self.error = error


@dataclasses.dataclass
class ClientResult:
    columns: List[Tuple[str, str]]          # (name, type display)
    rows: List[List[object]]
    query_id: str


class StatementClient:
    def __init__(self, base_url: str, user: str = "presto",
                 catalog: Optional[str] = None,
                 schema: Optional[str] = None,
                 timeout: float = 3600.0,
                 password: Optional[str] = None):
        self.base_url = base_url.rstrip("/")
        self.user = user
        self.catalog = catalog
        self.schema = schema
        self.timeout = timeout
        self.password = password
        self.session_properties: Dict[str, str] = {}

    # -- protocol ------------------------------------------------------------
    def _request(self, url: str, method: str = "GET",
                 body: Optional[bytes] = None):
        headers = {"X-Presto-User": self.user}
        if self.password is not None:
            import base64
            raw = f"{self.user}:{self.password}".encode()
            headers["Authorization"] = \
                "Basic " + base64.b64encode(raw).decode()
        if self.catalog:
            headers["X-Presto-Catalog"] = self.catalog
        if self.schema:
            headers["X-Presto-Schema"] = self.schema
        if self.session_properties:
            headers["X-Presto-Session"] = ",".join(
                f"{k}={urllib.parse.quote(str(v))}"
                for k, v in self.session_properties.items())
        req = urllib.request.Request(url, data=body, method=method,
                                     headers=headers)
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            doc = json.loads(resp.read() or b"{}")
            for header, value in resp.headers.items():
                if header == "X-Presto-Set-Session" and "=" in value:
                    k, v = value.split("=", 1)
                    self.session_properties[k.strip()] = v.strip()
                elif header == "X-Presto-Clear-Session":
                    self.session_properties.pop(value.strip(), None)
            return doc

    def pages(self, sql: str) -> Iterator[Dict]:
        """Yield raw QueryResults documents until the query drains."""
        doc = self._request(f"{self.base_url}/v1/statement", "POST",
                            sql.encode())
        yield doc
        while doc.get("nextUri"):
            doc = self._request(doc["nextUri"])
            yield doc
        if doc.get("error"):
            raise QueryFailed(doc["error"])

    def execute(self, sql: str) -> ClientResult:
        columns: List[Tuple[str, str]] = []
        rows: List[List[object]] = []
        qid = ""
        for doc in self.pages(sql):
            qid = doc.get("id", qid)
            if doc.get("columns") and not columns:
                columns = [(c["name"], c["type"]) for c in doc["columns"]]
            rows.extend(doc.get("data") or [])
        return ClientResult(columns=columns, rows=rows, query_id=qid)

"""Statement protocol client: POST /v1/statement, follow nextUri.

The Python analogue of the reference client (reference
presto-client/.../StatementClientV1.java:86 — execute():147 POSTs the
statement, advance():339 follows ``nextUri`` until it is absent; session
mutations arrive via X-Presto-Set-Session / X-Presto-Clear-Session
response headers, client/PrestoHeaders.java:30-31). Uses only the
standard library (urllib) — the role OkHttp plays for the reference.
"""
from __future__ import annotations

import dataclasses
import itertools
import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, Iterator, List, Optional, Tuple


class QueryFailed(Exception):
    def __init__(self, error: Dict):
        super().__init__(error.get("message", "query failed"))
        self.error = error


@dataclasses.dataclass
class ClientResult:
    columns: List[Tuple[str, str]]          # (name, type display)
    rows: List[List[object]]
    query_id: str


class _RawHTTPConnection:
    """Minimal HTTP/1.1 keep-alive transport for the statement
    protocol. ``http.client`` parses every response's headers through
    the email package (~40% of a warm statement's CLIENT-side CPU at
    serving rates); the statement server's responses are plain
    HTTP/1.1 with an explicit Content-Length and no chunking, so a
    status line + header-lines + counted-body reader covers them in a
    fraction of the cost. Anything off-pattern (no Content-Length, a
    1.0 server) raises ``ConnectionError`` — an OSError, which the
    caller's stale-connection retry already handles, falling back to a
    fresh connection."""

    def __init__(self, netloc: str, timeout: float):
        import socket
        host, _, port = netloc.partition(":")
        self.sock = socket.create_connection(
            (host, int(port or 80)), timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._rfile = self.sock.makefile("rb", buffering=65536)
        self._host = netloc
        self.closed = False

    def close(self) -> None:
        self.closed = True
        try:
            self._rfile.close()
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass

    def send_request(self, method: str, path: str,
                     headers: Dict[str, str],
                     body: Optional[bytes]) -> None:
        body = body or b""
        lines = [f"{method} {path} HTTP/1.1",
                 f"Host: {self._host}",
                 f"Content-Length: {len(body)}"]
        for k, v in headers.items():
            lines.append(f"{k}: {v}")
        self.sock.sendall(
            ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1") + body)

    def read_response(self):
        """Returns ``(status, reason, headers_dict, data)``. Raises
        OSError subclasses on transport trouble so callers can retry on
        a fresh connection."""
        status_line = self._rfile.readline(65537)
        if not status_line:
            raise ConnectionResetError("server closed the connection")
        parts = status_line.decode("latin-1").rstrip("\r\n").split(" ", 2)
        try:
            status = int(parts[1])
        except (IndexError, ValueError):
            raise ConnectionError(
                f"malformed status line {status_line!r}") from None
        if not parts[0].startswith("HTTP/1."):
            raise ConnectionError(f"not an HTTP/1.x response: {parts[0]!r}")
        reason = parts[2] if len(parts) > 2 else ""
        resp_headers: Dict[str, str] = {}
        while True:
            line = self._rfile.readline(65537)
            if line in (b"\r\n", b"\n", b""):
                break
            k, _, v = line.decode("latin-1").partition(":")
            resp_headers[k.strip()] = v.strip()
        try:
            length = int(resp_headers["Content-Length"])
        except (KeyError, ValueError):
            raise ConnectionError(
                "response without a usable Content-Length") from None
        data = self._rfile.read(length)
        if len(data) != length:
            raise ConnectionResetError("short response body")
        if resp_headers.get("Connection", "").lower() == "close":
            self.close()
        return status, reason, resp_headers, data


class StatementClient:
    def __init__(self, base_url: str, user: str = "presto",
                 catalog: Optional[str] = None,
                 schema: Optional[str] = None,
                 timeout: float = 3600.0,
                 password: Optional[str] = None):
        self.base_url = base_url.rstrip("/")
        self.user = user
        self.catalog = catalog
        self.schema = schema
        self.timeout = timeout
        self.password = password
        self.session_properties: Dict[str, str] = {}
        # persistent keep-alive connection (the server speaks
        # HTTP/1.1): a serving fleet issuing thousands of short
        # statements must not pay a TCP handshake per request — at 100
        # concurrent clients the fresh-connection storm overflows
        # listen backlogs and the SYN retransmits quantize cache-hit
        # latencies to whole seconds. One connection per client
        # instance; clients are thread-confined like the reference's.
        self._conn = None
        self._conn_netloc: Optional[str] = None

    # -- protocol ------------------------------------------------------------
    def close(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            finally:
                self._conn = None
                self._conn_netloc = None

    def _connection(self, netloc: str) -> _RawHTTPConnection:
        if (self._conn is None or self._conn.closed
                or self._conn_netloc != netloc):
            self.close()
            self._conn = _RawHTTPConnection(netloc, timeout=self.timeout)
            self._conn_netloc = netloc
        return self._conn

    def _headers(self) -> Dict[str, str]:
        """Request headers; the static part builds once per client and
        the session overlay re-renders only when it changed (a serving
        client issues thousands of identical-header requests)."""
        cached = getattr(self, "_hdr_cache", None)
        if cached is not None and cached[0] == self.session_properties:
            return cached[1]
        headers = {"X-Presto-User": self.user}
        if self.password is not None:
            import base64
            raw = f"{self.user}:{self.password}".encode()
            headers["Authorization"] = \
                "Basic " + base64.b64encode(raw).decode()
        if self.catalog:
            headers["X-Presto-Catalog"] = self.catalog
        if self.schema:
            headers["X-Presto-Schema"] = self.schema
        if self.session_properties:
            headers["X-Presto-Session"] = ",".join(
                f"{k}={urllib.parse.quote(str(v))}"
                for k, v in self.session_properties.items())
        self._hdr_cache = (dict(self.session_properties), headers)
        return headers

    def _request(self, url: str, method: str = "GET",
                 body: Optional[bytes] = None):
        headers = self._headers()
        parts = urllib.parse.urlsplit(url)
        path = parts.path + (f"?{parts.query}" if parts.query else "")
        status = reason = resp_headers = data = None
        for attempt in (0, 1):
            conn = self._connection(parts.netloc)
            sent = False
            try:
                conn.send_request(method, path, headers, body)
                sent = True
                status, reason, resp_headers, data = conn.read_response()
                break
            except OSError as e:
                # server closed the idle keep-alive (or first use of a
                # stale connection): reconnect once, then surface. A
                # non-idempotent request that FAILED AFTER SENDING is
                # never replayed — the server may have executed it
                # (POST /v1/statement runs INSERTs); the caller sees
                # the transport error instead of silent double writes.
                # The annotation lets a failover policy (FleetClient)
                # make the same distinction.
                e.sent_request = sent
                self.close()
                if attempt or (sent and method != "GET"):
                    raise
        if status >= 400:
            # urllib-compatible error surface for callers that catch
            # HTTPError (drain 503s, auth 401s)
            import io
            raise urllib.error.HTTPError(url, status, reason,
                                         resp_headers, io.BytesIO(data))
        doc = json.loads(data or b"{}")
        for header, value in resp_headers.items():
            if header == "X-Presto-Set-Session" and "=" in value:
                k, v = value.split("=", 1)
                self.session_properties[k.strip()] = v.strip()
            elif header == "X-Presto-Clear-Session":
                self.session_properties.pop(value.strip(), None)
        return doc

    def pages(self, sql: str) -> Iterator[Dict]:
        """Yield raw QueryResults documents until the query drains."""
        doc = self._request(f"{self.base_url}/v1/statement", "POST",
                            sql.encode())
        yield doc
        while doc.get("nextUri"):
            doc = self._request(doc["nextUri"])
            yield doc
        if doc.get("error"):
            raise QueryFailed(doc["error"])

    def execute(self, sql: str) -> ClientResult:
        columns: List[Tuple[str, str]] = []
        rows: List[List[object]] = []
        qid = ""
        for doc in self.pages(sql):
            qid = doc.get("id", qid)
            if doc.get("columns") and not columns:
                columns = [(c["name"], c["type"]) for c in doc["columns"]]
            rows.extend(doc.get("data") or [])
        return ClientResult(columns=columns, rows=rows, query_id=qid)


class FleetClient:
    """Round-robin, retry-on-failure statement client over a
    coordinator fleet.

    Statements rotate across the fleet's coordinators; a dispatch that
    fails on TRANSPORT (connection refused/reset — a crashed
    coordinator) or DRAIN (503 — a coordinator mid-rolling-restart)
    re-dispatches the whole statement to the next coordinator, up to
    two passes over the fleet. A statement that fails mid-pagination
    (the coordinator died while the client was following ``nextUri``)
    re-dispatches from scratch the same way — re-execution is cheap on
    a warm fleet (template/result caches), and pages already collected
    from the dead coordinator are discarded, never mixed with the
    retry's.

    Engine verdicts (:class:`QueryFailed`) and non-503 HTTP errors are
    the QUERY's outcome, not the coordinator's — they surface without
    retry.

    ``replay_sent=True`` (default) retries even non-GET requests that
    failed AFTER the request body was sent, making dispatch
    at-least-once: a coordinator that dies between executing an INSERT
    and answering may leave the INSERT applied, and the retry applies
    it again. Read-dominant serving fleets want this (availability over
    exactly-once side effects); set ``replay_sent=False`` to surface
    those ambiguous failures instead, like :class:`StatementClient`
    does.

    Thread-confined, like :class:`StatementClient` (one underlying
    keep-alive connection per coordinator)."""

    #: process-wide instance counter staggering each client's ring
    #: start. Without it every instance begins at coordinator 0 and a
    #: fleet of C coordinators serving clients issuing Q statements
    #: each splits ceil/floor(Q/C) per coordinator — at Q=8, C=3 the
    #: last coordinator systematically gets 2/8 of ALL traffic.
    _instances = itertools.count()

    def __init__(self, base_urls, user: str = "presto",
                 replay_sent: bool = True, fleet_passes: int = 2,
                 **client_kwargs):
        urls = list(base_urls)
        if not urls:
            raise ValueError("FleetClient needs at least one "
                             "coordinator URL")
        self.clients = [StatementClient(u, user=user, **client_kwargs)
                        for u in urls]
        self.replay_sent = replay_sent
        self.fleet_passes = max(1, int(fleet_passes))
        self._rr = next(FleetClient._instances) % len(urls)
        #: statements that needed >1 dispatch attempt
        self.retries_total = 0
        #: dispatch attempts moved to a DIFFERENT coordinator
        self.failovers_total = 0

    def close(self) -> None:
        for c in self.clients:
            c.close()

    def _ring(self) -> List[StatementClient]:
        """This statement's coordinator order: round-robin start, then
        the rest of the fleet in ring order (the failover chain)."""
        start = self._rr
        self._rr = (self._rr + 1) % len(self.clients)
        n = len(self.clients)
        return [self.clients[(start + k) % n] for k in range(n)]

    def _retryable(self, e: Exception) -> bool:
        import http.client
        if isinstance(e, urllib.error.HTTPError):
            return e.code == 503          # drain; 4xx/5xx else = verdict
        if isinstance(e, (OSError, http.client.HTTPException)):
            if getattr(e, "sent_request", False) and not self.replay_sent:
                return False              # ambiguous non-GET: surface
            return True
        return False

    def execute(self, sql: str) -> ClientResult:
        ring = self._ring()
        last: Optional[Exception] = None
        attempts = 0
        for _ in range(self.fleet_passes):
            for cl in ring:
                attempts += 1
                try:
                    res = cl.execute(sql)
                    if attempts > 1:
                        self.retries_total += 1
                    return res
                except QueryFailed:
                    raise
                except Exception as e:
                    if not self._retryable(e):
                        raise
                    last = e
                    self.failovers_total += 1
        raise last if last is not None else RuntimeError(
            "fleet dispatch failed")

"""Statement protocol client: POST /v1/statement, follow nextUri.

The Python analogue of the reference client (reference
presto-client/.../StatementClientV1.java:86 — execute():147 POSTs the
statement, advance():339 follows ``nextUri`` until it is absent; session
mutations arrive via X-Presto-Set-Session / X-Presto-Clear-Session
response headers, client/PrestoHeaders.java:30-31). Uses only the
standard library (urllib) — the role OkHttp plays for the reference.
"""
from __future__ import annotations

import dataclasses
import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, Iterator, List, Optional, Tuple


class QueryFailed(Exception):
    def __init__(self, error: Dict):
        super().__init__(error.get("message", "query failed"))
        self.error = error


@dataclasses.dataclass
class ClientResult:
    columns: List[Tuple[str, str]]          # (name, type display)
    rows: List[List[object]]
    query_id: str


class StatementClient:
    def __init__(self, base_url: str, user: str = "presto",
                 catalog: Optional[str] = None,
                 schema: Optional[str] = None,
                 timeout: float = 3600.0,
                 password: Optional[str] = None):
        self.base_url = base_url.rstrip("/")
        self.user = user
        self.catalog = catalog
        self.schema = schema
        self.timeout = timeout
        self.password = password
        self.session_properties: Dict[str, str] = {}
        # persistent keep-alive connection (the server speaks
        # HTTP/1.1): a serving fleet issuing thousands of short
        # statements must not pay a TCP handshake per request — at 100
        # concurrent clients the fresh-connection storm overflows
        # listen backlogs and the SYN retransmits quantize cache-hit
        # latencies to whole seconds. One connection per client
        # instance; clients are thread-confined like the reference's.
        self._conn = None
        self._conn_netloc: Optional[str] = None

    # -- protocol ------------------------------------------------------------
    def close(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            finally:
                self._conn = None
                self._conn_netloc = None

    def _connection(self, netloc: str):
        import http.client
        if self._conn is None or self._conn_netloc != netloc:
            self.close()
            self._conn = http.client.HTTPConnection(
                netloc, timeout=self.timeout)
            self._conn_netloc = netloc
        return self._conn

    def _headers(self) -> Dict[str, str]:
        """Request headers; the static part builds once per client and
        the session overlay re-renders only when it changed (a serving
        client issues thousands of identical-header requests)."""
        cached = getattr(self, "_hdr_cache", None)
        if cached is not None and cached[0] == self.session_properties:
            return cached[1]
        headers = {"X-Presto-User": self.user}
        if self.password is not None:
            import base64
            raw = f"{self.user}:{self.password}".encode()
            headers["Authorization"] = \
                "Basic " + base64.b64encode(raw).decode()
        if self.catalog:
            headers["X-Presto-Catalog"] = self.catalog
        if self.schema:
            headers["X-Presto-Schema"] = self.schema
        if self.session_properties:
            headers["X-Presto-Session"] = ",".join(
                f"{k}={urllib.parse.quote(str(v))}"
                for k, v in self.session_properties.items())
        self._hdr_cache = (dict(self.session_properties), headers)
        return headers

    def _request(self, url: str, method: str = "GET",
                 body: Optional[bytes] = None):
        import http.client
        headers = self._headers()
        parts = urllib.parse.urlsplit(url)
        path = parts.path + (f"?{parts.query}" if parts.query else "")
        resp = data = None
        for attempt in (0, 1):
            conn = self._connection(parts.netloc)
            sent = False
            try:
                conn.request(method, path, body=body, headers=headers)
                sent = True
                resp = conn.getresponse()
                data = resp.read()
                break
            except (http.client.HTTPException, OSError):
                # server closed the idle keep-alive (or first use of a
                # stale connection): reconnect once, then surface. A
                # non-idempotent request that FAILED AFTER SENDING is
                # never replayed — the server may have executed it
                # (POST /v1/statement runs INSERTs); the caller sees
                # the transport error instead of silent double writes.
                self.close()
                if attempt or (sent and method != "GET"):
                    raise
        if resp.status >= 400:
            # urllib-compatible error surface for callers that catch
            # HTTPError (drain 503s, auth 401s)
            import io
            raise urllib.error.HTTPError(url, resp.status, resp.reason,
                                         resp.headers, io.BytesIO(data))
        doc = json.loads(data or b"{}")
        for header, value in resp.headers.items():
            if header == "X-Presto-Set-Session" and "=" in value:
                k, v = value.split("=", 1)
                self.session_properties[k.strip()] = v.strip()
            elif header == "X-Presto-Clear-Session":
                self.session_properties.pop(value.strip(), None)
        return doc

    def pages(self, sql: str) -> Iterator[Dict]:
        """Yield raw QueryResults documents until the query drains."""
        doc = self._request(f"{self.base_url}/v1/statement", "POST",
                            sql.encode())
        yield doc
        while doc.get("nextUri"):
            doc = self._request(doc["nextUri"])
            yield doc
        if doc.get("error"):
            raise QueryFailed(doc["error"])

    def execute(self, sql: str) -> ClientResult:
        columns: List[Tuple[str, str]] = []
        rows: List[List[object]] = []
        qid = ""
        for doc in self.pages(sql):
            qid = doc.get("id", qid)
            if doc.get("columns") and not columns:
                columns = [(c["name"], c["type"]) for c in doc["columns"]]
            rows.extend(doc.get("data") or [])
        return ClientResult(columns=columns, rows=rows, query_id=qid)

"""Columnar data plane: device-resident batches with static padded shapes.

Conceptual parity with Presto's Page/Block (reference
presto-spi/src/main/java/io/prestosql/spi/Page.java:39-62 and
presto-spi/src/main/java/io/prestosql/spi/block/Block.java:23), re-designed
for XLA:

- A Batch is a struct-of-arrays: one flat jnp array per column, padded to a
  static *capacity* (power-of-two bucket) so kernels compile once per bucket
  and never see dynamic shapes.
- Liveness is a boolean ``row_mask`` (True = live row). Filters produce masks
  instead of compacting, which keeps everything branch-free on the VPU;
  explicit ``compact()`` exists for when gathers pay off.
- Nulls are per-column validity masks (Presto's per-Block isNull arrays).
- Strings are dictionary codes (int32) + a host-side vocabulary per column
  (Presto's DictionaryBlock made mandatory for device residency).

Batch and Column are registered as JAX pytrees, so jitted operator kernels
take and return them directly; the schema/dictionaries ride in the static
treedef, which is exactly the "compile once per (schema, bucket)" contract of
Presto's compiled PageProcessor (reference
presto-main/.../sql/gen/PageFunctionCompiler.java:121-136 cache keys).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .types import ArrayType, MapType, Type, VarcharType, CharType, parse_type


def bucket_capacity(n: int, minimum: int = 128) -> int:
    """Round row count up to a power-of-two bucket (recompile avoidance).

    Mirrors PageProcessor's adaptive batching buckets (reference
    presto-main/.../operator/project/PageProcessor.java:56 MAX_BATCH_SIZE).
    """
    cap = minimum
    while cap < n:
        cap *= 2
    return cap


@dataclasses.dataclass(frozen=True)
class Field:
    name: str
    type: Type


class Schema:
    """Ordered, named, typed columns."""

    def __init__(self, fields: Sequence[Tuple[str, Type]]):
        self.fields: Tuple[Field, ...] = tuple(
            f if isinstance(f, Field) else Field(f[0], f[1]) for f in fields
        )
        self._index = {f.name: i for i, f in enumerate(self.fields)}

    @property
    def names(self) -> List[str]:
        return [f.name for f in self.fields]

    @property
    def types(self) -> List[Type]:
        return [f.type for f in self.fields]

    def index_of(self, name: str) -> int:
        return self._index[name]

    def type_of(self, name: str) -> Type:
        return self.fields[self._index[name]].type

    def __len__(self) -> int:
        return len(self.fields)

    def __contains__(self, name: str) -> bool:
        return name in self._index

    def __eq__(self, other) -> bool:
        return isinstance(other, Schema) and self.fields == other.fields

    def __hash__(self) -> int:
        return hash(self.fields)

    def __repr__(self) -> str:
        inner = ", ".join(f"{f.name} {f.type.display()}" for f in self.fields)
        return f"Schema({inner})"

    def select(self, names: Sequence[str]) -> "Schema":
        return Schema([(n, self.type_of(n)) for n in names])


class Column:
    """One device column: data + validity, plus host dictionary for strings."""

    def __init__(
        self,
        type: Type,
        data: jax.Array,
        validity: jax.Array,
        dictionary: Optional[Tuple[str, ...]] = None,
    ):
        self.type = type
        self.data = data
        self.validity = validity
        self.dictionary = dictionary

    @property
    def capacity(self) -> int:
        # validity is always the row-level [capacity] mask, even for
        # composite columns whose data is a tuple of arrays
        return self.validity.shape[0]

    def tree_flatten(self):
        # data may be a tuple of arrays (ARRAY/MAP/ROW columns): jax
        # recurses into nested containers automatically
        return (self.data, self.validity), (self.type, self.dictionary)

    @classmethod
    def tree_unflatten(cls, aux, children):
        type_, dictionary = aux
        data, validity = children
        return cls(type_, data, validity, dictionary)

    def __repr__(self) -> str:
        return f"Column({self.type.display()}, cap={self.data.shape})"


jax.tree_util.register_pytree_node(
    Column, Column.tree_flatten, Column.tree_unflatten
)


class Batch:
    """A horizontal slice of rows: aligned columns + row liveness mask."""

    def __init__(self, schema: Schema, columns: Sequence[Column], row_mask: jax.Array):
        self.schema = schema
        self.columns = tuple(columns)
        self.row_mask = row_mask

    # -- pytree protocol ----------------------------------------------------
    # Columns are themselves registered pytree nodes; let JAX recurse.
    def tree_flatten(self):
        return (self.columns, self.row_mask), self.schema

    @classmethod
    def tree_unflatten(cls, aux, children):
        columns, row_mask = children
        return cls(aux, columns, row_mask)

    # -- basic accessors ----------------------------------------------------
    @property
    def capacity(self) -> int:
        return int(self.row_mask.shape[0])

    def count(self) -> jax.Array:
        """Number of live rows (device scalar)."""
        return jnp.sum(self.row_mask.astype(jnp.int32))

    def host_count(self) -> int:
        # explicit device_get: an int() on a device scalar is an IMPLICIT
        # transfer, which jax.transfer_guard("disallow") rejects — sizing
        # syncs are deliberate and should read as such
        return int(jax.device_get(self.count()))

    def column(self, name: str) -> Column:
        return self.columns[self.schema.index_of(name)]

    def with_columns(self, schema: Schema, columns: Sequence[Column]) -> "Batch":
        return Batch(schema, columns, self.row_mask)

    def select(self, names: Sequence[str]) -> "Batch":
        cols = [self.column(n) for n in names]
        return Batch(self.schema.select(names), cols, self.row_mask)

    # -- construction -------------------------------------------------------
    @staticmethod
    def from_arrays(
        schema: Schema,
        arrays: Sequence[np.ndarray],
        validity: Optional[Sequence[Optional[np.ndarray]]] = None,
        dictionaries: Optional[Sequence[Optional[Tuple[str, ...]]]] = None,
        capacity: Optional[int] = None,
        num_rows: Optional[int] = None,
    ) -> "Batch":
        """Build a device batch from host numpy arrays (already in storage repr)."""
        n = num_rows if num_rows is not None else (len(arrays[0]) if arrays else 0)
        cap = capacity or bucket_capacity(max(n, 1))
        cols = []
        for i, (f, arr) in enumerate(zip(schema.fields, arrays)):
            dt = f.type.storage_dtype
            width = getattr(f.type, "storage_width", None)
            shape = (cap,) if width is None else (cap, width)
            padded = np.zeros(shape, dtype=np.dtype(dt))
            padded[:n] = np.asarray(arr[:n]).astype(np.dtype(dt))
            if validity is not None and validity[i] is not None:
                v = np.zeros(cap, dtype=bool)
                v[:n] = validity[i][:n]
            else:
                v = np.zeros(cap, dtype=bool)
                v[:n] = True
            d = dictionaries[i] if dictionaries is not None else None
            cols.append(Column(f.type, jnp.asarray(padded), jnp.asarray(v), d))
        mask = np.zeros(cap, dtype=bool)
        mask[:n] = True
        return Batch(schema, cols, jnp.asarray(mask))

    @staticmethod
    def from_pydict(
        data: Dict[str, Tuple[Type, Sequence[Any]]], capacity: Optional[int] = None
    ) -> "Batch":
        """Build from python values: {name: (type, [values... (None = null)])}."""
        names = list(data.keys())
        schema_fields = []
        arrays: List[np.ndarray] = []
        validities: List[Optional[np.ndarray]] = []
        dictionaries: List[Optional[Tuple[str, ...]]] = []
        composite: Dict[int, Tuple[Type, List[Any]]] = {}
        n = None
        for name in names:
            typ, values = data[name]
            values = list(values)
            if n is None:
                n = len(values)
            elif len(values) != n:
                raise ValueError(
                    f"column {name!r} has {len(values)} values, expected {n}"
                )
            schema_fields.append((name, typ))
            if isinstance(typ, ArrayType):
                composite[len(schema_fields) - 1] = (typ, values)
                arrays.append(np.zeros(n, dtype=np.int32))   # placeholder
                validities.append(None)
                dictionaries.append(None)
                continue
            valid = np.array([v is not None for v in values], dtype=bool)
            if typ.is_string:
                vocab: List[str] = []
                lookup: Dict[str, int] = {}
                codes = np.full(len(values), -1, dtype=np.int32)
                for i, v in enumerate(values):
                    if v is None:
                        continue
                    if isinstance(typ, CharType):
                        v = str(v).ljust(typ.length)
                    code = lookup.get(v)
                    if code is None:
                        code = lookup[v] = len(vocab)
                        vocab.append(v)
                    codes[i] = code
                arrays.append(codes)
                dictionaries.append(tuple(vocab))
            else:
                storage = [typ.to_storage(v) if v is not None else typ.null_storage() for v in values]
                arrays.append(np.asarray(storage))
                dictionaries.append(None)
            validities.append(valid)
        schema = Schema(schema_fields)
        out = Batch.from_arrays(
            schema, arrays, validities, dictionaries, capacity=capacity, num_rows=n
        )
        if composite:
            cols = list(out.columns)
            for i, (typ, values) in composite.items():
                cols[i] = make_array_column(typ, values, out.capacity)
            out = Batch(schema, cols, out.row_mask)
        return out

    # -- export -------------------------------------------------------------
    def to_pylist(self) -> List[Tuple]:
        """Decode live rows to python tuples (for tests / client results)."""
        mask = np.asarray(jax.device_get(self.row_mask))
        out_cols = []
        for col in self.columns:
            if isinstance(col.type, (ArrayType, MapType)):
                out_cols.append(_composite_to_pylist(col, mask))
                continue
            data = np.asarray(jax.device_get(col.data))[mask]
            valid = np.asarray(jax.device_get(col.validity))[mask]
            vals: List[Any] = []
            for d, v in zip(data, valid):
                if not v:
                    vals.append(None)
                elif col.type.is_string:
                    code = int(d)
                    vals.append(col.dictionary[code] if col.dictionary and 0 <= code < len(col.dictionary) else None)
                else:
                    vals.append(col.type.from_storage(d))
            out_cols.append(vals)
        return [tuple(r) for r in zip(*out_cols)] if out_cols else []

    # -- transforms ---------------------------------------------------------
    def compact(self, capacity: Optional[int] = None, *,
                check: bool = True) -> "Batch":
        """Gather live rows to the front (device-side, static output shape).

        ``capacity`` smaller than the live-row count would silently drop rows;
        callers shrinking buckets must check ``host_count()`` first, so guard.
        Pass ``check=False`` from traced (jit/shard_map) contexts where the
        bound is guaranteed by construction — the guard needs a host sync.
        """
        cap = capacity or self.capacity
        if check and capacity is not None and capacity < self.capacity:
            live = self.host_count()
            if live > capacity:
                raise ValueError(
                    f"compact capacity {capacity} < live rows {live}"
                )
        idx = jnp.nonzero(self.row_mask, size=cap, fill_value=self.capacity - 1)[0]
        n = self.count()
        new_mask = jnp.arange(cap) < n
        cols = []
        for c in self.columns:
            cols.append(
                Column(
                    c.type,
                    jax.tree_util.tree_map(
                        lambda a: jnp.take(a, idx, axis=0), c.data),
                    jnp.take(c.validity, idx, axis=0) & new_mask,
                    c.dictionary,
                )
            )
        return Batch(self.schema, cols, new_mask)

    def pad(self, capacity: int) -> "Batch":
        """Grow to a larger capacity with dead padding lanes — the
        inverse of compact. The scan pipeline pads a split's ragged
        final chunk up to the stream's standard bucket so shape-keyed
        executables (ops/jitcache) are reused instead of recompiled per
        residual size. Padding lanes are dead (row_mask/validity False),
        so results are unchanged."""
        if capacity <= self.capacity:
            return self
        extra = capacity - self.capacity

        def grow(a):
            widths = [(0, extra)] + [(0, 0)] * (a.ndim - 1)
            return jnp.pad(a, widths)

        cols = [
            Column(c.type, jax.tree_util.tree_map(grow, c.data),
                   grow(c.validity), c.dictionary)
            for c in self.columns
        ]
        return Batch(self.schema, cols, grow(self.row_mask))

    def __repr__(self) -> str:
        return f"Batch({self.schema!r}, capacity={self.capacity})"


jax.tree_util.register_pytree_node(
    Batch, Batch.tree_flatten, Batch.tree_unflatten
)


def _composite_to_pylist(col: Column, mask: np.ndarray) -> List[Any]:
    """Decode an ARRAY/MAP column's live rows to python lists/dicts."""
    def decode_elem(typ, d, vocab):
        if typ.is_string:
            code = int(d)
            return (vocab[code] if vocab and 0 <= code < len(vocab)
                    else None)
        return typ.from_storage(d)

    valid = np.asarray(jax.device_get(col.validity))[mask]
    if isinstance(col.type, ArrayType):
        values, lengths, elem_valid = (np.asarray(a) for a in col.data)
        values, lengths, elem_valid = values[mask], lengths[mask], elem_valid[mask]
        et = col.type.element
        out: List[Any] = []
        for i, v in enumerate(valid):
            if not v:
                out.append(None)
                continue
            row = []
            for j in range(int(lengths[i])):
                row.append(decode_elem(et, values[i, j], col.dictionary)
                           if elem_valid[i, j] else None)
            out.append(row)
        return out
    # MAP
    keys, values, lengths, val_valid = (np.asarray(a) for a in col.data)
    keys, values = keys[mask], values[mask]
    lengths, val_valid = lengths[mask], val_valid[mask]
    kt, vt = col.type.key, col.type.value
    kd, vd = col.dictionary or (None, None)
    out = []
    for i, v in enumerate(valid):
        if not v:
            out.append(None)
            continue
        m = {}
        for j in range(int(lengths[i])):
            k = decode_elem(kt, keys[i, j], kd)
            m[k] = (decode_elem(vt, values[i, j], vd)
                    if val_valid[i, j] else None)
        out.append(m)
    return out


def make_array_column(typ: ArrayType, values: Sequence[Optional[Sequence]],
                      cap: int) -> Column:
    """Build an ARRAY column from python lists (None = NULL row)."""
    et = typ.element
    max_len = max([len(v) for v in values if v is not None] + [1])
    data = np.zeros((cap, max_len), dtype=np.dtype(et.storage_dtype))
    lengths = np.zeros(cap, dtype=np.int32)
    elem_valid = np.zeros((cap, max_len), dtype=bool)
    row_valid = np.zeros(cap, dtype=bool)
    vocab: List[str] = []
    lookup: Dict[str, int] = {}
    for i, row in enumerate(values):
        if row is None:
            continue
        row_valid[i] = True
        lengths[i] = len(row)
        for j, e in enumerate(row):
            if e is None:
                continue
            elem_valid[i, j] = True
            if et.is_string:
                code = lookup.get(e)
                if code is None:
                    code = lookup[e] = len(vocab)
                    vocab.append(e)
                data[i, j] = code
            else:
                data[i, j] = et.to_storage(e)
    return Column(typ, (jnp.asarray(data), jnp.asarray(lengths),
                        jnp.asarray(elem_valid)), jnp.asarray(row_valid),
                  tuple(vocab) if et.is_string else None)


def _concat_array_columns(cols: Sequence[Column], cap: int) -> Column:
    """Concatenate ARRAY columns along rows, padding widths to the max."""
    typ = cols[0].type
    max_len = max(c.data[0].shape[1] for c in cols)
    if typ.element.is_string:
        vocab, remaps = unify_dictionaries(cols)
        dictionary: Optional[Tuple[str, ...]] = vocab
    else:
        vocab, remaps, dictionary = None, None, None
    vals, lens, evs, rvs = [], [], [], []
    for ci, c in enumerate(cols):
        v, ln, ev = c.data
        pad = max_len - v.shape[1]
        if pad:
            v = jnp.pad(v, ((0, 0), (0, pad)))
            ev = jnp.pad(ev, ((0, 0), (0, pad)))
        if remaps is not None:
            table = jnp.asarray(remaps[ci])
            idx = jnp.where(v >= 0, v, len(remaps[ci]) - 1)
            v = jnp.take(table, idx, axis=0)
        vals.append(v)
        lens.append(ln)
        evs.append(ev)
        rvs.append(c.validity)
    def cat_pad(parts, width=None):
        out = jnp.concatenate(parts)
        pad = cap - out.shape[0]
        if pad > 0:
            padding = ((0, pad),) + ((0, 0),) * (out.ndim - 1)
            out = jnp.pad(out, padding)
        return out
    return Column(typ, (cat_pad(vals), cat_pad(lens), cat_pad(evs)),
                  cat_pad(rvs), dictionary)


def unify_dictionaries(columns: Sequence[Column]) -> Tuple[Tuple[str, ...], List[np.ndarray]]:
    """Merge per-column vocabularies; return (vocab, remap arrays per column).

    remap[i] maps old codes of columns[i] to codes in the unified vocab; -1
    stays -1 via the sentinel slot appended at the end.
    """
    vocab: List[str] = []
    lookup: Dict[str, int] = {}
    remaps: List[np.ndarray] = []
    for col in columns:
        src = col.dictionary or ()
        remap = np.full(len(src) + 1, -1, dtype=np.int32)  # last slot: -1 sentinel
        for old_code, s in enumerate(src):
            code = lookup.get(s)
            if code is None:
                code = lookup[s] = len(vocab)
                vocab.append(s)
            remap[old_code] = code
        remaps.append(remap)
    return tuple(vocab), remaps


def apply_remap_np(codes: np.ndarray, remap: np.ndarray) -> np.ndarray:
    """Host-side dictionary code remap (-1 maps through the sentinel)."""
    idx = np.where(codes >= 0, codes, len(remap) - 1)
    return remap[idx]


def vocab_column(vocab: Optional[Tuple[str, ...]]) -> Column:
    """Dummy 1-slot column carrying only a vocabulary — lets host code
    reuse unify_dictionaries without real data."""
    from .types import VARCHAR
    return Column(VARCHAR, jnp.zeros(1, dtype=jnp.int32),
                  jnp.zeros(1, dtype=bool), vocab)


def remap_codes(col: Column, remap: np.ndarray, vocab: Tuple[str, ...]) -> Column:
    """Apply a dictionary remap on device (gather)."""
    table = jnp.asarray(remap)
    # codes may be -1 (null padding): index the appended sentinel slot
    idx = jnp.where(col.data >= 0, col.data, len(remap) - 1)
    return Column(col.type, jnp.take(table, idx, axis=0), col.validity, vocab)


def concat_batches(batches: Sequence[Batch], capacity: Optional[int] = None) -> Batch:
    """Concatenate batches of identical schema (host orchestration op)."""
    assert batches, "concat of zero batches"
    schema = batches[0].schema
    total_cap = sum(b.capacity for b in batches)
    cap = capacity or bucket_capacity(total_cap)
    ncols = len(schema)
    out_cols = []
    for i in range(ncols):
        cols = [b.columns[i] for b in batches]
        typ = cols[0].type
        if isinstance(typ, ArrayType):
            out_cols.append(_concat_array_columns(cols, cap))
            continue
        if isinstance(typ, MapType):
            raise NotImplementedError("concat of MAP columns")
        if typ.is_string:
            vocab, remaps = unify_dictionaries(cols)
            cols = [remap_codes(c, r, vocab) for c, r in zip(cols, remaps)]
            dictionary = vocab
        else:
            dictionary = None
        data = jnp.concatenate([c.data for c in cols])
        validity = jnp.concatenate([c.validity for c in cols])
        pad = cap - data.shape[0]
        if pad > 0:
            # pad only the row axis: vector-state columns (HLL registers)
            # carry a trailing width dimension
            data = jnp.pad(data,
                           ((0, pad),) + ((0, 0),) * (data.ndim - 1))
            validity = jnp.pad(validity, (0, pad))
        elif pad < 0:
            raise ValueError("concat capacity too small")
        out_cols.append(Column(typ, data, validity, dictionary))
    mask = jnp.concatenate([b.row_mask for b in batches])
    if cap - mask.shape[0] > 0:
        mask = jnp.pad(mask, (0, cap - mask.shape[0]))
    return Batch(schema, out_cols, mask)

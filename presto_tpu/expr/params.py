"""Execution-time bindings for plan-template parameters (ir.Param).

A parameter-generic plan (serving/template.py) carries ``ir.Param``
nodes where the statement had literals. The three scopes here keep the
value out of every compile key while still delivering it to the kernel:

- the **binding scope** (:func:`bound`) is set per query around plan
  execution with the query's slot->value map. It is a contextvar, so
  the exchange driver threads (which copy their spawn context) and the
  main drain loop both see it, and two concurrent queries sharing one
  cached plan keep their own bindings.
- the **trace scope** (:func:`trace_scope`) is set by the expression
  compiler INSIDE the jitted function, mapping each slot to the traced
  scalar the kernel received as an argument. ``eval_expr`` reads it
  when it meets a Param. Evaluating a Param outside any trace scope is
  a hard error — a silently-stale build-time value must never leak
  into results.
- the **guard scope** (:func:`recording_guards`) is active only while
  the PLANNER builds a template. An optimizer site that bakes a
  parameter's value into the plan (scan-pushdown bounds — which seed
  key-bounds gates and stats downstream) must go through
  :func:`consult`, which records an equality guard; a later binding
  that flips the guard makes the template unusable for it and falls
  back to a per-binding fingerprint (serving/template.py).
"""
from __future__ import annotations

import contextlib
import contextvars
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp

from . import ir

#: per-query slot -> python-domain value
_BINDINGS: contextvars.ContextVar[Optional[Dict[int, Any]]] = \
    contextvars.ContextVar("param_bindings", default=None)
#: per-trace slot -> traced scalar (storage domain)
_TRACE: contextvars.ContextVar[Optional[Dict[int, Any]]] = \
    contextvars.ContextVar("param_trace", default=None)
#: planner-side guard recorder: list of (slot, python value)
_GUARDS: contextvars.ContextVar[Optional[List[Tuple[int, Any]]]] = \
    contextvars.ContextVar("param_guards", default=None)


@contextlib.contextmanager
def bound(bindings: Optional[Dict[int, Any]]):
    """Query-scope binding map; no-op when ``bindings`` is None."""
    if bindings is None:
        yield
        return
    token = _BINDINGS.set(dict(bindings))
    try:
        yield
    finally:
        _BINDINGS.reset(token)


@contextlib.contextmanager
def trace_scope(slot_vals: Dict[int, Any]):
    token = _TRACE.set(slot_vals)
    try:
        yield
    finally:
        _TRACE.reset(token)


@contextlib.contextmanager
def recording_guards():
    guards: List[Tuple[int, Any]] = []
    token = _GUARDS.set(guards)
    try:
        yield guards
    finally:
        _GUARDS.reset(token)


def consult(p: ir.Param) -> Any:
    """Planner-only read of a Param's build-time value. Records an
    equality guard when a template build is recording: the produced
    plan is only reusable for bindings that repeat this value."""
    guards = _GUARDS.get()
    if guards is not None:
        guards.append((p.slot, p.bound))
    return p.bound


def collect_params(exprs: Sequence[object]) -> List[ir.Param]:
    """Every distinct Param slot in the given IR trees, slot-ordered."""
    by_slot: Dict[int, ir.Param] = {}

    def walk(e):
        if isinstance(e, ir.Param):
            by_slot.setdefault(e.slot, e)
        for c in getattr(e, "children", lambda: ())():
            walk(c)

    for e in exprs:
        if e is not None:
            walk(e)
    return [by_slot[s] for s in sorted(by_slot)]


def current_args(slots: Sequence[ir.Param]) -> Tuple[Any, ...]:
    """The live binding for each slot as device scalars in storage
    domain — the extra jit operands of a parameterized kernel. Values
    come from the active binding scope; running a parameterized plan
    without one is a programming error (the template path always binds)."""
    bindings = _BINDINGS.get()
    if bindings is None:
        raise RuntimeError(
            "parameterized plan executed outside a binding scope "
            "(serving/template.py must supply Session.param_bindings)")
    out = []
    for p in slots:
        if p.slot not in bindings:
            raise RuntimeError(f"no binding for parameter slot {p.slot}")
        storage = p.type.to_storage(bindings[p.slot])
        out.append(jnp.asarray(storage, dtype=p.type.storage_dtype))
    return tuple(out)


def traced_val(p: ir.Param, n: int):
    """Val for a Param during kernel tracing: the traced scalar from the
    active trace scope broadcast to the batch capacity. Never NULL —
    the parameterizer only hole-punches non-null literals."""
    from .functions import Val
    trace = _TRACE.get()
    if trace is None or p.slot not in trace:
        raise RuntimeError(
            f"parameter slot {p.slot} evaluated outside a trace scope "
            "(kernels over parameterized expressions must pass param "
            "operands — expr/compiler.ExprCompiler does)")
    scalar = trace[p.slot]
    return Val(jnp.broadcast_to(scalar, (n,)),
               jnp.ones(n, dtype=bool), p.type)


def has_params(obj) -> bool:
    """True when a plan (or any dataclass tree) contains an ir.Param —
    the gate for paths that must materialize bindings first (remote
    fragments, mesh SPMD programs, fused join chains)."""
    import dataclasses as _dc
    seen = set()

    def walk(n) -> bool:
        if isinstance(n, ir.Param):
            return True
        if id(n) in seen:
            return False
        if _dc.is_dataclass(n) and not isinstance(n, type):
            seen.add(id(n))
            return any(walk(getattr(n, f.name))
                       for f in _dc.fields(n))
        if isinstance(n, (tuple, list)):
            return any(walk(x) for x in n)
        return False

    return walk(obj)


def bind_plan(plan, bindings: Dict[int, Any]):
    """Materialize a parameterized plan for substrates that trace
    values as constants (cluster fragments shipped over the codec, the
    SPMD mesh executor): every ir.Param becomes an ir.Literal of the
    query's binding. Returns a structurally-shared rebuild; the cached
    template is never mutated."""
    import dataclasses as _dc

    def walk(n):
        if isinstance(n, ir.Param):
            return ir.Literal(type=n.type, value=bindings[n.slot])
        if _dc.is_dataclass(n) and not isinstance(n, type):
            changes = {}
            for f in _dc.fields(n):
                v = getattr(n, f.name)
                nv = walk(v)
                if nv is not v:
                    changes[f.name] = nv
            return _dc.replace(n, **changes) if changes else n
        if isinstance(n, tuple):
            out = tuple(walk(x) for x in n)
            return out if any(a is not b for a, b in zip(out, n)) else n
        if isinstance(n, list):
            out = [walk(x) for x in n]
            return out if any(a is not b
                              for a, b in zip(out, n)) else n
        return n

    return walk(plan)

"""ARRAY scalar functions over the padded dense device representation.

The TPU re-design of Presto's array function surface (reference
presto-main/.../operator/scalar/ArrayFunctions + the ~45 Array* classes,
spi/block/ArrayBlock.java): an array Val's ``data`` is the tuple
(values[cap, L], lengths[cap] int32, elem_valid[cap, L] bool) — every
operation below is a static-shape vectorized 2D kernel (no offsets
indirection, no per-row loops). Higher-order functions (transform/filter/
reduce/…) live in compiler.py because they evaluate lambda IR.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from .. import errors as E
from .. import types as T
from ..types import Type
from .functions import (
    Val, _all_valid, _code_gather, cast_val, flag_err, merge_err, register,
    vocab_table,
)


def arr_parts(v: Val):
    values, lengths, elem_valid = v.data
    return values, lengths, elem_valid


def in_length(values: jnp.ndarray, lengths: jnp.ndarray) -> jnp.ndarray:
    L = values.shape[1]
    return jnp.arange(L)[None, :] < lengths[:, None]


def unify_codes(vals: Sequence[Val]):
    """Remap each string Val's codes onto one merged vocabulary (the
    expression-layer face of batch.unify_dictionaries)."""
    from ..batch import unify_dictionaries, vocab_column
    vocab, remaps = unify_dictionaries(
        [vocab_column(v.dictionary) for v in vals])
    out = [_code_gather(jnp.asarray(r), v.data)
           for v, r in zip(vals, remaps)]
    return vocab, out


@register("array_constructor")
def _array_constructor(args: List[Val], out: Type) -> Val:
    et = out.element
    if not args:
        raise NotImplementedError("empty ARRAY[] literal")
    if et.is_string:
        vocab, codes = unify_codes(args)
        values = jnp.stack(codes, axis=1)
        dictionary: Optional[Tuple[str, ...]] = vocab
    else:
        values = jnp.stack([cast_val(a, et).data for a in args], axis=1)
        dictionary = None
    elem_valid = jnp.stack([a.valid for a in args], axis=1)
    n = values.shape[0]
    lengths = jnp.full(n, len(args), dtype=jnp.int32)
    row_valid = jnp.ones(n, dtype=bool)
    return Val((values, lengths, elem_valid), row_valid, out,
               dictionary=dictionary,
               err=merge_err(*[a.err for a in args]))


@register("cardinality")
def _cardinality(args, out):
    (a,) = args
    if isinstance(a.type, T.MapType):
        lengths = a.data[2]
    else:
        _, lengths, _ = arr_parts(a)
    return Val(lengths.astype(jnp.int64), a.valid, out)


def _gather_element(a: Val, j: jnp.ndarray):
    """values[i, j[i]] + element validity at that slot (j pre-clipped)."""
    values, lengths, elem_valid = arr_parts(a)
    jj = jnp.clip(j, 0, values.shape[1] - 1)[:, None]
    data = jnp.take_along_axis(values, jj, axis=1)[:, 0]
    ev = jnp.take_along_axis(elem_valid, jj, axis=1)[:, 0]
    return data, ev


@register("subscript")
def _subscript(args, out):
    a, i = args
    if isinstance(a.type, T.MapType):
        return _map_lookup(a, i, out, null_on_missing=False)
    values, lengths, _ = arr_parts(a)
    idx = i.data.astype(jnp.int64)
    in_range = (idx >= 1) & (idx <= lengths.astype(jnp.int64))
    data, ev = _gather_element(a, idx - 1)
    both = a.valid & i.valid
    # out-of-bounds subscript is an error (reference ArraySubscriptOperator)
    err = flag_err(both & ~in_range, E.INVALID_FUNCTION_ARGUMENT)
    return Val(data, both & in_range & ev, out, dictionary=a.dictionary,
               err=merge_err(err, a.err, i.err))


@register("element_at")
def _element_at(args, out):
    a, i = args
    if isinstance(a.type, T.MapType):
        return _map_lookup(a, i, out, null_on_missing=True)
    values, lengths, _ = arr_parts(a)
    idx = i.data.astype(jnp.int64)
    ln = lengths.astype(jnp.int64)
    # negative index counts from the end; index 0 raises (reference
    # ElementAtFunction: "SQL array indices start at 1")
    j = jnp.where(idx < 0, ln + idx, idx - 1)
    in_range = (j >= 0) & (j < ln)
    data, ev = _gather_element(a, j)
    err = flag_err(a.valid & i.valid & (idx == 0),
                   E.INVALID_FUNCTION_ARGUMENT)
    return Val(data, a.valid & i.valid & in_range & ev, out,
               dictionary=a.dictionary, err=merge_err(err, a.err, i.err))


def _elem_compare_eq(a: Val, x: Val):
    """values[i, j] == x[i] with dictionary unification for strings."""
    values, lengths, elem_valid = arr_parts(a)
    if a.type.element.is_string:
        vocab, (acodes_flat, xcodes) = unify_codes(
            [Val(values.reshape(-1), None, T.VARCHAR,
                 dictionary=a.dictionary), x])
        values = acodes_flat.reshape(values.shape)
        xdata = xcodes
    else:
        xdata = cast_val(x, a.type.element).data
    return (values == xdata[:, None]) & elem_valid & in_length(
        values, lengths)


@register("contains")
def _contains(args, out):
    a, x = args
    values, lengths, elem_valid = arr_parts(a)
    hit = _elem_compare_eq(a, x)
    any_hit = jnp.any(hit, axis=1)
    # ANSI 3VL: no match over an array with NULL elements is unknown
    has_null = jnp.any(~elem_valid & in_length(values, lengths), axis=1)
    return Val(any_hit, a.valid & x.valid & (any_hit | ~has_null),
               T.BOOLEAN, err=merge_err(a.err, x.err))


@register("array_position")
def _array_position(args, out):
    a, x = args
    hit = _elem_compare_eq(a, x)
    L = hit.shape[1]
    first = jnp.argmax(hit, axis=1) + 1
    pos = jnp.where(jnp.any(hit, axis=1), first, 0).astype(jnp.int64)
    return Val(pos, a.valid & x.valid, out, err=merge_err(a.err, x.err))


def _rank_tables(vocab):
    from ..ops.sort import rank_codes, unrank_table
    return rank_codes, unrank_table(vocab)


def _array_extreme(is_max):
    def impl(args, out):
        (a,) = args
        values, lengths, elem_valid = arr_parts(a)
        live = elem_valid & in_length(values, lengths)
        unrank = None
        if a.type.element.is_string:
            from ..ops.sort import rank_codes, unrank_table
            values = rank_codes(values, a.dictionary or ()).astype(jnp.int64)
            unrank = unrank_table(a.dictionary or ())
        if jnp.issubdtype(values.dtype, jnp.floating):
            sent = jnp.asarray(-jnp.inf if is_max else jnp.inf,
                               dtype=values.dtype)
        else:
            info = jnp.iinfo(values.dtype)
            sent = jnp.asarray(info.min if is_max else info.max,
                               dtype=values.dtype)
        masked = jnp.where(live, values, sent)
        data = jnp.max(masked, axis=1) if is_max else jnp.min(masked, axis=1)
        any_live = jnp.any(live, axis=1)
        # Presto: NULL if array contains a NULL element
        has_null = jnp.any(~elem_valid & in_length(values, lengths), axis=1)
        if unrank is not None:
            data = jnp.take(unrank, jnp.clip(data, 0, unrank.shape[0] - 1),
                            axis=0)
        return Val(data, a.valid & any_live & ~has_null, out,
                   dictionary=a.dictionary, err=a.err)
    return impl


register("array_max")(_array_extreme(True))
register("array_min")(_array_extreme(False))


@register("array_sort")
def _array_sort(args, out):
    """Ascending, nulls last (reference ArraySortFunction)."""
    (a,) = args
    values, lengths, elem_valid = arr_parts(a)
    inl = in_length(values, lengths)
    svals = values
    unrank = None
    if a.type.element.is_string:
        from ..ops.sort import rank_codes, unrank_table
        svals = rank_codes(values, a.dictionary or ()).astype(jnp.int64)
        unrank = unrank_table(a.dictionary or ())
    # slot class: 0 = value, 1 = null element, 2 = beyond length
    slot = jnp.where(inl & elem_valid, 0, jnp.where(inl, 1, 2))
    neutral = jnp.where(inl & elem_valid, svals, jnp.zeros_like(svals))
    order = jnp.lexsort((neutral, slot), axis=1)
    sorted_vals = jnp.take_along_axis(values, order, axis=1)
    sorted_valid = jnp.take_along_axis(elem_valid & inl, order, axis=1)
    return Val((sorted_vals, lengths, sorted_valid), a.valid, out,
               dictionary=a.dictionary, err=a.err)


@register("array_distinct")
def _array_distinct(args, out):
    """First-occurrence order (reference ArrayDistinctFunction)."""
    (a,) = args
    values, lengths, elem_valid = arr_parts(a)
    inl = in_length(values, lengths)
    live = inl & elem_valid
    nulls = inl & ~elem_valid
    # pairwise O(L^2): dup[i, j] = exists k<j with equal value (or null)
    eq = (values[:, :, None] == values[:, None, :])
    prior = jnp.tril(jnp.ones((values.shape[1],) * 2, dtype=bool), k=-1)
    dup_val = jnp.any(eq & live[:, :, None] & live[:, None, :]
                      & prior[None, :, :], axis=2)
    dup_null = jnp.any(nulls[:, :, None] & nulls[:, None, :]
                       & prior[None, :, :], axis=2)
    keep = inl & ~jnp.where(elem_valid, dup_val, dup_null)
    return _compact_rows(values, elem_valid, keep, a, out)


def _compact_rows(values, elem_valid, keep, a: Val, out: Type) -> Val:
    """Keep flagged elements, preserving order; recompute lengths."""
    L = values.shape[1]
    order = jnp.lexsort((jnp.broadcast_to(jnp.arange(L), values.shape),
                         ~keep), axis=1)
    new_vals = jnp.take_along_axis(values, order, axis=1)
    new_valid = jnp.take_along_axis(elem_valid & keep, order, axis=1)
    new_len = jnp.sum(keep, axis=1).astype(jnp.int32)
    return Val((new_vals, new_len, new_valid), a.valid, out,
               dictionary=a.dictionary, err=a.err)


@register("array_concat")
def _array_concat(args, out):
    if len(args) > 2:
        # variadic: left fold (reference ArrayConcatFunction)
        acc = args[0]
        for nxt in args[1:]:
            acc = _array_concat([acc, nxt], out)
        return acc
    a, b = args
    if a.type.element.is_string:
        av, al, ae = arr_parts(a)
        bv, bl, be = arr_parts(b)
        vocab, (ac, bc) = unify_codes([
            Val(av.reshape(-1), None, T.VARCHAR, dictionary=a.dictionary),
            Val(bv.reshape(-1), None, T.VARCHAR, dictionary=b.dictionary)])
        a = Val((ac.reshape(av.shape), al, ae), a.valid, a.type, vocab)
        b = Val((bc.reshape(bv.shape), bl, be), b.valid, b.type, vocab)
        dictionary: Optional[Tuple[str, ...]] = vocab
    else:
        dictionary = None
    av, al, ae = arr_parts(a)
    bv, bl, be = arr_parts(b)
    La, Lb = av.shape[1], bv.shape[1]
    Lo = La + Lb
    # out[i, j] = a[i, j] if j < len_a else b[i, j - len_a]
    j = jnp.arange(Lo)[None, :]
    from_a = j < al[:, None]
    bj = jnp.clip(j - al[:, None], 0, Lb - 1)
    aj = jnp.clip(j, 0, La - 1)
    a_vals = jnp.take_along_axis(av, aj.astype(jnp.int32), axis=1)
    b_vals = jnp.take_along_axis(bv, bj.astype(jnp.int32), axis=1)
    a_ev = jnp.take_along_axis(ae, aj.astype(jnp.int32), axis=1)
    b_ev = jnp.take_along_axis(be, bj.astype(jnp.int32), axis=1)
    new_len = (al + bl).astype(jnp.int32)
    inl = j < new_len[:, None]
    vals = jnp.where(from_a, a_vals, b_vals)
    ev = jnp.where(from_a, a_ev, b_ev) & inl
    return Val((vals, new_len, ev), a.valid & b.valid, out,
               dictionary=dictionary, err=merge_err(a.err, b.err))


@register("repeat")
def _repeat(args, out):
    x, n = args
    if n.literal is None:
        raise NotImplementedError("repeat() count must be a constant")
    k = max(int(n.literal), 0)
    values = jnp.broadcast_to(x.data[:, None], (x.data.shape[0], max(k, 1)))
    ev = jnp.broadcast_to(x.valid[:, None], values.shape)
    lengths = jnp.full(values.shape[0], k, dtype=jnp.int32)
    return Val((values, lengths, ev), n.valid, out,
               dictionary=x.dictionary, err=merge_err(x.err, n.err))


@register("sequence")
def _sequence(args, out):
    """sequence(a, b[, step]) with constant bounds (static length)."""
    for v in args:
        if v.literal is None:
            raise NotImplementedError("sequence() bounds must be constants")
    start = int(args[0].literal)
    stop = int(args[1].literal)
    step = int(args[2].literal) if len(args) > 2 else (
        1 if stop >= start else -1)
    if step == 0:
        raise E.QueryError(E.INVALID_FUNCTION_ARGUMENT,
                           "sequence step cannot be zero")
    seq = list(range(start, stop + (1 if step > 0 else -1), step))
    n = args[0].data.shape[0]
    k = max(len(seq), 1)
    values = jnp.broadcast_to(
        jnp.asarray(seq or [0], dtype=jnp.int64)[None, :], (n, k))
    lengths = jnp.full(n, len(seq), dtype=jnp.int32)
    ev = jnp.broadcast_to((jnp.arange(k) < len(seq))[None, :], (n, k))
    return Val((values, lengths, ev), _all_valid(args), out)


@register("split")
def _split(args, out):
    """split(s, delim[, limit]): per-vocab-entry parts baked as tables."""
    a, d = args[0], args[1]
    from .functions import _string_literal_of
    delim = _string_literal_of(d)
    if a.dictionary is None or delim is None:
        raise NotImplementedError("split() needs a dictionary column and "
                                  "a constant delimiter")
    limit = None
    if len(args) > 2:
        if args[2].literal is None:
            raise NotImplementedError("split() limit must be a constant")
        limit = int(args[2].literal)
    parts_per = []
    for s in a.dictionary:
        parts = s.split(delim, limit - 1 if limit else -1) if delim else [s]
        parts_per.append(parts)
    L = max([len(p) for p in parts_per] + [1])
    vocab: List[str] = []
    lookup: dict = {}
    val_table = np.zeros((len(a.dictionary) + 1, L), dtype=np.int32)
    len_table = np.zeros(len(a.dictionary) + 1, dtype=np.int32)
    for i, parts in enumerate(parts_per):
        len_table[i] = len(parts)
        for j, p in enumerate(parts):
            code = lookup.get(p)
            if code is None:
                code = lookup[p] = len(vocab)
                vocab.append(p)
            val_table[i, j] = code
    values = _code_gather(jnp.asarray(val_table), a.data)
    lengths = _code_gather(jnp.asarray(len_table), a.data)
    ev = in_length(values, lengths)
    return Val((values, lengths, ev), a.valid, out,
               dictionary=tuple(vocab), err=a.err)


# -- MAP ---------------------------------------------------------------------

@register("map")
def _map_constructor(args, out):
    """map(key_array, value_array) (reference MapConstructor)."""
    karr, varr = args
    kv, kl, ke = arr_parts(karr)
    vv, vl, ve = arr_parts(varr)
    if kv.shape[1] != vv.shape[1]:
        L = max(kv.shape[1], vv.shape[1])
        kv = jnp.pad(kv, ((0, 0), (0, L - kv.shape[1])))
        ke = jnp.pad(ke, ((0, 0), (0, L - ke.shape[1])))
        vv = jnp.pad(vv, ((0, 0), (0, L - vv.shape[1])))
        ve = jnp.pad(ve, ((0, 0), (0, L - ve.shape[1])))
    # equal lengths required; duplicate keys raise (reference
    # MapConstructor "Duplicate map keys are not allowed")
    inl = in_length(kv, kl)
    prior = jnp.tril(jnp.ones((kv.shape[1],) * 2, dtype=bool), k=-1)
    dup_rows = jnp.any((kv[:, :, None] == kv[:, None, :])
                       & inl[:, :, None] & inl[:, None, :]
                       & prior[None, :, :], axis=(1, 2))
    err = flag_err(karr.valid & varr.valid & ((kl != vl) | dup_rows),
                   E.INVALID_FUNCTION_ARGUMENT)
    dictionary = (karr.dictionary, varr.dictionary) \
        if (karr.dictionary or varr.dictionary) else None
    return Val((kv, vv, kl, ve), karr.valid & varr.valid, out,
               dictionary=dictionary,
               err=merge_err(err, karr.err, varr.err))


def _map_lookup(m: Val, k: Val, out: Type, null_on_missing: bool) -> Val:
    keys, values, lengths, val_valid = m.data
    kd, vd = m.dictionary or (None, None)
    if m.type.key.is_string:
        vocab, (kcodes_flat, xcodes) = unify_codes([
            Val(keys.reshape(-1), None, T.VARCHAR, dictionary=kd), k])
        keys = kcodes_flat.reshape(keys.shape)
        xdata = xcodes
    else:
        xdata = cast_val(k, m.type.key).data
    inl = in_length(keys, lengths)
    hit = (keys == xdata[:, None]) & inl
    found = jnp.any(hit, axis=1)
    j = jnp.argmax(hit, axis=1)
    data = jnp.take_along_axis(values, j[:, None], axis=1)[:, 0]
    vv = jnp.take_along_axis(val_valid, j[:, None], axis=1)[:, 0]
    both = m.valid & k.valid
    err = None
    if not null_on_missing:
        # missing key on m[k] raises (reference MapSubscriptOperator)
        err = flag_err(both & ~found, E.INVALID_FUNCTION_ARGUMENT)
    return Val(data, both & found & vv, out, dictionary=vd,
               err=merge_err(err, m.err, k.err))


@register("map_keys")
def _map_keys(args, out):
    (m,) = args
    keys, values, lengths, val_valid = m.data
    kd, _ = m.dictionary or (None, None)
    ev = in_length(keys, lengths)
    return Val((keys, lengths, ev), m.valid, out, dictionary=kd, err=m.err)


@register("map_values")
def _map_values(args, out):
    (m,) = args
    keys, values, lengths, val_valid = m.data
    _, vd = m.dictionary or (None, None)
    ev = in_length(values, lengths) & val_valid
    return Val((values, lengths, ev), m.valid, out, dictionary=vd, err=m.err)

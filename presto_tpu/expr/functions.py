"""Scalar function implementations over (data, validity) column pairs.

The analogue of Presto's FunctionRegistry + operator/scalar/* (reference
presto-main/.../metadata/FunctionRegistry.java:350 and operator/scalar/): each
function is a pure jnp transform over storage arrays plus explicit SQL
three-valued-logic validity handling. String functions operate on dictionary
codes with host-side vocabulary precomputation at trace time — the vocab is
static under jit, so LIKE/substr/comparison tables bake into the compiled
kernel as constants (the TPU answer to Presto's per-invocation Joni regex).

Error semantics (reference spi/StandardErrorCode.java): kernels record a
per-row int32 error code on the Val (``err``; 0/None = ok) instead of
raising — integer/decimal division by zero sets DIVISION_BY_ZERO exactly
like Presto's BigintOperators.divide, while double division follows IEEE
(Infinity/NaN, no error) like DoubleOperators. The compiler propagates the
codes with branch masking (IF/CASE/AND-OR short circuits) and the executor
raises QueryError after the batch is produced; TRY() clears them to NULL.
"""
from __future__ import annotations

import dataclasses
import fnmatch
import math
import re
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from .. import types as T
from .. import errors as E
from ..types import Type


@dataclasses.dataclass
class Val:
    """Evaluation-time column value: storage data + validity (+ vocab)."""

    data: jnp.ndarray
    valid: jnp.ndarray
    type: Type
    dictionary: Optional[Tuple[str, ...]] = None
    #: static python value when this Val is a compile-time constant —
    #: lets string/positional args (substr offsets, LIKE patterns) stay
    #: static under jit, like constant folding in the reference codegen
    literal: Optional[object] = None
    #: per-row int32 error code (0 = ok); None = statically error-free
    err: Optional[jnp.ndarray] = None

    @staticmethod
    def constant(value, typ: Type, n: int) -> "Val":
        if value is None:
            return Val(
                jnp.full(n, typ.null_storage(), dtype=typ.storage_dtype),
                jnp.zeros(n, dtype=bool), typ, literal=None,
            )
        if typ.is_string:
            s = value
            if isinstance(typ, T.CharType):
                s = str(s).ljust(typ.length)
            return Val(
                jnp.zeros(n, dtype=jnp.int32),
                jnp.ones(n, dtype=bool), typ, dictionary=(s,), literal=s,
            )
        storage = typ.to_storage(value)
        return Val(
            jnp.full(n, storage, dtype=typ.storage_dtype),
            jnp.ones(n, dtype=bool), typ, literal=value,
        )


def _all_valid(args: Sequence[Val]) -> jnp.ndarray:
    v = args[0].valid
    for a in args[1:]:
        v = v & a.valid
    return v


def merge_err(*errs: Optional[jnp.ndarray]) -> Optional[jnp.ndarray]:
    """Combine per-row error codes; the max code wins on a row."""
    present = [e for e in errs if e is not None]
    if not present:
        return None
    out = present[0]
    for e in present[1:]:
        out = jnp.maximum(out, e)
    return out


def flag_err(cond: jnp.ndarray, code: int) -> jnp.ndarray:
    return jnp.where(cond, jnp.int32(code), jnp.int32(0))


# -- decimal helpers ---------------------------------------------------------

def rescale_decimal(data: jnp.ndarray, from_scale: int, to_scale: int) -> jnp.ndarray:
    """Rescale int64 decimal storage, rounding half-up away from zero."""
    if to_scale == from_scale:
        return data
    if to_scale > from_scale:
        return data * (10 ** (to_scale - from_scale))
    div = 10 ** (from_scale - to_scale)
    half = div // 2
    sign = jnp.sign(data)
    return sign * ((jnp.abs(data) + half) // div)


def _unify_numeric(a: Val, b: Val) -> Tuple[Val, Val, Type]:
    """Coerce two numeric Vals to a common type (planner usually pre-casts;
    this is the defensive fallback)."""
    t = T.common_super_type(a.type, b.type)
    if t is None:
        raise TypeError(f"cannot unify {a.type} and {b.type}")
    return cast_val(a, t), cast_val(b, t), t


def cast_val(v: Val, to: Type) -> Val:
    """CAST implementation (reference operator/scalar casts per type)."""
    f = v.type
    if f == to:
        return v
    data = v.data
    if isinstance(f, T.DecimalType) and isinstance(to, T.DecimalType):
        return Val(rescale_decimal(data, f.scale, to.scale), v.valid, to)
    if isinstance(to, T.DoubleType) or isinstance(to, T.RealType):
        if isinstance(f, T.DecimalType):
            out = data.astype(to.storage_dtype) / (10.0 ** f.scale)
        else:
            out = data.astype(to.storage_dtype)
        return Val(out, v.valid, to)
    if isinstance(to, T.DecimalType):
        if T.is_integral(f):
            return Val(data.astype(jnp.int64) * (10 ** to.scale), v.valid, to)
        if T.is_floating(f):
            scaled = data * (10.0 ** to.scale)
            out = jnp.sign(scaled) * jnp.floor(jnp.abs(scaled) + 0.5)
            return Val(out.astype(jnp.int64), v.valid, to)
    if T.is_integral(to) or isinstance(to, T.BigintType):
        if T.is_floating(f):
            # Presto DoubleOperators.castToLong: Math.round = half-up
            out = jnp.floor(data + 0.5).astype(to.storage_dtype)
            return Val(out, v.valid, to)
        if isinstance(f, T.DecimalType):
            return Val(
                rescale_decimal(data, f.scale, 0).astype(to.storage_dtype),
                v.valid, to,
            )
        if T.is_integral(f) or isinstance(f, T.BooleanType):
            return Val(data.astype(to.storage_dtype), v.valid, to)
    if isinstance(to, T.BooleanType) and T.is_numeric(f):
        return Val(data != 0, v.valid, to)
    if isinstance(to, T.VarcharType) and f.is_string:
        return Val(data, v.valid, to, v.dictionary)
    if isinstance(to, T.TimestampType) and isinstance(f, T.DateType):
        return Val(data.astype(jnp.int64) * 86_400_000_000, v.valid, to)
    if isinstance(to, T.DateType) and isinstance(f, T.TimestampType):
        return Val((data // 86_400_000_000).astype(jnp.int32), v.valid, to)
    raise NotImplementedError(f"cast {f.display()} -> {to.display()}")


# -- date math (branch-free civil calendar, VPU-friendly) --------------------

def _civil_from_days(days: jnp.ndarray):
    """days since 1970-01-01 -> (year, month, day). Howard Hinnant's
    branch-free algorithm, exact for the whole int32 range."""
    z = days.astype(jnp.int64) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097                                # [0, 146096]
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)       # [0, 365]
    mp = (5 * doy + 2) // 153                             # [0, 11]
    d = doy - (153 * mp + 2) // 5 + 1                     # [1, 31]
    m = jnp.where(mp < 10, mp + 3, mp - 9)                # [1, 12]
    year = jnp.where(m <= 2, y + 1, y)
    return year, m, d


def _days_from_civil(y: jnp.ndarray, m: jnp.ndarray, d: jnp.ndarray):
    y = y.astype(jnp.int64)
    yy = jnp.where(m <= 2, y - 1, y)
    era = jnp.floor_divide(yy, 400)
    yoe = yy - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = 365 * yoe + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


# -- string helpers (host-side over static vocab) ----------------------------

def _like_to_regex(pattern: str, escape: Optional[str] = None) -> str:
    out = []
    i = 0
    esc = escape
    while i < len(pattern):
        c = pattern[i]
        if esc is not None and c == esc and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(re.escape(c))
        i += 1
    return "".join(out)


def vocab_table(vocab: Tuple[str, ...], fn: Callable[[str], object], dtype) -> jnp.ndarray:
    """Evaluate a host predicate/transform over the vocab -> device table.
    Appends a slot for the -1 (null) code at the end."""
    vals = [fn(s) for s in vocab]
    vals.append(fn("") if dtype != np.bool_ else False)
    return jnp.asarray(np.asarray(vals, dtype=dtype))


def _code_gather(table: jnp.ndarray, codes: jnp.ndarray) -> jnp.ndarray:
    idx = jnp.where(codes >= 0, codes, table.shape[0] - 1)
    return jnp.take(table, idx, axis=0)


def _string_literal_of(v: Val) -> Optional[str]:
    if v.dictionary is not None and len(v.dictionary) == 1 and v.data.ndim >= 1:
        # constant produced by Val.constant
        return v.dictionary[0]
    return None


def _str_padded(v: Val, s: str) -> str:
    return s.ljust(v.type.length) if isinstance(v.type, T.CharType) else s


def _string_compare(a: Val, b: Val, op: str) -> Val:
    """Comparison on dictionary-coded strings."""
    lit_b = _string_literal_of(b)
    lit_a = _string_literal_of(a)
    valid = a.valid & b.valid
    if a.dictionary is not None and lit_b is not None:
        target = _str_padded(a, lit_b)
        if op in ("eq", "ne"):
            code = a.dictionary.index(target) if target in a.dictionary else -2
            d = a.data == code
            return Val(d if op == "eq" else ~d, valid, T.BOOLEAN)
        table = vocab_table(
            a.dictionary,
            {"lt": lambda s: s < target, "le": lambda s: s <= target,
             "gt": lambda s: s > target, "ge": lambda s: s >= target}[op],
            np.bool_,
        )
        return Val(_code_gather(table, a.data), valid, T.BOOLEAN)
    if lit_a is not None and b.dictionary is not None:
        flipped = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le",
                   "eq": "eq", "ne": "ne"}[op]
        return _string_compare(b, a, flipped)
    if a.dictionary is not None and b.dictionary is not None:
        if a.dictionary == b.dictionary:
            if op in ("eq", "ne"):
                d = a.data == b.data
                return Val(d if op == "eq" else ~d, valid, T.BOOLEAN)
            rank = vocab_table(
                a.dictionary,
                lambda s, order=sorted(a.dictionary): order.index(s),
                np.int32,
            )
            ra, rb = _code_gather(rank, a.data), _code_gather(rank, b.data)
            d = {"lt": ra < rb, "le": ra <= rb, "gt": ra > rb, "ge": ra >= rb}[op]
            return Val(d, valid, T.BOOLEAN)
        # different vocabularies: build a shared ordering at trace time
        merged = sorted(set(a.dictionary) | set(b.dictionary))
        order = {s: i for i, s in enumerate(merged)}
        ta = vocab_table(a.dictionary, lambda s: order[s], np.int64)
        tb = vocab_table(b.dictionary, lambda s: order[s], np.int64)
        ra, rb = _code_gather(ta, a.data), _code_gather(tb, b.data)
        d = {"eq": ra == rb, "ne": ra != rb, "lt": ra < rb,
             "le": ra <= rb, "gt": ra > rb, "ge": ra >= rb}[op]
        return Val(d, valid, T.BOOLEAN)
    raise NotImplementedError("string comparison without dictionaries")


# -- function registry -------------------------------------------------------

FunctionImpl = Callable[[List[Val], Type], Val]
_REGISTRY: Dict[str, FunctionImpl] = {}


def register(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn
    return deco


def lookup(name: str) -> FunctionImpl:
    if name not in _REGISTRY:
        raise KeyError(f"unknown function {name!r}")
    return _REGISTRY[name]


def _arith(op):
    def impl(args: List[Val], out: Type) -> Val:
        a, b = args
        valid = a.valid & b.valid
        if isinstance(out, T.DecimalType):
            s_out = out.scale
            sa = a.type.scale if isinstance(a.type, T.DecimalType) else 0
            sb = b.type.scale if isinstance(b.type, T.DecimalType) else 0
            da = a.data.astype(jnp.int64)
            db = b.data.astype(jnp.int64)
            if op == "mul":
                data = rescale_decimal(da * db, sa + sb, s_out)
            elif op == "div":
                # scale numerator to s_out + sb, integer divide, round half-up
                num = rescale_decimal(da, sa, s_out + sb)
                den = jnp.where(db == 0, 1, db)
                q = num / den
                data = (jnp.sign(q) * jnp.floor(jnp.abs(num) / jnp.abs(den) + 0.5)).astype(jnp.int64)
                err = flag_err(valid & (db == 0), E.DIVISION_BY_ZERO)
                valid = valid & (db != 0)
                return Val(data, valid, out, err=err)
            elif op == "mod":
                sc = max(sa, sb)
                da2, db2 = rescale_decimal(da, sa, sc), rescale_decimal(db, sb, sc)
                den = jnp.where(db2 == 0, 1, db2)
                data = jnp.sign(da2) * (jnp.abs(da2) % jnp.abs(den))
                err = flag_err(valid & (db2 == 0), E.DIVISION_BY_ZERO)
                valid = valid & (db2 != 0)
                return Val(data, valid, out, err=err)
            else:
                sc = s_out
                da2, db2 = rescale_decimal(da, sa, sc), rescale_decimal(db, sb, sc)
                data = da2 + db2 if op == "add" else da2 - db2
            return Val(data, valid, out)
        a2, b2 = cast_val(a, out), cast_val(b, out)
        da, db = a2.data, b2.data
        if op == "add":
            data = da + db
        elif op == "sub":
            data = da - db
        elif op == "mul":
            data = da * db
        elif op == "div":
            if T.is_integral(out):
                den = jnp.where(db == 0, 1, db)
                # SQL integer division truncates toward zero
                data = (jnp.sign(da) * jnp.sign(den)) * (jnp.abs(da) // jnp.abs(den))
                err = flag_err(valid & (db == 0), E.DIVISION_BY_ZERO)
                valid = valid & (db != 0)
                return Val(data, valid, out, err=err)
            # double/real: IEEE semantics like Java (DoubleOperators.divide):
            # x/0 = ±Infinity, 0/0 = NaN — no error, no NULL
            data = da / db
        elif op == "mod":
            if T.is_integral(out):
                den = jnp.where(db == 0, 1, db)
                data = jnp.sign(da) * (jnp.abs(da) % jnp.abs(den))
                err = flag_err(valid & (db == 0), E.DIVISION_BY_ZERO)
                valid = valid & (db != 0)
                return Val(data, valid, out, err=err)
            # double % 0 = NaN (Java remainder semantics)
            den = jnp.where(db == 0.0, jnp.nan, db)
            data = jnp.sign(da) * (jnp.abs(da) % jnp.abs(den))
        else:
            raise AssertionError(op)
        return Val(data, valid, out)
    return impl


for _name, _op in [("add", "add"), ("subtract", "sub"), ("multiply", "mul"),
                   ("divide", "div"), ("modulus", "mod")]:
    register(_name)(_arith(_op))


@register("negate")
def _negate(args, out):
    (a,) = args
    return Val(-a.data, a.valid, out)


def _cmp(op):
    def impl(args: List[Val], out: Type) -> Val:
        a, b = args
        if a.type.is_string or b.type.is_string:
            return _string_compare(a, b, op)
        if a.type != b.type:
            a, b, _ = _unify_numeric(a, b)
        valid = a.valid & b.valid
        da, db = a.data, b.data
        data = {"eq": da == db, "ne": da != db, "lt": da < db,
                "le": da <= db, "gt": da > db, "ge": da >= db}[op]
        return Val(data, valid, T.BOOLEAN)
    return impl


for _name in ["eq", "ne", "lt", "le", "gt", "ge"]:
    register(_name)(_cmp(_name))


@register("not")
def _not(args, out):
    (a,) = args
    return Val(~a.data, a.valid, T.BOOLEAN)


@register("abs")
def _abs(args, out):
    (a,) = args
    return Val(jnp.abs(a.data), a.valid, out)


def _dbl_fn(fn):
    def impl(args, out):
        (a,) = args
        a = cast_val(a, T.DOUBLE)
        return Val(fn(a.data), a.valid, out)
    return impl


register("sqrt")(_dbl_fn(jnp.sqrt))
register("ln")(_dbl_fn(jnp.log))
register("exp")(_dbl_fn(jnp.exp))


@register("floor")
def _floor(args, out):
    (a,) = args
    if isinstance(a.type, T.DecimalType):
        div = 10 ** a.type.scale
        return Val(jnp.floor_divide(a.data, div) * div, a.valid, out)
    if T.is_integral(a.type):
        return Val(a.data, a.valid, out)
    return Val(jnp.floor(a.data), a.valid, out)


@register("ceil")
def _ceil(args, out):
    (a,) = args
    if isinstance(a.type, T.DecimalType):
        div = 10 ** a.type.scale
        return Val(-(jnp.floor_divide(-a.data, div)) * div, a.valid, out)
    if T.is_integral(a.type):
        return Val(a.data, a.valid, out)
    return Val(jnp.ceil(a.data), a.valid, out)


@register("round")
def _round(args, out):
    a = args[0]
    digits = 0
    if len(args) > 1:
        # digits must be a compile-time constant (Literal-backed)
        try:
            digits = int(np.asarray(args[1].data)[0])
        except Exception as e:
            raise NotImplementedError("round() with non-constant digits") from e
    if isinstance(a.type, T.DecimalType):
        data = rescale_decimal(a.data, a.type.scale, digits)
        data = rescale_decimal(data, digits, a.type.scale)
        return Val(data, a.valid, out)
    scale = 10.0 ** digits
    x = a.data * scale
    data = jnp.sign(x) * jnp.floor(jnp.abs(x) + 0.5) / scale
    return Val(data, a.valid, out)


@register("power")
def _power(args, out):
    a, b = (cast_val(x, T.DOUBLE) for x in args)
    return Val(jnp.power(a.data, b.data), a.valid & b.valid, out)


# -- datetime ----------------------------------------------------------------

def _date_part(part):
    def impl(args, out):
        (a,) = args
        days = a.data if isinstance(a.type, T.DateType) else a.data // 86_400_000_000
        y, m, d = _civil_from_days(days)
        val = {"year": y, "month": m, "day": d, "quarter": (m + 2) // 3}[part]
        return Val(val.astype(jnp.int64), a.valid, out)
    return impl


for _p in ["year", "month", "day", "quarter"]:
    register(_p)(_date_part(_p))


@register("date_add_days")
def _date_add_days(args, out):
    a, n = args
    return Val(a.data + n.data.astype(a.data.dtype), a.valid & n.valid, out)


@register("date_add_months")
def _date_add_months(args, out):
    a, n = args
    y, m, d = _civil_from_days(a.data)
    months = y * 12 + (m - 1) + n.data.astype(jnp.int64)
    ny, nm = jnp.floor_divide(months, 12), months % 12 + 1
    # clamp day to end of target month
    dim_table = jnp.asarray([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31])
    leap = ((ny % 4 == 0) & (ny % 100 != 0)) | (ny % 400 == 0)
    dim = jnp.take(dim_table, nm - 1) + jnp.where(leap & (nm == 2), 1, 0)
    nd = jnp.minimum(d, dim)
    return Val(_days_from_civil(ny, nm, nd).astype(a.data.dtype), a.valid & n.valid, out)


@register("date_add_years")
def _date_add_years(args, out):
    a, n = args
    months = Val(n.data * 12, n.valid, n.type)
    return _date_add_months([a, months], out)


# -- strings -----------------------------------------------------------------

@register("like")
def _like(args, out):
    a, pat = args[0], args[1]
    pattern = _string_literal_of(pat)
    if pattern is None:
        raise NotImplementedError("LIKE with non-constant pattern")
    escape = None
    if len(args) > 2:
        escape = _string_literal_of(args[2])
    if a.dictionary is None:
        raise NotImplementedError("LIKE on non-dictionary column")
    rx = re.compile(_like_to_regex(pattern, escape), re.DOTALL)
    table = vocab_table(a.dictionary, lambda s: rx.fullmatch(s) is not None, np.bool_)
    return Val(_code_gather(table, a.data), a.valid, T.BOOLEAN)


def _vocab_transform(fn):
    """String->string function: transform the vocab, keep the codes."""
    def impl(args, out):
        a = args[0]
        if a.dictionary is None:
            raise NotImplementedError("string fn on non-dictionary column")
        extra = []
        for x in args[1:]:
            if x.type.is_string:
                extra.append(_string_literal_of(x))
            elif x.literal is not None:
                extra.append(int(x.literal))
            else:
                raise NotImplementedError(
                    "string function positional args must be constants")
        entries = [fn(s, *extra) for s in a.dictionary]
        # dedupe the transformed vocab and remap codes: distinct inputs can
        # map to one output (substr prefixes), and equal strings MUST share
        # one code — grouping/joins compare codes
        lookup: dict = {}
        vocab: list = []
        remap = np.empty(len(entries) + 1, dtype=np.int32)
        for i, s in enumerate(entries):
            code = lookup.get(s)
            if code is None:
                code = lookup[s] = len(vocab)
                vocab.append(s)
            remap[i] = code
        remap[-1] = -1
        if len(vocab) == len(entries):
            return Val(a.data, a.valid, out, dictionary=tuple(entries))
        codes = _code_gather(jnp.asarray(remap), a.data)
        return Val(codes, a.valid, out, dictionary=tuple(vocab))
    return impl


register("lower")(_vocab_transform(lambda s: s.lower()))
register("upper")(_vocab_transform(lambda s: s.upper()))
register("trim")(_vocab_transform(lambda s: s.strip()))
# SQL substr is 1-based
register("substr")(_vocab_transform(
    lambda s, start, length=None: s[start - 1: start - 1 + length]
    if length is not None else s[start - 1:]))


@register("length")
def _length(args, out):
    (a,) = args
    if a.dictionary is None:
        raise NotImplementedError("length on non-dictionary column")
    table = vocab_table(a.dictionary, len, np.int64)
    return Val(_code_gather(table, a.data), a.valid, out)


@register("concat")
def _concat(args, out):
    lits = [_string_literal_of(v) for v in args]
    dyn = [i for i, l in enumerate(lits) if l is None]
    if len(dyn) == 0:
        return Val.constant("".join(lits), out, args[0].data.shape[0])
    if len(dyn) == 1:
        i = dyn[0]
        a = args[i]
        if a.dictionary is None:
            raise NotImplementedError("concat on non-dictionary column")
        prefix = "".join(lits[:i])
        suffix = "".join(lits[i + 1:])
        vocab = tuple(prefix + s + suffix for s in a.dictionary)
        return Val(a.data, jnp.stack([v.valid for v in args]).all(0), out, vocab)
    raise NotImplementedError("concat of multiple non-constant strings")


def infer_call_type(name: str, arg_types: List[Type]) -> Type:
    """Return type inference for scalar calls (used by the analyzer).

    Mirrors the signature-resolution role of FunctionRegistry.resolveFunction
    (reference metadata/FunctionRegistry.java) for the engine's builtins.
    """
    if name in ("eq", "ne", "lt", "le", "gt", "ge", "not", "like"):
        return T.BOOLEAN
    if name in ("add", "subtract", "multiply", "divide", "modulus"):
        a, b = arg_types
        if isinstance(a, T.DecimalType) or isinstance(b, T.DecimalType):
            sa = a.scale if isinstance(a, T.DecimalType) else 0
            pa = a.precision if isinstance(a, T.DecimalType) else 18
            sb = b.scale if isinstance(b, T.DecimalType) else 0
            pb = b.precision if isinstance(b, T.DecimalType) else 18
            if T.is_floating(a) or T.is_floating(b):
                return T.DOUBLE
            if name == "multiply":
                return T.DecimalType(min(18, pa + pb), min(18, sa + sb))
            if name == "divide":
                # Presto: scale = max(s1 + p2 - s2, ...) — simplified:
                return T.DecimalType(18, max(sa, sb, 6))
            s = max(sa, sb)
            p = min(18, max(pa - sa, pb - sb) + s + 1)
            return T.DecimalType(p, s)
        t = T.common_super_type(a, b)
        if t is None:
            raise TypeError(f"{name}({a.display()}, {b.display()})")
        return t
    if name == "negate" or name == "abs":
        return arg_types[0]
    if name in ("sqrt", "ln", "exp", "power"):
        return T.DOUBLE
    if name in ("floor", "ceil", "round"):
        return arg_types[0]
    if name in ("year", "month", "day", "quarter"):
        return T.BIGINT
    if name in ("date_add_days", "date_add_months", "date_add_years"):
        return arg_types[0]
    if name in ("lower", "upper", "trim", "substr", "concat"):
        return T.VARCHAR
    if name == "length":
        return T.BIGINT
    raise KeyError(f"unknown function {name!r}")

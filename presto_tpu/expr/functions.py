"""Scalar function implementations over (data, validity) column pairs.

The analogue of Presto's FunctionRegistry + operator/scalar/* (reference
presto-main/.../metadata/FunctionRegistry.java:350 and operator/scalar/): each
function is a pure jnp transform over storage arrays plus explicit SQL
three-valued-logic validity handling. String functions operate on dictionary
codes with host-side vocabulary precomputation at trace time — the vocab is
static under jit, so LIKE/substr/comparison tables bake into the compiled
kernel as constants (the TPU answer to Presto's per-invocation Joni regex).

Error semantics (reference spi/StandardErrorCode.java): kernels record a
per-row int32 error code on the Val (``err``; 0/None = ok) instead of
raising — integer/decimal division by zero sets DIVISION_BY_ZERO exactly
like Presto's BigintOperators.divide, while double division follows IEEE
(Infinity/NaN, no error) like DoubleOperators. The compiler propagates the
codes with branch masking (IF/CASE/AND-OR short circuits) and the executor
raises QueryError after the batch is produced; TRY() clears them to NULL.
"""
from __future__ import annotations

import dataclasses
import fnmatch
import math
import re
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from .. import types as T
from .. import errors as E
from ..types import Type


@dataclasses.dataclass
class Val:
    """Evaluation-time column value: storage data + validity (+ vocab)."""

    data: jnp.ndarray
    valid: jnp.ndarray
    type: Type
    dictionary: Optional[Tuple[str, ...]] = None
    #: static python value when this Val is a compile-time constant —
    #: lets string/positional args (substr offsets, LIKE patterns) stay
    #: static under jit, like constant folding in the reference codegen
    literal: Optional[object] = None
    #: per-row int32 error code (0 = ok); None = statically error-free
    err: Optional[jnp.ndarray] = None

    @staticmethod
    def constant(value, typ: Type, n: int) -> "Val":
        if value is None:
            if isinstance(typ, T.ArrayType):
                return Val(
                    (jnp.zeros((n, 1), dtype=typ.storage_dtype),
                     jnp.zeros(n, dtype=jnp.int32),
                     jnp.zeros((n, 1), dtype=bool)),
                    jnp.zeros(n, dtype=bool), typ,
                    dictionary=() if typ.element.is_string else None,
                )
            width = getattr(typ, "storage_width", None)
            shape = (n,) if width is None else (n, width)
            return Val(
                jnp.zeros(shape, dtype=typ.storage_dtype),
                jnp.zeros(n, dtype=bool), typ, literal=None,
            )
        if typ.is_string:
            s = value
            if isinstance(typ, T.CharType):
                s = str(s).ljust(typ.length)
            return Val(
                jnp.zeros(n, dtype=jnp.int32),
                jnp.ones(n, dtype=bool), typ, dictionary=(s,), literal=s,
            )
        storage = typ.to_storage(value)
        if getattr(typ, "storage_width", None):
            data = jnp.tile(
                jnp.asarray(storage, dtype=typ.storage_dtype)[None, :],
                (n, 1))
        else:
            data = jnp.full(n, storage, dtype=typ.storage_dtype)
        return Val(data, jnp.ones(n, dtype=bool), typ, literal=value)


def _all_valid(args: Sequence[Val]) -> jnp.ndarray:
    v = args[0].valid
    for a in args[1:]:
        v = v & a.valid
    return v


def merge_err(*errs: Optional[jnp.ndarray]) -> Optional[jnp.ndarray]:
    """Combine per-row error codes; the max code wins on a row."""
    present = [e for e in errs if e is not None]
    if not present:
        return None
    out = present[0]
    for e in present[1:]:
        out = jnp.maximum(out, e)
    return out


def flag_err(cond: jnp.ndarray, code: int) -> jnp.ndarray:
    return jnp.where(cond, jnp.int32(code), jnp.int32(0))


# -- decimal helpers ---------------------------------------------------------

def _is_long_dec(t) -> bool:
    return isinstance(t, T.DecimalType) and t.is_long


def _dec_limbs(v: Val, to_scale: int):
    """Numeric Val -> ([n, 2] limb tile at to_scale, overflow rows).
    Decimal inputs rescale from their own scale; integrals from 0
    (ops/int128.py; reference UnscaledDecimal128Arithmetic.rescale)."""
    from ..ops import int128 as I
    t = v.type
    if isinstance(t, T.DecimalType):
        x = v.data if t.is_long else I.from_i64(v.data)
        return I.rescale(x, to_scale - t.scale)
    if T.is_integral(t) or isinstance(t, T.BigintType):
        return I.rescale(I.from_i64(v.data.astype(jnp.int64)), to_scale)
    raise NotImplementedError(
        f"cannot take decimal limbs of {t.display()}")


def rescale_decimal(data: jnp.ndarray, from_scale: int, to_scale: int) -> jnp.ndarray:
    """Rescale int64 decimal storage, rounding half-up away from zero."""
    if to_scale == from_scale:
        return data
    if to_scale > from_scale:
        return data * (10 ** (to_scale - from_scale))
    div = 10 ** (from_scale - to_scale)
    half = div // 2
    sign = jnp.sign(data)
    return sign * ((jnp.abs(data) + half) // div)


def _cast_long_decimal(v: Val, to: Type) -> Val:
    """Casts where the source or target is a long decimal (p > 18):
    limb rescales with range checks (reference DecimalCasts.java +
    UnscaledDecimal128Arithmetic). Out-of-range rows error with
    NUMERIC_VALUE_OUT_OF_RANGE like the reference's throw."""
    from ..ops import int128 as I
    f = v.type
    if isinstance(to, T.DecimalType):
        if isinstance(f, T.DecimalType) or T.is_integral(f) \
                or isinstance(f, T.BigintType):
            x, ovf = _dec_limbs(v, to.scale)
        elif T.is_floating(f):
            bound = 10.0 ** (to.precision - to.scale)
            scaled = v.data.astype(jnp.float64) * (10.0 ** to.scale)
            half_up = jnp.sign(scaled) * jnp.floor(jnp.abs(scaled) + 0.5)
            x = I.from_f64(half_up)
            ovf = ~(jnp.abs(v.data.astype(jnp.float64)) < bound)
        else:
            raise NotImplementedError(
                f"cast {f.display()} -> {to.display()}")
        fits = I.fits_decimal(x, to.precision) & ~ovf
        err = flag_err(v.valid & ~fits, E.NUMERIC_VALUE_OUT_OF_RANGE)
        if to.is_long:
            return Val(x, v.valid & fits, to, err=err)
        return Val(I.lo(x), v.valid & fits, to, err=err)
    # source is long decimal
    if isinstance(to, T.DoubleType) or isinstance(to, T.RealType):
        out = (I.to_f64(v.data) / (10.0 ** f.scale)).astype(to.storage_dtype)
        return Val(out, v.valid, to)
    if T.is_integral(to) or isinstance(to, T.BigintType):
        x, _ = I.rescale(v.data, -f.scale)
        fits = I.hi(x) == (I.lo(x) >> 63)       # value fits one limb
        narrow = I.lo(x)
        if not isinstance(to, T.BigintType):
            info = jnp.iinfo(to.storage_dtype)
            fits = fits & (narrow >= info.min) & (narrow <= info.max)
        err = flag_err(v.valid & ~fits, E.NUMERIC_VALUE_OUT_OF_RANGE)
        return Val(narrow.astype(to.storage_dtype), v.valid & fits, to,
                   err=err)
    if isinstance(to, T.BooleanType):
        return Val(~I.is_zero(v.data), v.valid, to)
    raise NotImplementedError(f"cast {f.display()} -> {to.display()}")


def _unify_numeric(a: Val, b: Val) -> Tuple[Val, Val, Type]:
    """Coerce two numeric Vals to a common type (planner usually pre-casts;
    this is the defensive fallback)."""
    t = T.common_super_type(a.type, b.type)
    if t is None:
        raise TypeError(f"cannot unify {a.type} and {b.type}")
    return cast_val(a, t), cast_val(b, t), t


def cast_val(v: Val, to: Type) -> Val:
    """CAST implementation (reference operator/scalar casts per type)."""
    f = v.type
    if f == to:
        return v
    if isinstance(f, T.UnknownType):
        # typed NULL: all-invalid storage of the target type
        n = v.data.shape[0]
        if isinstance(to, T.ArrayType):
            return Val((jnp.zeros((n, 1), dtype=to.storage_dtype),
                        jnp.zeros(n, dtype=jnp.int32),
                        jnp.zeros((n, 1), dtype=bool)),
                       jnp.zeros(n, dtype=bool), to,
                       dictionary=() if to.element.is_string else None,
                       err=v.err)
        return Val(jnp.zeros(n, dtype=to.storage_dtype),
                   jnp.zeros(n, dtype=bool), to,
                   dictionary=() if to.is_string else None, err=v.err)
    data = v.data
    if _is_long_dec(f) or _is_long_dec(to):
        return _cast_long_decimal(v, to)
    if isinstance(f, T.DecimalType) and isinstance(to, T.DecimalType):
        return Val(rescale_decimal(data, f.scale, to.scale), v.valid, to)
    if isinstance(to, T.DoubleType) or isinstance(to, T.RealType):
        if isinstance(f, T.DecimalType):
            out = data.astype(to.storage_dtype) / (10.0 ** f.scale)
        else:
            out = data.astype(to.storage_dtype)
        return Val(out, v.valid, to)
    if isinstance(to, T.DecimalType):
        if T.is_integral(f):
            return Val(data.astype(jnp.int64) * (10 ** to.scale), v.valid, to)
        if T.is_floating(f):
            scaled = data * (10.0 ** to.scale)
            out = jnp.sign(scaled) * jnp.floor(jnp.abs(scaled) + 0.5)
            return Val(out.astype(jnp.int64), v.valid, to)
    if T.is_integral(to) or isinstance(to, T.BigintType):
        if T.is_floating(f):
            # Presto DoubleOperators.castToLong: Math.round = half-up
            out = jnp.floor(data + 0.5).astype(to.storage_dtype)
            return Val(out, v.valid, to)
        if isinstance(f, T.DecimalType):
            return Val(
                rescale_decimal(data, f.scale, 0).astype(to.storage_dtype),
                v.valid, to,
            )
        if T.is_integral(f) or isinstance(f, T.BooleanType):
            return Val(data.astype(to.storage_dtype), v.valid, to)
    if isinstance(to, T.BooleanType) and T.is_numeric(f):
        return Val(data != 0, v.valid, to)
    if isinstance(to, T.VarcharType) and f.is_string \
            and not isinstance(f, T.VarbinaryType):
        return Val(data, v.valid, to, v.dictionary)
    if isinstance(to, T.TimestampType) and isinstance(f, T.DateType):
        return Val(data.astype(jnp.int64) * 86_400_000_000, v.valid, to)
    if isinstance(to, T.DateType) and isinstance(f, T.TimestampType):
        return Val((data // 86_400_000_000).astype(jnp.int32), v.valid, to)
    if isinstance(to, T.DateType) and f.is_string \
            and isinstance(v.dictionary, tuple):
        # dictionary-string -> date: parse each distinct VALUE host-side
        # (the vocabulary is static at trace time), then one device
        # gather maps codes to epoch days. Unparseable values raise the
        # row-error channel like the reference's failing DATE cast
        # (reference operator/scalar/DateTimeFunctions castToDate).
        import datetime as _dt
        from ..errors import INVALID_FUNCTION_ARGUMENT
        days, ok = [], []
        for s in v.dictionary:
            try:
                # lenient y-m-d split like the reference's date parse:
                # '2002-2-01' is a valid DATE literal (unpadded fields)
                y, m, d = (int(p) for p in s.strip().split("-"))
                days.append((_dt.date(y, m, d)
                             - _dt.date(1970, 1, 1)).days)
                ok.append(True)
            except (ValueError, TypeError):
                days.append(0)
                ok.append(False)
        table = jnp.asarray(days + [0], dtype=jnp.int32)
        okt = jnp.asarray(ok + [False])
        codes = jnp.clip(data.astype(jnp.int32), 0, len(days))
        parsed_ok = jnp.take(okt, codes, axis=0)
        err = jnp.where(v.valid & ~parsed_ok,
                        jnp.int32(INVALID_FUNCTION_ARGUMENT),
                        jnp.int32(0))
        return Val(jnp.take(table, codes, axis=0),
                   v.valid & parsed_ok, to,
                   err=merge_err(v.err, err))
    raise NotImplementedError(f"cast {f.display()} -> {to.display()}")


# -- date math (branch-free civil calendar, VPU-friendly) --------------------

def _civil_from_days(days: jnp.ndarray):
    """days since 1970-01-01 -> (year, month, day). Howard Hinnant's
    branch-free algorithm, exact for the whole int32 range."""
    z = days.astype(jnp.int64) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097                                # [0, 146096]
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)       # [0, 365]
    mp = (5 * doy + 2) // 153                             # [0, 11]
    d = doy - (153 * mp + 2) // 5 + 1                     # [1, 31]
    m = jnp.where(mp < 10, mp + 3, mp - 9)                # [1, 12]
    year = jnp.where(m <= 2, y + 1, y)
    return year, m, d


def _days_from_civil(y: jnp.ndarray, m: jnp.ndarray, d: jnp.ndarray):
    y = y.astype(jnp.int64)
    yy = jnp.where(m <= 2, y - 1, y)
    era = jnp.floor_divide(yy, 400)
    yoe = yy - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = 365 * yoe + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


# -- string helpers (host-side over static vocab) ----------------------------

def _like_to_regex(pattern: str, escape: Optional[str] = None) -> str:
    out = []
    i = 0
    esc = escape
    while i < len(pattern):
        c = pattern[i]
        if esc is not None and c == esc and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(re.escape(c))
        i += 1
    return "".join(out)


def vocab_table(vocab: Tuple[str, ...], fn: Callable[[str], object], dtype) -> jnp.ndarray:
    """Evaluate a host predicate/transform over the vocab -> device table.
    Appends a slot for the -1 (null) code at the end."""
    vals = [fn(s) for s in vocab]
    vals.append(fn("") if dtype != np.bool_ else False)
    return jnp.asarray(np.asarray(vals, dtype=dtype))


def _code_gather(table: jnp.ndarray, codes: jnp.ndarray) -> jnp.ndarray:
    idx = jnp.where(codes >= 0, codes, table.shape[0] - 1)
    return jnp.take(table, idx, axis=0)


def _string_literal_of(v: Val) -> Optional[str]:
    if v.dictionary is not None and len(v.dictionary) == 1 and v.data.ndim >= 1:
        # constant produced by Val.constant
        return v.dictionary[0]
    return None


def _str_padded(v: Val, s: str) -> str:
    return s.ljust(v.type.length) if isinstance(v.type, T.CharType) else s


def _string_compare(a: Val, b: Val, op: str) -> Val:
    """Comparison on dictionary-coded strings."""
    lit_b = _string_literal_of(b)
    lit_a = _string_literal_of(a)
    valid = a.valid & b.valid
    if a.dictionary is not None and lit_b is not None:
        target = _str_padded(a, lit_b)
        if op in ("eq", "ne"):
            code = a.dictionary.index(target) if target in a.dictionary else -2
            d = a.data == code
            return Val(d if op == "eq" else ~d, valid, T.BOOLEAN)
        table = vocab_table(
            a.dictionary,
            {"lt": lambda s: s < target, "le": lambda s: s <= target,
             "gt": lambda s: s > target, "ge": lambda s: s >= target}[op],
            np.bool_,
        )
        return Val(_code_gather(table, a.data), valid, T.BOOLEAN)
    if lit_a is not None and b.dictionary is not None:
        flipped = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le",
                   "eq": "eq", "ne": "ne"}[op]
        return _string_compare(b, a, flipped)
    if a.dictionary is not None and b.dictionary is not None:
        if a.dictionary == b.dictionary:
            if op in ("eq", "ne"):
                d = a.data == b.data
                return Val(d if op == "eq" else ~d, valid, T.BOOLEAN)
            rank = vocab_table(
                a.dictionary,
                lambda s, order=sorted(a.dictionary): (
                    order.index(s) if s in order else -1),
                np.int32,
            )
            ra, rb = _code_gather(rank, a.data), _code_gather(rank, b.data)
            d = {"lt": ra < rb, "le": ra <= rb, "gt": ra > rb, "ge": ra >= rb}[op]
            return Val(d, valid, T.BOOLEAN)
        # different vocabularies: build a shared ordering at trace time
        # (the -1 sentinel slot probes with "", which need not be a
        # member — rank -1 compares like nothing real but the slot is
        # masked by validity anyway)
        merged = sorted(set(a.dictionary) | set(b.dictionary))
        order = {s: i for i, s in enumerate(merged)}
        ta = vocab_table(a.dictionary, lambda s: order.get(s, -1),
                         np.int64)
        tb = vocab_table(b.dictionary, lambda s: order.get(s, -1),
                         np.int64)
        ra, rb = _code_gather(ta, a.data), _code_gather(tb, b.data)
        d = {"eq": ra == rb, "ne": ra != rb, "lt": ra < rb,
             "le": ra <= rb, "gt": ra > rb, "ge": ra >= rb}[op]
        return Val(d, valid, T.BOOLEAN)
    raise NotImplementedError("string comparison without dictionaries")


# -- function registry -------------------------------------------------------

FunctionImpl = Callable[[List[Val], Type], Val]
_REGISTRY: Dict[str, FunctionImpl] = {}
#: plugin-provided return-type inference, name -> (arg_types) -> Type
#: (the Plugin.getFunctions surface; reference spi/Plugin.java:33-78 +
#: metadata/FunctionRegistry registration)
_EXTERNAL_SIGNATURES: Dict[str, Callable[[List[Type]], Type]] = {}


def register(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn
    return deco


def register_external(name: str, impl: FunctionImpl,
                      infer: Callable[[List[Type]], Type]) -> None:
    """Register a plugin scalar function: device kernel + return-type
    inference. The kernel receives (args: List[Val], out_type) and must
    be jax-traceable like every builtin."""
    key = name.lower()
    _REGISTRY[key] = impl
    _EXTERNAL_SIGNATURES[key] = infer


def lookup(name: str) -> FunctionImpl:
    if name not in _REGISTRY:
        raise KeyError(f"unknown function {name!r}")
    return _REGISTRY[name]


def _long_decimal_arith(op: str, a: Val, b: Val, out, valid) -> Val:
    """Decimal arithmetic through int128 limb kernels (reference
    DecimalOperators.java long-decimal paths over Int128). add/sub/mul
    are exact with NUMERIC_VALUE_OUT_OF_RANGE on 38-digit overflow;
    division supports divisors whose unscaled value fits 31 bits
    (precision <= 9 — the short-division kernel's bound), which covers
    constants and typical scaled divisors."""
    from ..ops import int128 as I
    s_out = out.scale
    sa = a.type.scale if isinstance(a.type, T.DecimalType) else 0
    sb = b.type.scale if isinstance(b.type, T.DecimalType) else 0
    if op in ("add", "sub"):
        xa, oa = _dec_limbs(a, s_out)
        xb, ob = _dec_limbs(b, s_out)
        res = I.add(xa, xb) if op == "add" else I.sub(xa, xb)
        rhs = xb if op == "add" else I.neg(xb)
        wrap = I.add_overflows(xa, rhs, res)
        fits = I.fits_decimal(res, out.precision) & ~(oa | ob | wrap)
    elif op == "mul":
        xa, oa = _dec_limbs(a, sa)
        xb, ob = _dec_limbs(b, sb)
        prod, om = I.mul(xa, xb)
        res, orr = I.rescale(prod, s_out - (sa + sb))
        fits = I.fits_decimal(res, out.precision) & ~(oa | ob | om | orr)
    elif op == "div":
        # general int128/int128 division (float-estimate + exact
        # correction, ops/int128.py divmod_abs); the base-2^32 short
        # kernel stays for small divisors where it's cheaper
        num, on = _dec_limbs(a, s_out + sb)
        small_type = (isinstance(b.type, T.DecimalType)
                      and not b.type.is_long and b.type.precision <= 9) \
            or (T.is_integral(b.type)
                and not isinstance(b.type, T.BigintType))
        if small_type:
            db = b.data.astype(jnp.int64)
            zero = db == 0
            q = I.div_round_half_up(num, jnp.abs(jnp.where(zero, 1, db)))
            q = I.where(db < 0, I.neg(q), q)
        else:
            den, od = _dec_limbs(b, sb)
            on = on | od
            zero = I.is_zero(den)
            safe = I.where(zero, I.from_i64(
                jnp.ones(num.shape[:-1], dtype=jnp.int64)), den)
            q = I.div_round_half_up_wide(num, safe)
        err = flag_err(valid & zero, E.DIVISION_BY_ZERO)
        fits = I.fits_decimal(q, out.precision) & ~on & ~zero
        err = err | flag_err(valid & ~zero & ~fits,
                             E.NUMERIC_VALUE_OUT_OF_RANGE)
        data = q if out.is_long else I.lo(q)
        return Val(data, valid & fits, out, err=err)
    else:
        raise NotImplementedError(f"long decimal {op} is not supported")
    err = flag_err(valid & ~fits, E.NUMERIC_VALUE_OUT_OF_RANGE)
    data = res if out.is_long else I.lo(res)
    return Val(data, valid & fits, out, err=err)


def _arith(op):
    def impl(args: List[Val], out: Type) -> Val:
        a, b = args
        valid = a.valid & b.valid
        if isinstance(out, T.DecimalType) and (
                out.is_long or _is_long_dec(a.type) or _is_long_dec(b.type)):
            return _long_decimal_arith(op, a, b, out, valid)
        if isinstance(out, T.DecimalType):
            s_out = out.scale
            sa = a.type.scale if isinstance(a.type, T.DecimalType) else 0
            sb = b.type.scale if isinstance(b.type, T.DecimalType) else 0
            da = a.data.astype(jnp.int64)
            db = b.data.astype(jnp.int64)
            if op == "mul":
                data = rescale_decimal(da * db, sa + sb, s_out)
            elif op == "div":
                # scale numerator to s_out + sb, integer divide, round half-up
                num = rescale_decimal(da, sa, s_out + sb)
                den = jnp.where(db == 0, 1, db)
                q = num / den
                data = (jnp.sign(q) * jnp.floor(jnp.abs(num) / jnp.abs(den) + 0.5)).astype(jnp.int64)
                err = flag_err(valid & (db == 0), E.DIVISION_BY_ZERO)
                valid = valid & (db != 0)
                return Val(data, valid, out, err=err)
            elif op == "mod":
                sc = max(sa, sb)
                da2, db2 = rescale_decimal(da, sa, sc), rescale_decimal(db, sb, sc)
                den = jnp.where(db2 == 0, 1, db2)
                data = jnp.sign(da2) * (jnp.abs(da2) % jnp.abs(den))
                err = flag_err(valid & (db2 == 0), E.DIVISION_BY_ZERO)
                valid = valid & (db2 != 0)
                return Val(data, valid, out, err=err)
            else:
                sc = s_out
                da2, db2 = rescale_decimal(da, sa, sc), rescale_decimal(db, sb, sc)
                data = da2 + db2 if op == "add" else da2 - db2
            return Val(data, valid, out)
        a2, b2 = cast_val(a, out), cast_val(b, out)
        da, db = a2.data, b2.data
        if op == "add":
            data = da + db
        elif op == "sub":
            data = da - db
        elif op == "mul":
            data = da * db
        elif op == "div":
            if T.is_integral(out):
                den = jnp.where(db == 0, 1, db)
                # SQL integer division truncates toward zero
                data = (jnp.sign(da) * jnp.sign(den)) * (jnp.abs(da) // jnp.abs(den))
                err = flag_err(valid & (db == 0), E.DIVISION_BY_ZERO)
                valid = valid & (db != 0)
                return Val(data, valid, out, err=err)
            # double/real: IEEE semantics like Java (DoubleOperators.divide):
            # x/0 = ±Infinity, 0/0 = NaN — no error, no NULL
            data = da / db
        elif op == "mod":
            if T.is_integral(out):
                den = jnp.where(db == 0, 1, db)
                data = jnp.sign(da) * (jnp.abs(da) % jnp.abs(den))
                err = flag_err(valid & (db == 0), E.DIVISION_BY_ZERO)
                valid = valid & (db != 0)
                return Val(data, valid, out, err=err)
            # double % 0 = NaN (Java remainder semantics)
            den = jnp.where(db == 0.0, jnp.nan, db)
            data = jnp.sign(da) * (jnp.abs(da) % jnp.abs(den))
        else:
            raise AssertionError(op)
        return Val(data, valid, out)
    return impl


for _name, _op in [("add", "add"), ("subtract", "sub"), ("multiply", "mul"),
                   ("divide", "div"), ("modulus", "mod")]:
    register(_name)(_arith(_op))


@register("negate")
def _negate(args, out):
    (a,) = args
    if _is_long_dec(a.type):
        from ..ops import int128 as I
        return Val(I.neg(a.data), a.valid, out)
    return Val(-a.data, a.valid, out)


def _long_dec_compare(a: Val, b: Val, op: str) -> Val:
    """Compare when either side is a long decimal and both are exact
    numerics: rescale to the wider scale, limb compare. When the
    rescale would exceed 38 digits (extreme scale gap), fall back to
    f64 compare (beyond-38-digit distinctions round away, documented)."""
    from ..ops import int128 as I
    sa = a.type.scale if isinstance(a.type, T.DecimalType) else 0
    sb = b.type.scale if isinstance(b.type, T.DecimalType) else 0
    pa = a.type.precision if isinstance(a.type, T.DecimalType) else 19
    pb = b.type.precision if isinstance(b.type, T.DecimalType) else 19
    s = max(sa, sb)
    valid = a.valid & b.valid
    if max(pa + s - sa, pb + s - sb) > 38:
        fa = cast_val(a, T.DOUBLE).data
        fb = cast_val(b, T.DOUBLE).data
        data = {"eq": fa == fb, "ne": fa != fb, "lt": fa < fb,
                "le": fa <= fb, "gt": fa > fb, "ge": fa >= fb}[op]
        return Val(data, valid, T.BOOLEAN)
    xa, _ = _dec_limbs(a, s)
    xb, _ = _dec_limbs(b, s)
    data = {"eq": I.eq(xa, xb), "ne": ~I.eq(xa, xb),
            "lt": I.lt(xa, xb), "le": I.le(xa, xb),
            "gt": I.lt(xb, xa), "ge": I.le(xb, xa)}[op]
    return Val(data, valid, T.BOOLEAN)


def _cmp(op):
    def impl(args: List[Val], out: Type) -> Val:
        a, b = args
        if a.type.is_string or b.type.is_string:
            return _string_compare(a, b, op)
        if (_is_long_dec(a.type) or _is_long_dec(b.type)) \
                and not (T.is_floating(a.type) or T.is_floating(b.type)):
            return _long_dec_compare(a, b, op)
        if a.type != b.type:
            a, b, _ = _unify_numeric(a, b)
        valid = a.valid & b.valid
        da, db = a.data, b.data
        data = {"eq": da == db, "ne": da != db, "lt": da < db,
                "le": da <= db, "gt": da > db, "ge": da >= db}[op]
        return Val(data, valid, T.BOOLEAN)
    return impl


for _name in ["eq", "ne", "lt", "le", "gt", "ge"]:
    register(_name)(_cmp(_name))


@register("not")
def _not(args, out):
    (a,) = args
    return Val(~a.data, a.valid, T.BOOLEAN)


@register("abs")
def _abs(args, out):
    (a,) = args
    if _is_long_dec(a.type):
        from ..ops import int128 as I
        return Val(I.abs_(a.data), a.valid, out)
    return Val(jnp.abs(a.data), a.valid, out)


def _dbl_fn(fn):
    def impl(args, out):
        (a,) = args
        a = cast_val(a, T.DOUBLE)
        return Val(fn(a.data), a.valid, out)
    return impl


register("sqrt")(_dbl_fn(jnp.sqrt))
register("ln")(_dbl_fn(jnp.log))
register("exp")(_dbl_fn(jnp.exp))


@register("floor")
def _floor(args, out):
    (a,) = args
    if _is_long_dec(a.type):
        return Val(_long_dec_floor_ceil(a, ceil=False), a.valid, out)
    if isinstance(a.type, T.DecimalType):
        div = 10 ** a.type.scale
        return Val(jnp.floor_divide(a.data, div) * div, a.valid, out)
    if T.is_integral(a.type):
        return Val(a.data, a.valid, out)
    return Val(jnp.floor(a.data), a.valid, out)


def _long_dec_floor_ceil(a: Val, ceil: bool) -> jnp.ndarray:
    """Exact floor/ceil to integer multiples of 10**scale for long
    decimals: truncate the fraction digits by digit division, then bump
    toward -inf (floor of negatives) / +inf (ceil of positives) when
    any fraction digit was nonzero."""
    from ..ops import int128 as I
    s = a.type.scale
    m = I.abs_(a.data)
    k = s
    rem_any = jnp.zeros(a.data.shape[:-1], dtype=bool)
    while k > 0:
        step = min(k, 9)
        m, rr = I.divmod_small_abs(m, 10 ** step)
        rem_any = rem_any | (rr != 0)
        k -= step
    neg_in = I.is_neg(a.data)
    bump_rows = rem_any & (neg_in != ceil)   # floor: negatives; ceil: positives
    bump = bump_rows.astype(jnp.int64)
    m = I.add(m, I.pack(jnp.zeros_like(bump), bump))
    signed = I.where(neg_in, I.neg(m), m)
    back, _ = I.rescale(signed, s)
    return back


@register("ceil")
def _ceil(args, out):
    (a,) = args
    if _is_long_dec(a.type):
        return Val(_long_dec_floor_ceil(a, ceil=True), a.valid, out)
    if isinstance(a.type, T.DecimalType):
        div = 10 ** a.type.scale
        return Val(-(jnp.floor_divide(-a.data, div)) * div, a.valid, out)
    if T.is_integral(a.type):
        return Val(a.data, a.valid, out)
    return Val(jnp.ceil(a.data), a.valid, out)


@register("round")
def _round(args, out):
    a = args[0]
    digits = 0
    if len(args) > 1:
        # digits must be a compile-time constant (Literal-backed)
        if args[1].literal is not None:
            digits = int(args[1].literal)
        else:
            try:
                digits = int(np.asarray(args[1].data)[0])
            except Exception as e:
                raise NotImplementedError(
                    "round() with non-constant digits") from e
    if _is_long_dec(a.type):
        if digits >= a.type.scale:
            return Val(a.data, a.valid, out)   # nothing to round away
        from ..ops import int128 as I
        x, _ = I.rescale(a.data, digits - a.type.scale)  # half-up here
        x, _ = I.rescale(x, a.type.scale - digits)
        return Val(x, a.valid, out)
    if isinstance(a.type, T.DecimalType):
        if digits >= a.type.scale:
            return Val(a.data, a.valid, out)   # nothing to round away
        data = rescale_decimal(a.data, a.type.scale, digits)
        data = rescale_decimal(data, digits, a.type.scale)
        return Val(data, a.valid, out)
    scale = 10.0 ** digits
    x = a.data * scale
    data = jnp.sign(x) * jnp.floor(jnp.abs(x) + 0.5) / scale
    return Val(data, a.valid, out)


@register("power")
def _power(args, out):
    a, b = (cast_val(x, T.DOUBLE) for x in args)
    return Val(jnp.power(a.data, b.data), a.valid & b.valid, out)


# -- datetime ----------------------------------------------------------------

def _date_part(part):
    def impl(args, out):
        (a,) = args
        days = a.data if isinstance(a.type, T.DateType) else a.data // 86_400_000_000
        y, m, d = _civil_from_days(days)
        val = {"year": y, "month": m, "day": d, "quarter": (m + 2) // 3}[part]
        return Val(val.astype(jnp.int64), a.valid, out)
    return impl


for _p in ["year", "month", "day", "quarter"]:
    register(_p)(_date_part(_p))


@register("date_add_days")
def _date_add_days(args, out):
    a, n = args
    return Val(a.data + n.data.astype(a.data.dtype), a.valid & n.valid, out)


@register("date_add_months")
def _date_add_months(args, out):
    a, n = args
    y, m, d = _civil_from_days(a.data)
    months = y * 12 + (m - 1) + n.data.astype(jnp.int64)
    ny, nm = jnp.floor_divide(months, 12), months % 12 + 1
    # clamp day to end of target month
    dim_table = jnp.asarray([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31])
    leap = ((ny % 4 == 0) & (ny % 100 != 0)) | (ny % 400 == 0)
    dim = jnp.take(dim_table, nm - 1) + jnp.where(leap & (nm == 2), 1, 0)
    nd = jnp.minimum(d, dim)
    return Val(_days_from_civil(ny, nm, nd).astype(a.data.dtype), a.valid & n.valid, out)


@register("date_add_years")
def _date_add_years(args, out):
    a, n = args
    months = Val(n.data * 12, n.valid, n.type)
    return _date_add_months([a, months], out)


# -- strings -----------------------------------------------------------------

@register("like")
def _like(args, out):
    a, pat = args[0], args[1]
    pattern = _string_literal_of(pat)
    if pattern is None:
        raise NotImplementedError("LIKE with non-constant pattern")
    escape = None
    if len(args) > 2:
        escape = _string_literal_of(args[2])
    if a.dictionary is None:
        raise NotImplementedError("LIKE on non-dictionary column")
    rx = re.compile(_like_to_regex(pattern, escape), re.DOTALL)
    table = vocab_table(a.dictionary, lambda s: rx.fullmatch(s) is not None, np.bool_)
    return Val(_code_gather(table, a.data), a.valid, T.BOOLEAN)


def _vocab_transform(fn):
    """String->string function: transform the vocab, keep the codes."""
    def impl(args, out):
        a = args[0]
        if a.dictionary is None:
            raise NotImplementedError("string fn on non-dictionary column")
        extra = []
        for x in args[1:]:
            if x.type.is_string:
                extra.append(_string_literal_of(x))
            elif x.literal is not None:
                extra.append(int(x.literal))
            else:
                raise NotImplementedError(
                    "string function positional args must be constants")
        entries = [fn(s, *extra) for s in a.dictionary]
        # dedupe the transformed vocab and remap codes: distinct inputs can
        # map to one output (substr prefixes), and equal strings MUST share
        # one code — grouping/joins compare codes
        lookup: dict = {}
        vocab: list = []
        remap = np.empty(len(entries) + 1, dtype=np.int32)
        for i, s in enumerate(entries):
            code = lookup.get(s)
            if code is None:
                code = lookup[s] = len(vocab)
                vocab.append(s)
            remap[i] = code
        remap[-1] = -1
        if len(vocab) == len(entries):
            return Val(a.data, a.valid, out, dictionary=tuple(entries))
        codes = _code_gather(jnp.asarray(remap), a.data)
        return Val(codes, a.valid, out, dictionary=tuple(vocab))
    return impl


register("lower")(_vocab_transform(lambda s: s.lower()))
# varbinary bridge (reference operator/scalar/VarbinaryFunctions.java):
# the dictionary plan carries bytes vocabularies the same way as strings
register("to_utf8")(_vocab_transform(
    lambda s: s.encode("utf-8") if isinstance(s, str) else s))
register("from_utf8")(_vocab_transform(
    lambda s: s.decode("utf-8", "replace")
    if isinstance(s, (bytes, bytearray)) else s))
register("upper")(_vocab_transform(lambda s: s.upper()))
register("trim")(_vocab_transform(lambda s: s.strip()))
# SQL substr is 1-based
register("substr")(_vocab_transform(
    lambda s, start, length=None: s[start - 1: start - 1 + length]
    if length is not None else s[start - 1:]))


@register("length")
def _length(args, out):
    (a,) = args
    if a.dictionary is None:
        raise NotImplementedError("length on non-dictionary column")
    table = vocab_table(a.dictionary, len, np.int64)
    return Val(_code_gather(table, a.data), a.valid, out)


@register("concat")
def _concat(args, out):
    lits = [_string_literal_of(v) for v in args]
    dyn = [i for i, l in enumerate(lits) if l is None]
    if len(dyn) == 0:
        return Val.constant("".join(lits), out, args[0].data.shape[0])
    if len(dyn) == 1:
        i = dyn[0]
        a = args[i]
        if a.dictionary is None:
            raise NotImplementedError("concat on non-dictionary column")
        prefix = "".join(lits[:i])
        suffix = "".join(lits[i + 1:])
        vocab = tuple(prefix + s + suffix for s in a.dictionary)
        return Val(a.data, jnp.stack([v.valid for v in args]).all(0), out, vocab)
    raise NotImplementedError("concat of multiple non-constant strings")


# -- widened math surface (reference operator/scalar/MathFunctions.java) -----

for _name, _jfn in [
        ("sin", jnp.sin), ("cos", jnp.cos), ("tan", jnp.tan),
        ("asin", jnp.arcsin), ("acos", jnp.arccos), ("atan", jnp.arctan),
        ("sinh", jnp.sinh), ("cosh", jnp.cosh), ("tanh", jnp.tanh),
        ("log2", jnp.log2), ("log10", jnp.log10), ("cbrt", jnp.cbrt),
        ("degrees", jnp.degrees), ("radians", jnp.radians)]:
    register(_name)(_dbl_fn(_jfn))


@register("atan2")
def _atan2(args, out):
    a, b = (cast_val(x, T.DOUBLE) for x in args)
    return Val(jnp.arctan2(a.data, b.data), a.valid & b.valid, out)


@register("log")
def _log(args, out):
    # log(b, x): base-b logarithm of x (reference MathFunctions.log)
    b, x = (cast_val(v, T.DOUBLE) for v in args)
    return Val(jnp.log(x.data) / jnp.log(b.data), b.valid & x.valid, out)


@register("sign")
def _sign(args, out):
    (a,) = args
    if _is_long_dec(a.type):
        from ..ops import int128 as I
        return Val(I.sign(a.data).astype(out.storage_dtype), a.valid, out)
    # decimal input: out is decimal(1,0), so the raw -1/0/1 is already
    # correctly scaled; double/bigint keep their type
    return Val(jnp.sign(a.data).astype(out.storage_dtype), a.valid, out)


@register("truncate")
def _truncate(args, out):
    a = cast_val(args[0], T.DOUBLE)
    if len(args) == 1:
        return Val(jnp.trunc(a.data), a.valid, out)
    if args[1].literal is None:
        raise NotImplementedError("truncate() scale must be a constant")
    scale = 10.0 ** int(args[1].literal)
    return Val(jnp.trunc(a.data * scale) / scale, _all_valid(args), out)


@register("width_bucket")
def _width_bucket(args, out):
    x, lo, hi, n = (cast_val(v, T.DOUBLE) for v in args)
    frac = (x.data - lo.data) / (hi.data - lo.data)
    b = jnp.floor(frac * n.data).astype(jnp.int64) + 1
    b = jnp.clip(b, 0, n.data.astype(jnp.int64) + 1)
    return Val(b, _all_valid(args), out)


@register("is_nan")
def _is_nan(args, out):
    a = cast_val(args[0], T.DOUBLE)
    return Val(jnp.isnan(a.data), a.valid, T.BOOLEAN)


@register("is_finite")
def _is_finite(args, out):
    a = cast_val(args[0], T.DOUBLE)
    return Val(jnp.isfinite(a.data), a.valid, T.BOOLEAN)


@register("is_infinite")
def _is_infinite(args, out):
    a = cast_val(args[0], T.DOUBLE)
    return Val(jnp.isinf(a.data), a.valid, T.BOOLEAN)


def _variadic_extreme(is_max):
    def impl(args, out):
        # NULL if any argument is NULL (reference GreatestFunction)
        if out.is_string:
            # dictionary codes are insertion-ordered, not lexicographic
            raise NotImplementedError("greatest/least on varchar")
        vals = [cast_val(a, out) for a in args]
        data = vals[0].data
        for v in vals[1:]:
            data = jnp.maximum(data, v.data) if is_max else jnp.minimum(data, v.data)
        return Val(data, _all_valid(vals), out)
    return impl


register("greatest")(_variadic_extreme(True))
register("least")(_variadic_extreme(False))


# -- bitwise (reference operator/scalar/BitwiseFunctions.java) ---------------

def _bitwise(fn):
    def impl(args, out):
        vals = [cast_val(a, T.BIGINT) for a in args]
        return Val(fn(*[v.data for v in vals]), _all_valid(vals), out)
    return impl


register("bitwise_and")(_bitwise(jnp.bitwise_and))
register("bitwise_or")(_bitwise(jnp.bitwise_or))
register("bitwise_xor")(_bitwise(jnp.bitwise_xor))
register("bitwise_not")(_bitwise(jnp.bitwise_not))
register("bitwise_left_shift")(_bitwise(lambda a, n: a << n))
register("bitwise_right_shift")(
    _bitwise(lambda a, n: ((a.astype(jnp.uint64)) >> n.astype(jnp.uint64))
             .astype(jnp.int64)))
register("bitwise_arithmetic_shift_right")(_bitwise(lambda a, n: a >> n))


@register("bit_count")
def _bit_count(args, out):
    import jax.lax as lax
    a = cast_val(args[0], T.BIGINT)
    bits = 64
    if len(args) > 1:
        if args[1].literal is None:
            raise NotImplementedError("bit_count() bits must be a constant")
        bits = int(args[1].literal)
    data = a.data if bits == 64 else a.data & ((1 << bits) - 1)
    return Val(lax.population_count(data.astype(jnp.uint64)).astype(jnp.int64),
               a.valid, out)


# -- widened strings (reference operator/scalar/StringFunctions.java) --------

register("replace")(_vocab_transform(
    lambda s, find, repl="": s.replace(find, repl)))
register("reverse")(_vocab_transform(lambda s: s[::-1]))
register("lpad")(_vocab_transform(
    lambda s, n, pad=" ": s[:n] if len(s) >= n
    else ((pad * n)[: n - len(s)] + s if pad else s)))
register("rpad")(_vocab_transform(
    lambda s, n, pad=" ": s[:n] if len(s) >= n
    else (s + (pad * n)[: n - len(s)] if pad else s)))
register("ltrim")(_vocab_transform(lambda s: s.lstrip()))
register("rtrim")(_vocab_transform(lambda s: s.rstrip()))
def _vocab_transform_nullable(fn):
    """Like _vocab_transform but fn may return None (SQL NULL): the null
    slots clear validity and the output vocab is deduplicated so equal
    strings share one code (required by code-comparing joins/grouping)."""
    def impl(args, out):
        a = args[0]
        if a.dictionary is None:
            raise NotImplementedError("string fn on non-dictionary column")
        extra = []
        for x in args[1:]:
            lit = _string_literal_of(x) if x.type.is_string else x.literal
            if lit is None:
                raise NotImplementedError(
                    "string function positional args must be constants")
            extra.append(lit)
        entries = [fn(s, *extra) for s in a.dictionary]
        lookup: dict = {}
        vocab: list = []
        remap = np.empty(len(entries) + 1, dtype=np.int32)
        for i, s in enumerate(entries):
            if s is None:
                remap[i] = -1
                continue
            code = lookup.get(s)
            if code is None:
                code = lookup[s] = len(vocab)
                vocab.append(s)
            remap[i] = code
        remap[-1] = -1
        codes = _code_gather(jnp.asarray(remap), a.data)
        return Val(codes, a.valid & (codes >= 0), out,
                   dictionary=tuple(vocab))
    return impl


def _split_part(s: str, delim: str, idx: int) -> Optional[str]:
    if idx <= 0:
        # constant index: raised at trace time like Presto's
        # INVALID_FUNCTION_ARGUMENT for non-positive indexes
        from ..errors import INVALID_FUNCTION_ARGUMENT, QueryError
        raise QueryError(INVALID_FUNCTION_ARGUMENT,
                         "split_part index must be greater than zero")
    if not delim:
        return s if idx == 1 else None
    parts = s.split(delim)
    return parts[idx - 1] if idx <= len(parts) else None


register("split_part")(_vocab_transform_nullable(_split_part))


def _vocab_int_fn(fn):
    """String->bigint function via a host-computed vocab table."""
    def impl(args, out):
        a = args[0]
        if a.dictionary is None:
            raise NotImplementedError("string fn on non-dictionary column")
        extra = []
        for x in args[1:]:
            lit = _string_literal_of(x) if x.type.is_string else x.literal
            if lit is None:
                raise NotImplementedError(
                    "string function positional args must be constants")
            extra.append(lit)
        table = vocab_table(a.dictionary, lambda s: fn(s, *extra), np.int64)
        return Val(_code_gather(table, a.data), a.valid, out)
    return impl


register("strpos")(_vocab_int_fn(lambda s, sub: s.find(sub) + 1))
register("codepoint")(_vocab_int_fn(lambda s: ord(s[0]) if s else 0))
register("levenshtein_distance")(_vocab_int_fn(
    lambda s, t: _levenshtein(s, t)))


def _levenshtein(s: str, t: str) -> int:
    if len(s) < len(t):
        s, t = t, s
    prev = list(range(len(t) + 1))
    for i, cs in enumerate(s, 1):
        cur = [i]
        for j, ct in enumerate(t, 1):
            cur.append(min(prev[j] + 1, cur[j - 1] + 1,
                           prev[j - 1] + (cs != ct)))
        prev = cur
    return prev[-1]


def _vocab_bool_fn(fn):
    def impl(args, out):
        a = args[0]
        if a.dictionary is None:
            raise NotImplementedError("string fn on non-dictionary column")
        extra = []
        for x in args[1:]:
            lit = _string_literal_of(x) if x.type.is_string else x.literal
            if lit is None:
                raise NotImplementedError(
                    "string function positional args must be constants")
            extra.append(lit)
        table = vocab_table(a.dictionary, lambda s: fn(s, *extra), np.bool_)
        return Val(_code_gather(table, a.data), a.valid, T.BOOLEAN)
    return impl


register("starts_with")(_vocab_bool_fn(lambda s, p: s.startswith(p)))
register("ends_with")(_vocab_bool_fn(lambda s, p: s.endswith(p)))
# reference operator/scalar/StringFunctions.java translate(): chars in
# `from` map positionally to `to`; unmatched positions delete
register("translate")(_vocab_transform(
    lambda s, frm, to: s.translate(
        {ord(c): (to[i] if i < len(to) else None)
         for i, c in enumerate(frm)})))
# deviation: the reference raises for unequal lengths
# (StringFunctions.hammingDistance); the vocab-table evaluation path has
# no per-entry error channel, so unequal lengths count their difference
register("hamming_distance")(_vocab_int_fn(
    lambda s, t: sum(a != b for a, b in zip(s, t))
    + abs(len(s) - len(t))))


def _presto_replacement(repl: str) -> str:
    """Presto/Java replacement syntax -> Python re.sub template:
    $n / ${name} are group refs, \\$ is a literal dollar."""
    out = []
    i = 0
    while i < len(repl):
        c = repl[i]
        if c == "\\" and i + 1 < len(repl):
            nxt = repl[i + 1]
            out.append(nxt if nxt in ("$", "\\") else "\\" + nxt)
            i += 2
        elif c == "$" and i + 1 < len(repl):
            j = i + 1
            if repl[j] == "{":
                end = repl.index("}", j)
                out.append(f"\\g<{repl[j + 1:end]}>")
                i = end + 1
            elif repl[j].isdigit():
                while j < len(repl) and repl[j].isdigit():
                    j += 1
                out.append(f"\\g<{repl[i + 1:j]}>")
                i = j
            else:
                out.append("$")
                i += 1
        else:
            out.append("\\\\" if c == "\\" else c)
            i += 1
    return "".join(out)


# regex: host-compiled over the static vocab — the TPU answer to Joni/RE2J
# (reference operator/scalar/JoniRegexpFunctions.java); patterns must be
# constants, which they virtually always are in SQL
register("regexp_like")(_vocab_bool_fn(
    lambda s, pat: re.search(pat, s) is not None))
register("regexp_extract")(_vocab_transform_nullable(
    lambda s, pat, group=0: (
        (lambda m: m.group(group) if m else None)(re.search(pat, s)))))
register("regexp_replace")(_vocab_transform(
    lambda s, pat, repl="": re.sub(pat, _presto_replacement(repl), s)))


def _json_extract_scalar(doc: str, path: str):
    """Tiny JSONPath: $.key / [idx] steps only (the common Presto usage)."""
    import json as _json
    try:
        v = _json.loads(doc)
    except Exception:
        return None
    if not path.startswith("$"):
        return None
    i = 1
    while i < len(path):
        if path[i] == ".":
            j = i + 1
            while j < len(path) and path[j] not in ".[":
                j += 1
            key = path[i + 1: j]
            if not isinstance(v, dict) or key not in v:
                return None
            v = v[key]
            i = j
        elif path[i] == "[":
            j = path.index("]", i)
            token = path[i + 1: j].strip("\"'")
            if isinstance(v, list):
                try:
                    v = v[int(token)]
                except (ValueError, IndexError):
                    return None
            elif isinstance(v, dict):
                if token not in v:
                    return None
                v = v[token]
            else:
                return None
            i = j + 1
        else:
            return None
    if isinstance(v, (dict, list)):
        return None      # scalar extraction only
    if isinstance(v, bool):
        return "true" if v else "false"
    if v is None:
        return None
    return str(v)


register("json_extract_scalar")(
    _vocab_transform_nullable(_json_extract_scalar))


# -- URL functions (reference operator/scalar/UrlFunctions.java) -------------

def _url_part(part):
    from urllib.parse import urlparse

    def get(s: str) -> str:
        try:
            u = urlparse(s)
        except Exception:
            return ""
        return {"protocol": u.scheme, "host": u.hostname or "",
                "path": u.path, "query": u.query,
                "fragment": u.fragment}[part]
    return get


for _p in ["protocol", "host", "path", "query", "fragment"]:
    register(f"url_extract_{_p}")(_vocab_transform(_url_part(_p)))


@register("url_extract_port")
def _url_extract_port(args, out):
    from urllib.parse import urlparse
    a = args[0]
    if a.dictionary is None:
        raise NotImplementedError("url fn on non-dictionary column")

    def port(s):
        try:
            p = urlparse(s).port
        except Exception:
            p = None
        return -1 if p is None else p
    table = vocab_table(a.dictionary, port, np.int64)
    vals = _code_gather(table, a.data)
    return Val(vals, a.valid & (vals >= 0), out)


# -- widened datetime (reference operator/scalar/DateTimeFunctions.java) -----

_US_PER = {"millisecond": 1_000, "second": 1_000_000,
           "minute": 60_000_000, "hour": 3_600_000_000,
           "day": 86_400_000_000, "week": 7 * 86_400_000_000}


def _to_micros(v: Val) -> jnp.ndarray:
    if isinstance(v.type, T.DateType):
        return v.data.astype(jnp.int64) * 86_400_000_000
    return v.data.astype(jnp.int64)


@register("day_of_week")
def _day_of_week(args, out):
    (a,) = args
    days = a.data if isinstance(a.type, T.DateType) else a.data // 86_400_000_000
    # ISO: Monday=1..Sunday=7; 1970-01-01 was a Thursday (=4)
    dow = (days.astype(jnp.int64) + 3) % 7 + 1
    return Val(dow, a.valid, out)


@register("day_of_year")
def _day_of_year(args, out):
    (a,) = args
    days = a.data if isinstance(a.type, T.DateType) else a.data // 86_400_000_000
    y, _, _ = _civil_from_days(days)
    jan1 = _days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
    return Val(days.astype(jnp.int64) - jan1 + 1, a.valid, out)


def _iso_week(days: jnp.ndarray):
    """ISO-8601 (week, week-year), branch-free."""
    days = days.astype(jnp.int64)
    y, _, _ = _civil_from_days(days)
    jan1 = _days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
    doy = days - jan1 + 1
    isodow = (days + 3) % 7 + 1

    def weeks_in(year):
        # 53-week years: Jan 1 is Thursday, or leap year starting Wednesday
        jan1d = _days_from_civil(year, jnp.ones_like(year),
                                 jnp.ones_like(year))
        dow1 = (jan1d + 3) % 7 + 1
        leap = ((year % 4 == 0) & (year % 100 != 0)) | (year % 400 == 0)
        return jnp.where((dow1 == 4) | (leap & (dow1 == 3)), 53, 52)

    w = (doy - isodow + 10) // 7
    week = jnp.where(w < 1, weeks_in(y - 1), jnp.where(w > weeks_in(y), 1, w))
    wyear = jnp.where(w < 1, y - 1, jnp.where(w > weeks_in(y), y + 1, y))
    return week, wyear


@register("week")
def _week(args, out):
    (a,) = args
    days = a.data if isinstance(a.type, T.DateType) else a.data // 86_400_000_000
    week, _ = _iso_week(days)
    return Val(week, a.valid, out)


@register("year_of_week")
def _year_of_week(args, out):
    (a,) = args
    days = a.data if isinstance(a.type, T.DateType) else a.data // 86_400_000_000
    _, wyear = _iso_week(days)
    return Val(wyear, a.valid, out)


def _time_part(part):
    div = {"hour": 3_600_000_000, "minute": 60_000_000,
           "second": 1_000_000, "millisecond": 1_000}[part]
    mod = {"hour": 24, "minute": 60, "second": 60, "millisecond": 1000}[part]

    def impl(args, out):
        (a,) = args
        us = _to_micros(a)
        return Val(jnp.floor_divide(us, div) % mod, a.valid, out)
    return impl


for _p in ["hour", "minute", "second", "millisecond"]:
    register(_p)(_time_part(_p))


@register("date_trunc")
def _date_trunc(args, out):
    unit_v, a = args
    unit = _string_literal_of(unit_v)
    if unit is None:
        raise NotImplementedError("date_trunc needs a constant unit")
    unit = unit.lower()
    is_date = isinstance(a.type, T.DateType)
    days = a.data.astype(jnp.int64) if is_date else a.data // 86_400_000_000
    if unit in ("millisecond", "second", "minute", "hour"):
        if is_date:
            return Val(a.data, a.valid, out)
        q = _US_PER[unit]
        return Val(jnp.floor_divide(a.data, q) * q, a.valid, out)
    if unit == "day":
        td = days
    elif unit == "week":
        td = days - ((days + 3) % 7)          # back to Monday
    elif unit in ("month", "quarter", "year"):
        y, m, _ = _civil_from_days(days)
        if unit == "month":
            tm = m
        elif unit == "quarter":
            tm = ((m - 1) // 3) * 3 + 1
        else:
            tm = jnp.ones_like(m)
        td = _days_from_civil(y, tm, jnp.ones_like(m))
    else:
        raise NotImplementedError(f"date_trunc({unit!r})")
    if is_date:
        return Val(td.astype(a.data.dtype), a.valid, out)
    return Val(td * 86_400_000_000, a.valid, out)


@register("date_diff")
def _date_diff(args, out):
    unit_v, a, b = args
    unit = _string_literal_of(unit_v)
    if unit is None:
        raise NotImplementedError("date_diff needs a constant unit")
    unit = unit.lower()
    valid = a.valid & b.valid
    if unit in _US_PER:
        delta = _to_micros(b) - _to_micros(a)
        q = _US_PER[unit]
        return Val(jnp.sign(delta) * (jnp.abs(delta) // q), valid, out)
    da = _to_micros(a) // 86_400_000_000
    db = _to_micros(b) // 86_400_000_000
    ya, ma, dda = _civil_from_days(da)
    yb, mb, ddb = _civil_from_days(db)
    months = (yb * 12 + mb) - (ya * 12 + ma)
    # complete months only (Joda monthsBetween semantics)
    months = months - jnp.where((months > 0) & (ddb < dda), 1, 0) \
        + jnp.where((months < 0) & (ddb > dda), 1, 0)
    if unit == "month":
        val = months
    elif unit == "quarter":
        val = jnp.sign(months) * (jnp.abs(months) // 3)
    elif unit == "year":
        val = jnp.sign(months) * (jnp.abs(months) // 12)
    else:
        raise NotImplementedError(f"date_diff({unit!r})")
    return Val(val, valid, out)


@register("date_add")
def _date_add(args, out):
    unit_v, n, a = args
    unit = _string_literal_of(unit_v)
    if unit is None:
        raise NotImplementedError("date_add needs a constant unit")
    unit = unit.lower()
    valid = a.valid & n.valid
    if unit in ("month", "quarter", "year"):
        mult = {"month": 1, "quarter": 3, "year": 12}[unit]
        is_date = isinstance(a.type, T.DateType)
        days = a.data.astype(jnp.int64) if is_date \
            else a.data // 86_400_000_000
        rem = jnp.zeros_like(days) if is_date else a.data % 86_400_000_000
        shifted = _date_add_months(
            [Val(days, a.valid, T.DATE),
             Val(n.data.astype(jnp.int64) * mult, n.valid, n.type)], T.DATE)
        if is_date:
            return Val(shifted.data.astype(a.data.dtype), valid, out)
        return Val(shifted.data * 86_400_000_000 + rem, valid, out)
    q = _US_PER.get(unit)
    if q is None:
        raise NotImplementedError(f"date_add({unit!r})")
    if isinstance(a.type, T.DateType):
        if unit in ("day", "week"):
            days = q // 86_400_000_000
            return Val(a.data + (n.data * days).astype(a.data.dtype),
                       valid, out)
        raise NotImplementedError("date_add of sub-day unit to a DATE")
    return Val(a.data + n.data.astype(jnp.int64) * q, valid, out)


@register("last_day_of_month")
def _last_day_of_month(args, out):
    (a,) = args
    is_date = isinstance(a.type, T.DateType)
    days = a.data.astype(jnp.int64) if is_date else a.data // 86_400_000_000
    y, m, _ = _civil_from_days(days)
    ny = jnp.where(m == 12, y + 1, y)
    nm = jnp.where(m == 12, 1, m + 1)
    td = _days_from_civil(ny, nm, jnp.ones_like(m)) - 1
    return Val(td.astype(jnp.int32), a.valid, out)


@register("from_unixtime")
def _from_unixtime(args, out):
    a = cast_val(args[0], T.DOUBLE)
    return Val((a.data * 1_000_000.0).astype(jnp.int64), a.valid, out)


@register("to_unixtime")
def _to_unixtime(args, out):
    (a,) = args
    return Val(_to_micros(a).astype(jnp.float64) / 1_000_000.0, a.valid, out)


def infer_call_type(name: str, arg_types: List[Type]) -> Type:
    """Return type inference for scalar calls (used by the analyzer).

    Mirrors the signature-resolution role of FunctionRegistry.resolveFunction
    (reference metadata/FunctionRegistry.java) for the engine's builtins.
    """
    if name in ("eq", "ne", "lt", "le", "gt", "ge", "not", "like"):
        return T.BOOLEAN
    if name in ("add", "subtract", "multiply", "divide", "modulus"):
        a, b = arg_types
        if isinstance(a, T.DecimalType) or isinstance(b, T.DecimalType):
            # Presto's decimal operator signatures (reference
            # type/DecimalOperators.java), precision saturating at the
            # Int128-backed MAX_PRECISION 38
            sa = a.scale if isinstance(a, T.DecimalType) else 0
            pa = a.precision if isinstance(a, T.DecimalType) else 19
            sb = b.scale if isinstance(b, T.DecimalType) else 0
            pb = b.precision if isinstance(b, T.DecimalType) else 19
            if T.is_floating(a) or T.is_floating(b):
                return T.DOUBLE
            if name == "multiply":
                return T.DecimalType(min(38, pa + pb), min(38, sa + sb))
            if name == "divide":
                s = max(sa, sb)
                p = min(38, pa + sb + max(0, sb - sa))
                return T.DecimalType(max(p, s), s)
            s = max(sa, sb)
            p = min(38, max(pa - sa, pb - sb) + s + 1)
            return T.DecimalType(p, s)
        t = T.common_super_type(a, b)
        if t is None:
            raise TypeError(f"{name}({a.display()}, {b.display()})")
        return t
    if name == "negate" or name == "abs":
        return arg_types[0]
    if name == "sign":
        # sign(decimal) -> decimal(1,0) (reference MathFunctions.signDecimal)
        if isinstance(arg_types[0], T.DecimalType):
            return T.DecimalType(1, 0)
        return arg_types[0]
    if name in ("sqrt", "ln", "exp", "power", "sin", "cos", "tan", "asin",
                "acos", "atan", "atan2", "sinh", "cosh", "tanh", "log2",
                "log10", "log", "cbrt", "degrees", "radians", "truncate",
                "to_unixtime"):
        return T.DOUBLE
    if name in ("floor", "ceil", "round"):
        return arg_types[0]
    if name in ("year", "month", "day", "quarter", "day_of_week",
                "day_of_year", "week", "year_of_week", "hour", "minute",
                "second", "millisecond", "date_diff", "width_bucket",
                "strpos", "codepoint", "levenshtein_distance",
                "hamming_distance", "bit_count",
                "url_extract_port", "bitwise_and", "bitwise_or",
                "bitwise_xor", "bitwise_not", "bitwise_left_shift",
                "bitwise_right_shift", "bitwise_arithmetic_shift_right"):
        return T.BIGINT
    if name in ("is_nan", "is_finite", "is_infinite", "starts_with",
                "ends_with", "regexp_like"):
        return T.BOOLEAN
    if name in ("greatest", "least"):
        out = arg_types[0]
        for t in arg_types[1:]:
            nxt = T.common_super_type(out, t)
            if nxt is None:
                raise TypeError(f"{name} args have incompatible types")
            out = nxt
        return out
    if name in ("date_add_days", "date_add_months", "date_add_years"):
        return arg_types[0]
    if name == "date_trunc":
        return arg_types[1]
    if name == "date_add":
        return arg_types[2]
    if name == "last_day_of_month":
        return T.DATE
    if name == "from_unixtime":
        return T.TIMESTAMP
    if name in ("lower", "upper", "trim", "ltrim", "rtrim", "substr",
                "translate",
                "concat", "replace", "reverse", "lpad", "rpad", "split_part",
                "regexp_extract", "regexp_replace", "json_extract_scalar",
                "url_extract_protocol", "url_extract_host",
                "url_extract_path", "url_extract_query",
                "url_extract_fragment"):
        return T.VARCHAR
    if name == "length":
        return T.BIGINT
    if name == "to_utf8":
        return T.VARBINARY
    if name == "from_utf8":
        return T.VARCHAR
    if name in _EXTERNAL_SIGNATURES:
        return _EXTERNAL_SIGNATURES[name](list(arg_types))
    raise KeyError(f"unknown function {name!r}")

from .ir import (  # noqa: F401
    Expr, InputRef, Literal, Call, Cast, SpecialForm, Form,
    input_ref, lit, call, cast,
)
from .compiler import compile_projection, compile_filter, ExprCompiler  # noqa: F401

"""Typed scalar expression IR.

Conceptual parity with Presto's RowExpression IR (reference
presto-main/src/main/java/io/prestosql/sql/relational/RowExpression.java and
subclasses CallExpression, ConstantExpression, InputReferenceExpression,
SpecialForm) — the planner lowers analyzed AST expressions into this IR and
the kernel compiler (compiler.py) traces it into XLA, playing the role of
Presto's bytecode generator (sql/gen/PageFunctionCompiler.java).

Expressions are immutable and hashable: the hash is the compile-cache key.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Any, Optional, Tuple

from ..types import Type


class Form(enum.Enum):
    """Special forms with non-default null/short-circuit semantics
    (reference sql/relational/SpecialForm.java Form enum)."""

    AND = "and"
    OR = "or"
    IF = "if"                # IF(cond, then, else)
    COALESCE = "coalesce"
    IS_NULL = "is_null"
    IN = "in"                # IN(value, c1, c2, ...)
    BETWEEN = "between"      # BETWEEN(v, lo, hi)
    NULL_IF = "null_if"
    SWITCH = "switch"        # SWITCH(cond1, val1, cond2, val2, ..., default)
    TRY = "try"              # TRY(expr): row-level errors become NULL


@dataclasses.dataclass(frozen=True)
class Expr:
    type: Type

    def children(self) -> Tuple["Expr", ...]:
        return ()


@dataclasses.dataclass(frozen=True)
class InputRef(Expr):
    """Reference to input column by position (InputReferenceExpression)."""

    index: int = 0

    def __repr__(self) -> str:
        return f"#{self.index}:{self.type.display()}"


@dataclasses.dataclass(frozen=True)
class Literal(Expr):
    """Constant. value is the python-domain value (None = NULL).

    Hashability: python scalars and strings only — arrays never appear here.
    """

    value: Any = None

    def __repr__(self) -> str:
        return f"lit({self.value!r}:{self.type.display()})"


@dataclasses.dataclass(frozen=True)
class Call(Expr):
    """Scalar function call, including operators (name like 'add', 'eq')."""

    name: str = ""
    args: Tuple[Expr, ...] = ()

    def children(self) -> Tuple[Expr, ...]:
        return self.args

    def __repr__(self) -> str:
        return f"{self.name}({', '.join(map(repr, self.args))})"


@dataclasses.dataclass(frozen=True)
class Cast(Expr):
    arg: Optional[Expr] = None

    def children(self) -> Tuple[Expr, ...]:
        return (self.arg,)

    def __repr__(self) -> str:
        return f"cast({self.arg!r} as {self.type.display()})"


@dataclasses.dataclass(frozen=True)
class LambdaRef(Expr):
    """Reference to an enclosing lambda's parameter: ``level`` is the
    absolute nesting depth of the owning lambda (0 = outermost), ``index``
    the parameter position within it — so nested lambdas can reference
    outer parameters unambiguously."""

    index: int = 0
    level: int = 0

    def __repr__(self) -> str:
        return f"$lam{self.level}.{self.index}:{self.type.display()}"


@dataclasses.dataclass(frozen=True)
class LambdaExpr(Expr):
    """Lambda passed to a higher-order function (reference
    sql/relational/LambdaDefinitionExpression.java). ``type`` is the body's
    result type; parameters appear in the body as LambdaRef nodes."""

    body: Optional[Expr] = None
    n_params: int = 0

    def children(self) -> Tuple[Expr, ...]:
        return (self.body,)

    def __repr__(self) -> str:
        return f"lambda({self.n_params})->{self.body!r}"


@dataclasses.dataclass(frozen=True, eq=False, repr=False)
class Param(Expr):
    """Execution-bound parameter slot (the plan-template analogue of
    Presto's Parameter after ParameterRewriter — except the value stays
    a RUNTIME input instead of folding to a constant).

    ``bound`` carries the binding the plan was BUILT with, but equality,
    hashing and repr deliberately exclude it: two plans differing only
    in bindings compare equal expression-by-expression, so the compile
    caches (expr/compiler.ExprCompiler, ops/jitcache) hand every binding
    the SAME traced executable. At dispatch the kernel reads the live
    value from the query's binding scope (expr/params.py) as a traced
    scalar argument."""

    slot: int = 0
    #: build-time binding (python-domain value). NEVER read at trace
    #: time — only the planner may consult it, and only through
    #: expr/params.consult(), which records a reuse guard.
    bound: Any = None

    def __eq__(self, other):
        return (type(other) is Param and other.type == self.type
                and other.slot == self.slot)

    def __ne__(self, other):
        return not self.__eq__(other)

    def __hash__(self):
        return hash((Param, self.type, self.slot))

    def __repr__(self) -> str:
        return f"?{self.slot}:{self.type.display()}"


@dataclasses.dataclass(frozen=True)
class SpecialForm(Expr):
    form: Form = Form.AND
    args: Tuple[Expr, ...] = ()

    def children(self) -> Tuple[Expr, ...]:
        return self.args

    def __repr__(self) -> str:
        return f"{self.form.value}({', '.join(map(repr, self.args))})"


# -- convenience constructors ------------------------------------------------

def input_ref(index: int, type: Type) -> InputRef:
    return InputRef(type=type, index=index)


def lit(value: Any, type: Type) -> Literal:
    return Literal(type=type, value=value)


def param(slot: int, value: Any, type: Type) -> Param:
    return Param(type=type, slot=slot, bound=value)


def call(name: str, type: Type, *args: Expr) -> Call:
    return Call(type=type, name=name, args=tuple(args))


def cast(arg: Expr, to_type: Type) -> Cast:
    return Cast(type=to_type, arg=arg)


def special(form: Form, type: Type, *args: Expr) -> SpecialForm:
    return SpecialForm(type=type, form=form, args=tuple(args))

"""IR rewriting utilities used by the optimizer.

The positional-column analogue of the reference's symbol rewriters
(reference sql/planner/plan/SimplePlanRewriter.java +
ExpressionSymbolInliner): remapping input indices is how plan
transformations keep expressions consistent when children change shape.
"""
from __future__ import annotations

from typing import Callable, Dict, Sequence, Set

from . import ir


def rewrite(e: ir.Expr, fn: Callable[[ir.Expr], ir.Expr]) -> ir.Expr:
    """Bottom-up rewrite: fn sees each node after its children rewrote."""
    if isinstance(e, ir.Call):
        e = ir.Call(type=e.type, name=e.name,
                    args=tuple(rewrite(a, fn) for a in e.args))
    elif isinstance(e, ir.Cast):
        e = ir.Cast(type=e.type, arg=rewrite(e.arg, fn))
    elif isinstance(e, ir.SpecialForm):
        e = ir.SpecialForm(type=e.type, form=e.form,
                           args=tuple(rewrite(a, fn) for a in e.args))
    elif isinstance(e, ir.LambdaExpr):
        # lambda bodies capture outer InputRefs: rewrite through them
        # (LambdaRefs are leaves and pass through fn untouched)
        e = ir.LambdaExpr(type=e.type, body=rewrite(e.body, fn),
                          n_params=e.n_params)
    return fn(e)


def remap_inputs(e: ir.Expr, mapping: Dict[int, int]) -> ir.Expr:
    def fn(n: ir.Expr) -> ir.Expr:
        if isinstance(n, ir.InputRef):
            return ir.InputRef(type=n.type, index=mapping[n.index])
        return n
    return rewrite(e, fn)


def referenced_inputs(e: ir.Expr) -> Set[int]:
    out: Set[int] = set()

    def walk(n: ir.Expr):
        if isinstance(n, ir.InputRef):
            out.add(n.index)
        for c in n.children():
            walk(c)
    walk(e)
    return out


def substitute_literals(e: ir.Expr,
                        resolve: Callable[[object], object]) -> ir.Expr:
    """Replace placeholder literal values (init-plan results)."""
    def fn(n: ir.Expr) -> ir.Expr:
        if isinstance(n, ir.Literal):
            v = resolve(n.value)
            if v is not n.value:
                return ir.Literal(type=n.type, value=v)
        return n
    return rewrite(e, fn)


def conjuncts(e: ir.Expr) -> Sequence[ir.Expr]:
    if isinstance(e, ir.SpecialForm) and e.form == ir.Form.AND:
        out = []
        for a in e.args:
            out.extend(conjuncts(a))
        return out
    return [e]


def combine_conjuncts(parts: Sequence[ir.Expr]):
    from .. import types as T
    parts = list(parts)
    if not parts:
        return None
    if len(parts) == 1:
        return parts[0]
    return ir.special(ir.Form.AND, T.BOOLEAN, *parts)

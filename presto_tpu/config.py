"""Config-file system: etc/config.properties + etc/catalog/*.properties.

The role of the reference's airlift bootstrap config binding (reference
server/PrestoServer.java:86 Bootstrap over @Config classes like
ServerConfig/TaskManagerConfig; StaticCatalogStore loading
etc/catalog/*.properties into ConnectorManager.createConnection, and
spi/Plugin.java ConnectorFactories resolved by 'connector.name').

Layout:

    etc/
      config.properties          node.id, coordinator, discovery.uri,
                                 http-server.http.port, session defaults
                                 (session.<name>=<value>)
      catalog/
        tpch.properties          connector.name=tpch
                                 tpch.scale-factor=1
        warehouse.properties     connector.name=orc
                                 orc.root=/data/warehouse

Connector factories are a plain registry keyed by ``connector.name`` —
the plugin SPI's loading half (PluginManager.java:121's role without
classloader isolation, which Python does not need).
"""
from __future__ import annotations

import dataclasses
import os
from typing import Callable, Dict, Optional

from .connectors.spi import CatalogManager


# -- session-property registry -----------------------------------------------
# The single declaration point for every session property the engine
# reads (the reference's SystemSessionProperties.java role): name ->
# type/default/doc plus an optional extra validator. SET SESSION on an
# unknown or type-mismatched name raises a user-facing error instead of
# silently latching a string no read site will ever consult, and the
# static registry lint (tools/analyze/registries.py) cross-checks every
# ``session.properties.get("...")``/``bool_property(...)`` literal in
# the tree against this table — a typo'd property name fails CI, not a
# user's dashboard.

@dataclasses.dataclass(frozen=True)
class SessionProperty:
    name: str
    type: str           # boolean | integer | double | varchar | duration
    default: object     # documentation only; read sites supply defaults
    doc: str
    validator: Optional[Callable[[object], object]] = None


class SessionPropertyError(ValueError):
    """User-facing SET SESSION rejection (unknown name / bad type)."""

    name = "INVALID_SESSION_PROPERTY"


SESSION_PROPERTIES: Dict[str, SessionProperty] = {}


def _sp(name: str, type_: str, default, doc: str,
        validator: Optional[Callable] = None) -> None:
    SESSION_PROPERTIES[name] = SessionProperty(name, type_, default, doc,
                                               validator)


def _valid_retry_policy(v):
    p = str(v).upper()
    if p not in ("TASK", "QUERY", "NONE"):
        raise SessionPropertyError(
            f"retry_policy must be TASK, QUERY or NONE, got {v!r}")
    return p


def _valid_duration(v):
    from .exec.cluster import parse_duration_s
    try:
        parse_duration_s(v)
    except ValueError as e:
        raise SessionPropertyError(str(e)) from None
    return v


_sp("broadcast_join_row_limit", "integer", 4_000_000,
    "build sides at or under this many estimated rows broadcast; "
    "larger ones hash-partition")
_sp("cluster_memory_limit", "integer", None,
    "cluster-wide reservation cap in bytes; the coordinator memory "
    "manager kills the largest query above it")
_sp("dense_grouping", "boolean", True,
    "allow the stats-bounded dense (scatter-path) GROUP BY plan")
_sp("enable_dynamic_filtering", "boolean", True,
    "build-side key bounds prune probe-side scans at runtime")
_sp("exchange_failure_timeout_s", "double", 45.0,
    "seconds an exchange client retries transport loss before failing "
    "the upstream task")
_sp("fair_scheduling", "boolean", True,
    "time-slice concurrent queries through the device scheduler")
_sp("fused_compact_floor", "integer", 1 << 17,
    "skip fused-chain compaction below this batch capacity")
_sp("fused_compact_window", "integer", 4,
    "fused-chain liveness readbacks amortize over this many batches")
_sp("fused_pipeline", "boolean", True,
    "fuse filter->project->join chains into one jitted pipeline")
_sp("grouped_execution", "boolean", True,
    "run bucketed scans one lifespan at a time")
_sp("join_dense_path", "boolean", True,
    "stats-driven dense-key direct-address join builds: the planner "
    "attaches hard build-key bounds (JoinNode.key_bounds) and the "
    "executor answers bounded key tuples in two gathers")
_sp("join_pallas_probe", "boolean", True,
    "fuse direct-join probe lookup + liveness + payload gathers into "
    "the Pallas ragged-gather kernel on TPU backends (pure-XLA gather "
    "fallback otherwise, and on any kernel compile failure)")


def _valid_mesh_execution(v):
    m = str(v).lower()
    if m not in ("auto", "on", "off"):
        raise SessionPropertyError(
            f"mesh_execution must be auto, on or off, got {v!r}")
    return m


_sp("mesh_execution", "varchar", "auto",
    "multi-chip SPMD execution substrate: auto runs SQL on the device "
    "mesh whenever more than one device is visible and the plan "
    "fragments into mesh stages, on forces it, off pins the "
    "single-device path (PRESTO_TPU_MESH_EXECUTION overrides the "
    "unset default)", _valid_mesh_execution)
_sp("mesh_devices", "integer", 0,
    "devices in the execution mesh (0 = every visible device); 1 "
    "behaves like mesh_execution=off under auto")
_sp("mesh_fused_exchange", "boolean", True,
    "fused SPMD exchange (exec/distributed.py): compute + bucket-count "
    "+ ship collapse into one shard_map program per round, "
    "stats-bounded aggregation stages batch multiple rounds into a "
    "single lax.fori_loop dispatch with donated shard buffers, and "
    "control scalars are fetched once per stage; off is the escape "
    "hatch back to the per-round host control plane")
_sp("mesh_fused_loop_rounds", "integer", 32,
    "cap on chunks one fused lax.fori_loop dispatch may stack "
    "(bounds resident memory: the stacked wave holds every chunk of "
    "the wave on device at once); minimum 1")
_sp("mesh_flight", "boolean", True,
    "mesh flight recorder (obs/flight.py): record every exchange "
    "round of a mesh-path query (dispatch, staging, control sync, "
    "repartition, stall) for the post-query wall-clock attribution "
    "surfaced in EXPLAIN ANALYZE, system.runtime.mesh_rounds and the "
    "mesh_attr_* metric families; off skips recording entirely")
_sp("plan_template_cache", "boolean", False,
    "fingerprint the PARAMETERIZED statement shape (literals "
    "hole-punched) so a fleet of bindings shares one optimized plan + "
    "one warm executable set; optimizer decisions that consulted a "
    "literal record equality guards and fall back to per-binding "
    "fingerprints when a binding flips them (serving/template.py)")
_sp("plan_cache", "boolean", True,
    "serve repeated statements from the compiled-plan cache "
    "(fingerprinted bound AST; skips parse/plan/optimize)")
_sp("probe_prefetch", "boolean", True,
    "overlap probe-side host staging with device dispatch")
_sp("profile", "boolean", False,
    "bracket every jit dispatch and attribute device time per operator")
_sp("push_partial_aggregation_through_join", "boolean", True,
    "eager aggregation below joins when the grouping key covers the "
    "probe join key")
_sp("query_max_memory", "integer", None,
    "per-query memory pool limit in bytes (spill beyond it)")
_sp("query_max_run_time", "duration", None,
    "wall-clock deadline (e.g. 30s, 500ms); the query aborts past it",
    _valid_duration)
_sp("query_queued_timeout", "duration", None,
    "admission deadline (e.g. 5s): a query still queued in its "
    "resource group past it fails with QUERY_QUEUED_TIMEOUT",
    _valid_duration)
_sp("query_retry_attempts", "integer", 1,
    "whole-query re-runs under retry_policy=QUERY")
_sp("result_cache", "boolean", False,
    "serve repeated statements from the versioned result cache "
    "(serving/resultcache.py): stored host rows when every scanned "
    "table's data_version matches, changed-split delta recompute + "
    "distributive merge when a filebase table grew append-only")
_sp("retry_policy", "varchar", "TASK",
    "fault-tolerance mode: TASK, QUERY or NONE", _valid_retry_policy)
_sp("role", "varchar", None,
    "active role for access-control checks (SET ROLE)")
_sp("scan_cache", "boolean", True,
    "serve repeated scans from the device-resident scan cache")
_sp("scan_pad_batches", "boolean", True,
    "pad ragged final split chunks to the stream's capacity bucket")
_sp("scan_prefetch", "boolean", True,
    "decode+stage splits on background threads ahead of the consumer")
_sp("scan_prefetch_depth", "integer", 4,
    "buffered batches per split in the prefetch pipeline")
_sp("scan_threads", "integer", 2,
    "background decode threads per scan")
_sp("shared_scan", "boolean", True,
    "attach concurrent identical-split scan misses to one in-flight "
    "decode instead of racing duplicates")
_sp("speculative_execution", "boolean", True,
    "duplicate straggler tasks on another node, first finished wins")
_sp("speculative_spool_reads", "boolean", True,
    "on an exchange transport failure with a committed spool copy, "
    "race the spool replay against a resumed live pull (first "
    "complete remainder wins, loser cancelled) instead of committing "
    "to the replay — pays off when the spool is a latency-modeled "
    "object store and the worker was merely restarting")
_sp("spill_partitions", "integer", 16,
    "hash partitions for spill-to-host aggregation")
_sp("spool_exchange", "boolean", True,
    "write exchange pages through to the durable page-addressed spool "
    "under retry_policy=TASK (false = PR 5 retained in-memory buffers)")
_sp("spill_path", "varchar", None,
    "directory for second-tier disk spill pages")
_sp("spill_to_disk_bytes", "integer", 4 << 30,
    "staged host bytes beyond this flush to compressed disk pages")
_sp("stats_bounded_grouping", "boolean", True,
    "attach hard per-key bounds from connector stats to aggregations")
_sp("task_concurrency", "integer", 1,
    "parallel driver threads per local pipeline")
_sp("task_retry_attempts", "integer", 2,
    "per-task retry budget under retry_policy=TASK")
_sp("task_retry_backoff_s", "double", 0.05,
    "base backoff between task retry attempts (exponential)")

_TRUE = ("true", "1", "on", "yes")
_FALSE = ("false", "0", "off", "no")


def validate_session_property(name: str, value):
    """Coerced canonical value for ``SET SESSION name = value``; raises
    :class:`SessionPropertyError` on an unknown name or a value that
    does not parse as the declared type."""
    sp = SESSION_PROPERTIES.get(name)
    if sp is None:
        raise SessionPropertyError(
            f"unknown session property {name!r} "
            f"(known: {', '.join(sorted(SESSION_PROPERTIES))})")

    def bad(detail: str = ""):
        return SessionPropertyError(
            f"session property {name!r} expects a {sp.type}, "
            f"got {value!r}" + (f" ({detail})" if detail else ""))

    out = value
    if sp.type == "boolean":
        if isinstance(value, bool):
            out = value
        elif isinstance(value, str) \
                and value.strip().lower() in _TRUE + _FALSE:
            out = value.strip().lower() in _TRUE
        else:
            raise bad()
    elif sp.type == "integer":
        if isinstance(value, bool):
            raise bad()
        elif isinstance(value, int):
            out = value
        elif isinstance(value, str):
            try:
                out = int(value.strip())
            except ValueError:
                raise bad() from None
        else:
            raise bad()
    elif sp.type == "double":
        if isinstance(value, bool):
            raise bad()
        elif isinstance(value, (int, float)):
            out = float(value)
        elif isinstance(value, str):
            try:
                out = float(value.strip())
            except ValueError:
                raise bad() from None
        else:
            raise bad()
    elif sp.type == "varchar":
        if not isinstance(value, str):
            raise bad()
    elif sp.type == "duration":
        if not isinstance(value, (str, int, float)) \
                or isinstance(value, bool):
            raise bad()
    if sp.validator is not None:
        out = sp.validator(out)
    return out


# -- config-file key registry ------------------------------------------------
# Every literal read off a parsed *.properties dict (NodeConfig,
# catalog/connector factories, plugin loader) must appear here — the
# static registry lint (tools/analyze/registries.py) cross-checks the
# ``props.get("...")`` call sites, so a typo'd key in code fails CI
# instead of silently reading the default forever. Globs cover
# namespaced families (``session.*`` defaults).

CONFIG_KEYS: Dict[str, str] = {
    "node.id": "stable node identity (defaults to worker-<port>)",
    "coordinator": "true/false — run the coordinator role",
    "http-server.http.port": "statement/worker HTTP port (0 = ephemeral)",
    "discovery.uri": "coordinator discovery endpoint workers announce to",
    "session.catalog": "default catalog for new sessions",
    "session.schema": "default schema for new sessions",
    "session.*": "session-property defaults (validated against "
                 "SESSION_PROPERTIES at boot)",
    "scan-cache.max-bytes": "process-wide device scan-cache resident "
                            "limit (deliberately not a session prop)",
    "result-cache.max-bytes": "process-wide result-cache host-row "
                              "budget (serving/resultcache.py; "
                              "deliberately not a session prop)",
    "spool.dir": "exchange spool directory (exec/spool.py); point "
                 "every node at shared storage for cross-node replay",
    "spool.max-bytes": "spool disk budget; appends past it fail the "
                       "writing task (default 4GiB)",
    "spool.backend": "which SpoolStore backend serves new queries: "
                     "local (append-only page logs, default) or "
                     "object (content-addressed emulated bucket — "
                     "exec/spool.py ObjectSpoolStore)",
    "spool.object.dir": "object-backend bucket directory; point every "
                        "node at common storage so shuffle state "
                        "survives the worker set scaling to zero",
    "spool.object.put-latency-ms": "modeled per-put object-store "
                                   "round-trip latency (emulates "
                                   "GCS/S3; default 0)",
    "spool.object.get-latency-ms": "modeled per-get object-store "
                                   "round-trip latency (default 0)",
    "spool.object.bandwidth-mbps": "modeled object-store transfer "
                                   "bandwidth in megabits/s "
                                   "(0 = latency-only model)",
    "autoscale.enabled": "run the elasticity control loop "
                         "(exec/autoscale.py) on this coordinator",
    "autoscale.min-workers": "autoscaler floor for the worker set "
                             "(default 1)",
    "autoscale.max-workers": "autoscaler ceiling for the worker set "
                             "(default 8)",
    "autoscale.scale-step": "max workers launched/drained per control "
                            "decision (bounded scale steps; default 1)",
    "autoscale.cooldown-s": "minimum seconds between applied scale "
                            "actions (default 30)",
    "autoscale.interval-s": "control-loop evaluation cadence in "
                            "seconds (default 5)",
    "failpoints": "deterministic fault-injection spec "
                  "(exec/failpoints.py grammar)",
    "timeseries.sample-interval-s": "health-plane sampler cadence in "
                                    "seconds (obs/timeseries.py; "
                                    "default 5)",
    "timeseries.retention-points": "bounded ring size per series "
                                   "(default 360 = 30 min at the "
                                   "default cadence)",
    # resource-groups.json group keys (server/resource_groups.py; not
    # *.properties keys, registered here so tools/analyze round-trips
    # the serving-plane configuration surface)
    "softMemoryLimit": "resource-groups.json: group device-memory bytes "
                       "beyond which new queries queue",
    "hardMemoryLimit": "resource-groups.json: group device-memory bytes "
                       "beyond which a growing query is killed",
    "queryQueuedTimeout": "resource-groups.json: admission deadline for "
                          "queries queued in the group (duration)",
    "slo": "resource-groups.json: per-group SLO block (obs/slo.py) — "
           "latencyTargetMs/latencyObjective/availabilityObjective/"
           "windows",
    "latencyTargetMs": "resource-groups.json slo block: latency "
                       "threshold in milliseconds (snaps up to the "
                       "histogram bucket ladder)",
    "latencyObjective": "resource-groups.json slo block: fraction of "
                        "queries that must finish under the threshold "
                        "(e.g. 0.95)",
    "availabilityObjective": "resource-groups.json slo block: fraction "
                             "of queries that must succeed "
                             "(e.g. 0.999)",
    "windows": "resource-groups.json slo block: burn-rate windows in "
               "seconds (default [300, 3600])",
    "connector.name": "catalog properties: which connector factory",
    "tpch.scale-factor": "tpch catalog scale factor",
    "tpcds.scale-factor": "tpcds catalog scale factor",
    "orc.root": "orc catalog data directory",
    "parquet.root": "parquet catalog data directory",
    "sqlite.path": "sqlite catalog database file",
    "path": "sqlite catalog database file (legacy alias)",
    "plugin.modules": "comma-separated plugin modules to import",
    "plugin.dir": "directory of plugin modules to load",
}

#: declared environment variables — the same two-way contract as the
#: other string-keyed registries (tools/analyze/registries.py): every
#: ``PRESTO_TPU_*`` / ``BENCH_*`` read in the tree must resolve to an
#: entry here, every entry must have a read site, and the table in
#: docs/static_analysis.md round-trips both ways. Foreign variables
#: (XLA_FLAGS, JAX_PLATFORMS) are deliberately NOT declared: they
#: belong to other projects' registries.
ENV_VARS: Dict[str, str] = {
    "PRESTO_TPU_LOCKCHECK": "force the runtime lock-order validator "
                            "on/off (default: on under pytest only)",
    "PRESTO_TPU_LOG": "structured JSON-lines log destination "
                      "(obs/log.py; empty = disabled)",
    "PRESTO_TPU_TRACE": "enable the span tracer outside explicit "
                        "--trace-out runs (obs/trace.py)",
    "PRESTO_TPU_MESH_EXECUTION": "environment default for the "
                                 "mesh_execution session property "
                                 "(auto/on/off; tests pin off)",
    "PRESTO_TPU_MESH_FLIGHT": "environment default for the "
                              "mesh_flight session property "
                              "(on/off; default on)",
    "PRESTO_TPU_FAILPOINTS": "failpoint arming spec applied at import "
                             "(exec/failpoints.py grammar)",
    "PRESTO_TPU_DEVICE_FLOOR_MS": "modeled per-quantum/per-scanned-"
                                  "batch device-service floor in ms "
                                  "(exec/taskexec.py; 0 = off) — the "
                                  "fixed-throughput device model the "
                                  "elastic load-ramp bench uses on "
                                  "hosts whose CPUs cannot show real "
                                  "multi-process scaling",
    "PRESTO_TPU_TIMESERIES": "set to 'off' to disable the background "
                             "health-plane sampler (obs/timeseries.py)",
    "BENCH_REPIN": "allow bench.py to overwrite pinned proxy seconds",
    "BENCH_OUT": "write the bench summary JSON here (regression gate "
                 "input)",
    "BENCH_BUDGET_S": "wall-clock budget for a bench run (seconds)",
    "BENCH_SF": "default TPC-H scale factor for bench configs",
    "BENCH_SF_Q1": "scale-factor override for the q1 config",
    "BENCH_SF_Q1SQL": "scale-factor override for the q1sql config",
    "BENCH_SF_Q3": "scale-factor override for the q3 config",
    "BENCH_SF_Q6": "scale-factor override for the q6 config",
    "BENCH_SF_DS": "scale-factor override for the TPC-DS configs",
    "BENCH_SF_ORC": "scale-factor for the ORC device-decode config",
    "BENCH_ORC": "include the ORC device-decode config in the tuple",
    "BENCH_SERVING": "run the serving bench axis",
    "BENCH_SERVING_SF": "serving bench scale factor",
    "BENCH_SERVING_CLIENTS": "legacy alias of SERVING_CLIENTS",
    "BENCH_SERVING_QUERIES": "legacy alias of SERVING_QUERIES",
    "BENCH_MULTICHIP": "run the multichip bench axis",
    "BENCH_MULTICHIP_DEVICES": "max mesh width for the multichip axis",
    "BENCH_MULTICHIP_FORCE_CPU": "self-provision a virtual CPU mesh "
                                 "for the multichip axis (default 1)",
    "BENCH_MULTICHIP_SF": "multichip bench scale factor",
    "SERVING_CLIENTS": "serving bench concurrent client count",
    "SERVING_QUERIES": "serving bench statements per client",
    "SERVING_MIX": "comma-separated serving bench phases "
                   "(mixed/execute/repeated)",
    "SERVING_COORDINATORS": "serving bench fleet width: N>=2 spawns N "
                            "coordinator subprocesses behind a "
                            "FleetClient (tools/fleet.py); unset/0 = "
                            "classic single-coordinator bench",
    "SERVING_INLINE_LANE": "set to 0 to disable the statement POST "
                           "inline lane (proven-fast statements "
                           "executing in the handler thread); default "
                           "on",
    "SERVING_OUT": "write the serving bench pin JSON here",
    "MULTICHIP_OUT": "write the multichip bench pin JSON here",
    "ELASTIC_OUT": "write the chaos recovery-time summary here "
                   "(tools/chaos_smoke.py)",
}


def parse_properties(path: str) -> Dict[str, str]:
    """key=value lines; '#' comments; whitespace-tolerant (the reference
    uses java.util.Properties semantics)."""
    out: Dict[str, str] = {}
    with open(path) as f:
        for raw in f:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            if "=" not in line:
                raise ValueError(f"{path}: malformed line {line!r}")
            k, v = line.split("=", 1)
            out[k.strip()] = v.strip()
    return out


# -- connector factory registry (the Plugin/ConnectorFactory role) ----------

def _tpch_factory(props):
    from .connectors.tpch import TpchConnector
    return TpchConnector(sf=float(props.get("tpch.scale-factor", "1")))


def _tpcds_factory(props):
    from .connectors.tpcds import TpcdsConnector
    return TpcdsConnector(sf=float(props.get("tpcds.scale-factor", "1")))


def _memory_factory(props):
    from .connectors.memory import MemoryConnector
    return MemoryConnector()


def _orc_factory(props):
    from .connectors.orc import OrcConnector
    return OrcConnector(props["orc.root"])


def _parquet_factory(props):
    from .connectors.parquet import ParquetConnector
    return ParquetConnector(props["parquet.root"])


def _sqlite_factory(props):
    from .connectors.sqlite import connector_factory
    return connector_factory(props)


CONNECTOR_FACTORIES: Dict[str, Callable] = {
    "tpch": _tpch_factory,
    "tpcds": _tpcds_factory,
    "memory": _memory_factory,
    "orc": _orc_factory,
    "parquet": _parquet_factory,
    "sqlite": _sqlite_factory,
}


def register_connector_factory(name: str, factory: Callable) -> None:
    """Third-party connector registration (the Plugin.getConnectorFactories
    surface)."""
    CONNECTOR_FACTORIES[name] = factory


def load_catalogs(etc_dir: str,
                  catalogs: Optional[CatalogManager] = None
                  ) -> CatalogManager:
    """etc/catalog/*.properties -> mounted connectors (reference
    StaticCatalogStore.loadCatalogs)."""
    catalogs = catalogs or CatalogManager()
    cat_dir = os.path.join(etc_dir, "catalog")
    if not os.path.isdir(cat_dir):
        return catalogs
    for entry in sorted(os.listdir(cat_dir)):
        if not entry.endswith(".properties"):
            continue
        props = parse_properties(os.path.join(cat_dir, entry))
        name = entry[:-len(".properties")]
        kind = props.get("connector.name")
        if kind is None:
            raise ValueError(f"{entry}: missing connector.name")
        factory = CONNECTOR_FACTORIES.get(kind)
        if factory is None:
            raise ValueError(
                f"{entry}: unknown connector.name {kind!r} "
                f"(registered: {sorted(CONNECTOR_FACTORIES)})")
        catalogs.register(name, factory(props))
    # the system catalog reflects over everything mounted so far
    from .connectors.system import SystemConnector
    if "system" not in catalogs.names():
        catalogs.register("system", SystemConnector(catalogs))
    return catalogs


class NodeConfig:
    """Parsed etc/config.properties (reference ServerConfig +
    NodeConfig + the session-default slice of SystemSessionProperties)."""

    def __init__(self, props: Dict[str, str]):
        self.props = props
        self.node_id: Optional[str] = props.get("node.id")
        self.coordinator = props.get("coordinator", "true") \
            .lower() == "true"
        self.http_port = int(props.get("http-server.http.port", "0"))
        self.discovery_uri = props.get("discovery.uri")
        self.catalog = props.get("session.catalog", "tpch")
        self.schema = props.get("session.schema", "default")
        #: process-wide device scan-cache resident limit
        #: (exec/scancache.py); None keeps the built-in default
        raw_sc = props.get("scan-cache.max-bytes")
        self.scan_cache_bytes = int(raw_sc) if raw_sc else None
        #: process-wide result-cache host-row budget
        #: (serving/resultcache.py); None keeps the built-in default
        raw_rc = props.get("result-cache.max-bytes")
        self.result_cache_bytes = int(raw_rc) if raw_rc else None
        #: exchange-spool backend config (exec/spool.py SPOOL)
        self.spool_dir = props.get("spool.dir")
        raw_sp = props.get("spool.max-bytes")
        self.spool_max_bytes = int(raw_sp) if raw_sp else None
        #: which SpoolStore backend serves new queries (local/object)
        #: plus the object backend's bucket + latency/bandwidth model
        self.spool_backend = props.get("spool.backend")
        self.spool_object_dir = props.get("spool.object.dir")
        raw_pl = props.get("spool.object.put-latency-ms")
        self.spool_object_put_latency_s = \
            float(raw_pl) / 1e3 if raw_pl else None
        raw_gl = props.get("spool.object.get-latency-ms")
        self.spool_object_get_latency_s = \
            float(raw_gl) / 1e3 if raw_gl else None
        raw_bw = props.get("spool.object.bandwidth-mbps")
        self.spool_object_bandwidth_mbps = \
            float(raw_bw) if raw_bw else None
        #: elasticity control loop (exec/autoscale.py)
        self.autoscale_enabled = props.get(
            "autoscale.enabled", "false").lower() == "true"
        raw_min = props.get("autoscale.min-workers")
        self.autoscale_min_workers = int(raw_min) if raw_min else 1
        raw_max = props.get("autoscale.max-workers")
        self.autoscale_max_workers = int(raw_max) if raw_max else 8
        raw_step = props.get("autoscale.scale-step")
        self.autoscale_scale_step = int(raw_step) if raw_step else 1
        raw_cd = props.get("autoscale.cooldown-s")
        self.autoscale_cooldown_s = float(raw_cd) if raw_cd else 30.0
        raw_iv = props.get("autoscale.interval-s")
        self.autoscale_interval_s = float(raw_iv) if raw_iv else 5.0
        #: deterministic fault-injection spec (exec/failpoints.py
        #: grammar, ';'-separated) — chaos/soak runs arm failpoints
        #: straight from config.properties, same as the
        #: PRESTO_TPU_FAILPOINTS env var
        self.failpoints = props.get("failpoints")
        #: health-plane sampler cadence / per-series ring size
        #: (obs/timeseries.py); None keeps the built-in defaults
        raw_ts = props.get("timeseries.sample-interval-s")
        self.timeseries_interval_s = float(raw_ts) if raw_ts else None
        raw_tr = props.get("timeseries.retention-points")
        self.timeseries_retention = int(raw_tr) if raw_tr else None
        #: session property defaults: session.<name>=<value>
        self.session_defaults = {
            k[len("session."):]: v for k, v in props.items()
            if k.startswith("session.")
            and k not in ("session.catalog", "session.schema")}


def load_node_config(etc_dir: str) -> NodeConfig:
    path = os.path.join(etc_dir, "config.properties")
    return NodeConfig(parse_properties(path) if os.path.isfile(path)
                      else {})


def load_resource_groups(etc_dir: str):
    """etc/resource-groups.json -> ResourceGroupManager config dict
    (the file-backed half of reference
    presto-resource-group-managers/.../FileResourceGroupConfigurationManager
    .java; selectors/limits keep this engine's JSON shape)."""
    import json as _json
    path = os.path.join(etc_dir, "resource-groups.json")
    if not os.path.isfile(path):
        return None
    with open(path) as f:
        return _json.load(f)


def configure_spool(cfg: NodeConfig,
                    directory: Optional[str] = None) -> None:
    """Apply a NodeConfig's ``spool.*`` block to the process-wide
    store (both the coordinator and worker boot paths route here)."""
    if not (directory or cfg.spool_dir or cfg.spool_max_bytes is not None
            or cfg.spool_backend or cfg.spool_object_dir
            or cfg.spool_object_put_latency_s is not None
            or cfg.spool_object_get_latency_s is not None
            or cfg.spool_object_bandwidth_mbps is not None):
        return
    from .exec.spool import SPOOL
    SPOOL.configure(
        directory=directory or cfg.spool_dir,
        max_bytes=cfg.spool_max_bytes,
        backend=cfg.spool_backend,
        object_dir=cfg.spool_object_dir,
        object_put_latency_s=cfg.spool_object_put_latency_s,
        object_get_latency_s=cfg.spool_object_get_latency_s,
        object_bandwidth_mbps=cfg.spool_object_bandwidth_mbps)


def server_from_etc(etc_dir: str, host: str = "127.0.0.1",
                    port: Optional[int] = None):
    """Boot a statement server from a config directory — the
    PrestoServer.run analogue (reference server/PrestoServer.java:86:
    config binding, catalog store, resource groups, announce)."""
    from .exec.runner import LocalRunner
    from .server.protocol import PrestoTpuServer
    cfg = load_node_config(etc_dir)
    # plugins install connector factories / functions BEFORE catalogs
    # mount (reference PrestoServer.run: loadPlugins then catalog store)
    from .plugin import load_plugins_from_config
    load_plugins_from_config(cfg.props)
    catalogs = load_catalogs(etc_dir)
    if cfg.scan_cache_bytes is not None:
        from .exec.scancache import CACHE
        CACHE.set_limit(cfg.scan_cache_bytes)
    if cfg.result_cache_bytes is not None:
        from .serving.resultcache import RESULTS
        RESULTS.set_limit(cfg.result_cache_bytes)
    configure_spool(cfg)
    if cfg.failpoints:
        from .exec.failpoints import FAILPOINTS
        FAILPOINTS.configure_from_spec(cfg.failpoints)
    if cfg.timeseries_interval_s is not None \
            or cfg.timeseries_retention is not None:
        from .obs.timeseries import TIMESERIES
        TIMESERIES.configure(
            sample_interval_s=cfg.timeseries_interval_s,
            retention_points=cfg.timeseries_retention)
    runner = LocalRunner(catalogs=catalogs, catalog=cfg.catalog,
                         schema=cfg.schema)
    # session.<name> defaults go through the same registry gate as SET
    # SESSION: a typo'd default fails the boot, not a dashboard
    runner.session.properties.update(
        {k: validate_session_property(k, v)
         for k, v in cfg.session_defaults.items()})
    srv = PrestoTpuServer(
        runner=runner, host=host,
        port=cfg.http_port if port is None else port,
        resource_groups=load_resource_groups(etc_dir))
    if cfg.autoscale_enabled:
        # close the elasticity loop: signals feed -> rules -> local
        # subprocess workers announcing back to this coordinator. The
        # controller starts with the server (PrestoTpuServer.start is
        # not hooked — the loop thread is harmless pre-start) and
        # stops with it (protocol.stop()).
        from .exec.autoscale import (AutoscaleController,
                                     AutoscalePolicy,
                                     LocalProcessProvider)
        policy = AutoscalePolicy(
            min_workers=cfg.autoscale_min_workers,
            max_workers=cfg.autoscale_max_workers,
            scale_step=cfg.autoscale_scale_step,
            cooldown_s=cfg.autoscale_cooldown_s,
            interval_s=cfg.autoscale_interval_s)
        provider = LocalProcessProvider(
            [f"http://{host}:{srv.port}"],
            spool_dir=cfg.spool_dir, etc_dir=etc_dir)
        srv.autoscaler = AutoscaleController(provider, policy=policy)
        srv.autoscaler.start()
    return srv, cfg

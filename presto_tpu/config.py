"""Config-file system: etc/config.properties + etc/catalog/*.properties.

The role of the reference's airlift bootstrap config binding (reference
server/PrestoServer.java:86 Bootstrap over @Config classes like
ServerConfig/TaskManagerConfig; StaticCatalogStore loading
etc/catalog/*.properties into ConnectorManager.createConnection, and
spi/Plugin.java ConnectorFactories resolved by 'connector.name').

Layout:

    etc/
      config.properties          node.id, coordinator, discovery.uri,
                                 http-server.http.port, session defaults
                                 (session.<name>=<value>)
      catalog/
        tpch.properties          connector.name=tpch
                                 tpch.scale-factor=1
        warehouse.properties     connector.name=orc
                                 orc.root=/data/warehouse

Connector factories are a plain registry keyed by ``connector.name`` —
the plugin SPI's loading half (PluginManager.java:121's role without
classloader isolation, which Python does not need).
"""
from __future__ import annotations

import os
from typing import Callable, Dict, Optional

from .connectors.spi import CatalogManager


def parse_properties(path: str) -> Dict[str, str]:
    """key=value lines; '#' comments; whitespace-tolerant (the reference
    uses java.util.Properties semantics)."""
    out: Dict[str, str] = {}
    with open(path) as f:
        for raw in f:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            if "=" not in line:
                raise ValueError(f"{path}: malformed line {line!r}")
            k, v = line.split("=", 1)
            out[k.strip()] = v.strip()
    return out


# -- connector factory registry (the Plugin/ConnectorFactory role) ----------

def _tpch_factory(props):
    from .connectors.tpch import TpchConnector
    return TpchConnector(sf=float(props.get("tpch.scale-factor", "1")))


def _tpcds_factory(props):
    from .connectors.tpcds import TpcdsConnector
    return TpcdsConnector(sf=float(props.get("tpcds.scale-factor", "1")))


def _memory_factory(props):
    from .connectors.memory import MemoryConnector
    return MemoryConnector()


def _orc_factory(props):
    from .connectors.orc import OrcConnector
    return OrcConnector(props["orc.root"])


def _parquet_factory(props):
    from .connectors.parquet import ParquetConnector
    return ParquetConnector(props["parquet.root"])


def _sqlite_factory(props):
    from .connectors.sqlite import connector_factory
    return connector_factory(props)


CONNECTOR_FACTORIES: Dict[str, Callable] = {
    "tpch": _tpch_factory,
    "tpcds": _tpcds_factory,
    "memory": _memory_factory,
    "orc": _orc_factory,
    "parquet": _parquet_factory,
    "sqlite": _sqlite_factory,
}


def register_connector_factory(name: str, factory: Callable) -> None:
    """Third-party connector registration (the Plugin.getConnectorFactories
    surface)."""
    CONNECTOR_FACTORIES[name] = factory


def load_catalogs(etc_dir: str,
                  catalogs: Optional[CatalogManager] = None
                  ) -> CatalogManager:
    """etc/catalog/*.properties -> mounted connectors (reference
    StaticCatalogStore.loadCatalogs)."""
    catalogs = catalogs or CatalogManager()
    cat_dir = os.path.join(etc_dir, "catalog")
    if not os.path.isdir(cat_dir):
        return catalogs
    for entry in sorted(os.listdir(cat_dir)):
        if not entry.endswith(".properties"):
            continue
        props = parse_properties(os.path.join(cat_dir, entry))
        name = entry[:-len(".properties")]
        kind = props.get("connector.name")
        if kind is None:
            raise ValueError(f"{entry}: missing connector.name")
        factory = CONNECTOR_FACTORIES.get(kind)
        if factory is None:
            raise ValueError(
                f"{entry}: unknown connector.name {kind!r} "
                f"(registered: {sorted(CONNECTOR_FACTORIES)})")
        catalogs.register(name, factory(props))
    # the system catalog reflects over everything mounted so far
    from .connectors.system import SystemConnector
    if "system" not in catalogs.names():
        catalogs.register("system", SystemConnector(catalogs))
    return catalogs


class NodeConfig:
    """Parsed etc/config.properties (reference ServerConfig +
    NodeConfig + the session-default slice of SystemSessionProperties)."""

    def __init__(self, props: Dict[str, str]):
        self.props = props
        self.node_id: Optional[str] = props.get("node.id")
        self.coordinator = props.get("coordinator", "true") \
            .lower() == "true"
        self.http_port = int(props.get("http-server.http.port", "0"))
        self.discovery_uri = props.get("discovery.uri")
        self.catalog = props.get("session.catalog", "tpch")
        self.schema = props.get("session.schema", "default")
        #: process-wide device scan-cache resident limit
        #: (exec/scancache.py); None keeps the built-in default
        raw_sc = props.get("scan-cache.max-bytes")
        self.scan_cache_bytes = int(raw_sc) if raw_sc else None
        #: deterministic fault-injection spec (exec/failpoints.py
        #: grammar, ';'-separated) — chaos/soak runs arm failpoints
        #: straight from config.properties, same as the
        #: PRESTO_TPU_FAILPOINTS env var
        self.failpoints = props.get("failpoints")
        #: session property defaults: session.<name>=<value>
        self.session_defaults = {
            k[len("session."):]: v for k, v in props.items()
            if k.startswith("session.")
            and k not in ("session.catalog", "session.schema")}


def load_node_config(etc_dir: str) -> NodeConfig:
    path = os.path.join(etc_dir, "config.properties")
    return NodeConfig(parse_properties(path) if os.path.isfile(path)
                      else {})


def load_resource_groups(etc_dir: str):
    """etc/resource-groups.json -> ResourceGroupManager config dict
    (the file-backed half of reference
    presto-resource-group-managers/.../FileResourceGroupConfigurationManager
    .java; selectors/limits keep this engine's JSON shape)."""
    import json as _json
    path = os.path.join(etc_dir, "resource-groups.json")
    if not os.path.isfile(path):
        return None
    with open(path) as f:
        return _json.load(f)


def server_from_etc(etc_dir: str, host: str = "127.0.0.1",
                    port: Optional[int] = None):
    """Boot a statement server from a config directory — the
    PrestoServer.run analogue (reference server/PrestoServer.java:86:
    config binding, catalog store, resource groups, announce)."""
    from .exec.runner import LocalRunner
    from .server.protocol import PrestoTpuServer
    cfg = load_node_config(etc_dir)
    # plugins install connector factories / functions BEFORE catalogs
    # mount (reference PrestoServer.run: loadPlugins then catalog store)
    from .plugin import load_plugins_from_config
    load_plugins_from_config(cfg.props)
    catalogs = load_catalogs(etc_dir)
    if cfg.scan_cache_bytes is not None:
        from .exec.scancache import CACHE
        CACHE.set_limit(cfg.scan_cache_bytes)
    if cfg.failpoints:
        from .exec.failpoints import FAILPOINTS
        FAILPOINTS.configure_from_spec(cfg.failpoints)
    runner = LocalRunner(catalogs=catalogs, catalog=cfg.catalog,
                         schema=cfg.schema)
    runner.session.properties.update(cfg.session_defaults)
    srv = PrestoTpuServer(
        runner=runner, host=host,
        port=cfg.http_port if port is None else port,
        resource_groups=load_resource_groups(etc_dir))
    return srv, cfg

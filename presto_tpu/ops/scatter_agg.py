"""Direct-address (scatter) grouped aggregation for bounded integer keys.

The TPU-native analogue of the reference's dense-integer group-by fast
path (reference presto-main/.../operator/BigintGroupByHash.java: when a
single BIGINT key fits a bounded range, group ids come from the value
itself and the hash table degenerates to an array). Here the "array" is
the scatter target of ``jax.ops.segment_sum``: slot = key - lo.

Why this exists (measured on v5e, 67M rows -> 16.8M segments):

- ``segment_sum`` over f64/i64 runs ~8.6s (both are double-wide
  emulations on this chip), while the identical scatter over f32/i32
  runs ~0.6-0.8s — a 14x cliff at the 32-bit boundary.
- The sort-based path (ops/aggregation.py) pays a large-operand
  ``lax.sort`` plus permutation gathers; for a key that is already a
  bounded integer the scatter path skips both.

So exact 64-bit sums are computed as a few 32-bit scatters: split each
value into base-2^w digits with w chosen so a segment's digit-sum cannot
exceed 2^31 (i32 exactness), segment-sum each digit in i32, and
recombine the per-segment digit sums in i64. The caller supplies
``max_rows_per_segment`` (e.g. a join-key multiplicity bound, or the
batch row count) and the value bit-width; both are host-static so the
digit plan compiles into the kernel.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from .. import types as T
from ..batch import Batch, Column, Schema, bucket_capacity
from .aggregation import AggSpec


def _digit_plan(value_bits: int, max_rows_per_segment: int):
    """(width, n_digits): i32 digit sums stay < 2^31 exactly."""
    head = max(int(math.ceil(math.log2(max(max_rows_per_segment, 1) + 1))),
               0)
    w = 31 - head
    if w <= 0:
        raise ValueError(
            f"max_rows_per_segment={max_rows_per_segment} leaves no i32 "
            "digit headroom; use the sort-based aggregation path")
    return w, max(int(math.ceil(value_bits / w)), 1)


def segment_sum_exact(values: jnp.ndarray, seg: jnp.ndarray,
                      num_segments: int, max_rows_per_segment: int,
                      value_bits: int = 62,
                      indices_are_sorted: bool = False) -> jnp.ndarray:
    """Exact i64 segment sums of non-negative i64 values via i32 digit
    scatters. ``value_bits`` bounds each value (< 2^value_bits);
    ``value_bits + log2(max_rows_per_segment)`` must stay < 63."""
    values = values.astype(jnp.int64)
    w, d = _digit_plan(value_bits, max_rows_per_segment)
    out = jnp.zeros(num_segments, dtype=jnp.int64)
    mask = jnp.int64((1 << w) - 1)
    for k in range(d):
        digit = ((values >> (k * w)) & mask).astype(jnp.int32)
        s = jax.ops.segment_sum(digit, seg, num_segments=num_segments,
                                indices_are_sorted=indices_are_sorted)
        out = out + (s.astype(jnp.int64) << (k * w))
    return out


def segment_count(seg: jnp.ndarray, live: jnp.ndarray, num_segments: int,
                  indices_are_sorted: bool = False) -> jnp.ndarray:
    """Per-segment live-row counts via one i32 scatter (counts < 2^31)."""
    ones = jnp.where(live, jnp.int32(1), jnp.int32(0))
    c = jax.ops.segment_sum(ones, seg, num_segments=num_segments,
                            indices_are_sorted=indices_are_sorted)
    return c.astype(jnp.int64)


def _as_int_data(col: Column):
    """(int64 data, value_bits, scale, is_float) for a column whose values
    are exactly representable as scaled integers on the scatter path:
    ints/dates/decimals directly; bools as 0/1. Returns None for float or
    string columns (those stay on the sort path)."""
    t = col.type
    if isinstance(t, T.DecimalType):
        return col.data.astype(jnp.int64), 63, None, False
    if col.data.dtype == jnp.bool_:
        return col.data.astype(jnp.int64), 1, None, False
    if jnp.issubdtype(col.data.dtype, jnp.integer):
        bits = min(jnp.iinfo(col.data.dtype).bits, 62)
        return col.data.astype(jnp.int64), bits, None, False
    return None


def supported_direct(aggs: Sequence[AggSpec], batch: Batch) -> bool:
    """True when every aggregate fits the scatter path: sum/avg/count over
    integer-like inputs, count_star, min/max over 32-bit-safe ints."""
    for a in aggs:
        if a.fn == "count_star" or a.fn == "count":
            continue
        if a.fn not in ("sum", "avg", "min", "max"):
            return False
        c = batch.columns[a.input]
        if a.fn in ("min", "max"):
            if c.dictionary is not None:
                return False
            if not (jnp.issubdtype(c.data.dtype, jnp.integer)
                    or c.data.dtype == jnp.bool_):
                return False
            continue
        if _as_int_data(c) is None:
            return False
    return True


def grouped_aggregate_direct(
    batch: Batch,
    key_index: int,
    lo: int,
    span: int,
    aggs: Sequence[AggSpec],
    mode: str = "partial",
    max_group_rows: Optional[int] = None,
    sorted_keys: bool = False,
    liveness: str = "counts",
    nonnegative: bool = False,
) -> Batch:
    """Group by ONE integer key with host-known bounds [lo, lo+span) via
    direct-address scatters; no sort, no boundary pass.

    Output rows sit at slot (key - lo); slot ``span`` collects NULL-key
    rows (SQL GROUP BY treats NULL as a group). Capacity is
    bucket_capacity(span + 1); slots beyond the live domain are dead.

    mode 'partial' emits the same state-column layout as
    ops.aggregation.grouped_aggregate (states are ordinary columns, so
    merge/final interoperate); mode 'single' emits finalized outputs.

    ``liveness='skip'`` omits the count scatter that marks which slots
    saw rows — every in-span slot is emitted live with additive
    identities (sum 0 / count 0) for untouched groups. Only callers that
    post-filter groups (e.g. a bench top-n over sum>0) may use it.
    ``nonnegative=True`` asserts every summed value is >= 0, halving the
    scatter count (signed data otherwise scatters positive and negative
    magnitudes separately).
    """
    assert mode in ("partial", "single")
    key_col = batch.columns[key_index]
    n_rows = batch.capacity
    max_rows = max_group_rows if max_group_rows is not None else n_rows
    cap = bucket_capacity(span + 1)
    live_row = batch.row_mask
    kvalid = key_col.validity
    key = key_col.data.astype(jnp.int64)
    in_span = (key >= lo) & (key < lo + span)
    # dead rows and (defensively) out-of-span keys go to a trash slot
    # past the null group; they must not pollute slot sums
    slot = jnp.where(live_row & kvalid & in_span, key - lo,
                     jnp.where(live_row & ~kvalid, span, cap))
    slot = slot.astype(jnp.int32)

    cnt_star = None
    if liveness != "skip" or any(a.fn == "count_star" for a in aggs):
        cnt_star = segment_count(slot, live_row, cap,
                                 indices_are_sorted=sorted_keys)

    out_cols: List[Column] = []
    out_fields: List = []
    if cnt_star is not None:
        slot_live = cnt_star > 0
    else:
        slot_live = jnp.ones(cap, dtype=bool)
    out_mask = slot_live & (jnp.arange(cap) <= span)

    # key column: slot index decodes straight back to the key value
    key_data = (jnp.arange(cap, dtype=jnp.int64) + lo).astype(
        key_col.data.dtype)
    key_valid = out_mask & (jnp.arange(cap) < span)
    out_fields.append((batch.schema.names[key_index], key_col.type))
    out_cols.append(Column(key_col.type, key_data, key_valid,
                           key_col.dictionary))

    for agg in aggs:
        base = agg.name or agg.fn
        if agg.fn == "count_star":
            cnt = cnt_star
            if mode == "partial":
                out_fields.append((f"{base}$cnt", T.BIGINT))
                out_cols.append(Column(T.BIGINT, cnt, out_mask, None))
            else:
                out_fields.append((base, agg.output_type))
                out_cols.append(Column(agg.output_type, cnt, out_mask,
                                       None))
            continue
        c = batch.columns[agg.input]
        valid = c.validity & live_row
        if agg.mask is not None:
            valid = valid & batch.columns[agg.mask].data.astype(bool)
        if agg.fn in ("count",):
            cnt = segment_count(slot, valid, cap,
                                indices_are_sorted=sorted_keys)
            name = f"{base}$cnt" if mode == "partial" else base
            out_fields.append((name, T.BIGINT if mode == "partial"
                               else agg.output_type))
            out_cols.append(Column(T.BIGINT, cnt, out_mask, None))
            continue
        if agg.fn in ("min", "max"):
            if c.data.dtype == jnp.bool_:
                use32 = True
            else:
                use32 = jnp.iinfo(c.data.dtype).bits <= 32
            dt = jnp.int32 if use32 else jnp.int64
            x = c.data.astype(dt)
            if agg.fn == "min":
                sent = jnp.iinfo(dt).max
                r = jax.ops.segment_min(
                    jnp.where(valid, x, sent), slot, num_segments=cap,
                    indices_are_sorted=sorted_keys)
            else:
                sent = jnp.iinfo(dt).min
                r = jax.ops.segment_max(
                    jnp.where(valid, x, sent), slot, num_segments=cap,
                    indices_are_sorted=sorted_keys)
            cnt = segment_count(slot, valid, cap,
                                indices_are_sorted=sorted_keys)
            val = r.astype(c.data.dtype)
            if mode == "partial":
                out_fields += [(f"{base}$val", c.type),
                               (f"{base}$cnt", T.BIGINT)]
                out_cols += [Column(c.type, val, out_mask & (cnt > 0),
                                    None),
                             Column(T.BIGINT, cnt, out_mask, None)]
            else:
                out_fields.append((base, agg.output_type))
                out_cols.append(Column(agg.output_type, val,
                                       out_mask & (cnt > 0), None))
            continue
        # sum / avg over integer-like data
        conv = _as_int_data(c)
        assert conv is not None, \
            f"direct path requires integer-like input for {agg.fn}"
        data, bits, _, _ = conv
        cnt = segment_count(slot, valid, cap,
                            indices_are_sorted=sorted_keys)
        if nonnegative:
            vals = jnp.where(valid, data, 0)
            s = segment_sum_exact(vals, slot, cap, max_rows,
                                  value_bits=bits,
                                  indices_are_sorted=sorted_keys)
        else:
            # signed inputs: scatter positive and negative magnitudes
            # separately (the digit split needs non-negative values; a
            # bias term would overflow i64 for wide types)
            pos = jnp.where(valid, jnp.maximum(data, 0), 0)
            neg = jnp.where(valid, jnp.maximum(-data, 0), 0)
            s = (segment_sum_exact(pos, slot, cap, max_rows,
                                   value_bits=bits,
                                   indices_are_sorted=sorted_keys)
                 - segment_sum_exact(neg, slot, cap, max_rows,
                                     value_bits=bits,
                                     indices_are_sorted=sorted_keys))
        if mode == "partial":
            st = agg.state_types()
            out_fields += [(st[0][0], st[0][1]), (st[1][0], T.BIGINT)]
            sum_t = st[0][1]
            out_cols += [Column(sum_t, s.astype(sum_t.storage_dtype),
                                out_mask & (cnt > 0), None),
                         Column(T.BIGINT, cnt, out_mask, None)]
        elif agg.fn == "sum":
            out_fields.append((base, agg.output_type))
            out_cols.append(Column(
                agg.output_type, s.astype(agg.output_type.storage_dtype),
                out_mask & (cnt > 0), None))
        else:  # avg
            out_fields.append((base, agg.output_type))
            if isinstance(agg.output_type, T.DecimalType):
                den = jnp.maximum(cnt, 1)
                q = s.astype(jnp.float64) / den
                out = (jnp.sign(q) * jnp.floor(
                    jnp.abs(s).astype(jnp.float64) / den + 0.5)
                ).astype(jnp.int64)
            else:
                out = s.astype(jnp.float64) / jnp.maximum(
                    cnt, 1).astype(jnp.float64)
            out_cols.append(Column(
                agg.output_type, out.astype(
                    agg.output_type.storage_dtype),
                out_mask & (cnt > 0), None))
    return Batch(Schema(out_fields), out_cols, out_mask)

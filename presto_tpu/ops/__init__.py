from .aggregation import AggSpec, grouped_aggregate, global_aggregate  # noqa: F401
from .sort import SortKey, sort_batch, top_n, limit  # noqa: F401
from .join import lookup_join, semi_join_mask  # noqa: F401

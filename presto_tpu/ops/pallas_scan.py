"""Pallas TPU scan kernels: sequential-carry cumsum and sorted-run
segment sums.

Why these exist (measured on the v5e, see docs/perf.md):

- ``jax.ops.segment_sum`` over 64-bit elements runs ~8M rows/s on this
  chip (i64 and f64 are both double-wide emulations, and the scatter
  falls off the 32-bit fast path), while i32 scans stream at
  ~690M rows/s. The sort-path group-by (ops/aggregation.py) produces
  group ids as SORTED RUNS, where a segment sum needs no scatter at
  all: one inclusive prefix sum + one gather of per-group boundary
  differences.
- XLA's big-array cumsum lowering also compiles slowly as shapes grow
  (measured 9.9s at 2^26 i32 vs 5.1s for this kernel, and minutes for
  64-bit variants); the Pallas grid re-uses one tile-sized program.

Backend constraint that shapes this file: the tunneled TPU backend
rewrites all X64 types (f64 -> double-float, i64 -> pairs) and CANNOT
rewrite custom calls, so 64-bit arrays can't cross a pallas_call
boundary at all. 64-bit segment sums therefore decompose into base-2^w
i32 digit planes OUTSIDE the kernel: i32 prefix sums wrap mod 2^32,
but differences of wrapped prefixes are exact modulo 2^32, so choosing
w with ``w + ceil(log2(max_rows_per_group)) <= 31`` makes every
per-group digit sum exactly recoverable — the same digit algebra as
ops/scatter_agg.py, with the scatter replaced by a linear scan.

The hash-table role: this is the engine's answer to the reference's
MultiChannelGroupByHash/PagesHash hot loops (reference
presto-main/.../operator/MultiChannelGroupByHash.java:1) — on TPU the
"hash table" is sort + segmented reduction, and this kernel is the
reduction's fast path.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

R, L = 64, 128           # grid tile: 64 sublanes x 128 lanes = 8192 rows
TILE = R * L


def _scan_tile(t):
    """Inclusive row-major prefix sum over one [R, L] tile: log-step
    lane scan, then a log-step cross-row scan of row totals (full-width
    operands — width-1 sublane vectors hit Mosaic layout bugs)."""
    for k in (1, 2, 4, 8, 16, 32, 64):
        sh = jnp.concatenate(
            [jnp.zeros((R, k), t.dtype), t[:, :L - k]], axis=1)
        t = t + sh
    rt = jnp.broadcast_to(t[:, L - 1:L], (R, L))
    acc = rt
    k = 1
    while k < R:
        sh = jnp.concatenate(
            [jnp.zeros((k, L), t.dtype), acc[:R - k]], axis=0)
        acc = acc + sh
        k *= 2
    return t + (acc - rt), acc[R - 1:R, 0:1]


def _cumsum_kernel(x_ref, out_ref, carry_ref):
    from jax.experimental import pallas as pl
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        carry_ref[:, :] = jnp.zeros((1, 1), x_ref.dtype)

    t, total = _scan_tile(x_ref[:])
    out_ref[:] = t + carry_ref[0:1, 0:1]
    carry_ref[:, :] = carry_ref[0:1, 0:1] + total


def _imap(i):
    # jax_enable_x64 would make literal indices i64, which Mosaic
    # rejects at func.return — pin them to i32
    return (jnp.asarray(i, jnp.int32), jnp.int32(0))


def _cumsum_tiled(x2d: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    # deliberately NOT jitted here: every engine call site reaches this
    # inside an already-jitted kernel (ops/aggregation.py group-by),
    # where an inner jax.jit is inlined anyway — a raw jit wrapper
    # would only create an executable invisible to ops/jitcache
    # (tracing/raw-jit) for the eager test-only path
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    n = x2d.shape[0] // R
    return pl.pallas_call(
        _cumsum_kernel,
        grid=(n,),
        in_specs=[pl.BlockSpec((R, L), _imap)],
        out_specs=pl.BlockSpec((R, L), _imap),
        out_shape=jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
        scratch_shapes=[pltpu.VMEM((1, 1), x2d.dtype)],
        interpret=interpret,
    )(x2d)


#: tests set this to exercise the scan paths on the CPU mesh (pallas
#: runs in interpret mode there); engine call sites otherwise use the
#: scan paths only on real TPU backends
FORCE_SCAN_PATHS = False


def pallas_supported() -> bool:
    """The kernels run on real TPU backends; the CPU test mesh uses the
    interpret path only when explicitly requested (tests), and engine
    call sites fall back to XLA primitives."""
    return FORCE_SCAN_PATHS or jax.default_backend() not in ("cpu",)


def _interpret() -> bool:
    return jax.default_backend() in ("cpu",)


def cumsum_i32(x: jnp.ndarray,
               interpret: Optional[bool] = None) -> jnp.ndarray:
    """Inclusive prefix sum of a 1-D i32 array (wraps mod 2^32 like any
    i32 sum). Pads to a tile multiple internally."""
    if interpret is None:
        interpret = _interpret()
    n = x.shape[0]
    pad = (-n) % TILE
    if pad:
        x = jnp.concatenate([x, jnp.zeros(pad, jnp.int32)])
    out = _cumsum_tiled(x.reshape(-1, L), interpret=interpret)
    return out.reshape(-1)[:n]


def _digit_plan(max_rows_per_group: int, bits: int = 64):
    """(width, n_digits): per-group digit sums stay within 31 bits so
    wrapped-prefix differences recover them exactly."""
    w = max(31 - max(int(math.ceil(math.log2(max(max_rows_per_group, 2)))),
                     1), 1)
    return w, int(math.ceil(bits / w))


def segment_sum_sorted_i64(
    values: jnp.ndarray,
    starts: jnp.ndarray,
    num_segments: int,
    max_rows_per_group: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Exact i64 segment sums when segment members are CONTIGUOUS RUNS
    (ids sorted ascending; dead rows must carry zero values).

    ``starts[g]`` is the row index of segment g's first row; ABSENT
    segments must carry ``starts[g] == n`` (one past the end) so the
    preceding live segment's run extends to the array end (their own
    results are garbage and callers mask them by the segment liveness
    they already track).
    """
    n = values.shape[0]
    cap = num_segments
    w, nd = _digit_plan(max_rows_per_group or n)
    mask = jnp.int64((1 << w) - 1)
    # prefix[g] = csum at the row BEFORE segment g's start
    prev = jnp.clip(starts - 1, 0, n - 1)
    at_zero = starts <= 0
    ends = jnp.concatenate(
        [jnp.clip(starts[1:] - 1, 0, n - 1),
         jnp.full((1,), n - 1, starts.dtype)])
    total = jnp.zeros(cap, dtype=jnp.int64)
    for d in range(nd):
        digit = ((values >> jnp.int64(d * w)) & mask).astype(jnp.int32)
        csum = cumsum_i32(digit, interpret=interpret)
        hi = jnp.take(csum, ends, axis=0)
        lo = jnp.where(at_zero, 0, jnp.take(csum, prev, axis=0))
        dsum = (hi - lo).astype(jnp.int64) & jnp.int64(0xFFFFFFFF)
        total = total + (dsum << jnp.int64(d * w))
    return total


def segment_count_sorted(
    live: jnp.ndarray,
    starts: jnp.ndarray,
    num_segments: int,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Per-segment live-row counts over sorted runs: one i32 prefix sum
    + boundary differences (counts < 2^31 by construction)."""
    n = live.shape[0]
    prev = jnp.clip(starts - 1, 0, n - 1)
    at_zero = starts <= 0
    ends = jnp.concatenate(
        [jnp.clip(starts[1:] - 1, 0, n - 1),
         jnp.full((1,), n - 1, starts.dtype)])
    csum = cumsum_i32(live.astype(jnp.int32), interpret=interpret)
    hi = jnp.take(csum, ends, axis=0)
    lo = jnp.where(at_zero, 0, jnp.take(csum, prev, axis=0))
    return (hi - lo).astype(jnp.int64)

"""Two-limb int128 vector kernels: the storage/arithmetic layer for
long decimals (precision 19..38).

The reference models decimal(38) as a Java Int128 in flat limb arrays
(reference presto-spi/.../spi/block/Int128ArrayBlock.java,
spi/type/Decimals.java MAX_PRECISION = 38, decimal arithmetic in
spi/type/UnscaledDecimal128Arithmetic.java). The TPU shape of the same
idea: a column of long decimals is an [capacity, 2] i64 tile —
``value = hi * 2**64 + (lo mod 2**64)`` with ``hi`` signed and ``lo``
holding the low 64 bits' two's-complement pattern — and every operation
is a handful of branch-free vector ops over the limbs. i64 adds wrap
two's-complement on XLA, so carries come from unsigned compares
(sign-bit-flipped signed compares), never per-element control flow.

Multiplication and base-10 rescaling decompose limbs into 32-bit
digits: 32x32 partial products fit u64 exactly, and short division by a
< 2**31 divisor runs as a static 4-step digit loop with a carried
remainder (each step's ``r * 2**32 + digit`` fits i64). Exact sums over
rows decompose the same way: four digit segment-sums recombine with
carry propagation (ops/scatter_agg.py applies the identical trick to
make 64-bit group sums fast; here it makes 128-bit sums *possible*).
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

SIGN64 = jnp.int64(-(1 << 63))
MASK32 = jnp.int64(0xFFFFFFFF)

#: largest value magnitude a decimal(38) may hold, as Python int
MAX_UNSCALED = 10 ** 38 - 1


# -- packing ----------------------------------------------------------------

def pack(hi: jnp.ndarray, lo: jnp.ndarray) -> jnp.ndarray:
    return jnp.stack([hi.astype(jnp.int64), lo.astype(jnp.int64)], axis=-1)


def hi(x: jnp.ndarray) -> jnp.ndarray:
    return x[..., 0]


def lo(x: jnp.ndarray) -> jnp.ndarray:
    return x[..., 1]


def limbs_of(value: int) -> Tuple[int, int]:
    """Python int -> (hi, lo) limb ints (lo as SIGNED two's complement)."""
    lo_u = value & ((1 << 64) - 1)
    h = value >> 64
    if not -(1 << 63) <= h < (1 << 63):
        raise OverflowError(f"{value} out of int128 range")
    return h, lo_u - (1 << 64) if lo_u >= (1 << 63) else lo_u


def int_of(h: int, l: int) -> int:
    """(hi, lo) limb ints -> Python int."""
    return (int(h) << 64) + (int(l) & ((1 << 64) - 1))


def const(value: int) -> jnp.ndarray:
    h, l = limbs_of(value)
    return pack(jnp.int64(h), jnp.int64(l))


def from_i64(v: jnp.ndarray) -> jnp.ndarray:
    """Sign-extend i64 values into limb pairs."""
    v = v.astype(jnp.int64)
    return pack(v >> 63, v)


# -- compares ---------------------------------------------------------------

def _ult(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Unsigned < over i64 bit patterns."""
    return (a ^ SIGN64) < (b ^ SIGN64)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return (hi(a) == hi(b)) & (lo(a) == lo(b))


def lt(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return (hi(a) < hi(b)) | ((hi(a) == hi(b)) & _ult(lo(a), lo(b)))


def le(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return lt(a, b) | eq(a, b)


def is_neg(x: jnp.ndarray) -> jnp.ndarray:
    return hi(x) < 0


def is_zero(x: jnp.ndarray) -> jnp.ndarray:
    return (hi(x) == 0) & (lo(x) == 0)


def sign(x: jnp.ndarray) -> jnp.ndarray:
    """-1 / 0 / 1 as i64."""
    return jnp.where(is_neg(x), jnp.int64(-1),
                     jnp.where(is_zero(x), jnp.int64(0), jnp.int64(1)))


def sortable_lo(x: jnp.ndarray) -> jnp.ndarray:
    """lo limb transformed so SIGNED i64 order matches unsigned order
    (for (hi, sortable_lo) lexicographic sort keys)."""
    return lo(x) ^ SIGN64


# -- add / sub / neg --------------------------------------------------------

def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    l = lo(a) + lo(b)                       # wraps mod 2^64
    carry = _ult(l, lo(a)).astype(jnp.int64)
    return pack(hi(a) + hi(b) + carry, l)


def add_overflows(a: jnp.ndarray, b: jnp.ndarray,
                  s: jnp.ndarray) -> jnp.ndarray:
    """True where a + b = s overflowed int128 (same-sign operands,
    different-sign result)."""
    return ((hi(a) < 0) == (hi(b) < 0)) & ((hi(s) < 0) != (hi(a) < 0))


def neg(a: jnp.ndarray) -> jnp.ndarray:
    l = -lo(a)                              # wraps
    h = jnp.where(lo(a) == 0, -hi(a), ~hi(a))
    return pack(h, l)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return add(a, neg(b))


def abs_(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.where(is_neg(x)[..., None], neg(x), x)


def where(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Elementwise select over limb pairs (cond is row-shaped)."""
    return jnp.where(cond[..., None], a, b)


# -- digit decomposition ----------------------------------------------------

def digits32(x: jnp.ndarray) -> Tuple[jnp.ndarray, ...]:
    """(d0, d1, d2, d3): x = sum di * 2**(32 i); d0..d2 in [0, 2**32),
    d3 = arithmetic high digit (signed). All i64."""
    d0 = lo(x) & MASK32
    d1 = (lo(x) >> 32) & MASK32
    d2 = hi(x) & MASK32
    d3 = hi(x) >> 32
    return d0, d1, d2, d3


def from_digits(d0, d1, d2, d3) -> jnp.ndarray:
    """Recombine possibly-carrying digit values (each i64; d0..d2 may
    exceed 32 bits, carries propagate upward; d3 absorbs the rest)."""
    t0 = d0
    c0 = t0 >> 32
    t1 = d1 + c0
    c1 = t1 >> 32
    t2 = d2 + c1
    c2 = t2 >> 32
    t3 = d3 + c2
    l = (t0 & MASK32) | ((t1 & MASK32) << 32)
    h = (t2 & MASK32) | ((t3 & MASK32) << 32)
    return pack(h, l)


# -- multiplication ---------------------------------------------------------

def mul(a: jnp.ndarray, b: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Signed 128x128 -> low 128 product + overflow flag (any bits past
    the 127-bit magnitude). Magnitude multiply, sign fixup."""
    an, bn = is_neg(a), is_neg(b)
    am, bm = abs_(a), abs_(b)
    a0, a1, a2, a3 = digits32(am)
    b0, b1, b2, b3 = digits32(bm)
    ad = [a0, a1, a2, a3]
    bd = [b0, b1, b2, b3]

    def p(i: int, j: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
        # 32x32 partial product, split into (lo32, hi32); u64 is exact
        full = ad[i].astype(jnp.uint64) * bd[j].astype(jnp.uint64)
        return ((full & jnp.uint64(0xFFFFFFFF)).astype(jnp.int64),
                (full >> jnp.uint64(32)).astype(jnp.int64))

    # accumulate digit sums (each term < 2^32; <= 8 terms, fits i64)
    s = [jnp.zeros_like(a0) for _ in range(5)]
    overflow = jnp.zeros(a0.shape, dtype=bool)
    for i in range(4):
        for j in range(4):
            plo, phi = p(i, j)
            k = i + j
            if k < 4:
                s[k] = s[k] + plo
                s[k + 1] = s[k + 1] + phi
            else:
                overflow = overflow | (plo != 0) | (phi != 0)
    m = from_digits(s[0], s[1], s[2], s[3])
    # bits spilling past digit 3, magnitude sign bit set, or high
    # partial of digit 3 all mean the magnitude left 127 bits
    carry_out = (s[3] + ((s[2] + ((s[1] + (s[0] >> 32)) >> 32)) >> 32)) >> 32
    overflow = overflow | (s[4] != 0) | (carry_out != 0) | is_neg(m)
    out = jnp.where((an ^ bn)[..., None], neg(m), m)
    return out, overflow


def mul_small(a: jnp.ndarray, k: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """a * k for a static Python int k >= 0 (k < 2**63)."""
    return mul(a, jnp.broadcast_to(const(k), a.shape))


# -- short division (magnitudes) --------------------------------------------

def divmod_small_abs(x: jnp.ndarray, d) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Nonnegative x divided by divisor d (static int or i64 array,
    1 <= d <= 2**31): (quotient limbs, remainder i64). Classic base-2**32
    short division — remainder < 2**31 keeps every step in i64 (d = 2**31
    exactly still fits: r <= 2**31 - 1, so r*2**32 + digit < 2**63)."""
    if isinstance(d, int):
        d = jnp.int64(d)
    d = jnp.clip(d.astype(jnp.int64), 1, 1 << 31)
    d0, d1, d2, d3 = digits32(x)
    r = jnp.zeros_like(d0)
    qs = []
    for di in (d3, d2, d1, d0):
        cur = (r << 32) + di
        qs.append(cur // d)
        r = cur % d
    q3, q2, q1, q0 = qs
    l = (q0 & MASK32) | ((q1 & MASK32) << 32)
    h = (q2 & MASK32) | ((q3 & MASK32) << 32)
    return pack(h, l), r


def div_round_half_up(x: jnp.ndarray, d) -> jnp.ndarray:
    """Signed x / d (d as in divmod_small_abs), rounding half up away
    from zero (Presto decimal rounding)."""
    if isinstance(d, int):
        d = jnp.int64(d)
    neg_in = is_neg(x)
    q, r = divmod_small_abs(abs_(x), d)
    bump = (2 * r >= d.astype(jnp.int64)).astype(jnp.int64)
    q = add(q, pack(jnp.zeros_like(bump), bump))
    return jnp.where(neg_in[..., None], neg(q), q)


# -- wide division (int128 / int128) -----------------------------------------

def divmod_abs(x: jnp.ndarray, d: jnp.ndarray
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Nonnegative x divided by positive d, BOTH int128 limb tiles:
    (quotient, remainder). Float-estimated quotient with exact integer
    correction — each round's floor(to_f64(r)/to_f64(d)) estimate is
    exact-rational to ~2^-52 relative (better on TPU's double-double),
    and the exact mul/sub shrink the residual by that factor per round:
    2^127 -> 2^75 -> 2^23 -> O(d) over three rounds, then a bounded
    +-3d fix-up lands r in [0, d). Replaces a Knuth long division whose
    per-digit carries would need 96-bit intermediates (reference
    UnscaledDecimal128Arithmetic.divide works digitwise in Java)."""
    df = jnp.maximum(to_f64(d), 1.0)
    one = from_i64(jnp.ones(x.shape[:-1], dtype=jnp.int64))

    # lax loops, not Python unrolling: the unrolled 4x estimate/correct
    # chain sends XLA's algebraic simplifier into its circular-
    # simplification bailout and (observed under
    # --xla_force_host_platform_device_count) miscompiles the arithmetic;
    # a fori_loop body compiles once and stays out of that path
    def estimate(_, qr):
        q, r = qr
        e128 = from_f64(jnp.floor(to_f64(r) / df))
        prod, _ = mul(e128, d)
        return add(q, e128), sub(r, prod)

    q, r = jax.lax.fori_loop(0, 4, estimate, (jnp.zeros_like(x), x))

    def fixup(_, qr):
        q, r = qr
        neg_r = is_neg(r)
        q = where(neg_r, sub(q, one), q)
        r = where(neg_r, add(r, d), r)
        ge = le(d, r) & ~is_neg(r)
        q = where(ge, add(q, one), q)
        r = where(ge, sub(r, d), r)
        return q, r

    return jax.lax.fori_loop(0, 3, fixup, (q, r))


def div_round_half_up_wide(x: jnp.ndarray, d: jnp.ndarray) -> jnp.ndarray:
    """Signed int128 x / int128 d (|d| >= 1), rounding half up away from
    zero — the general long-decimal division kernel."""
    neg_out = is_neg(x) ^ is_neg(d)
    da = abs_(d)
    q, r = divmod_abs(abs_(x), da)
    # 2r >= d without overflowing: r >= d - r
    bump = le(sub(da, r), r)
    q = where(bump, add(q, from_i64(
        jnp.ones(q.shape[:-1], dtype=jnp.int64))), q)
    return where(neg_out, neg(q), q)


# -- base-10 rescale --------------------------------------------------------

_P9 = 10 ** 9


def rescale(x: jnp.ndarray, delta: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """x * 10**delta (delta > 0) or round-half-up(x / 10**-delta)
    (delta < 0). Static delta. Returns (value, overflow)."""
    overflow = jnp.zeros(x.shape[:-1], dtype=bool)
    if delta == 0:
        return x, overflow
    if delta > 0:
        while delta > 0:
            step = min(delta, 18)
            x, o = mul_small(x, 10 ** step)
            overflow = overflow | o
            delta -= step
        return x, overflow
    k = -delta
    # all but the last step truncate (exact digit drops happen only at
    # the final rounding position, matching integer half-up semantics)
    neg_in = is_neg(x)
    m = abs_(x)
    while k > 9:
        m, _ = divmod_small_abs(m, _P9)
        k -= 9
    d = 10 ** k
    q, r = divmod_small_abs(m, d)
    bump = (2 * r >= d).astype(jnp.int64)
    q = add(q, pack(jnp.zeros_like(bump), bump))
    return jnp.where(neg_in[..., None], neg(q), q), overflow


# -- float conversion -------------------------------------------------------

def to_f64(x: jnp.ndarray) -> jnp.ndarray:
    # lo as two 32-bit halves: the obvious (lo ^ SIGN64) + 2^63 form
    # catastrophically cancels for small magnitudes (4 - 2^63 rounds to
    # -2^63 exactly at f64 precision, so adding 2^63 back returns 0)
    l = lo(x)
    lo_low = (l & MASK32).astype(jnp.float64)
    lo_high = ((l >> 32) & MASK32).astype(jnp.float64) * jnp.float64(2.0 ** 32)
    return (hi(x).astype(jnp.float64) * jnp.float64(2.0 ** 64)
            + lo_high + lo_low)


def from_f64(v: jnp.ndarray) -> jnp.ndarray:
    """Round-to-nearest f64 -> int128 (|v| must be < 2**127; f64 only
    carries 53 significant bits, so low bits of huge values are zeros)."""
    v = jnp.round(v)
    # small magnitudes convert exactly through one i64 cast — the limb
    # split below goes through frac = v + 2**64 for negative v, whose
    # ulp (4096) would wipe the low bits (-2357 became -2048)
    small = jnp.abs(v) < 2.0 ** 62
    direct = from_i64(jnp.where(small, v, 0.0).astype(jnp.int64))
    h = jnp.floor(v / (2.0 ** 64))
    frac = v - h * (2.0 ** 64)
    # the quotient rounds, so frac can fall outside [0, 2^64) by an ulp
    # of v — renormalize or the lo limb is off by a whole 2^64
    h = jnp.where(frac < 0, h - 1, jnp.where(frac >= 2.0 ** 64, h + 1, h))
    frac = jnp.where(frac < 0, frac + 2.0 ** 64,
                     jnp.where(frac >= 2.0 ** 64, frac - 2.0 ** 64, frac))
    l_signed = jnp.where(frac >= 2.0 ** 63,
                         frac - 2.0 ** 64, frac).astype(jnp.int64)
    return where(small, direct, pack(h.astype(jnp.int64), l_signed))


# -- exact row sums via digit decomposition ---------------------------------

def digit_sum_tiles(x: jnp.ndarray) -> jnp.ndarray:
    """[..., 4] digit planes of limb tiles, ready for per-digit
    segment/global sums (sums of < 2**31 rows cannot overflow i64)."""
    d0, d1, d2, d3 = digits32(x)
    return jnp.stack([d0, d1, d2, d3], axis=-1)


def from_digit_sum_tiles(s: jnp.ndarray) -> jnp.ndarray:
    """Recombine [..., 4] summed digit planes into limb pairs."""
    return from_digits(s[..., 0], s[..., 1], s[..., 2], s[..., 3])


def from_digit_sum_tiles_checked(s: jnp.ndarray):
    """Like from_digit_sum_tiles but also detects int128 overflow: the
    carried top digit must fit 32 signed bits, and digit sums of up to
    2^31 rows keep it exactly in i64 — so detection sees the TRUE sum,
    never a wrapped one. Returns (value, overflow)."""
    t0 = s[..., 0]
    c0 = t0 >> 32
    t1 = s[..., 1] + c0
    c1 = t1 >> 32
    t2 = s[..., 2] + c1
    c2 = t2 >> 32
    t3 = s[..., 3] + c2
    ovf = t3 != ((t3 << 32) >> 32)
    l = (t0 & MASK32) | ((t1 & MASK32) << 32)
    h = (t2 & MASK32) | ((t3 & MASK32) << 32)
    return pack(h, l), ovf


#: poisoned limb pattern for decimal aggregate overflow: unreachable by
#: any value with |v| <= 10^38 (|hi| would be < 2^63), detected at
#: result decode (types.DecimalType.from_storage) and re-poisoned
#: through merges — the deferred-raise analogue of the reference's
#: throw in DecimalSumAggregation
OVERFLOW_SENTINEL = np.array([-(1 << 63), 1], dtype=np.int64)


def is_overflow_sentinel(x: jnp.ndarray) -> jnp.ndarray:
    return (hi(x) == jnp.int64(-(1 << 63))) & (lo(x) == jnp.int64(1))


# -- bounds -----------------------------------------------------------------

def fits_decimal(x: jnp.ndarray, precision: int) -> jnp.ndarray:
    """|x| <= 10**precision - 1 (the reference's overflow contract,
    UnscaledDecimal128Arithmetic.overflows)."""
    bound = const(10 ** precision - 1)
    m = abs_(x)
    return le(m, jnp.broadcast_to(bound, m.shape)) & ~is_neg(m)


# -- host conversion --------------------------------------------------------

def np_limbs(values, null_value: int = 0) -> np.ndarray:
    """Python ints -> [n, 2] i64 numpy limb array (host-side builder)."""
    out = np.empty((len(values), 2), dtype=np.int64)
    for i, v in enumerate(values):
        h, l = limbs_of(null_value if v is None else int(v))
        out[i, 0] = h
        out[i, 1] = l
    return out

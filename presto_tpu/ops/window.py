"""Window function kernels.

The TPU-native replacement for Presto's window machinery (reference
presto-main/.../operator/WindowOperator.java sorts via PagesIndex, then
WindowPartition evaluates functions per partition; built-ins in
operator/window/). Here the whole batch is sorted once by
(partition keys, order keys) with every payload column riding along, and
per-row values come from branch-free cumulative/segment ops:

- partition boundaries -> segment ids (like the group-by kernel);
- peer runs (equal order keys within a partition) for RANGE-frame
  semantics: ranking ties and running aggregates include full peer runs;
- running aggregates = cumsum over peer-run ends minus the partition base.

Rows are returned in (partition, order) order — a valid SQL result order;
the planner's own ORDER BY, if any, sorts afterwards.

Explicit frames (ROWS/RANGE BETWEEN <bound> AND <bound>, reference
operator/window/FrameInfo.java) compute per-row [fs, fe] position spans:
ROWS bounds are position offsets, RANGE bounds binary-search the
partition-sorted order key, aggregates answer from cumsum differences,
and MIN/MAX answer arbitrary spans from sparse range-query tables.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .prefix import prefix_sum
from .. import types as T
from ..batch import Batch, Column, Schema
from ..types import Type
from .sort import SortKey, _sortable, rank_codes, unrank_table

RANKING = ("row_number", "rank", "dense_rank", "percent_rank", "cume_dist",
           "ntile")
VALUE_FNS = ("first_value", "last_value", "lag", "lead", "nth_value")
AGG_FNS = ("sum", "count", "avg", "min", "max", "count_star")


@dataclasses.dataclass(frozen=True)
class WindowSpec:
    """One window function application over shared partition/order keys."""

    fn: str
    args: Tuple[int, ...]          # input column indices
    output_type: Type
    name: str
    offset: int = 1                # lag/lead offset; ntile buckets
    ignore_order: bool = False     # aggregate without ORDER BY: whole part.
    frame: str = "range"           # frame unit: RANGE | ROWS
    #: frame bounds (kind, offset): unbounded_preceding | preceding |
    #: current_row | following | unbounded_following (reference
    #: operator/window/FrameInfo.java)
    frame_start: Tuple[str, int] = ("unbounded_preceding", 0)
    frame_end: Tuple[str, int] = ("current_row", 0)

    @property
    def default_frame(self) -> bool:
        return (self.frame_start == ("unbounded_preceding", 0)
                and self.frame_end == ("current_row", 0))


def _cummax_int(x: jnp.ndarray) -> jnp.ndarray:
    return jax.lax.associative_scan(jnp.maximum, x)


def _bounded_searchsorted(vals: jnp.ndarray, targets: jnp.ndarray,
                          lo0: jnp.ndarray, hi0: jnp.ndarray,
                          side: str, ascending: bool) -> jnp.ndarray:
    """Per-lane binary search with per-lane [lo, hi) bounds: first
    position p in [lo0_i, hi0_i) whose value passes the boundary test
    against targets_i (vals sorted within each lane's own bound range —
    the partition). O(log cap) gathers, branch-free."""
    cap = vals.shape[0]
    lo, hi = lo0.astype(jnp.int64), hi0.astype(jnp.int64)

    def body(_, lh):
        lo, hi = lh
        mid = (lo + hi) >> 1
        v = jnp.take(vals, jnp.clip(mid, 0, cap - 1), axis=0)
        if ascending:
            go = (v < targets) if side == "left" else (v <= targets)
        else:
            go = (v > targets) if side == "left" else (v >= targets)
        go = go & (lo < hi)
        return (jnp.where(go, mid + 1, lo), jnp.where(go, hi, mid))

    lo, hi = jax.lax.fori_loop(0, max(cap.bit_length(), 1), body,
                               (lo, hi))
    return lo


def _rmq_tables(x: jnp.ndarray, op, sentinel,
                max_width: Optional[int] = None) -> jnp.ndarray:
    """Sparse-table range-min/max: [levels, cap] where level k holds the
    reduction of [i, i + 2^k) — O(cap log cap) build, O(1) (two gathers)
    per query. The device answer to arbitrary-frame MIN/MAX windows
    (reference WindowPartition re-aggregates per row; here every row's
    frame is answered from the shared table). ``max_width`` (a static
    bound on any queried frame length, e.g. from constant ROWS offsets)
    caps the level count — an unbounded table at 2^26 rows would cost
    ~levels x cap x 8B of HBM for levels no query ever touches."""
    cap = x.shape[0]
    levels = max(cap.bit_length(), 1)
    if max_width is not None:
        levels = min(levels, max(int(max_width).bit_length(), 1))
    tabs = [x]
    for k in range(1, levels):
        shift = 1 << (k - 1)
        prev = tabs[-1]
        if shift < cap:
            shifted = jnp.concatenate(
                [prev[shift:], jnp.full((shift,), sentinel, prev.dtype)])
        else:
            shifted = jnp.full((cap,), sentinel, prev.dtype)
        tabs.append(op(prev, shifted))
    return jnp.stack(tabs)


def _rmq_query(tabs: jnp.ndarray, op, sentinel, fs: jnp.ndarray,
               fe: jnp.ndarray) -> jnp.ndarray:
    """Reduce [fs, fe] per lane from sparse tables; empty -> sentinel."""
    levels, cap = tabs.shape
    length = jnp.maximum(fe - fs + 1, 1)
    k = (jnp.int64(63) - jax.lax.clz(length.astype(jnp.uint64))
         .astype(jnp.int64))
    k = jnp.clip(k, 0, levels - 1)
    flat = tabs.reshape(-1)
    a = jnp.take(flat, k * cap + jnp.clip(fs, 0, cap - 1), axis=0)
    b = jnp.take(flat, k * cap
                 + jnp.clip(fe - (jnp.int64(1) << k) + 1, 0, cap - 1),
                 axis=0)
    return jnp.where(fe >= fs, op(a, b), sentinel)


def _frame_positions(spec: "WindowSpec", idx, pstart, pend, ostart, oend,
                     order_vals):
    """(fs, fe) inclusive frame row-positions per lane for an explicit
    frame (reference operator/window/FrameInfo.java semantics): ROWS
    bounds offset by physical positions, RANGE bounds by order-key value
    (computed with bounded binary searches over the partition-sorted
    key). fs > fe encodes an empty frame."""

    def one(kind_off, is_start):
        kind, off = kind_off
        if kind == "unbounded_preceding":
            return pstart
        if kind == "unbounded_following":
            return pend
        if spec.frame == "rows":
            if kind == "current_row":
                return idx
            return idx - off if kind == "preceding" else idx + off
        # RANGE unit
        if kind == "current_row":
            return ostart if is_start else oend
        vals, valid, asc, vstart, vend, key_scale = order_vals
        assert vals is not None, \
            "offset RANGE frame requires one ORDER BY key"
        # DECIMAL order keys store scaled integers: the literal offset
        # scales by 10^scale so `price RANGE 10 PRECEDING` means 10.00,
        # not 0.10 (reference FrameInfo applies offsets in VALUE space)
        delta = jnp.asarray(off * key_scale, vals.dtype)
        if kind == "preceding":
            target = vals - delta if asc else vals + delta
        else:
            target = vals + delta if asc else vals - delta
        side = "left" if is_start else "right"
        # search only the partition's non-NULL run: NULL rows cluster at
        # one end of the partition and their payloads are not ordered
        p = _bounded_searchsorted(vals, target, vstart, vend + 1,
                                  side, asc)
        p = p if is_start else p - 1
        # SQL: a NULL order key's offset frame is its peer run
        return jnp.where(valid, p, ostart if is_start else oend)

    fs = jnp.maximum(one(spec.frame_start, True), pstart)
    fe = jnp.minimum(one(spec.frame_end, False), pend)
    return fs, fe


def _reverse_cummin_int(x: jnp.ndarray) -> jnp.ndarray:
    return jax.lax.associative_scan(jnp.minimum, x, reverse=True)


def evaluate_window(
    batch: Batch,
    partition_by: Sequence[int],
    order_by: Sequence[SortKey],
    specs: Sequence[WindowSpec],
) -> Batch:
    """Append one output column per spec; rows re-ordered by
    (partition, order, original position)."""
    cap = batch.capacity
    # ---- global sort: dead rows last, then partition keys, then order keys
    dead = jnp.where(batch.row_mask, 0, 1).astype(jnp.int32)
    operands: List[jnp.ndarray] = [dead]
    for pi in partition_by:
        c = batch.columns[pi]
        operands.append(jnp.where(c.validity, 0, 1).astype(jnp.int32))
        d = c.data
        if getattr(d, "ndim", 1) == 2:
            # long-decimal limb pairs: two operands (hi, unsigned lo)
            from .int128 import SIGN64
            operands.append(jnp.where(c.validity, d[..., 0],
                                      jnp.zeros_like(d[..., 0])))
            operands.append(jnp.where(c.validity, d[..., 1] ^ SIGN64,
                                      jnp.zeros_like(d[..., 1])))
            continue
        d = d.astype(jnp.int32) if d.dtype == jnp.bool_ else d
        # neutralize NULL rows' storage so stale values can't split one
        # NULL partition into many (same rule as _group_key_ops)
        operands.append(jnp.where(c.validity, d, jnp.zeros_like(d)))
    n_part_ops = len(operands)
    for k in order_by:
        operands.extend(_sortable(batch.columns[k.column], k))
    n_ops = len(operands)
    # sort keys + row index only; gather payload by the permutation (TPU
    # variadic-sort compile time scales badly with operand count — see
    # ops/sort.py sort_permutation)
    out = jax.lax.sort(operands + [jnp.arange(cap, dtype=jnp.int32)],
                       num_keys=n_ops, is_stable=True)
    s_ops = out[:n_ops]
    perm = out[-1]
    mask = jnp.take(batch.row_mask, perm, axis=0)
    s_cols = []
    for c in batch.columns:
        s_cols.append(jnp.take(c.data, perm, axis=0))
        s_cols.append(jnp.take(c.validity, perm, axis=0))

    idx = jnp.arange(cap, dtype=jnp.int64)

    # ---- partition boundaries and per-partition segment base
    pboundary = jnp.zeros(cap, dtype=bool).at[0].set(True)
    for op in s_ops[1:n_part_ops]:
        pboundary = pboundary | (op != jnp.roll(op, 1))
    pboundary = pboundary.at[0].set(True)
    pstart = _cummax_int(jnp.where(pboundary, idx, -1))          # seg start
    # partition end (last live row of the partition)
    live_n = jnp.sum(mask.astype(jnp.int64))
    nxt_start = _reverse_cummin_int(
        jnp.where(jnp.roll(pboundary, -1).at[-1].set(True),
                  idx + 1, jnp.iinfo(jnp.int64).max))
    pend = jnp.minimum(nxt_start, live_n) - 1                     # inclusive
    psize = jnp.maximum(pend - pstart + 1, 1)

    # ---- peer runs (order-key ties)
    oboundary = pboundary
    for op in s_ops[n_part_ops:]:
        oboundary = oboundary | (op != jnp.roll(op, 1))
    oboundary = oboundary.at[0].set(True)
    ostart = _cummax_int(jnp.where(oboundary, idx, -1))
    onext = _reverse_cummin_int(
        jnp.where(jnp.roll(oboundary, -1).at[-1].set(True),
                  idx + 1, jnp.iinfo(jnp.int64).max))
    oend = jnp.minimum(onext, live_n) - 1                         # inclusive

    row_in_part = idx - pstart                                    # 0-based
    dense = prefix_sum(oboundary.astype(jnp.int64))               # global
    dense_at_pstart = jnp.take(dense, jnp.maximum(pstart, 0))

    # first-order-key context for offset RANGE frames: raw sorted values,
    # their validity, direction, each partition's non-NULL run, and the
    # key's decimal scale factor (offsets are given in VALUE space)
    order_ctx = (None, None, True, pstart, pend, 1)
    if order_by:
        k0 = order_by[0]
        k0_t = batch.columns[k0.column].type
        key_scale = (10 ** k0_t.scale
                     if isinstance(k0_t, T.DecimalType) else 1)
        ovals = jnp.take(batch.columns[k0.column].data, perm, axis=0)
        ovalid = jnp.take(batch.columns[k0.column].validity, perm,
                          axis=0) & mask
        vfirst = jnp.take(_segment_scan(
            jnp.where(ovalid, idx, jnp.iinfo(jnp.int64).max), pstart,
            jnp.minimum), jnp.clip(pend, 0, cap - 1), axis=0)
        vlast = jnp.take(_segment_scan(
            jnp.where(ovalid, idx, jnp.int64(-1)), pstart, jnp.maximum),
            jnp.clip(pend, 0, cap - 1), axis=0)
        order_ctx = (ovals, ovalid, bool(k0.ascending), vfirst, vlast,
                     key_scale)

    new_cols: List[Column] = []
    fields: List[Tuple[str, Type]] = []
    for i, c in enumerate(batch.columns):
        fields.append((batch.schema.names[i], batch.schema.types[i]))
        new_cols.append(Column(c.type, s_cols[2 * i], s_cols[2 * i + 1],
                               c.dictionary))

    for spec in specs:
        data, valid = _one_window(
            spec, s_cols, batch, mask, idx, pstart, pend, psize,
            row_in_part, ostart, oend, dense, dense_at_pstart, order_ctx)
        fields.append((spec.name, spec.output_type))
        # String-valued outputs (lag/lead/first/last/nth_value, min/max over
        # varchar) are dictionary codes drawn from the argument column's
        # vocabulary — carry that dictionary (reference LagFunction.java
        # returns the source block's value, dictionary included).
        dictionary = None
        if spec.output_type.is_string and spec.args:
            dictionary = batch.columns[spec.args[0]].dictionary
        new_cols.append(Column(spec.output_type,
                               data.astype(spec.output_type.storage_dtype),
                               valid & mask, dictionary))
    return Batch(Schema(fields), new_cols, mask)


def _one_window(spec, s_cols, batch, mask, idx, pstart, pend, psize,
                row_in_part, ostart, oend, dense, dense_at_pstart,
                order_ctx):
    fn = spec.fn
    cap = mask.shape[0]
    # explicit frame positions (ranking functions and lag/lead ignore
    # frames per the SQL standard)
    explicit = (not spec.default_frame
                and fn not in RANKING and fn not in ("lag", "lead"))
    if explicit:
        fs, fe = _frame_positions(spec, idx, pstart, pend, ostart, oend,
                                  order_ctx)
        frame_nonempty = fe >= fs
    if fn == "row_number":
        return row_in_part + 1, jnp.ones(cap, dtype=bool)
    if fn == "rank":
        return ostart - pstart + 1, jnp.ones(cap, dtype=bool)
    if fn == "dense_rank":
        return dense - dense_at_pstart + 1, jnp.ones(cap, dtype=bool)
    if fn == "percent_rank":
        r = (ostart - pstart).astype(jnp.float64)
        den = jnp.maximum(psize - 1, 1).astype(jnp.float64)
        return jnp.where(psize > 1, r / den, 0.0), jnp.ones(cap, dtype=bool)
    if fn == "cume_dist":
        covered = (oend - pstart + 1).astype(jnp.float64)
        return covered / psize.astype(jnp.float64), jnp.ones(cap, dtype=bool)
    if fn == "ntile":
        n = jnp.int64(spec.offset)
        size, rem = psize // n, psize % n
        big = (size + 1) * rem
        bucket = jnp.where(
            row_in_part < big,
            row_in_part // jnp.maximum(size + 1, 1),
            rem + (row_in_part - big) // jnp.maximum(size, 1))
        return bucket + 1, jnp.ones(cap, dtype=bool)

    def col(j):
        return s_cols[2 * j], s_cols[2 * j + 1]

    if fn in ("lag", "lead"):
        data, valid = col(spec.args[0])
        off = spec.offset if fn == "lag" else -spec.offset
        src = idx - off
        in_part = (src >= pstart) & (src <= pend)
        src = jnp.clip(src, 0, cap - 1)
        return (jnp.take(data, src, axis=0),
                jnp.take(valid, src, axis=0) & in_part)
    if fn == "first_value":
        data, valid = col(spec.args[0])
        if explicit:
            src = jnp.clip(fs, 0, cap - 1)
            return (jnp.take(data, src, axis=0),
                    jnp.take(valid, src, axis=0) & frame_nonempty)
        src = jnp.maximum(pstart, 0)
        return jnp.take(data, src, axis=0), jnp.take(valid, src, axis=0)
    # frame end: RANGE frames end at the current row's last peer, ROWS
    # frames at the current row itself (reference window/FrameInfo.java)
    frame_end = idx if spec.frame == "rows" else oend

    if fn == "last_value":
        data, valid = col(spec.args[0])
        if explicit:
            src = jnp.clip(fe, 0, cap - 1)
            return (jnp.take(data, src, axis=0),
                    jnp.take(valid, src, axis=0) & frame_nonempty)
        src = jnp.clip(frame_end, 0, cap - 1)
        return jnp.take(data, src, axis=0), jnp.take(valid, src, axis=0)
    if fn == "nth_value":
        data, valid = col(spec.args[0])
        if explicit:
            src = fs + spec.offset - 1
            ok = frame_nonempty & (src <= fe)
            src = jnp.clip(src, 0, cap - 1)
            return (jnp.take(data, src, axis=0),
                    jnp.take(valid, src, axis=0) & ok)
        src = pstart + spec.offset - 1
        ok = src <= jnp.minimum(frame_end, pend)
        src = jnp.clip(src, 0, cap - 1)
        return jnp.take(data, src, axis=0), jnp.take(valid, src, axis=0) & ok

    # ---- aggregates over the default frame --------------------------------
    if fn == "count_star":
        contrib = mask.astype(jnp.int64)
        valid_in = mask
        data = contrib
    else:
        data, valid_in = col(spec.args[0])
        valid_in = valid_in & mask
    acc_dtype = spec.output_type.storage_dtype
    if fn in ("count", "count_star"):
        x = valid_in.astype(jnp.int64)
        zero = jnp.int64(0)
    else:
        x = jnp.where(valid_in, data.astype(acc_dtype)
                      if fn != "avg" else data.astype(jnp.float64), 0)
        zero = jnp.zeros((), dtype=x.dtype)
    if fn in ("min", "max"):
        # min/max over strings must compare lexicographic ranks, not codes
        # (codes are assigned in order of appearance).
        is_str = bool(spec.args) and batch.columns[spec.args[0]].type.is_string
        if is_str:
            vocab = batch.columns[spec.args[0]].dictionary
            xdata = rank_codes(data, vocab)
            red_dtype = xdata.dtype
        else:
            xdata = data.astype(acc_dtype)
            red_dtype = acc_dtype
        big = jnp.iinfo(red_dtype).max if jnp.issubdtype(red_dtype, jnp.integer) \
            else jnp.asarray(jnp.inf, red_dtype)
        small = jnp.iinfo(red_dtype).min if jnp.issubdtype(red_dtype, jnp.integer) \
            else jnp.asarray(-jnp.inf, red_dtype)
        sent = big if fn == "min" else small
        op = jnp.minimum if fn == "min" else jnp.maximum
        xm = jnp.where(valid_in, xdata, sent)
        if explicit:
            # arbitrary [fs, fe] frames: sparse-table range queries;
            # constant ROWS offsets statically bound the frame width
            max_width = None
            if spec.frame == "rows" and \
                    spec.frame_start[0] != "unbounded_preceding" and \
                    spec.frame_end[0] != "unbounded_following":
                max_width = spec.frame_start[1] + spec.frame_end[1] + 1
            tabs = _rmq_tables(xm, op, sent, max_width)
            val = _rmq_query(tabs, op, sent, fs, fe)
            cnt = _frame_count(valid_in, fs, fe)
        else:
            run = _segment_scan(xm, pstart, op)
            upto = _agg_frame_end(spec, frame_end, pend)
            val = jnp.take(run, jnp.clip(upto, 0, cap - 1), axis=0)
            cnt = _running_count(valid_in, pstart, upto)
        if is_str:
            # map winning rank back to a dictionary code
            inv = unrank_table(vocab)
            val = jnp.take(inv, jnp.clip(val, 0, inv.shape[0] - 1), axis=0)
        return val, cnt > 0
    # sum / count / avg
    csum = prefix_sum(x)
    if explicit:
        base = jnp.where(fs > 0,
                         jnp.take(csum, jnp.clip(fs - 1, 0, cap - 1),
                                  axis=0), zero)
        val = jnp.where(
            fe >= fs,
            jnp.take(csum, jnp.clip(fe, 0, cap - 1), axis=0) - base,
            zero)
        cnt = _frame_count(valid_in, fs, fe)
    else:
        base = jnp.where(pstart > 0,
                         jnp.take(csum, jnp.maximum(pstart - 1, 0),
                                  axis=0), zero)
        upto = _agg_frame_end(spec, frame_end, pend)
        val = jnp.take(csum, jnp.clip(upto, 0, cap - 1), axis=0) - base
        cnt = _running_count(valid_in, pstart, upto)
    if fn in ("count", "count_star"):
        return val, jnp.ones(cap, dtype=bool)
    if fn == "avg":
        return val / jnp.maximum(cnt, 1).astype(jnp.float64), cnt > 0
    return val, cnt > 0


def _agg_frame_end(spec, frame_end, pend):
    """Frame end for running aggregates: an explicit ROWS frame always ends
    at the current row, even without ORDER BY (ignore_order covers only the
    default whole-partition frame of order-less windows)."""
    if spec.frame == "rows":
        return frame_end
    return pend if spec.ignore_order else frame_end


def _running_count(valid_in, pstart, upto):
    cap = valid_in.shape[0]
    csum = prefix_sum(valid_in.astype(jnp.int64))
    base = jnp.where(pstart > 0,
                     jnp.take(csum, jnp.maximum(pstart - 1, 0), axis=0), 0)
    return jnp.take(csum, jnp.clip(upto, 0, cap - 1), axis=0) - base


def _frame_count(valid_in, fs, fe):
    """Valid-row count over explicit [fs, fe] frames (0 when empty)."""
    cap = valid_in.shape[0]
    csum = prefix_sum(valid_in.astype(jnp.int64))
    base = jnp.where(fs > 0,
                     jnp.take(csum, jnp.clip(fs - 1, 0, cap - 1), axis=0),
                     0)
    return jnp.where(
        fe >= fs,
        jnp.take(csum, jnp.clip(fe, 0, cap - 1), axis=0) - base, 0)


def _segment_scan(x, pstart, op):
    """Inclusive running-op within segments: reset at segment starts."""
    idx = jnp.arange(x.shape[0], dtype=jnp.int64)

    def combine(a, b):
        (sa, va) = a
        (sb, vb) = b
        # b's segment start wins if it started later
        s = jnp.maximum(sa, sb)
        v = jnp.where(sb > sa, vb, op(va, vb))
        return (s, v)
    _, out = jax.lax.associative_scan(combine, (pstart, x))
    return out

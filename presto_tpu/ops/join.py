"""Join kernels: sorted-lookup equi-join.

The TPU-native replacement for Presto's hash join (reference
presto-main/.../operator/HashBuilderOperator.java:51, LookupJoinOperator.java,
PagesHash.java, JoinProbe.java): the build side is sorted by key on device
once; each probe row binary-searches it (``jnp.searchsorted``, O(log n)
vectorized across all probe lanes) and gathers the payload. Static shapes
throughout: the output has the probe's capacity, with the row mask narrowed
for misses (inner) or payload validity cleared (left outer).

This path assumes *unique build keys* — the PK-FK joins that dominate
TPC-H/TPC-DS. Many-to-many expansion (capacity-padded) is a follow-up; Presto
has the same split between JoinProbe fast paths and PositionLinks chains.

SQL semantics: NULL keys never match (either side).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import types as T
from ..batch import Batch, Column, Schema


def _join_key(batch: Batch, key_cols: Sequence[int]) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Combine key columns into a single sortable i64 key + key validity.

    Multi-column keys are packed by shifting (caller guarantees ranges) or
    must be pre-combined by the planner; v1 packs up to two 32-bit-range
    columns, else requires a single column.
    """
    if len(key_cols) == 1:
        c = batch.columns[key_cols[0]]
        return c.data.astype(jnp.int64), c.validity
    if len(key_cols) == 2:
        a, b = (batch.columns[i] for i in key_cols)
        key = (a.data.astype(jnp.int64) << 32) | (
            b.data.astype(jnp.int64) & 0xFFFFFFFF)
        return key, a.validity & b.validity
    raise NotImplementedError("join on >2 key columns (pre-combine in planner)")


def build_sorted(build: Batch, key_cols: Sequence[int]):
    """Sort the build side by join key; dead/null-key rows to the end.

    Returns (sorted_key, sorted_live, permutation) for probing; the
    permutation reorders build payload columns on demand.
    """
    key, kvalid = _join_key(build, key_cols)
    live = build.row_mask & kvalid
    skey = jnp.where(live, key, jnp.iinfo(jnp.int64).max)
    perm = jnp.argsort(skey, stable=True)
    return skey[perm], live[perm], perm


def lookup_join(
    probe: Batch,
    build: Batch,
    probe_keys: Sequence[int],
    build_keys: Sequence[int],
    payload: Sequence[int],
    payload_names: Sequence[str],
    join_type: str = "inner",
) -> Batch:
    """Join probe against unique-key build side.

    join_type: 'inner' | 'left' (probe-preserving).
    Output schema = probe columns + named build payload columns.
    """
    assert join_type in ("inner", "left")
    skey, slive, perm = build_sorted(build, build_keys)
    pkey, pvalid = _join_key(probe, probe_keys)
    pos = jnp.searchsorted(skey, pkey, side="left")
    pos = jnp.minimum(pos, skey.shape[0] - 1)
    hit_key = jnp.take(skey, pos, axis=0)
    hit_live = jnp.take(slive, pos, axis=0)
    match = probe.row_mask & pvalid & hit_live & (hit_key == pkey)

    out_fields = list(zip(probe.schema.names, probe.schema.types))
    out_cols: List[Column] = list(probe.columns)
    for ci, name in zip(payload, payload_names):
        c = build.columns[ci]
        sdata = jnp.take(c.data, perm, axis=0)
        svalid = jnp.take(c.validity, perm, axis=0)
        out_fields.append((name, c.type))
        out_cols.append(Column(
            c.type,
            jnp.take(sdata, pos, axis=0),
            jnp.take(svalid, pos, axis=0) & match,
            c.dictionary,
        ))
    if join_type == "inner":
        mask = match
    else:
        mask = probe.row_mask
    return Batch(Schema(out_fields), out_cols, mask)


def semi_join_mask(
    probe: Batch,
    build: Batch,
    probe_keys: Sequence[int],
    build_keys: Sequence[int],
    negated: bool = False,
) -> jnp.ndarray:
    """Membership mask for semi/anti-joins (IN / NOT IN; reference
    HashSemiJoinOperator.java + SetBuilderOperator.java).

    ANSI null semantics: a NULL probe key never matches; for NOT IN, any
    NULL build key makes membership UNKNOWN for non-matching rows (nothing
    passes), while an EMPTY build set makes NOT IN vacuously TRUE for every
    probe row — including NULL keys.
    """
    skey, slive, _ = build_sorted(build, build_keys)
    pkey, pvalid = _join_key(probe, probe_keys)
    pos = jnp.searchsorted(skey, pkey, side="left")
    pos = jnp.minimum(pos, skey.shape[0] - 1)
    hit = (jnp.take(skey, pos, axis=0) == pkey) & jnp.take(slive, pos, axis=0)
    if not negated:
        return probe.row_mask & pvalid & hit
    _bkey, bvalid = _join_key(build, build_keys)
    build_has_null = jnp.any(build.row_mask & ~bvalid)
    build_empty = ~jnp.any(build.row_mask)
    anti = probe.row_mask & pvalid & ~hit & ~build_has_null
    return jnp.where(build_empty, probe.row_mask, anti)

"""Join kernels: sorted-lookup equi-join.

The TPU-native replacement for Presto's hash join (reference
presto-main/.../operator/HashBuilderOperator.java:51, LookupJoinOperator.java,
PagesHash.java, JoinProbe.java): the build side is sorted by key on device
once; each probe row binary-searches it (``jnp.searchsorted``, O(log n)
vectorized across all probe lanes) and gathers the payload. Static shapes
throughout: the output has the probe's capacity, with the row mask narrowed
for misses (inner) or payload validity cleared (left outer).

This path assumes *unique build keys* — the PK-FK joins that dominate
TPC-H/TPC-DS. Many-to-many expansion (capacity-padded) is a follow-up; Presto
has the same split between JoinProbe fast paths and PositionLinks chains.

SQL semantics: NULL keys never match (either side).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import types as T
from ..batch import Batch, Column, Schema


def _join_key(batch: Batch, key_cols: Sequence[int]) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Combine key columns into a single sortable i64 key + key validity.

    Multi-column keys are packed by shifting (caller guarantees ranges) or
    must be pre-combined by the planner; v1 packs up to two 32-bit-range
    columns, else requires a single column.
    """
    if len(key_cols) == 1:
        c = batch.columns[key_cols[0]]
        return c.data.astype(jnp.int64), c.validity
    if len(key_cols) == 2:
        a, b = (batch.columns[i] for i in key_cols)
        key = (a.data.astype(jnp.int64) << 32) | (
            b.data.astype(jnp.int64) & 0xFFFFFFFF)
        return key, a.validity & b.validity
    raise NotImplementedError("join on >2 key columns (pre-combine in planner)")


def build_sorted(build: Batch, key_cols: Sequence[int]):
    """Sort the build side by join key; dead/null-key rows to the end.

    Returns (sorted_key, sorted_live, permutation) for probing; the
    permutation reorders build payload columns on demand.
    """
    key, kvalid = _join_key(build, key_cols)
    live = build.row_mask & kvalid
    skey = jnp.where(live, key, jnp.iinfo(jnp.int64).max)
    perm = jnp.argsort(skey, stable=True)
    return skey[perm], live[perm], perm


def lookup_join(
    probe: Batch,
    build: Batch,
    probe_keys: Sequence[int],
    build_keys: Sequence[int],
    payload: Sequence[int],
    payload_names: Sequence[str],
    join_type: str = "inner",
) -> Batch:
    """Join probe against unique-key build side.

    join_type: 'inner' | 'left' (probe-preserving).
    Output schema = probe columns + named build payload columns.
    """
    assert join_type in ("inner", "left")
    skey, slive, perm = build_sorted(build, build_keys)
    pkey, pvalid = _join_key(probe, probe_keys)
    pos = jnp.searchsorted(skey, pkey, side="left")
    pos = jnp.minimum(pos, skey.shape[0] - 1)
    hit_key = jnp.take(skey, pos, axis=0)
    hit_live = jnp.take(slive, pos, axis=0)
    match = probe.row_mask & pvalid & hit_live & (hit_key == pkey)

    out_fields = list(zip(probe.schema.names, probe.schema.types))
    out_cols: List[Column] = list(probe.columns)
    for ci, name in zip(payload, payload_names):
        c = build.columns[ci]
        sdata = jnp.take(c.data, perm, axis=0)
        svalid = jnp.take(c.validity, perm, axis=0)
        out_fields.append((name, c.type))
        out_cols.append(Column(
            c.type,
            jnp.take(sdata, pos, axis=0),
            jnp.take(svalid, pos, axis=0) & match,
            c.dictionary,
        ))
    if join_type == "inner":
        mask = match
    else:
        mask = probe.row_mask
    return Batch(Schema(out_fields), out_cols, mask)


def match_count_max(
    probe: Batch, build: Batch,
    probe_keys: Sequence[int], build_keys: Sequence[int],
) -> jnp.ndarray:
    """Max build matches for any live probe key (device scalar).

    The host syncs this once per (probe, build) pair to pick the static
    expansion factor for ``expand_join`` — the capacity analogue of
    Presto's PositionLinks chain length (reference operator/
    ArrayPositionLinks.java).
    """
    skey, slive, _ = build_sorted(build, build_keys)
    pkey, pvalid = _join_key(probe, probe_keys)
    live = probe.row_mask & pvalid
    lo = jnp.searchsorted(skey, pkey, side="left")
    hi = jnp.searchsorted(skey, pkey, side="right")
    # slive is sorted live-first within equal keys (dead rows pushed to the
    # int64-max sentinel), so [lo, hi) spans only live matches
    cnt = jnp.where(live, hi - lo, 0)
    return jnp.max(cnt) if cnt.shape[0] else jnp.asarray(0)


def expand_join(
    probe: Batch,
    build: Batch,
    probe_keys: Sequence[int],
    build_keys: Sequence[int],
    payload: Sequence[int],
    payload_names: Sequence[str],
    join_type: str = "inner",
    max_matches: int = 1,
) -> Batch:
    """Many-to-many equi-join with static expansion factor.

    Output capacity = probe capacity * max_matches: slot k of probe row i
    holds its k-th match (masked off past the row's match count). The
    caller obtains ``max_matches`` from ``match_count_max`` (bucketed, so
    kernels recompile only when the multiplicity crosses a power of two).
    Left joins keep unmatched probe rows in slot 0 with null payload.
    """
    assert join_type in ("inner", "left")
    k = max(1, max_matches)
    skey, slive, perm = build_sorted(build, build_keys)
    pkey, pvalid = _join_key(probe, probe_keys)
    live = probe.row_mask & pvalid
    lo = jnp.searchsorted(skey, pkey, side="left")
    hi = jnp.searchsorted(skey, pkey, side="right")
    cnt = jnp.where(live, hi - lo, 0)

    # [k, C] grids -> flattened [k*C] output (probe-major within slots)
    slot = jnp.arange(k)[:, None]                      # [k, 1]
    pos = jnp.minimum(lo[None, :] + slot, skey.shape[0] - 1)
    # slive guards the sentinel edge (a probe key equal to int64-max would
    # otherwise "match" dead build rows)
    matched = (slot < cnt[None, :]) & jnp.take(slive, pos, axis=0)  # [k, C]

    out_fields = list(zip(probe.schema.names, probe.schema.types))
    out_cols: List[Column] = []
    for c in probe.columns:
        data = jnp.broadcast_to(c.data[None, :], (k,) + c.data.shape)
        valid = jnp.broadcast_to(c.validity[None, :], (k,) + c.validity.shape)
        out_cols.append(Column(c.type, data.reshape(-1), valid.reshape(-1),
                               c.dictionary))
    for ci, name in zip(payload, payload_names):
        c = build.columns[ci]
        sdata = jnp.take(c.data, perm, axis=0)
        svalid = jnp.take(c.validity, perm, axis=0)
        gdata = jnp.take(sdata, pos, axis=0)           # [k, C]
        gvalid = jnp.take(svalid, pos, axis=0) & matched
        out_fields.append((name, c.type))
        out_cols.append(Column(c.type, gdata.reshape(-1), gvalid.reshape(-1),
                               c.dictionary))
    if join_type == "inner":
        mask = matched
    else:
        # unmatched probe rows survive in slot 0 with null payload
        first_slot = (slot == 0) & (cnt[None, :] == 0) & probe.row_mask
        mask = matched | first_slot
    return Batch(Schema(out_fields), out_cols, mask.reshape(-1))


def semi_join_mask(
    probe: Batch,
    build: Batch,
    probe_keys: Sequence[int],
    build_keys: Sequence[int],
    negated: bool = False,
    null_aware: bool = True,
) -> jnp.ndarray:
    """Membership mask for semi/anti-joins (IN / NOT IN / [NOT] EXISTS;
    reference HashSemiJoinOperator.java + SetBuilderOperator.java).

    null_aware=True (IN / NOT IN) follows ANSI IN-predicate semantics: a
    NULL probe key never matches; for NOT IN, any NULL build key makes
    membership UNKNOWN for non-matching rows (nothing passes), while an
    EMPTY build set makes NOT IN vacuously TRUE for every probe row —
    including NULL keys. null_aware=False (decorrelated [NOT] EXISTS)
    treats NULL keys as simply never equal: NOT EXISTS keeps every probe
    row without a live match.
    """
    skey, slive, _ = build_sorted(build, build_keys)
    pkey, pvalid = _join_key(probe, probe_keys)
    pos = jnp.searchsorted(skey, pkey, side="left")
    pos = jnp.minimum(pos, skey.shape[0] - 1)
    hit = (jnp.take(skey, pos, axis=0) == pkey) & jnp.take(slive, pos, axis=0)
    if not negated:
        return probe.row_mask & pvalid & hit
    if not null_aware:
        return probe.row_mask & ~(pvalid & hit)
    _bkey, bvalid = _join_key(build, build_keys)
    build_has_null = jnp.any(build.row_mask & ~bvalid)
    build_empty = ~jnp.any(build.row_mask)
    anti = probe.row_mask & pvalid & ~hit & ~build_has_null
    return jnp.where(build_empty, probe.row_mask, anti)
